"""Ring attention — sequence/context parallelism over the mesh's
``seq`` axis.

The reference has no attention or sequence dimension at all
(SURVEY §5.7; fixed 28×28 inputs, src/mnist.py:27-30), but long-context
support is first-class here: sequences are sharded over devices, each
device holds one Q/K/V block, and K/V blocks rotate around the ring
via ``lax.ppermute`` while a streaming (online-softmax) accumulator
builds exact attention — FLOPs and memory per device stay O(S_local·S)
and O(S_local), and the permute traffic rides ICI neighbor links.

This is the blockwise/ring formulation (cf. Liu et al., "Ring
Attention with Blockwise Transformers", arXiv:2310.01889) implemented
as a pure shard_map-compatible function.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

_NEG_INF = -1e30  # finite mask value: keeps online-softmax algebra NaN-free


def ring_self_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                        axis_name: str, *, causal: bool = True,
                        scale: float | None = None) -> jax.Array:
    """Exact multi-head attention over a ring of sequence blocks.

    Args (all *local* blocks inside shard_map):
      q, k, v: [batch, heads, seq_local, head_dim]
      axis_name: mesh axis the sequence is sharded over.
      causal: apply a causal mask in *global* positions.

    Returns: [batch, heads, seq_local, head_dim] attention output for
    this device's query block.
    """
    n = lax.axis_size(axis_name)
    me = lax.axis_index(axis_name)
    b, h, s_loc, d = q.shape
    if scale is None:
        scale = 1.0 / (d ** 0.5)

    q32 = q.astype(jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(r, carry):
        k_cur, v_cur, m, l, acc = carry
        # k_cur/v_cur originated on device (me - r) mod n
        src = (me - r) % n
        scores = jnp.einsum("bhqd,bhkd->bhqk", q32,
                            k_cur.astype(jnp.float32)) * scale
        if causal:
            qpos = me * s_loc + jnp.arange(s_loc)[:, None]
            kpos = src * s_loc + jnp.arange(s_loc)[None, :]
            scores = jnp.where(qpos >= kpos, scores, _NEG_INF)
        m_new = jnp.maximum(m, scores.max(axis=-1))
        p = jnp.exp(scores - m_new[..., None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + p.sum(axis=-1)
        acc_new = acc * corr[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_cur.astype(jnp.float32))
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        return (k_next, v_next, m_new, l_new, acc_new)

    def vary(x):
        # initial accumulators must carry the same varying-axis type as
        # the loop outputs — i.e. q's full vma, which under DP×SP
        # includes the replica axis too, not just the ring axis
        want = getattr(jax.typeof(q), "vma", frozenset()) or frozenset()
        have = getattr(jax.typeof(x), "vma", frozenset()) or frozenset()
        missing = tuple(want - have)
        return lax.pcast(x, missing, to="varying") if missing else x

    m0 = vary(jnp.full((b, h, s_loc), _NEG_INF, jnp.float32))
    l0 = vary(jnp.zeros((b, h, s_loc), jnp.float32))
    acc0 = vary(jnp.zeros((b, h, s_loc, d), jnp.float32))
    _, _, m, l, acc = lax.fori_loop(0, n, step, (k, v, m0, l0, acc0))
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.astype(q.dtype)


def local_self_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                         *, causal: bool = True,
                         scale: float | None = None) -> jax.Array:
    """Single-device reference attention (same signature minus the
    axis): the oracle ring_self_attention is tested against."""
    d = q.shape[-1]
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    scores = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    if causal:
        s = q.shape[2]
        mask = jnp.tril(jnp.ones((s, s), bool))
        scores = jnp.where(mask, scores, _NEG_INF)
    p = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)
