"""distributedmnist_tpu — a TPU-native distributed-training framework.

A ground-up JAX/XLA re-design of the capabilities of
agnusmaximus/DistributedMNIST (a TF-1.x parameter-server codebase for
studying synchronous distributed SGD under stragglers; see
/root/reference/src/distributed_train.py).

Architecture stance (vs. the reference's PS star):

* One SPMD program over a `jax.sharding.Mesh` — no parameter-server /
  worker split, no gRPC star, no token queues
  (reference: src/mnist_distributed_train.py:25-35,
  src/sync_replicas_optimizer_modified/sync_replicas_optimizer_modified.py:199-206).
* Replicated parameters; gradients reduced with a **masked mean psum**
  over the ICI mesh: ``psum(grad * flag) / psum(flag)``.
* Every aggregation discipline of the reference — k-of-n quorum /
  backup workers, wall-clock interval pacing, deadline straggler drop,
  full-barrier CDF instrumentation, drop-connect — is expressed as a
  per-replica contribution-mask policy inside that single reduction
  (reference: sync_replicas_optimizer_modified.py:237-429,
  src/timeout_manager.py, src/distributed_train.py:194-196).

Package layout:

* ``core``     — configs, mesh/topology discovery, PRNG policy, logging.
* ``data``     — idx loaders (MNIST / Fashion-MNIST), CIFAR-10, synthetic
                 data, host-sharded batching, native C++ prefetch pipeline.
* ``models``   — pure-function models (LeNet-style CNN, ResNet-20,
                 a small transformer for the long-context path).
* ``ops``      — masked reductions, drop-connect, ring attention.
* ``parallel`` — the SPMD train step and mask policies (the heart;
                 replaces reference L3+L4).
* ``train``    — train loop, LR schedule, checkpoint/resume.
* ``evalsvc``  — continuous checkpoint evaluator (≙ src/nn_eval.py).
* ``obsv``     — step-time CDFs, profiler traces, metric sinks.
* ``launch``   — topology bring-up and experiment sweep runner
                 (≙ tools/tf_ec2.py + tools/benchmark.py + cfg/).
"""

__version__ = "0.1.0"
