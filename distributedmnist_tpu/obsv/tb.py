"""TensorBoard-compatible scalar summaries — first-party tfevents
writer.

≙ the reference's summary path: the chief merges/writes TB scalars on a
cadence (src/distributed_train.py:78-79,225,382-390) and the evaluator
writes Validation Accuracy / Validation Loss
(src/nn_eval.py:107-110), with TensorBoard pointed at the log dirs
(tools/tf_ec2.py:141-145).

The tfevents wire format is small and stable — length-prefixed records
with masked CRC32C checksums, each payload a serialized ``Event`` proto
— so the writer is implemented directly (no tensorflow/tensorboard
package dependency on the write side; compatibility with the real
reader is covered by tests). Only the fields the framework emits are
encoded: Event{wall_time=1, step=2, file_version=3, summary=5} and
Summary{value=1{tag=1, simple_value=2}}.
"""

from __future__ import annotations

import os
import socket
import struct
import time
from pathlib import Path

# --------------------------------------------------------------------------
# CRC32C (Castagnoli, reflected poly 0x82F63B78) + TF record masking
# --------------------------------------------------------------------------

_CRC_TABLE = []


def _crc_table() -> list[int]:
    if not _CRC_TABLE:
        for i in range(256):
            c = i
            for _ in range(8):
                c = (c >> 1) ^ (0x82F63B78 if c & 1 else 0)
            _CRC_TABLE.append(c)
    return _CRC_TABLE


def crc32c(data: bytes) -> int:
    table = _crc_table()
    crc = 0xFFFFFFFF
    for b in data:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# --------------------------------------------------------------------------
# minimal protobuf encoding (only what Event/Summary scalars need)
# --------------------------------------------------------------------------

def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        out.append(b | (0x80 if n else 0))
        if not n:
            return bytes(out)


def _key(field: int, wire: int) -> bytes:
    return _varint(field << 3 | wire)


def _f64(field: int, value: float) -> bytes:
    return _key(field, 1) + struct.pack("<d", value)


def _f32(field: int, value: float) -> bytes:
    return _key(field, 5) + struct.pack("<f", value)


def _i64(field: int, value: int) -> bytes:
    return _key(field, 0) + _varint(value & 0xFFFFFFFFFFFFFFFF)


def _bytes(field: int, value: bytes) -> bytes:
    return _key(field, 2) + _varint(len(value)) + value


def _event(wall_time: float, step: int | None = None,
           file_version: str | None = None,
           scalars: dict[str, float] | None = None) -> bytes:
    ev = _f64(1, wall_time)
    if step is not None:
        ev += _i64(2, step)
    if file_version is not None:
        ev += _bytes(3, file_version.encode())
    if scalars:
        summary = b"".join(
            _bytes(1, _bytes(1, tag.encode()) + _f32(2, float(v)))
            for tag, v in scalars.items())
        ev += _bytes(5, summary)
    return ev


# --------------------------------------------------------------------------
# writer
# --------------------------------------------------------------------------

class SummaryWriter:
    """Append-only tfevents scalar writer.

    ``add_scalars({"loss": 0.3}, step)`` buffers one Event record;
    ``flush()`` appends to disk. Files land as
    ``events.out.tfevents.<ts>.<host>`` under ``log_dir`` — exactly
    what ``tensorboard --logdir`` expects.
    """

    def __init__(self, log_dir: str | Path):
        self.log_dir = Path(log_dir)
        self.log_dir.mkdir(parents=True, exist_ok=True)
        ts = time.time()
        host = socket.gethostname() or "host"
        self.path = self.log_dir / f"events.out.tfevents.{ts:.6f}.{host}.{os.getpid()}"
        self._buf = bytearray(self._record(_event(ts, file_version="brain.Event:2")))
        self._closed = False

    @staticmethod
    def _record(payload: bytes) -> bytes:
        header = struct.pack("<Q", len(payload))
        return (header + struct.pack("<I", _masked_crc(header))
                + payload + struct.pack("<I", _masked_crc(payload)))

    def add_scalars(self, scalars: dict[str, float], step: int,
                    wall_time: float | None = None) -> None:
        if self._closed:
            raise RuntimeError("SummaryWriter is closed")
        ev = _event(wall_time if wall_time is not None else time.time(),
                    step=step, scalars=scalars)
        self._buf += self._record(ev)

    def add_scalar(self, tag: str, value: float, step: int,
                   wall_time: float | None = None) -> None:
        self.add_scalars({tag: value}, step, wall_time)

    def flush(self) -> None:
        if self._buf:
            with open(self.path, "ab") as f:
                f.write(self._buf)
            self._buf = bytearray()

    def close(self) -> None:
        if not self._closed:
            self.flush()
            self._closed = True
