"""Post-run invariant checking: is a *recovered* run a *correct* run?

PRs 1–3 built fault injection (``launch/exec.py`` FaultPlan) and fault
recovery (``launch/supervisor.py``, checkpoint fallback, NaN rollback);
every scenario so far asserted its own hand-written expectations. This
module is the machine-checked half of the chaos campaign
(``launch/chaos.py``): it replays a finished run's ARTIFACTS ALONE —
``train_log.jsonl``, the command journal, the recovery journals, the
checkpoint dir — and verifies the five end-to-end invariants any
survived fault schedule must satisfy:

1. **terminal_state** — the run reached its target step, or aborted
   only the way the quorum policy allows (a journaled
   ``below_quorum_abort`` with the restart budget respected).
2. **metrics_log** — the step series is gap-free and duplicate-free
   after rollback splicing, and every rewind in the log is explained
   by a journaled recovery event (an unexplained duplicate record is
   exactly how a buggy rollback would corrupt every downstream report).
3. **determinism** — a faulted-but-fully-recovered worker's final
   params AND optimizer state are BITWISE equal to a fault-free
   same-seed reference run's (``train/checkpoint.py`` params + opt
   digests; the canonical-layout save contract makes the opt-state
   half meaningful even for ZeRO-1 replica-sharded momentum).
4. **causality** — every ``restart`` is preceded by a ``detect``,
   every ``fallback_restore`` by a corruption/IO event: recovery
   actions without recorded causes mean the journal lies.
5. **checkpoint_integrity** — every digest sidecar in the checkpoint
   dir verifies (deliberately-torn fault targets journaled by the
   injector are exempt) and the manifest pointer resolves.
6. **reconfigure** — the cross-world resume invariant (elastic
   shrink/grow): a run whose final roster differs from its launch
   world must hold a journaled ``event: "reconfigure"`` record as the
   causal LICENSE for the change (a silently-reshaped run fails
   replay), the journaled transition must land on the world the
   artifacts actually show, and post-resize metrics must splice
   gap-free across the world change — each relaunch is an allowed
   rewind for the workers it respawned, and a GROWN worker (seeded
   from a survivor's checkpoint) may start its series mid-run. The
   bitwise determinism claim (invariant 3) keeps applying across the
   resize for the sync discipline: each local worker's compute is
   world-size-independent, so a fully recovered resized trial still
   reproduces the fault-free reference digest exactly.

Serving trials add four more (:func:`check_serving`): 7.
**serve_outcomes** (exactly one terminal outcome per issued request),
8. **serve_digest** (never serve a torn publish), 9.
**serve_monotone** (served step never goes backwards), and 10.
**decode_swap** (a weight swap mid-generation is licensed: a sequence
finishing on a different model step than it started on must hold a
journaled ``seq_restart``, and every restart must follow its
``weight_swap``). Network chaos trials (launch/netchaos.py proxies)
add 13. **net_faults** (:func:`check_net_faults`): exactly-once
outcomes under retry amplification — duplicate server-side admits of
one request id are legal only when licensed by a journaled retry or
``net_*`` fault, and every ``dedup_hit`` must follow a completed
terminal for that id on the same replica.

No cluster, supervisor, or trainer state is consulted — a report over
downloaded artifacts is as checkable as a live run, which is what lets
the chaos campaign shrink failing schedules by re-running and
re-checking mechanically.

The event vocabulary this module filters on (kinds, actions, required
fields) is declared ONCE in ``obsv/schema.py`` — the same registry the
emitters are checked against by graftcheck
(``distributedmnist_tpu.analysis``), so reader and writer cannot
drift.
"""

from __future__ import annotations

import dataclasses
import json
from pathlib import Path
from typing import Any, Callable

from . import schema
from .report import load_jsonl

INVARIANTS = ("terminal_state", "metrics_log", "determinism",
              "causality", "checkpoint_integrity", "reconfigure",
              "serve_outcomes", "serve_digest", "serve_monotone",
              "decode_swap", "serve_group", "autoscale", "discipline",
              "net_faults", "storage_faults")


@dataclasses.dataclass(frozen=True)
class Violation:
    invariant: str          # one of INVARIANTS
    detail: str
    worker: int | None = None

    def to_dict(self) -> dict[str, Any]:
        d = {"invariant": self.invariant, "detail": self.detail}
        if self.worker is not None:
            d["worker"] = self.worker
        return d


# ---------------------------------------------------------------------------
# (2) metrics log: rollback splicing + gap/duplicate checking
# ---------------------------------------------------------------------------

def splice_rollbacks(steps: list[dict]) -> tuple[list[dict], int]:
    """Replay the append-ordered step records through rewind-supersede
    splicing: when a record's step is <= the previous one (a rollback
    or restart-resume re-ran that span), the superseded suffix is
    dropped and the re-run records take its place — the same view a
    log consumer must take after any rollback. Returns the spliced
    series (strictly increasing by construction) and the number of
    rewinds observed."""
    out: list[dict] = []
    rewinds = 0
    for rec in steps:
        s = rec.get("step")
        if not isinstance(s, int):
            continue
        if out and s <= out[-1]["step"]:
            rewinds += 1
            while out and out[-1]["step"] >= s:
                out.pop()
        out.append(rec)
    return out, rewinds


def check_metrics_log(steps: list[dict], allowed_rewinds: int | None = None,
                      worker: int | None = None,
                      expect_first_step: int | None = 1) -> list[Violation]:
    """Invariant (2) over one worker's step records.

    ``allowed_rewinds``: how many rewinds the recovery journals justify
    (restarts + NaN rollbacks + reconfigure relaunches). None skips the
    explanation check (a bare log with no journal context). A rewind
    count EXCEEDING the justified one is how a doctored/duplicated
    record — or a rollback that re-emitted a window it already wrote —
    surfaces. ``expect_first_step``: where the spliced series must
    begin; None waives it (a GROWN worker seeded from a survivor's
    checkpoint legitimately starts mid-run)."""
    out: list[Violation] = []
    if not steps:
        return [Violation("metrics_log", "no step records at all", worker)]
    spliced, rewinds = splice_rollbacks(steps)
    if allowed_rewinds is not None and rewinds > allowed_rewinds:
        out.append(Violation(
            "metrics_log",
            f"{rewinds} rewind(s) in the step series but only "
            f"{allowed_rewinds} journaled recovery cause(s) — "
            "duplicated or re-emitted step records", worker))
    if (spliced and expect_first_step is not None
            and spliced[0]["step"] != expect_first_step):
        out.append(Violation(
            "metrics_log",
            f"spliced series starts at step {spliced[0]['step']}, not "
            f"{expect_first_step} (missing leading records)", worker))
    for prev, rec in zip(spliced, spliced[1:]):
        if rec["step"] != prev["step"] + 1:
            out.append(Violation(
                "metrics_log",
                f"gap in spliced series: step {prev['step']} -> "
                f"{rec['step']}", worker))
    return out


# ---------------------------------------------------------------------------
# (1) terminal-state legality and (4) journal causality
# ---------------------------------------------------------------------------

def check_terminal_state(outcome: dict, recovery_events: list[dict]
                         ) -> list[Violation]:
    """Invariant (1): ``outcome`` is the campaign's trial record
    ({"outcome", "step", "target", "supervisor": SupervisorConfig
    fields}); legality is judged against the journaled events."""
    out: list[Violation] = []
    target = outcome.get("target", 0)
    kind = outcome.get("outcome")
    aborts = [r for r in recovery_events
              if r.get("action") == "below_quorum_abort"]
    if kind == "completed":
        if outcome.get("step", -1) < target:
            out.append(Violation(
                "terminal_state",
                f"trial reported completed at step {outcome.get('step')} "
                f"< target {target}"))
        if aborts:
            out.append(Violation(
                "terminal_state",
                "completed trial has a below_quorum_abort event"))
    elif kind == "aborted":
        if not aborts:
            out.append(Violation(
                "terminal_state",
                "aborted without a journaled below_quorum_abort — the "
                "quorum policy never sanctioned this exit"))
        else:
            quorum = (outcome.get("supervisor") or {}).get("quorum")
            rec = aborts[-1]
            if (quorum is not None and rec.get("workers_alive") is not None
                    and rec["workers_alive"] >= quorum):
                out.append(Violation(
                    "terminal_state",
                    f"abort with workers_alive={rec['workers_alive']} >= "
                    f"quorum {quorum}"))
    else:
        out.append(Violation(
            "terminal_state",
            f"illegal terminal state {kind!r}: "
            f"{outcome.get('error', 'no error recorded')}"))
    # restart budget respected regardless of the terminal kind
    budget = (outcome.get("supervisor") or {}).get("max_restarts_per_worker")
    if budget is not None:
        per_worker: dict[int, int] = {}
        for r in recovery_events:
            if r.get("action") == "restart" and "worker" in r:
                per_worker[r["worker"]] = per_worker.get(r["worker"], 0) + 1
        for k, n in sorted(per_worker.items()):
            if n > budget:
                out.append(Violation(
                    "terminal_state",
                    f"{n} restarts > budget {budget}", k))
    return out


def check_causality(recovery_events: list[dict],
                    worker_events: dict[int, list[dict]]) -> list[Violation]:
    """Invariant (4). ``recovery_events``: the supervisor's records from
    the command journal; ``worker_events``: each worker's own
    ``recovery_journal.jsonl`` records."""
    out: list[Violation] = []
    chains: dict[int, list[str]] = {}
    for r in recovery_events:
        if "worker" in r:
            chains.setdefault(r["worker"], []).append(r.get("action", "?"))
    for k, chain in sorted(chains.items()):
        detects = restarts = 0
        for action in chain:
            detects += action == "detect"
            restarts += action == "restart"
            if restarts > detects:
                out.append(Violation(
                    "causality",
                    f"restart #{restarts} not preceded by a detect "
                    f"(chain: {chain})", k))
                break
    for k, events in sorted(worker_events.items()):
        causes = restores = 0
        for r in events:
            action = r.get("action")
            causes += action in ("corrupt_checkpoint_fallback",
                                 "rollback_candidate_unusable")
            restores += action == "fallback_restore"
            if restores > causes:
                out.append(Violation(
                    "causality",
                    "fallback_restore without a preceding corruption/IO "
                    "event in the worker recovery journal", k))
                break
    return out


# ---------------------------------------------------------------------------
# (5) checkpoint-dir integrity
# ---------------------------------------------------------------------------

def check_checkpoint_dir(logdir: str | Path, exempt: set[str] = frozenset(),
                         worker: int | None = None) -> list[Violation]:
    """Invariant (5) over one worker's logdir. ``exempt``: artifact
    names the command journal records as DELIBERATELY torn by the fault
    injector — finding those corrupt is the plan working, any other
    mismatch is damage nobody injected."""
    from ..train.checkpoint import CheckpointCorruptError, verify_artifact
    logdir = Path(logdir)
    out: list[Violation] = []
    for sidecar in sorted(logdir.glob("ckpt-*.sha256")):
        data_file = sidecar.with_suffix("")  # strip the .sha256 suffix
        if data_file.name in exempt:
            continue
        if not data_file.exists():
            out.append(Violation(
                "checkpoint_integrity",
                f"digest sidecar {sidecar.name} has no data file", worker))
            continue
        try:
            # the ONE sidecar contract (train/checkpoint.py) — the
            # checker must verify what the writer actually promises
            verify_artifact(data_file)
        except CheckpointCorruptError as e:
            out.append(Violation(
                "checkpoint_integrity", str(e), worker))
    pointer = logdir / "checkpoint.json"
    if pointer.exists():
        try:
            d = json.loads(pointer.read_text())
            target = logdir / d["latest_path"]
        except (json.JSONDecodeError, KeyError, TypeError) as e:
            out.append(Violation(
                "checkpoint_integrity",
                f"checkpoint.json unreadable ({e})", worker))
        else:
            if not target.exists():
                out.append(Violation(
                    "checkpoint_integrity",
                    f"pointer names {target.name} which does not exist",
                    worker))
    return out


# ---------------------------------------------------------------------------
# (3) exact-resume determinism
# ---------------------------------------------------------------------------

def determinism_verdict(logdir: str | Path, reference_dir: str | Path,
                        worker: int | None = None,
                        reference_digest: tuple[str, int] | None = None,
                        reference_opt_digest: tuple[str, int] | None = None,
                        ) -> tuple[bool, list[Violation]]:
    """Invariant (3): the worker's final checkpoint params AND
    optimizer state must be BITWISE equal to the fault-free same-seed
    reference run's. The opt-state half compares the artifact's
    canonical-layout ``momentum`` subtree (train/checkpoint.py
    ``checkpoint_opt_state_digest``) — covered, not skipped, when the
    run sharded its weight update (ZeRO-1), because checkpoints always
    store the logical layout.

    Returns ``(checked, violations)``. The comparison only applies to a
    FULLY recovered worker — one whose latest loadable checkpoint
    reached the reference's final step; a worker left behind (exhausted
    restart budget, or a latest checkpoint the injector deliberately
    tore and nothing ever re-saved) yields ``checked=False`` rather
    than a comparison against a further-along reference."""
    from ..train.checkpoint import (CheckpointCorruptError,
                                    checkpoint_state_digests)
    try:
        if reference_digest is not None:
            ref, ref_opt = reference_digest, reference_opt_digest
        else:
            both = checkpoint_state_digests(reference_dir)
            ref, ref_opt = ((None, None) if both is None else
                            ((both[0], both[2]), (both[1], both[2])))
    except CheckpointCorruptError as e:
        return True, [Violation(
            "determinism", f"reference checkpoint unreadable: {e}", worker)]
    if ref is None:
        # the payload writes no real checkpoints (shell smoke runs):
        # there is no bitwise claim to make — skipped, not violated
        return False, []
    try:
        both = checkpoint_state_digests(logdir)  # ONE artifact read
    except CheckpointCorruptError:
        return False, []  # torn latest, never re-saved: not recovered
    if both is None or both[2] != ref[1]:
        return False, []  # never reached the reference step
    got_params, got_opt, at_step = both
    out: list[Violation] = []
    if got_params != ref[0]:
        out.append(Violation(
            "determinism",
            f"final params at step {at_step} differ bitwise from the "
            f"fault-free reference ({got_params[:12]}… != {ref[0][:12]}…)",
            worker))
    if ref_opt is not None and got_opt != ref_opt[0]:
        out.append(Violation(
            "determinism",
            f"optimizer state at step {at_step} differs bitwise from "
            f"the fault-free reference ({got_opt[:12]}… != "
            f"{ref_opt[0][:12]}…)", worker))
    return True, out


# ---------------------------------------------------------------------------
# (6) cross-world resume (elastic reconfigure)
# ---------------------------------------------------------------------------

def check_reconfigure(trial_dir: str | Path, outcome: dict,
                      journal_records: list[dict]
                      ) -> tuple[list[Violation], bool, set[int],
                                 dict[int, int]]:
    """Invariant (6) over the artifacts alone. Returns
    ``(violations, applicable, grown_workers, relaunch_counts)`` —
    ``applicable`` False when the run neither reshaped nor claims to
    have (verdict: skipped); ``grown_workers`` are ids whose logdirs
    were seeded mid-run (their metric series may start mid-run);
    ``relaunch_counts`` maps worker → number of journaled reconfigure
    relaunches that respawned it (each one licenses a log rewind).

    The causal-license rule: the launch world is ``outcome
    ["num_workers"]``; the final world is what the backend's
    ``state.json`` artifact shows. A difference with NO journaled
    ``event: "reconfigure"`` record fails — a run that silently
    changed shape must not replay green. When reconfigure events DO
    exist, the last journaled reshape must land on the world the
    artifacts show."""
    trial_dir = Path(trial_dir)
    reconf = [r for r in journal_records
              if r.get("event") == schema.RECONFIGURE]
    reshapes = [r for r in reconf if r.get("action") == "reshape"]
    relaunches = [r for r in reconf if r.get("action") == "relaunched"]
    grown = {int(k) for r in reshapes for k in (r.get("grown") or {})}
    relaunch_counts: dict[int, int] = {}
    for r in (relaunches or reshapes):
        for k in r.get("workers", []):
            relaunch_counts[k] = relaunch_counts.get(k, 0) + 1

    final_ids: list[int] | None = None
    state_path = trial_dir / "state.json"
    if state_path.exists():
        try:
            st = json.loads(state_path.read_text())
            final_ids = sorted(int(w["worker"])
                               for w in st.get("workers", []))
        except (json.JSONDecodeError, KeyError, TypeError, ValueError):
            final_ids = None
    initial = outcome.get("num_workers")

    out: list[Violation] = []
    world_changed = (final_ids is not None and initial is not None
                     and len(final_ids) != initial)
    if world_changed and not reconf:
        out.append(Violation(
            "reconfigure",
            f"world changed {initial} -> {len(final_ids)} workers "
            f"(roster {final_ids}) with no journaled reconfigure event "
            "— no causal license for the resize"))
    if reshapes and final_ids is not None:
        last = sorted(int(k) for k in reshapes[-1].get("workers", []))
        if last and last != final_ids:
            out.append(Violation(
                "reconfigure",
                f"journaled reconfigure lands on roster {last} but the "
                f"artifacts show {final_ids} — the journal and the "
                "cluster state disagree about the final world"))
    # a trial that claims a final world must match the artifact too
    claimed = outcome.get("final_world")
    if (claimed is not None and final_ids is not None
            and claimed != len(final_ids)):
        out.append(Violation(
            "reconfigure",
            f"outcome claims final_world={claimed} but state.json shows "
            f"{len(final_ids)} workers"))
    applicable = bool(reconf) or world_changed
    return out, applicable, grown, relaunch_counts


# ---------------------------------------------------------------------------
# (11) autoscale: every roster change in a brokered run is licensed
# ---------------------------------------------------------------------------

def check_autoscale(outcome: dict, journal_records: list[dict]
                    ) -> tuple[list[Violation], bool]:
    """Invariant (11), replayed from the journal alone. Returns
    ``(violations, applicable)`` — not applicable (verdict: skipped)
    for runs with no broker and no autoscale records.

    The causal-license rule, same discipline as invariant 6: a
    brokered run's roster may only change because a recorded signal
    crossed its recorded threshold. Three claims:

    * every ``autoscale begin`` carries a license that actually holds
      — ``value op threshold`` must be true of the numbers the broker
      itself journaled (a begin whose own evidence contradicts it is
      a fabricated license);
    * decisions are single-flight and closed: each begin is followed
      by its ``complete`` or ``error`` before the next begin (the
      broker's cooldown-from-settlement discipline), and no begin is
      left dangling at the end of the run;
    * every cluster ``reshape`` in a brokered run is consumed against
      a preceding unconsumed license — an ``autoscale begin`` or a
      supervisor ``reconfigure begin`` (fault-path reshapes keep
      their own license) — and a reshape consuming an autoscale
      license must land on the world that begin declared
      (``new_serve + new_train``). Silent scaling fails replay.
    """
    recs = [r for r in journal_records
            if r.get("event") == schema.AUTOSCALE]
    applicable = bool(recs) or bool(outcome.get("broker"))
    out: list[Violation] = []
    if not applicable:
        return out, False

    open_begin: dict | None = None
    for r in recs:
        action = r.get("action")
        if action == "begin":
            v, thr, op = r.get("value"), r.get("threshold"), r.get("op")
            if not (isinstance(v, (int, float))
                    and isinstance(thr, (int, float))
                    and op in (">=", "<=")):
                out.append(Violation(
                    "autoscale",
                    f"autoscale begin ({r.get('decision')}) with a "
                    f"malformed license: value={v!r} op={op!r} "
                    f"threshold={thr!r}"))
            elif not (v >= thr if op == ">=" else v <= thr):
                out.append(Violation(
                    "autoscale",
                    f"autoscale begin ({r.get('decision')}) licensed by "
                    f"{r.get('trigger')}={v} {op} {thr}, which does not "
                    "hold — the recorded signal never crossed the "
                    "recorded threshold"))
            if open_begin is not None:
                out.append(Violation(
                    "autoscale",
                    "overlapping autoscale decisions: a second begin "
                    f"({r.get('decision')}) before the previous one "
                    f"({open_begin.get('decision')}) completed — the "
                    "broker is single-flight by construction"))
            open_begin = r
        elif action in ("complete", "error"):
            open_begin = None
    if open_begin is not None:
        out.append(Violation(
            "autoscale",
            f"autoscale begin ({open_begin.get('decision')}) never "
            "closed by a complete or error record"))

    # license-consumption walk over the whole journal, in order
    licenses: list[dict | None] = []  # None = supervisor reconfigure
    for r in journal_records:
        ev, action = r.get("event"), r.get("action")
        if ev == schema.AUTOSCALE and action == "begin":
            licenses.append(r)
        elif (ev == schema.RECONFIGURE and action == "begin"
                and r.get("layer") == "supervisor"):
            licenses.append(None)
        elif ev == schema.RECONFIGURE and action == "reshape":
            if not licenses:
                out.append(Violation(
                    "autoscale",
                    f"roster reshape {r.get('old_world')} -> "
                    f"{r.get('new_world')} with no preceding autoscale "
                    "or reconfigure begin — an unlicensed roster change "
                    "in a brokered run"))
                continue
            lic = licenses.pop()
            if lic is not None:
                want = lic.get("new_serve", 0) + lic.get("new_train", 0)
                got_world = r.get("new_world")
                if isinstance(got_world, int) and got_world != want:
                    out.append(Violation(
                        "autoscale",
                        f"reshape lands on world {got_world} but its "
                        f"licensing autoscale begin declared "
                        f"{lic.get('new_serve')} serving + "
                        f"{lic.get('new_train')} train = {want}"))
    return out, True


# ---------------------------------------------------------------------------
# (12) discipline: every adaptive-controller parameter change licensed
# ---------------------------------------------------------------------------

def check_discipline(steps: list[dict], log_records: list[dict],
                     worker: int | None = None
                     ) -> tuple[list[Violation], bool]:
    """Invariant (12) over one worker's train log. Returns
    ``(violations, applicable)`` — not applicable (verdict: skipped)
    when the log carries neither discipline events nor per-step
    discipline observations (controller never armed).

    The causal-license rule, same discipline as invariants 6/11, with
    the step series itself as the observation channel: adaptive mode
    stamps every step record with the ``[k, timeout_ms]`` pair in force
    (obsv/schema.py STEP optional), so a parameter change is OBSERVED
    as two adjacent spliced step records disagreeing. Three claims:

    * every ``discipline begin`` carries a license that actually holds
      — ``value op threshold`` re-checked with the emitter's OWN
      predicate (train/discipline.py ``threshold_holds``), so a begin
      whose recorded CDF signal never crossed the recorded mark is a
      fabricated license;
    * episodes are single-flight and closed: begin → its ``complete``
      (agreeing on the new pair) before the next begin, none dangling,
      and each complete's ``effective_step`` is exactly the step after
      its begin's ``at_step`` — the epoch boundary;
    * every OBSERVED pair change in the spliced step series is consumed
      against a licensed complete naming that exact boundary and pair
      — a doctored step record (or a deleted begin) fails replay.

    Rollback tolerance: the series is spliced first (the invariant-2
    view), and a licensed change whose boundary step was superseded by
    a rewind simply goes unconsumed — licenses are permissions, not
    obligations."""
    from ..train.discipline import threshold_holds
    disc = [r for r in log_records
            if r.get("event") == schema.DISCIPLINE]
    observed = [r for r in steps if "discipline" in r]
    applicable = bool(disc) or bool(observed)
    out: list[Violation] = []
    if not applicable:
        return out, False

    # -- license validity + single-flight pairing ----------------------
    completes: list[dict] = []
    open_begin: dict | None = None
    for r in disc:
        action = r.get("action")
        if action == "begin":
            v, thr, op = r.get("value"), r.get("threshold"), r.get("op")
            if not (isinstance(v, (int, float))
                    and isinstance(thr, (int, float))
                    and op in (">=", "<=")):
                out.append(Violation(
                    "discipline",
                    f"discipline begin ({r.get('decision')}) with a "
                    f"malformed license: value={v!r} op={op!r} "
                    f"threshold={thr!r}", worker))
            elif not threshold_holds(v, op, thr):
                out.append(Violation(
                    "discipline",
                    f"discipline begin ({r.get('decision')}) licensed "
                    f"by {r.get('trigger')}={v} {op} {thr}, which does "
                    "not hold — the recorded CDF signal never crossed "
                    "the recorded percentile mark", worker))
            if open_begin is not None:
                out.append(Violation(
                    "discipline",
                    "overlapping discipline decisions: a second begin "
                    f"({r.get('decision')}) before the previous one "
                    f"({open_begin.get('decision')}) completed — the "
                    "controller is single-flight by construction",
                    worker))
            open_begin = r
        elif action == "complete":
            if open_begin is None:
                out.append(Violation(
                    "discipline",
                    f"discipline complete ({r.get('decision')}) with no "
                    "open begin — an unlicensed change record", worker))
            else:
                b = open_begin
                if (r.get("k") != b.get("new_k")
                        or r.get("timeout_ms") != b.get("new_timeout_ms")):
                    out.append(Violation(
                        "discipline",
                        f"discipline complete lands on (k={r.get('k')}, "
                        f"timeout_ms={r.get('timeout_ms')}) but its "
                        f"begin declared (k={b.get('new_k')}, "
                        f"timeout_ms={b.get('new_timeout_ms')})", worker))
                at, eff = b.get("at_step"), r.get("effective_step")
                if (isinstance(at, int) and isinstance(eff, int)
                        and eff != at + 1):
                    out.append(Violation(
                        "discipline",
                        f"discipline epoch boundary mismatch: begin at "
                        f"step {at} but complete claims effective_step "
                        f"{eff} (must be {at + 1})", worker))
            completes.append(r)
            open_begin = None
    if open_begin is not None:
        out.append(Violation(
            "discipline",
            f"discipline begin ({open_begin.get('decision')}) never "
            "closed by a complete record", worker))

    # -- observed-change consumption over the spliced series -----------
    spliced, _ = splice_rollbacks(observed)
    licenses = list(completes)  # consumed in order
    prev: dict | None = None
    for rec in spliced:
        pair = rec.get("discipline")
        if prev is not None and pair != prev.get("discipline"):
            lic = None
            while licenses:
                cand = licenses.pop(0)
                if cand.get("effective_step") == rec.get("step"):
                    lic = cand
                    break
                # boundary superseded by a rewind (or predates this
                # span): an unconsumed permission, not a violation
            want = (None if lic is None else
                    [float(lic.get("k", -1)),
                     float(lic.get("timeout_ms", -1))])
            got = ([float(x) for x in pair]
                   if isinstance(pair, (list, tuple)) else pair)
            if lic is None:
                out.append(Violation(
                    "discipline",
                    f"step {rec.get('step')} observed a discipline "
                    f"change {prev.get('discipline')} -> {pair} with no "
                    "licensing complete at that boundary — an "
                    "unlicensed parameter change", worker))
            elif got != want:
                out.append(Violation(
                    "discipline",
                    f"step {rec.get('step')} observed discipline {got} "
                    f"but the licensing complete declared {want}",
                    worker))
        prev = rec
    return out, True


# ---------------------------------------------------------------------------
# (7-9) serving invariants (the online inference tier under chaos)
# ---------------------------------------------------------------------------

_SERVE_CKPT_STEP = None  # lazy import of the checkpoint name regex


def _ckpt_name_step(name: str) -> int | None:
    global _SERVE_CKPT_STEP
    if _SERVE_CKPT_STEP is None:
        import re
        _SERVE_CKPT_STEP = re.compile(r"^ckpt-(\d+)")
    m = _SERVE_CKPT_STEP.match(name)
    return int(m.group(1)) if m else None


def check_serving(trial_dir: str | Path, outcome: dict,
                  journal_records: list[dict]
                  ) -> tuple[list[Violation], bool, set[int], bool]:
    """The serving invariants, replayed from artifacts alone.
    Returns ``(violations, applicable, serve_workers,
    decode_applicable)`` — not applicable (all verdicts: skipped) for
    trials with no serving tier; ``decode_applicable`` True only when
    some replica's journal shows the decode workload (the
    ``decode_swap`` invariant is skipped otherwise).

    * **serve_outcomes** — every request the load generator issued has
      EXACTLY one terminal outcome (response or typed reject/error; no
      silent drops), and on every serving replica the admitted-request
      count equals the admitted-terminal count — except on replicas
      the run faulted or restarted (a SIGKILLed replica's in-flight
      admissions legitimately died with it; the CLIENT side still had
      to reach a terminal outcome for those requests via failover).
    * **serve_digest** — no weight swap installed a checkpoint AFTER
      the injector journaled tearing that step's artifact: digest
      verification (plus fallback-to-previous-loadable) must have
      skipped it. Swaps predating the tear served the then-intact
      bytes and are correct. Covers the quantized ``.quant`` sidecar
      tiers too: a swap that records which artifact it read
      (``source_artifact``) is matched against the torn target by
      NAME — a replica that served the intact fp32 artifact after
      only the sidecar was torn (or vice versa) is digest
      verification working, not a violation; legacy swaps without the
      field keep the historical step-based match.
    * **serve_monotone** — each replica's journaled ``weight_swap``
      step series is monotone non-decreasing (across restarts too: the
      publisher's steps only advance).
    * **decode_swap** — swap-during-generation bookkeeping (decode
      replicas, invariant 10): a sequence that finishes on a model
      step other than the one it started on (``decode_finish``'s
      ``model_step`` vs ``started_step``) must hold a journaled
      ``seq_restart`` license for that id — the restart policy's
      re-prefill — and every ``seq_restart``'s target step must be
      licensed by an earlier journaled ``weight_swap`` to that step.
      Under the pin policy no sequence ever changes step mid-flight,
      so any unlicensed drift is a replica serving mixed weights —
      the silent-corruption mode this invariant exists to catch.
    """
    trial_dir = Path(trial_dir)
    serve_workers = {int(k) for k in (outcome.get("serve_workers") or [])}
    if not serve_workers:
        # artifact-only replay: a serving replica is a worker dir with
        # a serve journal
        serve_workers = {k for k, d in _worker_dirs(trial_dir).items()
                        if (d / "serve_log.jsonl").exists()}
    loadgen = trial_dir / "loadgen.jsonl"
    applicable = bool(serve_workers) or loadgen.exists()
    if not applicable:
        return [], False, set(), False
    out: list[Violation] = []
    decode_applicable = False

    # ---- (a) client side: issued ↔ exactly-one-terminal ----------------
    load_records = load_jsonl(loadgen, schema.LOAD)
    issued: dict[Any, int] = {}
    terminal: dict[Any, int] = {}
    for r in load_records:
        if r.get("action") == "issue":
            issued[r.get("id")] = issued.get(r.get("id"), 0) + 1
        elif r.get("action") == "outcome":
            terminal[r.get("id")] = terminal.get(r.get("id"), 0) + 1
    dropped = [i for i, n in issued.items() if terminal.get(i, 0) < n]
    doubled = [i for i, n in terminal.items() if n > issued.get(i, 0)]
    if dropped:
        out.append(Violation(
            "serve_outcomes",
            f"{len(dropped)} issued request(s) never reached a terminal "
            f"outcome (silent drop), e.g. ids {sorted(dropped)[:5]}"))
    if doubled:
        out.append(Violation(
            "serve_outcomes",
            f"request ids with more terminal outcomes than issues: "
            f"{sorted(doubled)[:5]} — the load journal lies"))

    # workers the run faulted/killed/restarted: their in-flight
    # admissions may legitimately have died server-side. Network
    # faults license too: the ``net_*`` actions (launch/netchaos.py
    # proxies) journal the PROXIED replica as ``worker``, so a replica
    # whose link was reset/partitioned/blackholed mid-request is
    # exempt from the admit↔terminal books the same way a SIGKILLed
    # one is — the client still owes every request a terminal.
    exempt: set[int] = set()
    for r in journal_records:
        if r.get("event") == schema.FAULT and isinstance(r.get("worker"), int):
            exempt.add(r["worker"])
        if (r.get("event") == schema.RECOVERY and r.get("action") == "restart"
                and isinstance(r.get("worker"), int)):
            exempt.add(r["worker"])

    corrupt_faults = [
        r for r in journal_records
        if r.get("event") == schema.FAULT
        and r.get("action") == "corrupt_latest_checkpoint"
        and r.get("target")]

    workers = _worker_dirs(trial_dir)
    for k in sorted(serve_workers):
        d = workers.get(k)
        if d is None:
            continue
        recs = load_jsonl(d / "serve_log.jsonl", schema.SERVE)
        if not recs:
            out.append(Violation(
                "serve_outcomes", "serving replica left no serve journal "
                "at all", k))
            continue
        # ---- (a) server side: admits ↔ admitted terminals ------------
        # (a classification replica's terminal is "respond", a decode
        # replica's is "decode_finish" — both close an admit)
        admits = sum(1 for r in recs if r.get("action") == "admit")
        responds = sum(1 for r in recs
                       if r.get("action") in ("respond", "decode_finish"))
        admitted_rejects = sum(1 for r in recs
                               if r.get("action") == "reject"
                               and r.get("admitted"))
        if k not in exempt and admits != responds + admitted_rejects:
            out.append(Violation(
                "serve_outcomes",
                f"{admits} admitted request(s) but "
                f"{responds + admitted_rejects} admitted-terminal "
                "outcome(s) on an unfaulted replica — admitted work "
                "vanished without a response or a typed reject", k))
        # ---- (b) never serve a torn publish --------------------------
        swaps = [r for r in recs if r.get("action") == "weight_swap"]
        for sw in swaps:
            step = sw.get("step")
            at = sw.get("time", sw.get("ts"))
            src = sw.get("source_artifact")
            for f in corrupt_faults:
                torn_name = str(f["target"])
                torn_step = _ckpt_name_step(torn_name)
                f_at = f.get("ts", f.get("time"))
                if src is not None and src != torn_name:
                    # the swap names the artifact it read and it is
                    # NOT the torn one (e.g. the intact quant sidecar
                    # while the fp32 artifact was torn) — different
                    # bytes, different digest, no claim violated
                    continue
                if not (torn_step is not None and step == torn_step
                        and isinstance(at, (int, float))
                        and isinstance(f_at, (int, float))):
                    continue
                # the flip is a batch boundary AFTER the read: judge
                # by when the READ began (time − swap_ms), not when
                # the reference flipped — bytes read intact before
                # the tear may legitimately install after it. Any
                # swap whose read STARTED after the tear had to pass
                # the digest check on torn bytes: impossible unless
                # verification failed.
                swap_ms = sw.get("swap_ms")
                read_at = (at - swap_ms / 1e3
                           if isinstance(swap_ms, (int, float)) else at)
                if read_at > f_at:
                    out.append(Violation(
                        "serve_digest",
                        f"weight_swap installed step {step} (read began "
                        f"t={read_at:.3f}) AFTER its artifact "
                        f"{f['target']} was torn at t={f_at:.3f} — "
                        "digest verification failed to refuse it", k))
        # ---- (c) served step monotone non-decreasing -----------------
        # Per INCARNATION: the journal is append-mode across restarts,
        # and a restarted replica whose newest publish was torn
        # legitimately boots on the previous loadable step (its
        # ``initial: true`` swap may land BELOW the dead incarnation's
        # last step — that is digest verification working, not a
        # regression). Within an incarnation, backwards is always a
        # violation.
        prev: int | None = None
        for sw in swaps:
            step = sw.get("step")
            if not isinstance(step, int):
                continue
            if sw.get("initial"):
                prev = step  # a fresh incarnation restarts the scan
                continue
            if prev is not None and step < prev:
                out.append(Violation(
                    "serve_monotone",
                    f"served model step went backwards across swaps: "
                    f"{prev} -> {step}", k))
                break
            prev = step
        # ---- (d) swap-during-generation (decode replicas) ------------
        # One ordered pass over the journal: the license must EXIST
        # BEFORE it is used (a seq_restart must follow the weight_swap
        # it targets; a drifted finish must follow ITS OWN sequence's
        # restart), and a license is consumed at the finish — request
        # ids recycle across sweeps in one journal, so a stale restart
        # from an earlier generation must not launder a later one's
        # mixed-weights finish.
        if any(r.get("action") in ("decode_start", "decode_finish",
                                   "seq_restart") for r in recs):
            decode_applicable = True
            seen_swap_steps: set = set()
            licensed_to: dict = {}  # id -> to_step of its live restart
            for r in recs:
                action = r.get("action")
                if action == "weight_swap":
                    seen_swap_steps.add(r.get("step"))
                elif action == "seq_restart":
                    if r.get("to_step") not in seen_swap_steps:
                        out.append(Violation(
                            "decode_swap",
                            f"seq_restart of {r.get('id')!r} targets "
                            f"step {r.get('to_step')} before any "
                            "journaled weight_swap to that step — a "
                            "restart without its causal swap", k))
                    licensed_to[r.get("id")] = r.get("to_step")
                elif action == "decode_finish":
                    st, ms = r.get("started_step"), r.get("model_step")
                    if (isinstance(st, int) and isinstance(ms, int)
                            and st != ms
                            and licensed_to.get(r.get("id")) != ms):
                        out.append(Violation(
                            "decode_swap",
                            f"sequence {r.get('id')!r} finished on "
                            f"model step {ms} but started on {st} with "
                            "no live seq_restart license to that step "
                            "— the replica served mixed weights "
                            "mid-generation", k))
                    licensed_to.pop(r.get("id"), None)
    return out, True, serve_workers, decode_applicable


def check_serve_group(trial_dir: str | Path
                      ) -> tuple[list[Violation], bool]:
    """**serve_group** — die-as-a-unit for tensor-parallel serving
    groups (servesvc/tp_group.py), replayed from ``group_log.jsonl``.

    A TP replica is one process group; a group missing a rank holds
    only part of every sharded weight, so it must NEVER keep (or
    resume) serving half-dead.  The supervisor's journal chain makes
    that checkable: every ``rank_exit`` must be answered by a
    ``group_down`` (all surviving ranks killed) before any later
    ``group_start`` (the unit restart), and restart ``attempt``
    numbers only move forward — a supervisor looping without
    acknowledging teardown is exactly the bug this invariant exists
    to catch.  Applicable only to workers that left a group journal;
    returns ``(violations, applicable)``."""
    trial_dir = Path(trial_dir)
    out: list[Violation] = []
    applicable = False
    for k, d in sorted(_worker_dirs(trial_dir).items()):
        glog = d / "group_log.jsonl"
        if not glog.exists():
            continue
        applicable = True
        recs = load_jsonl(glog, schema.SERVE)
        pending_exit: Any = None   # rank of an unanswered rank_exit
        started = False
        last_attempt = -1
        for r in recs:
            a = r.get("action")
            if a == "group_start":
                if pending_exit is not None:
                    out.append(Violation(
                        "serve_group",
                        f"group restarted after rank {pending_exit} "
                        "exited with no group_down in between — a "
                        "half-dead TP group was never torn down as a "
                        "unit", k))
                    pending_exit = None
                att = r.get("attempt")
                if isinstance(att, int):
                    if started and att <= last_attempt:
                        out.append(Violation(
                            "serve_group",
                            f"group_start attempt went backwards "
                            f"({last_attempt} -> {att}) — the restart "
                            "budget scan is meaningless", k))
                    last_attempt = att
                started = True
            elif a == "rank_exit":
                pending_exit = r.get("rank")
            elif a == "group_down":
                pending_exit = None
        if pending_exit is not None:
            out.append(Violation(
                "serve_group",
                f"rank {pending_exit} exited and no group_down ever "
                "followed — the group may have kept serving with a "
                "missing shard", k))
    return out, applicable


# ---------------------------------------------------------------------------
# (13) net_faults: exactly-once outcomes under retry amplification
# ---------------------------------------------------------------------------

def check_net_faults(trial_dir: str | Path, outcome: dict,
                     journal_records: list[dict]
                     ) -> tuple[list[Violation], bool]:
    """Invariant (13), replayed from artifacts alone. Returns
    ``(violations, applicable)`` — not applicable (verdict: skipped)
    when the trial shows no network-fault evidence at all: no
    journaled ``net_*`` fault, no ``dedup_hit`` in any serve journal,
    and no retried client terminal.

    Network faults (launch/netchaos.py) make requests ARRIVE more
    than once — a mid-stream reset or partition forces the client to
    retry an id on a sibling, or on the same replica after its
    connection died. The hardened protocol's claim is exactly-once
    OUTCOMES, not exactly-once arrivals, and this invariant holds the
    books to it:

    * **exactly one client terminal per issue, globally** — retry
      amplification (``attempts`` > 1) must never surface as a second
      terminal outcome for one id; the failover loop returns one.
    * **duplicate admits are licensed** — a request id admitted more
      than once across the roster (double execution) is legal only
      when the client journaled a retry for that id or a ``net_*``
      fault was journaled against one of the replicas involved;
      an unlicensed duplicate admit is the server double-executing a
      request nobody resent.
    * **dedup hits are honest** — a ``dedup_hit`` record must FOLLOW
      a completed terminal (``respond``/``decode_finish``) for that
      id on the same replica, in journal order: both server paths
      journal the terminal before populating the cache (the journal
      lock serializes the writes), so a hit with no prior terminal is
      a cache returning an outcome it never computed.
    """
    trial_dir = Path(trial_dir)
    net_faults = [r for r in journal_records
                  if r.get("event") == schema.FAULT
                  and str(r.get("action", "")).startswith("net_")]
    net_faulted = {r["worker"] for r in net_faults
                   if isinstance(r.get("worker"), int)}

    # client side: per-id issue/terminal books + retry licenses
    load_records = load_jsonl(trial_dir / "loadgen.jsonl", schema.LOAD)
    issued: dict[Any, int] = {}
    terminal: dict[Any, int] = {}
    retried_ids: set = set()
    for r in load_records:
        if r.get("action") == "issue":
            issued[r.get("id")] = issued.get(r.get("id"), 0) + 1
        elif r.get("action") == "outcome":
            terminal[r.get("id")] = terminal.get(r.get("id"), 0) + 1
            attempts = r.get("attempts")
            if r.get("retried") or (isinstance(attempts, int)
                                    and attempts > 1):
                retried_ids.add(r.get("id"))

    # server side: admits per id across the roster + dedup honesty
    out: list[Violation] = []
    admits_by_id: dict[Any, list[int]] = {}
    dedup_hits = 0
    for k, d in sorted(_worker_dirs(trial_dir).items()):
        recs = load_jsonl(d / "serve_log.jsonl", schema.SERVE)
        completed: set = set()  # ids with a terminal SO FAR, in order
        for r in recs:
            action = r.get("action")
            if action == "admit":
                admits_by_id.setdefault(r.get("id"), []).append(k)
            elif action in ("respond", "decode_finish"):
                completed.add(r.get("id"))
            elif action == "dedup_hit":
                dedup_hits += 1
                if r.get("id") not in completed:
                    out.append(Violation(
                        "net_faults",
                        f"dedup_hit for id {r.get('id')!r} with no "
                        "earlier completed terminal for that id on this "
                        "replica — the cache returned an outcome it "
                        "never computed", k))

    applicable = bool(net_faults) or dedup_hits > 0 or bool(retried_ids)
    if not applicable:
        return [], False

    for i in sorted(issued, key=str):
        if terminal.get(i, 0) > issued[i]:
            out.append(Violation(
                "net_faults",
                f"request id {i!r} issued {issued[i]}x but reached "
                f"{terminal[i]} terminal outcomes — retry amplification "
                "leaked a duplicate terminal to the client"))
    for i, ks in sorted(admits_by_id.items(), key=str):
        if len(ks) <= 1 or i in retried_ids:
            continue
        if any(k in net_faulted for k in ks):
            continue
        out.append(Violation(
            "net_faults",
            f"request id {i!r} admitted {len(ks)}x (replicas "
            f"{sorted(set(ks))}) with no journaled retry or net fault "
            "licensing the duplicate — an unlicensed double execution"))
    return out, True


# ---------------------------------------------------------------------------
# (14) storage-fault licensing + atomic-save protocol ordering
# ---------------------------------------------------------------------------

# injector actions that surface to the writer as an OSError — the only
# firings that can license a skipped cadence save (train/storage.py)
_DISK_ERROR_ACTIONS = ("disk_enospc", "disk_eio", "disk_torn_write")
# injector actions that leave CORRUPT bytes behind (a torn prefix, a
# power-cut rename) — the only firings that can license a restore
# walking past a checkpoint, and the targets invariant (5) must exempt
_DISK_CORRUPT_ACTIONS = ("disk_torn_write", "disk_crash_rename")


def load_storage_faults(trial_dir: str | Path) -> dict[int, list[dict]]:
    """{worker: [fault records]} from each worker's own
    ``storage_faults.jsonl`` — the disk injector journals from INSIDE
    the faulted process (train/storage.py), so its evidence lives next
    to the worker's checkpoints, not in the supervisor's command
    journal. Keyed by the logdir's worker id (the injector stamps the
    same id on every record)."""
    out: dict[int, list[dict]] = {}
    for k, d in _worker_dirs(Path(trial_dir)).items():
        recs = load_jsonl(d / "storage_faults.jsonl", schema.FAULT)
        if recs:
            out[k] = recs
    return out


def storage_exempt_targets(storage_faults: dict[int, list[dict]]
                           ) -> dict[int, set[str]]:
    """{worker: {artifact names}} the disk injector journaled as
    deliberately corrupted (torn prefix / power-cut rename) — exempt
    from invariant (5), same standing as the supervisor's
    ``corrupt_latest_checkpoint`` targets."""
    out: dict[int, set[str]] = {}
    for k, recs in storage_faults.items():
        for r in recs:
            if (r.get("action") in _DISK_CORRUPT_ACTIONS
                    and r.get("path")):
                out.setdefault(k, set()).add(r["path"])
    return out


def check_storage_faults(trial_dir: str | Path,
                         journal_records: list[dict],
                         worker_events: dict[int, list[dict]] | None = None,
                         storage_faults: dict[int, list[dict]] | None = None
                         ) -> tuple[list[Violation], bool]:
    """Invariant (14), replayed from artifacts alone. Returns
    ``(violations, applicable)`` — not applicable (verdict: skipped)
    when the trial shows no storage-fault evidence at all: no
    journaled ``disk_*`` firing in any worker's storage_faults.jsonl
    and no ``save_failed`` in any recovery journal.

    Disk faults (train/storage.py) make durable writes FAIL or LIE —
    a full disk mid-checkpoint, a write that lands only a prefix, a
    rename whose data never hit the platter. The storage shim's claim
    is graceful degradation plus crash consistency, and this invariant
    holds the books to it:

    * **every skipped save is licensed** — a ``save_failed`` record
      (the trainer journaling that it SKIPPED a cadence save and kept
      training) is legal only when that worker's injector journaled an
      error-surfacing firing (ENOSPC / EIO / torn write); an
      unlicensed save_failed is real storage damage nobody injected.
    * **every fallback is licensed** — a worker whose restore walked
      past a corrupt checkpoint (``corrupt_checkpoint_fallback`` /
      ``fallback_restore``) must show an injected corruption for that
      worker: a supervisor ``corrupt_latest_checkpoint`` firing or an
      injector torn-write/crash-rename firing. Unlicensed corruption
      at restore time means bytes rotted with no fault scripted.
    * **no resumable bytes without a landed digest** — the atomic-save
      protocol orders data → digest → pointer, so the pointer must
      never name a single-file artifact whose digest sidecar is
      missing, UNLESS a journaled process kill or disk firing explains
      the gap (a crash between the digest unlink and rewrite of a
      re-saved step is the one legal path to a pointed digest-less
      file). A clean-run pointer past a missing digest is the
      protocol writing the pointer early.
    """
    trial_dir = Path(trial_dir)
    workers = _worker_dirs(trial_dir)
    if storage_faults is None:
        storage_faults = load_storage_faults(trial_dir)
    if worker_events is None:
        worker_events = {k: load_jsonl(d / "recovery_journal.jsonl",
                                       schema.RECOVERY)
                         for k, d in workers.items()}

    fired_actions: dict[int, set[str]] = {
        k: {str(r.get("action", "")) for r in recs}
        for k, recs in storage_faults.items()}
    save_failures: dict[int, int] = {}
    for k, events in worker_events.items():
        n = sum(1 for r in events if r.get("action") == "save_failed")
        if n:
            save_failures[k] = n

    applicable = bool(storage_faults) or bool(save_failures)
    if not applicable:
        return [], False

    out: list[Violation] = []
    # supervisor-injected corruption and process kills also license
    # what a restore finds (the training arm's corrupt+kill pairing)
    sup_corrupted: set[int] = set()
    killed: set[int] = set()
    for r in journal_records:
        if r.get("event") != schema.FAULT:
            continue
        if (r.get("action") == "corrupt_latest_checkpoint"
                and isinstance(r.get("worker"), int)):
            sup_corrupted.add(r["worker"])
        elif (r.get("action") == "kill_worker"
                and isinstance(r.get("worker"), int)):
            killed.add(r["worker"])

    for k, n in sorted(save_failures.items()):
        errors = fired_actions.get(k, set()) & set(_DISK_ERROR_ACTIONS)
        if not errors:
            out.append(Violation(
                "storage_faults",
                f"{n} save_failed record(s) with no error-surfacing "
                "disk firing journaled by this worker's injector — a "
                "skipped cadence save nobody's fault plan licensed", k))

    for k, events in sorted(worker_events.items()):
        hit_corruption = any(
            r.get("action") in ("corrupt_checkpoint_fallback",
                                "fallback_restore")
            for r in events)
        if not hit_corruption:
            continue
        licensed = (k in sup_corrupted
                    or bool(fired_actions.get(k, set())
                            & set(_DISK_CORRUPT_ACTIONS)))
        if not licensed:
            out.append(Violation(
                "storage_faults",
                "restore fell back past a corrupt checkpoint with no "
                "injected corruption (supervisor corrupt fault or "
                "injector torn-write/crash-rename) journaled for this "
                "worker", k))

    for k, d in sorted(workers.items()):
        pointer = d / "checkpoint.json"
        if not pointer.exists():
            continue
        try:
            latest = json.loads(pointer.read_text()).get("latest_path", "")
        except (json.JSONDecodeError, AttributeError):
            continue  # unreadable pointers are invariant (5)'s problem
        if not str(latest).endswith(".msgpack"):
            continue  # sharded saves point at a manifest (embedded
            # checksum), not a digest-sidecar'd single file
        target = d / str(latest)
        sidecar = target.with_suffix(target.suffix + ".sha256")
        if target.exists() and not sidecar.exists():
            if k in killed or k in fired_actions:
                continue  # a crash/fault can legally land between the
                # digest unlink and rewrite of a re-saved step
            out.append(Violation(
                "storage_faults",
                f"pointer names {target.name} whose digest sidecar "
                "never landed, with no journaled kill or disk firing "
                "to explain it — the save protocol published the "
                "pointer before the digest", k))
    return out, True


# ---------------------------------------------------------------------------
# whole-run replay
# ---------------------------------------------------------------------------

def _worker_dirs(trial_dir: Path) -> dict[int, Path]:
    out = {}
    for d in sorted(trial_dir.glob("worker*")):
        if d.is_dir() and d.name[len("worker"):].isdigit():
            out[int(d.name[len("worker"):])] = d
    return out


def corruption_exempt_targets(journal_records: list[dict]
                              ) -> dict[int, set[str]]:
    """{worker: {artifact names}} the fault injector journaled as
    deliberately torn — exempt from invariant (5)."""
    out: dict[int, set[str]] = {}
    for r in journal_records:
        if (r.get("event") == schema.FAULT
                and r.get("action") == "corrupt_latest_checkpoint"
                and r.get("target")):
            out.setdefault(r.get("worker", -1), set()).add(r["target"])
    return out


def check_run(trial_dir: str | Path, outcome: dict | None = None,
              reference_dir: str | Path | None = None) -> dict[str, Any]:
    """Replay one trial's artifact set and verify all five invariants.

    ``trial_dir`` is a LocalProcessCluster root: ``worker<k>/`` logdirs
    plus ``command_journal.jsonl``; the campaign also leaves
    ``outcome.json`` (trial metadata) there, or the caller passes
    ``outcome`` directly. Returns ``{"verdicts": {invariant:
    pass|fail|skipped}, "violations": [...], "workers": [...]}``.
    """
    trial_dir = Path(trial_dir)
    if outcome is None:
        opath = trial_dir / "outcome.json"
        outcome = (json.loads(opath.read_text()) if opath.exists() else {})
    if reference_dir is None and outcome.get("reference_dir"):
        reference_dir = outcome["reference_dir"]

    journal_all = load_jsonl(trial_dir / "command_journal.jsonl")
    recovery = [r for r in journal_all if r.get("event") == schema.RECOVERY]
    workers = _worker_dirs(trial_dir)
    worker_events = {k: load_jsonl(d / "recovery_journal.jsonl",
                                   schema.RECOVERY)
                     for k, d in workers.items()}
    exempt = corruption_exempt_targets(journal_all)
    # artifacts the workers' own disk injectors journaled as torn
    # (train/storage.py) carry the same exemption standing as the
    # supervisor's corrupt_latest_checkpoint targets
    storage_faults = load_storage_faults(trial_dir)
    for k, names in storage_exempt_targets(storage_faults).items():
        exempt.setdefault(k, set()).update(names)

    violations: list[Violation] = []
    skipped: set[str] = set()

    # the reference checkpoint is immutable once its run completed:
    # digest it ONCE per check, not once per worker
    ref_digest: tuple[str, int] | None = None
    ref_opt_digest: tuple[str, int] | None = None
    if reference_dir is not None:
        from ..train.checkpoint import (CheckpointCorruptError,
                                        checkpoint_state_digests)
        try:
            both = checkpoint_state_digests(reference_dir)
            if both is not None:
                ref_digest = (both[0], both[2])
                ref_opt_digest = (both[1], both[2])
        except CheckpointCorruptError as e:
            violations.append(Violation(
                "determinism", f"reference checkpoint unreadable: {e}"))
        if ref_digest is None:
            reference_dir = None  # nothing to compare against → skip

    violations += check_terminal_state(outcome, recovery)
    violations += check_causality(recovery, worker_events)
    reconf_violations, reconf_applicable, grown, relaunch_counts = \
        check_reconfigure(trial_dir, outcome, journal_all)
    violations += reconf_violations
    if not reconf_applicable:
        skipped.add("reconfigure")
    serve_violations, serving_applicable, serve_workers, \
        decode_applicable = check_serving(trial_dir, outcome, journal_all)
    violations += serve_violations
    if not serving_applicable:
        skipped.update(("serve_outcomes", "serve_digest",
                        "serve_monotone"))
    if not decode_applicable:
        # only trials whose replicas ran the decode workload make the
        # swap-during-generation claim
        skipped.add("decode_swap")
    group_violations, group_applicable = check_serve_group(trial_dir)
    violations += group_violations
    if not group_applicable:
        # only trials that booted a TP serving process group (a worker
        # left a group_log.jsonl) make the die-as-a-unit claim
        skipped.add("serve_group")
    autoscale_violations, autoscale_applicable = check_autoscale(
        outcome, journal_all)
    violations += autoscale_violations
    if not autoscale_applicable:
        skipped.add("autoscale")
    net_violations, net_applicable = check_net_faults(
        trial_dir, outcome, journal_all)
    violations += net_violations
    if not net_applicable:
        # only trials with network-fault evidence (a journaled net_*
        # fault, a dedup hit, or a retried terminal) make the
        # exactly-once-under-retry claim
        skipped.add("net_faults")
    storage_violations, storage_applicable = check_storage_faults(
        trial_dir, journal_all, worker_events=worker_events,
        storage_faults=storage_faults)
    violations += storage_violations
    if not storage_applicable:
        # only trials with storage-fault evidence (a journaled disk_*
        # firing or a save_failed) make the crash-consistency claim
        skipped.add("storage_faults")

    restarts_by_worker: dict[int, int] = {}
    for r in recovery:
        if r.get("action") == "restart" and "worker" in r:
            restarts_by_worker[r["worker"]] = (
                restarts_by_worker.get(r["worker"], 0) + 1)

    # invariant (3) under the adaptive controller is epoch-spliced:
    # bitwise WITHIN a discipline epoch, causal ACROSS them. With only
    # terminal digests as artifacts, the comparable case is identical
    # epoch histories (the seeded-synthetic contract: same decisions →
    # same series → bitwise must hold end-to-end); a trial whose
    # licensed trace diverged from the reference's has no common final
    # epoch to compare, so its digest check is spliced out — the
    # discipline invariant still holds every change to account.
    from ..train.discipline import discipline_trace
    ref_trace: list = []
    if reference_dir is not None:
        ref_trace = discipline_trace(
            load_jsonl(Path(reference_dir) / "train_log.jsonl"))

    det_checked = 0
    det_spliced = 0
    disc_applicable = False
    for k, d in sorted(workers.items()):
        if k in serve_workers:
            # serving replicas have no train series or checkpoints —
            # their artifacts are replayed by check_serving above
            continue
        full_log = load_jsonl(d / "train_log.jsonl")
        # the trainer stamps event:"step"; minimal payloads (chaos
        # shell smoke, the reference's own tools) may write bare
        # {"step": N, ...} records — both are the metrics series
        steps = [r for r in full_log
                 if isinstance(r.get("step"), int)
                 and r.get("event", schema.STEP) == schema.STEP]
        disc_violations, disc_app = check_discipline(
            steps, full_log, worker=k)
        violations += disc_violations
        disc_applicable = disc_applicable or disc_app
        if k in grown and not steps:
            # a grown worker that never produced a step before
            # teardown has nothing to splice — its resume evidence is
            # the reconfigure journal, not a log. Its SEEDED checkpoint
            # dir still gets the integrity check: a source file copied
            # while torn is exactly what invariant 5 exists to catch.
            violations += check_checkpoint_dir(d, exempt.get(k, set()),
                                               worker=k)
            continue
        allowed = (restarts_by_worker.get(k, 0)
                   + relaunch_counts.get(k, 0)
                   + sum(1 for r in worker_events.get(k, [])
                         if r.get("action") in ("nan_rollback",
                                                "fallback_restore")))
        violations += check_metrics_log(
            steps, allowed_rewinds=allowed, worker=k,
            # a grown worker's logdir was seeded mid-run: its series
            # legitimately starts at the seed checkpoint's step
            expect_first_step=None if k in grown else 1)
        violations += check_checkpoint_dir(d, exempt.get(k, set()), worker=k)
        if reference_dir is not None:
            if discipline_trace(full_log) != ref_trace:
                # divergent epoch history: the bitwise claim stops at
                # the first differing boundary, before the terminal
                # digest — splice this worker out, causality above
                # remains the binding check
                det_spliced += 1
                continue
            checked, det_violations = determinism_verdict(
                d, reference_dir, worker=k, reference_digest=ref_digest,
                reference_opt_digest=ref_opt_digest)
            violations += det_violations
            det_checked += checked
    if reference_dir is None:
        skipped.add("determinism")
    elif det_checked == 0:
        # every worker was left short of the reference step — nothing
        # was "fully recovered", so the bitwise claim has no subject
        # (or every worker was epoch-spliced out)
        skipped.add("determinism")
    if not disc_applicable:
        skipped.add("discipline")

    failed = {v.invariant for v in violations}
    verdicts = {inv: ("fail" if inv in failed
                      else "skipped" if inv in skipped else "pass")
                for inv in INVARIANTS}
    return {"verdicts": verdicts,
            "violations": [v.to_dict() for v in violations],
            "workers": sorted(workers),
            "determinism_workers_checked": det_checked,
            "determinism_workers_spliced": det_spliced}


# ---------------------------------------------------------------------------
# schedule shrinking (used by launch/chaos.py; lives here so the
# reduction is defined next to the predicate it minimizes against)
# ---------------------------------------------------------------------------

def shrink_faults(faults: tuple, still_fails: Callable[[tuple], bool],
                  max_probes: int = 32) -> tuple[tuple, int]:
    """Greedy one-at-a-time reduction: repeatedly try dropping each
    fault; keep any drop under which the violation persists
    (``still_fails(candidate)`` True). Returns (minimal fault tuple,
    probes spent). The classic ddmin endgame without the partitioning
    prelude — chaos schedules are small (a handful of faults), so the
    linear pass converges in O(n²) probes worst-case, bounded by
    ``max_probes``."""
    current = tuple(faults)
    probes = 0
    changed = True
    while changed and len(current) > 1 and probes < max_probes:
        changed = False
        for i in range(len(current)):
            cand = current[:i] + current[i + 1:]
            probes += 1
            if still_fails(cand):
                current = cand
                changed = True
                break
            if probes >= max_probes:
                break
    return current, probes
