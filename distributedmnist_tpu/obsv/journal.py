"""Command-journal analysis: the obsv view of ``launch/exec.py``'s JSONL.

Every cluster action leaves a ``command_journal.jsonl``; this module
loads it torn-write-tolerantly and aggregates the run into per-verb
stats — attempt counts, retry/failure totals, duration percentiles —
the same load-then-aggregate shape ``obsv/report.py`` applies to
training logs (≙ the reference's regex scrape of orchestrator output,
tools/benchmark.py:24-34, replaced by structured records).
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any, Iterator

from . import schema
from .report import load_jsonl


def tail_records(path: str | Path | None = None, *,
                 text: str | None = None,
                 tail_bytes: int = 1 << 16) -> Iterator[dict]:
    """Intact dict records from the tail of a live JSONL stream,
    NEWEST FIRST.

    The one torn-tail discipline every poll-loop reader shares: the
    writer may be mid-append (or the tail window may start mid-line),
    so blank, torn, and non-dict lines are skipped rather than treated
    as evidence — a reader that reports "nothing" for a whole poll
    tick because one line was torn makes live progress look stalled.
    Callers filter for the record shape they want and stop at the
    first hit; this generator does no more file I/O than the single
    tail read.

    Pass EITHER ``path`` (reads only the final ``tail_bytes`` of the
    file; unreadable/missing file yields nothing) OR ``text`` (a tail
    another transport already captured, e.g. a remote ``tail -n``
    result). Distinct keywords, not one polymorphic argument: a str
    path and a str blob are indistinguishable by type.
    """
    if (path is None) == (text is None):
        raise ValueError("tail_records: pass exactly one of path/text")
    if text is None:
        try:
            with open(Path(path), "rb") as f:
                f.seek(0, 2)
                size = f.tell()
                f.seek(max(0, size - tail_bytes))
                text = f.read().decode("utf-8", errors="replace")
        except OSError:
            return
    for line in reversed(text.splitlines()):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue  # torn write (or the window started mid-line)
        if isinstance(rec, dict):
            yield rec


def load_journal(path: str | Path) -> list[dict]:
    """Command records from a journal (tolerates a torn tail write)."""
    return load_jsonl(path, event=schema.COMMAND)


def load_recovery_events(path: str | Path) -> list[dict]:
    """Structured recovery records (``event: "recovery"``) — written by
    the supervisor into the command journal and by the trainer /
    checkpoint layer into ``train_dir/recovery_journal.jsonl``."""
    return load_jsonl(path, event=schema.RECOVERY)


def load_reconfigure_events(path: str | Path) -> list[dict]:
    """Elastic world-reshape records (``event: "reconfigure"``) —
    written by the supervisor (begin → relaunched → resume) and the
    cluster backend (reshape) into the command journal. Their presence
    is the causal LICENSE for a world change: the cross-world resume
    invariant (obsv/invariants.py) fails a run whose world silently
    changed shape without one."""
    return load_jsonl(path, event=schema.RECONFIGURE)


def summarize_reconfigure_events(records: list[dict]) -> dict[str, Any]:
    """Aggregate reconfigure records into the transition evidence:
    one entry per ``begin`` (old/new world, trigger, the quorum as
    specified and as rescaled for the new world) folded with its
    ``relaunched`` (drain latency, per-worker respawn-vs-standby) and
    ``resume`` (drain→first-moved-step latency — the MTTR analogue
    for a world change). Supervisor-less reshapes (a bare backend
    ``reconfigure``) count as their own transitions."""
    transitions: list[dict[str, Any]] = []
    cur: dict[str, Any] | None = None
    for r in records:
        a = r.get("action")
        if a == "begin":
            # the schema registry IS the field list: every required
            # begin field lands in the transition, so emitter and
            # summarizer can't drift
            cur = {k: r.get(k) for k in schema.required_fields(
                schema.RECONFIGURE, "begin")}
            transitions.append(cur)
        elif a == "reshape" and cur is None:
            t = {k: r.get(k) for k in schema.required_fields(
                schema.RECONFIGURE, "reshape")}
            t["trigger"] = "backend"
            transitions.append(t)
        elif a == "relaunched" and cur is not None:
            cur["drain_s"] = r.get("drain_s")
            cur["via"] = r.get("via")
            cur["grown"] = r.get("grown")
        elif a == "resume" and cur is not None:
            cur["reconfigure_s"] = r.get("reconfigure_s")
            cur["first_moved_worker"] = r.get("worker")
            cur["first_moved_step"] = r.get("step")
            cur = None
    return {"count": len(transitions), "transitions": transitions}


def summarize_reconfigures(path: str | Path) -> dict[str, Any]:
    """Load + aggregate the reconfigure events in one journal file."""
    return summarize_reconfigure_events(load_reconfigure_events(path))


def _percentile(sorted_vals: list[float], q: float) -> float:
    """Nearest-rank percentile over an already-sorted list."""
    idx = min(len(sorted_vals) - 1, max(0, round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


def summarize_serving_swaps(records: list[dict]) -> dict[str, Any]:
    """Weight-swap accounting over serve-journal records (``event:
    "serve"``), broken down by the precision tier each swap installed.
    A ``weight_swap`` WITHOUT a ``tier`` field is a legacy journal
    from before the quantized serving tiers existed — it counts as
    ``fp32`` (the only representation that path ever served), so
    replaying pre-quantization artifacts can never KeyError here.
    ``quant_sidecar_fallbacks`` counts publishes where a quantized
    replica fell back to full precision (absent/torn/tier-less
    sidecar) — the nightly campaign's evidence that the sidecar digest
    refusal actually fired."""
    swaps = [r for r in records if r.get("action") == "weight_swap"]
    by_tier: dict[str, int] = {}
    for r in swaps:
        tier = r.get("tier") or "fp32"
        by_tier[tier] = by_tier.get(tier, 0) + 1
    return {"swaps": len(swaps), "by_tier": by_tier,
            "quant_sidecar_fallbacks": sum(
                1 for r in records
                if r.get("action") == "follow_quant_sidecar_fallback")}


def summarize_mttr(records: list[dict]) -> dict[str, Any]:
    """MTTR (mean-time-to-recovery) over the recovery episodes in a
    journal: each ``resume`` closes a detect→respawned→first-moved-step
    episode. Prefers the explicit ``mttr_s`` the supervisor stamps on
    resume events; legacy journals without it fall back to the wall
    timestamps of the worker's pending detect. Always returns the dict
    (``episodes: 0`` when none) so campaign reports can assert the
    metric is PRESENT, not just non-crashing; ``unrecovered`` counts
    detects no resume ever closed (exhausted budgets, teardown before
    the restarted worker moved)."""
    pending_detect: dict[int, float] = {}
    episodes: list[float] = []
    respawn: list[float] = []
    superseded = 0
    by_worker: dict[int, list[float]] = {}
    for rec in records:
        action = rec.get("action")
        k = rec.get("worker")
        if action == "detect" and k is not None:
            pending_detect[k] = rec.get("time")
        elif action == "episode_superseded" and k is not None:
            # a world reshape (reconfigure) replaced the in-flight
            # restart: the episode is neither recovered nor lost — the
            # reconfigure transition's own latency covers it
            if pending_detect.pop(k, None) is not None:
                superseded += 1
        elif action == "resume" and k is not None:
            m = rec.get("mttr_s")
            if m is None:
                t0 = pending_detect.get(k)
                t1 = rec.get("time")
                m = (round(t1 - t0, 3)
                     if t0 is not None and t1 is not None else None)
            pending_detect.pop(k, None)
            if m is not None:
                episodes.append(m)
                by_worker.setdefault(k, []).append(m)
            if rec.get("resume_after_respawn_s") is not None:
                respawn.append(rec["resume_after_respawn_s"])
    # detects never closed by a resume: budget-exhausted workers (no
    # recovery to time) or a run torn down before the restarted worker
    # ever moved — surfaced instead of silently undercounting episodes
    out: dict[str, Any] = {"episodes": len(episodes),
                           "unrecovered": len(pending_detect),
                           "superseded": superseded}
    if episodes:
        s = sorted(episodes)
        out.update(mean_s=round(sum(s) / len(s), 3),
                   p50_s=_percentile(s, 0.50),
                   p90_s=_percentile(s, 0.90),
                   max_s=s[-1],
                   by_worker={k: v for k, v in sorted(by_worker.items())})
    if respawn:
        # the respawn→first-moved-step leg alone: what the compile
        # cache / standby fast path actually shrinks
        s = sorted(respawn)
        out["resume_after_respawn_p50_s"] = _percentile(s, 0.50)
        out["resume_after_respawn_max_s"] = s[-1]
    return out


def summarize_recovery_events(records: list[dict]) -> dict[str, Any]:
    """Aggregate recovery records into the episode's evidence:

    * ``by_action`` — counts per action (detect, restart, resume,
      nan_rollback, corrupt_checkpoint_fallback, …),
    * ``by_worker`` — each worker's ordered action chain, e.g.
      ``["detect", "restart", "resume"]`` for a clean
      kill → restart → resume episode,
    * ``quorum_transitions`` — the workers_alive trajectory,
    * ``resume_steps`` — {worker: step} where restarted workers picked
      the run back up,
    * ``mttr`` — detect→first-moved-step latency percentiles per
      :func:`summarize_mttr` (present even when zero episodes).
    """
    by_action: dict[str, int] = {}
    by_worker: dict[int, list[str]] = {}
    quorum: list[dict] = []
    resume_steps: dict[int, int] = {}
    for rec in records:
        action = rec.get("action", "?")
        by_action[action] = by_action.get(action, 0) + 1
        if "worker" in rec:
            by_worker.setdefault(rec["worker"], []).append(action)
        if action == "quorum_transition":
            quorum.append({k: rec.get(k) for k in schema.required_fields(
                schema.RECOVERY, "quorum_transition")})
        if action == "resume" and "worker" in rec:
            resume_steps[rec["worker"]] = rec.get("step")
    return {"events": len(records), "by_action": by_action,
            "by_worker": by_worker, "quorum_transitions": quorum,
            "resume_steps": resume_steps,
            "mttr": summarize_mttr(records)}


def summarize_recovery(path: str | Path) -> dict[str, Any]:
    """Load + aggregate the recovery events in one journal file."""
    return summarize_recovery_events(load_recovery_events(path))


def summarize_autoscale(records: list[dict]) -> dict[str, Any]:
    """Aggregate a run's ``event: "autoscale"`` records (the resource
    broker's decision journal, ``launch/broker.py``) into its scaling
    evidence:

    * ``decisions`` / ``completed`` / ``errors`` — begin records and
      how each closed,
    * ``by_trigger`` / ``by_direction`` — which signal fired each
      decision and which way the roster moved,
    * ``reaction_s`` — detect→capacity-live latency percentiles from
      the ``complete`` records (the broker's MTTR analogue),
    * ``flaps`` — consecutive opposite-direction decisions closer than
      twice the recorded cooldown: the oscillation the hysteresis
      band exists to prevent, surfaced so a campaign can gate on it
      staying zero.
    """
    begins = [r for r in records if r.get("action") == "begin"]
    completes = [r for r in records if r.get("action") == "complete"]
    errors = [r for r in records if r.get("action") == "error"]
    by_trigger: dict[str, int] = {}
    by_direction: dict[str, int] = {}
    for r in begins:
        t = r.get("trigger", "?")
        by_trigger[t] = by_trigger.get(t, 0) + 1
        d = r.get("decision", "?")
        by_direction[d] = by_direction.get(d, 0) + 1
    flaps = 0
    prev: dict | None = None
    for r in begins:
        if prev is not None and r.get("decision") != prev.get("decision"):
            gap = (r.get("time") or 0) - (prev.get("time") or 0)
            lim = 2 * float(r.get("cooldown_s") or 30.0)
            if 0 <= gap < lim:
                flaps += 1
        prev = r
    out: dict[str, Any] = {"decisions": len(begins),
                           "completed": len(completes),
                           "errors": len(errors),
                           "by_trigger": by_trigger,
                           "by_direction": by_direction,
                           "flaps": flaps,
                           "reaction_s": {}}
    reactions = sorted(float(r["reaction_s"]) for r in completes
                       if isinstance(r.get("reaction_s"), (int, float)))
    if reactions:
        out["reaction_s"] = {
            "mean": round(sum(reactions) / len(reactions), 3),
            "p50": _percentile(reactions, 0.50),
            "p99": _percentile(reactions, 0.99),
            "max": reactions[-1]}
    return out


def summarize_discipline(records: list[dict]) -> dict[str, Any]:
    """Aggregate a run's ``event: "discipline"`` records (the straggler
    discipline controller's decision journal, ``train/discipline.py``)
    into its adaptation evidence — the same shape
    :func:`summarize_autoscale` gives the broker:

    * ``changes`` / ``completed`` — begin records and how many closed,
    * ``by_trigger`` / ``by_direction`` — which CDF signal licensed
      each change and which way the discipline moved (tighten/relax
      quorum, retarget/restore timeout),
    * ``trace`` — the per-window discipline trajectory
      ``[(effective_step, k, timeout_ms), ...]`` from the completes:
      the parameter-vs-step curve a bench report plots,
    * ``reaction_s`` — decide→staged latency percentiles,
    * ``flaps`` — consecutive opposite-direction changes closer (in
      STEPS — the controller's clock) than twice the recorded
      cooldown: the oscillation the dead band exists to prevent,
      surfaced so campaigns gate on it staying zero.
    """
    begins = [r for r in records if r.get("event") == schema.DISCIPLINE
              and r.get("action") == "begin"]
    completes = [r for r in records if r.get("event") == schema.DISCIPLINE
                 and r.get("action") == "complete"]
    by_trigger: dict[str, int] = {}
    by_direction: dict[str, int] = {}
    for r in begins:
        t = r.get("trigger", "?")
        by_trigger[t] = by_trigger.get(t, 0) + 1
        d = r.get("decision", "?")
        by_direction[d] = by_direction.get(d, 0) + 1
    # tighten_* vs relax_*/restore_* are the two directions; a flap is
    # a reversal inside 2× the step cooldown
    def _dir(decision: str | None) -> str:
        return "tighten" if (decision or "").startswith("tighten") \
            else "relax"
    flaps = 0
    prev: dict | None = None
    for r in begins:
        if prev is not None and _dir(r.get("decision")) != _dir(
                prev.get("decision")):
            gap = (r.get("at_step") or 0) - (prev.get("at_step") or 0)
            lim = 2 * int(r.get("cooldown_steps") or 40)
            if 0 <= gap < lim:
                flaps += 1
        prev = r
    trace = [(r.get("effective_step"), r.get("k"), r.get("timeout_ms"))
             for r in completes]
    out: dict[str, Any] = {"changes": len(begins),
                           "completed": len(completes),
                           "by_trigger": by_trigger,
                           "by_direction": by_direction,
                           "flaps": flaps,
                           "trace": trace,
                           "reaction_s": {}}
    reactions = sorted(float(r["reaction_s"]) for r in completes
                       if isinstance(r.get("reaction_s"), (int, float)))
    if reactions:
        out["reaction_s"] = {
            "mean": round(sum(reactions) / len(reactions), 3),
            "p50": _percentile(reactions, 0.50),
            "p99": _percentile(reactions, 0.99),
            "max": reactions[-1]}
    return out


def summarize_net_chaos(trial_dir: str | Path) -> dict[str, Any] | None:
    """One trial's network-fault evidence, from artifacts alone: the
    ``net_*`` fault records the chaos proxies (launch/netchaos.py)
    journaled, the dedup-cache hits and deadline aborts the hardened
    replicas booked, and the client-side retry amplification the load
    journal shows. Returns ``None`` when the trial carries no network
    evidence at all — the per-trial ``net`` slot in the chaos report
    stays absent for non-network campaigns."""
    trial_dir = Path(trial_dir)
    by_kind: dict[str, int] = {}
    for r in load_jsonl(trial_dir / "command_journal.jsonl"):
        a = str(r.get("action", ""))
        if r.get("event") == schema.FAULT and a.startswith("net_"):
            by_kind[a] = by_kind.get(a, 0) + 1
    dedup_hits = conn_aborts = 0
    for f in sorted(trial_dir.glob("worker*/serve_log.jsonl")):
        for r in load_jsonl(f, schema.SERVE):
            if r.get("action") == "dedup_hit":
                dedup_hits += 1
            elif r.get("action") == "conn_abort":
                conn_aborts += 1
    attempts: list[float] = []
    retried = terminals = 0
    for r in load_jsonl(trial_dir / "loadgen.jsonl", schema.LOAD):
        if r.get("action") != "outcome":
            continue
        terminals += 1
        n = r.get("attempts")
        if isinstance(n, (int, float)):
            attempts.append(float(n))
        if r.get("retried") or (isinstance(n, int) and n > 1):
            retried += 1
    if not by_kind and not dedup_hits and not retried:
        return None
    out: dict[str, Any] = {
        "faults": by_kind, "fired": sum(by_kind.values()),
        "dedup_hits": dedup_hits, "conn_aborts": conn_aborts,
        "retried": retried,
        "retry_rate": round(retried / max(1, terminals), 4)}
    if attempts:
        s = sorted(attempts)
        out["attempts"] = {"p50": _percentile(s, 0.50),
                           "p99": _percentile(s, 0.99), "max": s[-1]}
    return out


def summarize_disk_chaos(trial_dir: str | Path) -> dict[str, Any] | None:
    """One trial's storage-fault evidence, from artifacts alone: the
    ``disk_*`` fault records each worker's injector (train/storage.py)
    journaled into its own ``storage_faults.jsonl``, and the
    degradation bookkeeping the trainer left behind — ``save_failed``
    (a cadence save skipped under ENOSPC/EIO, training continued) and
    ``fallback_restore`` (a restore that walked past a torn or
    power-cut artifact) in each worker's ``recovery_journal.jsonl``.
    Returns ``None`` when the trial carries no storage evidence at all
    — the per-trial ``disk`` slot in the chaos report stays absent for
    non-disk campaigns."""
    trial_dir = Path(trial_dir)
    by_action: dict[str, int] = {}
    workers: set[int] = set()
    for f in sorted(trial_dir.glob("worker*/storage_faults.jsonl")):
        for r in load_jsonl(f, schema.FAULT):
            a = str(r.get("action", ""))
            if not a.startswith("disk_"):
                continue
            by_action[a] = by_action.get(a, 0) + 1
            if isinstance(r.get("worker"), int):
                workers.add(r["worker"])
    save_failed = fallbacks = 0
    for f in sorted(trial_dir.glob("worker*/recovery_journal.jsonl")):
        for r in load_jsonl(f, schema.RECOVERY):
            if r.get("action") == "save_failed":
                save_failed += 1
            elif r.get("action") == "fallback_restore":
                fallbacks += 1
    if not by_action and not save_failed:
        return None
    return {"faults": by_action, "fired": sum(by_action.values()),
            "workers": sorted(workers), "save_failed": save_failed,
            "fallback_restores": fallbacks}


def summarize_chaos(path: str | Path) -> dict[str, Any]:
    """Aggregate a chaos campaign's ``chaos_report.jsonl`` (one
    ``event: "chaos_trial"`` record per trial, written by
    ``launch/chaos.py``) into the single-line campaign verdict: trial
    outcomes, per-invariant pass/fail/skip tallies, which trials
    violated what, and any shrunk reproducer paths. ``all_green`` means
    every trial passed every applicable invariant — the regression
    signal a scheduled chaos sweep gates on."""
    records = load_jsonl(path, event=schema.CHAOS_TRIAL)
    outcomes: dict[str, int] = {}
    by_invariant: dict[str, dict[str, int]] = {}
    failing: list[dict[str, Any]] = []
    reproducers: list[str] = []
    mttr_trials: list[dict[str, Any]] = []
    mttr_all: list[float] = []
    fault_trials: list[dict[str, Any]] = []
    serving_trials: list[dict[str, Any]] = []
    autoscale_trials: list[dict[str, Any]] = []
    discipline_trials: list[dict[str, Any]] = []
    net_trials: list[dict[str, Any]] = []
    disk_trials: list[dict[str, Any]] = []
    reconfigures = 0
    swaps_by_tier: dict[str, int] = {}
    quant_fallbacks = 0
    for rec in records:
        sv = rec.get("serving")
        if sv is not None:
            serving_trials.append({
                "trial": rec.get("trial"),
                "issued": sv.get("issued"),
                "dropped": sv.get("dropped"),
                "responses": sv.get("responses"),
                "rejected": sv.get("rejected"),
                "errors": sv.get("errors"),
                "reject_rate": sv.get("reject_rate"),
                "p50_ms": (sv.get("latency_ms") or {}).get("p50"),
                "p99_ms": (sv.get("latency_ms") or {}).get("p99"),
                # decode sweeps: tokens actually streamed and the
                # time-to-first-token tail (None on classify trials)
                "tokens_streamed": sv.get("tokens_streamed"),
                "ttft_p99_ms": (sv.get("ttft_ms") or {}).get("p99"),
                "model_steps_served": sv.get("model_steps_served"),
                "tiers_served": sv.get("tiers_served"),
                "serve_swaps": rec.get("serve_swaps")})
            # swap-by-tier tally across the campaign; a trial record
            # (or its swaps) written before the quantized tiers
            # existed carries no tier breakdown — those swaps count as
            # fp32, the only tier that path ever served (never a
            # KeyError on legacy journals)
            sw = rec.get("serve_swaps") or {}
            tiers = sw.get("by_tier")
            if tiers is None:
                tiers = {"fp32": sw.get("swaps", 0)} if sw else {}
            for tier, n in tiers.items():
                key = tier or "fp32"
                swaps_by_tier[key] = swaps_by_tier.get(key, 0) + (n or 0)
            quant_fallbacks += sw.get("quant_sidecar_fallbacks") or 0
        a = rec.get("autoscale")
        if a is not None:
            autoscale_trials.append({
                "trial": rec.get("trial"),
                "decisions": a.get("decisions", 0),
                "fired": a.get("fired", 0),
                "by_direction": a.get("by_direction") or {},
                "flaps": a.get("flaps", 0),
                "reaction_p99_s": (a.get("reaction_s") or {}).get("p99")})
        dc = rec.get("discipline")
        if dc is not None:
            discipline_trials.append({
                "trial": rec.get("trial"),
                "changes": dc.get("changes", 0),
                "by_direction": dc.get("by_direction") or {},
                "flaps": dc.get("flaps", 0),
                "trace": dc.get("trace") or []})
        nt = rec.get("net")
        if nt is not None:
            net_trials.append({
                "trial": rec.get("trial"),
                "faults": nt.get("faults") or {},
                "fired": nt.get("fired", 0),
                "dedup_hits": nt.get("dedup_hits", 0),
                "conn_aborts": nt.get("conn_aborts", 0),
                "retried": nt.get("retried", 0),
                "retry_rate": nt.get("retry_rate"),
                "attempts_p50": (nt.get("attempts") or {}).get("p50"),
                "attempts_p99": (nt.get("attempts") or {}).get("p99")})
        dk = rec.get("disk")
        if dk is not None:
            disk_trials.append({
                "trial": rec.get("trial"),
                "faults": dk.get("faults") or {},
                "fired": dk.get("fired", 0),
                "save_failed": dk.get("save_failed", 0),
                "fallback_restores": dk.get("fallback_restores", 0)})
        f = rec.get("faults")
        if f is not None:
            fault_trials.append({"trial": rec.get("trial"),
                                 "scheduled": f.get("scheduled", 0),
                                 "fired": f.get("fired", 0),
                                 "unfired": f.get("unfired", [])})
        reconfigures += rec.get("reconfigures") or 0
        outcomes[rec.get("outcome", "?")] = (
            outcomes.get(rec.get("outcome", "?"), 0) + 1)
        for inv, verdict in (rec.get("verdicts") or {}).items():
            slot = by_invariant.setdefault(
                inv, {"pass": 0, "fail": 0, "skipped": 0})
            slot[verdict] = slot.get(verdict, 0) + 1
        if rec.get("violations"):
            failing.append({
                "trial": rec.get("trial"),
                "schedule": rec.get("described"),
                "invariants": sorted({v["invariant"]
                                      for v in rec["violations"]})})
        shrunk = rec.get("shrunk")
        if shrunk and shrunk.get("fault_plan_path"):
            reproducers.append(shrunk["fault_plan_path"])
        m = rec.get("mttr")
        if m is not None:
            mttr_trials.append({"trial": rec.get("trial"),
                                "episodes": m.get("episodes", 0),
                                "unrecovered": m.get("unrecovered", 0),
                                "p50_s": m.get("p50_s"),
                                "max_s": m.get("max_s")})
            mttr_all += [v for w in (m.get("by_worker") or {}).values()
                         for v in w]
    mttr: dict[str, Any] = {
        "episodes": sum(t["episodes"] for t in mttr_trials),
        # detects no resume ever closed (exhausted budgets, or a worker
        # torn down before it moved): surfaced so "every recovery
        # episode has an MTTR" is checkable, not assumed
        "unrecovered": sum(t["unrecovered"] for t in mttr_trials),
        "per_trial": mttr_trials}
    if mttr_all:
        s = sorted(mttr_all)
        mttr.update(mean_s=round(sum(s) / len(s), 3),
                    p50_s=_percentile(s, 0.50),
                    p90_s=_percentile(s, 0.90), max_s=s[-1])
    return {"trials": len(records),
            "seed": records[0].get("seed") if records else None,
            "outcomes": outcomes,
            "invariants": by_invariant,
            "all_green": not failing and bool(records),
            "failing_trials": failing,
            "reproducers": reproducers,
            # scheduled-vs-fired accounting: a kill that lands after
            # run-end fires nothing — without this a zero-episode
            # trial is indistinguishable from a real all-quiet run,
            # and the nightly gate asserts the campaign actually
            # FIRED something (fired > 0)
            "faults": {
                "scheduled": sum(t["scheduled"] for t in fault_trials),
                "fired": sum(t["fired"] for t in fault_trials),
                "never_fired": sum(len(t["unfired"])
                                   for t in fault_trials),
                "per_trial": fault_trials},
            # elastic world reshapes across the campaign (the resize
            # fault kind / below-quorum shrinks)
            "reconfigures": reconfigures,
            # MTTR as a first-class campaign metric: detect→first-
            # moved-step latency over every recovery episode in every
            # trial (the chaos CI asserts this key exists and uploads
            # its one-line summary)
            "mttr": mttr,
            # serving-mode campaigns: per-trial load-sweep evidence
            # (issued/dropped/rejects/p99 under live faults) — the
            # zero-drop claim is checkable from the one-line summary
            "serving": ({
                "trials": len(serving_trials),
                "issued": sum(t["issued"] or 0 for t in serving_trials),
                "dropped": sum(t["dropped"] or 0 for t in serving_trials),
                "responses": sum(t["responses"] or 0
                                 for t in serving_trials),
                "errors": sum(t["errors"] or 0 for t in serving_trials),
                # decode campaigns: total generated tokens + the worst
                # per-trial time-to-first-token tail (the decode
                # latency split the loadgen records per request)
                "tokens_streamed": sum(t["tokens_streamed"] or 0
                                       for t in serving_trials),
                "ttft_p99_ms": max(
                    (t["ttft_p99_ms"] for t in serving_trials
                     if t["ttft_p99_ms"] is not None), default=None),
                # which precision tier each installed swap served
                # (tier-less legacy swaps counted as fp32) and how
                # often a quantized replica's sidecar preference fell
                # back to full precision — the campaign-level evidence
                # for the quantized serving path
                "swaps_by_tier": swaps_by_tier,
                "quant_sidecar_fallbacks": quant_fallbacks,
                "per_trial": serving_trials}
                if serving_trials else None),
            # brokered campaigns: the autoscale evidence per trial and
            # in aggregate — the nightly broker gate asserts decisions
            # fired (> 0), in BOTH directions, with zero flaps
            "autoscale": ({
                "trials": len(autoscale_trials),
                "decisions": sum(t["decisions"] or 0
                                 for t in autoscale_trials),
                "fired": sum(t["fired"] or 0 for t in autoscale_trials),
                "scale_ups": sum(
                    t["by_direction"].get("scale_up_serving", 0)
                    for t in autoscale_trials),
                "scale_downs": sum(
                    t["by_direction"].get("scale_down_serving", 0)
                    for t in autoscale_trials),
                "flaps": sum(t["flaps"] or 0 for t in autoscale_trials),
                "reaction_p99_s": max(
                    (t["reaction_p99_s"] for t in autoscale_trials
                     if t["reaction_p99_s"] is not None), default=None),
                "per_trial": autoscale_trials}
                if autoscale_trials else None),
            # controller-armed campaigns: the straggler-discipline
            # evidence per trial and in aggregate — the nightly gate
            # asserts changes fired with zero flaps and every trial's
            # discipline invariant green
            "discipline": ({
                "trials": len(discipline_trials),
                "changes": sum(t["changes"] or 0
                               for t in discipline_trials),
                "tightens": sum(
                    n for t in discipline_trials
                    for d, n in t["by_direction"].items()
                    if d.startswith("tighten")),
                "relaxes": sum(
                    n for t in discipline_trials
                    for d, n in t["by_direction"].items()
                    if not d.startswith("tighten")),
                "flaps": sum(t["flaps"] or 0 for t in discipline_trials),
                "per_trial": discipline_trials}
                if discipline_trials else None),
            # network-mode campaigns: the transport-fault evidence per
            # trial and in aggregate — faults by kind, dedup-cache
            # hits (the exactly-once proof), retry amplification —
            # the nightly network gate asserts faults fired (incl. a
            # mid-stream reset), dropped==0, and invariant 13 green
            "net": ({
                "trials": len(net_trials),
                "fired": sum(t["fired"] or 0 for t in net_trials),
                "faults_by_kind": {
                    k: sum((t["faults"] or {}).get(k, 0)
                           for t in net_trials)
                    for t2 in net_trials for k in (t2["faults"] or {})},
                "dedup_hits": sum(t["dedup_hits"] or 0
                                  for t in net_trials),
                "conn_aborts": sum(t["conn_aborts"] or 0
                                   for t in net_trials),
                "retried": sum(t["retried"] or 0 for t in net_trials),
                "attempts_p50": max(
                    (t["attempts_p50"] for t in net_trials
                     if t["attempts_p50"] is not None), default=None),
                "attempts_p99": max(
                    (t["attempts_p99"] for t in net_trials
                     if t["attempts_p99"] is not None), default=None),
                "per_trial": net_trials}
                if net_trials else None),
            # disk-mode campaigns: the storage-fault evidence per
            # trial and in aggregate — firings by action, cadence
            # saves skipped under injected ENOSPC/EIO, fallback
            # restores past torn/power-cut artifacts — the nightly
            # disk gate asserts faults fired (incl. a retry-exhausting
            # ENOSPC) and invariant 14 green
            "disk": ({
                "trials": len(disk_trials),
                "fired": sum(t["fired"] or 0 for t in disk_trials),
                "faults_by_action": {
                    k: sum((t["faults"] or {}).get(k, 0)
                           for t in disk_trials)
                    for t2 in disk_trials for k in (t2["faults"] or {})},
                "save_failed": sum(t["save_failed"] or 0
                                   for t in disk_trials),
                "fallback_restores": sum(t["fallback_restores"] or 0
                                         for t in disk_trials),
                "per_trial": disk_trials}
                if disk_trials else None)}


def summarize_journal(path: str | Path) -> dict[str, Any]:
    """Aggregate a command journal into run-level evidence.

    Returns {"commands", "attempts", "retries", "failures",
    "probe_nonzero", "timeouts", "injected", "dry_run", "by_verb":
    {verb: {"attempts", "failures", "retries", "total_duration_ms"}}} —
    "commands" counts final attempts (one per executor.run call),
    "failures" final attempts of CHECKED commands that still failed.
    A nonzero rc from a check=False command (e.g. the ``kill -0``
    liveness probe of a dead worker) is an observation, not a control-
    plane failure — it lands in "probe_nonzero" instead, so
    ``failures == 0`` keeps meaning "nothing unexpected happened".
    """
    records = load_journal(path)
    by_verb: dict[str, dict[str, float]] = {}
    summary: dict[str, Any] = {"commands": 0, "attempts": 0, "retries": 0,
                               "failures": 0, "probe_nonzero": 0,
                               "timeouts": 0, "injected": 0,
                               "dry_run": 0, "by_verb": by_verb}
    for rec in records:
        verb = rec.get("verb", "?")
        v = by_verb.setdefault(verb, {"attempts": 0, "failures": 0,
                                      "retries": 0, "total_duration_ms": 0.0})
        if rec.get("dry_run"):
            summary["dry_run"] += 1
            continue
        summary["attempts"] += 1
        v["attempts"] += 1
        v["total_duration_ms"] = round(
            v["total_duration_ms"] + (rec.get("duration_ms") or 0.0), 3)
        if rec.get("timed_out"):
            summary["timeouts"] += 1
        if rec.get("injected"):
            summary["injected"] += 1
        if rec.get("will_retry"):
            summary["retries"] += 1
            v["retries"] += 1
        else:
            summary["commands"] += 1  # final attempt of its run() call
            ok = rec.get("rc") == 0 and not rec.get("timed_out")
            if not ok:
                if rec.get("check", True):
                    summary["failures"] += 1
                    v["failures"] += 1
                else:
                    summary["probe_nonzero"] += 1
    return summary
