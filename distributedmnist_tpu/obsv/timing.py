"""Step-time CDF collection and straggler statistics.

≙ the reference's cluster-wide timing gossip: workers RPC-broadcast
token-dequeue / gradients-done timestamps to worker 0, which aggregates
and periodically logs ``ELAPSED TIMES`` / ``ITERATION TIMES`` tables
(src/timeout_manager.py:31-70, src/distributed_train.py:305-307,
344-345), later parsed into stdev/p80/p90/p95/p99/p100 stats and CDF
plots (tools/benchmark.py:60-111,226-263).

TPU-native collapse: per-replica step times come out of the train step
as an all-gathered [n] vector (no RPC mesh, no shared-dict bug — the
reference's ``[{}] * n`` aliasing, src/timeout_manager.py:31-32, is a
documented quirk we do not copy). Collection is async-friendly: the
collector holds device arrays and only materializes them at report
points, so the device pipeline is never synced per step (SURVEY §7
"hard parts": timing capture must not cost scaling efficiency).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any

import numpy as np

PERCENTILES = (50.0, 80.0, 90.0, 95.0, 99.0, 100.0)  # ≙ tools/benchmark.py:86-111


@dataclasses.dataclass
class CdfStats:
    count: int
    mean: float
    stdev: float
    percentiles: dict[str, float]

    def to_dict(self) -> dict[str, Any]:
        return {"count": self.count, "mean": self.mean, "stdev": self.stdev,
                **{f"p{p:g}": v for p, v in zip(PERCENTILES, self.percentiles.values())}}


def compute_stats(samples: np.ndarray) -> CdfStats:
    samples = np.asarray(samples, np.float64).ravel()
    if samples.size == 0:
        return CdfStats(0, float("nan"), float("nan"),
                        {f"p{p:g}": float("nan") for p in PERCENTILES})
    pcts = np.percentile(samples, PERCENTILES)
    return CdfStats(
        count=int(samples.size),
        mean=float(samples.mean()),
        stdev=float(samples.std()),
        percentiles={f"p{p:g}": float(v) for p, v in zip(PERCENTILES, pcts)},
    )


class StepTimeCollector:
    """Accumulates per-step, per-replica time vectors lazily.

    ``add`` accepts a device array (or numpy) of shape [n_replicas] —
    kept as-is; conversion happens at ``snapshot``/report time so adds
    never force a device sync.
    """

    def __init__(self, num_replicas: int, capacity: int = 100_000):
        self.num_replicas = num_replicas
        self.capacity = capacity
        self._raw: list[Any] = []
        self._materialized = 0  # prefix of _raw already fetched to host
        self._host_steps: list[float] = []  # host-measured wall per step
        self._prefetch_depths: list[int] = []  # staged-queue gauge per step
        # ZeRO-1 overlap gauges (set only when comm bucketing is on —
        # the prefetch_queue_depth pattern: the report key exists iff
        # the feature does): bucket structure + calibrated per-bucket
        # comm time, plus the per-save snapshot stall series.
        self._overlap: dict[str, Any] | None = None
        self._snapshot_stalls: list[float] = []  # ms per save event
        # rolling-CDF window (set only when the adaptive discipline
        # controller is armed — same present-iff-on pattern): the
        # report then carries per-replica p50/p90/p99 over the LAST
        # window, the exact gauges the controller decides on.
        self._rolling_window: int | None = None

    def add(self, per_replica_times: Any, host_step_seconds: float | None = None,
            prefetch_depth: int | None = None) -> None:
        if len(self._raw) < self.capacity:
            self._raw.append(per_replica_times)
        if host_step_seconds is not None and len(self._host_steps) < self.capacity:
            self._host_steps.append(host_step_seconds)
        if prefetch_depth is not None and len(self._prefetch_depths) < self.capacity:
            self._prefetch_depths.append(int(prefetch_depth))

    def matrix(self) -> np.ndarray:
        """[steps, n_replicas] materialized compute times.

        Materialization is incremental: entries already fetched from
        device stay numpy, so periodic report/dump calls only transfer
        rows added since the last call (not O(steps) device fetches
        each time)."""
        if not self._raw:
            return np.zeros((0, self.num_replicas))
        for i in range(self._materialized, len(self._raw)):
            self._raw[i] = np.asarray(self._raw[i])
        self._materialized = len(self._raw)
        return np.stack(self._raw)

    def per_replica_stats(self) -> list[CdfStats]:
        """≙ per-worker ELAPSED TIMES stats (tools/benchmark.py:67-111)."""
        m = self.matrix()
        return [compute_stats(m[:, i]) for i in range(m.shape[1])] if m.size else []

    def per_step_stats(self) -> CdfStats:
        """Distribution over per-step *slowest replica* (the barrier
        time in a full-sync step) — the p99 the north star tracks."""
        m = self.matrix()
        return compute_stats(m.max(axis=1) if m.size else np.empty(0))

    def host_step_stats(self) -> CdfStats:
        return compute_stats(np.asarray(self._host_steps))

    def set_overlap_info(self, bucket_count: int,
                         per_bucket_pad_elems: list[int],
                         per_bucket_comm_ms: list[float] | None = None
                         ) -> None:
        """Record the comm-overlap structure (``parallel.comm_buckets``
        > 1): how many layer-ordered buckets the ZeRO-1 collectives are
        grouped into, each bucket's padded element count, and — when a
        calibration probe ran (Trainer.precompile) — the measured
        per-bucket scatter+gather wall ms in isolation. Structural
        gauges, not per-step measurements: inside one fused XLA program
        the per-bucket comm time is not separately observable, so the
        report carries the calibrated cost next to the live step
        times instead of pretending to split them."""
        self._overlap = {
            "bucket_count": int(bucket_count),
            "per_bucket_pad_elems": [int(x) for x in per_bucket_pad_elems],
        }
        if per_bucket_comm_ms is not None:
            self._overlap["per_bucket_comm_ms"] = [
                round(float(x), 3) for x in per_bucket_comm_ms]

    def add_snapshot_stall_ms(self, ms: float) -> None:
        """One checkpoint save's step-loop stall (train/loop.py _save):
        the sync-fetch path pays host fetch + canonical conversion
        here; the async-snapshot path only the device-copy dispatch."""
        if len(self._snapshot_stalls) < self.capacity:
            self._snapshot_stalls.append(float(ms))

    def snapshot_stall_stats(self) -> CdfStats:
        return compute_stats(np.asarray(self._snapshot_stalls, np.float64))

    def enable_rolling_cdf(self, window_steps: int) -> None:
        """Arm the rolling-window gauges (the adaptive discipline
        controller's view of the CDF; train/loop.py sets this iff
        ``sync.adaptive``)."""
        if window_steps < 1:
            raise ValueError(f"window_steps must be >= 1, got {window_steps}")
        self._rolling_window = int(window_steps)

    def rolling_cdf(self, window_steps: int | None = None
                    ) -> dict[str, Any] | None:
        """Per-replica p50/p90/p99 (and the pooled tail ratio) over the
        last ``window_steps`` rows — None until the window is full, so
        callers never decide on a half-filled CDF."""
        w = self._rolling_window if window_steps is None else int(window_steps)
        if w is None or len(self._raw) < w:
            return None
        tail = self.matrix()[-w:]
        pcts = np.percentile(tail, (50.0, 90.0, 99.0), axis=0)  # [3, n]
        pooled = np.percentile(tail, (50.0, 90.0, 99.0))
        p50 = float(pooled[0])
        # the fastest replica's median = the cohort pace. The pooled
        # p50 drifts to the midpoint once ~half the replicas straggle;
        # the controller's tail ratio divides by THIS instead
        fast_p50 = float(pcts[0].min())
        return {
            "window_steps": w,
            "per_replica": [
                {"p50": float(pcts[0, i]), "p90": float(pcts[1, i]),
                 "p99": float(pcts[2, i])}
                for i in range(tail.shape[1])],
            "p50_ms": p50,
            "p90_ms": float(pooled[1]),
            "p99_ms": float(pooled[2]),
            "fast_p50_ms": fast_p50,
            "tail_ratio": (float(pooled[2]) / fast_p50
                           if fast_p50 > 0 else 0.0),
        }

    def prefetch_depth_stats(self) -> CdfStats:
        """Distribution of the device-prefetch queue depth sampled at
        each step's dequeue: pinned at 0 means the producer (host
        assembly + H2D) is the bottleneck; pinned at the configured
        depth means the device is — the one gauge that says which side
        of the overlap to optimize next."""
        return compute_stats(np.asarray(self._prefetch_depths, np.float64))

    def report(self) -> dict[str, Any]:
        per_replica = self.per_replica_stats()
        out = {
            "num_steps": len(self._raw),
            "per_replica": [s.to_dict() for s in per_replica],
            "barrier": self.per_step_stats().to_dict(),
            "host_wall": self.host_step_stats().to_dict(),
        }
        if self._prefetch_depths:
            out["prefetch_queue_depth"] = self.prefetch_depth_stats().to_dict()
        if self._rolling_window is not None:
            rolling = self.rolling_cdf()
            if rolling is not None:
                out["rolling_cdf"] = rolling
        if self._overlap is not None:
            overlap = dict(self._overlap)
            if self._snapshot_stalls:
                overlap["snapshot_stall_ms"] = (
                    self.snapshot_stall_stats().to_dict())
            out["overlap"] = overlap
        elif self._snapshot_stalls:
            # async snapshots pay off without bucketing too — the stall
            # series stays visible when only that half is on
            out["snapshot_stall_ms"] = self.snapshot_stall_stats().to_dict()
        return out

    def reset(self) -> None:
        self._raw.clear()
        self._materialized = 0
        self._host_steps.clear()
        self._prefetch_depths.clear()
        self._snapshot_stalls.clear()


class ReplicaDeviceProbe:
    """Per-replica DEVICE-side completion probes.

    One representative device per LOCAL replica is probed each step
    with a trivial jitted op on a device-resident token. On real
    accelerator backends per-device execution is FIFO, so the probe
    completes only once everything queued on that device — the train
    step's program slice plus any work dispatched after it (injected
    chaos programs, per-device callbacks) — has drained. Readiness is
    POLLED (not serially blocked) so each device gets its own
    completion timestamp.

    FIFO does NOT hold everywhere: the CPU client executes
    data-independent same-device computations on a shared host pool, so
    a bare token probe there either completes while injected work is
    still in flight (reads zero skew) or queues behind it on EVERY
    device at once (the shared pool stalls all probes together and the
    min-subtraction erases the differential). For work the dispatcher
    has a handle on, :meth:`note` registers the dispatched output with
    its replica and a dispatch timestamp; the drain measurement times
    each noted output from its OWN dispatch — a per-device load signal
    no shared-pool stall can smear across devices — and takes the max
    of that and the token-probe skew, so FIFO backends (where the token
    probe already queues behind the noted work) do not double-count.

    The lockstep SPMD step itself cannot produce skew (its collectives
    barrier the devices); what this measures is precisely the
    per-device work OUTSIDE the shared program — the part a per-host
    wall clock is blind to. ≙ the per-worker measured times the
    reference gossips (src/timeout_manager.py:48-61), at per-DEVICE
    granularity on one host.
    """

    def __init__(self, topo) -> None:
        import jax
        me = jax.process_index()
        n = topo.num_replicas
        grid = topo.mesh.devices.reshape(n, -1)
        self.devices: list = []   # (replica_index, device), local only
        for r in range(n):
            local = [d for d in grid[r] if d.process_index == me]
            if local:
                self.devices.append((r, local[0]))
        self._tokens = [jax.device_put(np.float32(0), d)
                        for _, d in self.devices]
        self._inc = jax.jit(lambda x: x + 1.0)
        # warm the per-device executables NOW: the first call per token
        # sharding compiles, and a compile inside measure_skew_ms would
        # charge ~tens of ms of compiler time to whichever device the
        # loop reached first
        for t in self._tokens:
            self._inc(t).block_until_ready()
        self._index_of = {r: i for i, (r, _) in enumerate(self.devices)}
        self._noted: list[list] = [[] for _ in self.devices]

    def note(self, replica: int, out) -> None:
        """Register a just-dispatched computation's output as part of
        ``replica``'s device queue for the NEXT ``measure_skew_ms``
        (the chaos-injection seam; no-op for non-local replicas)."""
        i = self._index_of.get(replica)
        if i is not None:
            self._noted[i].append((out, time.perf_counter()))

    def measure_skew_ms(self) -> np.ndarray:
        """Dispatch one probe per local replica device and poll
        completions; returns per-local-replica drain skew in ms.

        Per device: the token probe's completion time (min-subtracted
        across devices — the differential a lockstep step reads as
        ~zero) maxed with each noted output's dispatch-to-ready
        duration (zero when nothing was noted).

        The noted duration is an UPPER bound on the replica's excess:
        on FIFO backends it also includes whatever residual step drain
        was queued ahead at dispatch (the token differential alone
        reports the exact excess there, and the max keeps it when it is
        larger… the noted value can only overstate the magnitude, never
        the ORDERING — the noted replica genuinely drains last, which
        is what quorum selection ranks on). Separating the shared-drain
        component out is not robustly measurable across queue
        disciplines: subtracting the token baseline erases the signal
        on shared-pool backends, where that baseline is itself the
        noted program's doing."""
        import jax  # noqa: F401  (tokens/jit already bound)
        outs = [self._inc(t) for t in self._tokens]
        noted, self._noted = self._noted, [[] for _ in self.devices]
        t0 = time.perf_counter()
        times = np.zeros(len(outs), np.float64)
        extra = np.zeros(len(outs), np.float64)
        pending = set(range(len(outs)))
        npending = {i for i in range(len(outs)) if noted[i]}
        while pending or npending:
            now = time.perf_counter()
            for i in list(pending):
                if outs[i].is_ready():
                    times[i] = (now - t0) * 1000.0
                    pending.discard(i)
            for i in list(npending):
                # drop entries as they finish; the device's extra is
                # its slowest noted program's dispatch→ready duration
                still = []
                for a, at in noted[i]:
                    if a.is_ready():
                        extra[i] = max(extra[i], (now - at) * 1000.0)
                    else:
                        still.append((a, at))
                noted[i] = still
                if not still:
                    npending.discard(i)
            if pending or npending:
                time.sleep(0.0002)
        return np.maximum(times - times.min(), extra).astype(np.float32)
