"""Experiment report generation: figures + stats from structured logs.

≙ the reference's analysis half of ``tools/benchmark.py``: it
re-parsed stdout logs by regex (`.*step ([0-9]*),` :30, `Precision @ 1`
:151, `ELAPSED TIMES`/`ITERATION TIMES` :60-144) and drew matplotlib
figures — time-vs-precision, step-vs-loss, time-vs-loss, time-vs-step,
and per-worker compute-time CDFs (:165-263). Here the trainer and
evaluator already emit structured JSONL (train_log.jsonl /
eval_log.jsonl) and npy series, so this module only loads, aggregates
and draws — the regex stage does not exist.

All figures are produced with the Agg backend (headless) and written
as PNG next to a stats.json.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any

import numpy as np

from ..core.log import get_logger
from .timing import compute_stats

logger = get_logger("report")


# ---------------------------------------------------------------------------
# loading
# ---------------------------------------------------------------------------

def load_jsonl(path: str | Path, event: str | None = None) -> list[dict]:
    """Load a JSONL log, optionally filtering by record ``event`` type.
    Tolerates a torn final line (the writer may still be appending)."""
    out: list[dict] = []
    path = Path(path)
    if not path.exists():
        return out
    for line in path.read_text().splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue  # torn tail write
        if event is None or rec.get("event") == event:
            out.append(rec)
    return out


def load_experiment(train_dir: str | Path,
                    eval_dir: str | Path | None = None) -> dict[str, Any]:
    """Gather everything one experiment produced.

    Returns {"steps": [...], "evals": [...], "step_times": [S,R] array
    or None, "time_acc": [S,4] array or None}.
    """
    train_dir = Path(train_dir)
    # Rollback splicing (obsv/invariants.py): after a NaN rollback or a
    # restart-resume the append-only log re-emits the replayed span, so
    # the raw series doubles back. Every stat/figure consumer wants the
    # spliced monotone view (identical to raw for a clean run); the raw
    # records stay available under "steps_raw".
    from .invariants import splice_rollbacks
    raw_steps = load_jsonl(train_dir / "train_log.jsonl", "step")
    spliced_steps, rewinds = splice_rollbacks(raw_steps)
    data: dict[str, Any] = {
        "steps": spliced_steps,
        "steps_raw": raw_steps,
        "log_rewinds": rewinds,
        "evals": [],
        "step_times": None,
        "time_acc": None,
        # trainer-side self-healing events (NaN rollbacks, corrupt-
        # checkpoint fallbacks, preemption flushes) — empty for a run
        # that never needed to recover
        "recovery": load_jsonl(train_dir / "recovery_journal.jsonl",
                               "recovery"),
    }
    if eval_dir is not None:
        data["evals"] = load_jsonl(Path(eval_dir) / "eval_log.jsonl", "eval")
    st = train_dir / "step_times.npy"
    if st.exists():
        data["step_times"] = np.load(st)
    ta = train_dir / "time_acc.npy"
    if ta.exists():
        data["time_acc"] = np.load(ta)
    return data


# ---------------------------------------------------------------------------
# stats
# ---------------------------------------------------------------------------

def experiment_stats(data: dict[str, Any]) -> dict[str, Any]:
    """Timing + convergence stats (≙ compute_stdev_and_percentiles and
    friends, tools/benchmark.py:60-144)."""
    out: dict[str, Any] = {}
    steps = data["steps"]
    if steps:
        out["num_steps"] = steps[-1]["step"]
        out["final_loss"] = steps[-1]["loss"]
        out["final_train_acc"] = steps[-1]["train_acc"]
        rates = [s["examples_per_sec"] for s in steps if s.get("examples_per_sec")]
        if rates:
            out["examples_per_sec"] = {"mean": float(np.mean(rates)),
                                       "max": float(np.max(rates))}
    if data.get("log_rewinds"):
        out["log_rewinds"] = data["log_rewinds"]
    if data["evals"]:
        best = max(e["precision_at_1"] for e in data["evals"])
        out["best_precision_at_1"] = best
        out["final_precision_at_1"] = data["evals"][-1]["precision_at_1"]
    if data.get("recovery"):
        from .journal import summarize_recovery_events
        out["recovery"] = summarize_recovery_events(data["recovery"])
    m = data["step_times"]
    if m is not None and m.size:
        out["per_replica"] = [compute_stats(m[:, i]).to_dict()
                              for i in range(m.shape[1])]
        out["barrier"] = compute_stats(m.max(axis=1)).to_dict()
        # per-iteration straggler quantiles (≙ ITERATION TIMES analysis,
        # tools/benchmark.py:86-111): p95/p99/p100 within each step row
        per_iter = np.percentile(m, [95, 99, 100], axis=1)
        out["per_iteration"] = {
            f"p{p}": {"mean": float(v.mean()), "median": float(np.median(v))}
            for p, v in zip((95, 99, 100), per_iter)}
    return out


# ---------------------------------------------------------------------------
# figures
# ---------------------------------------------------------------------------

def _axes(title: str, xlabel: str, ylabel: str):
    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt
    fig, ax = plt.subplots(figsize=(7, 4.5))
    ax.set_title(title, fontsize=10)
    ax.set_xlabel(xlabel)
    ax.set_ylabel(ylabel)
    return fig, ax


def _save(fig, path: Path) -> Path:
    import matplotlib.pyplot as plt
    fig.tight_layout()
    path.parent.mkdir(parents=True, exist_ok=True)
    fig.savefig(path, dpi=120)
    plt.close(fig)
    return path


def plot_experiment(data: dict[str, Any], out_dir: str | Path,
                    name: str = "experiment") -> list[Path]:
    """The reference's four curve figures + the per-replica CDF figure
    for a single experiment (tools/benchmark.py:165-263)."""
    out_dir = Path(out_dir)
    written: list[Path] = []
    steps = data["steps"]
    # logs from older runs may lack the "time" field — time-axis
    # figures degrade away individually, the rest still draw
    timed_steps = [s for s in steps if "time" in s]
    t0 = timed_steps[0]["time"] if timed_steps else None
    if steps:
        xs = np.array([s["step"] for s in steps])
        losses = np.array([s["loss"] for s in steps])
        fig, ax = _axes(f"{name}: loss vs step", "global step", "train loss")
        ax.plot(xs, losses)
        written.append(_save(fig, out_dir / "step_loss.png"))

    if timed_steps:
        ts = np.array([s["time"] - t0 for s in timed_steps])
        xs = np.array([s["step"] for s in timed_steps])
        losses = np.array([s["loss"] for s in timed_steps])

        fig, ax = _axes(f"{name}: loss vs time", "seconds", "train loss")
        ax.plot(ts, losses)
        written.append(_save(fig, out_dir / "time_loss.png"))

        fig, ax = _axes(f"{name}: step vs time", "seconds", "global step")
        ax.plot(ts, xs)
        written.append(_save(fig, out_dir / "time_step.png"))

    timed_evals = [e for e in data["evals"] if "time" in e]
    if timed_evals and t0 is not None:
        ets = np.array([e["time"] - t0 for e in timed_evals])
        prec = np.array([e["precision_at_1"] for e in timed_evals])
        fig, ax = _axes(f"{name}: test precision vs time", "seconds",
                        "precision @ 1")
        ax.plot(ets, prec, marker="o", markersize=3)
        written.append(_save(fig, out_dir / "time_precision.png"))

    m = data["step_times"]
    if m is not None and m.size:
        fig, ax = _axes(f"{name}: per-replica compute-time CDFs",
                        "step time (ms)", "CDF")
        for i in range(m.shape[1]):
            col = np.sort(m[:, i])
            ax.step(col, np.arange(1, col.size + 1) / col.size,
                    where="post", alpha=0.6, linewidth=0.9)
        written.append(_save(fig, out_dir / "replica_time_cdf.png"))
    return written


def plot_sweep(records: list[dict[str, Any]], out_dir: str | Path) -> list[Path]:
    """Cross-experiment comparison figures for a sweep: accuracy and
    throughput against the swept quorum size / interval, plus the
    overlaid per-replica mean CDFs (≙ the multi-cfg overlays,
    tools/benchmark.py:165-224)."""
    out_dir = Path(out_dir)
    written: list[Path] = []
    if not records:
        return written

    def numeric_sweep(key):
        vals = [r.get(key) for r in records]
        return (all(isinstance(v, (int, float)) for v in vals)
                and len(set(vals)) > 1)

    sweep_key = next((k for k in ("aggregate_k", "interval_ms")
                      if numeric_sweep(k)), None)
    if sweep_key:
        order = sorted(records, key=lambda r: r[sweep_key])
        xs = [r[sweep_key] for r in order]
        fig, ax = _axes(f"test accuracy vs {sweep_key}", sweep_key,
                        "test accuracy")
        ax.plot(xs, [r["test_accuracy"] for r in order], marker="o")
        written.append(_save(fig, out_dir / f"acc_vs_{sweep_key}.png"))

        fig, ax = _axes(f"throughput vs {sweep_key}", sweep_key,
                        "examples/sec")
        ax.plot(xs, [r["examples_per_sec"] or 0 for r in order], marker="o")
        written.append(_save(fig, out_dir / f"throughput_vs_{sweep_key}.png"))

    fig, ax = _axes("per-replica mean step time CDFs", "mean step time (ms)",
                    "CDF over replicas")
    drew = False
    for r in records:
        per_replica = r.get("timing", {}).get("per_replica", [])
        if not per_replica:
            continue
        means = sorted(s["mean"] for s in per_replica)
        ax.step(means, np.arange(1, len(means) + 1) / len(means),
                where="post", label=r["name"])
        drew = True
    if drew:
        ax.legend(fontsize=7)
        written.append(_save(fig, out_dir / "step_time_cdf.png"))
    else:
        import matplotlib.pyplot as plt
        plt.close(fig)
    return written


def steps_to_loss(steps: list[dict], threshold: float) -> int | None:
    """First logged step whose train loss falls to ``threshold``. With
    reference-parity dropout the train-acc forward runs at p=0.5, so
    loss is the usable per-step convergence signal."""
    for s in steps:
        if s.get("loss", float("inf")) <= threshold:
            return int(s["step"])
    return None


def modeled_step_durations_ms(steps: list[dict],
                              step_times: np.ndarray | None) -> np.ndarray | None:
    """Per-step MODELED barrier: the slowest CONTRIBUTING replica's
    sampled time — the wall-clock cost the aggregation discipline
    actually pays. Under quorum k-of-n this is the k-th order statistic
    of the per-replica times (backups past it are not waited for,
    arXiv:1604.00981's core effect); under full sync/cdf it is the max.

    This is what the reference's Experiment A measures on real EC2
    stragglers: convergence per STEP is nearly k-invariant (any masked
    mean is an unbiased gradient), so the whole quorum tradeoff lives
    in how long each step takes. Requires the per-step `flags` record
    and the [steps, n] step_times matrix."""
    if step_times is None or not len(step_times):
        return None
    out = []
    for rec in steps:
        i = rec["step"] - 1
        if not (0 <= i < len(step_times)):
            return None  # resumed run: rows don't align with steps
        row = step_times[i]
        flags = rec.get("flags")
        if flags and sum(flags) and len(flags) == len(row):
            out.append(max(t for t, f in zip(row, flags) if f))
        else:
            out.append(float(row.max()))
    return np.asarray(out)


def plot_group_overlays(records: list[dict[str, Any]],
                        results_dir: str | Path,
                        step_series: dict[str, list[dict]] | None = None
                        ) -> list[Path]:
    """Cross-experiment per-step overlays for one sweep group: train
    loss vs step and train accuracy vs step, one curve per experiment
    (≙ the reference's multi-cfg step_loss overlays,
    tools/benchmark.py:165-224). Reads each experiment's
    train_log.jsonl from ``results_dir/<name>/train`` unless the caller
    already loaded the series (``step_series``: name → step records)."""
    results_dir = Path(results_dir)
    series = []
    for r in records:
        steps = (step_series.get(r["name"]) if step_series is not None
                 else load_jsonl(results_dir / r["name"] / "train"
                                 / "train_log.jsonl", "step"))
        if steps:
            series.append((r["name"], steps))
    if not series:
        return []
    written = []
    for key, ylabel, fname in (("loss", "train loss", "group_step_loss.png"),
                               ("train_acc", "train accuracy",
                                "group_step_acc.png")):
        fig, ax = _axes(f"{results_dir.name}: {ylabel} vs step",
                        "global step", ylabel)
        for name, steps in series:
            xs = [s["step"] for s in steps]
            ys = [s[key] for s in steps]
            ax.plot(xs, ys, label=name, linewidth=1.0, alpha=0.85)
        ax.legend(fontsize=7)
        written.append(_save(fig, results_dir / fname))

    # loss vs MODELED wall-clock (cumulative contributor-barrier): the
    # discipline tradeoff the step-axis overlays can't show — under
    # heavy-tailed stragglers small k pays far less time per step at
    # near-identical per-step convergence (≙ the reference's
    # time_loss/time_precision figures, tools/benchmark.py:165-224)
    fig, ax = _axes(f"{results_dir.name}: train loss vs modeled wall-clock",
                    "modeled seconds (cumulative contributor barrier)",
                    "train loss")
    drew = False
    for name, steps in series:
        st = results_dir / name / "train" / "step_times.npy"
        durations = modeled_step_durations_ms(
            steps, np.load(st) if st.exists() else None)
        if durations is None:
            continue
        ax.plot(np.cumsum(durations) / 1e3, [s["loss"] for s in steps],
                label=name, linewidth=1.0, alpha=0.85)
        drew = True
    if drew:
        ax.legend(fontsize=7)
        written.append(_save(fig, results_dir / "group_modeled_time_loss.png"))
    else:
        import matplotlib.pyplot as plt
        plt.close(fig)
    return written


def generate_report(train_dir: str | Path, eval_dir: str | Path | None,
                    out_dir: str | Path, name: str = "experiment") -> dict:
    """One-stop: load logs → stats.json + figures. Returns the stats."""
    data = load_experiment(train_dir, eval_dir)
    stats = experiment_stats(data)
    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    (out_dir / "stats.json").write_text(json.dumps(stats, indent=2))
    try:
        figs = plot_experiment(data, out_dir, name)
        logger.info("report: %d figures → %s", len(figs), out_dir)
    except Exception as e:  # plotting is best-effort, stats always land
        logger.warning("figure generation skipped: %s", e)
    return stats
