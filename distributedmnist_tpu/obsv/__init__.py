from .timing import CdfStats, StepTimeCollector, compute_stats

__all__ = ["CdfStats", "StepTimeCollector", "compute_stats"]
