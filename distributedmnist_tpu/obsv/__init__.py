from .journal import load_journal, summarize_journal
from .timing import CdfStats, StepTimeCollector, compute_stats

__all__ = ["CdfStats", "StepTimeCollector", "compute_stats",
           "load_journal", "summarize_journal"]
