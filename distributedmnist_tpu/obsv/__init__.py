from .journal import load_journal, summarize_journal
from .schema import EventSchemaError, check_event, validate_event
from .timing import CdfStats, StepTimeCollector, compute_stats

__all__ = ["CdfStats", "EventSchemaError", "StepTimeCollector",
           "check_event", "compute_stats", "load_journal",
           "summarize_journal", "validate_event"]
