"""Journal-event schema registry — the single source of truth for what
every journaled record carries.

Thirteen PRs grew seven-plus journaled event contracts (command,
recovery, reconfigure, serve, step/save/compile, heartbeat, load,
fault, lifecycle, spawn, chaos_trial, eval) with the emitter side
(``launch/exec.py``, ``launch/supervisor.py``, ``train/loop.py``,
``servesvc/server.py``, …) and the reader side (``obsv/journal.py``
summarizers, ``obsv/invariants.py`` replay checks) each keeping their
own implicit field lists.  Drift between them — a save event writing
``at_step`` while a reader expects ``step``, a summarizer KeyError-ing
on a legacy tier-less swap — surfaced at chaos-campaign time or never.

This module is the mechanical contract both sides import:

* every event KIND is declared once, with its required fields (present
  at every emit site) and optional fields (present at some);
* kinds with an ``action`` axis (recovery, serve, …) declare the
  per-action payload the same way;
* ``obsv/journal.py`` and ``obsv/invariants.py`` project records
  through :func:`required_fields` / the kind constants below instead
  of re-listing field names;
* the static analysis pass (``distributedmnist_tpu.analysis``,
  "graftcheck") resolves every emit site at CI time and verifies
  literal payloads against this registry;
* :func:`validate_event` is the runtime half for payloads the AST pass
  cannot see (``**fields`` expansions, dicts built in loops) — wired
  into :class:`core.log.JsonlSink` behind the ``DMT_VALIDATE_EVENTS``
  env gate, on in tests, off in production hot paths.

Readers stay tolerant of LEGACY journals (replaying old artifacts must
never crash); the registry governs what the CURRENT tree is allowed to
WRITE.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Mapping

# -- canonical event-kind names (import these, don't re-spell them) ------
COMMAND = "command"
RECOVERY = "recovery"
RECONFIGURE = "reconfigure"
SERVE = "serve"
STEP = "step"
SAVE = "save"
COMPILE = "compile"
HEARTBEAT = "heartbeat"
LOAD = "load"
FAULT = "fault"
LIFECYCLE = "lifecycle"
SPAWN = "spawn"
CHAOS_TRIAL = "chaos_trial"
EVAL = "eval"
AUTOSCALE = "autoscale"
DISCIPLINE = "discipline"

# Fields any journaled record may carry regardless of kind: the sink
# stamps ``ts``, emitters stamp ``time``, the supervisor stamps ``seed``
# on everything it records, and multi-layer emitters tag ``layer``.
ENVELOPE_FIELDS = ("event", "ts", "time", "seed", "layer")


class EventSchemaError(ValueError):
    """A journaled record violates its declared event schema."""


@dataclasses.dataclass(frozen=True)
class ActionSchema:
    """Payload contract for one ``action`` of an event kind."""

    required: tuple[str, ...] = ()
    optional: tuple[str, ...] = ()


@dataclasses.dataclass(frozen=True)
class EventSchema:
    """Payload contract for one event kind.

    ``required``/``optional`` apply to every record of the kind;
    ``actions`` (when the kind has an action axis) adds per-action
    fields on top.  ``open_payload`` marks kinds whose payload is
    legitimately dynamic (e.g. ``compile`` carries whatever the AOT
    cache measured) — unknown keys are allowed, required keys still
    checked."""

    kind: str
    required: tuple[str, ...] = ()
    optional: tuple[str, ...] = ()
    actions: Mapping[str, ActionSchema] | None = None
    open_payload: bool = False


def _act(required: tuple[str, ...] = (),
         optional: tuple[str, ...] = ()) -> ActionSchema:
    return ActionSchema(required=required, optional=optional)


EVENT_SCHEMAS: dict[str, EventSchema] = {}


def _declare(schema: EventSchema) -> None:
    EVENT_SCHEMAS[schema.kind] = schema


# ---------------------------------------------------------------------------
# the registry
# ---------------------------------------------------------------------------

# launch/exec.py Executor.run / journal: one record per command attempt.
_declare(EventSchema(
    COMMAND,
    required=("verb", "argv"),
    optional=("rc", "duration_ms", "attempt", "check", "timed_out",
              "injected", "injected_delay_ms", "stdout_tail",
              "stderr_tail", "will_retry", "dry_run", "error"),
))

# Recovery episodes: supervisor detect/restart/resume chain
# (launch/supervisor.py), trainer self-healing (train/loop.py), and the
# checkpoint layer's fallback events (train/checkpoint.py,
# parallel/api.py) — all land as ``event: "recovery"`` records in the
# command journal and/or ``recovery_journal.jsonl``.
_declare(EventSchema(
    RECOVERY,
    required=("action",),
    optional=("worker",),
    actions={
        "detect": _act(("worker", "kind"), ("at_step", "stalled_at")),
        "restart_scheduled": _act(("worker", "attempt", "backoff_s")),
        "restart": _act(("worker", "attempt", "at_step", "via"),
                        ("detected_at", "respawn_s")),
        "restart_budget_exhausted": _act(("worker", "restarts"),
                                         ("reason",)),
        "resume": _act(("worker",),
                       ("step", "detected_at", "mttr_s", "respawned_at",
                        "resume_after_respawn_s")),
        "episode_superseded": _act(("worker", "by", "trigger")),
        "target_reached": _act(("step",)),
        "quorum_transition": _act(("workers_alive", "num_workers",
                                   "quorum", "degraded")),
        "below_quorum_abort": _act(("workers_alive", "quorum")),
        "standbys_requested": _act(("count",)),
        "standbys_unavailable": _act(("error",)),
        # trainer self-healing (train/loop.py)
        "nonfinite_loss_detected": _act(("step", "loss")),
        "nan_rollback": _act(("from_step", "to_step", "loss")),
        "rollback_candidate_unusable": _act(("step", "error")),
        "rollback_candidate_poisoned": _act(("step",)),
        "preempt_flush": _act(("signal", "step")),
        # checkpoint layer (train/checkpoint.py, parallel/api.py).
        # ``save_failed`` is the graceful ENOSPC/EIO degradation: a
        # cadence save that still failed after the bounded I/O retries
        # was journaled and SKIPPED (train/loop.py) — the
        # ``storage_faults`` invariant licenses every one against an
        # injected disk fault.
        "save_failed": _act(("step", "error"), ("errno", "where")),
        "follow_skip": _act(("step", "error")),
        "corrupt_checkpoint_fallback": _act(("bad_step", "error")),
        "fallback_restore": _act(("step",)),
        "cross_world_restore": _act(("step", "saved_world",
                                     "new_world")),
    },
))

# Elastic world reshapes — the causal LICENSE the cross-world resume
# invariant requires (launch/supervisor.py begin/relaunched/resume,
# launch/cluster.py reshape).
_declare(EventSchema(
    RECONFIGURE,
    required=("action",),
    actions={
        "begin": _act(("old_world", "new_world", "trigger", "quorum",
                       "effective_quorum", "survivors")),
        "reshape": _act(("old_world", "new_world", "old_workers",
                         "workers", "dropped", "grown")),
        "relaunched": _act(("old_world", "new_world", "trigger",
                            "drain_s", "workers", "via", "grown")),
        "resume": _act(("worker", "step", "old_world", "new_world",
                        "trigger", "reconfigure_s")),
    },
))

# Serving-replica journal (servesvc/server.py serve_log.jsonl).  The
# ``follow_*`` actions are the checkpoint follower's restore events
# re-journaled with their serve-side prefix.  The group lifecycle
# actions (``group_*`` / ``rank_*`` / ``shard_verify``) are the TP
# serving group's journal (servesvc/tp_group.py): the supervisor
# writes them to ``group_log.jsonl`` and follower ranks stamp every
# record with their ``rank`` — hence the top-level optional.
_declare(EventSchema(
    SERVE,
    required=("action",),
    optional=("rank",),
    actions={
        "serve_start": _act(("port", "model_step", "precision_tier",
                             "active_tier", "queue_depth", "max_batch")),
        "serve_stop": _act(("terminals", "model_step", "swaps")),
        "admit": _act(("id", "deadline_ms")),
        "respond": _act(("id", "model_step", "tier", "batch", "bucket",
                         "latency_ms")),
        "reject": _act(("id", "reason", "admitted")),
        # a retried request whose terminal is already cached: the
        # server returns the cached payload WITHOUT re-executing — the
        # exactly-once evidence invariant 13 (net_faults) requires
        "dedup_hit": _act(("id", "status"), ("age_s",)),
        # a connection closed by the read/write deadline or half-open
        # detection BEFORE any admit — no terminal is owed for it
        "conn_abort": _act(("reason",), ("bytes_read", "id")),
        "weight_swap": _act(("step", "from_step", "digest", "tier",
                             "source_artifact", "source_digest",
                             "swap_ms"),
                            ("initial", "sequences_pinned",
                             "sequences_restarted")),
        # -- decode service (servesvc/decode.py) ----------------------
        "decode_start": _act(("slots", "block_size", "num_blocks",
                              "max_prompt_len", "max_new_tokens",
                              "swap_policy", "model_step")),
        "prefill": _act(("id", "prompt_len", "bucket", "blocks",
                         "model_step", "ttft_ms"),
                        ("restart",)),
        "decode_finish": _act(("id", "reason", "tokens_streamed",
                               "model_step", "started_step",
                               "latency_ms"),
                              ("ttft_ms", "restarts")),
        "seq_restart": _act(("id", "from_step", "to_step",
                             "tokens_discarded")),
        "follow_quant_sidecar_fallback": _act(("step", "tier",
                                               "reason")),
        "follow_skip": _act(("step", "error")),
        "follow_corrupt_checkpoint_fallback": _act(("bad_step",
                                                    "error")),
        "follow_fallback_restore": _act(("step",)),
        "follow_cross_world_restore": _act(("step", "saved_world",
                                            "new_world")),
        # -- TP serving group lifecycle (servesvc/tp_group.py) ---------
        # die-as-a-unit is a CHECKED chain: every unexpected
        # ``rank_exit`` must be followed by a ``group_down`` before the
        # next ``group_start`` (the ``serve_group`` invariant) — a TP
        # replica missing a shard must never keep serving.
        "group_start": _act(("ranks", "attempt")),
        "rank_spawn": _act(("rank", "pid")),
        "rank_exit": _act(("rank", "pid", "rc")),
        "group_down": _act(("reason", "ranks"), ("rank",)),
        "group_restart": _act(("attempt", "backoff_s")),
        "group_stop": _act(("ranks",)),
        # follower ranks: sha256 of THIS rank's model-axis param shard
        # per verified publish — the shard-wise hot-swap evidence
        "shard_verify": _act(("rank", "step", "digest"),
                             ("source_digest",)),
    },
))

# Trainer metrics series (train/loop.py train_log.jsonl).  The
# optional ``discipline`` field is the [k, timeout_ms] pair in force
# when the step ran — written only when the adaptive controller is
# armed, and the per-step observation the ``discipline`` replay
# invariant matches licensed changes against.
_declare(EventSchema(
    STEP,
    required=("step", "time", "loss", "train_acc", "lr",
              "updates_applied", "num_contributors", "examples_per_sec",
              "flags"),
    optional=("discipline",),
))

# Checkpoint-save marker.  Deliberately ``at_step``, NOT ``step``: the
# resume watch (launch/cluster.py parse_poll_output) treats any record
# carrying ``step`` as training progress — a save record naming
# ``step`` would fake progress on a stalled worker.  This registry
# entry is what makes that a checked contract instead of lore.
_declare(EventSchema(
    SAVE,
    required=("at_step", "save_stall_ms", "async_snapshot"),
    optional=("quant_tiers",),
))

# Compile record: ``compile_s``/``source`` plus whatever the AOT
# executable cache measured — dynamic by design.
_declare(EventSchema(
    COMPILE,
    optional=("compile_s", "source", "persistent_cache", "error"),
    open_payload=True,
))

# Serving liveness counter (servesvc/server.py, the replica's
# train_log.jsonl — the supervisor's progress probe reads ``step``).
# The optional fields are the replica's live PRESSURE snapshot — queue
# depth at the admission bound, and (decode replicas) KV block-pool
# occupancy — so ``parse_poll_output`` surfaces per-replica pressure to
# the resource broker without a second channel.
_declare(EventSchema(
    HEARTBEAT,
    required=("step",),
    optional=("tp_rank", "queue_depth", "queue_limit", "kv_blocks_free",
              "kv_blocks_total", "kv_blocks_reserved",
              "decode_waiting"),
))

# Load-generator journal (servesvc/loadgen.py loadgen.jsonl): every
# issued request and its exactly-one terminal outcome, plus periodic
# rolling-window pressure snapshots (``window``) — the live signal the
# resource broker (launch/broker.py) scales the roster on.
_declare(EventSchema(
    LOAD,
    required=("action",),
    actions={
        "issue": _act(("id",)),
        "outcome": _act(("id", "status"),
                        ("reason", "model_step", "tier", "attempts",
                         "retried", "endpoint", "latency_ms",
                         # decode sweeps: the two-number latency split
                         "ttft_ms", "itl_ms", "tokens")),
        # rolling-window snapshot over the last ``window_s`` seconds:
        # latency percentiles only when the window saw ok responses
        "window": _act(("window_s", "terminal", "responses",
                        "rejected", "errors", "reject_rate"),
                       ("issued", "p50_ms", "p99_ms", "ttft_p50_ms",
                        "ttft_p99_ms", "throughput_rps", "retried",
                        "retry_rate")),
    },
))

# Fault-injector firings (launch/cluster.py process/disk faults,
# launch/netchaos.py transport faults) — the exemption evidence the
# replay invariants match violations against.  The ``net_*`` actions
# are the chaos proxy's journal: ``worker`` is the PROXIED replica (so
# the serve_outcomes faulted-replica exemption auto-covers it) and
# ``conn`` its per-proxy connection ordinal.
_declare(EventSchema(
    FAULT,
    required=("action", "worker"),
    actions={
        "kill_worker": _act(("pid", "at_step", "planned_step")),
        "hang_worker": _act(("pid", "at_step", "planned_step")),
        "stall_worker": _act(("pid", "stall_ms", "at_step",
                              "planned_step")),
        "corrupt_latest_checkpoint": _act(("at_step", "planned_step"),
                                          ("target", "truncated_to")),
        # -- transport faults (launch/netchaos.py ChaosProxy) ----------
        "net_latency": _act(("delay_ms", "jitter_ms"), ("conn",)),
        "net_bandwidth": _act(("bytes_per_s",), ("conn",)),
        "net_reset": _act(("after_bytes",),
                          ("conn", "bytes_passed", "mid_stream")),
        "net_blackhole": _act(("hold_s",), ("conn",)),
        "net_partition": _act(("start_s", "duration_s"),
                              ("conns_dropped",)),
        # -- storage faults (train/storage.py DiskFaultInjector) -------
        # journaled by the WORKER process into its own
        # storage_faults.jsonl (a worker cannot reach the supervisor's
        # command journal); ``path`` is the durable artifact the op
        # targeted, ``at_step`` the trainer step the injector last saw,
        # ``planned_step`` the script's arming step.
        "disk_enospc": _act(("path", "op"),
                            ("at_step", "planned_step", "budget_bytes")),
        "disk_eio": _act(("path", "op", "nth"),
                         ("at_step", "planned_step")),
        "disk_slow_io": _act(("path", "op", "ms"),
                             ("at_step", "planned_step")),
        "disk_torn_write": _act(("path", "at_byte"),
                                ("at_step", "planned_step", "op")),
        "disk_crash_rename": _act(("path", "kept_bytes"),
                                  ("at_step", "planned_step")),
    },
))

# Cluster-backend bookkeeping markers (launch/cluster.py).
_declare(EventSchema(
    LIFECYCLE,
    required=("action",),
    actions={
        "stale_state": _act(("cluster", "error")),
        "delete": _act(("cluster",)),
        "stale_worker_reaped": _act(("worker", "pid")),
        "standby_reaped": _act(("standby", "pid")),
        "promote_standby": _act(("worker", "standby", "pid")),
        "standby_backfill_failed": _act(("error",)),
    },
))

# Process spawns: a worker slot or a warm standby.
_declare(EventSchema(
    SPAWN,
    required=("pid", "command"),
    optional=("worker", "standby"),
))

# One record per chaos trial (launch/chaos.py chaos_report.jsonl).
_declare(EventSchema(
    CHAOS_TRIAL,
    required=("trial", "seed", "schedule", "described", "outcome",
              "step", "target", "duration_s", "verdicts", "violations"),
    optional=("mttr", "boot_s", "stall_timeout_s", "faults",
              "reconfigures", "final_world", "serving", "serve_swaps",
              "shrunk", "broker", "autoscale", "discipline", "net",
              "disk"),
))

# Continuous evaluator (evalsvc/evaluator.py eval_log.jsonl).
_declare(EventSchema(
    EVAL,
    required=("step", "num_examples", "precision_at_1", "loss",
              "seconds"),
))

# Resource-broker decisions (launch/broker.py) — the causal LICENSE the
# ``autoscale`` replay invariant requires for every roster change in a
# brokered run.  ``begin`` names the signal that crossed its threshold
# (``value op threshold`` must hold, checked at replay), ``complete``
# closes the episode once the new capacity is LIVE and carries the
# detect→capacity-live reaction time.
_declare(EventSchema(
    AUTOSCALE,
    required=("action",),
    actions={
        "begin": _act(("decision", "trigger", "value", "threshold",
                       "op", "old_serve", "new_serve", "old_train",
                       "new_train"),
                      ("window_s", "cooldown_s")),
        "complete": _act(("decision", "trigger", "reaction_s", "serve",
                          "train"),
                         ("worker", "grown", "dropped")),
        "error": _act(("decision", "error")),
    },
))

# Adaptive straggler-discipline changes (train/discipline.py, written
# to the trainer's train_log.jsonl) — the causal LICENSE the
# ``discipline`` replay invariant requires for every runtime change of
# the aggregation parameters.  ``begin`` names the CDF-percentile
# crossing that licensed the change (``value op threshold`` must hold,
# re-checked at replay); ``complete`` closes the episode once the new
# [k, timeout_ms] vector is staged and names the first step it governs
# (``effective_step`` — the discipline-epoch boundary the determinism
# invariant splices at).
_declare(EventSchema(
    DISCIPLINE,
    required=("action",),
    actions={
        "begin": _act(("decision", "trigger", "value", "threshold",
                       "op", "old_k", "new_k", "old_timeout_ms",
                       "new_timeout_ms", "at_step"),
                      ("window_steps", "cooldown_steps", "p50_ms",
                       "p99_ms", "num_replicas")),
        "complete": _act(("decision", "trigger", "reaction_s", "k",
                          "timeout_ms", "effective_step")),
    },
))


# ---------------------------------------------------------------------------
# accessors — what journal.py / invariants.py / the AST pass consume
# ---------------------------------------------------------------------------

def event_kinds() -> tuple[str, ...]:
    return tuple(sorted(EVENT_SCHEMAS))


def schema_for(kind: str) -> EventSchema | None:
    return EVENT_SCHEMAS.get(kind)


def action_schema(kind: str, action: str) -> ActionSchema | None:
    s = EVENT_SCHEMAS.get(kind)
    if s is None or s.actions is None:
        return None
    return s.actions.get(action)


def required_fields(kind: str, action: str | None = None
                    ) -> tuple[str, ...]:
    """The fields every record of ``kind`` (and ``action``, when given)
    is required to carry — payload fields only, envelope excluded.
    Summarizers project records through this instead of keeping their
    own lists."""
    s = EVENT_SCHEMAS.get(kind)
    if s is None:
        raise KeyError(f"unknown journal event kind {kind!r}")
    out = [f for f in s.required if f != "action"]
    if action is not None:
        a = action_schema(kind, action)
        if a is None:
            raise KeyError(f"unknown action {action!r} for journal "
                           f"event kind {kind!r}")
        out += [f for f in a.required if f not in out]
    return tuple(out)


def payload_fields(kind: str, action: str | None = None
                   ) -> tuple[str, ...]:
    """Required + optional payload fields, in declaration order."""
    s = EVENT_SCHEMAS.get(kind)
    if s is None:
        raise KeyError(f"unknown journal event kind {kind!r}")
    out = list(required_fields(kind, action))
    out += [f for f in s.optional if f not in out]
    if action is not None:
        a = action_schema(kind, action)
        if a is not None:
            out += [f for f in a.optional if f not in out]
    return tuple(out)


# ---------------------------------------------------------------------------
# runtime validation (the dynamic-payload half of graftcheck)
# ---------------------------------------------------------------------------

def validate_event(record: Mapping[str, Any],
                   source: str | None = None) -> list[str]:
    """Check one about-to-be-written record against the registry.

    Returns a list of problem strings (empty = conforming).  Records
    without an ``event`` key are not journal events (sweep-result rows
    share the JSONL sink) and pass vacuously."""
    kind = record.get("event")
    if kind is None:
        return []
    where = f" ({source})" if source else ""
    if not isinstance(kind, str) or kind not in EVENT_SCHEMAS:
        return [f"unknown journal event kind {kind!r}{where} — declare "
                "it in obsv/schema.py"]
    s = EVENT_SCHEMAS[kind]
    problems: list[str] = []
    keys = set(record) - set(ENVELOPE_FIELDS)
    allowed = set(s.required) | set(s.optional)
    for f in s.required:
        if f not in record:
            problems.append(f"event {kind!r}{where} missing required "
                            f"field {f!r}")
    action = record.get("action")
    a: ActionSchema | None = None
    if (s.actions is not None and "action" in record
            and not isinstance(action, str)):
        # a non-string action is exactly the dynamically-built-payload
        # bug this validator exists to catch — never let it pass as
        # "no action to check"
        problems.append(f"event {kind!r}{where} has non-string action "
                        f"{action!r} — actions are declared string "
                        "names (obsv/schema.py)")
    if s.actions is not None and isinstance(action, str):
        a = s.actions.get(action)
        if a is None:
            problems.append(f"event {kind!r}{where} has undeclared "
                            f"action {action!r} — declare it in "
                            "obsv/schema.py")
        else:
            allowed |= set(a.required) | set(a.optional)
            for f in a.required:
                if f not in record:
                    problems.append(
                        f"event {kind!r} action {action!r}{where} "
                        f"missing required field {f!r}")
    # unknown-key check only when the payload is closed AND the allowed
    # set is fully known (no action axis, or the action resolved)
    if not s.open_payload and (s.actions is None or a is not None):
        unknown = sorted(keys - allowed)
        if unknown:
            problems.append(
                f"event {kind!r}"
                + (f" action {action!r}" if action else "")
                + f"{where} carries undeclared field(s) "
                + ", ".join(repr(u) for u in unknown)
                + " — add them to obsv/schema.py or stop writing them")
    return problems


def check_event(record: Mapping[str, Any],
                source: str | None = None) -> None:
    """Raise :class:`EventSchemaError` on a non-conforming record."""
    problems = validate_event(record, source=source)
    if problems:
        raise EventSchemaError("; ".join(problems))


def validation_enabled() -> bool:
    """Debug-mode gate: ``DMT_VALIDATE_EVENTS`` truthy (tests set it;
    production writers skip the per-record check entirely)."""
    return os.environ.get("DMT_VALIDATE_EVENTS", "").lower() in (
        "1", "true", "yes", "on")


def maybe_check_event(record: Mapping[str, Any],
                      source: str | None = None) -> None:
    """The env-gated hook the shared journal-write helpers call."""
    if validation_enabled():
        check_event(record, source=source)
