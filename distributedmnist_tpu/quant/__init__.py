"""Post-training quantization (``quant/``): the publish-time pass that
writes int8/bf16 serving tiers as digest-verified sidecar artifacts,
and the dequantize/parity helpers the serving replica and the
accuracy oracle share."""

from .ptq import (QuantPublisher, calibrate_tiers, cast_tree_bf16,
                  dequantize_tree_int8, dynamic_input_fake_quant,
                  parity_report, quantize_leaf_int8, quantize_tree_int8,
                  tree_params_digest)

__all__ = [
    "QuantPublisher", "calibrate_tiers", "cast_tree_bf16",
    "dequantize_tree_int8", "dynamic_input_fake_quant", "parity_report",
    "quantize_leaf_int8", "quantize_tree_int8", "tree_params_digest",
]
