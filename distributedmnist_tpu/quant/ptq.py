"""Post-training quantization at checkpoint-publish time.

The serving tier runs inference only — none of the training-precision
guarantees apply to a predict pass, and mixed/low precision is the
single largest per-chip inference lever on TPUs (arXiv:1909.09756).
This module extends the storage-vs-compute dtype axis ``PrecisionConfig``
opened for training (PR 10, following arXiv:2004.13336's treatment of
storage dtype as an independent axis) to the SERVING side:

* **int8 tier** — per-channel symmetric int8 weights: every float
  param leaf with ndim ≥ 2 is quantized along its LAST axis (the
  output-channel axis for both HWIO conv kernels and ``[in, out]``
  dense kernels in this repo) as ``q = round(w / scale)`` with
  ``scale = amax(|w|, per-channel) / 127`` kept in float32; 1-D
  leaves (biases, norm scales) stay float32 — quantizing them buys
  nothing and costs parity, per the standard PTQ recipe. At serve
  time the int8 leaves live on-device (≈4× less weight HBM) and the
  predict function dequantizes them INSIDE the jitted graph — the
  per-channel rescale is a broadcast multiply XLA fuses into the
  matmul/conv operand pipeline (scale fusion), so no fp32 weight copy
  is ever resident. Activations keep the model's compute dtype, with
  one exception: the network INPUT — the one activation tensor every
  model family exposes without a per-family graph rewrite — is
  round-tripped through a per-tensor DYNAMIC int8 quantization
  (scale = amax(|x|)/127 computed in-graph per batch) when it is a
  float tensor, so the tier's precision claim covers the input edge
  too; integer token inputs pass through untouched.

* **bf16 tier** — a straight bfloat16 cast of the float leaves: the
  cheap middle tier (2× less weight HBM, MXU-native matmuls via the
  ``effective_model_config`` compute-dtype seam on the serving side).

* **Calibration** — at publish time the pass runs a held-out
  (test-split) batch through the fp32 graph and every tier's graph,
  records the observed input activation range and the per-tier top-1
  agreement in the sidecar metadata, and REFUSES to publish a tier
  whose agreement drops more than ``quant.parity_epsilon`` below the
  full-precision predictions — a publish-time guard so speed never
  silently buys wrongness (the serving replica then falls back to
  fp32 for that publish). The sidecar itself is written through
  ``train/checkpoint.py``'s atomic-write + sha256 machinery, so a torn
  sidecar is refused by digest verification exactly like a torn
  checkpoint.

The full-precision artifact is BYTE-UNCHANGED by all of this — the
sidecar is additive, pinned by the cross-knob digest test.
"""

from __future__ import annotations

import hashlib
import time
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
from flax import serialization

from ..core.log import get_logger

logger = get_logger("quant")

# int8 symmetric range: ±127 (not −128) so the scale maps amax exactly
# and negation is closed — the standard symmetric-PTQ convention.
_QMAX = 127.0


def _is_quantizable(a: np.ndarray) -> bool:
    """Per-channel int8 applies to float weight MATRICES/KERNELS
    (ndim ≥ 2); 1-D floats (biases, norm scales) and integer leaves
    pass through in their storage dtype."""
    return (isinstance(a, np.ndarray)
            and np.issubdtype(a.dtype, np.floating) and a.ndim >= 2)


def quantize_leaf_int8(w: np.ndarray) -> dict[str, np.ndarray]:
    """One float leaf → ``{"q": int8, "scale": float32}`` with the
    scale per LAST-axis channel (kept broadcast-shaped so the
    dequantize is one multiply). An all-zero channel gets scale 1.0 —
    its int8 zeros dequantize to exact zeros either way."""
    w = np.asarray(w, np.float32)
    absmax = np.max(np.abs(w), axis=tuple(range(w.ndim - 1)),
                    keepdims=True)
    scale = np.where(absmax > 0, absmax / _QMAX, 1.0).astype(np.float32)
    q = np.clip(np.round(w / scale), -_QMAX, _QMAX).astype(np.int8)
    return {"q": q, "scale": scale}


def quantize_tree_int8(params_sd: Any) -> Any:
    """A state-dict-shaped params tree → the int8 tier: quantizable
    leaves become ``{"q", "scale"}`` pairs, the rest stay float32 (or
    their integer storage dtype) as-is."""
    def leaf(a):
        a = np.asarray(a)
        if _is_quantizable(a):
            return quantize_leaf_int8(a)
        if np.issubdtype(a.dtype, np.floating):
            return a.astype(np.float32)
        return a
    return jax.tree.map(leaf, params_sd)


def cast_tree_bf16(params_sd: Any) -> Any:
    """A state-dict-shaped params tree → the bf16 tier (float leaves
    cast; integer leaves untouched)."""
    import ml_dtypes

    def leaf(a):
        a = np.asarray(a)
        if np.issubdtype(a.dtype, np.floating):
            return a.astype(ml_dtypes.bfloat16)
        return a
    return jax.tree.map(leaf, params_sd)


def _is_qpair(node: Any) -> bool:
    return (isinstance(node, dict) and set(node) == {"q", "scale"})


def dequantize_tree_int8(qtree: Any, dtype=jnp.float32) -> Any:
    """The int8 tier back to a float state-dict tree. jnp-traceable:
    the serving predict calls this INSIDE jit, so the per-channel
    rescale lowers next to its consuming matmul (scale fusion) and the
    int8 leaves are what stays resident on device."""
    def leaf(node):
        if _is_qpair(node):
            return node["q"].astype(dtype) * node["scale"].astype(dtype)
        return node
    return jax.tree.map(leaf, qtree, is_leaf=_is_qpair)


def dynamic_input_fake_quant(x: jax.Array) -> jax.Array:
    """Per-tensor DYNAMIC int8 round-trip of a float activation
    tensor: scale = amax(|x|)/127 computed in-graph for THIS batch, x
    rounded onto that grid and dequantized — the input edge of the
    int8 tier's precision claim, with no calibration constant to go
    stale (out-of-calibration inputs rescale instead of clipping)."""
    scale = jnp.maximum(jnp.max(jnp.abs(x)), 1e-12) / _QMAX
    return jnp.clip(jnp.round(x / scale), -_QMAX, _QMAX) * scale


def tier_param_bytes(tree: Any) -> int:
    """Resident weight bytes of a tier tree (the memory claim the
    bench artifact records)."""
    return sum(np.asarray(l).nbytes for l in jax.tree.leaves(tree))


def tree_params_digest(params_sd: Any) -> str:
    """sha256 over a host state-dict params tree — the 'source digest'
    the sidecar meta records, computed with the SAME canonical walk as
    ``train/checkpoint.py``'s artifact digests so it equals
    ``checkpoint_params_digest`` of the artifact the pass rode along
    with (single-file layout)."""
    from ..train.checkpoint import _digest_tree
    h = hashlib.sha256()
    _digest_tree(params_sd, h)
    return h.hexdigest()


# ---------------------------------------------------------------------------
# parity: the accuracy oracle shared by calibration, tests, and bench
# ---------------------------------------------------------------------------

def build_tier_predict(model, template_params: Any,
                       tier: str) -> Callable[[Any, Any], Any]:
    """The per-tier predict function (UNjitted; callers jit): takes
    the tier's stored tree (state-dict shaped) + an input batch,
    reconstructs the model's param pytree via ``from_state_dict``
    (structure only — template values unused), and returns
    ``model.predictions`` probabilities. ``fp32`` consumes the plain
    float state dict; ``bf16`` applies the bf16-stored leaves
    directly; ``int8`` dequantizes in-graph and fake-quants a float
    input dynamically."""
    input_is_float = np.issubdtype(np.dtype(model.input_dtype),
                                   np.floating)

    def predict(tree, x):
        if tier == "int8":
            if input_is_float:
                x = dynamic_input_fake_quant(x)
            tree = dequantize_tree_int8(tree)
        params = serialization.from_state_dict(template_params, tree)
        return model.predictions(model.apply(params, x, train=False))
    return predict


def parity_report(probs_ref: np.ndarray, probs_tier: np.ndarray,
                  labels: np.ndarray | None = None) -> dict[str, Any]:
    """Top-1 parity between a reference and a tier prediction set:
    ``agreement`` (fraction of examples whose argmax matches — the
    quantity ``quant.parity_epsilon`` gates) plus per-arm accuracy
    when labels are given."""
    top_ref = np.argmax(probs_ref, axis=-1)
    top_tier = np.argmax(probs_tier, axis=-1)
    out: dict[str, Any] = {
        "examples": int(top_ref.shape[0]),
        "agreement": round(float(np.mean(top_ref == top_tier)), 4),
        "max_abs_prob_delta": round(
            float(np.max(np.abs(probs_ref - probs_tier))), 5),
    }
    if labels is not None:
        labels = np.asarray(labels)
        out["top1_ref"] = round(float(np.mean(top_ref == labels)), 4)
        out["top1_tier"] = round(float(np.mean(top_tier == labels)), 4)
    return out


def calibrate_tiers(model, template_params: Any, params_sd: Any,
                    tiers: dict[str, Any], calib_inputs: np.ndarray,
                    calib_labels: np.ndarray | None = None,
                    predict_cache: dict | None = None) -> dict[str, Any]:
    """Run the held-out calibration batch through the fp32 graph and
    every tier's graph; returns ``{tier: parity_report, "input_amax":
    observed range}``. ``predict_cache`` (tier → jitted fn) amortizes
    the compiles across publishes."""
    cache = predict_cache if predict_cache is not None else {}

    def fn(tier):
        if tier not in cache:
            cache[tier] = jax.jit(
                build_tier_predict(model, template_params, tier))
        return cache[tier]

    x = calib_inputs
    ref = np.asarray(jax.device_get(fn("fp32")(params_sd, x)))
    out: dict[str, Any] = {"examples": int(x.shape[0])}
    if np.issubdtype(np.asarray(x).dtype, np.floating):
        out["input_amax"] = round(float(np.max(np.abs(x))), 6)
    for tier, tree in tiers.items():
        probs = np.asarray(jax.device_get(fn(tier)(tree, x)))
        out[tier] = parity_report(ref, probs, calib_labels)
    return out


# ---------------------------------------------------------------------------
# the publish-time pass
# ---------------------------------------------------------------------------

class QuantPublisher:
    """The checkpoint-publish hook (``quant.publish_tiers``): quantize
    the just-saved canonical params and write the sidecar next to the
    artifact. Thread-agnostic — the Trainer calls :meth:`publish`
    inline after a synchronous save, or hands it to the
    ``AsyncCheckpointer`` worker as the post-write callback (so on the
    async path the whole pass stays off the step loop's critical
    path). Per-tier jitted predicts are built once and reused across
    publishes."""

    def __init__(self, model, cfg, template_params: Any,
                 calib_inputs: np.ndarray | None,
                 calib_labels: np.ndarray | None = None):
        self.model = model
        self.qcfg = cfg.quant
        self.tiers = self.qcfg.resolved_publish_tiers()  # validates
        self.template_params = template_params
        n = self.qcfg.calibration_examples
        self.calib_inputs = (None if calib_inputs is None or n <= 0
                             else np.asarray(calib_inputs[:n]))
        self.calib_labels = (None if calib_labels is None or n <= 0
                             else np.asarray(calib_labels[:n]))
        self._predict_cache: dict[str, Any] = {}
        self.published = 0     # sidecars written (telemetry/tests)
        self.refused: list[tuple[int, str]] = []  # (step, tier) parity refusals

    def _params_from_snapshot(self, state: Any) -> Any | None:
        """The canonical params state dict out of whatever the save
        path holds: a ``("full", state_dict)`` snapshot (the async
        worker's shape), or a live/host state with a ``params``
        field. None for the per-host sharded layout — like the
        artifact digests, the pass needs the whole params here."""
        if (isinstance(state, tuple) and state
                and state[0] in ("full", "sharded")):
            if state[0] != "full":
                return None
            sd = state[1]
            return sd.get("params") if isinstance(sd, dict) else None
        sd = serialization.to_state_dict(state)
        if isinstance(sd, dict) and "params" in sd:
            return jax.tree.map(lambda a: np.asarray(jax.device_get(a)),
                                sd["params"])
        return None

    def publish(self, train_dir, state: Any, step: int) -> dict | None:
        """Quantize + calibrate + write the sidecar for ``step``.
        Returns the sidecar meta, or None when nothing was published
        (no tiers configured, sharded layout, or every tier refused).
        Never raises into the save path — a failed sidecar must not
        cost a checkpoint (logged instead; the serving tier falls back
        to fp32)."""
        if not self.tiers:
            return None
        try:
            return self._publish(train_dir, state, step)
        except Exception as e:  # additive artifact: degrade, don't fail
            logger.warning("quant sidecar publish for step=%d failed "
                           "(%s: %s) — serving falls back to fp32",
                           step, type(e).__name__, e)
            return None

    def _publish(self, train_dir, state: Any, step: int) -> dict | None:
        from ..train import checkpoint as ckpt
        params_sd = self._params_from_snapshot(state)
        if params_sd is None:
            logger.warning("quant tiers skipped at step=%d: per-host "
                           "sharded layout (quantize from a restored "
                           "template instead)", step)
            return None
        src_digest = tree_params_digest(params_sd)
        try:
            # idempotent per (step, source digest, tier set): the
            # final save at max_steps re-triggers the cadence step's
            # publish when the async writer drained between the two
            # enqueues — identical params must not pay the pass (or
            # bump the telemetry) twice. A different digest (same-step
            # re-save after a rollback) OR a tier the existing sidecar
            # lacks (re-publish under a widened quant.publish_tiers)
            # still republishes.
            existing = ckpt.read_quant_sidecar(train_dir, step)
            meta = existing.get("meta") or {}
            if (meta.get("source_params_digest") == src_digest
                    and set(self.tiers) <= set(meta.get("tiers") or ())):
                logger.info("quant sidecar step=%d already published "
                            "for this source digest + tiers; skipping",
                            step)
                return meta
        except (OSError, ValueError, KeyError):
            pass  # absent/torn sidecar: publish (re-)writes it
        t0 = time.perf_counter()
        built: dict[str, Any] = {}
        for tier in self.tiers:
            built[tier] = (quantize_tree_int8(params_sd) if tier == "int8"
                           else cast_tree_bf16(params_sd))
        meta: dict[str, Any] = {
            "step": step,
            "tiers": list(built),
            "source_params_digest": src_digest,
            "parity_epsilon": self.qcfg.parity_epsilon,
            "param_bytes": {"fp32": tier_param_bytes(params_sd),
                            **{t: tier_param_bytes(tr)
                               for t, tr in built.items()}},
        }
        if self.calib_inputs is not None:
            calib = calibrate_tiers(self.model, self.template_params,
                                    params_sd, built, self.calib_inputs,
                                    self.calib_labels,
                                    predict_cache=self._predict_cache)
            meta["calibration"] = calib
            floor = 1.0 - self.qcfg.parity_epsilon
            for tier in list(built):
                agreement = calib[tier]["agreement"]
                if agreement < floor:
                    # speed must never silently buy wrongness: the
                    # tier is NOT published; the serving replica's
                    # sidecar preference falls back to fp32
                    logger.warning(
                        "quant tier %s REFUSED at step=%d: calibration "
                        "top-1 agreement %.4f < %.4f (epsilon %.3f)",
                        tier, step, agreement, floor,
                        self.qcfg.parity_epsilon)
                    self.refused.append((step, tier))
                    del built[tier]
            meta["tiers"] = list(built)
        if not built:
            return None
        meta["publish_ms"] = round((time.perf_counter() - t0) * 1e3, 3)
        ckpt.write_quant_sidecar(train_dir, step, built, meta)
        self.published += 1
        logger.info("published quant sidecar step=%d tiers=%s (%.0f ms)",
                    step, ",".join(built), meta["publish_ms"])
        return meta
