"""graftcheck — contract-aware static analysis for jax_graft.

A pytest-free, import-free AST toolchain (``python -m
distributedmnist_tpu.analysis``) that moves contract violations from
chaos-campaign time to CI time.  Four checkers:

* ``schema``  — every journal emit site's literal payload verified
  against the ``obsv/schema.py`` event registry (reader/emitter drift
  becomes a CI failure, not a replay KeyError);
* ``config``  — every ``cfg.<section>.<field>`` access resolves to a
  declared dataclass field in ``core/config.py``; declared knobs never
  read anywhere are flagged dead;
* ``threads`` — instance attributes written from more than one
  thread-entry reachability root without a lock guard;
* ``jax``     — donated-buffer reuse after a donating jitted call,
  host-syncing ``.item()``/``float()`` inside step/batcher loops, and
  Python-scalar jit signatures that force per-value recompiles.

Never imports the analyzed modules (no jax required): everything is
``ast.parse`` over source.  Findings are machine-readable JSON;
known-accepted findings live in ``analysis/baseline.json`` with a
one-line justification each — legacy findings are explicit, never
silent.
"""

from .core import (Finding, iter_sources, load_baseline, run_checkers,
                   CHECKERS)

__all__ = ["Finding", "iter_sources", "load_baseline", "run_checkers",
           "CHECKERS"]
