"""graftcheck ``net``: the socket-deadline lint.

The serving protocol's robustness story (netchaos partitions, half-open
peers, mid-stream resets) only holds if **every blocking socket
operation on a hot path is bounded**: an unbounded ``recv`` against a
blackholed peer parks a connection thread forever, and an unbounded
``accept`` makes shutdown depend on one more client showing up.  This
pass walks ``servesvc/`` and ``launch/`` (the two packages that own
wire protocol) and flags:

1. ``.recv(...)`` / ``.accept(...)`` / ``.connect(...)`` calls whose
   enclosing **class** (or enclosing function, for module-level code)
   contains no ``settimeout`` call.  Evidence is class-scoped on
   purpose: the listener's ``settimeout`` often lives in ``start()``
   while the ``accept`` loop is a different method of the same object.
2. ``socket.create_connection(...)`` calls that pass no ``timeout``
   (neither the kwarg nor the second positional argument) — the
   default is a *blocking* connect, which a SYN-blackholed endpoint
   turns into a multi-minute kernel stall.

Class-scoped evidence is an over-approximation by design: a timeout
set on socket A does not bound socket B.  But the codebase's idiom is
one socket role per class, and the lint's job is to catch the call
site with *no* deadline discipline anywhere in sight — per-socket
dataflow belongs to review, not AST matching.
"""

from __future__ import annotations

import ast

from .core import Finding, Source, add_parents, enclosing, make_key, register

_BLOCKING_ATTRS = ("recv", "accept", "connect")
_SCOPE_PREFIXES = ("distributedmnist_tpu/servesvc/",
                   "distributedmnist_tpu/launch/")


def _callee_name(call: ast.Call) -> str | None:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


def _scope_of(node: ast.AST, src: Source) -> tuple[ast.AST, str]:
    """The deadline-evidence scope for a call: its class if it has one,
    else its function, else the whole module."""
    cls = enclosing(node, ast.ClassDef)
    if cls is not None:
        return cls, cls.name
    fn = enclosing(node, ast.FunctionDef, ast.AsyncFunctionDef)
    if fn is not None:
        return fn, fn.name
    return src.tree, "<module>"


def _has_settimeout(scope: ast.AST) -> bool:
    for node in ast.walk(scope):
        if (isinstance(node, ast.Call)
                and _callee_name(node) == "settimeout"):
            return True
    return False


def _fn_name(node: ast.AST) -> str:
    fn = enclosing(node, ast.FunctionDef, ast.AsyncFunctionDef)
    return fn.name if fn is not None else "<module>"


@register("net")
def check(sources: list[Source]) -> list[Finding]:
    out: list[Finding] = []
    for src in sources:
        if src.is_test:
            continue
        if not src.path.startswith(_SCOPE_PREFIXES):
            continue
        add_parents(src.tree)
        timeout_cache: dict[int, bool] = {}
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _callee_name(node)
            if name == "create_connection":
                # create_connection(addr, timeout) — bounded iff the
                # timeout kwarg or the 2nd positional arg is passed
                if (len(node.args) < 2
                        and not any(kw.arg == "timeout"
                                    for kw in node.keywords)):
                    fn = _fn_name(node)
                    out.append(Finding(
                        "net", src.path, node.lineno,
                        make_key("net", src.path,
                                 f"{fn}.create_connection"),
                        f"create_connection in {fn}() passes no "
                        "timeout — a SYN-blackholed endpoint stalls "
                        "this thread at the kernel's connect "
                        "timeout, minutes past any request "
                        "deadline"))
                continue
            if not (isinstance(node.func, ast.Attribute)
                    and node.func.attr in _BLOCKING_ATTRS):
                continue
            scope, scope_name = _scope_of(node, src)
            key = id(scope)
            if key not in timeout_cache:
                timeout_cache[key] = _has_settimeout(scope)
            if timeout_cache[key]:
                continue
            fn = _fn_name(node)
            out.append(Finding(
                "net", src.path, node.lineno,
                make_key("net", src.path,
                         f"{scope_name}.{fn}.{node.func.attr}"),
                f"{node.func.attr}() in {scope_name}.{fn} has no "
                "settimeout anywhere in its scope — a half-open or "
                "blackholed peer blocks this call forever and the "
                "thread never rejoins shutdown"))
    return out
