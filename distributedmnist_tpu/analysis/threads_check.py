"""graftcheck ``threads``: the concurrency lint.

The codebase runs at least six long-lived thread kinds (device-prefetch
producer, checkpoint follower, serving batcher/accept/conn threads,
async checkpointer worker, supervisor tick, standby back-fill).  For
every class that spawns threads, this pass:

1. resolves the class's **thread-entry roots** — methods passed as
   ``threading.Thread(target=...)`` (directly, via a loop over a tuple
   of bound methods, or via a local alias) — plus the synthetic
   ``caller`` root (public methods invoked from whatever thread owns
   the object);
2. builds the intra-class call graph and computes which roots can
   reach each method;
3. flags instance attributes assigned (outside ``__init__`` —
   construction happens-before thread start) from **more than one
   root** where at least one write is not under a ``with self.<lock>``
   guard (lock attributes are recognized by construction —
   ``threading.Lock/RLock/Condition/Semaphore`` — or by name).

This is a reachability over-approximation by design: a write two
threads CAN reach without a lock is a hazard even if today's
interleavings dodge it.  Guards taken by the caller one frame up are
invisible to the AST — those are exactly what the baseline file's
one-line justifications are for.
"""

from __future__ import annotations

import ast

from .core import (Finding, Source, add_parents, enclosing, make_key,
                   register)

_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore"}
_LOCKISH = ("lock", "cond", "mutex", "wake", "cv")


def _callee_name(call: ast.Call) -> str | None:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


def _self_attr(node: ast.expr, self_name: str = "self") -> str | None:
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == self_name):
        return node.attr
    return None


def _thread_targets(cls: ast.ClassDef,
                    method_names: set[str]) -> set[str]:
    """Methods of this class used as Thread targets."""
    roots: set[str] = set()
    for node in ast.walk(cls):
        if not (isinstance(node, ast.Call)
                and _callee_name(node) in ("Thread", "Timer")):
            continue
        # Thread(group, target, ...) / Timer(interval, function, ...):
        # the callable is the `target`/`function` kwarg, or positional
        # index 1 — arg0 is group/interval, never the callable
        target_expr = None
        for kw in node.keywords:
            if kw.arg in ("target", "function"):
                target_expr = kw.value
        if target_expr is None and len(node.args) > 1:
            target_expr = node.args[1]
        if target_expr is None:
            continue
        m = _self_attr(target_expr)
        if m in method_names:
            roots.add(m)
            continue
        if isinstance(target_expr, ast.Name):
            # resolve a local alias: `t = self._m` assignments and
            # `for target in (self._a, self._b): Thread(target=target)`
            fn = enclosing(node, ast.FunctionDef, ast.AsyncFunctionDef)
            if fn is None:
                continue
            var = target_expr.id
            for stmt in ast.walk(fn):
                if (isinstance(stmt, ast.Assign)
                        and any(isinstance(t, ast.Name) and t.id == var
                                for t in stmt.targets)):
                    m = _self_attr(stmt.value)
                    if m in method_names:
                        roots.add(m)
                elif (isinstance(stmt, ast.For)
                      and isinstance(stmt.target, ast.Name)
                      and stmt.target.id == var
                      and isinstance(stmt.iter, (ast.Tuple, ast.List))):
                    for el in stmt.iter.elts:
                        m = _self_attr(el)
                        if m in method_names:
                            roots.add(m)
    return roots


def _lock_attrs(cls: ast.ClassDef) -> set[str]:
    out: set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and isinstance(node.value,
                                                       ast.Call):
            if _callee_name(node.value) in _LOCK_CTORS:
                for t in node.targets:
                    a = _self_attr(t)
                    if a:
                        out.add(a)
    return out


def _guarded(node: ast.AST, lock_attrs: set[str]) -> bool:
    """Is this statement lexically inside ``with self.<lock>:``?"""
    cur = getattr(node, "parent", None)
    while cur is not None and not isinstance(cur, (ast.FunctionDef,
                                                   ast.AsyncFunctionDef)):
        if isinstance(cur, ast.With):
            for item in cur.items:
                ctx = item.context_expr
                a = _self_attr(ctx)
                if a is None and isinstance(ctx, ast.Attribute):
                    a = ctx.attr
                if a and (a in lock_attrs
                          or any(s in a.lower() for s in _LOCKISH)):
                    return True
        cur = getattr(cur, "parent", None)
    return False


def _reach(edges: dict[str, set[str]], entries: set[str]) -> set[str]:
    seen = set(entries)
    work = list(entries)
    while work:
        m = work.pop()
        for n in edges.get(m, ()):
            if n not in seen:
                seen.add(n)
                work.append(n)
    return seen


def _check_class(src: Source, cls: ast.ClassDef,
                 out: list[Finding]) -> None:
    methods = {n.name: n for n in cls.body
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    thread_roots = _thread_targets(cls, set(methods))
    if not thread_roots:
        return
    lock_attrs = _lock_attrs(cls)

    edges: dict[str, set[str]] = {}
    for name, fn in methods.items():
        outs: set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                m = _self_attr(node.func)
                if m in methods:
                    outs.add(m)
        edges[name] = outs

    reach = {t: _reach(edges, {t}) for t in thread_roots}
    caller_entries = {n for n in methods
                      if not n.startswith("_") and n not in thread_roots}
    reach["caller"] = _reach(edges, caller_entries)

    # attr -> {root}, plus the unguarded evidence
    attr_roots: dict[str, set[str]] = {}
    attr_unguarded: dict[str, tuple[int, str]] = {}
    for name, fn in methods.items():
        if name == "__init__":
            continue  # construction happens-before thread start
        roots = {t for t in thread_roots if name in reach[t]}
        if name in reach["caller"]:
            roots.add("caller")
        if not roots:
            continue
        for node in ast.walk(fn):
            targets: list[ast.expr] = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = [node.target]
            for t in targets:
                attr = _self_attr(t)
                if attr is None or attr in lock_attrs:
                    continue
                attr_roots.setdefault(attr, set()).update(roots)
                if (attr not in attr_unguarded
                        and not _guarded(node, lock_attrs)):
                    attr_unguarded[attr] = (node.lineno, name)

    for attr, roots in sorted(attr_roots.items()):
        if len(roots) < 2 or attr not in attr_unguarded:
            continue
        line, method = attr_unguarded[attr]
        out.append(Finding(
            "threads", src.path, line,
            make_key("threads", src.path, f"{cls.name}.{attr}"),
            f"{cls.name}.{attr} is written from "
            f"{len(roots)} thread-entry roots "
            f"({', '.join(sorted(roots))}) and the write in "
            f"{method}() holds no lock — unsynchronized cross-thread "
            "mutation"))


@register("threads")
def check(sources: list[Source]) -> list[Finding]:
    out: list[Finding] = []
    for src in sources:
        if src.is_test:
            continue
        add_parents(src.tree)
        for node in ast.walk(src.tree):
            if isinstance(node, ast.ClassDef):
                _check_class(src, node, out)
    return out
