"""graftcheck ``config``: the config-knob audit.

The declared surface is parsed from ``core/config.py``'s AST (never
imported): every ``@dataclass`` section class's fields and methods,
and the ``ExperimentConfig`` section map.  Two directions:

* **undeclared access** — any ``<cfg>.<section>.<field>`` attribute
  chain in the package or tests, where ``<cfg>`` is a config-named
  base (``cfg``, ``config``, ``self.cfg``, ``base_config()``, …) and
  ``<section>`` is a declared section, must name a declared field or
  method of that section class.  A typo'd knob read returns
  AttributeError at runtime only on the code path that reaches it —
  here it fails CI.
* **dead knob** — a declared field never read anywhere (not as an
  attribute of anything, not as a keyword argument, not as a string
  key in any dict/config literal) is flagged: config surface nobody
  consumes is a lie to operators.
"""

from __future__ import annotations

import ast

from .core import Finding, Source, make_key, register

_CONFIG_PATH = "distributedmnist_tpu/core/config.py"

# names every dataclass instance answers without declaring
_ALWAYS_OK = {"replace",}


def _is_dataclass_def(node: ast.ClassDef) -> bool:
    for d in node.decorator_list:
        target = d.func if isinstance(d, ast.Call) else d
        name = (target.id if isinstance(target, ast.Name)
                else target.attr if isinstance(target, ast.Attribute)
                else None)
        if name == "dataclass":
            return True
    return False


def parse_declared(config_src: Source) -> tuple[dict[str, str],
                                                dict[str, set[str]],
                                                dict[str, set[str]],
                                                dict[str, int]]:
    """(section -> class name, class -> fields, class -> methods,
    ``section.field`` -> declaration line)."""
    fields: dict[str, set[str]] = {}
    methods: dict[str, set[str]] = {}
    lines: dict[str, dict[str, int]] = {}
    classes: dict[str, ast.ClassDef] = {}
    for node in config_src.tree.body:
        if isinstance(node, ast.ClassDef) and _is_dataclass_def(node):
            classes[node.name] = node
            fields[node.name] = set()
            methods[node.name] = set()
            lines[node.name] = {}
            for stmt in node.body:
                if (isinstance(stmt, ast.AnnAssign)
                        and isinstance(stmt.target, ast.Name)):
                    fields[node.name].add(stmt.target.id)
                    lines[node.name][stmt.target.id] = stmt.lineno
                elif isinstance(stmt, ast.FunctionDef):
                    methods[node.name].add(stmt.name)
    sections: dict[str, str] = {}
    decl_lines: dict[str, int] = {}
    exp = classes.get("ExperimentConfig")
    if exp is not None:
        for stmt in exp.body:
            if (isinstance(stmt, ast.AnnAssign)
                    and isinstance(stmt.target, ast.Name)):
                ann = stmt.annotation
                cls = (ann.id if isinstance(ann, ast.Name)
                       else ann.value if isinstance(ann, ast.Constant)
                       else None)
                if isinstance(cls, str) and cls in classes:
                    sections[stmt.target.id] = cls
    for sec, cls in sections.items():
        for f, ln in lines[cls].items():
            decl_lines[f"{sec}.{f}"] = ln
    return sections, fields, methods, decl_lines


def _config_base(node: ast.expr) -> bool:
    """Is this expression plausibly an ExperimentConfig value?"""
    if isinstance(node, ast.Name):
        n = node.id.lower()
        return n in ("cfg", "config") or n.endswith("cfg") \
            or n.endswith("config")
    if isinstance(node, ast.Attribute):
        n = node.attr.lower()
        return n in ("cfg", "_cfg", "config") or n.endswith("cfg")
    if isinstance(node, ast.Call):
        f = node.func
        n = (f.id if isinstance(f, ast.Name)
             else f.attr if isinstance(f, ast.Attribute) else "")
        return "config" in n.lower() or n.lower().endswith("cfg")
    return False


@register("config")
def check(sources: list[Source]) -> list[Finding]:
    config_src = next((s for s in sources if s.path == _CONFIG_PATH),
                      None)
    if config_src is None:
        return []
    sections, fields, methods, decl_lines = parse_declared(config_src)

    out: list[Finding] = []
    # everything that counts as "this name is consumed somewhere"
    read_names: set[str] = set()

    for src in sources:
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Attribute):
                read_names.add(node.attr)
                # strict <cfg>.<section>.<field> resolution
                v = node.value
                if (isinstance(v, ast.Attribute)
                        and v.attr in sections
                        and _config_base(v.value)):
                    cls = sections[v.attr]
                    field = node.attr
                    if field.startswith("__"):
                        continue
                    if (field not in fields[cls]
                            and field not in methods[cls]
                            and field not in _ALWAYS_OK):
                        out.append(Finding(
                            "config", src.path, node.lineno,
                            make_key("config", src.path,
                                     f"unknown.{v.attr}.{field}"),
                            f"cfg.{v.attr}.{field} does not resolve to "
                            f"a declared field of {cls} "
                            "(core/config.py) — typo'd or removed "
                            "knob"))
            elif isinstance(node, ast.keyword) and node.arg:
                read_names.add(node.arg)
            elif (isinstance(node, ast.Constant)
                  and isinstance(node.value, str)
                  and len(node.value) < 200):
                # dict keys in config literals, dotted CLI overrides,
                # f-string fragments — split on the delimiters knobs
                # travel through
                for part in node.value.replace("=", ".").split("."):
                    part = part.strip()
                    if part.isidentifier():
                        read_names.add(part)

    # dead knobs: declared but consumed nowhere outside config.py's own
    # declarations.  config.py itself contributes reads too (validate()
    # bodies, effective_* helpers) — those count.
    for section, cls in sorted(sections.items()):
        for field in sorted(fields[cls]):
            if field not in read_names:
                out.append(Finding(
                    "config", _CONFIG_PATH,
                    decl_lines.get(f"{section}.{field}", 1),
                    make_key("config", _CONFIG_PATH,
                             f"dead.{section}.{field}"),
                    f"declared knob {section}.{field} is never read "
                    "anywhere in the package or tests — dead config "
                    "surface"))
    return out
