"""graftcheck ``schema``: journal emit sites vs the event registry.

Resolves every emit site whose payload is a LITERAL dict — full
records carrying ``"event"`` (``exec.journal``, ``JsonlSink.write``,
the chaos/eval/loadgen writers), and the wrapper helpers that add the
kind downstream (``ServingReplica._journal``/``_terminal`` → serve,
``Trainer._recovery_event`` / checkpoint ``on_event`` callbacks →
recovery, ``ClusterSupervisor._event``/``_reconf_event`` →
recovery/reconfigure) — and verifies the payload against
``obsv/schema.py``: the kind is declared, the action is declared,
every required field is present, no undeclared field is written.

Payloads the AST can't see (``**fields`` expansions, dicts built in
loops) get the literal keys they DO show checked, and the rest is the
runtime validator's job (``schema.maybe_check_event``, on in tests).

Test files are exempt: their event-dict literals are overwhelmingly
READER fixtures (deliberately legacy/torn records proving the readers
tolerate them); writes tests perform through the shared sinks are
runtime-validated instead.

The registry is loaded by file path (``importlib`` on
``obsv/schema.py`` alone — it is pure stdlib), so the checker never
imports the analyzed package.
"""

from __future__ import annotations

import ast
import importlib.util
import sys
from pathlib import Path

from .core import Finding, Source, make_key, register

# wrapper-call table: helper name -> (event kind, mode, path prefixes)
#   mode "payload":    sole positional arg is the payload dict
#   mode "action-arg": arg0 is the action literal, keywords the payload
# implicit: fields the wrapper adds before the record hits the sink
_WRAPPERS: dict[str, tuple[str, str, tuple[str, ...], frozenset[str]]] = {
    "_journal": ("serve", "payload", ("distributedmnist_tpu/servesvc/",),
                 frozenset({"event", "time"})),
    "_terminal": ("serve", "action-arg",
                  ("distributedmnist_tpu/servesvc/",),
                  frozenset({"event", "time", "action", "id"})),
    "_recovery_event": ("recovery", "payload",
                        ("distributedmnist_tpu/train/",),
                        frozenset({"event", "time"})),
    "_event": ("recovery", "action-arg",
               ("distributedmnist_tpu/launch/supervisor",),
               frozenset({"event", "layer", "action", "time", "seed"})),
    "_reconf_event": ("reconfigure", "action-arg",
                      ("distributedmnist_tpu/launch/supervisor",),
                      frozenset({"event", "layer", "action", "time",
                                 "seed"})),
    "_autoscale_event": ("autoscale", "action-arg",
                         ("distributedmnist_tpu/launch/broker",),
                         frozenset({"event", "layer", "action", "time",
                                    "seed"})),
    # checkpoint-layer callbacks: the Trainer re-journals these as
    # event:"recovery" records (train/loop.py _recovery_event)
    "on_event": ("recovery", "payload", ("distributedmnist_tpu/",),
                 frozenset({"event", "time"})),
    "_on_event": ("recovery", "payload", ("distributedmnist_tpu/",),
                  frozenset({"event", "time"})),
}


def load_registry():
    """The ``obsv/schema.py`` registry, loaded standalone (no package
    import, no jax)."""
    path = Path(__file__).resolve().parents[1] / "obsv" / "schema.py"
    spec = importlib.util.spec_from_file_location("_graftcheck_schema",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    # dataclasses resolves string annotations via sys.modules — the
    # standalone module must be registered before exec
    sys.modules["_graftcheck_schema"] = mod
    spec.loader.exec_module(mod)  # type: ignore[union-attr]
    return mod


def _dict_literal_keys(node: ast.Dict) -> tuple[dict[str, ast.expr], bool]:
    """(literal string keys -> value node, has_dynamic_part)."""
    keys: dict[str, ast.expr] = {}
    dynamic = False
    for k, v in zip(node.keys, node.values):
        if k is None:  # **expansion
            dynamic = True
        elif isinstance(k, ast.Constant) and isinstance(k.value, str):
            keys[k.value] = v
        else:
            dynamic = True
    return keys, dynamic


def _check_payload(reg, src: Source, line: int, kind: str,
                   action: str | None, action_dynamic: bool,
                   keys: set[str], payload_dynamic: bool,
                   implicit: frozenset[str],
                   out: list[Finding]) -> None:
    envelope = set(reg.ENVELOPE_FIELDS) | implicit
    sch = reg.schema_for(kind)
    if sch is None:
        out.append(Finding(
            "schema", src.path, line,
            make_key("schema", src.path, f"unknown-kind.{kind}"),
            f'emit of undeclared journal event kind "{kind}" — declare '
            "it in obsv/schema.py"))
        return
    allowed = set(sch.required) | set(sch.optional) | envelope
    required = [f for f in sch.required
                if f != "action" or "action" not in implicit]
    act = None
    if sch.actions is not None:
        if action is not None:
            act = sch.actions.get(action)
            if act is None:
                out.append(Finding(
                    "schema", src.path, line,
                    make_key("schema", src.path,
                             f"unknown-action.{kind}.{action}"),
                    f'emit of event "{kind}" with undeclared action '
                    f'"{action}" — declare it in obsv/schema.py'))
                return
            allowed |= set(act.required) | set(act.optional)
            required = required + list(act.required)
        elif action_dynamic or "action" in keys:
            # action resolved at runtime: any declared action's fields
            # are plausible — only literal-key sanity applies
            for a in sch.actions.values():
                allowed |= set(a.required) | set(a.optional)
            required = []
        else:
            # kind has an action axis but this emit names none
            required = list(sch.required)
    if not payload_dynamic:
        subj = f"{kind}.{action}" if action else kind
        for f in required:
            if f not in keys and f not in envelope:
                out.append(Finding(
                    "schema", src.path, line,
                    make_key("schema", src.path, f"missing.{subj}.{f}"),
                    f'emit of event "{kind}"'
                    + (f' action "{action}"' if action else "")
                    + f' omits required field "{f}" '
                    "(obsv/schema.py) — a reader projecting this field "
                    "gets None"))
    if not sch.open_payload:
        subj = f"{kind}.{action}" if action else kind
        for f in sorted(keys - allowed - {"event", "action"}):
            out.append(Finding(
                "schema", src.path, line,
                make_key("schema", src.path, f"undeclared.{subj}.{f}"),
                f'emit of event "{kind}"'
                + (f' action "{action}"' if action else "")
                + f' writes undeclared field "{f}" — add it to '
                "obsv/schema.py or stop writing it"))


def _scan_module(reg, src: Source, out: list[Finding]) -> None:
    handled_dicts: set[int] = set()

    # pass 1: wrapper helper calls
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.Call):
            continue
        name = None
        if isinstance(node.func, ast.Attribute):
            name = node.func.attr
        elif isinstance(node.func, ast.Name):
            name = node.func.id
        spec = _WRAPPERS.get(name or "")
        if spec is None:
            continue
        kind, mode, prefixes, implicit = spec
        if not any(src.path.startswith(p) for p in prefixes):
            continue
        if mode == "payload":
            if len(node.args) != 1 or not isinstance(node.args[0],
                                                     ast.Dict):
                continue
            payload = node.args[0]
            keys, dynamic = _dict_literal_keys(payload)
            if "event" in keys:
                continue  # a full record: pass 2 owns it
            handled_dicts.add(id(payload))
            action_node = keys.get("action")
            action = (action_node.value
                      if isinstance(action_node, ast.Constant)
                      and isinstance(action_node.value, str) else None)
            _check_payload(reg, src, node.lineno, kind, action,
                           action_dynamic="action" in keys
                           and action is None,
                           keys=set(keys), payload_dynamic=dynamic,
                           implicit=implicit, out=out)
        else:  # action-arg
            if not node.args:
                continue
            a0 = node.args[0]
            action = (a0.value if isinstance(a0, ast.Constant)
                      and isinstance(a0.value, str) else None)
            keys = {kw.arg for kw in node.keywords if kw.arg is not None}
            dynamic = any(kw.arg is None for kw in node.keywords)
            _check_payload(reg, src, node.lineno, kind, action,
                           action_dynamic=action is None,
                           keys=keys, payload_dynamic=dynamic,
                           implicit=implicit, out=out)

    # pass 2: any literal dict that IS a full journal record
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.Dict) or id(node) in handled_dicts:
            continue
        keys, dynamic = _dict_literal_keys(node)
        ev = keys.get("event")
        if not (isinstance(ev, ast.Constant) and isinstance(ev.value,
                                                            str)):
            continue
        action_node = keys.get("action")
        action = (action_node.value
                  if isinstance(action_node, ast.Constant)
                  and isinstance(action_node.value, str) else None)
        _check_payload(reg, src, node.lineno, ev.value, action,
                       action_dynamic="action" in keys and action is
                       None,
                       keys=set(keys) - {"event"},
                       payload_dynamic=dynamic,
                       implicit=frozenset(), out=out)


@register("schema")
def check(sources: list[Source]) -> list[Finding]:
    reg = load_registry()
    out: list[Finding] = []
    for src in sources:
        if src.is_test:
            continue
        if src.path.endswith("obsv/schema.py"):
            continue  # the registry's own docs/examples
        _scan_module(reg, src, out)
    return out
