"""graftcheck CLI: ``python -m distributedmnist_tpu.analysis``.

Exit status is the CI gate: 0 when every finding is baselined (or the
tree is clean), 1 when any non-baselined finding exists, 2 when the
baseline names findings that no longer fire (stale entries must be
pruned so the file stays an honest ledger).

Typical runs::

    python -m distributedmnist_tpu.analysis                  # text
    python -m distributedmnist_tpu.analysis --format json    # CI
    python -m distributedmnist_tpu.analysis --checkers schema,config
    python -m distributedmnist_tpu.analysis --write-baseline # accept
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from .core import (CHECKERS, baseline_to_json, iter_sources,
                   load_baseline, run_checkers)


def main(argv: list[str] | None = None) -> int:
    repo_root = Path(__file__).resolve().parents[2]
    ap = argparse.ArgumentParser(
        prog="python -m distributedmnist_tpu.analysis",
        description="graftcheck: contract-aware static analysis")
    ap.add_argument("roots", nargs="*",
                    default=[str(repo_root / "distributedmnist_tpu"),
                             str(repo_root / "tests")],
                    help="files/directories to analyze (default: the "
                         "package + tests)")
    ap.add_argument("--format", choices=("text", "json"),
                    default="text")
    ap.add_argument("--output", default=None,
                    help="also write the findings JSON here (the CI "
                         "artifact)")
    ap.add_argument("--baseline", default=None,
                    help="baseline file (default: the checked-in "
                         "analysis/baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignoring the baseline")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write current findings as the baseline "
                         "skeleton to stdout and exit 0")
    ap.add_argument("--checkers", default=None,
                    help="comma-separated subset (default: all)")
    args = ap.parse_args(argv)

    # resolve checker names BEFORE any analysis work: a typo'd
    # --checkers must fail as a usage error (argparse's own exit
    # path), not after parsing the whole tree
    names = (set(args.checkers.split(",")) if args.checkers else None)
    if names is not None:
        from . import (config_check, durability_check,  # noqa: F401
                       jax_check, net_check, paged_check,
                       schema_check, threads_check)
        unknown = names - set(CHECKERS)
        if unknown:
            ap.error(f"unknown checker(s): "
                     f"{', '.join(sorted(unknown))}; available: "
                     f"{', '.join(sorted(CHECKERS))}")
    sources = iter_sources(args.roots, repo_root=repo_root)
    findings = run_checkers(sources, names)

    if args.write_baseline:
        sys.stdout.write(baseline_to_json(findings))
        return 0

    baseline = ({} if args.no_baseline
                else load_baseline(args.baseline))
    # staleness is only judgeable for entries this run could have
    # reproduced: the checker must have run AND the file must be among
    # the analyzed sources — a targeted invocation (subset roots or
    # --checkers) must not read untested suppressions as stale
    # run_checkers emits "parse" findings unconditionally, so their
    # baseline entries are always judgeable for staleness
    ran = (names or set(CHECKERS)) | {"parse"}
    analyzed = {s.path for s in sources}
    new = [f for f in findings if f.key not in baseline]
    fired = {f.key for f in findings}
    stale = sorted(
        k for k in baseline
        if k not in fired
        and k.split(":", 2)[0] in ran
        and (k.split(":", 2) + [""])[1] in analyzed)

    report = {
        "checkers": sorted(ran),
        "files_analyzed": len(sources),
        "findings": [f.to_dict() for f in findings],
        "new": [f.to_dict() for f in new],
        "baselined": sorted(fired & set(baseline)),
        "stale_baseline": stale,
        "ok": not new and not stale,
    }
    if args.output:
        Path(args.output).write_text(json.dumps(report, indent=2))
    if args.format == "json":
        print(json.dumps(report, indent=2))
    else:
        for f in findings:
            mark = " (baselined)" if f.key in baseline else ""
            print(f"{f.path}:{f.line}: [{f.checker}]{mark} {f.message}")
        for k in stale:
            print(f"STALE baseline entry (no longer fires): {k}")
        print(f"graftcheck: {len(findings)} finding(s), "
              f"{len(new)} new, "
              f"{len(fired & set(baseline))} baselined, "
              f"{len(stale)} stale baseline entr(ies) "
              f"over {len(sources)} files")
    if new:
        return 1
    if stale:
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
