"""graftcheck ``durability``: the write-path provenance lint.

ISSUE 20's crash-consistency story (tests/test_crash_consistency.py,
invariant 14) is only as strong as its coverage: the storage shim
(train/storage.py) is where fsync policy is applied, disk faults are
injected, and torn/ENOSPC degradation is journaled — so a durable
artifact written around the shim is an artifact the chaos campaign
can never fault and the durability knob can never fsync. This pass
flags raw write APIs that bypass it:

1. In the packages that OWN durable training artifacts (``train/``,
   ``quant/``), every raw ``open(.., "w"/"a"/"x")``, ``Path.
   write_bytes`` / ``write_text``, and ``os.replace`` / ``os.rename``
   outside the shim itself is a finding — there is no legitimate
   direct write there; checkpoints, manifests, digest sidecars, and
   pointers all route through ``storage.write_bytes`` /
   ``write_text`` / ``replace``.
2. Everywhere else, the same raw calls are findings only when the
   path expression textually names a durable artifact (``ckpt``,
   ``checkpoint``, ``manifest``, a digest/journal suffix) — a
   supervisor writing ``results.json`` is fine; a supervisor writing
   ``checkpoint.json`` behind the shim's back is the lint's point.

Textual path evidence is an under-approximation by design: a write to
an alias the AST cannot name slips through. The lint's job is the
honest-mistake case — a new call site pasted from pre-shim code — not
adversarial dataflow; that belongs to review.

Journal APPENDS are deliberately out of scope: ``core/log.py``'s
JsonlSink is the one append path and already routes its fsync
decision through ``storage.journal_sync_enabled()``.
"""

from __future__ import annotations

import ast

from .core import Finding, Source, add_parents, enclosing, make_key, register

# the shim itself — the one module allowed to touch raw write APIs
_SHIM = "distributedmnist_tpu/train/storage.py"
# packages where EVERY raw write is a bypass, path spelling aside
_STRICT_PREFIXES = ("distributedmnist_tpu/train/",
                    "distributedmnist_tpu/quant/")
# spellings that mark a path expression as a durable artifact
_DURABLE_MARKERS = ("ckpt", "checkpoint", "manifest", "sha256",
                    "msgpack", "sidecar", "recovery_journal",
                    "storage_faults")


def _callee(call: ast.Call) -> tuple[str | None, str | None]:
    """(receiver module/name, attribute) for ``x.y(...)``; (None, name)
    for a bare ``name(...)`` call."""
    f = call.func
    if isinstance(f, ast.Name):
        return None, f.id
    if isinstance(f, ast.Attribute):
        base = f.value
        return (base.id if isinstance(base, ast.Name) else None), f.attr
    return None, None


def _open_mode(call: ast.Call) -> str | None:
    """The literal mode string of an ``open`` call, '' when defaulted
    (→ read), None when non-literal (undecidable — skip)."""
    mode: ast.AST | None = call.args[1] if len(call.args) > 1 else None
    for kw in call.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if mode is None:
        return ""
    if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
        return mode.value
    return None


def _path_expr(call: ast.Call) -> str:
    """Unparsed source of the call's path operand — the first argument
    for ``open``/``os.replace``, the receiver for Path methods."""
    if isinstance(call.func, ast.Attribute) and call.func.attr in (
            "write_bytes", "write_text"):
        return ast.unparse(call.func.value)
    return ast.unparse(call.args[0]) if call.args else ""


def _fn_name(node: ast.AST) -> str:
    fn = enclosing(node, ast.FunctionDef, ast.AsyncFunctionDef)
    return fn.name if fn is not None else "<module>"


@register("durability")
def check(sources: list[Source]) -> list[Finding]:
    out: list[Finding] = []
    for src in sources:
        if src.is_test or src.path == _SHIM:
            continue
        if not src.path.startswith("distributedmnist_tpu/"):
            continue
        add_parents(src.tree)
        strict = src.path.startswith(_STRICT_PREFIXES)
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            base, name = _callee(node)
            if name == "open" and base is None:
                mode = _open_mode(node)
                if mode is None or not any(c in mode for c in "wax"):
                    continue
                what = f'open(mode="{mode}")'
            elif name in ("write_bytes", "write_text") and isinstance(
                    node.func, ast.Attribute):
                if base == "storage":
                    continue  # the shim's own API — the routed path
                what = f"{name}()"
            elif base == "os" and name in ("replace", "rename"):
                what = f"os.{name}()"
            else:
                continue
            path_src = _path_expr(node)
            lowered = path_src.lower()
            if not strict and not any(m in lowered
                                      for m in _DURABLE_MARKERS):
                continue
            fn = _fn_name(node)
            out.append(Finding(
                "durability", src.path, node.lineno,
                make_key("durability", src.path, f"{fn}.{what}"),
                f"{what} on {path_src or '<unknown path>'} in {fn}() "
                "bypasses the storage shim (train/storage.py) — this "
                "write gets no fsync policy, no fault injection, and "
                "no torn/ENOSPC degradation journaling; route it "
                "through storage.write_bytes/write_text/replace"))
    return out
