"""graftcheck ``jax``: the JAX-hazard lint.

Three hazards the type system can't see and the test suite only hits
when the wrong interleaving/shape shows up:

* **donated-buffer reuse** — after calling a jitted function built
  with ``donate_argnums``/``donate_argnames``, the donated operand's
  buffer is dead; reading the same variable afterwards (or around a
  loop without rebinding it) is use-after-donate, which jax surfaces
  as a runtime error only on backends that actually alias.
* **host sync in hot loops** — ``.item()`` (and ``float()``/``int()``
  over values produced by a jitted call in the same loop) inside a
  ``for``/``while`` in step/batch/loop/run-shaped functions blocks the
  dispatch queue every iteration — the async-dispatch overlap the
  step loop is built around silently degrades to lockstep.
* **python-scalar jit signature** — passing an enclosing loop's
  induction variable positionally to a jitted callable with no
  ``static_argnums``/``static_argnames`` recompiles per value (a
  Python int is a new constant each trace).

Jitted callables are resolved module-locally: names (or ``self.x``
attributes) bound from a ``jit(...)``/``jax.jit(...)`` call.  Cross-
module donation tracking is out of scope — the fixture tests pin the
in-module contract.
"""

from __future__ import annotations

import ast
import dataclasses
import re

from .core import (Finding, Source, add_parents, enclosing, make_key,
                   register)

_HOT_NAME = re.compile(r"step|batch|loop|run", re.IGNORECASE)


@dataclasses.dataclass
class Jitted:
    name: str            # bound name ("f" or "self.f" normalized to f)
    donating: bool
    has_static: bool
    # positional indices donate_argnums names, when statically
    # readable; None with donating=True means "unknown positions" (a
    # computed argnums expression, or donate_argnames whose positions
    # the AST can't map without the signature) — all args assumed
    donate_positions: tuple[int, ...] | None = None


def _callee_name(call: ast.Call) -> str | None:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


def _bound_name(target: ast.expr) -> str | None:
    if isinstance(target, ast.Name):
        return target.id
    if isinstance(target, ast.Attribute):
        return target.attr  # self._step → "_step"
    return None


def _collect_jitted(src: Source) -> dict[str, Jitted]:
    out: dict[str, Jitted] = {}
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.Assign):
            continue
        call = node.value
        if not (isinstance(call, ast.Call)
                and _callee_name(call) == "jit"):
            continue
        kwargs = {kw.arg: kw.value for kw in call.keywords if kw.arg}
        donating = bool(set(kwargs) & {"donate_argnums",
                                       "donate_argnames"})
        has_static = any(k.startswith("static_arg") for k in kwargs)
        positions: tuple[int, ...] | None = None
        argnums = kwargs.get("donate_argnums")
        if argnums is not None and "donate_argnames" not in kwargs:
            if (isinstance(argnums, ast.Constant)
                    and isinstance(argnums.value, int)):
                positions = (argnums.value,)
            elif (isinstance(argnums, (ast.Tuple, ast.List))
                  and all(isinstance(e, ast.Constant)
                          and isinstance(e.value, int)
                          for e in argnums.elts)):
                positions = tuple(e.value for e in argnums.elts)
        for t in node.targets:
            name = _bound_name(t)
            if name:
                out[name] = Jitted(name, donating, has_static,
                                   positions)
    return out


def _donated_args(call: ast.Call, j: Jitted) -> list[ast.expr]:
    if j.donate_positions is None:
        return list(call.args)
    return [a for i, a in enumerate(call.args)
            if i in j.donate_positions]


def _call_of(node: ast.expr, jitted: dict[str, Jitted]
             ) -> Jitted | None:
    if not isinstance(node, ast.Call):
        return None
    name = _callee_name(node)
    return jitted.get(name or "")


def _names_read(node: ast.AST) -> set[str]:
    return {n.id for n in ast.walk(node)
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)}


def _names_bound(node: ast.AST) -> set[str]:
    out: set[str] = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store):
            out.add(n.id)
    return out


def _check_donation(src: Source, fn: ast.FunctionDef,
                    jitted: dict[str, Jitted],
                    out: list[Finding]) -> None:
    """Linear scan of each statement list: after a donating call whose
    positional args are plain names, those names are dead until
    rebound."""

    def scan(body: list[ast.stmt]) -> None:
        dead: dict[str, int] = {}  # name -> donate line
        for stmt in body:
            # reads in this statement of names donated by a PRIOR
            # sibling statement
            reads = _names_read(stmt)
            for name in sorted(reads & set(dead)):
                out.append(Finding(
                    "jax", src.path, stmt.lineno,
                    make_key("jax", src.path,
                             f"donate.{fn.name}.{name}"),
                    f"{name!r} is read at line {stmt.lineno} after "
                    f"being donated to a jitted call at line "
                    f"{dead[name]} in {fn.name}() — the buffer is "
                    "dead (use-after-donate)"))
                del dead[name]
            if isinstance(stmt, (ast.Assign, ast.AnnAssign,
                                 ast.AugAssign, ast.Expr)):
                # only simple statements propagate donations to their
                # siblings: a donation inside an If branch that
                # returns, or in a Return itself, never flows here
                for node in ast.walk(stmt):
                    j = _call_of(node, jitted)
                    if j is not None and j.donating:
                        for arg in _donated_args(node, j):
                            if isinstance(arg, ast.Name):
                                dead.setdefault(arg.id, node.lineno)
            elif isinstance(stmt, ast.If):
                scan(stmt.body)
                scan(stmt.orelse)
            elif isinstance(stmt, ast.With):
                scan(stmt.body)
            elif isinstance(stmt, ast.Try):
                for b in (stmt.body, stmt.orelse, stmt.finalbody):
                    scan(b)
                for h in stmt.handlers:
                    scan(h.body)
            elif isinstance(stmt, (ast.For, ast.While)):
                # a donation inside the loop must rebind its operand
                # within the same iteration, else the next iteration
                # reads a dead buffer
                bound = _names_bound(stmt)
                for node in ast.walk(stmt):
                    j = _call_of(node, jitted)
                    if j is None or not j.donating:
                        continue
                    for arg in _donated_args(node, j):
                        if (isinstance(arg, ast.Name)
                                and arg.id not in bound):
                            out.append(Finding(
                                "jax", src.path, node.lineno,
                                make_key("jax", src.path,
                                         f"donate-loop.{fn.name}."
                                         f"{arg.id}"),
                                f"{arg.id!r} is donated to a jitted "
                                f"call inside a loop in {fn.name}() "
                                "but never rebound in the loop body — "
                                "the next iteration reads a dead "
                                "buffer"))
            # rebinds revive the name AFTER same-statement donations:
            # `state = f(state)` donates the old buffer, then binds
            # the name to the fresh result
            for name in _names_bound(stmt) & set(dead):
                del dead[name]

    scan(fn.body)


def _check_host_sync(src: Source, fn: ast.FunctionDef,
                     jitted: dict[str, Jitted],
                     out: list[Finding]) -> None:
    if not _HOT_NAME.search(fn.name):
        return
    for loop in ast.walk(fn):
        if not isinstance(loop, (ast.For, ast.While)):
            continue
        # names assigned from jitted calls inside this loop: their
        # values live on device
        device_names: set[str] = set()
        for node in ast.walk(loop):
            if (isinstance(node, ast.Assign)
                    and _call_of(node.value, jitted) is not None):
                device_names |= _names_bound(node)
        for node in ast.walk(loop):
            if not isinstance(node, ast.Call):
                continue
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "item"):
                out.append(Finding(
                    "jax", src.path, node.lineno,
                    make_key("jax", src.path,
                             f"host-sync.{fn.name}.item"),
                    f".item() inside the loop in {fn.name}() blocks "
                    "on device completion every iteration — hoist the "
                    "fetch to the flush cadence"))
            elif (isinstance(node.func, ast.Name)
                  and node.func.id in ("float", "int")
                  and node.args
                  and _names_read(node.args[0]) & device_names):
                out.append(Finding(
                    "jax", src.path, node.lineno,
                    make_key("jax", src.path,
                             f"host-sync.{fn.name}."
                             f"{node.func.id}"),
                    f"{node.func.id}() over a jitted-call result "
                    f"inside the loop in {fn.name}() forces a device "
                    "sync every iteration"))


def _check_scalar_signature(src: Source, fn: ast.FunctionDef,
                            jitted: dict[str, Jitted],
                            out: list[Finding]) -> None:
    for node in ast.walk(fn):
        j = _call_of(node, jitted)
        if j is None or j.has_static:
            continue
        loop = enclosing(node, ast.For)
        if loop is None or not isinstance(loop.target, ast.Name):
            continue
        # only range()-style loops: their induction variable is a
        # Python scalar (a new traced constant per value); iterating
        # device arrays/batches is not this hazard
        if not (isinstance(loop.iter, ast.Call)
                and _callee_name(loop.iter) in ("range", "enumerate")):
            continue
        loop_var = loop.target.id
        for arg in node.args:
            if isinstance(arg, ast.Name) and arg.id == loop_var:
                out.append(Finding(
                    "jax", src.path, node.lineno,
                    make_key("jax", src.path,
                             f"scalar-jit.{fn.name}.{arg.id}"),
                    f"loop variable {arg.id!r} is passed positionally "
                    f"to jitted {j.name!r} in {fn.name}() with no "
                    "static_argnums — every value traces a new "
                    "program (recompile per iteration)"))


@register("jax")
def check(sources: list[Source]) -> list[Finding]:
    out: list[Finding] = []
    for src in sources:
        if src.is_test:
            continue
        add_parents(src.tree)
        jitted = _collect_jitted(src)
        if not jitted:
            continue
        for node in ast.walk(src.tree):
            if isinstance(node, ast.FunctionDef):
                _check_donation(src, node, jitted, out)
                _check_host_sync(src, node, jitted, out)
                _check_scalar_signature(src, node, jitted, out)
    return out
