"""graftcheck plumbing: findings, source walking, baseline handling.

Checker modules register themselves in :data:`CHECKERS`; each exposes
``check(sources) -> list[Finding]`` over the parsed source set.  A
finding's ``key`` is deliberately line-number-free so the checked-in
baseline survives unrelated edits to the same file.
"""

from __future__ import annotations

import ast
import dataclasses
import json
from pathlib import Path
from typing import Any, Callable, Iterable


@dataclasses.dataclass
class Source:
    """One parsed module: repo-relative path + AST + raw text."""

    path: str            # repo-relative, forward slashes
    tree: ast.Module
    text: str
    # set when the file did not parse: the tree is an empty sentinel
    # and run_checkers reports the error as a finding — an unparseable
    # file must never read as a clean one
    parse_error: str | None = None

    @property
    def is_test(self) -> bool:
        return self.path.startswith("tests/")


@dataclasses.dataclass(frozen=True)
class Finding:
    checker: str         # schema | config | threads | jax
    path: str            # repo-relative file
    line: int            # 1-indexed (display only; not part of the key)
    key: str             # stable identity: checker:path:subject
    message: str

    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)


def make_key(checker: str, path: str, subject: str) -> str:
    return f"{checker}:{path}:{subject}"


# ---------------------------------------------------------------------------
# source loading
# ---------------------------------------------------------------------------

def iter_sources(roots: Iterable[str | Path],
                 repo_root: str | Path | None = None) -> list[Source]:
    """Parse every ``*.py`` under ``roots`` (files or directories).
    Paths in findings are relative to ``repo_root`` (default: the
    repository checkout containing this package)."""
    if repo_root is None:
        repo_root = Path(__file__).resolve().parents[2]
    repo_root = Path(repo_root).resolve()
    out: list[Source] = []
    for root in roots:
        root = Path(root).resolve()
        files = [root] if root.is_file() else sorted(root.rglob("*.py"))
        for f in files:
            try:
                rel = f.relative_to(repo_root).as_posix()
            except ValueError:
                rel = f.as_posix()
            text = f.read_text()
            err: str | None = None
            try:
                tree = ast.parse(text, filename=str(f))
            except SyntaxError as e:
                tree = ast.Module(body=[], type_ignores=[])
                err = f"{e.msg} (line {e.lineno})"
            out.append(Source(path=rel, tree=tree, text=text,
                              parse_error=err))
    return out


def add_parents(tree: ast.AST) -> None:
    """Annotate every node with ``.parent`` (checkers walk upward for
    lock guards / enclosing loops)."""
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child.parent = node  # type: ignore[attr-defined]


def enclosing(node: ast.AST, *types: type) -> ast.AST | None:
    """Nearest ancestor of one of ``types`` (requires add_parents)."""
    cur = getattr(node, "parent", None)
    while cur is not None:
        if isinstance(cur, types):
            return cur
        cur = getattr(cur, "parent", None)
    return None


# ---------------------------------------------------------------------------
# baseline (accepted findings, each with a justification)
# ---------------------------------------------------------------------------

def load_baseline(path: str | Path | None = None) -> dict[str, str]:
    """{finding key: justification}.  The default baseline ships with
    the package (``analysis/baseline.json``)."""
    if path is None:
        path = Path(__file__).with_name("baseline.json")
    path = Path(path)
    if not path.exists():
        return {}
    data = json.loads(path.read_text())
    out: dict[str, str] = {}
    for entry in data.get("accepted", []):
        out[entry["key"]] = entry.get("justification", "")
    return out


def baseline_to_json(findings: list[Finding],
                     justification: str = "TODO: justify") -> str:
    """Serialize current findings as a baseline skeleton (the
    ``--write-baseline`` helper output)."""
    return json.dumps(
        {"accepted": [{"key": f.key, "justification": justification,
                       "message": f.message}
                      for f in sorted(findings, key=lambda f: f.key)]},
        indent=2) + "\n"


# ---------------------------------------------------------------------------
# checker registry
# ---------------------------------------------------------------------------

CHECKERS: dict[str, Callable[[list[Source]], list[Finding]]] = {}


def register(name: str):
    def deco(fn):
        CHECKERS[name] = fn
        return fn
    return deco


def run_checkers(sources: list[Source],
                 names: Iterable[str] | None = None) -> list[Finding]:
    # import for side effect: each checker module registers itself
    from . import (config_check, durability_check, jax_check,  # noqa: F401
                   net_check, paged_check, schema_check, threads_check)
    findings: list[Finding] = []
    # an unparseable file yields an empty AST — every checker would
    # silently report it clean (and its dropped reads could even fake
    # dead-knob findings elsewhere), so the parse failure IS a finding
    for src in sources:
        if src.parse_error is not None:
            findings.append(Finding(
                "parse", src.path, 1,
                make_key("parse", src.path, "syntax-error"),
                f"file does not parse ({src.parse_error}) — no checker "
                "can see into it"))
    for name, fn in sorted(CHECKERS.items()):
        if names is not None and name not in names:
            continue
        findings += fn(sources)
    return sorted(findings, key=lambda f: (f.path, f.line, f.key))
