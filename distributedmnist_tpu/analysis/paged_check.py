"""graftcheck ``paged``: the dense-materialization lint for the
decode hot path.

The paged KV cache exists so the per-step decode cost scales with the
tokens a sequence ACTUALLY holds, not with ``max_blocks_per_seq``.
Two regressions keep trying to sneak that guarantee away, both
invisible to the type system and to parity tests (the numerics stay
bit-identical — only the cost model breaks):

* **dense gather in a hot function** — calling ``gather_dense`` (the
  host-side test oracle) or ``take_along_axis``-style whole-table
  gathers inside a step/loop/batch/run-shaped function in
  ``servesvc/`` re-materializes ``[slots, max_context]`` K/V every
  iteration.  The paged kernel walks block tables in-kernel; the
  oracle is for tests and the dense *kernel* arm lives in
  ``models/transformer.py``, outside this lint's scope on purpose.
* **per-iteration table rebuild** — constructing the block-table
  array (``zeros``/``asarray``/``array`` over a ``table``-named
  value) inside a loop in a hot function re-uploads the host table
  every step.  The replica caches tables per (version, epoch) and
  re-uploads only when slot composition changes — a rebuild inside
  the loop silently undoes that (the PR-17 satellite fix this lint
  pins).

Scope: ``distributedmnist_tpu/servesvc/`` only, tests exempt.  The
expected steady state is ZERO findings — anything this checker emits
is a fresh regression, not baseline material.
"""

from __future__ import annotations

import ast
import re

from .core import (Finding, Source, add_parents, enclosing, make_key,
                   register)

_HOT_NAME = re.compile(r"step|batch|loop|run", re.IGNORECASE)
_TABLE_NAME = re.compile(r"table", re.IGNORECASE)
_DENSE_GATHERS = ("gather_dense", "take_along_axis")
_BUILDERS = ("zeros", "asarray", "array", "stack")


def _callee_name(call: ast.Call) -> str | None:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


def _reads_table_name(node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Name) and _TABLE_NAME.search(n.id):
            return True
        if isinstance(n, ast.Attribute) and _TABLE_NAME.search(n.attr):
            return True
    return False


def _targets_table_name(call: ast.Call) -> bool:
    """The rebuilt value is table-shaped when the call's result is
    BOUND to a table-named target (``tables = np.zeros(...)``) — the
    arguments are just dims and carry no name signal."""
    stmt = enclosing(call, ast.Assign, ast.AnnAssign, ast.AugAssign)
    if stmt is None:
        return False
    targets = (stmt.targets if isinstance(stmt, ast.Assign)
               else [stmt.target])
    return any(_reads_table_name(t) for t in targets)


def _check_fn(src: Source, fn: ast.FunctionDef,
              out: list[Finding]) -> None:
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        name = _callee_name(node)
        if name in _DENSE_GATHERS:
            out.append(Finding(
                "paged", src.path, node.lineno,
                make_key("paged", src.path,
                         f"dense-gather.{fn.name}.{name}"),
                f"{name}() inside hot function {fn.name}() "
                "re-materializes the dense [slots, max_context] view "
                "every step — the paged kernel walks block tables "
                "in-kernel; the dense gather is a test oracle, not a "
                "serving path"))
        elif (name in _BUILDERS
              and enclosing(node, ast.For, ast.While) is not None
              and (_reads_table_name(node)
                   or _targets_table_name(node))):
            out.append(Finding(
                "paged", src.path, node.lineno,
                make_key("paged", src.path,
                         f"table-rebuild.{fn.name}.{name}"),
                f"block-table {name}() inside a loop in hot function "
                f"{fn.name}() rebuilds + re-uploads the host table "
                "every iteration — cache per (version, epoch) and "
                "re-upload only when slot composition changes"))


@register("paged")
def check(sources: list[Source]) -> list[Finding]:
    out: list[Finding] = []
    for src in sources:
        if src.is_test:
            continue
        if "/servesvc/" not in f"/{src.path}":
            continue
        add_parents(src.tree)
        for node in ast.walk(src.tree):
            if (isinstance(node, ast.FunctionDef)
                    and _HOT_NAME.search(node.name)):
                _check_fn(src, node, out)
    return out
