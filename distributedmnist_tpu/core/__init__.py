from .config import (ConfigError, DataConfig, EvalConfig, ExperimentConfig,
                     MeshConfig, ModelConfig, OptimConfig, SyncConfig,
                     TrainConfig, parse_cli_overrides)
from .mesh import (Topology, ensure_mesh, initialize_distributed,
                   make_seq_topology, make_topology, simulate_devices)
from . import log, prng

__all__ = [
    "ConfigError", "DataConfig", "EvalConfig", "ExperimentConfig",
    "MeshConfig", "ModelConfig", "OptimConfig", "SyncConfig", "TrainConfig",
    "parse_cli_overrides", "Topology", "ensure_mesh",
    "initialize_distributed", "make_seq_topology", "make_topology",
    "simulate_devices", "log", "prng",
]
