"""Device-mesh / topology discovery.

Replaces the reference's cluster plumbing — ``tf.train.ClusterSpec`` +
per-process ``tf.train.Server`` with explicit ps_hosts/worker_hosts
strings (reference: src/mnist_distributed_train.py:25-31,
src/distributed_train.py:41-48) and the EC2 role-assignment machinery
(tools/tf_ec2.py:462-491) — with TPU-slice discovery: every host runs
the same SPMD program, devices come from ``jax.devices()``, and the
"cluster spec" is just a `jax.sharding.Mesh`.

There is no parameter-server role: parameters are replicated and
gradient aggregation is a compiler-scheduled psum over ICI (SURVEY §5.8).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from .config import MeshConfig

P = PartitionSpec


# True when this jax needed the check_rep=False shard_map shim below.
# Two consequences downstream (the gold-parity tests key off this
# flag): cross-shard reductions may REASSOCIATE relative to a dense
# single-device reference (float32 noise at the ~1e-4 scale), and
# jax.lax.pcast degrades to an identity whose transpose psum is LOST
# from backward passes — so sharded-vs-dense parameter-update parity
# is structurally unachievable, while forward/loss parity still holds.
CHECK_REP_SHIM = False

if not hasattr(jax, "shard_map"):
    # jax < 0.4.38 ships shard_map only under jax.experimental; alias it
    # so the package (and tests) use one spelling on every jax this repo
    # runs against. The call shape (f, mesh=, in_specs=, out_specs=) is
    # identical. check_rep off: this jax predates the vma/pcast marker
    # API the kernels use to satisfy the replication checker, so the
    # checker cannot be satisfied — the markers become no-ops below.
    CHECK_REP_SHIM = True
    from jax.experimental.shard_map import shard_map as _experimental_sm

    def _shard_map_compat(f, *, mesh, in_specs, out_specs):
        return _experimental_sm(f, mesh=mesh, in_specs=in_specs,
                                out_specs=out_specs, check_rep=False)

    jax.shard_map = _shard_map_compat
if not hasattr(jax.lax, "pcast"):
    # the replication→varying marker is purely a check_vma annotation;
    # with the checker off (above) the identity is semantically exact
    jax.lax.pcast = lambda x, axes, *, to="varying": x
if not hasattr(jax.lax, "axis_size"):
    # psum of the literal 1 constant-folds to the concrete axis size on
    # every jax this repo supports — the pre-0.4.38 spelling
    jax.lax.axis_size = lambda name: jax.lax.psum(1, name)
if not hasattr(jax, "typeof"):
    # jax.typeof is get_aval with vma metadata; callers here only read
    # `.vma` through getattr(..., frozenset()) so the plain aval works
    jax.typeof = lambda x: jax.core.get_aval(x)


def shard_map(f, *, mesh, in_specs, out_specs):
    """Version-portable ``shard_map`` (see the alias install above)."""
    return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs)


def gather_chunks_replicated(chunk, axis_name: str, full_len: int,
                             offset) -> "jax.Array":
    """Reassemble per-replica 1-D ``chunk``s (this replica's slice
    starting at ``offset`` of a ``full_len`` vector) into the FULL
    vector on every replica — the allgather leg of the ZeRO-1 weight
    update (parallel/api.py).

    Under the jax-0.4.37 check_rep=False shim this is a plain tiled
    ``all_gather``. On a replication-checked jax an all_gather result
    stays marked device-varying and could not leave shard_map under a
    P() out_spec (the same constraint behind parallel/api.py's
    ``_gather_replicated`` one-hot psum for the [n] metrics vector) —
    there, each replica scatters its chunk into a zeros vector and one
    psum reassembles a statically-replicated result; communication
    degrades from an allgather to an all-reduce, correctness and the
    sharded-optimizer-state memory win are unchanged."""
    if CHECK_REP_SHIM:
        return jax.lax.all_gather(chunk, axis_name, tiled=True)
    import jax.numpy as jnp
    buf = jnp.zeros((full_len,), chunk.dtype)
    buf = jax.lax.dynamic_update_slice(buf, chunk, (offset,))
    return jax.lax.psum(buf, axis_name)


def gather_bucket_replicated(chunk, axis_name: str, n: int) -> "jax.Array":
    """Per-BUCKET variant of :func:`gather_chunks_replicated`: stack
    each replica's 1-D concatenated bucket chunk (every sharded leaf's
    ``[chunk]`` slice for one comm bucket, concatenated) into the
    replicated ``[n, C]`` matrix whose row ``r`` is replica ``r``'s
    contribution — ONE collective reassembles a whole bucket's params
    instead of one per leaf (the bucketed ZeRO-1 allgather leg and the
    resident-sharded just-in-time weight gather, parallel/api.py).
    Column slices of the result recover each leaf's ``[n, chunk]``
    view, which flattens row-major to exactly its padded ``[pad]``
    layout.

    Same shim split as the per-leaf helper: a plain ``all_gather``
    under the jax-0.4.37 check_rep=False shim; on a replication-checked
    jax each replica scatters its row into a zeros matrix and one psum
    produces a statically-replicated result."""
    if CHECK_REP_SHIM:
        return jax.lax.all_gather(chunk, axis_name)  # [n, C]
    import jax.numpy as jnp
    buf = jnp.zeros((n,) + tuple(chunk.shape), chunk.dtype)
    buf = jax.lax.dynamic_update_slice(
        buf, chunk[None], (jax.lax.axis_index(axis_name), 0))
    return jax.lax.psum(buf, axis_name)


def initialize_distributed() -> None:
    """Multi-host bring-up (≙ tf.train.Server + startup barrier,
    src/mnist_distributed_train.py:27-35, src/timeout_manager.py:198-211).

    On a real multi-host TPU slice, `jax.distributed.initialize()`
    discovers the coordinator (from TPU pod metadata, or the
    JAX_COORDINATOR_ADDRESS / slurm env). MUST be called before
    anything initializes the XLA backend, so this function touches no
    other jax APIs first. A no-op when already initialized or when
    nothing indicates a multi-host environment. Safe to call twice.
    """
    from jax._src import distributed as _dist
    if _dist.global_state.client is not None:
        return  # already initialized
    hostnames = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    explicit = os.environ.get("JAX_COORDINATOR_ADDRESS")
    multi_host_hint = (
        explicit
        or os.environ.get("MEGASCALE_COORDINATOR_ADDRESS")
        or len([h for h in hostnames.split(",") if h]) > 1)
    if not multi_host_hint:
        return  # single-process run (one chip / CPU simulation)
    if "cpu" in os.environ.get("JAX_PLATFORMS", ""):
        # multi-process CPU needs the gloo collectives backend; on jax
        # < 0.5 the default ("none") makes every collective raise
        # "Multiprocess computations aren't implemented on the CPU
        # backend". Newer jax defaults to gloo and may drop the knob.
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except AttributeError:
            pass
    if explicit and ("JAX_NUM_PROCESSES" in os.environ
                     or "JAX_PROCESS_ID" in os.environ):
        # Generic-cluster bring-up (≙ the reference's explicit
        # ps_hosts/worker_hosts + task_index flags,
        # src/mnist_distributed_train.py:25-31): jax's auto-detection
        # only covers TPU-metadata / SLURM / MPI environments, so a
        # plain N-process launch names its coordinator explicitly.
        missing = [v for v in ("JAX_NUM_PROCESSES", "JAX_PROCESS_ID")
                   if v not in os.environ]
        if missing:
            raise RuntimeError(
                "explicit multi-process launch needs JAX_COORDINATOR_ADDRESS, "
                f"JAX_NUM_PROCESSES and JAX_PROCESS_ID; missing: {missing}")
        jax.distributed.initialize(
            coordinator_address=explicit,
            num_processes=int(os.environ["JAX_NUM_PROCESSES"]),
            process_id=int(os.environ["JAX_PROCESS_ID"]))
    else:
        jax.distributed.initialize()


# Env values as they were BEFORE the first simulate_devices call (None
# = the variable was unset). strip_forced_platform_env restores exactly
# this snapshot, so operator-set values survive untouched.
_env_before_force: dict | None = None


def simulate_devices(n: int) -> None:
    """Force an ``n``-virtual-CPU-device platform. MUST run before the
    XLA backend initializes — call from conftest/env setup.

    This is the framework's answer to the reference's total lack of a
    mock distributed backend (SURVEY §4): N-device SPMD semantics are
    testable on one CPU host. The single point of truth for this idiom
    (conftest and __graft_entry__ both route through it).

    Note: some environments (this image's axon boot hook) re-register
    an accelerator backend and override the JAX_PLATFORMS env var, so
    the platform is forced via jax.config, not just env.
    """
    import re
    global _env_before_force
    if _env_before_force is None:
        _env_before_force = {
            "XLA_FLAGS": os.environ.get("XLA_FLAGS"),
            "JAX_PLATFORMS": os.environ.get("JAX_PLATFORMS"),
        }
    flags = os.environ.get("XLA_FLAGS", "")
    flag = f"--xla_force_host_platform_device_count={n}"
    if "xla_force_host_platform_device_count" in flags:
        flags = re.sub(r"--xla_force_host_platform_device_count=\d+", flag, flags)
    else:
        flags = (flags + " " + flag).strip()
    os.environ["XLA_FLAGS"] = flags
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    jax.config.update("jax_platforms", "cpu")
    # XLA_FLAGS is parsed once per process; if a backend already
    # initialized (axon registers one eagerly) the flag above is never
    # re-read. jax_num_cpu_devices works post-hoc — but only after the
    # stale backend is torn down, so callers in that state must
    # clear_backends() BEFORE calling here (see __graft_entry__).
    try:
        jax.config.update("jax_num_cpu_devices", n)
    except RuntimeError:
        pass  # backend already initialized; XLA_FLAGS path applies
    except AttributeError:
        pass  # jax < 0.4.38 has no jax_num_cpu_devices; XLA_FLAGS applies


def strip_forced_platform_env(env: dict) -> dict:
    """Undo :func:`simulate_devices`' env mutations in a CHILD's env so
    a subprocess boots the true ambient backend (the campaign's lean
    single-device evaluator). Restores the exact pre-force snapshot —
    values the operator set themselves (e.g. a deliberate
    JAX_PLATFORMS=cpu pin) are preserved, and if simulate_devices never
    ran in this process the env passes through unchanged. The one
    exception: a ``--xla_force_host_platform_device_count`` flag is
    stripped even if it predates the force — an evaluator child on a
    forced multi-device mesh would recreate exactly the trainer
    contention this function exists to avoid. Kept here, next to the
    code that writes the flag, so the two can't drift."""
    import re
    env = dict(env)
    if _env_before_force is not None:
        for key, orig in _env_before_force.items():
            if orig is None:
                env.pop(key, None)
            else:
                env[key] = orig
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   env.get("XLA_FLAGS", "")).strip()
    if flags:
        env["XLA_FLAGS"] = flags
    else:
        env.pop("XLA_FLAGS", None)
    return env


_ambient_mesh: tuple[int, str] | None = None  # (device_count, platform)


def ensure_mesh(simulate: int) -> None:
    """Make the process's device set match what a config expects.

    ``simulate > 0`` forces that many virtual CPU devices (tearing down
    a previously initialized backend if the count differs);
    ``simulate == 0`` means "the ambient devices" — captured at this
    helper's first call — and RESTORES them if a previous config left a
    different simulated platform behind.

    This is the guard that makes mixed sweeps safe: without it, a
    ``launch sweep`` over a directory where one config forces a
    50-device mesh (configs/quorum50_*) would silently run every
    subsequent ambient-mesh config 50-wide under its 8-wide name.
    Restoration is only possible when the ambient platform was CPU
    (re-forcing a torn-down accelerator backend is not supported) —
    otherwise this raises rather than continuing on the wrong mesh.
    """
    global _ambient_mesh
    if _ambient_mesh is None:
        _ambient_mesh = (len(jax.devices()), jax.default_backend())
    want, platform = ((simulate, "cpu") if simulate > 0 else _ambient_mesh)
    if len(jax.devices()) == want and jax.default_backend() == platform:
        return
    if platform != "cpu":
        raise RuntimeError(
            f"cannot restore the ambient {platform} backend after a "
            "simulated-mesh config ran in this process; run "
            "simulated-mesh configs (mesh.simulate_devices > 0) in their "
            "own process")
    import jax.extend.backend as jeb
    jeb.clear_backends()
    simulate_devices(want)


@dataclasses.dataclass(frozen=True)
class Topology:
    """Resolved topology: the mesh plus canonical shardings."""

    mesh: Mesh
    replica_axis: str
    model_axis: str
    seq_axis: str
    stage_axis: str
    expert_axis: str = "expert"

    @property
    def num_replicas(self) -> int:
        return self.mesh.shape[self.replica_axis]

    @property
    def replicated(self) -> NamedSharding:
        """Sharding for parameters/state: replicated everywhere
        (≙ vars pinned to the PS and read by all workers,
        src/distributed_train.py:133-136 — except here every replica
        holds the copy and XLA keeps them identical)."""
        return NamedSharding(self.mesh, P())

    @property
    def batch_sharded(self) -> NamedSharding:
        """Sharding for a global batch: leading dim split over replicas."""
        return NamedSharding(self.mesh, P(self.replica_axis))

    def device_put_batch(self, batch, seq_sharded: bool = False):
        """Place a batch sharded over replicas (rows) and, when
        ``seq_sharded``, over the seq axis (second dim — the DP×SP
        token layout).

        Single-process: a plain device_put of the global batch.
        Multi-host: each process holds only its local rows
        (global_batch / process_count — see data.pipeline), so the
        global array must be assembled from process-local shards.
        (Sequence sharding should stay within a host for ingest: each
        process holds full rows, and the placement splits the token dim
        across its local devices.)
        """
        sharding = (NamedSharding(self.mesh, P(self.replica_axis, self.seq_axis))
                    if seq_sharded else self.batch_sharded)
        if jax.process_count() > 1:
            return jax.tree.map(
                lambda x: jax.make_array_from_process_local_data(
                    sharding, np.asarray(x)),
                batch)
        return jax.device_put(batch, sharding)

    @property
    def measured_timing_supported(self) -> bool:
        """Per-host measured timing is well-defined only when every
        replica lives wholly on one process (replicas split evenly
        across processes). E.g. cross-host TP with num_replicas=1 on 2
        processes has no owner whose measurement could fill the row —
        and two hosts writing different values into a replicated array
        would silently diverge its shards."""
        return (self.num_replicas % jax.process_count() == 0
                and self.num_replicas >= jax.process_count())

    @property
    def local_replica_count(self) -> int:
        """Replicas whose shards this process owns (even split)."""
        return self.num_replicas // jax.process_count()

    def zeros_measured(self) -> jax.Array:
        """The all-zeros measured vector [n] — valid on ANY mesh shape
        (zeros are identical whoever materializes them)."""
        n = self.num_replicas
        sharding = NamedSharding(self.mesh, P(self.replica_axis))
        return jax.make_array_from_callback(
            (n,), sharding, lambda idx: np.zeros(n, np.float32)[idx])

    def device_put_measured(self, local_ms) -> jax.Array:
        """Assemble the per-replica measured-step-time vector [n] from
        this process's local entries (shape [local_replica_count]).

        Each host contributes only the rows for its own replicas — the
        real per-host measurement — giving the policies a genuinely
        per-replica time base (≙ the per-worker timing tables the
        reference gossips over RPC, src/timeout_manager.py:48-61)."""
        if not self.measured_timing_supported:
            raise ValueError(
                f"per-host measured timing needs num_replicas "
                f"({self.num_replicas}) to split evenly over "
                f"{jax.process_count()} processes")
        local = np.asarray(local_ms, np.float32)
        if local.shape != (self.local_replica_count,):
            raise ValueError(
                f"measured vector must be [{self.local_replica_count}] "
                f"(local replicas), got {local.shape}")
        sharding = NamedSharding(self.mesh, P(self.replica_axis))
        if jax.process_count() > 1:
            return jax.make_array_from_process_local_data(sharding, local)
        return jax.device_put(local, sharding)

    def measured_stage(self) -> "MeasuredStage":
        """A per-step staging handle for the measured-timing vector —
        validate once, reuse the sharding and the host assembly buffer
        every step (see :class:`MeasuredStage`)."""
        return MeasuredStage(self)

    def device_put_replicated(self, tree):
        return jax.device_put(tree, self.replicated)

    def device_put_state(self, tree, specs):
        """Place a state pytree per a PartitionSpec tree. ``specs`` may
        be a *prefix* of ``tree`` (a single spec covering a subtree —
        e.g. P() for all params when not tensor-parallel)."""
        is_spec = lambda x: isinstance(x, PartitionSpec)  # noqa: E731
        spec_leaves, treedef = jax.tree.flatten(specs, is_leaf=is_spec)
        subtrees = treedef.flatten_up_to(tree)
        placed = [jax.device_put(sub, NamedSharding(self.mesh, spec))
                  for sub, spec in zip(subtrees, spec_leaves)]
        return jax.tree.unflatten(treedef, placed)


class MeasuredStage:
    """Pre-staged assembly for the per-step measured-timing vector.

    :meth:`Topology.device_put_measured` validates its arguments and
    builds a fresh ``NamedSharding`` on every call — fine for one-shot
    placement (tests, multihost bring-up), wasteful at once-per-step
    cadence in the train loop. The stage validates ONCE, caches the
    sharding, and owns a reusable host-side ``buffer`` the loop writes
    its per-replica milliseconds into; :meth:`put` hands back the
    staged ``[n]`` device array. The all-zeros vector — every step
    with no injection and no skew — is staged once and that device
    buffer is reused outright (no H2D at all on those steps).
    """

    def __init__(self, topo: Topology):
        if not topo.measured_timing_supported:
            raise ValueError(
                f"per-host measured timing needs num_replicas "
                f"({topo.num_replicas}) to split evenly over "
                f"{jax.process_count()} processes")
        self._n_local = topo.local_replica_count
        self._sharding = NamedSharding(topo.mesh, P(topo.replica_axis))
        self._multi = jax.process_count() > 1
        self._zeros: jax.Array | None = None
        self._zeros_fn = topo.zeros_measured
        #: host assembly scratch — write this step's values here, then
        #: :meth:`put` with no argument
        self.buffer = np.zeros(self._n_local, np.float32)

    def put(self, local_ms=None) -> jax.Array:
        """Stage ``local_ms`` (default: the assembly ``buffer``) as the
        sharded ``[n]`` measured vector."""
        local = (self.buffer if local_ms is None
                 else np.asarray(local_ms, np.float32))
        if local.shape != (self._n_local,):
            raise ValueError(
                f"measured vector must be [{self._n_local}] "
                f"(local replicas), got {local.shape}")
        if not local.any():
            if self._zeros is None:
                self._zeros = self._zeros_fn()
            return self._zeros
        # device_put may alias the host buffer (CPU backend) or copy
        # asynchronously (accelerators) — stage a private copy so the
        # loop reusing ``buffer`` next step can't corrupt this one
        local = np.array(local, np.float32)
        if self._multi:
            return jax.make_array_from_process_local_data(
                self._sharding, local)
        return jax.device_put(local, self._sharding)


def make_topology(cfg: MeshConfig | None = None,
                  devices: Sequence[jax.Device] | None = None) -> Topology:
    """Build the device mesh.

    Axes: (replica, model, seq, stage, expert). Data parallelism rides
    ``replica``; ``model`` carries Megatron tensor parallelism, ``seq``
    ring/all-to-all sequence parallelism, ``stage`` GPipe layer
    pipelining, ``expert`` MoE expert sharding. Unused axes default to
    size 1.
    """
    cfg = cfg or MeshConfig()
    if cfg.pipeline_chunks > 1 and cfg.pipeline_schedule != "1f1b":
        # chunks only exist under the interleaved schedule — silently
        # ignoring them would hand back plain GPipe with its full
        # bubble while the config promises interleaving
        raise ValueError(
            f"mesh.pipeline_chunks={cfg.pipeline_chunks} requires "
            f"pipeline_schedule='1f1b' (got {cfg.pipeline_schedule!r})")
    if (devices is None and cfg.simulate_devices > 0
            and len(jax.devices()) < cfg.simulate_devices):
        # A config that trained on a simulated mesh must be loadable by
        # every consumer (evaluator, sweep, report), not just the train
        # CLI — tear down the 1-device backend and force the CPU mesh.
        # Capture the TRUE ambient devices first: if ensure_mesh's
        # lazy capture ran only after this forcing, it would record the
        # simulated mesh as "ambient" and a later simulate_devices=0
        # config would silently keep running on the forced mesh.
        global _ambient_mesh
        if _ambient_mesh is None:
            _ambient_mesh = (len(jax.devices()), jax.default_backend())
        import jax.extend.backend as jeb
        jeb.clear_backends()
        simulate_devices(cfg.simulate_devices)
    devs = list(devices if devices is not None else jax.devices())
    mp, sp = max(1, cfg.model_parallelism), max(1, cfg.seq_parallelism)
    pp = max(1, cfg.pipeline_parallelism)
    ep = max(1, cfg.expert_parallelism)
    n = cfg.num_replicas
    if n == -1:
        n = len(devs) // (mp * sp * pp * ep)
    want = n * mp * sp * pp * ep
    if want > len(devs):
        raise ValueError(
            f"mesh needs {want} devices (replica={n} × model={mp} × seq={sp} "
            f"× stage={pp} × expert={ep}) but only {len(devs)} are visible")
    grid = np.array(devs[:want]).reshape(n, mp, sp, pp, ep)
    mesh = Mesh(grid, (cfg.replica_axis, cfg.model_axis, cfg.seq_axis,
                       cfg.stage_axis, cfg.expert_axis))
    return Topology(mesh=mesh,
                    replica_axis=cfg.replica_axis,
                    model_axis=cfg.model_axis,
                    seq_axis=cfg.seq_axis,
                    stage_axis=cfg.stage_axis,
                    expert_axis=cfg.expert_axis)


def make_seq_topology(n_seq: int, devices: Sequence[jax.Device] | None = None) -> Topology:
    """A mesh that spends its devices on the sequence axis (ring
    attention / context parallelism — the long-context path)."""
    return make_topology(
        MeshConfig(num_replicas=1, seq_parallelism=n_seq), devices=devices)
