"""Structured logging.

The reference logs through ``tf.logging`` and downstream tooling scrapes
stdout with regexes (tools/benchmark.py:30,67,140,151). We keep the
canonical human-readable per-step line — format-compatible with the
reference's record at src/distributed_train.py:367-371 so its
log-reading habits transfer — and *additionally* emit machine-readable
JSONL so nothing downstream ever parses free text again.
"""

from __future__ import annotations

import json
import logging
import sys
import time
from pathlib import Path
from typing import Any, IO

# The storage shim (train/storage.py) owns the fsync policy for
# journal appends (train.durability=full). Looked up through
# sys.modules instead of imported: a process that never loaded the
# trainer can never have set a non-default policy, and this module
# must stay importable without jax (the train package pulls it in).
_STORAGE_MODULE = __package__.rsplit(".", 1)[0] + ".train.storage"

# Sampled once, on the first write: the gate is a test-harness/debug
# switch, not a runtime toggle, and the write path is hot (per-step
# records). The parse itself lives in ONE place —
# obsv.schema.validation_enabled — so every enforcement point agrees.
_VALIDATE_EVENTS: bool | None = None

_LOGGER = logging.getLogger("distributedmnist_tpu")
if not _LOGGER.handlers:
    _h = logging.StreamHandler(sys.stderr)
    _h.setFormatter(logging.Formatter("%(asctime)s %(levelname)s %(name)s] %(message)s"))
    _LOGGER.addHandler(_h)
    _LOGGER.setLevel(logging.INFO)
    _LOGGER.propagate = False


def get_logger(name: str | None = None) -> logging.Logger:
    return _LOGGER if name is None else _LOGGER.getChild(name)


class JsonlSink:
    """Append-only JSONL event sink (one file per run/role)."""

    def __init__(self, path: str | Path):
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self._fh: IO[str] = open(self.path, "a", buffering=1)

    def write(self, record: dict[str, Any]) -> None:
        record.setdefault("ts", time.time())
        global _VALIDATE_EVENTS
        if _VALIDATE_EVENTS is None:
            from ..obsv.schema import validation_enabled
            _VALIDATE_EVENTS = validation_enabled()
        if _VALIDATE_EVENTS:
            # debug-mode journal-schema enforcement (on in tests): the
            # runtime half of graftcheck — payloads built dynamically
            # (**fields, loops) that the AST pass can't see as literal
            # dicts still get checked against obsv/schema.py before
            # they land in an artifact.  Records without an "event" key
            # (sweep-result rows share this sink) pass vacuously.
            from ..obsv.schema import check_event
            check_event(record, source=self.path.name)
        self._fh.write(json.dumps(record, default=_default) + "\n")
        storage = sys.modules.get(_STORAGE_MODULE)
        if storage is not None and storage.journal_sync_enabled():
            storage.fsync_journal(self._fh)

    def close(self) -> None:
        self._fh.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _default(o: Any):
    try:
        import numpy as np
        if isinstance(o, np.generic):
            return o.item()
        if isinstance(o, np.ndarray):
            return o.tolist()
    except ImportError:
        pass
    return str(o)


def text_tail(s: str | None, limit: int = 2000) -> str | None:
    """Last ``limit`` characters of ``s`` — the journal-friendly form of
    a subprocess stream (a crashing worker's last lines are the
    diagnostic ones; the driver that reads these artifacts keeps tails,
    not heads)."""
    if s is None:
        return None
    return s if len(s) <= limit else s[-limit:]


def step_line(replica: int, step: int, loss: float, train_acc: float,
              examples_per_sec: float, sec_per_batch: float) -> str:
    """The canonical per-step record (≙ src/distributed_train.py:367-371)."""
    return ("Worker %d: step %d, loss = %.6f, train_acc = %.6f "
            "(%.1f examples/sec; %.3f sec/batch)"
            % (replica, step, loss, train_acc, examples_per_sec, sec_per_batch))


def eval_line(num_examples: int, precision: float, loss: float, seconds: float) -> str:
    """The evaluator's regex-parseable line — exact format of
    src/nn_eval.py:102-103 so the reference's parser
    (tools/benchmark.py:151) would still work."""
    return ("Num examples: %d Precision @ 1: %f Loss: %f Time: %f"
            % (num_examples, precision, loss, seconds))
