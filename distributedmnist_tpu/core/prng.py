"""PRNG seed policy.

The reference mixes a fixed graph seed (66478, src/mnist.py:32) with
time-seeded numpy shuffles (src/mnist_data.py:55,80-84) — runs are not
reproducible. Here every random stream derives from one root seed by
folding in a stable stream name, the step, and (when per-replica) the
replica index, so any run is exactly replayable yet streams never
collide.
"""

from __future__ import annotations

import hashlib

import jax
import jax.numpy as jnp


def _stream_tag(name: str) -> int:
    """Stable 31-bit tag for a stream name (hash-based, not Python hash)."""
    return int.from_bytes(hashlib.blake2s(name.encode(), digest_size=4).digest(), "big") & 0x7FFFFFFF


def root_key(seed: int) -> jax.Array:
    return jax.random.PRNGKey(seed)


def stream_key(root: jax.Array, name: str) -> jax.Array:
    """Key for a named stream ("dropout", "drop_connect", "data", ...)."""
    return jax.random.fold_in(root, _stream_tag(name))


def step_key(root: jax.Array, name: str, step: jax.Array | int) -> jax.Array:
    return jax.random.fold_in(stream_key(root, name), jnp.asarray(step, jnp.uint32))


def replica_key(root: jax.Array, name: str, step: jax.Array | int,
                replica: jax.Array | int) -> jax.Array:
    """Per-replica, per-step key — safe inside shard_map where
    ``replica`` is `lax.axis_index`."""
    return jax.random.fold_in(step_key(root, name, step), jnp.asarray(replica, jnp.uint32))
