"""Persistent-compilation-cache wiring (restart-latency fast path).

Every supervisor restart and every chaos trial used to pay the full
XLA compile of the train step on top of process boot — the dominant
self-inflicted straggler in the recovery path (ROADMAP item 5). jax
ships a persistent compilation cache keyed on the lowered program +
compile options; this module is the single place its knobs are applied
so the CLI entry points, the driver hooks, and the cluster backends
cannot drift on how the cache is enabled:

* :func:`enable_persistent_cache` — apply a :class:`~.config.
  CompileConfig`'s knobs to ``jax.config``. The cache dir resolves
  config → ``DMT_COMPILE_CACHE_DIR`` env (how ``LocalProcessCluster``
  threads one SHARED dir into every worker it spawns, so a restarted
  worker hits warm compiles from its predecessor's run) → disabled.
* :func:`cache_stats` — entries/bytes on disk plus this process's
  hit/miss counters (from jax's monitoring events), so compile-cache
  regressions are visible in bench artifacts and worker journals
  instead of only as mysteriously slower restarts.

Measured on this repo's chaos train payload (2-device simulated mesh,
ZeRO-1 on): spawn→first-logged-step drops ~10 s → ~5 s when the cache
is warm — the compile simply disappears from the boot path.
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import Any

from .config import CompileConfig
from .log import get_logger

logger = get_logger("compile_cache")

#: the env var LocalProcessCluster threads into worker processes
CACHE_DIR_ENV = "DMT_COMPILE_CACHE_DIR"

# this process's persistent-cache hit/miss counters, fed by jax's
# monitoring events (registered once, on first enable)
_counters = {"hits": 0, "misses": 0}
_listener_installed = False
_enabled_dir: Path | None = None


def resolve_cache_dir(cfg: CompileConfig | None = None) -> Path | None:
    """The cache dir a config resolves to: ``cfg.cache_dir`` when set,
    else ``DMT_COMPILE_CACHE_DIR``, else None (cache disabled)."""
    cfg = cfg or CompileConfig()
    if not cfg.persistent_cache:
        return None
    raw = cfg.cache_dir or os.environ.get(CACHE_DIR_ENV, "")
    return Path(raw) if raw else None


#: jax releases whose serialized executables are UNSAFE to load in a
#: different process than the one that compiled them. Measured on this
#: container's 0.4.37: a restarted worker reading its predecessor's
#: persistent-cache (or AOT) entries computes wrong numerics at its
#: first resumed step and segfaults within a few more — dense and
#: ZeRO-1 programs alike, graceful-drain and SIGKILL handoffs alike
#: (13/13 corrupt with the cache on, 0/4 without). This is the
#: cross-process face of the same-process reload corruption the AOT
#: cache already refuses via its pid stamp. Newer jax releases fall
#: outside the tuple and re-enable automatically.
_CROSS_PROCESS_UNSAFE_MAX = (0, 4, 37)


def cross_process_reuse_quarantined() -> str | None:
    """Reason string when loading compile-cache entries written by a
    DIFFERENT process is known to corrupt this jax, else None. Version
    check only — no backend touch, so entry points may call this
    before the mesh is forced."""
    import jax
    try:
        ver = tuple(int(x) for x in jax.__version__.split(".")[:3])
    except ValueError:
        return None  # dev/dirty version string: assume current = fixed
    if ver <= _CROSS_PROCESS_UNSAFE_MAX:
        return (f"jax {jax.__version__} deserializes corrupt "
                "executables cross-process (wrong numerics then "
                "SIGSEGV on restarted workers — measured)")
    return None


def _install_listener() -> None:
    global _listener_installed
    if _listener_installed:
        return
    try:
        from jax._src import monitoring

        def _on_event(name: str, **kw: Any) -> None:
            if name == "/jax/compilation_cache/cache_hits":
                _counters["hits"] += 1
            elif name == "/jax/compilation_cache/cache_misses":
                _counters["misses"] += 1

        monitoring.register_event_listener(_on_event)
        _listener_installed = True
    except Exception as e:  # private API — stats degrade, cache doesn't
        logger.debug("no cache hit/miss monitoring on this jax: %s", e)


def enable_persistent_cache(cfg: CompileConfig | None = None) -> Path | None:
    """Apply the persistent-cache knobs to ``jax.config``; returns the
    active cache dir (None = disabled/unsupported). Safe to call more
    than once and before or after backend init — jax reads the config
    at each compile. Unknown knobs on older jax are skipped, never
    fatal: a worker must train with a cold cache rather than not at
    all."""
    global _enabled_dir
    import jax

    cfg = cfg or CompileConfig()
    cache_dir = resolve_cache_dir(cfg)
    if cache_dir is None:
        return None
    reason = cross_process_reuse_quarantined()
    if reason is not None and not cfg.trust_cache_cross_process:
        # The persistent cache's ONLY value is cross-process reuse
        # (in-process recompiles hit jax's in-memory caches first), so
        # a quarantined jax disables it outright: a restart must train
        # with a cold compile rather than resume on corrupt numerics.
        # compile.trust_cache_cross_process=true overrides for
        # platforms someone has actually validated.
        logger.warning("persistent compile cache QUARANTINED: %s — "
                       "compiles stay cold (override: "
                       "compile.trust_cache_cross_process)", reason)
        return None
    try:
        cache_dir.mkdir(parents=True, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", str(cache_dir))
    except Exception as e:
        logger.warning("persistent compile cache unavailable (%s) — "
                       "compiles stay cold", e)
        return None
    for knob, value in (
            ("jax_persistent_cache_min_entry_size_bytes",
             cfg.min_entry_size_bytes),
            ("jax_persistent_cache_min_compile_time_secs",
             cfg.min_compile_time_secs)):
        try:
            jax.config.update(knob, value)
        except Exception as e:  # older jax: knob absent
            logger.debug("compile-cache knob %s unsupported: %s", knob, e)
    _install_listener()
    if _enabled_dir != cache_dir:
        # jax latches "no cache" at the first compile that runs with
        # the dir unset (measured on 0.4.37: enabling afterwards
        # silently writes nothing) — reset the latch so enabling works
        # whenever it happens, not only in a pristine process
        try:
            from jax._src import compilation_cache as _ccache
            _ccache.reset_cache()
        except Exception as e:
            logger.debug("compilation-cache reset unavailable: %s", e)
        logger.info("persistent compile cache: %s", cache_dir)
        _enabled_dir = cache_dir
    return cache_dir


def cache_stats(cache_dir: str | Path | None = None) -> dict[str, Any]:
    """On-disk entry count/bytes for ``cache_dir`` (default: the dir
    last enabled in this process) plus this process's hit/miss
    counters. The counters only move once :func:`enable_persistent_
    cache` installed the monitoring listener."""
    d = Path(cache_dir) if cache_dir is not None else _enabled_dir
    entries = 0
    size = 0
    if d is not None and d.is_dir():
        for p in d.glob("*-cache"):
            try:
                size += p.stat().st_size
                entries += 1
            except OSError:
                continue
    return {"dir": str(d) if d is not None else None,
            "entries": entries, "bytes": size,
            "hits": _counters["hits"], "misses": _counters["misses"]}
