"""Typed experiment configuration.

Replaces the reference's two-tier flag system — ~25 global
``tf.app.flags`` (reference: src/distributed_train.py:36-99) plus
``eval()``-loaded ``Cfg`` dict literals with %-interpolation
(reference: tools/tf_ec2.py:17-25, tools/benchmark.py:13-15) — with
frozen dataclasses, safe literal config files (JSON or Python literals
via ``ast.literal_eval``, never ``eval``), and dotted-path CLI
overrides.
"""

from __future__ import annotations

import ast
import dataclasses
import json
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any


class ConfigError(ValueError):
    pass


@dataclass(frozen=True)
class DataConfig:
    """Dataset selection and ingest policy (≙ src/mnist_data.py)."""

    dataset: str = "mnist"  # mnist | fashion_mnist | cifar10 | synthetic
    data_dir: str = "/tmp/dmt_data"
    # Global batch size across all replicas. The reference's
    # ``batch_size`` flag (src/distributed_train.py:63) is *per worker*;
    # here per-replica batch = batch_size // num_replicas.
    batch_size: int = 128
    # "sharded": deterministic per-host split (fixes the reference's
    # ignored worker_id/n_workers args, src/mnist_data.py:156-163,212-213).
    # "independent": each replica samples its own shuffle of the full
    # train set — faithful to the reference's behavior
    # (src/mnist_data.py:55,80-84).
    shard_mode: str = "sharded"
    # Synthetic-data fallback (≙ the latent fake_data fixture,
    # src/mnist_data.py:164-172) — also the default when no idx files
    # exist on disk (this environment has no network egress).
    synthetic_train_size: int = 8192
    synthetic_test_size: int = 2048
    use_native_pipeline: bool = True  # C++ prefetch loader when built
    prefetch_batches: int = 2
    # Device-side prefetch (data.device_prefetch): stage batches
    # through Topology.device_put_batch on a producer thread, a
    # bounded queue of device_prefetch_depth ahead of the consuming
    # step — host assembly + H2D overlap device compute instead of
    # sitting on its critical path (data/device_prefetch.py). Enabled
    # by default where a producer thread pays: a spare host core, or a
    # real accelerator backend whose drains park the host GIL-free
    # (single-core CPU-backend hosts fall back to the inline feed, per
    # the same measurement behind the native-pipeline gate).
    device_prefetch: bool = True
    device_prefetch_depth: int = 2

    def effective_device_prefetch_depth(self) -> int:
        """The depth eval paths should stage ahead — 0 (inline feed)
        whenever the enable knob is off. One definition, so Trainer
        eval and the evaluator service can't drift."""
        return self.device_prefetch_depth if self.device_prefetch else 0
    # Fetch missing idx files into data_dir before loading
    # (≙ maybe_download, src/mnist_data.py:176-187). Degrades to the
    # synthetic fallback when there is no network egress.
    download: bool = True


@dataclass(frozen=True)
class ModelConfig:
    """Model family + numerics (≙ src/mnist.py)."""

    name: str = "mnist_cnn"  # mnist_cnn | resnet20 | transformer
    # Reference fixes its init seed at 66478 (src/mnist.py:32).
    init_seed: int = 66478
    dropout_rate: float = 0.5  # src/mnist.py:140
    num_classes: int = 10
    image_size: int = 28
    num_channels: int = 1
    # bfloat16 activations/matmuls feed the MXU; params stay float32.
    compute_dtype: str = "bfloat16"
    # transformer (long-context path) only:
    seq_len: int = 512
    model_dim: int = 128
    num_heads: int = 4
    num_layers: int = 2
    vocab_size: int = 256
    # "flash": fused pallas kernel (ops/pallas_attention; interpreted
    # off-TPU), "dense": XLA einsum attention.
    attention_impl: str = "flash"
    # Sequence-parallel strategy when mesh.seq_parallelism > 1:
    # "ring" (ppermute K/V rotation, any head count) or "ulysses"
    # (all-to-all head scatter; needs num_heads % seq_parallelism == 0,
    # composes with the flash kernel).
    sp_attention: str = "ring"
    # Mixture-of-experts FFNs (transformer): 0 = dense MLP. Experts
    # shard over mesh.expert_parallelism (the 'expert' axis); composes
    # with mesh.model_parallelism (TP on heads + every expert's FFN).
    num_experts: int = 0
    expert_capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01
    # Tokens are routed in fixed per-row groups: each sequence row
    # splits into moe_num_groups contiguous chunks, and capacity +
    # load-balance aux are computed per chunk (GShard group routing).
    # Groups nest inside rows, so routing semantics are invariant to
    # the pipeline microbatch split. 0 = auto: the minimum the mesh
    # requires (one group per expert rank per seq shard per row) —
    # convenient, but mesh-dependent; set explicitly for numerics that
    # are identical across every mesh (the gold-parity tests do).
    moe_num_groups: int = 0
    # 1 = Switch top-1 (gate = raw top prob); ≥2 = GShard top-k with
    # renormalized gates and sequential capacity filling (round k's
    # queue positions start after all earlier rounds' claims).
    moe_router_top_k: int = 1
    # Rematerialize each transformer block in the backward pass
    # (jax.checkpoint): activation memory per layer drops from O(all
    # intermediates) to O(block boundary), bought with one extra
    # forward — the standard HBM/FLOPs trade for long sequences.
    remat: bool = False
    # remat_policy (only meaningful with remat=True):
    #   "full"     — recompute everything inside the block (minimum HBM)
    #   "save_attn" — keep each block's attention OUTPUT resident and
    #     recompute only the projections/norms/MLP: the backward never
    #     re-runs the attention kernel, cutting the remat recompute by
    #     the attention fraction for O(b·s·d) extra bytes per layer —
    #     the right trade once attention dominates (long
    #     sequences): measured 1.14x tokens/sec at the S=8192
    #     long-context bench shape on v5e.
    remat_policy: str = "full"


@dataclass(frozen=True)
class OptimConfig:
    """Optimizer selection + LR schedule.

    The reference hardwires plain GradientDescentOptimizer with
    exponential staircase decay (src/distributed_train.py:88-99,
    143-156,176); ``name`` opens that into the large-batch registry
    (train/optim.py) per "Scale MLPerf-0.6 models on Google TPU-v3
    Pods" (arXiv:1909.09756):

      * ``sgd``      — plain SGD; ``momentum > 0`` adds heavyball
                       momentum (the historical behavior of this knob).
      * ``momentum`` — explicit heavyball momentum-SGD.
      * ``lars``     — layer-wise adaptive rate scaling
                       (arXiv:1708.03888): per-leaf trust ratio
                       ``eta·‖w‖/‖g + wd·w‖`` scales the momentum
                       input; ``beta1`` is its momentum coefficient.
      * ``lamb``     — layer-wise Adam (arXiv:1904.00962): Adam moments
                       (``beta1``/``beta2``/``eps``) with the per-leaf
                       trust ratio ``‖w‖/‖update‖``.

    LARS/LAMB own their momentum term (``beta1``): combining them with
    ``momentum != 0`` is a validated ConfigError, as is an unknown
    ``name`` (train/optim.py ``validate``). 1-D leaves (biases, norm
    scales) skip weight decay and trust-ratio adaptation, per both
    papers' recipes.
    """

    name: str = "sgd"  # sgd | momentum | lars | lamb
    initial_learning_rate: float = 0.1
    num_epochs_per_decay: float = 2.0
    learning_rate_decay_factor: float = 0.999
    staircase: bool = True
    # decay_steps = batches_per_epoch * num_epochs_per_decay / k where k
    # is the aggregation quorum (src/distributed_train.py:147).
    momentum: float = 0.0  # reference uses plain GradientDescentOptimizer (:176)
    # -- trust-ratio optimizer hyperparameters (lars/lamb) -------------
    beta1: float = 0.9       # lamb first moment / lars momentum
    beta2: float = 0.999     # lamb second moment
    eps: float = 1e-6        # lamb denominator floor
    weight_decay: float = 0.0
    trust_coefficient: float = 0.001  # lars eta
    # -- schedule ------------------------------------------------------
    # "exponential": the reference's staircase decay (the default path;
    #   learning_rate_decay_factor == 1.0 degrades to constant).
    # "polynomial": linear warmup over warmup_steps then polynomial
    #   decay to end_learning_rate at decay_total_steps — the MLPerf
    #   large-batch recipe (arXiv:1909.09756 §3). decay_total_steps=0
    #   resolves to train.max_steps at Trainer build.
    schedule: str = "exponential"  # exponential | polynomial
    warmup_steps: int = 0
    decay_total_steps: int = 0
    end_learning_rate: float = 0.0
    poly_power: float = 2.0


@dataclass(frozen=True)
class SyncConfig:
    """Aggregation discipline — the reference's core contribution (SURVEY §2.2).

    mode:
      * "sync"     — all replicas contribute every step (flag ≡ 1).
      * "quorum"   — k-of-n backup-worker semantics: only the k fastest
                     replicas (by modeled/measured step time) contribute
                     (≙ tf.train.SyncReplicasOptimizer(replicas_to_aggregate=k),
                     src/distributed_train.py:184-188).
      * "timeout"  — deadline straggler drop: replicas whose step time
                     exceeds ``timeout_ms`` are masked out (≙ the
                     disabled RPC-kill path, src/timeout_manager.py:38-46).
      * "interval" — wall-clock-paced windowed aggregation: gradients
                     accumulate across steps and apply when the window
                     elapses, averaging whatever arrived (take_grad(1)
                     semantics, sync_replicas_optimizer_modified.py:208-215,371-373).
      * "cdf"      — full barrier + per-replica step-time CDF collection
                     (≙ --worker_times_cdf_method, TimeoutReplicasOptimizer
                     take_grad(total), sync_replicas_optimizer_modified.py:370-376).
    """

    mode: str = "sync"
    # -1 → all replicas, matching the reference default
    # (src/distributed_train.py:118-121).
    num_replicas_to_aggregate: int = -1
    interval_ms: float = 1000.0  # ≙ FLAGS.interval_ms (sync_replicas_optimizer_modified.py:38)
    timeout_ms: float = 1000.0
    drop_connect: bool = False  # src/distributed_train.py:60
    drop_connect_probability: float = 0.9  # keep-probability (:98-99)
    # Synthetic per-replica straggler model for experiments on uniform
    # TPU hardware (replaces the reference's method of inducing
    # stragglers with slow EC2 instance types, cfg/time_cdf_cfgs/*).
    straggler_profile: str = "none"  # none | lognormal | spike
    straggler_mean_ms: float = 50.0
    straggler_sigma: float = 0.5
    straggler_spike_prob: float = 0.05
    straggler_spike_scale: float = 10.0
    # Per-replica DEVICE-side timing (obsv/timing.py:ReplicaDeviceProbe):
    # each local replica's device is probed with a trivial op enqueued
    # behind everything on its queue, and the measured drain SKEW joins
    # the per-host measured step time in the [n] vector the policies
    # rank on. Within one lockstep SPMD program replicas cannot diverge
    # (collectives barrier them), so the skew captures work queued
    # OUTSIDE the shared program — per-device callbacks, injected chaos
    # work, asymmetric host feeds. Off by default (one probe dispatch +
    # readiness poll per local replica per step).
    measure_device_skew: bool = False
    # -- adaptive straggler discipline (train/discipline.py) -----------
    # The online controller: watch the rolling per-replica step-time
    # CDF and adapt the discipline parameters (quorum k / timeout_ms)
    # at runtime — they are traced step inputs (parallel/api.py
    # make_discipline_vector), so a change swaps a scalar buffer, not a
    # compiled executable. Decision rule (pure, journal-licensed, the
    # broker decide() shape): when the window tail ratio p99/p50
    # crosses ``adaptive_tail_high`` the discipline TIGHTENS (quorum:
    # k−1 down to ceil(n·min_quorum_frac); timeout: deadline →
    # max(floor, p50·timeout_factor)); when it falls back under
    # ``adaptive_tail_low`` it RELAXES one notch toward the configured
    # static setting. Dead band between the marks, cooldown in steps
    # from the last completed change. Every change is journaled as an
    # event:"discipline" begin/complete pair and licensed by the
    # recorded crossing (obsv/invariants.py "discipline").
    adaptive: bool = False
    adaptive_window_steps: int = 20    # rolling CDF window (steps)
    adaptive_cooldown_steps: int = 40  # min steps between changes
    adaptive_tail_high: float = 2.0    # p99/p50 tighten mark
    adaptive_tail_low: float = 1.3    # p99/p50 relax mark (< high)
    adaptive_min_quorum_frac: float = 0.5   # quorum floor: ceil(n·frac)
    adaptive_timeout_factor: float = 1.5    # tightened deadline = p50·this
    adaptive_timeout_floor_ms: float = 1.0  # deadline never below this

    def validate(self, num_replicas: int | None = None) -> None:
        """Typed knob validation (ConfigError, the OptimConfig pattern)
        — called from ``build_train_step``, so every Trainer build hits
        it before any tracing. Base knobs stay permissive (timeout_ms=0
        legitimately masks every replica — pinned in tests); the
        ``adaptive`` family is strict."""
        if not (self.straggler_sigma >= 0.0):
            raise ConfigError(
                f"sync.straggler_sigma must be >= 0, got "
                f"{self.straggler_sigma}")
        if not (0.0 <= self.straggler_spike_prob <= 1.0):
            raise ConfigError(
                f"sync.straggler_spike_prob must be in [0, 1], got "
                f"{self.straggler_spike_prob}")
        if not self.adaptive:
            return
        if self.mode not in ("quorum", "timeout"):
            raise ConfigError(
                f"sync.adaptive=true requires a maskable mode "
                f"(quorum | timeout), got mode={self.mode!r} — sync/cdf "
                "have no straggler parameter to adapt, and interval "
                "pacing adapts the modeled wall clock only, not which "
                "replicas contribute")
        if self.adaptive_window_steps < 2:
            raise ConfigError(
                f"sync.adaptive_window_steps must be >= 2 (a one-sample "
                f"window has no CDF), got {self.adaptive_window_steps}")
        if self.adaptive_cooldown_steps < self.adaptive_window_steps:
            raise ConfigError(
                f"sync.adaptive_cooldown_steps "
                f"({self.adaptive_cooldown_steps}) must be >= "
                f"adaptive_window_steps ({self.adaptive_window_steps}) — "
                "a cooldown shorter than the window re-decides on "
                "samples from before the last change")
        if not (self.adaptive_tail_high > self.adaptive_tail_low >= 1.0):
            raise ConfigError(
                f"sync.adaptive tail marks need high > low >= 1.0 "
                f"(hysteresis needs a dead band; p99/p50 is >= 1 by "
                f"construction), got high={self.adaptive_tail_high} "
                f"low={self.adaptive_tail_low}")
        if not (0.0 < self.adaptive_min_quorum_frac <= 1.0):
            raise ConfigError(
                f"sync.adaptive_min_quorum_frac must be in (0, 1], got "
                f"{self.adaptive_min_quorum_frac}")
        if not (self.adaptive_timeout_factor >= 1.0):
            raise ConfigError(
                f"sync.adaptive_timeout_factor must be >= 1.0 (a "
                f"deadline under the window median masks the majority), "
                f"got {self.adaptive_timeout_factor}")
        if not (self.adaptive_timeout_floor_ms > 0.0):
            raise ConfigError(
                f"sync.adaptive_timeout_floor_ms must be > 0, got "
                f"{self.adaptive_timeout_floor_ms}")
        if num_replicas is not None and self.mode == "quorum":
            import math
            k_floor = max(1, math.ceil(num_replicas
                                       * self.adaptive_min_quorum_frac))
            k0 = (num_replicas if self.num_replicas_to_aggregate == -1
                  else self.num_replicas_to_aggregate)
            if k0 < k_floor:
                raise ConfigError(
                    f"sync.num_replicas_to_aggregate={k0} starts below "
                    f"the adaptive quorum floor ceil({num_replicas} * "
                    f"{self.adaptive_min_quorum_frac}) = {k_floor} — the "
                    "controller could never relax back to the "
                    "configured setting")


@dataclass(frozen=True)
class ParallelConfig:
    """Cross-replica weight-update sharding — ZeRO-1 per "Automatic
    Cross-Replica Sharding of Weight Update in Data-Parallel Training"
    (arXiv:2004.13336).

    ``shard_weight_update``: shard the optimizer state (momentum
    buffers) and the weight-update computation across the mesh's
    ``replica`` axis: gradients are reduce-scattered instead of
    all-reduced, each replica updates only its 1/n param shard, and the
    fresh params are allgathered back. Per-chip optimizer-state memory
    and update FLOPs drop by ~the replica count; total communication
    volume stays that of one all-reduce. A no-op (with a logged note)
    when the replica axis is 1 or ``sync.mode == "interval"`` (the
    windowed accumulator wants the full mean; see parallel/api.py).

    ``shard_min_leaf_size``: leaves with fewer elements than this stay
    replicated — slicing tiny norm/bias vectors buys nothing and costs
    a gather each. 0 = auto (the replica count, the smallest shardable
    size). Leaves already sharded over a model/stage/expert axis also
    stay on their tensor-parallel placement (they are not replicated
    across THOSE axes; only their replica-axis redundancy would be
    addressable, and the flattened composite layout is not worth the
    bookkeeping at this repo's scales).

    ``comm_buckets``: how many layer-ordered buckets the ZeRO-1
    communication is split into (arXiv:1810.11112's overlap lever).
    1 = the monolithic discipline: one collective per sharded leaf,
    all issued after the full backward. N > 1 groups the sharded
    leaves into N contiguous buckets balanced by padded size and
    issues ONE reduce-scatter (and one allgather) per bucket — each
    bucket's scatter depends only on its own leaves' gradients, so
    XLA's scheduler can overlap a bucket's communication with the
    remaining backward compute instead of serializing the whole comm
    phase behind it. Bucketing is pure regrouping: the per-element
    cross-replica sums are unchanged, so losses/params stay bitwise
    equal to the monolithic path (pinned in tests/test_zero1.py).
    Leave at 1 on CPU meshes, where collectives serialize on the host
    and regrouping buys nothing (see README Performance).

    ``resident_sharded``: keep the params THEMSELVES resident in the
    replica-sharded flat layout between steps (the arXiv:2004.13336 §5
    ending — a step toward ZeRO-3). Each step allgathers the weights
    just-in-time per bucket at the top of the forward and the update
    writes back only this replica's slice; peak per-chip param bytes
    drop toward 1/n for the sharded leaves, and the post-update
    allgather leaves the step entirely (the next forward's gather
    replaces it). Checkpoints still store the canonical logical layout,
    so artifacts (and their digests) are identical across this knob and
    restore bitwise into any other layout. Requires
    ``shard_weight_update`` (validated at build time)."""

    shard_weight_update: bool = False
    shard_min_leaf_size: int = 0
    comm_buckets: int = 1
    resident_sharded: bool = False

    def validate(self) -> None:
        """Build-time validation (called from ``zero1_plan_for``, which
        every step/state builder routes through): a bad knob combo must
        be a typed ConfigError naming the dependency at Trainer build,
        not a shape error mid-step."""
        if self.comm_buckets < 1:
            raise ConfigError(
                f"parallel.comm_buckets must be >= 1, got "
                f"{self.comm_buckets} (1 = monolithic per-leaf "
                "collectives, N > 1 = N layer-ordered overlap buckets)")
        if self.resident_sharded and not self.shard_weight_update:
            raise ConfigError(
                "parallel.resident_sharded=true requires "
                "parallel.shard_weight_update=true — resident-sharded "
                "params are a layout of the ZeRO-1 shard plan; without "
                "the sharded weight update there is no plan to shard "
                "them by")


@dataclass(frozen=True)
class PrecisionConfig:
    """Mixed precision as a config knob (arXiv:1909.09756 §2: bf16
    compute with fp32 master weights is the TPU large-batch recipe).

    ``param_dtype``: the dtype the forward/backward pass sees the
    parameters in. With ``master_weights=true``, ``TrainState.params``
    stay float32 (the master copy — what the optimizer updates, what
    the ZeRO-1 update shards/gathers, and what checkpoints store
    canonically) and the train step casts them to ``param_dtype`` just
    before ``apply``; the low-precision view is derived, never
    persistent state, so restores and digests are precision-portable.
    With ``master_weights=false`` and a low-precision ``param_dtype``,
    params are cast once at init and updated in that dtype — true
    low-precision training (optimizer moments stay float32 either way;
    gradients are accumulated and aggregated in float32).

    ``compute_dtype``: overrides ``model.compute_dtype`` when set
    (activations/matmuls); "" leaves the model section authoritative.

    When to leave it all off (the defaults): float32 params + the
    model's bf16 compute is already the MXU-native single-chip mode;
    master weights only start paying once ``param_dtype`` drops below
    float32 — at which point updates of tiny weights (lr·g below the
    bf16 ulp) would silently round to no-ops without the fp32 master.
    """

    param_dtype: str = "float32"
    compute_dtype: str = ""  # "" → model.compute_dtype
    master_weights: bool = False


@dataclass(frozen=True)
class CompileConfig:
    """Restart-latency fast path (ROADMAP item 5): persistent XLA
    compilation cache + ahead-of-time train-step compilation.

    Every supervisor restart and chaos trial used to pay the full XLA
    compile (~10 s) on top of process boot; these knobs let a restarted
    worker reuse its predecessor's compiles.

    ``cache_dir``: where jax's persistent compilation cache lives. ""
    resolves the ``DMT_COMPILE_CACHE_DIR`` env var (how
    ``LocalProcessCluster`` threads ONE shared cache dir into every
    worker it spawns) and disables the cache when that is unset too —
    so plain library use is unchanged unless a dir is provided.
    The global jax cache is only ENABLED at process entry points
    (launch CLI, ``__graft_entry__``) — never from inside the Trainer:
    on jaxlib 0.4.37 a process that builds several Trainers against an
    enabled cache corrupts itself (measured). Library callers wanting
    it call ``core.compile_cache.enable_persistent_cache`` once at
    startup; the Trainer itself only uses the dir for the AOT
    executable cache below.

    ``precompile``: Trainer AOT-compiles the train step
    (``jit(...).lower(...).compile()``) BEFORE the first batch, so
    compile time is journaled separately from step time (the
    ``event: "compile"`` record in train_log.jsonl) and a warm standby
    can park fully compiled.

    ``aot_executable_cache``: additionally serialize the compiled
    train-step executable into ``<cache_dir>/aot`` keyed on
    (model, config, topology) where the installed jax/backend supports
    cross-process executable serialization. Platforms that don't (the
    CPU backend raises "Symbols not found" on a foreign executable)
    discover it on first load, journal the fallback, and lean on the
    persistent compilation cache instead — measured, not assumed.
    """

    persistent_cache: bool = True
    cache_dir: str = ""
    min_entry_size_bytes: int = 0
    min_compile_time_secs: float = 0.0
    precompile: bool = True
    aot_executable_cache: bool = True
    # Cross-process cache reuse is QUARANTINED on jaxlib <= 0.4.37: a
    # restarted worker that loads executables serialized by its dead
    # predecessor computes wrong numerics and then segfaults (measured
    # on this container — dense and ZeRO-1 alike, graceful or SIGKILL
    # handoff; the cross-process face of the same-process reload
    # corruption the AOT cache already refuses via its pid stamp).
    # enable_persistent_cache and the AOT disk cache both refuse on a
    # quarantined jax unless this override asserts the platform has
    # been validated (e.g. a real TPU backend where serialization is
    # known good).
    trust_cache_cross_process: bool = False


@dataclass(frozen=True)
class MeshConfig:
    """Device-mesh topology. Replaces ClusterSpec/ps_hosts/worker_hosts
    (src/mnist_distributed_train.py:25-31, src/distributed_train.py:41-48)."""

    # -1 → use every visible device on the 'replica' axis.
    num_replicas: int = -1
    # Reserved axes so TP/SP can be added without redesign (SURVEY §5.7).
    model_parallelism: int = 1
    seq_parallelism: int = 1
    # Layer pipelining over the 'stage' axis.
    pipeline_parallelism: int = 1
    pipeline_microbatches: int = 4
    # "gpipe": all forwards then all backwards (AD transpose; bubble
    # 2(S-1) stage-works). "1f1b": fused interleaved 1F1B — each stage
    # split into pipeline_chunks virtual chunks, one chunk-work per
    # device-tick, backward-priority schedule (ops/pipeline.py; bubble
    # ~2(S-1) chunk-works, a pipeline_chunks-fold reduction).
    pipeline_schedule: str = "gpipe"
    pipeline_chunks: int = 1
    # Mixture-of-experts expert sharding over the 'expert' axis;
    # composes with model_parallelism (TP inside every expert's FFN and
    # the attention heads).
    expert_parallelism: int = 1
    # >0: force an N-virtual-CPU-device platform before backend init —
    # the mock distributed backend (SURVEY §4) reachable from the CLI.
    simulate_devices: int = 0
    replica_axis: str = "replica"
    model_axis: str = "model"
    seq_axis: str = "seq"
    stage_axis: str = "stage"
    expert_axis: str = "expert"


@dataclass(frozen=True)
class TrainConfig:
    """Loop / checkpoint / logging cadences (≙ src/distributed_train.py:56-87)."""

    max_steps: int = 1000
    train_dir: str = "/tmp/dmt_train"
    seed: int = 0
    # Gradient accumulation (arXiv:1909.09756 §2): each loop step pulls
    # this many consecutive batches, microbatch-scans them inside the
    # compiled step accumulating gradients in float32, and applies the
    # optimizer ONCE — effective batch = data.batch_size ×
    # grad_accum_steps, past what device memory fits in one pass.
    # Sync/quorum/timeout masking, LR-schedule pacing and the
    # BatchIterator cursor all see one step per application; the cursor
    # simply advances grad_accum_steps batches per step. 1 = off.
    grad_accum_steps: int = 1
    save_interval_steps: int = 200  # ≙ save_interval_secs=20 Supervisor autosave (:76)
    save_interval_secs: float = 0.0  # optional wall-clock cadence; 0 = step-based
    # The reference logs every step (:365-371); here metrics stay on
    # device and the canonical line flushes on this cadence so the step
    # loop issues no per-step host fetch at defaults.
    log_every_steps: int = 10
    save_results_period: int = 1000  # ≙ FLAGS.save_results_period (:56-57)
    summary_every_steps: int = 100  # ≙ save_summaries_secs (:78)
    keep_checkpoints: int = 5
    # Background-thread checkpoint writes (serialization + IO off the
    # hot loop); the final save always drains before run() returns.
    async_checkpoint: bool = True
    # Donation-safe DEVICE-side snapshot for async saves: a cadence
    # save dispatches an async copy of the state into fresh un-donated
    # buffers (enqueued on the device queue BEFORE the next step's
    # program, so the copy reads the buffers before donation reuses
    # them) and the D2H fetch + canonical-layout conversion move to the
    # checkpointer's worker thread — the step loop stalls only for the
    # copy dispatch, journaled as save_stall_ms on every save event.
    # Off: the historical sync fetch (state pulled to host in the train
    # loop before the worker gets it). Ignored when async_checkpoint is
    # off or the layout needs per-host sharded saves.
    async_snapshot: bool = True
    resume: bool = True  # ≙ Supervisor restore-if-present (:262)
    profile_steps: tuple[int, int] = (0, 0)  # (start, stop) jax.profiler window
    # Recurring trace dumps: every N steps, capture a one-window trace
    # into train_dir/profile/step_<k> — the always-on trace debugging
    # mode ≙ --timeline_logging's per-iteration Chrome traces
    # (src/distributed_train.py:354-358). 0 disables.
    trace_every_steps: int = 0
    # -- self-healing guards (train/loop.py) --------------------------
    # NaN/Inf loss guard: a nonfinite loss at a flush point rolls the
    # run back to the newest checkpoint whose params are finite instead
    # of letting the poison propagate into every later step and
    # checkpoint. Bounded: after nan_guard_max_rollbacks the run fails
    # loudly (a deterministic divergence would otherwise loop forever —
    # the guard exists for transient corruption, not bad hyperparams).
    nan_guard: bool = True
    nan_guard_max_rollbacks: int = 2
    # Deliberate per-step wall throttle (sleep after each step). 0 =
    # off (every real run). What the serving chaos trials use to make
    # a CPU-fast synthetic trainer publish checkpoints across a WALL
    # window long enough for serving replicas to boot, swap, and be
    # faulted mid-traffic — numerics are untouched, only the publish
    # cadence stretches.
    step_pace_ms: float = 0.0
    # Durability policy for durable artifacts, routed through the
    # storage shim (train/storage.py): "none" keeps the historical
    # buffered writes (rename-only atomicity), "data" fsyncs
    # checkpoint/manifest payload bytes before the publishing rename,
    # "full" additionally fsyncs digest sidecars, the pointer, JSONL
    # journal appends, and the parent dir after renames (the
    # power-cut-proof bound the checkpoint_durability bench prices).
    # Unknown values raise a typed ConfigError at trainer init.
    durability: str = "none"
    # Preemption handling: SIGTERM/SIGINT flush the AsyncCheckpointer
    # and stop the loop cleanly; the CLI then exits with
    # resumable_exit_code (default 75 = EX_TEMPFAIL) so a supervisor
    # can tell "resume me" from a crash. Handlers are only installed
    # when run() executes on the main thread.
    handle_preemption: bool = True
    resumable_exit_code: int = 75


@dataclass(frozen=True)
class ServeConfig:
    """Online serving tier (``servesvc/``): a replica that hot-follows
    the trainer's published checkpoints and serves inference over a
    local socket. Robustness knobs, not an endpoint zoo:

    * ``queue_depth`` is the ADMISSION bound — a full queue load-sheds
      with a typed ``overloaded`` reject immediately instead of
      queueing into unbounded latency.
    * ``max_batch`` is the compiled batch ceiling; pending requests are
      gathered into the smallest power-of-2 bucket that fits and padded
      to it, so the step function compiles once per bucket shape.
    * ``default_deadline_ms`` bounds a request that named no deadline;
      expired requests get a typed ``deadline_exceeded`` reject, never
      silent starvation.
    * ``poll_secs`` is the checkpoint hot-follow cadence (the swap
      itself is double-buffered: the in-flight batch finishes on the
      old weights, then the reference flips atomically).
    * ``precision_tier`` picks which published representation of the
      weights the replica PREFERS: ``fp32`` (the full-precision
      artifact — the historical path), or ``bf16`` / ``int8`` (the
      quantized tiers the publish-time pass writes into the
      digest-verified ``.quant`` sidecar next to each checkpoint,
      ``quant.publish_tiers``). A sidecar that is absent, torn, or
      missing the requested tier falls back to the full-precision
      artifact for that publish — journaled, never fatal, never served
      unverified.
    * ``compute_dtype`` overrides the dtype activations/matmuls run in
      on the SERVING replica only ("" = inherit the training-side
      resolution: ``precision.compute_dtype`` then
      ``model.compute_dtype``). Resolved through the shared
      ``effective_model_config`` seam so serving can run cheaper
      numerics than training without forking the model section.
    * ``tp_ranks`` — tensor-parallel replica width. 1 (default) keeps
      the historical single-chip replica. > 1 makes replica capacity a
      MESH SHAPE: the replica builds a ``(replica=1, model=tp_ranks)``
      serving mesh, sharded-loads each published checkpoint through
      the model's TP partition rules (``restore_for_topology``), and
      serves through GSPMD-partitioned compute — behind the UNCHANGED
      socket/failover/hot-swap/heartbeat contract. Launched as a
      process group (``launch serve --tp-ranks N``): rank 0 owns the
      socket, mesh, and serve.json; non-zero ranks are followers that
      digest-verify their weight shard per publish; the supervisor
      enforces die-as-a-unit (any rank exit kills and restarts the
      whole group — a half-dead TP group never serves). See
      ``servesvc/tp_group.py``.
    * ``tp_group_max_restarts`` / ``tp_group_poll_secs`` — group
      supervisor knobs: bounded whole-group restarts after a rank
      death, and the child-liveness poll cadence.
    * ``conn_read_timeout_s`` / ``conn_write_timeout_s`` — per-
      connection protocol deadlines: the TOTAL time a peer may take to
      deliver one request line (a slowloris or half-open peer costs
      one bounded stall, journaled as ``conn_abort``, never a wedged
      handler), and the ceiling on any single response write (a peer
      that stopped reading never wedges the batcher).
    * ``dedup_cache_size`` — bound of the per-replica idempotency
      cache (request id → final ok outcome). A retried request whose
      execution already completed here answers from the cache instead
      of double-executing — the exactly-once half of the network fault
      contract. 0 disables.
    """

    host: str = "127.0.0.1"
    port: int = 0            # 0 = ephemeral; the bound port lands in serve.json
    max_batch: int = 16
    queue_depth: int = 64
    batch_window_ms: float = 2.0   # gather window after the first request
    poll_secs: float = 0.25
    default_deadline_ms: float = 2000.0
    precision_tier: str = "fp32"   # fp32 | bf16 | int8
    compute_dtype: str = ""        # "" → precision/model resolution
    tp_ranks: int = 1              # >1 = tensor-parallel serving group
    tp_group_max_restarts: int = 3
    tp_group_poll_secs: float = 0.25
    conn_read_timeout_s: float = 5.0
    conn_write_timeout_s: float = 5.0
    dedup_cache_size: int = 256


# The serving-tier grammar: what ``serve.precision_tier`` accepts, and
# (minus fp32) what the quantization pass can publish.
SERVING_PRECISION_TIERS = ("fp32", "bf16", "int8")
QUANT_TIERS = ("bf16", "int8")

# Mid-generation weight-swap disciplines for the decode service.
DECODE_SWAP_POLICIES = ("pin", "restart")

# Cache-read implementations for the decode step: the dense full-table
# gather (the oracle) and the fused Pallas paged-attention kernel.
DECODE_ATTENTION_KERNELS = ("dense", "paged")


@dataclass(frozen=True)
class DecodeConfig:
    """Continuous-batching autoregressive decode (``servesvc/decode.py``)
    — the generation face of the serving tier. A decode replica holds
    ``decode_slots`` concurrently-generating sequences over ONE paged
    KV cache, so sequences of wildly different lengths share a single
    compiled decode shape; a slot is refilled the step its sequence
    finishes (EOS / max_tokens / deadline), never held for a padded
    round.

    * ``block_size`` / ``num_blocks`` — the paged cache geometry: K/V
      live in fixed-size blocks handed out by a free-list allocator
      (block 0 is the reserved null block idle slots write into), and
      each sequence owns a block table mapping its positions to
      blocks. Admission reserves every block a sequence can need
      (prompt + ``max_new_tokens``), so an admitted sequence can
      always run to completion — block pressure defers admission, it
      never kills a running generation.
    * ``max_prompt_len`` — prompts pad to power-of-2 buckets up to
      this (each bucket's prefill compiles once); longer prompts are a
      typed ``bad_request``.
    * ``max_new_tokens`` — the per-request generation ceiling (a
      request may ask for fewer, never more).
    * ``eos_token`` — generation stops when this token is sampled;
      -1 disables (sequences run to max_tokens).
    * ``temperature`` / ``top_k`` — default sampling knobs
      (``models.registry.sample_token``; temperature <= 0 = greedy
      argmax, deterministic). Requests may override per-request.
    * ``swap_policy`` — what a weight hot-swap does to sequences
      mid-generation: ``"pin"`` keeps each in-flight sequence on the
      params it started with until it finishes (new admissions use
      the new weights; at most a handful of param versions are live
      at once), ``"restart"`` re-prefills every in-flight sequence on
      the new weights (journaled per sequence as ``seq_restart`` —
      the causal license the ``decode_swap`` replay invariant
      requires whenever a sequence finishes on a different step than
      it started on).
    * ``attention_kernel`` — how the decode step reads the paged
      cache: ``"dense"`` (default) gathers each slot's full block
      table into a dense [slots, max_context, h, hd] view before
      attending — O(max context) traffic per token, and the oracle
      the parity tests pin; ``"paged"`` runs the fused Pallas kernel
      (``ops/pallas_paged_attention.py``) that walks the table
      in-kernel — O(actual context) per token. Numerics are pinned
      equal for live slots (tests/test_paged_attention.py).
    """

    decode_slots: int = 4
    block_size: int = 16
    num_blocks: int = 128
    max_prompt_len: int = 64
    max_new_tokens: int = 32
    eos_token: int = -1
    temperature: float = 0.0
    top_k: int = 0
    swap_policy: str = "pin"
    attention_kernel: str = "dense"  # dense | paged

    def validate(self) -> None:
        """Build-time validation (DecodeReplica construction): a bad
        knob is a typed ConfigError naming the constraint, not a shape
        error mid-generation."""
        if self.attention_kernel not in DECODE_ATTENTION_KERNELS:
            raise ConfigError(
                f"decode.attention_kernel={self.attention_kernel!r} is "
                f"not a known kernel; valid kernels: "
                f"{', '.join(DECODE_ATTENTION_KERNELS)}")
        if self.swap_policy not in DECODE_SWAP_POLICIES:
            raise ConfigError(
                f"decode.swap_policy={self.swap_policy!r} is not a "
                f"known policy; valid policies: "
                f"{', '.join(DECODE_SWAP_POLICIES)}")
        if self.decode_slots < 1:
            raise ConfigError(
                f"decode.decode_slots must be >= 1, got "
                f"{self.decode_slots}")
        if self.block_size < 1 or self.num_blocks < 2:
            raise ConfigError(
                f"decode.block_size must be >= 1 and decode.num_blocks "
                f">= 2 (block 0 is the reserved null block), got "
                f"block_size={self.block_size} "
                f"num_blocks={self.num_blocks}")
        if self.max_prompt_len < 1 or self.max_new_tokens < 1:
            raise ConfigError(
                "decode.max_prompt_len and decode.max_new_tokens must "
                f"be >= 1, got {self.max_prompt_len}/"
                f"{self.max_new_tokens}")
        need = self.max_blocks_per_seq()
        if self.num_blocks - 1 < need:
            raise ConfigError(
                f"decode.num_blocks={self.num_blocks} cannot hold even "
                f"one sequence: max_prompt_len + max_new_tokens = "
                f"{self.max_prompt_len + self.max_new_tokens} tokens "
                f"need {need} blocks of {self.block_size} (+1 reserved "
                "null block)")

    def max_blocks_per_seq(self) -> int:
        """Blocks one sequence can ever need (prompt + generation) —
        the fixed block-table width every compiled decode shape uses."""
        total = self.max_prompt_len + self.max_new_tokens
        return -(-total // self.block_size)


@dataclass(frozen=True)
class QuantConfig:
    """Post-training quantization at checkpoint-publish time
    (``quant/`` — ROADMAP item 5, the serving face of the
    storage-vs-compute dtype axis ``PrecisionConfig`` opened for
    training).

    ``publish_tiers``: comma-separated tiers to write into a
    ``ckpt-<step>.quant.msgpack`` sidecar next to every published
    checkpoint — ``"int8"``, ``"bf16"``, or ``"int8,bf16"``; "" = off
    (the default: no sidecars, byte-identical publish behavior). The
    int8 tier stores per-channel symmetric int8 weights + float32
    scales (weight leaves with ndim ≥ 2; 1-D biases/norms stay fp32);
    the bf16 tier stores a straight bf16 cast. The full-precision
    artifact and its digest are BYTE-UNCHANGED by publishing — the
    sidecar is purely additive, with its own sha256 digest sidecar
    under the same atomic-write/torn-read contract.

    ``calibration_examples``: how many held-out (test-split) examples
    the pass runs through the fp32 and quantized graphs at publish
    time — it records the observed activation range and the top-1
    agreement in the sidecar metadata, and REFUSES to publish a tier
    whose calibration agreement drops more than ``parity_epsilon``
    below the full-precision predictions (speed must never silently
    buy wrongness; the refusal is logged and the serving tier falls
    back to fp32 for that publish). 0 disables calibration (tiers
    publish unchecked — for tests and trusted recipes only).
    """

    publish_tiers: str = ""        # "" | "int8" | "bf16" | "int8,bf16"
    calibration_examples: int = 128
    parity_epsilon: float = 0.02

    def resolved_publish_tiers(self) -> tuple[str, ...]:
        """The validated tier tuple (the ``optim`` pattern: a bad knob
        is a typed ConfigError naming the valid set at build time, not
        a KeyError mid-publish)."""
        if not self.publish_tiers:
            return ()
        tiers = tuple(t.strip() for t in self.publish_tiers.split(",")
                      if t.strip())
        for t in tiers:
            if t not in QUANT_TIERS:
                raise ConfigError(
                    f"quant.publish_tiers names unknown tier {t!r}; "
                    f"valid tiers: {', '.join(QUANT_TIERS)} "
                    "(fp32 is the artifact itself, never a sidecar "
                    "tier)")
        return tiers


@dataclass(frozen=True)
class EvalConfig:
    """Continuous evaluator (≙ src/nn_eval.py:36-45)."""

    eval_interval_secs: float = 1.0
    eval_dir: str = "/tmp/dmt_eval"
    # 0 → auto: static batches of ≤4096 covering the full split. The
    # reference instead builds its graph at batch = the whole 10k test
    # set (nn_eval.py:121-122) — fixed-shape tiled batches are the
    # TPU-native answer (no dynamic-shape recompile, bounded memory).
    eval_batch_size: int = 0
    run_once: bool = False
    max_evals: int = 0  # 0 = unbounded


@dataclass(frozen=True)
class BrokerConfig:
    """Resource broker (``launch/broker.py``) — demand-driven
    autoscaling across one mixed trainer + serving roster.

    The broker reads a rolling window of journaled pressure signals
    (loadgen ``window`` snapshots, replica heartbeat queue/KV fields,
    trainer step rate) and trades roster slots through the cluster's
    existing reconfigure verb. Every threshold here is a PAIR — a high
    water mark that licenses scale-up and a strictly lower low water
    mark all signals must drop below before scale-down — because a
    single threshold flaps: a signal hovering at the mark would grow
    and shrink the roster on alternate polls. ``cooldown_s`` is the
    second anti-flap guard: after any roster change the broker holds
    its fire for that long no matter what the window says.

    * ``p99_high_ms`` / ``p99_low_ms`` — serving p99 latency marks.
    * ``reject_high`` / ``reject_low`` — overloaded-reject-rate marks
      (fraction of terminal outcomes in the window).
    * ``ttft_high_ms`` / ``ttft_low_ms`` — decode time-to-first-token
      p99 marks (ignored for windows with no TTFT data).
    * ``queue_high`` / ``queue_low`` — replica queue occupancy marks
      as a fraction of the admission bound (``serve.queue_depth``).
    * ``kv_free_low`` / ``kv_free_high`` — KV block-pool FREE fraction:
      scale up when free blocks fall BELOW the low mark (pool pressure
      defers admissions), scale down only once back above the high.
    * ``min_serve_replicas`` / ``max_serve_replicas`` and
      ``min_train_workers`` / ``max_train_workers`` — hard roster
      bounds the broker never crosses, whatever the signals say.
    * ``window_s`` — how much history a signal snapshot covers (also
      the loadgen snapshot window).
    * ``poll_secs`` — broker control-loop cadence.
    * ``settle_timeout_s`` — how long a begun roster change may take to
      report new capacity live before the broker journals an error.
    """

    poll_secs: float = 1.0
    window_s: float = 10.0
    cooldown_s: float = 15.0
    p99_high_ms: float = 500.0
    p99_low_ms: float = 150.0
    reject_high: float = 0.05
    reject_low: float = 0.005
    ttft_high_ms: float = 500.0
    ttft_low_ms: float = 150.0
    queue_high: float = 0.8
    queue_low: float = 0.2
    kv_free_low: float = 0.10
    kv_free_high: float = 0.50
    min_serve_replicas: int = 1
    max_serve_replicas: int = 3
    min_train_workers: int = 1
    max_train_workers: int = 8
    settle_timeout_s: float = 60.0

    def validate(self) -> None:
        """Build-time validation (broker construction): a bad knob is
        a typed ConfigError naming the constraint, not a roster that
        flaps or a bound violated mid-campaign."""
        for name, hi, lo in (("p99", self.p99_high_ms, self.p99_low_ms),
                             ("reject", self.reject_high,
                              self.reject_low),
                             ("ttft", self.ttft_high_ms,
                              self.ttft_low_ms),
                             ("queue", self.queue_high,
                              self.queue_low)):
            if not hi > lo >= 0:
                raise ConfigError(
                    f"broker.{name} marks must satisfy high > low >= 0 "
                    f"(hysteresis needs a dead band), got high={hi} "
                    f"low={lo}")
        if not 0 <= self.kv_free_low < self.kv_free_high <= 1:
            raise ConfigError(
                "broker.kv_free marks must satisfy 0 <= low < high "
                f"<= 1, got low={self.kv_free_low} "
                f"high={self.kv_free_high}")
        if self.min_serve_replicas < 1:
            raise ConfigError(
                "broker.min_serve_replicas must be >= 1 (traffic must "
                f"keep flowing), got {self.min_serve_replicas}")
        if self.max_serve_replicas < self.min_serve_replicas:
            raise ConfigError(
                f"broker.max_serve_replicas={self.max_serve_replicas} "
                f"< min_serve_replicas={self.min_serve_replicas}")
        if self.min_train_workers < 1:
            raise ConfigError(
                "broker.min_train_workers must be >= 1, got "
                f"{self.min_train_workers}")
        if self.max_train_workers < self.min_train_workers:
            raise ConfigError(
                f"broker.max_train_workers={self.max_train_workers} "
                f"< min_train_workers={self.min_train_workers}")
        if self.poll_secs <= 0 or self.window_s <= 0:
            raise ConfigError(
                "broker.poll_secs and broker.window_s must be > 0, "
                f"got {self.poll_secs}/{self.window_s}")
        if self.cooldown_s < 0 or self.settle_timeout_s <= 0:
            raise ConfigError(
                "broker.cooldown_s must be >= 0 and "
                "broker.settle_timeout_s > 0, got "
                f"{self.cooldown_s}/{self.settle_timeout_s}")


# Dtypes an activations/matmul override may name. The model section's
# own compute_dtype predates this list and stays unvalidated here (its
# consumers jnp.dtype() it at build); the OVERRIDE knobs
# (precision.compute_dtype, serve.compute_dtype) are validated at the
# shared resolution point so a typo is a typed ConfigError naming the
# valid set — the ``optim`` validation pattern — not a downstream
# jnp.dtype TypeError in whichever consumer resolves first.
_VALID_COMPUTE_DTYPES = ("float32", "bfloat16", "float16", "float64")


def _checked_compute_dtype(value: str, where: str) -> str:
    if value not in _VALID_COMPUTE_DTYPES:
        raise ConfigError(
            f"{where}={value!r} is not a known compute dtype; valid "
            f"dtypes: {', '.join(_VALID_COMPUTE_DTYPES)}")
    return value


def effective_model_config(cfg: "ExperimentConfig",
                           serving: bool = False) -> ModelConfig:
    """The model section with the compute-dtype overrides applied —
    the ONE resolution every model-building consumer (Trainer,
    evaluator, serving replica) goes through, so the precision/serve
    sections can't drift from the model section between tiers.

    Resolution order: ``serve.compute_dtype`` (serving consumers only,
    ``serving=True``) → ``precision.compute_dtype`` → the model
    section's own knob. Unknown dtype strings on either override raise
    a typed :class:`ConfigError` naming the valid set."""
    dtype = ""
    if serving and cfg.serve.compute_dtype:
        dtype = _checked_compute_dtype(cfg.serve.compute_dtype,
                                       "serve.compute_dtype")
    elif cfg.precision.compute_dtype:
        dtype = _checked_compute_dtype(cfg.precision.compute_dtype,
                                       "precision.compute_dtype")
    if not dtype:
        return cfg.model
    return dataclasses.replace(cfg.model, compute_dtype=dtype)


@dataclass(frozen=True)
class ExperimentConfig:
    name: str = "default"
    data: DataConfig = field(default_factory=DataConfig)
    model: ModelConfig = field(default_factory=ModelConfig)
    optim: OptimConfig = field(default_factory=OptimConfig)
    sync: SyncConfig = field(default_factory=SyncConfig)
    mesh: MeshConfig = field(default_factory=MeshConfig)
    parallel: ParallelConfig = field(default_factory=ParallelConfig)
    precision: PrecisionConfig = field(default_factory=PrecisionConfig)
    compile: CompileConfig = field(default_factory=CompileConfig)
    train: TrainConfig = field(default_factory=TrainConfig)
    eval: EvalConfig = field(default_factory=EvalConfig)
    serve: ServeConfig = field(default_factory=ServeConfig)
    decode: DecodeConfig = field(default_factory=DecodeConfig)
    quant: QuantConfig = field(default_factory=QuantConfig)
    broker: BrokerConfig = field(default_factory=BrokerConfig)

    # ---- construction helpers -------------------------------------------------

    def replace(self, **sections: Any) -> "ExperimentConfig":
        return dataclasses.replace(self, **sections)

    def override(self, overrides: dict[str, Any]) -> "ExperimentConfig":
        """Apply dotted-path overrides, e.g. {"sync.mode": "quorum"}."""
        cfg = self
        for path, value in overrides.items():
            cfg = _set_path(cfg, path.split("."), value)
        return cfg

    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "ExperimentConfig":
        return _build(cls, dict(d))

    @classmethod
    def from_file(cls, path: str | Path) -> "ExperimentConfig":
        """Load a config from JSON or a Python-literal file.

        The reference ``eval()``s its cfg files (tools/benchmark.py:15) —
        a known quirk we deliberately do not replicate (SURVEY §7):
        literals only.
        """
        text = Path(path).read_text()
        try:
            d = json.loads(text)
        except json.JSONDecodeError:
            try:
                d = ast.literal_eval(text)
            except (ValueError, SyntaxError) as e:
                raise ConfigError(f"{path}: not valid JSON or a Python literal: {e}")
        if not isinstance(d, dict):
            raise ConfigError(f"{path}: config must be a dict, got {type(d).__name__}")
        return cls.from_dict(d)

    def save(self, path: str | Path) -> None:
        Path(path).write_text(json.dumps(self.to_dict(), indent=2, sort_keys=True))


def _build(cls: type, d: dict[str, Any]) -> Any:
    if not dataclasses.is_dataclass(cls):
        return d
    fields = {f.name: f for f in dataclasses.fields(cls)}
    kwargs = {}
    for key, value in d.items():
        if key not in fields:
            raise ConfigError(f"unknown config key {key!r} for {cls.__name__}; "
                              f"valid keys: {sorted(fields)}")
        ftype = fields[key].type
        sub = _SECTION_TYPES.get((cls.__name__, key))
        if sub is not None and isinstance(value, dict):
            kwargs[key] = _build(sub, value)
        elif ftype in ("tuple[int, int]",) and isinstance(value, list):
            kwargs[key] = tuple(value)
        else:
            kwargs[key] = value
    return cls(**kwargs)


_SECTION_TYPES = {
    ("ExperimentConfig", "data"): DataConfig,
    ("ExperimentConfig", "model"): ModelConfig,
    ("ExperimentConfig", "optim"): OptimConfig,
    ("ExperimentConfig", "sync"): SyncConfig,
    ("ExperimentConfig", "mesh"): MeshConfig,
    ("ExperimentConfig", "parallel"): ParallelConfig,
    ("ExperimentConfig", "precision"): PrecisionConfig,
    ("ExperimentConfig", "compile"): CompileConfig,
    ("ExperimentConfig", "train"): TrainConfig,
    ("ExperimentConfig", "eval"): EvalConfig,
    ("ExperimentConfig", "serve"): ServeConfig,
    ("ExperimentConfig", "decode"): DecodeConfig,
    ("ExperimentConfig", "quant"): QuantConfig,
    ("ExperimentConfig", "broker"): BrokerConfig,
}


def _set_path(obj: Any, path: list[str], value: Any) -> Any:
    if not dataclasses.is_dataclass(obj):
        raise ConfigError(f"cannot descend into non-config value at {'.'.join(path)}")
    head, rest = path[0], path[1:]
    fields = {f.name: f for f in dataclasses.fields(obj)}
    if head not in fields:
        raise ConfigError(f"unknown config key {head!r} on {type(obj).__name__}")
    if rest:
        new_child = _set_path(getattr(obj, head), rest, value)
        return dataclasses.replace(obj, **{head: new_child})
    current = getattr(obj, head)
    if dataclasses.is_dataclass(current) and isinstance(value, dict):
        # whole-section override: build the section dataclass, don't
        # store a raw dict into the frozen config
        value = _build(type(current), value)
    elif current is not None and not isinstance(value, type(current)):
        value = _coerce(value, type(current))
    return dataclasses.replace(obj, **{head: value})


def _coerce(value: Any, target: type) -> Any:
    if target is bool:
        if isinstance(value, str):
            if value.lower() in ("true", "1", "yes"):
                return True
            if value.lower() in ("false", "0", "no"):
                return False
        return bool(value)
    if target in (int, float, str):
        return target(value)
    if target is tuple and isinstance(value, (list, str)):
        if isinstance(value, str):
            value = ast.literal_eval(value)
        return tuple(value)
    return value


def parse_cli_overrides(argv: list[str]) -> dict[str, Any]:
    """Parse ``section.key=value`` CLI args (values literal-eval'd when possible)."""
    out: dict[str, Any] = {}
    for arg in argv:
        if "=" not in arg:
            raise ConfigError(f"override {arg!r} must look like section.key=value")
        key, _, raw = arg.partition("=")
        try:
            out[key] = ast.literal_eval(raw)
        except (ValueError, SyntaxError):
            out[key] = raw
    return out
