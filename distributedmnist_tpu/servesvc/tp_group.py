"""Tensor-parallel serving process groups.

``serve.tp_ranks > 1`` turns one serving replica into a small process
group behind the UNCHANGED socket/failover/hot-swap/heartbeat
contract:

* **rank 0** is the real replica — it owns the socket, the serving
  mesh (``replica=1 × model=tp_ranks``, built inside
  ``ServingReplica``), ``serve.json`` and ``serve_log.jsonl`` in the
  worker's own dir. Clients, the chaos harness, and the serving
  invariants see exactly the single-chip replica surface.
* **ranks 1..N-1** are follower ranks: each hot-follows the same
  publish dir, digest-verifies every checkpoint through the identical
  ``restore_checkpoint`` machinery, and journals a ``shard_verify``
  record carrying the sha256 of ITS model-axis shard of the new params
  — the shard-wise staging evidence for hot-swap under TP. Followers
  write under ``serve_dir/rank<r>/`` and heartbeat like any worker.
* the **supervisor** (this module) spawns all ranks, journals the
  group lifecycle to ``group_log.jsonl`` (``group_start`` /
  ``rank_spawn`` / ``rank_exit`` / ``group_down`` / ``group_restart``
  / ``group_stop`` — schema-declared in ``obsv/schema.py``), and
  enforces **die-as-a-unit**: any rank exiting outside a graceful stop
  kills every other rank and restarts the whole group (bounded by
  ``serve.tp_group_max_restarts``). A half-dead TP group never serves
  — the ``serve_group`` replay invariant checks exactly this.

On a single CPU host the ranks cannot join one cross-process XLA
collective (the CPU backend has no multiprocess computations), so rank
0 carries the whole sharded mesh on virtual devices and followers
exercise the group-lifecycle + shard-verification contract; on a
multi-host accelerator pod the same layout puts real chips behind each
rank. The supervision, journaling, and invariant surface are identical
either way — that is the point of keeping the replica contract shape-
agnostic (TF-Replicator's resource-shape-agnostic replicas).
"""

from __future__ import annotations

import hashlib
import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path
from typing import Callable

from ..core.log import JsonlSink, get_logger

logger = get_logger("tp_group")

_KILL_WAIT_S = 10.0


def _set_pdeathsig():
    """Child preexec hook: die with the supervisor. A SIGKILLed
    supervisor must not orphan half a TP group into exactly the
    half-dead state the group exists to prevent (linux only; a no-op
    fallback elsewhere)."""
    try:
        import ctypes
        libc = ctypes.CDLL(None, use_errno=True)
        PR_SET_PDEATHSIG = 1
        libc.prctl(PR_SET_PDEATHSIG, signal.SIGKILL)
    except Exception:
        pass


class ServeGroup:
    """Spawn + supervise the ranks of one TP serving replica.

    ``spawn_fn(rank, attempt) -> subprocess.Popen`` builds one rank
    process (injectable so the die-as-a-unit logic is testable without
    booting jax); the CLI wires :func:`default_spawn_fn`.
    """

    def __init__(self, serve_dir: str | Path, ranks: int,
                 spawn_fn: Callable[[int, int], subprocess.Popen], *,
                 max_restarts: int = 3, poll_secs: float = 0.25):
        if ranks < 2:
            raise ValueError(f"a TP group needs >= 2 ranks, got {ranks}")
        self.serve_dir = Path(serve_dir)
        self.serve_dir.mkdir(parents=True, exist_ok=True)
        self.ranks = ranks
        self.spawn_fn = spawn_fn
        self.max_restarts = max_restarts
        self.poll_secs = poll_secs
        self.attempt = 0
        self.procs: dict[int, subprocess.Popen] = {}
        self._stopping = False
        self._log = JsonlSink(self.serve_dir / "group_log.jsonl")

    def _journal(self, record: dict) -> None:
        self._log.write({"event": "serve", "time": time.time(), **record})

    def _write_group_json(self) -> None:
        """Atomic group roster (pids per rank) — what the chaos/bench
        side reads to target a specific rank."""
        path = self.serve_dir / "group.json"
        tmp = path.with_suffix(".tmp")
        tmp.write_text(json.dumps({
            "ranks": self.ranks, "attempt": self.attempt,
            "supervisor_pid": os.getpid(),
            "pids": {str(r): p.pid for r, p in self.procs.items()}}))
        tmp.replace(path)

    def start(self) -> None:
        self._spawn_all()

    def _spawn_all(self) -> None:
        self._journal({"action": "group_start", "ranks": self.ranks,
                       "attempt": self.attempt})
        self.procs = {}
        for r in range(self.ranks):
            p = self.spawn_fn(r, self.attempt)
            self.procs[r] = p
            self._journal({"action": "rank_spawn", "rank": r,
                           "pid": p.pid})
        self._write_group_json()

    def _kill_all(self, sig=signal.SIGKILL) -> None:
        for p in self.procs.values():
            if p.poll() is None:
                try:
                    p.send_signal(sig)
                except OSError:
                    pass
        deadline = time.time() + _KILL_WAIT_S
        for p in self.procs.values():
            while p.poll() is None and time.time() < deadline:
                time.sleep(0.05)

    def _down(self, dead_rank: int, rc) -> None:
        """Die-as-a-unit: one rank is gone, so the whole group goes —
        a TP replica with a missing shard must never keep serving."""
        self._journal({"action": "rank_exit", "rank": dead_rank,
                       "pid": self.procs[dead_rank].pid, "rc": rc})
        self._kill_all()
        # rank 0's endpoint is dead with the group: drop the stale
        # advertisement so client discovery stops routing here until
        # the restarted group re-publishes it
        try:
            (self.serve_dir / "serve.json").unlink()
        except OSError:
            pass
        self._journal({"action": "group_down",
                       "reason": f"rank {dead_rank} exited (rc={rc})",
                       "ranks": self.ranks, "rank": dead_rank})

    def step(self) -> bool:
        """One supervision tick; returns False when the group is
        permanently over (restart budget exhausted or stopping)."""
        for r, p in self.procs.items():
            rc = p.poll()
            if rc is None or self._stopping:
                continue
            self._down(r, rc)
            if self.attempt >= self.max_restarts:
                self._journal({"action": "group_stop",
                               "ranks": self.ranks})
                return False
            self.attempt += 1
            backoff = min(2.0, 0.25 * self.attempt)
            self._journal({"action": "group_restart",
                           "attempt": self.attempt,
                           "backoff_s": backoff})
            time.sleep(backoff)
            self._spawn_all()
            return True
        return not self._stopping

    def stop(self) -> None:
        """Graceful whole-group stop: SIGTERM rank 0 first so it
        drains in-flight work (its own serve_forever contract), then
        the followers; stragglers are killed."""
        self._stopping = True
        for r in sorted(self.procs):
            p = self.procs[r]
            if p.poll() is None:
                try:
                    p.send_signal(signal.SIGTERM)
                except OSError:
                    pass
        deadline = time.time() + _KILL_WAIT_S
        for p in self.procs.values():
            while p.poll() is None and time.time() < deadline:
                time.sleep(0.05)
        self._kill_all()
        self._journal({"action": "group_stop", "ranks": self.ranks})

    def run_forever(self) -> None:
        def _on_term(signum, frame):
            self._stopping = True
        try:
            signal.signal(signal.SIGTERM, _on_term)
            signal.signal(signal.SIGINT, _on_term)
        except ValueError:
            pass  # not the main thread (tests)
        self.start()
        while self.step():
            time.sleep(self.poll_secs)
        if self._stopping:
            self.stop()


def default_spawn_fn(base_argv: list[str], serve_dir: str | Path,
                     ranks: int) -> Callable[[int, int], subprocess.Popen]:
    """Rank-process factory for the CLI: re-invoke ``launch serve``
    with the SAME user flags plus ``--tp-rank r`` (rank 0 becomes the
    real replica, others the followers) and a per-rank serve dir
    (rank 0 keeps the group's dir — the socket contract's surface)."""
    serve_dir = Path(serve_dir)
    argv = []
    skip = False
    for tok in base_argv:
        if skip:
            skip = False
            continue
        if tok in ("--serve-dir", "--tp-ranks", "--tp-rank"):
            skip = True
            continue
        if tok.startswith(("--serve-dir=", "--tp-ranks=", "--tp-rank=")):
            continue
        argv.append(tok)

    def _child_env() -> dict:
        """On a CPU host with fewer ambient devices than ranks, rank 0
        needs its virtual-device count forced BEFORE its XLA backend
        initializes (post-hoc re-forcing needs jax >= 0.4.38), so the
        supervisor plants the flag in the child env; on an accelerator
        pod the ranks see real chips and the env passes through."""
        env = dict(os.environ)
        try:
            import re

            import jax
            if (jax.default_backend() == "cpu"
                    and len(jax.devices()) < ranks):
                flag = (f"--xla_force_host_platform_device_count="
                        f"{ranks}")
                flags = env.get("XLA_FLAGS", "")
                if "xla_force_host_platform_device_count" in flags:
                    flags = re.sub(
                        r"--xla_force_host_platform_device_count=\d+",
                        flag, flags)
                else:
                    flags = (flags + " " + flag).strip()
                env["XLA_FLAGS"] = flags
        except Exception:
            pass
        return env

    def spawn(rank: int, attempt: int) -> subprocess.Popen:
        rank_dir = serve_dir if rank == 0 else serve_dir / f"rank{rank}"
        cmd = ([sys.executable, "-m", "distributedmnist_tpu.launch"]
               + argv + ["--serve-dir", str(rank_dir),
                         "--tp-ranks", str(ranks),
                         "--tp-rank", str(rank)])
        return subprocess.Popen(
            cmd, env=_child_env(),
            preexec_fn=_set_pdeathsig if os.name == "posix" else None)

    return spawn


# ---------------------------------------------------------------------------
# Follower ranks: shard-wise digest verification of every publish
# ---------------------------------------------------------------------------

def _model_axis_dim(spec) -> int | None:
    """The dim a PartitionSpec shards over the serving mesh's model
    axis, or None (replicated leaf)."""
    if spec is None:
        return None
    for dim, entry in enumerate(tuple(spec)):
        names = entry if isinstance(entry, (tuple, list)) else (entry,)
        if "model" in [n for n in names if n is not None]:
            return dim
    return None


def rank_shard_digest(params, specs, rank: int, ranks: int) -> str:
    """sha256 over THIS rank's model-axis shard of every param leaf —
    leaves in deterministic tree order, sharded dims split exactly the
    way the TP layout splits them (``np.array_split`` matches the even
    split the mesh uses; replicated leaves contribute whole). This is
    the identity of the bytes rank ``rank`` holds after a sharded
    load, so followers verifying it per publish IS the shard-wise half
    of the hot-swap digest discipline."""
    import numpy as np
    import jax

    from jax.sharding import PartitionSpec

    h = hashlib.sha256()
    leaves_p, treedef_p = jax.tree.flatten(params)
    leaves_s, _ = jax.tree.flatten(
        specs, is_leaf=lambda x: x is None or isinstance(x, PartitionSpec))
    if len(leaves_s) != len(leaves_p):
        # spec tree shape drifted from the param tree: hash whole
        # leaves (still a digest, just not shard-scoped) rather than
        # guessing an alignment
        leaves_s = [None] * len(leaves_p)
    for leaf, spec in zip(leaves_p, leaves_s):
        arr = np.asarray(leaf)
        dim = _model_axis_dim(spec)
        if dim is not None and arr.ndim > dim:
            arr = np.array_split(arr, ranks, axis=dim)[rank]
        h.update(np.ascontiguousarray(arr).tobytes())
    return h.hexdigest()


def run_rank_follower(train_dir: str | Path, serve_dir: str | Path,
                      rank: int, ranks: int, *,
                      poll_secs: float = 0.25) -> None:
    """A non-zero rank of a TP serving group: no socket, no mesh —
    hot-follow the publish dir, digest-verify each checkpoint through
    the same restore machinery as rank 0, journal the sha256 of this
    rank's model-axis param shard (``shard_verify``), heartbeat, park.

    Runs until killed (the supervisor owns this process's lifetime —
    SIGTERM from a graceful group stop just exits)."""
    import jax

    from ..core.config import effective_model_config
    from ..core.mesh import MeshConfig, make_topology
    from ..models.registry import get_model
    from ..parallel.api import init_train_state
    from ..train import checkpoint as ckpt

    train_dir = Path(train_dir)
    serve_dir = Path(serve_dir)
    serve_dir.mkdir(parents=True, exist_ok=True)
    cfg = ckpt.wait_for_run_config(train_dir)
    topo = make_topology(MeshConfig(num_replicas=1),
                         devices=jax.devices()[:1])
    model = get_model(effective_model_config(cfg, serving=True))
    template = init_train_state(model, cfg, topo)
    tp_specs = (model.tp_param_specs("model")
                if getattr(model, "tp_param_specs", None) else None)

    log = JsonlSink(serve_dir / "serve_log.jsonl")
    heartbeat = JsonlSink(serve_dir / "train_log.jsonl")
    verified = {"count": 0}

    def journal(rec: dict) -> None:
        log.write({"event": "serve", "time": time.time(), "rank": rank,
                   **rec})

    stop = {"flag": False}

    def _on_term(signum, frame):
        stop["flag"] = True

    try:
        signal.signal(signal.SIGTERM, _on_term)
        signal.signal(signal.SIGINT, _on_term)
    except ValueError:
        pass

    follower = ckpt.CheckpointFollower(train_dir)

    def read(step: int):
        restored = ckpt.restore_checkpoint(
            train_dir, template, None,
            on_event=lambda rec: journal(
                {"action": "follow_" + rec.get("action", "?"),
                 **{k: v for k, v in rec.items()
                    if k not in ("layer", "action")}}))
        if restored is None:
            return None
        state, _, at_step = restored
        digest = rank_shard_digest(state.params, tp_specs, rank, ranks)
        journal({"action": "shard_verify", "rank": rank, "step": at_step,
                 "digest": digest,
                 "source_digest": ckpt.artifact_digest(train_dir,
                                                       at_step)})
        verified["count"] += 1
        return at_step

    last_hb = -1
    while not stop["flag"]:
        follower.poll(read)
        # liveness counter = publishes shard-verified (the heartbeat
        # ``step`` contract is "monotone progress", same as the serving
        # replica's terminal count) — write-on-change only
        if verified["count"] != last_hb:
            last_hb = verified["count"]
            heartbeat.write({"event": "heartbeat", "step": last_hb,
                             "time": time.time(), "tp_rank": rank})
        time.sleep(poll_secs)
