"""Paged KV cache for continuous-batching autoregressive decode.

The decode service's memory manager: K/V for every in-flight sequence
live in ONE pair of device arrays shaped ``[layers, num_blocks,
block_size, heads, head_dim]``, carved into fixed-size blocks a
free-list allocator hands out. Each sequence owns a **block table** —
a fixed-width ``[max_blocks_per_seq]`` int32 map from its position
range to blocks — so the compiled decode step reads any mix of
sequence lengths through one gather, and finishing a 7-token sequence
returns its blocks to the pool the same step a 90-token neighbor keeps
generating. This is what lets wildly different lengths share a single
compiled decode shape instead of bucket-padding rounds.

Block 0 is the **reserved null block**: idle decode slots point their
whole table (and their writes) at it, so the fixed-shape step never
needs a branch — garbage lands in a block no sequence owns.

Invariants the allocator maintains (property-tested in
tests/test_kv_cache.py): a block is never assigned to two live
sequences, alloc+free conserves the pool exactly, and reading a
sequence back through its block table reproduces a dense reference
cache byte-for-byte.

Allocation policy: admission reserves EVERY block a sequence can need
(prompt + max_new_tokens) up front, so an admitted sequence always
runs to completion — block pressure defers admission (the request
waits, bounded by its deadline), it never kills a running generation.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

NULL_BLOCK = 0


class BlockAllocator:
    """Free-list allocator over ``num_blocks`` fixed-size blocks.

    Block 0 (:data:`NULL_BLOCK`) is reserved and never handed out.
    ``alloc`` is all-or-nothing: a request the pool cannot satisfy
    returns None and takes nothing (the caller defers admission)."""

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError(
                f"need >= 2 blocks (one is the reserved null block), "
                f"got {num_blocks}")
        self.num_blocks = num_blocks
        # LIFO free list: recently-freed blocks are re-used first
        # (their cache lines are the warmest)
        self._free: list[int] = list(range(num_blocks - 1, 0, -1))
        self._in_use: set[int] = set()

    @property
    def available(self) -> int:
        return len(self._free)

    @property
    def in_use(self) -> frozenset[int]:
        return frozenset(self._in_use)

    def alloc(self, n: int) -> tuple[int, ...] | None:
        """n blocks, or None (and no change) when the pool is short."""
        if n < 0:
            raise ValueError(f"cannot allocate {n} blocks")
        if n > len(self._free):
            return None
        got = tuple(self._free.pop() for _ in range(n))
        self._in_use.update(got)
        return got

    def free(self, blocks) -> None:
        for b in blocks:
            if b not in self._in_use:
                raise ValueError(
                    f"double free / foreign block {b} (in_use="
                    f"{sorted(self._in_use)})")
            self._in_use.remove(b)
            self._free.append(b)


def write_prompt_kv(k_cache: jax.Array, v_cache: jax.Array,
                    ks: jax.Array, vs: jax.Array,
                    block_table: jax.Array, length: jax.Array, *,
                    block_size: int) -> tuple[jax.Array, jax.Array]:
    """Scatter one sequence's prefill K/V into its blocks.

    ``ks``/``vs`` [L, s_pad, h, hd] (the prefill export for ONE
    sequence, padded to its prompt bucket); positions ``< length`` land
    at ``block_table[pos // block_size]`` offset ``pos % block_size``,
    padding positions are routed to the null block. jit this once per
    prompt bucket shape."""
    s_pad = ks.shape[1]
    pos = jnp.arange(s_pad)
    blk_ids = jnp.where(pos < length,
                        block_table[pos // block_size], NULL_BLOCK)
    offs = pos % block_size
    k_cache = k_cache.at[:, blk_ids, offs].set(ks.astype(k_cache.dtype))
    v_cache = v_cache.at[:, blk_ids, offs].set(vs.astype(v_cache.dtype))
    return k_cache, v_cache


class PagedKVCache:
    """The device arrays + allocator + block-table bookkeeping.

    ``k``/``v`` are functional jax arrays — every write goes through a
    jitted scatter that returns the new arrays and is reassigned here
    (single-writer: the decode loop thread)."""

    def __init__(self, num_layers: int, num_blocks: int, block_size: int,
                 num_heads: int, head_dim: int,
                 max_blocks_per_seq: int, dtype=jnp.float32):
        self.block_size = block_size
        self.max_blocks_per_seq = max_blocks_per_seq
        self.allocator = BlockAllocator(num_blocks)
        shape = (num_layers, num_blocks, block_size, num_heads, head_dim)
        self.k = jnp.zeros(shape, dtype)
        self.v = jnp.zeros(shape, dtype)
        import functools
        # write_prompt's caller rebinds self.k/self.v to the outputs —
        # donate the cache operands so the scatter updates in place
        self._write = jax.jit(functools.partial(
            write_prompt_kv, block_size=block_size),
            donate_argnums=(0, 1))

    def alloc_sequence(self, total_len: int) -> np.ndarray | None:
        """Reserve blocks for a sequence of up to ``total_len`` tokens;
        returns its fixed-width block table (padded with the null
        block) or None under block pressure (nothing taken)."""
        need = -(-total_len // self.block_size)
        if need > self.max_blocks_per_seq:
            raise ValueError(
                f"{total_len} tokens need {need} blocks > "
                f"max_blocks_per_seq={self.max_blocks_per_seq}")
        got = self.allocator.alloc(need)
        if got is None:
            return None
        table = np.full((self.max_blocks_per_seq,), NULL_BLOCK,
                        dtype=np.int32)
        table[:need] = got
        return table

    def free_sequence(self, block_table: np.ndarray) -> None:
        self.allocator.free(int(b) for b in block_table
                            if int(b) != NULL_BLOCK)

    def write_prompt(self, block_table: np.ndarray, ks, vs,
                     length: int) -> None:
        """Install one sequence's prefill K/V (``ks``/``vs``
        [L, s_pad, h, hd])."""
        self.k, self.v = self._write(self.k, self.v, ks, vs,
                                     jnp.asarray(block_table),
                                     jnp.asarray(length))

    def gather_dense(self, block_table: np.ndarray,
                     length: int) -> tuple[np.ndarray, np.ndarray]:
        """Read a sequence back as dense [L, length, h, hd] arrays —
        the reference view the property tests compare against (host
        path, not used by the decode step)."""
        k = np.asarray(jax.device_get(self.k))
        v = np.asarray(jax.device_get(self.v))
        ks, vs = [], []
        for pos in range(length):
            b = int(block_table[pos // self.block_size])
            o = pos % self.block_size
            ks.append(k[:, b, o])
            vs.append(v[:, b, o])
        return np.stack(ks, axis=1), np.stack(vs, axis=1)
