"""Online serving tier (ROADMAP item 3): the millions-of-users path.

``servesvc`` is to inference what ``launch/supervisor.py`` is to
training: the process that keeps answering while individual pieces
misbehave. A :class:`~.server.ServingReplica` hot-follows the
trainer's published checkpoints (digest-verified; a torn publish is
skipped, never served), admits requests over a local socket behind a
BOUNDED queue (overload load-sheds with a typed reject instead of
queueing into unbounded latency), pads/buckets dynamic request batches
to compiled shapes, and hot-swaps weights on publish without dropping
a single in-flight request (double-buffered params: the in-flight
batch drains on the old weights, then the reference flips atomically
and the swap is journaled).

N replicas run under the same :class:`~..launch.supervisor.
ClusterSupervisor` liveness/restart/standby machinery as trainers
(payload verb ``launch serve``), behind the round-robin failover
:class:`~.client.ServeClient` shim — the backup-workers discipline of
the source paper (arXiv:1604.00981), applied to the request path the
way TF-Replicator (arXiv:1902.00465) treats serving replicas as just
another resource shape behind one recovery surface.
"""

from .client import ServeClient, discover_endpoints
from .decode import DecodeReplica
from .kv_cache import BlockAllocator, PagedKVCache
from .loadgen import run_load
from .server import ServingReplica

__all__ = ["ServingReplica", "DecodeReplica", "ServeClient",
           "discover_endpoints", "run_load", "BlockAllocator",
           "PagedKVCache"]
