"""Round-robin failover client shim for the serving tier.

The backup-workers idea (arXiv:1604.00981) on the request path: N
interchangeable serving replicas behind one client, and a request
never depends on any SINGLE replica staying alive. Each request gets a
**deadline** and a **bounded retry budget with backoff**; a dead,
hung, restarting, or load-shedding replica costs one attempt and the
next attempt goes to the next replica (round-robin). Every request
ends in exactly one TERMINAL outcome:

* ``{"status": "ok", ...}`` — a replica answered,
* ``{"status": "rejected", ...}`` — a replica answered with a
  non-retryable typed reject (``bad_request``, ``deadline_exceeded``),
* ``{"status": "error", "reason": "unavailable" | "deadline_exceeded"}``
  — the budget or the deadline ran out before any replica answered.

``overloaded`` and ``shutting_down`` rejects ARE retried (that replica
shed load; a sibling may have room) — admission control composes with
failover instead of surfacing every shed to the caller.

Endpoints come from a list or a zero-arg callable returning one — the
callable form re-resolves on every attempt, so a replica restarted
onto a fresh ephemeral port (its ``serve.json`` rewritten by the new
incarnation) is picked up without any client restart.
"""

from __future__ import annotations

import itertools
import json
import random
import socket
import threading
import time
from pathlib import Path
from typing import Any, Callable

from ..core.log import get_logger

logger = get_logger("serveclient")

RETRYABLE_REJECTS = ("overloaded", "shutting_down")


def discover_endpoints(cluster_root: str | Path) -> list[dict[str, Any]]:
    """Scan a LocalProcessCluster root for replicas' ``serve.json``
    ready files → ``[{"worker", "host", "port"}, ...]`` (sorted by
    worker id). Torn/stale files are skipped — the shim treats a bad
    endpoint as one failed attempt anyway."""
    out: list[dict[str, Any]] = []
    root = Path(cluster_root)
    for f in sorted(root.glob("worker*/serve.json")):
        name = f.parent.name[len("worker"):]
        try:
            d = json.loads(f.read_text())
            out.append({"worker": int(name) if name.isdigit() else name,
                        "host": d["host"], "port": int(d["port"])})
        except (OSError, ValueError, KeyError):
            continue
    return out


class ServeClient:
    """Thread-safe round-robin client over N serving replicas."""

    def __init__(self,
                 endpoints: (list[dict] | list[tuple]
                             | Callable[[], list[dict]]),
                 deadline_s: float = 2.0, max_attempts: int = 4,
                 backoff_s: float = 0.05,
                 quarantine_s: float = 0.25,
                 quarantine_max_s: float = 5.0, seed: int = 0):
        self._endpoints_fn = (endpoints if callable(endpoints)
                              else (lambda: endpoints))
        self.deadline_s = deadline_s
        self.max_attempts = max_attempts
        self.backoff_s = backoff_s
        # partition-aware endpoint quarantine: an endpoint whose
        # attempt failed at the TRANSPORT (refused, reset, timed out —
        # a partitioned or half-open link) is benched for a jittered,
        # exponentially-growing window so retries stop stampeding the
        # dead link; any success clears it, and when EVERY endpoint is
        # benched the rotation ignores the bench entirely (quarantine
        # narrows the search, it never causes a total lockout).
        self.quarantine_s = quarantine_s
        self.quarantine_max_s = quarantine_max_s
        self._rng = random.Random(seed)
        self._quarantined_until: dict[tuple[str, int], float] = {}
        self._failures: dict[tuple[str, int], int] = {}
        self._rr = itertools.count()
        self._lock = threading.Lock()

    @staticmethod
    def _as_ep(ep) -> tuple[str, int]:
        if isinstance(ep, dict):
            return ep["host"], int(ep["port"])
        return ep[0], int(ep[1])

    def _next_endpoint(self) -> tuple[str, int] | None:
        eps = self._endpoints_fn()
        if not eps:
            return None
        now = time.monotonic()
        with self._lock:
            live = [e for e in eps
                    if self._quarantined_until.get(self._as_ep(e), 0.0)
                    <= now]
            pool = live or eps
            i = next(self._rr)
        return self._as_ep(pool[i % len(pool)])

    def _jitter(self) -> float:
        with self._lock:
            return 1.0 + 0.25 * (2.0 * self._rng.random() - 1.0)

    def _note_failure(self, host: str, port: int) -> None:
        ep = (host, port)
        with self._lock:
            n = self._failures.get(ep, 0) + 1
            self._failures[ep] = n
            hold = min(self.quarantine_max_s,
                       self.quarantine_s * 2.0 ** (n - 1))
            hold *= 1.0 + 0.25 * (2.0 * self._rng.random() - 1.0)
            self._quarantined_until[ep] = time.monotonic() + hold

    def _note_success(self, host: str, port: int) -> None:
        ep = (host, port)
        with self._lock:
            self._failures.pop(ep, None)
            self._quarantined_until.pop(ep, None)

    def quarantined(self) -> list[tuple[str, int]]:
        """Endpoints currently benched (for tests/introspection)."""
        now = time.monotonic()
        with self._lock:
            return sorted(ep for ep, t in self._quarantined_until.items()
                          if t > now)

    def _one_attempt(self, payload: bytes, host: str, port: int,
                     timeout_s: float) -> dict:
        with socket.create_connection((host, port),
                                      timeout=timeout_s) as conn:
            conn.settimeout(timeout_s)
            conn.sendall(payload)
            buf = b""
            while b"\n" not in buf:
                chunk = conn.recv(65536)
                if not chunk:
                    raise OSError("connection closed mid-response")
                buf += chunk
            return json.loads(buf.decode())

    def _failover_loop(self, request_id,
                       deadline_s: float | None, attempt) -> dict:
        """The ONE failover/retry engine both request shapes share:
        rotate endpoints, bound attempts and the deadline, back off
        between tries, and classify outcomes — ``attempt(host, port,
        remaining, attempts, t0)`` performs one wire exchange and
        returns the raw response dict (raising ``OSError``/
        ``ValueError`` for a dead/garbled replica). A retryable typed
        reject (``overloaded``/``shutting_down``: that replica shed
        load, a sibling may have room) costs one attempt; anything
        else terminal is returned enriched with ``attempts``/
        ``endpoint``/``latency_ms``."""
        deadline_s = self.deadline_s if deadline_s is None else deadline_s
        t0 = time.time()
        deadline = t0 + deadline_s
        last_reason = "unavailable"
        attempts = 0
        while attempts < self.max_attempts:
            remaining = deadline - time.time()
            if remaining <= 0:
                last_reason = "deadline_exceeded"
                break
            ep = self._next_endpoint()
            if ep is None:
                attempts += 1
                time.sleep(min(self.backoff_s * attempts, remaining))
                continue
            host, port = ep
            attempts += 1
            try:
                resp = attempt(host, port, remaining, attempts, t0)
            except (OSError, ValueError) as e:
                # transport-level failure: quarantine the endpoint
                # (partition-aware — the next attempts rotate PAST the
                # dead link) and back off with seeded jitter so N
                # retrying clients don't re-stampede in lockstep
                logger.debug("attempt %d via %s:%d failed: %s",
                             attempts, host, port, e)
                self._note_failure(host, port)
                time.sleep(min(self.backoff_s * attempts * self._jitter(),
                               max(0.0, deadline - time.time())))
                continue
            self._note_success(host, port)
            status = resp.get("status")
            out = {**resp, "attempts": attempts,
                   "retried": attempts > 1,
                   "endpoint": f"{host}:{port}",
                   "latency_ms": round((time.time() - t0) * 1e3, 3)}
            if (status == "rejected"
                    and resp.get("reason") in RETRYABLE_REJECTS):
                time.sleep(min(self.backoff_s * attempts * self._jitter(),
                               max(0.0, deadline - time.time())))
                continue
            return out  # ok / typed non-retryable / unknown: terminal
        return {"id": request_id, "status": "error", "reason": last_reason,
                "attempts": attempts, "retried": attempts > 1,
                "latency_ms": round((time.time() - t0) * 1e3, 3)}

    def request(self, inputs, request_id=None,
                deadline_s: float | None = None) -> dict:
        """One request → one terminal outcome dict (never raises for
        server/network trouble; see module docstring). The outcome
        carries ``latency_ms``, ``attempts``, and the answering
        replica's ``endpoint`` when one answered."""
        def attempt(host, port, remaining, attempts, t0):
            req = {"id": request_id, "inputs": inputs,
                   "deadline_ms": round(remaining * 1e3, 1)}
            return self._one_attempt(
                (json.dumps(req) + "\n").encode(), host, port,
                timeout_s=remaining)

        return self._failover_loop(request_id, deadline_s, attempt)

    def _stream_attempt(self, payload: bytes, host: str, port: int,
                        timeout_s: float,
                        on_token) -> tuple[dict, list[float], list]:
        """One streaming attempt: send, then read token lines until
        the terminal line. Returns (terminal, token_times, tokens)."""
        with socket.create_connection((host, port),
                                      timeout=timeout_s) as conn:
            conn.settimeout(timeout_s)
            conn.sendall(payload)
            buf = b""
            tokens: list = []
            token_times: list[float] = []
            while True:
                while b"\n" not in buf:
                    chunk = conn.recv(65536)
                    if not chunk:
                        raise OSError("connection closed mid-stream")
                    buf += chunk
                line, _, buf = buf.partition(b"\n")
                rec = json.loads(line.decode())
                if "status" in rec:
                    return rec, token_times, tokens
                if rec.get("stream") == "token":
                    tokens.append(rec.get("token"))
                    token_times.append(time.time())
                    if on_token is not None:
                        on_token(rec)
                elif rec.get("stream") == "restart":
                    # a server-side swap-policy restart: the replica
                    # regenerates on new weights — reset accumulation
                    tokens = []
                    token_times = []
                    if on_token is not None:
                        on_token(rec)

    def generate(self, prompt, request_id=None,
                 deadline_s: float | None = None,
                 max_tokens: int | None = None,
                 temperature: float | None = None,
                 top_k: int | None = None,
                 on_token=None) -> dict:
        """One generation request → one terminal outcome dict, with
        tokens streamed through ``on_token`` as they arrive. Same
        failover/retry/terminal semantics as :meth:`request` (the
        shared :meth:`_failover_loop`); a connection lost MID-STREAM
        costs one attempt and the whole generation retries on a
        sibling — accumulated tokens reset, and ``on_token`` receives
        the same ``{"stream": "restart"}`` signal a server-side
        swap-restart sends, so a consumer never keeps a duplicated
        prefix. The outcome carries ``ttft_ms`` (first token latency),
        ``itl_ms`` (mean inter-token gap) and ``tokens`` alongside the
        usual ``latency_ms``/``attempts``/``endpoint``."""
        def attempt(host, port, remaining, attempts, t0):
            req: dict[str, Any] = {"id": request_id, "prompt": prompt,
                                   "deadline_ms": round(remaining * 1e3,
                                                        1)}
            if max_tokens is not None:
                req["max_tokens"] = max_tokens
            if temperature is not None:
                req["temperature"] = temperature
            if top_k is not None:
                req["top_k"] = top_k
            attempt_t0 = time.time()
            streamed_any = False

            def _tap(rec):
                nonlocal streamed_any
                streamed_any = True
                if on_token is not None:
                    on_token(rec)

            try:
                resp, token_times, tokens = self._stream_attempt(
                    (json.dumps(req) + "\n").encode(), host, port,
                    timeout_s=remaining, on_token=_tap)
            except (OSError, ValueError):
                if streamed_any and on_token is not None:
                    # client-side failover mid-stream: the next
                    # attempt regenerates from scratch on a sibling —
                    # give the consumer the protocol's own reset
                    # signal, or its accumulated prefix silently
                    # duplicates
                    on_token({"id": request_id, "stream": "restart",
                              "reason": "failover"})
                raise
            if resp.get("status") == "ok":
                resp.setdefault("tokens", tokens)
                resp["tokens_streamed"] = len(resp["tokens"] or [])
                if token_times:
                    resp["ttft_ms"] = round(
                        (token_times[0] - attempt_t0) * 1e3, 3)
                if len(token_times) > 1:
                    gaps = [b - a for a, b in zip(token_times,
                                                  token_times[1:])]
                    resp["itl_ms"] = round(
                        sum(gaps) / len(gaps) * 1e3, 3)
            return resp

        return self._failover_loop(request_id, deadline_s, attempt)

    def meta(self, deadline_s: float | None = None) -> dict | None:
        """Model metadata from any live replica (input shape/dtype —
        what a load generator needs to fabricate requests), or None."""
        deadline_s = self.deadline_s if deadline_s is None else deadline_s
        deadline = time.time() + deadline_s
        payload = (json.dumps({"meta": True}) + "\n").encode()
        for _ in range(self.max_attempts):
            remaining = deadline - time.time()
            if remaining <= 0:
                return None
            ep = self._next_endpoint()
            if ep is None:
                time.sleep(min(self.backoff_s, remaining))
                continue
            try:
                return self._one_attempt(payload, ep[0], ep[1],
                                         timeout_s=remaining)
            except (OSError, ValueError):
                time.sleep(min(self.backoff_s,
                               max(0.0, deadline - time.time())))
        return None
