"""The serving replica process.

One replica = one socket + one bounded admission queue + one batcher
thread + one checkpoint-follower thread. The robustness contract
(checked post-run by ``obsv/invariants.py``'s serving invariants):

* **Exactly one terminal outcome per admitted request** — a response
  or a TYPED reject (``overloaded`` / ``deadline_exceeded`` /
  ``bad_request`` / ``shutting_down``); a graceful stop drains the
  queue by rejecting, never by dropping.
* **Never serve a checkpoint that failed digest verification** — the
  weight path is ``train/checkpoint.py`` ``restore_checkpoint`` with
  its fallback-to-previous-loadable-step, so a torn or corrupt publish
  is skipped (and journaled) while the replica keeps serving the
  previous weights.
* **Served model step is monotone non-decreasing across swaps** — a
  swap only installs a strictly newer step.

Precision tiers (``serve.precision_tier``): with ``bf16`` or ``int8``
the replica PREFERS the publish-time quantized sidecar
(``ckpt-<step>.quant.msgpack``, written by the ``quant/`` pass behind
``quant.publish_tiers``) — digest-verified through the same machinery
as the checkpoint itself, int8 weights resident on device and
dequantized inside the jitted predict (scale fusion). A sidecar that
is absent, torn, or missing the requested tier journals a
``follow_quant_sidecar_fallback`` and that publish serves from the
full-precision artifact instead — the torn-digest invariant covers
sidecars exactly like checkpoints, and the follower cursor still
advances (no skip-loop wedge). Every ``weight_swap`` records the
``tier`` it installed plus ``source_artifact``/``source_digest``, so
the journals say which representation actually served.

Wire protocol: one JSON line per connection each way (the client shim
opens a connection per request — serving rates here are bounded by
model compute, not connection setup).

  request:  {"id": ..., "inputs": [...], "deadline_ms": ...}
            {"meta": true}   → model metadata, never queued
  response: {"id": ..., "status": "ok", "model_step": N,
             "prediction": k, "probs": [...]}
            {"id": ..., "status": "rejected", "reason": "..."}

Artifacts per replica (in ``serve_dir``):

* ``serve_log.jsonl`` — ``event: "serve"`` records: admit / respond /
  reject per request id, ``weight_swap`` (step, digest, swap_ms),
  follower skip events. What the serving invariants replay.
* ``train_log.jsonl`` — ``event: "heartbeat"`` records whose ``step``
  is the terminal-outcome count: the liveness/progress signal that
  makes the EXISTING supervisor machinery (poll, stall detection,
  measured boot, MTTR) work unchanged for serving payloads.
* ``serve.json`` — the bound endpoint (host, port, pid), written once
  the replica is actually ready to serve; the client shim discovers
  replicas by these.
"""

from __future__ import annotations

import collections
import json
import queue
import socket
import threading
import time
from pathlib import Path
from typing import Any

import jax
import numpy as np

from ..core.config import (SERVING_PRECISION_TIERS, ConfigError,
                           ExperimentConfig, MeshConfig, ServeConfig,
                           effective_model_config)
from ..core.log import JsonlSink, get_logger
from ..core.mesh import Topology, make_topology
from ..models.registry import get_model
from ..parallel.api import init_train_state, state_partition_specs
from ..train import checkpoint as ckpt

logger = get_logger("serve")

_MAX_REQUEST_BYTES = 4 << 20  # a request is one image/sequence, not a shard


# The first-checkpoint config bootstrap lives at the checkpoint layer
# (train/checkpoint.py, next to the CheckpointFollower) — re-exported
# here because the serving CLI reads it off this module.
wait_for_run_config = ckpt.wait_for_run_config


class _Pending:
    """One admitted request waiting in the batch queue."""

    __slots__ = ("req_id", "inputs", "conn", "admitted_at", "deadline_at")

    def __init__(self, req_id, inputs, conn, admitted_at, deadline_at):
        self.req_id = req_id
        self.inputs = inputs
        self.conn = conn
        self.admitted_at = admitted_at
        self.deadline_at = deadline_at


class ServingReplica:
    """Load the latest digest-verified checkpoint and serve it; keep
    following publishes and hot-swap without dropping in-flight work."""

    def __init__(self, train_dir: str | Path, serve_dir: str | Path = ".",
                 scfg: ServeConfig | None = None,
                 cfg: ExperimentConfig | None = None,
                 topo: Topology | None = None):
        self.train_dir = Path(train_dir)
        self.serve_dir = Path(serve_dir)
        self.serve_dir.mkdir(parents=True, exist_ok=True)
        if cfg is None:
            cfg = wait_for_run_config(self.train_dir)
        self.cfg = cfg
        self.scfg = scfg or cfg.serve
        self.tp_ranks = max(1, int(self.scfg.tp_ranks))
        if topo is not None:
            self.topo = topo
        else:
            # Lean 1-device mesh, like the evaluator's --single_device
            # mode: serving shares a host with trainers and must not
            # force an N-device backend or join any collective. Same
            # refusal: pipeline-stacked layouts restore differently.
            if cfg.mesh.pipeline_parallelism > 1:
                raise ValueError(
                    "serving cannot restore pipeline-stacked parameter "
                    "layouts; serve from a non-pipeline checkpoint")
            if self.tp_ranks > 1:
                # TP serving: replica capacity as a mesh shape. One
                # replica axis × tp_ranks model axis; every published
                # checkpoint is sharded-loaded through the model's TP
                # partition rules (restore_for_topology below) and the
                # jitted predict/decode runs GSPMD-partitioned over
                # the serving mesh. On hosts with fewer devices than
                # ranks the mesh is simulated (virtual CPU devices) —
                # the sharded-load/swap/verify contract is identical.
                self.topo = make_topology(MeshConfig(
                    num_replicas=1, model_parallelism=self.tp_ranks,
                    simulate_devices=(0 if len(jax.devices())
                                      >= self.tp_ranks
                                      else self.tp_ranks)))
            else:
                self.topo = make_topology(MeshConfig(num_replicas=1),
                                          devices=jax.devices()[:1])
        # serve-side compute-dtype resolution (serve.compute_dtype →
        # precision.compute_dtype → model.compute_dtype), validated at
        # the shared seam — a typo is a typed ConfigError here, not a
        # jnp error mid-request
        self.model = get_model(effective_model_config(cfg, serving=True))
        self.tier = self.scfg.precision_tier or "fp32"
        if self.tier not in SERVING_PRECISION_TIERS:
            raise ConfigError(
                f"serve.precision_tier={self.tier!r} is not a known "
                f"tier; valid tiers: "
                f"{', '.join(SERVING_PRECISION_TIERS)}")
        try:
            self.template = init_train_state(self.model, cfg, self.topo)
            self._param_specs = state_partition_specs(
                self.model, cfg, self.topo).params
        except ValueError as e:
            if self.tp_ranks > 1:
                raise ConfigError(
                    f"serve.tp_ranks={self.tp_ranks} needs a model with "
                    f"tensor-parallel partition rules: {e}") from e
            raise
        self.follower = ckpt.CheckpointFollower(self.train_dir)

        model = self.model

        def predict(params, x):
            logits = model.apply(params, x, train=False)
            return model.predictions(logits)

        # one jit; each bucket shape compiles once on first use. The
        # fp32 predict always exists (it is the fallback every tier
        # degrades to); quant-tier predicts are built lazily on the
        # first sidecar install (quant/ptq.build_tier_predict — int8
        # dequantizes in-graph, bf16 applies the bf16-stored leaves
        # through a bf16-compute model unless serve.compute_dtype
        # pinned something else)
        self._predict = jax.jit(predict)
        self._predict_fp32 = self._predict
        self._tier_predict_fns: dict[str, Any] = {"fp32": self._predict}

        # current weights (batcher-owned) + double buffer staged by the
        # follower thread, flipped at a batch boundary
        self._params = None
        self.model_step = -1
        self.model_digest: str | None = None
        self.model_tier: str | None = None      # tier actually installed
        self.model_source_digest: str | None = None
        # last step a sidecar fallback was journaled for: when the
        # fp32 path ALSO has nothing to restore, the follower cursor
        # stays put and every poll re-reads the same step — the
        # fallback must journal once per publish, not once per tick
        # (quant_sidecar_fallbacks counts refusals, not poll cadence)
        self._quant_fallback_step: int | None = None
        self._staged: tuple | None = None
        self._staged_lock = threading.Lock()

        self._queue: queue.Queue[_Pending] = queue.Queue(
            maxsize=max(1, self.scfg.queue_depth))
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._conn_threads: set[threading.Thread] = set()
        self._conn_lock = threading.Lock()
        self._sock: socket.socket | None = None
        self.bound_port: int | None = None

        self._journal_lock = threading.Lock()
        self._journal_closed = False
        self._serve_log = JsonlSink(self.serve_dir / "serve_log.jsonl")
        self._heartbeat = JsonlSink(self.serve_dir / "train_log.jsonl")
        self._terminals = 0          # responses + rejects ever produced
        self._last_heartbeat = -1
        self.swaps = 0

        # idempotency: request id → (final ok payload, completed_at).
        # A retried request whose execution already completed here —
        # the sibling-failover case, or a reset that ate the response
        # after _terminal journaled it — answers from this cache
        # instead of double-executing (journaled as ``dedup_hit``).
        # Bounded LRU; only FINAL ok outcomes are cached (retryable
        # sheds must stay retryable).
        self._dedup_lock = threading.Lock()
        self._dedup: collections.OrderedDict[Any, tuple[dict, float]] = \
            collections.OrderedDict()
        self.dedup_hits = 0

    # -- journal ------------------------------------------------------

    def _journal(self, record: dict) -> None:
        with self._journal_lock:
            if self._journal_closed:
                return  # a straggler conn thread racing stop()
            self._serve_log.write({"event": "serve",
                                   "time": time.time(), **record})

    def _terminal(self, action: str, req_id, **fields) -> None:
        """Journal one terminal outcome (respond/reject) and bump the
        heartbeat counter — every admitted request must produce exactly
        one of these."""
        self._journal({"action": action, "id": req_id, **fields})
        with self._journal_lock:
            self._terminals += 1

    # -- idempotency / dedup cache ------------------------------------

    def _dedup_put(self, req_id, payload: dict) -> None:
        """Remember a FINAL ok outcome for its request id. Ids are the
        client's idempotency keys; requests without one opt out."""
        if req_id is None or int(self.scfg.dedup_cache_size) <= 0:
            return
        with self._dedup_lock:
            self._dedup[req_id] = (payload, time.time())
            self._dedup.move_to_end(req_id)
            while len(self._dedup) > int(self.scfg.dedup_cache_size):
                self._dedup.popitem(last=False)

    def _dedup_get(self, req_id) -> tuple[dict, float] | None:
        if req_id is None:
            return None
        with self._dedup_lock:
            got = self._dedup.get(req_id)
            if got is not None:
                self._dedup.move_to_end(req_id)
        return got

    def _pressure_fields(self) -> dict:
        """Live replica pressure stamped onto every heartbeat — queue
        occupancy against the admission bound here; the decode replica
        adds KV block-pool occupancy. What ``parse_poll_output``
        surfaces to the resource broker without a second channel."""
        return {"queue_depth": self._queue.qsize(),
                "queue_limit": max(1, self.scfg.queue_depth)}

    def _maybe_heartbeat(self) -> None:
        with self._journal_lock:
            n = self._terminals
            if n == self._last_heartbeat or self._journal_closed:
                return
            self._last_heartbeat = n
            self._heartbeat.write({"event": "heartbeat", "step": n,
                                   "time": time.time(),
                                   **self._pressure_fields()})

    # -- weights ------------------------------------------------------

    def _tier_predict(self, tier: str):
        """The jitted predict for a quant tier, built once per tier
        per replica (each bucket shape still compiles on first use)."""
        fn = self._tier_predict_fns.get(tier)
        if fn is None:
            import dataclasses

            from ..quant.ptq import build_tier_predict
            model = self.model
            if tier == "bf16" and not self.cfg.serve.compute_dtype:
                # the tier's point is MXU-native bf16 end-to-end; an
                # explicit serve.compute_dtype still wins
                model = get_model(dataclasses.replace(
                    effective_model_config(self.cfg, serving=True),
                    compute_dtype="bfloat16"))
            fn = jax.jit(build_tier_predict(model, self.template.params,
                                            tier))
            self._tier_predict_fns[tier] = fn
        return fn

    def _read_quant_tier(self, step: int, t0: float):
        """The sidecar-preference half of the follower read: a
        digest-verified quant sidecar holding the configured tier →
        a staged install; anything else (absent, torn, tier missing)
        journals ``follow_quant_sidecar_fallback`` and returns None so
        the read falls through to the full-precision artifact — the
        cursor still advances through THAT path, so a bad sidecar can
        never wedge the follower's skip loop."""
        def fallback(reason: str):
            if self._quant_fallback_step != step:
                self._quant_fallback_step = step
                self._journal({"action": "follow_quant_sidecar_fallback",
                               "step": step, "tier": self.tier,
                               "reason": reason})
            return None
        try:
            payload = ckpt.read_quant_sidecar(self.train_dir, step)
            tiers = payload["tiers"]
            if self.tier not in tiers:
                raise KeyError(
                    f"sidecar has tiers {sorted(tiers)}, not "
                    f"{self.tier!r}")
        except FileNotFoundError:
            return fallback("sidecar_absent")
        except (OSError, ValueError, KeyError) as e:
            # ValueError covers CheckpointCorruptError: the digest
            # refusal — a torn sidecar is never served, same contract
            # as a torn checkpoint
            return fallback(f"{type(e).__name__}: {e}")
        if step <= self.model_step:
            return ("noswap", step)
        params = jax.device_put(tiers[self.tier])
        meta = payload.get("meta") or {}
        return ("swap", {
            "params": params,
            "predict": self._tier_predict(self.tier),
            "step": step,
            "digest": ckpt.quant_sidecar_digest(self.train_dir, step),
            "tier": self.tier,
            "source_artifact": ckpt.quant_sidecar_path(
                self.train_dir, step).name,
            "source_digest": meta.get("source_params_digest"),
        }, t0)

    def _read_weights(self, ptr_step: int):
        """The follower's ``read``: tier preference first (the quant
        sidecar when ``serve.precision_tier`` names one), then the
        digest-verified full-precision restore with
        fallback-to-previous-loadable-step — a torn/corrupt publish is
        skipped (journaled), never served. Returns a staged swap, or a
        no-swap marker when the fallback landed on (or behind) what we
        already serve."""
        t0 = time.time()
        if self.tier != "fp32":
            got = self._read_quant_tier(ptr_step, t0)
            if got is not None:
                return got
            # journaled fallback: this publish serves full precision
        on_event = lambda rec: self._journal(
            {"action": "follow_" + rec.get("action", "?"),
             **{k: v for k, v in rec.items()
                if k not in ("layer", "action")}})
        if self.tp_ranks > 1:
            # TP replica: the mesh-portable restore — the checkpoint
            # was saved under the TRAINER's world, and every rank of
            # this serving mesh takes only its shard of each leaf when
            # device_put_state places the result over the TP specs
            # below (restore journals follow_cross_world_restore when
            # the worlds differ)
            from ..parallel.api import restore_for_topology
            restored = restore_for_topology(
                self.model, self.cfg, self.topo, self.train_dir,
                self.template, on_event=on_event)
        else:
            restored = ckpt.restore_checkpoint(
                self.train_dir, self.template, None, on_event=on_event)
        if restored is None:
            return None
        state, _, at_step = restored
        if at_step <= self.model_step:
            # the newest publish was unusable and the fallback landed
            # on weights we already serve: consume the pointer step so
            # the follower stops re-reading the torn artifact
            return ("noswap", at_step)
        params = self.topo.device_put_state(state.params, self._param_specs)
        digest = ckpt.artifact_digest(self.train_dir, at_step)
        # name the artifact the restore actually read — single-file
        # layout only; a sharded (manifest) restore records None so
        # the serve_digest invariant keeps its historical step-based
        # match instead of name-matching a file that doesn't exist
        name = f"ckpt-{at_step:08d}.msgpack"
        if not (self.train_dir / name).exists():
            name = None
        return ("swap", {
            # predict None = "the replica's fp32 predict" — resolved at
            # install time so a test-wrapped self._predict stays live
            "params": params, "predict": None,
            "step": at_step, "digest": digest, "tier": "fp32",
            "source_artifact": name,
            "source_digest": digest,
        }, t0)

    def _install(self, staged: dict, t0: float,
                 initial: bool = False,
                 extra: dict | None = None) -> None:
        """Flip the staged weights in (batcher/boot thread only) and
        journal the swap with its tier + source identity. ``extra``:
        additional declared swap-record fields (the decode replica's
        sequences_pinned / sequences_restarted bookkeeping)."""
        prev = self.model_step
        self._params = staged["params"]
        if staged["predict"] is not None:
            self._predict = staged["predict"]
        elif self.model_tier not in (None, "fp32"):
            # downgrading a quant tier to fp32: restore the pristine
            # fp32 predict (a pure-fp32 replica never reassigns
            # self._predict, so tests wrapping it keep their wrapper)
            self._predict = self._predict_fp32
        self.model_step = staged["step"]
        self.model_digest = staged["digest"]
        self.model_tier = staged["tier"]
        self.model_source_digest = staged["source_digest"]
        self.swaps += 1
        rec = {"action": "weight_swap", "step": staged["step"],
               "from_step": prev, "digest": staged["digest"],
               "tier": staged["tier"],
               "source_artifact": staged["source_artifact"],
               "source_digest": staged["source_digest"],
               "swap_ms": round((time.time() - t0) * 1e3, 3),
               **(extra or {})}
        if initial:
            rec["initial"] = True
        self._journal(rec)

    def _load_initial(self, timeout_s: float = 600.0) -> None:
        deadline = time.time() + timeout_s
        while time.time() < deadline and not self._stop.is_set():
            got = self.follower.poll(self._read_weights)
            if got is not None and got[0] == "swap":
                _, staged, t0 = got
                self._install(staged, t0, initial=True)
                return
            time.sleep(min(1.0, self.scfg.poll_secs))
        raise TimeoutError(
            f"no loadable checkpoint in {self.train_dir} within "
            f"{timeout_s:.0f}s")

    def _follow_loop(self) -> None:
        while not self._stop.is_set():
            try:
                got = self.follower.poll(self._read_weights)
            except Exception as e:  # the service must outlive any read
                logger.warning("checkpoint follow failed (%s: %s)",
                               type(e).__name__, e)
                got = None
            if got is not None and got[0] == "swap":
                with self._staged_lock:
                    self._staged = got[1:]
            self._stop.wait(self.scfg.poll_secs)

    def _maybe_swap(self) -> None:
        """Batch-boundary flip: the in-flight batch already drained on
        the old weights; installing the staged buffer is one reference
        assignment. Journals step + digest + tier + swap latency."""
        with self._staged_lock:
            staged, self._staged = self._staged, None
        if staged is None:
            return
        install, t0 = staged
        if install["step"] <= self.model_step:
            return  # monotone: never swap backwards
        self._install(install, t0)

    # -- socket front door --------------------------------------------

    def _respond(self, conn, payload: dict) -> bool:
        try:
            # write deadline: a peer that stopped reading (half-open,
            # partitioned) costs at most conn_write_timeout_s, never a
            # wedged batcher — the tighter per-connection timeout the
            # decode loop sets stays in force
            wt = float(self.scfg.conn_write_timeout_s)
            cur = conn.gettimeout()
            if wt > 0 and (cur is None or cur > wt):
                conn.settimeout(wt)
            conn.sendall((json.dumps(payload) + "\n").encode())
            return True
        except OSError:
            return False  # client went away; the outcome is journaled
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _reject(self, conn, req_id, reason: str, admitted: bool) -> None:
        self._terminal("reject", req_id, reason=reason, admitted=admitted)
        self._respond(conn, {"id": req_id, "status": "rejected",
                             "reason": reason,
                             "model_step": self.model_step})

    def _meta(self) -> dict:
        return {"status": "ok", "meta": True,
                "model": self.cfg.model.name,
                "input_shape": list(self.model.input_shape),
                "input_dtype": str(np.dtype(self.model.input_dtype)),
                "model_step": self.model_step,
                # which representation this replica PREFERS vs what it
                # actually has installed right now (a sidecar fallback
                # makes these differ), plus the installed tier's source
                # identity — what lets a loadgen artifact record which
                # tier a sweep ACTUALLY measured
                "precision_tier": self.tier,
                "active_tier": self.model_tier,
                "model_digest": self.model_digest,
                "tier_source_digest": self.model_source_digest,
                "max_batch": self.scfg.max_batch}

    def _conn_abort(self, conn, reason: str, bytes_read: int) -> None:
        """Close a connection that never became a request — the read
        deadline fired or the peer went half-open. Nothing was
        admitted, so no terminal outcome is owed; the abort is
        journaled so the books explain the closed socket."""
        self._journal({"action": "conn_abort", "reason": reason,
                       "bytes_read": bytes_read})
        try:
            conn.close()
        except OSError:
            pass

    def _read_request(self, conn) -> bytes | None:
        """Read one request line under a TOTAL deadline — a slowloris
        peer trickling bytes (or sending none: the half-open case)
        costs one bounded stall of at most ``conn_read_timeout_s``,
        then the connection is aborted. Returns None when aborted."""
        total_s = max(0.1, float(self.scfg.conn_read_timeout_s))
        deadline = time.monotonic() + total_s
        buf = b""
        while b"\n" not in buf:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                self._conn_abort(conn,
                                 "half_open" if not buf
                                 else "read_deadline", len(buf))
                return None
            conn.settimeout(remaining)
            try:
                chunk = conn.recv(65536)
            except socket.timeout:
                self._conn_abort(conn,
                                 "half_open" if not buf
                                 else "read_deadline", len(buf))
                return None
            if not chunk:
                break
            buf += chunk
            if len(buf) > _MAX_REQUEST_BYTES:
                self._reject(conn, None, "bad_request", admitted=False)
                return None
        return buf

    def _handle_conn(self, conn) -> None:
        """Read one request; admit it (or shed typed). Runs on a
        per-connection thread so a slow client can't stall admission."""
        req_id = None
        try:
            buf = self._read_request(conn)
            if buf is None:
                return  # _read_request aborted or rejected
            try:
                req = json.loads(buf.decode())
                if not isinstance(req, dict):
                    raise ValueError("request must be a JSON object")
            except (ValueError, UnicodeDecodeError):
                self._reject(conn, None, "bad_request", admitted=False)
                return
            if req.get("meta"):
                self._respond(conn, self._meta())
                return
            req_id = req.get("id")
            cached = self._dedup_get(req_id)
            if cached is not None:
                # this id already ran to a final outcome here (the
                # retry's first attempt, on this replica, before a
                # reset ate the response): answer from the cache —
                # exactly-once means never double-executing
                payload, done_at = cached
                with self._journal_lock:
                    self.dedup_hits += 1
                self._journal({"action": "dedup_hit", "id": req_id,
                               "status": payload.get("status"),
                               "age_s": round(time.time() - done_at, 3)})
                self._respond(conn, payload)
                return
            if self._stop.is_set():
                self._reject(conn, req_id, "shutting_down", admitted=False)
                return
            item = self._build_item(req, conn)
            if item is None:
                return  # _build_item already sent the typed reject
            try:
                # admission control: a full queue sheds IMMEDIATELY
                # with a typed reject — bounded queue, bounded latency,
                # never silent starvation
                self._queue.put_nowait(item)
            except queue.Full:
                self._reject(conn, req_id, "overloaded", admitted=False)
                return
            self._journal({"action": "admit", "id": req_id,
                           "deadline_ms": round(
                               (item.deadline_at - item.admitted_at)
                               * 1e3, 3)})
        except OSError:
            # the socket died before we could even reject; if nothing
            # was admitted there is no outcome to owe
            if conn is not None:
                try:
                    conn.close()
                except OSError:
                    pass

    def _build_item(self, req: dict, conn) -> _Pending | None:
        """Validate one request payload into a queue item, or send the
        typed ``bad_request`` and return None. The workload-shaped half
        of admission — the decode replica overrides it to parse
        ``prompt`` requests instead of fixed-shape ``inputs``."""
        req_id = req.get("id")
        try:
            inputs = np.asarray(req["inputs"],
                                dtype=np.dtype(self.model.input_dtype))
        except (KeyError, ValueError, TypeError):
            self._reject(conn, req_id, "bad_request", admitted=False)
            return None
        if tuple(inputs.shape) != tuple(self.model.input_shape):
            self._reject(conn, req_id, "bad_request", admitted=False)
            return None
        now = time.time()
        deadline_ms = req.get("deadline_ms",
                              self.scfg.default_deadline_ms)
        return _Pending(req_id, inputs, conn, now,
                        now + float(deadline_ms) / 1e3)

    def _accept_loop(self) -> None:
        assert self._sock is not None
        self._sock.settimeout(0.2)
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            t = threading.Thread(target=self._handle_conn, args=(conn,),
                                 daemon=True)
            with self._conn_lock:
                self._conn_threads = {x for x in self._conn_threads
                                      if x.is_alive()}
                self._conn_threads.add(t)
            t.start()

    # -- the batcher --------------------------------------------------

    @staticmethod
    def _bucket(n: int, max_batch: int) -> int:
        b = 1
        while b < n and b < max_batch:
            b *= 2
        return min(b, max_batch)

    def _gather(self) -> list[_Pending]:
        """Pop up to ``max_batch`` requests: block briefly for the
        first, then drain whatever arrived within the batch window."""
        try:
            first = self._queue.get(timeout=0.05)
        except queue.Empty:
            return []
        items = [first]
        window = self.scfg.batch_window_ms / 1e3
        deadline = time.monotonic() + window
        while len(items) < self.scfg.max_batch:
            remaining = deadline - time.monotonic()
            try:
                items.append(self._queue.get(
                    timeout=max(0.0, remaining)))
            except queue.Empty:
                break
        return items

    def _run_batch(self, items: list[_Pending]) -> None:
        now = time.time()
        live: list[_Pending] = []
        for it in items:
            if now >= it.deadline_at:
                self._reject(it.conn, it.req_id, "deadline_exceeded",
                             admitted=True)
            else:
                live.append(it)
        if not live:
            return
        bucket = self._bucket(len(live), self.scfg.max_batch)
        dtype = np.dtype(self.model.input_dtype)
        x = np.zeros((bucket, *self.model.input_shape), dtype)
        for i, it in enumerate(live):
            x[i] = it.inputs
        step, digest, tier = (self.model_step, self.model_digest,
                              self.model_tier)
        probs = np.asarray(jax.device_get(self._predict(self._params, x)))
        for i, it in enumerate(live):
            p = probs[i]
            self._terminal(
                "respond", it.req_id, model_step=step, tier=tier,
                batch=len(live), bucket=bucket,
                latency_ms=round((time.time() - it.admitted_at) * 1e3, 3))
            payload = {
                "id": it.req_id, "status": "ok", "model_step": step,
                "model_digest": digest, "tier": tier,
                "prediction": int(np.argmax(p)),
                "probs": [round(float(v), 6) for v in p]}
            # cache BEFORE sending: if the send dies mid-wire (reset,
            # partition) the retry finds the completed outcome here
            self._dedup_put(it.req_id, payload)
            self._respond(it.conn, payload)

    def _batch_loop(self) -> None:
        while not self._stop.is_set():
            self._maybe_swap()
            items = self._gather()
            if items:
                self._run_batch(items)
            self._maybe_heartbeat()
        # graceful drain: everything still queued gets a TYPED reject —
        # a stopping replica sheds, it never silently drops
        while True:
            try:
                it = self._queue.get_nowait()
            except queue.Empty:
                break
            self._reject(it.conn, it.req_id, "shutting_down", admitted=True)
        self._maybe_heartbeat()

    # -- lifecycle ----------------------------------------------------

    def start(self) -> None:
        """Load initial weights, bind, publish ``serve.json``, and
        start the follower/accept/batcher threads. Idempotent-unsafe:
        one start per replica object."""
        endpoint_path = self.serve_dir / "serve.json"
        endpoint_path.unlink(missing_ok=True)  # stale incarnation
        self._load_initial()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((self.scfg.host, self.scfg.port))
        self._sock.listen(128)
        self.bound_port = self._sock.getsockname()[1]
        for target in (self._follow_loop, self._accept_loop,
                       self._batch_loop):
            t = threading.Thread(target=target, daemon=True,
                                 name=f"serve-{target.__name__}")
            t.start()
            self._threads.append(t)
        import os
        tmp = endpoint_path.with_suffix(".tmp")
        tmp.write_text(json.dumps(
            {"host": self.scfg.host, "port": self.bound_port,
             "pid": os.getpid(), "model_step": self.model_step,
             "started_at": time.time()}))
        tmp.replace(endpoint_path)
        self._journal({"action": "serve_start", "port": self.bound_port,
                       "model_step": self.model_step,
                       "precision_tier": self.tier,
                       "active_tier": self.model_tier,
                       "queue_depth": self.scfg.queue_depth,
                       "max_batch": self.scfg.max_batch})
        self._maybe_heartbeat()
        logger.info("serving %s step=%d on %s:%d", self.cfg.model.name,
                    self.model_step, self.scfg.host, self.bound_port)

    def request_stop(self) -> None:
        self._stop.set()

    def stop(self) -> None:
        """Stop accepting, drain the queue with typed rejects, close."""
        self.request_stop()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        for t in self._threads:
            t.join(timeout=30)
        # close the admit-vs-drain race: a connection handler that
        # passed its stop check just before request_stop() may enqueue
        # AFTER the batcher's final drain — join the (short-lived)
        # handler threads, then drain once more so every admitted
        # request still gets its typed terminal outcome
        with self._conn_lock:
            stragglers = list(self._conn_threads)
        for t in stragglers:
            t.join(timeout=10)
        while True:
            try:
                it = self._queue.get_nowait()
            except queue.Empty:
                break
            self._reject(it.conn, it.req_id, "shutting_down",
                         admitted=True)
        self._journal({"action": "serve_stop",
                       "terminals": self._terminals,
                       "model_step": self.model_step, "swaps": self.swaps})
        with self._journal_lock:
            self._journal_closed = True
            self._serve_log.close()
            self._heartbeat.close()

    def serve_forever(self, install_signal_handlers: bool = True) -> None:
        """The process entry: start, park until SIGTERM/SIGINT (the
        graceful drain the supervisor's ``stop_all`` relies on), stop."""
        if install_signal_handlers:
            import signal

            def handler(signum, frame):
                logger.warning("received signal %s — draining and "
                               "stopping", signum)
                self.request_stop()

            for sig in (signal.SIGTERM, signal.SIGINT):
                try:
                    signal.signal(sig, handler)
                except (ValueError, OSError):
                    pass
        self.start()
        try:
            while not self._stop.is_set():
                self._stop.wait(0.5)
        finally:
            self.stop()
