"""Closed-loop load generator for the serving tier.

``concurrency`` workers each keep exactly one request in flight
(closed-loop: the next request is issued only when the previous one
reached a terminal outcome), so offered load is bounded and the
latency distribution is measurable instead of collapsing into queueing
divergence. Every request is journaled twice — ``issue`` when sent,
``outcome`` when terminal — which is the artifact the serving
invariants replay: a request with no outcome is a DROP, and the whole
point of the serving tier is that there are none.

The summary carries p50/p99 latency over successful responses, the
reject/error tallies by typed reason, the distinct model steps the
responses were served from (evidence that a hot-swap happened
mid-sweep), and ``dropped`` (issued − terminal; must be 0).
"""

from __future__ import annotations

import json
import threading
import time
from pathlib import Path
from typing import Any, Callable

import numpy as np

from ..core.log import JsonlSink, get_logger
from ..obsv.journal import tail_records
from .client import ServeClient

logger = get_logger("loadgen")


def _percentile(sorted_vals: list[float], q: float) -> float:
    idx = min(len(sorted_vals) - 1,
              max(0, round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


def make_input_fn(shape, dtype: str, vocab: int = 256
                  ) -> Callable[[int], list]:
    """Deterministic per-request inputs: request ``i`` is always the
    same array, so any replica (and any retry) sees identical bytes."""
    shape = tuple(shape)
    np_dtype = np.dtype(dtype)

    def make(i: int) -> list:
        rng = np.random.default_rng(i)
        if np_dtype.kind in "iu":
            return rng.integers(0, vocab, size=shape).astype(
                np_dtype).tolist()
        return (rng.random(size=shape).astype(np_dtype) - 0.5).tolist()

    return make


def make_prompt_fn(vocab: int, max_prompt_len: int,
                   min_prompt_len: int = 2) -> Callable[[int], list]:
    """Deterministic per-request prompts for the decode service:
    request ``i`` is always the same token list, with lengths spread
    across [min, max] — the wildly-different-lengths mix the paged
    cache exists to batch into one compiled shape."""
    lo = max(1, min_prompt_len)
    hi = max(lo, max_prompt_len)

    def make(i: int) -> list:
        rng = np.random.default_rng(i)
        n = int(rng.integers(lo, hi + 1))
        return rng.integers(0, vocab, size=(n,)).astype(int).tolist()

    return make


def run_load(client: ServeClient, num_requests: int | None,
             concurrency: int, make_input: Callable[[int], Any],
             journal_path: str | Path | None = None,
             stop_event: threading.Event | None = None,
             deadline_s: float | None = None,
             decode: bool = False,
             window_s: float = 0.0,
             snapshot_every_s: float = 0.0) -> dict[str, Any]:
    """Drive the cluster closed-loop until ``num_requests`` terminal
    outcomes (or ``stop_event``, whichever first; one of the two must
    be provided). Returns the summary; journals to ``journal_path``.

    ``decode``: drive the generation path (``make_input`` yields token
    prompts, requests go through :meth:`ServeClient.generate`) — the
    outcome records then carry the two decode latency numbers
    alongside e2e: ``ttft_ms`` (time-to-first-token) and ``itl_ms``
    (mean per-token inter-arrival), and the summary aggregates their
    p50/p99 plus total ``tokens_streamed``.

    ``window_s`` > 0 (with a journal) turns on rolling-window pressure
    snapshots: every ``snapshot_every_s`` (defaults to ``window_s/2``)
    a ``{"event": "load", "action": "window"}`` record summarizing the
    last ``window_s`` seconds of outcomes lands in the journal — the
    live signal the resource broker (and a human tailing the file)
    reads, where the end-of-run summary only exists after the fact."""
    if num_requests is None and stop_event is None:
        raise ValueError("run_load needs num_requests or stop_event")
    sink = JsonlSink(journal_path) if journal_path is not None else None
    sink_lock = threading.Lock()
    counter = iter(range(1 << 62))
    outcomes: list[dict] = []
    out_lock = threading.Lock()
    issued = [0]
    t_start = time.time()

    def journal(rec: dict) -> None:
        if sink is not None:
            with sink_lock:
                sink.write(rec)

    def should_stop() -> bool:
        return stop_event is not None and stop_event.is_set()

    def worker() -> None:
        while not should_stop():
            with out_lock:
                if num_requests is not None and issued[0] >= num_requests:
                    return
                issued[0] += 1
                rid = next(counter)
            journal({"event": "load", "action": "issue", "id": rid,
                     "time": time.time()})
            if decode:
                got = client.generate(make_input(rid), request_id=rid,
                                      deadline_s=deadline_s)
            else:
                got = client.request(make_input(rid), request_id=rid,
                                     deadline_s=deadline_s)
            rec = {"event": "load", "action": "outcome", "id": rid,
                   "time": time.time(), "status": got.get("status"),
                   "reason": got.get("reason"),
                   "model_step": got.get("model_step"),
                   # which precision tier actually answered — the
                   # loadgen artifact's record of what a sweep measured
                   "tier": got.get("tier"),
                   "attempts": got.get("attempts"),
                   # retry amplification is measured, not inferred:
                   # True exactly when the terminal took > 1 attempt
                   "retried": bool(got.get("retried")),
                   "endpoint": got.get("endpoint"),
                   "latency_ms": got.get("latency_ms")}
            if decode:
                # decode latency is two numbers, not one: when the
                # first token landed, and how fast they kept coming
                rec["ttft_ms"] = got.get("ttft_ms")
                rec["itl_ms"] = got.get("itl_ms")
                rec["tokens"] = got.get("tokens_streamed")
            journal(rec)
            with out_lock:
                outcomes.append(rec)

    threads = [threading.Thread(target=worker, daemon=True,
                                name=f"loadgen-{i}")
               for i in range(max(1, concurrency))]
    for t in threads:
        t.start()

    done = threading.Event()

    def snapshotter() -> None:
        every = snapshot_every_s if snapshot_every_s > 0 else window_s / 2
        while not done.wait(every):
            with out_lock:
                snap = summarize_window(outcomes, issued[0],
                                        time.time(), window_s)
            journal({"event": "load", "action": "window",
                     "time": time.time(), **snap})

    snap_thread = None
    if sink is not None and window_s > 0:
        snap_thread = threading.Thread(target=snapshotter, daemon=True,
                                       name="loadgen-window")
        snap_thread.start()

    for t in threads:
        # closed-loop workers exit on their own (count reached or stop
        # set); the join bounds a wedged worker by its own deadline
        t.join()
    duration = time.time() - t_start
    if snap_thread is not None:
        done.set()
        snap_thread.join()
    if sink is not None:
        sink.close()
    return summarize_outcomes(outcomes, issued[0], duration)


def summarize_window(outcomes: list[dict], issued: int, now: float,
                     window_s: float) -> dict[str, Any]:
    """The rolling-window pressure snapshot — a pure function of the
    outcome records whose ``time`` falls in ``[now - window_s, now]``
    (deterministic in its inputs; the broker's property tests feed it
    synthetic traces). Latency/TTFT percentiles appear only when the
    window saw ok responses carrying them."""
    recent = [r for r in outcomes
              if isinstance(r.get("time"), (int, float))
              and r["time"] >= now - window_s]
    ok = [r for r in recent if r.get("status") == "ok"]
    rejected = [r for r in recent if r.get("status") == "rejected"]
    errors = [r for r in recent if r.get("status") == "error"]
    retried = [r for r in recent if r.get("retried")]
    out: dict[str, Any] = {
        "window_s": window_s,
        "issued": issued,
        "terminal": len(recent),
        "responses": len(ok),
        "rejected": len(rejected),
        "errors": len(errors),
        "reject_rate": round(len(rejected) / max(1, len(recent)), 4),
        # retry amplification under faults, surfaced live: the share
        # of window terminals that needed more than one attempt
        "retried": len(retried),
        "retry_rate": round(len(retried) / max(1, len(recent)), 4),
        "throughput_rps": round(len(recent) / max(window_s, 1e-9), 2),
    }
    lat = sorted(r["latency_ms"] for r in ok
                 if isinstance(r.get("latency_ms"), (int, float)))
    if lat:
        out["p50_ms"] = _percentile(lat, 0.50)
        out["p99_ms"] = _percentile(lat, 0.99)
    ttft = sorted(r["ttft_ms"] for r in ok
                  if isinstance(r.get("ttft_ms"), (int, float)))
    if ttft:
        out["ttft_p50_ms"] = _percentile(ttft, 0.50)
        out["ttft_p99_ms"] = _percentile(ttft, 0.99)
    return out


def read_latest_window(journal_path: str | Path,
                       tail_bytes: int = 1 << 16) -> dict | None:
    """The newest ``window`` snapshot in a (possibly still-growing)
    loadgen journal, or None. Reads only the file tail and scans
    backwards past torn lines (obsv/journal.py ``tail_records``) — the
    broker polls this every second against a journal another process
    is appending to."""
    for rec in tail_records(journal_path, tail_bytes=tail_bytes):
        if rec.get("event") == "load" and rec.get("action") == "window":
            return rec
    return None


def summarize_outcomes(outcomes: list[dict], issued: int,
                       duration_s: float) -> dict[str, Any]:
    ok = [r for r in outcomes if r.get("status") == "ok"]
    rejected = [r for r in outcomes if r.get("status") == "rejected"]
    errors = [r for r in outcomes if r.get("status") == "error"]
    lat = sorted(r["latency_ms"] for r in ok
                 if isinstance(r.get("latency_ms"), (int, float)))
    by_reason: dict[str, int] = {}
    for r in rejected + errors:
        key = f"{r.get('status')}:{r.get('reason')}"
        by_reason[key] = by_reason.get(key, 0) + 1
    steps = sorted({r["model_step"] for r in ok
                    if isinstance(r.get("model_step"), int)})
    # which precision tier(s) answered; a pre-quantization journal has
    # no tier field — those responses count as fp32 (the legacy path)
    tiers = sorted({r.get("tier") or "fp32" for r in ok})
    out: dict[str, Any] = {
        "issued": issued,
        "terminal": len(outcomes),
        # issued − terminal: every request MUST reach a terminal
        # outcome; nonzero here is the silent drop the tier forbids
        "dropped": issued - len(outcomes),
        "responses": len(ok),
        "rejected": len(rejected),
        "errors": len(errors),
        "by_reason": by_reason,
        # terminals that took >1 attempt — under net faults this is the
        # retry amplification the dedup cache must absorb
        "retried": sum(1 for r in outcomes if r.get("retried")),
        "reject_rate": round(len(rejected) / max(1, len(outcomes)), 4),
        "duration_s": round(duration_s, 3),
        "throughput_rps": round(len(outcomes) / max(duration_s, 1e-9), 2),
        "model_steps_served": steps,
        "tiers_served": tiers,
    }
    if lat:
        out["latency_ms"] = {"p50": _percentile(lat, 0.50),
                             "p90": _percentile(lat, 0.90),
                             "p99": _percentile(lat, 0.99),
                             "max": lat[-1],
                             "mean": round(sum(lat) / len(lat), 3)}
    # decode sweeps: the per-request two-number latency split — TTFT
    # (prefill + queueing) and mean inter-token gap — aggregated only
    # when the records carry them (classification records don't)
    ttft = sorted(r["ttft_ms"] for r in ok
                  if isinstance(r.get("ttft_ms"), (int, float)))
    if ttft:
        out["ttft_ms"] = {"p50": _percentile(ttft, 0.50),
                          "p99": _percentile(ttft, 0.99),
                          "max": ttft[-1],
                          "mean": round(sum(ttft) / len(ttft), 3)}
    itl = sorted(r["itl_ms"] for r in ok
                 if isinstance(r.get("itl_ms"), (int, float)))
    if itl:
        out["inter_token_ms"] = {"p50": _percentile(itl, 0.50),
                                 "p99": _percentile(itl, 0.99),
                                 "max": itl[-1]}
    tokens = sum(r["tokens"] for r in ok
                 if isinstance(r.get("tokens"), int))
    if tokens:
        out["tokens_streamed"] = tokens
        out["tokens_per_sec"] = round(tokens / max(duration_s, 1e-9), 2)
    return out


def load_outcomes(journal_path: str | Path) -> tuple[list[dict],
                                                     list[dict]]:
    """(issues, outcomes) from a loadgen journal — what the serving
    invariants replay."""
    from ..obsv.report import load_jsonl
    records = load_jsonl(journal_path, "load")
    return ([r for r in records if r.get("action") == "issue"],
            [r for r in records if r.get("action") == "outcome"])
