"""Continuous-batching autoregressive decode replica.

The generation face of the serving tier: same replica contract as
:class:`~.server.ServingReplica` (supervised process, bounded
admission queue, heartbeats, digest-verified weight follow, typed
rejects, zero-drop teardown) with the workload inside it changed from
one-shot classification to streaming decode — the
resource-shape-agnostic-replica move (arXiv:1902.00465): the
supervisor, chaos schedules and invariants apply unchanged.

**Continuous batching.** The replica holds ``decode.decode_slots``
concurrently-generating sequences. Each loop iteration runs ONE
compiled decode step over all of them — a fixed ``[slots]`` shape
whatever mix of lengths is in flight, because every sequence reads its
K/V through its block table over the shared paged cache
(:mod:`.kv_cache`). A sequence that finishes (EOS / max_tokens /
deadline / client gone) frees its blocks and its slot is refilled from
the admission queue the SAME iteration — no padded rounds, no waiting
for a batch to drain.

**Prefill.** Prompts are admitted through the existing bounded queue
(typed ``overloaded`` shed when full), padded to power-of-2 buckets
(each bucket's prefill compiles once) and run through the model's
``decode_prefill`` export — the standard causal forward through the
CONFIGURED attention kernel (the fused pallas flash path when
``model.attention_impl=flash``) that also returns every layer's K/V,
scattered into the sequence's blocks. The first token samples off the
prefill logits: time-to-first-token is one prefill, not a decode-queue
wait.

**Weight swaps mid-generation.** The checkpoint follower stages
digest-verified publishes exactly as the classification replica does;
the flip happens at a decode-loop boundary under a declared policy
(``decode.swap_policy``):

* ``pin`` — every in-flight sequence keeps generating on the params it
  started with until it finishes; new admissions use the new weights.
  At most a handful of param versions are live (bounded by slots), and
  a version is dropped the moment its last pinned sequence finishes.
* ``restart`` — every in-flight sequence is re-prefilled on the new
  weights (its streamed tokens are discarded; the stream carries an
  explicit ``restart`` marker so clients reset), journaled per
  sequence as ``seq_restart``.

Either way the swap record grows ``sequences_pinned`` /
``sequences_restarted``, and the ``decode_swap`` replay invariant
(obsv/invariants.py, invariant 10) checks the books: a sequence that
finishes on a different model step than it started on MUST hold a
journaled ``seq_restart`` license, and every ``seq_restart`` must
follow a journaled ``weight_swap`` to its target step.

Wire protocol (one connection per request, line-delimited JSON):

  request:  {"id": ..., "prompt": [int, ...], "max_tokens": N,
             "temperature": t, "top_k": k, "deadline_ms": ...}
  stream:   {"id": ..., "stream": "token", "token": t, "index": i,
             "model_step": s}        (one line per generated token)
            {"id": ..., "stream": "restart", "model_step": s}
            (key "stream", not "event" — journal records own that key)
  terminal: {"id": ..., "status": "ok", "tokens": [...],
             "finish_reason": "eos" | "max_tokens" | "deadline" |
             "client_gone", "model_step": s, "started_step": s0}
            {"id": ..., "status": "rejected", "reason": ...}
"""

from __future__ import annotations

import collections
import functools
import json
import queue
import time

import jax
import jax.numpy as jnp
import numpy as np

from ..core.config import ConfigError
from ..models.registry import sample_token
from .kv_cache import PagedKVCache
from .server import ServingReplica, _Pending


class _DecodeSeq(_Pending):
    """One in-flight generation (``inputs`` holds the prompt)."""

    __slots__ = ("max_tokens", "temperature", "top_k", "block_table",
                 "length", "tokens", "params_step", "started_step",
                 "first_token_at", "restarts", "conn_dead", "sample_seed")

    def __init__(self, req_id, prompt, conn, admitted_at, deadline_at):
        super().__init__(req_id, prompt, conn, admitted_at, deadline_at)
        self.max_tokens = 0
        self.temperature = 0.0
        self.top_k = 0
        self.block_table = None
        self.length = 0            # context tokens written to the cache
        self.tokens: list[int] = []
        self.params_step = -1
        self.started_step = -1
        self.first_token_at: float | None = None
        self.restarts = 0
        self.conn_dead = False
        self.sample_seed = 0


class DecodeReplica(ServingReplica):
    """Hot-follow published checkpoints and stream autoregressive
    generations with continuous batching over a paged KV cache."""

    def __init__(self, train_dir, serve_dir=".", scfg=None, dcfg=None,
                 cfg=None, topo=None):
        super().__init__(train_dir, serve_dir=serve_dir, scfg=scfg,
                         cfg=cfg, topo=topo)
        if self.tier != "fp32":
            raise ConfigError(
                f"serve.precision_tier={self.tier!r}: the decode "
                "service serves full precision only (quant sidecars "
                "hold weights for the one-shot predict export, not the "
                "decode graph)")
        if (self.model.decode_prefill is None
                or self.model.decode_step is None):
            raise ConfigError(
                f"model {self.cfg.model.name!r} exports no decode step "
                "(decode needs a dense-FFN causal LM; MoE and "
                "classifier families have no incremental export)")
        self.dcfg = dcfg or self.cfg.decode
        self.dcfg.validate()
        if (self.dcfg.max_prompt_len + self.dcfg.max_new_tokens
                > self.cfg.model.seq_len):
            raise ConfigError(
                f"decode.max_prompt_len + decode.max_new_tokens = "
                f"{self.dcfg.max_prompt_len + self.dcfg.max_new_tokens} "
                f"exceeds model.seq_len={self.cfg.model.seq_len} (the "
                "learned position table is the hard context ceiling)")
        from ..core.config import effective_model_config
        dtype = jnp.dtype(
            effective_model_config(self.cfg, serving=True).compute_dtype)
        layers, heads, head_dim = self.model.decode_cache_shape
        self.cache = PagedKVCache(
            layers, self.dcfg.num_blocks, self.dcfg.block_size,
            heads, head_dim, self.dcfg.max_blocks_per_seq(), dtype=dtype)
        self._prefill_jit = jax.jit(self.model.decode_prefill)
        # the cache arrays are rebound to the step's outputs at every
        # call site — donate them so XLA updates in place instead of
        # copying the whole [L, N, B, h, hd] pair per generated token
        self._decode_jit = jax.jit(
            functools.partial(self.model.decode_step,
                              block_size=self.dcfg.block_size,
                              attention_kernel=self.dcfg.attention_kernel),
            donate_argnums=(3, 4))
        # decode-loop-owned state (single writer: the batcher thread)
        self._slots: list[_DecodeSeq | None] = (
            [None] * self.dcfg.decode_slots)
        self._waiting: collections.deque[_DecodeSeq] = collections.deque()
        self._versions: dict[int, object] = {}  # pinned old params
        self._seq_counter = 0
        self.tokens_streamed = 0
        self.sequences_finished = 0
        # block-table upload cache: slot→block assignments only change
        # on admit/finish/restart, so the [slots, width] tables array a
        # decode iteration feeds the jitted step is IDENTICAL between
        # those events — rebuild + re-upload it once per (params
        # version, table epoch) instead of every generated token. The
        # epoch counter is bumped by every mutation of any slot's table
        # or version assignment; bumping clears the cache.
        self._tables_epoch = 0
        self._tables_cache: dict[tuple[int, int], jax.Array] = {}
        self.table_uploads = 0
        self.table_upload_reuses = 0

    # -- admission ------------------------------------------------------

    def _build_item(self, req: dict, conn):
        req_id = req.get("id")
        try:
            prompt = np.asarray(req["prompt"], dtype=np.int32)
            if (prompt.ndim != 1 or prompt.size < 1
                    or prompt.size > self.dcfg.max_prompt_len):
                raise ValueError("prompt length out of range")
            if (int(prompt.min()) < 0
                    or int(prompt.max()) >= self.cfg.model.vocab_size):
                raise ValueError("token id out of vocab")
            max_tokens = int(req.get("max_tokens",
                                     self.dcfg.max_new_tokens))
            if not 1 <= max_tokens <= self.dcfg.max_new_tokens:
                raise ValueError("max_tokens out of range")
            temperature = float(req.get("temperature",
                                        self.dcfg.temperature))
            top_k = int(req.get("top_k", self.dcfg.top_k))
        except (KeyError, ValueError, TypeError):
            self._reject(conn, req_id, "bad_request", admitted=False)
            return None
        now = time.time()
        deadline_ms = req.get("deadline_ms",
                              self.scfg.default_deadline_ms)
        # streaming sends run on the SINGLE decode-loop thread: a
        # client that stopped reading must cost the loop a short
        # bounded stall ONCE (then conn_dead), never the accept-side
        # 5 s timeout per token — one stalled reader must not freeze
        # every other slot's generation
        try:
            conn.settimeout(0.5)
        except OSError:
            pass
        seq = _DecodeSeq(req_id, prompt, conn, now,
                         now + float(deadline_ms) / 1e3)
        seq.max_tokens = max_tokens
        seq.temperature = temperature
        seq.top_k = top_k
        return seq

    # -- weights: version registry + swap policies ----------------------

    def _params_for(self, step: int):
        return (self._params if step == self.model_step
                else self._versions[step])

    def _release_version(self, step: int) -> None:
        if step == self.model_step or step not in self._versions:
            return
        if not any(s is not None and s.params_step == step
                   for s in self._slots):
            del self._versions[step]

    def _maybe_swap(self) -> None:
        """Decode-loop-boundary flip under the declared mid-generation
        policy; journals the swap with its per-sequence bookkeeping."""
        with self._staged_lock:
            staged, self._staged = self._staged, None
        if staged is None:
            return
        install, t0 = staged
        if install["step"] <= self.model_step:
            return  # monotone: never swap backwards
        in_flight = [s for s in self._slots if s is not None]
        prev_step = self.model_step
        pinned = restarted = 0
        if in_flight:
            if self.dcfg.swap_policy == "pin":
                pinned = len(in_flight)
                if any(s.params_step == prev_step for s in in_flight):
                    # stash only a version something actually runs on:
                    # back-to-back swaps with everything pinned to an
                    # even older version must not leak the middle one
                    self._versions[prev_step] = self._params
            else:
                restarted = len(in_flight)
        self._install(install, t0,
                      extra={"sequences_pinned": pinned,
                             "sequences_restarted": restarted})
        if restarted:
            for s in in_flight:
                self._restart_seq(s, prev_step)

    def _restart_seq(self, s: _DecodeSeq, from_step: int) -> None:
        """The restart policy's per-sequence move: discard what the old
        params generated, re-prefill on the new — journaled as the
        causal license the decode_swap invariant requires."""
        self._journal({"action": "seq_restart", "id": s.req_id,
                       "from_step": from_step,
                       "to_step": self.model_step,
                       "tokens_discarded": len(s.tokens)})
        self._send_line(s, {"id": s.req_id, "stream": "restart",
                            "model_step": self.model_step})
        s.tokens = []
        s.length = 0
        s.restarts += 1
        s.params_step = self.model_step
        self._bump_tables_epoch()  # version composition changed
        # ttft is a property of the stream the client KEEPS: the
        # pre-restart first token was discarded, so the journaled
        # decode_finish must time the post-restart one (matching what
        # the client-side loadgen measures after its reset)
        s.first_token_at = None
        self._prefill(s, restart=True)

    def _pressure_fields(self) -> dict:
        """Queue occupancy plus KV block-pool pressure: free blocks
        against the usable pool (block 0 is the reserved null block)
        and the deferred-admission line — a pool near empty is the
        decode-side signal the broker scales on. Reads only; the
        allocator's single writer is this same batcher thread."""
        alloc = self.cache.allocator
        return {**super()._pressure_fields(),
                "kv_blocks_free": alloc.available,
                "kv_blocks_total": alloc.num_blocks - 1,
                "kv_blocks_reserved": len(alloc.in_use),
                "decode_waiting": len(self._waiting)}

    # -- the decode loop ------------------------------------------------

    def _batch_loop(self) -> None:  # overrides the classification batcher
        while not self._stop.is_set():
            self._maybe_swap()
            self._admit_new()
            self._step_active()
            self._maybe_heartbeat()
        # graceful drain: in-flight generations, deferred admissions
        # and everything still queued get a TYPED terminal — a
        # stopping replica sheds, it never silently drops
        for i, s in enumerate(self._slots):
            if s is not None:
                self._slots[i] = None
                self.cache.free_sequence(s.block_table)
                self._reject(s.conn, s.req_id, "shutting_down",
                             admitted=True)
        while self._waiting:
            s = self._waiting.popleft()
            self._reject(s.conn, s.req_id, "shutting_down", admitted=True)
        while True:
            try:
                it = self._queue.get_nowait()
            except queue.Empty:
                break
            self._reject(it.conn, it.req_id, "shutting_down",
                         admitted=True)
        self._maybe_heartbeat()

    def _admit_new(self) -> None:
        """Refill free slots from the admission queue. Block pressure
        (the free list cannot hold another worst-case sequence) defers
        the admission — bounded by the request's own deadline — rather
        than evicting a running generation."""
        idle = (not self._waiting
                and all(s is None for s in self._slots))
        try:
            # idle: park briefly on the queue instead of spinning.
            # _waiting is capped at the slot count — anything beyond
            # stays in the BOUNDED socket queue, so sustained block
            # pressure still sheds typed `overloaded` rejects at
            # admission instead of growing an unbounded staging line
            while len(self._waiting) < self.dcfg.decode_slots:
                self._waiting.append(
                    self._queue.get(timeout=0.05) if idle
                    else self._queue.get_nowait())
                idle = False
        except queue.Empty:
            pass
        while self._waiting:
            free = next((i for i, s in enumerate(self._slots)
                         if s is None), None)
            if free is None:
                return
            s = self._waiting[0]
            if time.time() >= s.deadline_at:
                self._waiting.popleft()
                self._reject(s.conn, s.req_id, "deadline_exceeded",
                             admitted=True)
                continue
            table = self.cache.alloc_sequence(
                int(s.inputs.size) + s.max_tokens)
            if table is None:
                return  # block pressure: retry next iteration
            self._waiting.popleft()
            s.block_table = table
            s.params_step = s.started_step = self.model_step
            s.sample_seed = self._seq_counter
            self._seq_counter += 1
            self._slots[free] = s
            self._bump_tables_epoch()
            self._prefill(s)

    def _prefill(self, s: _DecodeSeq, restart: bool = False) -> None:
        """Run the prompt through the model's prefill export (the
        configured attention kernel), seed the paged cache, and sample
        + stream the first token."""
        t0 = time.time()
        plen = int(s.inputs.size)
        bucket = self._bucket(plen, self.dcfg.max_prompt_len)
        toks = np.zeros((1, bucket), np.int32)
        toks[0, :plen] = s.inputs
        logits, ks, vs = self._prefill_jit(
            self._params_for(s.params_step), jnp.asarray(toks))
        self.cache.write_prompt(s.block_table, ks[:, 0], vs[:, 0], plen)
        s.length = plen
        tok = self._sample(s, logits[0, plen - 1])
        s.tokens.append(tok)
        self._stream_token(s, tok)
        rec = {"action": "prefill", "id": s.req_id, "prompt_len": plen,
               "bucket": bucket,
               "blocks": int(np.count_nonzero(s.block_table)),
               "model_step": s.params_step,
               "ttft_ms": round((time.time() - t0) * 1e3, 3)}
        if restart:
            rec["restart"] = True
        self._journal(rec)
        self._maybe_finish(self._slots.index(s), s)

    def _bump_tables_epoch(self) -> None:
        """Invalidate cached block-table uploads — called by every
        mutation of a slot's table or params-version assignment
        (admit, finish, restart)."""
        self._tables_epoch += 1
        self._tables_cache.clear()

    def _tables_for(self, ver: int, mine, num_slots: int,
                    width: int) -> jax.Array:
        """The device-resident [slots, width] block-tables array for
        one params version's compiled step. Rows of slots NOT on this
        version are zero (the null block) — load-bearing, not padding:
        the step scatters the new token's K/V through row
        ``positions[i] // block_size`` of EVERY slot, and zero routes
        the not-mine writes into the reserved null block instead of a
        live sequence's block 0. Cached per (version, table epoch):
        between admit/finish/restart events the array is bit-identical
        every iteration, so steady-state decoding reuses one upload
        instead of paying a host rebuild + transfer per token
        (measured in bench_decode_throughput's ``table_prep`` detail).
        """
        key = (ver, self._tables_epoch)
        cached = self._tables_cache.get(key)
        if cached is not None:
            self.table_upload_reuses += 1
            return cached
        tables = np.zeros((num_slots, width), np.int32)
        for i, s in mine:
            tables[i] = s.block_table
        dev = jnp.asarray(tables)
        self._tables_cache[key] = dev
        self.table_uploads += 1
        return dev

    def _step_active(self) -> None:
        """One decode iteration: a single compiled step per live param
        version over the fixed slot shape, then per-slot sample /
        stream / finish — a finished slot is free for the NEXT
        iteration's refill."""
        now = time.time()
        for i, s in enumerate(self._slots):
            if s is not None and now >= s.deadline_at:
                self._finish_seq(i, s, "deadline")
        active = [(i, s) for i, s in enumerate(self._slots)
                  if s is not None]
        if not active:
            return
        num_slots = self.dcfg.decode_slots
        width = self.cache.max_blocks_per_seq
        # pin policy: at most a handful of live versions — one compiled
        # step per version, idle-for-this-version slots masked via the
        # null block table + zero length
        for ver in sorted({s.params_step for _, s in active}):
            mine = [(i, s) for i, s in active if s.params_step == ver]
            tokens = np.zeros((num_slots,), np.int32)
            positions = np.zeros((num_slots,), np.int32)
            lengths = np.zeros((num_slots,), np.int32)
            for i, s in mine:
                tokens[i] = s.tokens[-1]
                positions[i] = s.length
                lengths[i] = s.length + 1
            logits, self.cache.k, self.cache.v = self._decode_jit(
                self._params_for(ver), jnp.asarray(tokens),
                jnp.asarray(positions), self.cache.k, self.cache.v,
                self._tables_for(ver, mine, num_slots, width),
                jnp.asarray(lengths))
            logits = np.asarray(jax.device_get(logits))
            for i, s in mine:
                s.length += 1  # the fed token's K/V is now cached
                tok = self._sample(s, logits[i])
                s.tokens.append(tok)
                self._stream_token(s, tok)
                self._maybe_finish(i, s)

    def _sample(self, s: _DecodeSeq, logits_row) -> int:
        if s.temperature <= 0.0:
            return int(sample_token(jnp.asarray(logits_row)))
        key = jax.random.fold_in(
            jax.random.PRNGKey(s.sample_seed),
            len(s.tokens) + 1000 * s.restarts)
        return int(sample_token(jnp.asarray(logits_row), key,
                                temperature=s.temperature,
                                top_k=s.top_k))

    # -- streaming + termination ----------------------------------------

    def _send_line(self, s: _DecodeSeq, payload: dict) -> None:
        if s.conn_dead:
            return
        try:
            s.conn.sendall((json.dumps(payload) + "\n").encode())
        except OSError:
            s.conn_dead = True  # finish early at the next check

    def _stream_token(self, s: _DecodeSeq, tok: int) -> None:
        if s.first_token_at is None:
            s.first_token_at = time.time()
        self.tokens_streamed += 1
        self._send_line(s, {"id": s.req_id, "stream": "token",
                            "token": int(tok),
                            "index": len(s.tokens) - 1,
                            "model_step": s.params_step})

    def _maybe_finish(self, i: int, s: _DecodeSeq) -> None:
        eos = self.dcfg.eos_token
        if eos >= 0 and s.tokens and s.tokens[-1] == eos:
            self._finish_seq(i, s, "eos")
        elif len(s.tokens) >= s.max_tokens:
            self._finish_seq(i, s, "max_tokens")
        elif s.conn_dead:
            self._finish_seq(i, s, "client_gone")
        elif time.time() >= s.deadline_at:
            self._finish_seq(i, s, "deadline")

    def _finish_seq(self, i: int, s: _DecodeSeq, reason: str) -> None:
        """Exactly-one-terminal: journal the finish, send the final
        line, free the blocks, release the slot (refillable this very
        iteration) and drop the param version if this was its last
        pinned sequence."""
        now = time.time()
        fields = {"reason": reason, "tokens_streamed": len(s.tokens),
                  "model_step": s.params_step,
                  "started_step": s.started_step,
                  "latency_ms": round((now - s.admitted_at) * 1e3, 3)}
        if s.first_token_at is not None:
            fields["ttft_ms"] = round(
                (s.first_token_at - s.admitted_at) * 1e3, 3)
        if s.restarts:
            fields["restarts"] = s.restarts
        self._terminal("decode_finish", s.req_id, **fields)
        payload = {
            "id": s.req_id, "status": "ok",
            "tokens": [int(t) for t in s.tokens],
            "finish_reason": reason, "model_step": s.params_step,
            "started_step": s.started_step}
        # idempotency: a mid-stream reset that ate this terminal makes
        # the retry a dedup hit carrying the SAME completed tokens —
        # the generation never runs twice for one request id
        self._dedup_put(s.req_id, payload)
        self._respond(s.conn, payload)
        self._slots[i] = None
        self.cache.free_sequence(s.block_table)
        self._bump_tables_epoch()
        self._release_version(s.params_step)
        self.sequences_finished += 1

    # -- metadata / lifecycle -------------------------------------------

    def _meta(self) -> dict:
        return {"status": "ok", "meta": True, "decode": True,
                "model": self.cfg.model.name,
                "vocab_size": self.cfg.model.vocab_size,
                "model_step": self.model_step,
                "model_digest": self.model_digest,
                "precision_tier": self.tier,
                "active_tier": self.model_tier,
                "decode_slots": self.dcfg.decode_slots,
                "block_size": self.dcfg.block_size,
                "num_blocks": self.dcfg.num_blocks,
                "max_prompt_len": self.dcfg.max_prompt_len,
                "max_new_tokens": self.dcfg.max_new_tokens,
                "eos_token": self.dcfg.eos_token,
                "swap_policy": self.dcfg.swap_policy}

    def start(self) -> None:
        super().start()
        self._journal({"action": "decode_start",
                       "slots": self.dcfg.decode_slots,
                       "block_size": self.dcfg.block_size,
                       "num_blocks": self.dcfg.num_blocks,
                       "max_prompt_len": self.dcfg.max_prompt_len,
                       "max_new_tokens": self.dcfg.max_new_tokens,
                       "swap_policy": self.dcfg.swap_policy,
                       "model_step": self.model_step})
