"""Test harness: 8 virtual CPU devices for SPMD semantics.

This is the mock distributed backend the reference never had
(SURVEY §4): quorum masks, psum semantics, interval windows, and
checkpoint round-trips are all exercised against a simulated 8-device
mesh on one CPU host. Platform setup MUST happen before any test
import initializes the XLA backend.
"""

import os

# Journal-schema enforcement ON for every test run: records the AST
# pass (distributedmnist_tpu.analysis, "graftcheck") can't see as
# literal dicts still get checked against obsv/schema.py at write time
# (core/log.py JsonlSink). Set before anything writes — the sink
# samples the gate on its FIRST write and freezes it for the process
# (hot path); per-call toggling only affects schema.maybe_check_event.
os.environ.setdefault("DMT_VALIDATE_EVENTS", "1")

from distributedmnist_tpu.core.mesh import simulate_devices  # noqa: E402

simulate_devices(8)

import jax  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def topo8():
    from distributedmnist_tpu.core.mesh import make_topology
    assert len(jax.devices()) == 8, "conftest failed to create 8 CPU devices"
    return make_topology()


@pytest.fixture()
def tmp_train_dir(tmp_path):
    return str(tmp_path / "train")


@pytest.fixture(scope="session")
def synthetic_datasets():
    from distributedmnist_tpu.data.datasets import make_synthetic
    return make_synthetic(num_train=2048, num_test=512)


# ---- jax-0.4.37 check_rep shim vs the gold-parity tests ----------------
#
# On jax < 0.4.38, core/mesh.py installs its check_rep=False shard_map
# shim (mesh.CHECK_REP_SHIM): the replication checker is off and
# jax.lax.pcast degrades to an identity. Two measured consequences for
# the sharded-vs-dense parity tests (moe/pp/tp):
#   * cross-shard reductions REASSOCIATE relative to the dense
#     single-device program — float32 forward/loss parity holds only
#     to ~1e-4, hence the shim-conditional 2e-4 loss tolerance;
#   * pcast's transpose (a psum) is DROPPED from backward passes, so
#     parameter-update parity is structurally broken (measured up to
#     ~1e-2 of param scale — a missing reduction, not noise). No
#     tolerance can honestly cover that, so under the shim
#     assert_update_parity skips the param comparison; loss/forward
#     parity still gates, and jax >= 0.4.38 runs the full check.
from distributedmnist_tpu.core.mesh import CHECK_REP_SHIM  # noqa: E402

LOSS_TOL = (dict(rtol=2e-4, atol=2e-4) if CHECK_REP_SHIM
            else dict(rtol=2e-5, atol=2e-5))


def assert_update_parity(got, want, rtol=3e-4, atol=3e-5):
    """Leaf-wise sharded-vs-dense post-update parameter comparison —
    skipped under the check_rep=False shim (see the note above)."""
    import numpy as np
    if CHECK_REP_SHIM:
        return
    for a, b in zip(jax.tree.leaves(got), jax.tree.leaves(want)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=rtol, atol=atol)


def base_config(**overrides):
    """Small fast config for tests; sections overridable via dicts."""
    from distributedmnist_tpu.core.config import ExperimentConfig
    d = {
        "data": {"dataset": "synthetic", "batch_size": 64,
                 "synthetic_train_size": 1024, "synthetic_test_size": 256,
                 "use_native_pipeline": False},
        "model": {"compute_dtype": "float32"},
        "train": {"max_steps": 10, "log_every_steps": 5,
                  "save_interval_steps": 0, "save_results_period": 0},
    }
    for k, v in overrides.items():
        if isinstance(v, dict) and k in d:
            d[k].update(v)
        else:
            d[k] = v
    return ExperimentConfig.from_dict(d)
