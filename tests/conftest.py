"""Test harness: 8 virtual CPU devices for SPMD semantics.

This is the mock distributed backend the reference never had
(SURVEY §4): quorum masks, psum semantics, interval windows, and
checkpoint round-trips are all exercised against a simulated 8-device
mesh on one CPU host. Platform setup MUST happen before any test
import initializes the XLA backend.
"""

from distributedmnist_tpu.core.mesh import simulate_devices

simulate_devices(8)

import jax  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def topo8():
    from distributedmnist_tpu.core.mesh import make_topology
    assert len(jax.devices()) == 8, "conftest failed to create 8 CPU devices"
    return make_topology()


@pytest.fixture()
def tmp_train_dir(tmp_path):
    return str(tmp_path / "train")


@pytest.fixture(scope="session")
def synthetic_datasets():
    from distributedmnist_tpu.data.datasets import make_synthetic
    return make_synthetic(num_train=2048, num_test=512)


def base_config(**overrides):
    """Small fast config for tests; sections overridable via dicts."""
    from distributedmnist_tpu.core.config import ExperimentConfig
    d = {
        "data": {"dataset": "synthetic", "batch_size": 64,
                 "synthetic_train_size": 1024, "synthetic_test_size": 256,
                 "use_native_pipeline": False},
        "model": {"compute_dtype": "float32"},
        "train": {"max_steps": 10, "log_every_steps": 5,
                  "save_interval_steps": 0, "save_results_period": 0},
    }
    for k, v in overrides.items():
        if isinstance(v, dict) and k in d:
            d[k].update(v)
        else:
            d[k] = v
    return ExperimentConfig.from_dict(d)
