"""Real-data path: idx read/write round-trips (raw and gz) and the
fetch-with-cache downloader (≙ maybe_download, reference
src/mnist_data.py:176-187) — exercised with real files on disk and a
mocked network, including the no-egress degrade and corrupt-download
purge paths."""

import gzip
import io
import struct

import numpy as np
import pytest

from distributedmnist_tpu.core.config import DataConfig
from distributedmnist_tpu.data import datasets as ds

pytestmark = pytest.mark.tier1


def _fixture_arrays(n_train=32, n_test=16, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "train_images": rng.integers(0, 256, (n_train, 28, 28), np.uint8),
        "train_labels": rng.integers(0, 10, (n_train,), np.uint8),
        "test_images": rng.integers(0, 256, (n_test, 28, 28), np.uint8),
        "test_labels": rng.integers(0, 10, (n_test,), np.uint8),
    }


def _write_fixture_dir(root, gz: bool, arrays=None):
    arrays = arrays or _fixture_arrays()
    suffix = ".gz" if gz else ""
    for key, arr in arrays.items():
        name = ds._IDX_FILES[key][0] + suffix
        ds.write_idx_ubyte(root / name, arr)
    return arrays


@pytest.mark.parametrize("gz", [False, True], ids=["raw", "gz"])
def test_idx_roundtrip(tmp_path, gz):
    arrays = _write_fixture_dir(tmp_path, gz)
    suffix = ".gz" if gz else ""
    img = ds.read_idx_images(
        tmp_path / (ds._IDX_FILES["train_images"][0] + suffix))
    lab = ds.read_idx_labels(
        tmp_path / (ds._IDX_FILES["train_labels"][0] + suffix))
    # [-0.5, 0.5] normalization parity (reference src/mnist_data.py:142)
    want = (arrays["train_images"].astype(np.float32) - 127.5) / 255.0
    np.testing.assert_allclose(img[..., 0], want)
    np.testing.assert_array_equal(lab, arrays["train_labels"])
    assert img.dtype == np.float32 and lab.dtype == np.int32


def test_load_idx_dataset_from_fixture(tmp_path):
    _write_fixture_dir(tmp_path, gz=True)
    d = ds.load_idx_dataset(tmp_path, validation_size=4)
    assert d.train.num_examples == 32 - 3  # 10% cap on validation carve
    assert d.validation.num_examples == 3
    assert d.test.num_examples == 16
    assert d.train.images.min() >= -0.5 and d.train.images.max() <= 0.5


class _FakeResponse:
    def __init__(self, payload: bytes):
        self._payload = payload

    def read(self) -> bytes:
        return self._payload

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def _gz_idx_payload(arr: np.ndarray) -> bytes:
    arr = np.ascontiguousarray(arr, np.uint8)
    raw = struct.pack(">HBB", 0, 0x08, arr.ndim)
    raw += struct.pack(f">{arr.ndim}I", *arr.shape) + arr.tobytes()
    buf = io.BytesIO()
    with gzip.GzipFile(fileobj=buf, mode="wb") as f:
        f.write(raw)
    return buf.getvalue()


def test_maybe_download_no_egress_degrades(tmp_path, monkeypatch):
    import urllib.request

    def refuse(url, timeout=None):
        raise OSError("no route to host")

    monkeypatch.setattr(urllib.request, "urlopen", refuse)
    assert ds.maybe_download(tmp_path, "mnist") is False
    assert not list(tmp_path.glob("*ubyte*"))  # nothing half-written
    # load_datasets falls back to synthetic, never raises
    cfg = DataConfig(dataset="mnist", data_dir=str(tmp_path),
                     synthetic_train_size=64, synthetic_test_size=32)
    d = ds.load_datasets(cfg)
    assert d.train.num_examples == 64


def test_maybe_download_purges_corrupt_files(tmp_path, monkeypatch):
    import urllib.request
    monkeypatch.setattr(urllib.request, "urlopen",
                        lambda url, timeout=None: _FakeResponse(b"garbage"))
    assert ds.maybe_download(tmp_path, "mnist") is False
    assert not list(tmp_path.glob("*")), "corrupt downloads must be purged"


def _pin_fixture_digests(monkeypatch, payloads):
    """Point the default mnist pins at the test fixture's payloads (the
    real pins would — correctly — reject fixture bytes)."""
    import hashlib
    monkeypatch.setattr(ds, "_PINNED_SHA256", {"mnist": {
        name: hashlib.sha256(data).hexdigest()
        for name, data in payloads.items()}})


def test_maybe_download_fetches_and_caches(tmp_path, monkeypatch):
    import urllib.request
    arrays = _fixture_arrays()
    payloads = {ds._IDX_FILES[k][0] + ".gz": _gz_idx_payload(v)
                for k, v in arrays.items()}
    calls = []

    def serve(url, timeout=None):
        calls.append(url)
        return _FakeResponse(payloads[url.rsplit("/", 1)[1]])

    monkeypatch.setattr(urllib.request, "urlopen", serve)
    _pin_fixture_digests(monkeypatch, payloads)
    assert ds.maybe_download(tmp_path, "mnist") is True
    assert len(calls) == 4
    # cache hit: nothing re-fetched
    assert ds.maybe_download(tmp_path, "mnist") is True
    assert len(calls) == 4
    # the moment files land, dataset='mnist' serves real data
    cfg = DataConfig(dataset="mnist", data_dir=str(tmp_path), download=False)
    d = ds.load_datasets(cfg)
    assert d.test.num_examples == 16
    np.testing.assert_array_equal(
        d.test.labels, arrays["test_labels"].astype(np.int32))


def test_load_datasets_downloads_when_missing(tmp_path, monkeypatch):
    """cfg.download=True wires maybe_download into the load path."""
    import urllib.request
    arrays = _fixture_arrays()
    payloads = {ds._IDX_FILES[k][0] + ".gz": _gz_idx_payload(v)
                for k, v in arrays.items()}
    monkeypatch.setattr(
        urllib.request, "urlopen",
        lambda url, timeout=None: _FakeResponse(payloads[url.rsplit("/", 1)[1]]))
    _pin_fixture_digests(monkeypatch, payloads)
    cfg = DataConfig(dataset="mnist", data_dir=str(tmp_path))
    d = ds.load_datasets(cfg)
    assert d.test.num_examples == 16  # real data, not the synthetic fallback


def test_download_lands_in_per_dataset_subdir(tmp_path, monkeypatch):
    """mnist and fashion_mnist share file names; the cache must not
    cross-serve between them."""
    import urllib.request
    arrays = _fixture_arrays()
    payloads = {ds._IDX_FILES[k][0] + ".gz": _gz_idx_payload(v)
                for k, v in arrays.items()}
    monkeypatch.setattr(
        urllib.request, "urlopen",
        lambda url, timeout=None: _FakeResponse(payloads[url.rsplit("/", 1)[1]]))
    _pin_fixture_digests(monkeypatch, payloads)
    cfg = DataConfig(dataset="mnist", data_dir=str(tmp_path))
    ds.load_datasets(cfg)
    assert (tmp_path / "mnist" / "train-images-idx3-ubyte.gz").exists()
    # a fashion_mnist run with the same data_dir must NOT see that cache
    assert ds._find_idx(tmp_path / "fashion_mnist",
                        ds._IDX_FILES["train_images"]) is None


def test_checksum_mismatch_rejected(tmp_path, monkeypatch):
    import urllib.request
    arrays = _fixture_arrays()
    payloads = {ds._IDX_FILES[k][0] + ".gz": _gz_idx_payload(v)
                for k, v in arrays.items()}
    monkeypatch.setattr(
        urllib.request, "urlopen",
        lambda url, timeout=None: _FakeResponse(payloads[url.rsplit("/", 1)[1]]))
    bad = {ds._IDX_FILES[k][0] + ".gz": "0" * 64 for k in ds._IDX_FILES}
    assert ds.maybe_download(tmp_path, "mnist", expected_sha256=bad) is False
    assert not list(tmp_path.glob("*ubyte*"))


def test_default_pins_reject_substituted_archive(tmp_path, monkeypatch):
    """The shipped sha256 pins apply BY DEFAULT: a well-formed idx
    archive with the wrong bytes (hostile-mirror substitution) is
    rejected without any caller opting in — and an explicit
    expected_sha256={} disables pinning."""
    import urllib.request
    arrays = _fixture_arrays()
    payloads = {ds._IDX_FILES[k][0] + ".gz": _gz_idx_payload(v)
                for k, v in arrays.items()}
    monkeypatch.setattr(
        urllib.request, "urlopen",
        lambda url, timeout=None: _FakeResponse(payloads[url.rsplit("/", 1)[1]]))
    # structurally valid substitute + real pins → rejected, nothing lands
    assert ds.maybe_download(tmp_path, "mnist") is False
    assert not list(tmp_path.glob("*ubyte*"))
    # explicit opt-out accepts the same bytes
    assert ds.maybe_download(tmp_path, "mnist", expected_sha256={}) is True


def test_materialize_idx_fixture_roundtrip(tmp_path):
    """The campaign's materialized fixture is a REAL idx dataset: the
    standard loader parses it, values land in [-0.5, 0.5], splits have
    archive-standard sizes (scaled), and generation is idempotent."""
    from distributedmnist_tpu.data.fixtures import materialize_idx_fixture
    root = materialize_idx_fixture(tmp_path, "mnist", num_train=256,
                                   num_test=64)
    d = ds.load_idx_dataset(root, validation_size=32)
    assert d.train.num_examples == 256 - 25  # loader carves min(32, 256//10)
    assert d.test.num_examples == 64
    assert -0.5 <= d.train.images.min() and d.train.images.max() <= 0.5
    assert set(np.unique(d.train.labels)) <= set(range(10))
    before = (root / "train-images-idx3-ubyte.gz").stat().st_mtime
    materialize_idx_fixture(tmp_path, "mnist", num_train=256, num_test=64)
    assert (root / "train-images-idx3-ubyte.gz").stat().st_mtime == before


def test_materialize_cifar10_fixture_roundtrip(tmp_path):
    """The CIFAR-10 fixture exercises load_cifar10's REAL parse path:
    five pickle batches + test_batch, [N, 3072] channel-major u8 rows
    decoded to NHWC in [-0.5, 0.5], matching the generating synthetic
    data to u8 quantization; generation is idempotent."""
    from distributedmnist_tpu.data.fixtures import (_FIXTURE_SEEDS,
                                                    materialize_cifar10_fixture)
    root = materialize_cifar10_fixture(tmp_path, num_train=500, num_test=100)
    batch_dir = root / "cifar-10-batches-py"
    assert sorted(p.name for p in batch_dir.iterdir()) == (
        [f"data_batch_{i}" for i in range(1, 6)] + ["test_batch"])
    d = ds.load_cifar10(root)
    v = 500 // 10  # loader carves min(5000, n//10) validation rows
    assert d.train.images.shape == (500 - v, 32, 32, 3)
    assert d.test.images.shape == (100, 32, 32, 3)
    assert -0.5 <= d.train.images.min() and d.train.images.max() <= 0.5
    ref = ds.make_synthetic(500, 100, image_size=32, num_channels=3,
                            seed=_FIXTURE_SEEDS.get("cifar10", 67890))
    np.testing.assert_allclose(d.train.images, ref.train.images[v:],
                               atol=0.51 / 255)
    assert (d.train.labels == ref.train.labels[v:]).all()
    before = (batch_dir / "data_batch_1").stat().st_mtime
    materialize_cifar10_fixture(tmp_path, num_train=500, num_test=100)
    assert (batch_dir / "data_batch_1").stat().st_mtime == before
