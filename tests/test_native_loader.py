"""Native (C++) data-pipeline tests: idx decode parity with the python
readers, loader determinism, epoch-permutation coverage, and exact
checkpoint/restore of the stream (SURVEY §4 "implication": the
reference has zero tests; its data path — src/mnist_data.py — is
covered here by construction)."""

import gzip
import struct

import numpy as np
import pytest

pytest.importorskip("distributedmnist_tpu.data.native_loader",
                    reason="native toolchain unavailable")

from distributedmnist_tpu.data import native_loader
from distributedmnist_tpu.data.datasets import (ArrayDataset,
                                                read_idx_images,
                                                read_idx_labels)
from distributedmnist_tpu.data.pipeline import BatchIterator


def _write_idx3(path, arr: np.ndarray, compress: bool) -> None:
    n, r, c = arr.shape
    payload = struct.pack(">IIII", 2051, n, r, c) + arr.astype(np.uint8).tobytes()
    if compress:
        path.write_bytes(gzip.compress(payload))
    else:
        path.write_bytes(payload)


def _write_idx1(path, labels: np.ndarray, compress: bool) -> None:
    payload = struct.pack(">II", 2049, len(labels)) + labels.astype(np.uint8).tobytes()
    if compress:
        path.write_bytes(gzip.compress(payload))
    else:
        path.write_bytes(payload)


@pytest.mark.parametrize("compress", [False, True])
def test_native_idx_roundtrip(tmp_path, compress):
    rng = np.random.default_rng(0)
    imgs = rng.integers(0, 256, (7, 5, 4), dtype=np.uint8)
    labels = rng.integers(0, 10, (7,), dtype=np.uint8)
    ipath = tmp_path / ("imgs.idx3-ubyte" + (".gz" if compress else ""))
    lpath = tmp_path / ("labs.idx1-ubyte" + (".gz" if compress else ""))
    _write_idx3(ipath, imgs, compress)
    _write_idx1(lpath, labels, compress)

    np.testing.assert_array_equal(native_loader.read_idx(ipath), imgs)
    np.testing.assert_array_equal(native_loader.read_idx(lpath), labels)
    # and through the high-level readers (normalization applied)
    out = read_idx_images(ipath)
    assert out.shape == (7, 5, 4, 1)
    assert out.min() >= -0.5 and out.max() <= 0.5
    np.testing.assert_array_equal(read_idx_labels(lpath), labels.astype(np.int32))


def test_native_idx_rejects_garbage(tmp_path):
    p = tmp_path / "bad.idx"
    p.write_bytes(b"\x01\x02\x03\x04garbage")
    with pytest.raises(ValueError):
        native_loader.read_idx(p)


def _make_dataset(n=40, feat=(3, 3, 1)):
    images = np.arange(n, dtype=np.float32)[:, None, None, None] * np.ones(
        feat, np.float32)
    labels = np.arange(n, dtype=np.int32)
    return ArrayDataset(images, labels)


def _prefetcher(batch=8, seed=5, n=40):
    it = BatchIterator(_make_dataset(n), batch_size=batch, seed=seed)
    return native_loader.NativePrefetcher(it, depth=3)


def test_epoch_is_a_permutation():
    n, batch = 40, 8
    pf = _prefetcher(batch=batch, n=n)
    seen = []
    for _ in range(n // batch):
        b = next(pf)
        assert b["image"].shape == (batch, 3, 3, 1)
        assert b["image"].dtype == np.float32
        # image payload rides with its label (row gather is consistent)
        np.testing.assert_array_equal(b["image"][:, 0, 0, 0].astype(np.int32),
                                      b["label"])
        seen.extend(b["label"].tolist())
    assert sorted(seen) == list(range(n))  # exactly one epoch, full coverage
    assert pf.state() == {"impl": "native", "epoch": 0, "pos": n}
    next(pf)
    assert pf.epoch == 1
    pf.close()


def test_deterministic_across_instances():
    a, b = _prefetcher(seed=9), _prefetcher(seed=9)
    for _ in range(12):
        x, y = next(a), next(b)
        np.testing.assert_array_equal(x["label"], y["label"])
        np.testing.assert_array_equal(x["image"], y["image"])
    c = _prefetcher(seed=10)
    assert any(not np.array_equal(next(a)["label"], next(c)["label"])
               for _ in range(5))
    for pf in (a, b, c):
        pf.close()


def test_restore_resumes_exact_stream():
    pf = _prefetcher(seed=3)
    for _ in range(7):  # cross an epoch boundary (40/8 = 5 batches/epoch)
        next(pf)
    state = pf.state()
    tail = [next(pf)["label"] for _ in range(6)]

    fresh = _prefetcher(seed=3)
    fresh.restore(state)
    tail2 = [next(fresh)["label"] for _ in range(6)]
    for x, y in zip(tail, tail2):
        np.testing.assert_array_equal(x, y)
    pf.close()
    fresh.close()


def test_restore_rejects_cross_impl_state():
    # a cursor from the numpy stream indexes a different permutation
    pf = _prefetcher()
    with pytest.raises(ValueError, match="numpy"):
        pf.restore({"impl": "numpy", "epoch": 0, "pos": 8})
    it = BatchIterator(_make_dataset(), batch_size=8, seed=5)
    with pytest.raises(ValueError, match="native"):
        it.restore(pf.state())
    pf.close()


def test_closed_prefetcher_raises():
    pf = _prefetcher()
    pf.close()
    with pytest.raises(RuntimeError, match="closed"):
        next(pf)
    with pytest.raises(RuntimeError, match="closed"):
        pf.restore({"impl": "native", "epoch": 0, "pos": 0})
    pf.close()  # idempotent


def test_lm_shaped_labels():
    """2-D int32 token labels (the transformer path) ride the same
    byte-strip gather."""
    n, s = 16, 12
    tokens = np.arange(n * s, dtype=np.int32).reshape(n, s)
    ds = ArrayDataset(tokens.copy(), tokens.copy())
    it = BatchIterator(ds, batch_size=4, seed=1)
    pf = native_loader.NativePrefetcher(it)
    b = next(pf)
    assert b["image"].shape == (4, s) and b["label"].shape == (4, s)
    np.testing.assert_array_equal(b["image"], b["label"])
    pf.close()


def test_trainer_end_to_end_with_native_pipeline(tmp_train_dir, monkeypatch):
    """Full Trainer loop fed by the C++ prefetcher, including the
    data-cursor checkpoint round-trip through train.checkpoint."""
    import os

    from conftest import base_config
    from distributedmnist_tpu.train.loop import Trainer

    monkeypatch.setattr(os, "cpu_count", lambda: 4)  # defeat 1-core gate

    cfg = base_config(
        data={"use_native_pipeline": True},
        train={"max_steps": 6, "train_dir": tmp_train_dir,
               "save_interval_secs": 0, "save_interval_steps": 3},
    )
    tr = Trainer(cfg)
    assert isinstance(tr.train_iter, native_loader.NativePrefetcher)
    summary = tr.run()
    assert summary["final_step"] == 6

    cfg2 = cfg.override({"train.resume": True, "train.max_steps": 8})
    tr2 = Trainer(cfg2)
    assert tr2._start_step == 6
    assert tr2.train_iter.state() == tr.train_iter.state()
    assert tr2.run()["final_step"] == 8


def test_make_train_iterator_uses_native(monkeypatch):
    import os

    from distributedmnist_tpu.core.config import DataConfig
    from distributedmnist_tpu.data.pipeline import make_train_iterator
    monkeypatch.setattr(os, "cpu_count", lambda: 4)  # defeat 1-core gate
    ds = _make_dataset()
    it = make_train_iterator(ds, DataConfig(batch_size=8,
                                            use_native_pipeline=True), seed=0)
    assert isinstance(it, native_loader.NativePrefetcher)
    batch = next(it)
    assert batch["image"].shape == (8, 3, 3, 1)
    it.close()


def test_make_train_iterator_single_core_skips_prefetch_thread(monkeypatch):
    """On a 1-core host the prefetch thread only fights the consumer
    (measured net slowdown) — the pipeline must fall back inline."""
    import os

    from distributedmnist_tpu.core.config import DataConfig
    from distributedmnist_tpu.data.pipeline import (BatchIterator,
                                                    make_train_iterator)
    monkeypatch.setattr(os, "cpu_count", lambda: 1)
    ds = _make_dataset()
    it = make_train_iterator(ds, DataConfig(batch_size=8,
                                            use_native_pipeline=True), seed=0)
    assert isinstance(it, BatchIterator)
