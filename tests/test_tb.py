"""TensorBoard event-sink tests: the first-party tfevents writer must
produce files the REAL tensorboard reader parses bit-for-bit
(≙ summary writes, src/distributed_train.py:382-390 +
src/nn_eval.py:107-110)."""

import numpy as np
import pytest

from distributedmnist_tpu.obsv import tb

pytestmark = pytest.mark.tier1


def _read_events(log_dir):
    """All (step, {tag: value}) records via tensorboard's own loader."""
    from tensorboard.backend.event_processing.event_file_loader import (
        EventFileLoader)
    def value_of(v):
        # the loader's data_compat pass migrates simple_value into a
        # rank-0 float tensor; accept either form
        if v.tensor.float_val:
            return v.tensor.float_val[0]
        return v.simple_value

    out = []
    for path in sorted(log_dir.glob("events.out.tfevents.*")):
        for ev in EventFileLoader(str(path)).Load():
            vals = {v.tag: value_of(v) for v in ev.summary.value}
            if vals:
                out.append((ev.step, vals))
    return out


def test_crc32c_known_vectors():
    # RFC 3720 test vectors for CRC32C
    assert tb.crc32c(b"") == 0x0
    assert tb.crc32c(b"123456789") == 0xE3069283
    assert tb.crc32c(bytes(32)) == 0x8A9136AA


def test_writer_roundtrips_through_tensorboard_reader(tmp_path):
    pytest.importorskip("tensorboard")
    w = tb.SummaryWriter(tmp_path)
    w.add_scalars({"train/loss": 0.5, "train/accuracy": 0.25}, step=10,
                  wall_time=123.0)
    w.add_scalar("train/loss", 0.125, step=20)
    w.close()
    events = _read_events(tmp_path)
    assert (10, {"train/loss": 0.5, "train/accuracy": 0.25}) == events[0]
    assert events[1][0] == 20
    np.testing.assert_allclose(events[1][1]["train/loss"], 0.125)


def test_trainer_emits_tb_scalars(tmp_path, topo8, synthetic_datasets):
    pytest.importorskip("tensorboard")
    from conftest import base_config
    from distributedmnist_tpu.train.loop import Trainer

    cfg = base_config(train={"max_steps": 6, "log_every_steps": 2,
                             "summary_every_steps": 2,
                             "save_interval_steps": 0,
                             "save_results_period": 0,
                             "train_dir": str(tmp_path / "train")})
    t = Trainer(cfg, topo=topo8, datasets=synthetic_datasets)
    t.run()
    events = _read_events(tmp_path / "train" / "tb")
    steps = [s for s, _ in events]
    assert steps == [2, 4, 6]
    assert all("train/loss" in v and "train/examples_per_sec" in v
               for _, v in events)


def test_evaluator_emits_tb_scalars(tmp_path, topo8, synthetic_datasets):
    pytest.importorskip("tensorboard")
    from conftest import base_config
    from distributedmnist_tpu.core.config import EvalConfig
    from distributedmnist_tpu.evalsvc.evaluator import Evaluator
    from distributedmnist_tpu.train.loop import Trainer

    cfg = base_config(train={"max_steps": 4, "save_interval_steps": 0,
                             "save_results_period": 0,
                             "train_dir": str(tmp_path / "train")})
    Trainer(cfg, topo=topo8, datasets=synthetic_datasets).run()
    ecfg = EvalConfig(run_once=True, eval_dir=str(tmp_path / "eval"))
    Evaluator(tmp_path / "train", ecfg, cfg=cfg, topo=topo8,
              datasets=synthetic_datasets).run()
    events = _read_events(tmp_path / "eval" / "tb")
    assert len(events) == 1
    step, vals = events[0]
    assert step == 4
    assert set(vals) == {"Validation Accuracy", "Validation Loss"}
