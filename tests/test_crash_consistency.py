"""Crash-point matrix over the atomic-save protocol (ISSUE 20).

``save_checkpoint`` promises: tmp write → data rename → digest sidecar
→ latest-pointer, each step leaving the directory restorable.  This
suite enumerates every crash point in that chain — via the storage
shim's deterministic disk faults (train/storage.py) where the fault
model covers it, and via a SimulatedCrash (a non-OSError, so the
retry wrapper propagates it like a process death) where the crash
must land BETWEEN shim ops — and proves, from the artifacts alone,
that restore lands on the last durable step with the fallback cause
journaled, and that every degradation the run booked is licensed by
an injected fault (obsv/invariants.py ``storage_faults``).
"""

import errno
import json

import numpy as np
import pytest

from distributedmnist_tpu.obsv.invariants import (
    Violation, check_checkpoint_dir, check_storage_faults,
    load_storage_faults, storage_exempt_targets)
from distributedmnist_tpu.obsv.report import load_jsonl
from distributedmnist_tpu.train import checkpoint as ckpt
from distributedmnist_tpu.train import storage


class SimulatedCrash(Exception):
    """Process death between shim ops: NOT an OSError, so
    ``_io_retries`` propagates it immediately instead of retrying —
    exactly what a power cut does to the protocol."""


@pytest.fixture(autouse=True)
def _disarm_storage_faults():
    storage.clear_faults()
    yield
    storage.clear_faults()


def _dict_state(v: int):
    return {"params": {"w": np.full((4, 3), float(v), np.float32)},
            "step": np.int32(v)}


def _restored_value(tmp_path, events=None):
    got = ckpt.restore_checkpoint(
        tmp_path, _dict_state(0),
        on_event=events.append if events is not None else None)
    assert got is not None
    state, _, step = got
    return step, float(state["params"]["w"][0, 0])


def _crash_in(monkeypatch, fn_name, role):
    """Crash the FIRST shim call of ``fn_name`` made with ``role``."""
    real = getattr(storage, fn_name)

    def boom(*args, **kwargs):
        if kwargs.get("role", args[2] if len(args) > 2 else None) == role:
            raise SimulatedCrash(f"{fn_name}(role={role})")
        return real(*args, **kwargs)

    monkeypatch.setattr(storage, fn_name, boom)


# ---------------------------------------------------------------------------
# the matrix: one test per crash point in the atomic-save chain
# ---------------------------------------------------------------------------

@pytest.mark.tier1
def test_crash_mid_tmp_write_torn_at_byte(tmp_path):
    """Point 1: the tmp write lands only a prefix (torn_write fault,
    times = the full retry budget so the save fails all the way
    through).  The torn ``.tmp`` is never a restore candidate: restore
    lands on the previous step with no fallback event — the crash cost
    a cadence, not consistency."""
    ckpt.save_checkpoint(tmp_path, _dict_state(3), 3)
    journal = tmp_path / "storage_faults.jsonl"
    storage.arm_faults(0, [{"kind": "torn_write_at_byte", "at_byte": 37,
                            "match": ".msgpack",
                            "times": ckpt._IO_ATTEMPTS}], journal)
    with pytest.raises(OSError) as ei:
        ckpt.save_checkpoint(tmp_path, _dict_state(6), 6)
    assert ei.value.errno == errno.EIO
    torn = tmp_path / "ckpt-00000006.msgpack.tmp"
    assert torn.exists() and torn.stat().st_size == 37
    events = []
    assert _restored_value(tmp_path, events) == (3, 3.0)
    assert events == []  # the torn tmp was never a candidate
    actions = [r["action"] for r in load_jsonl(journal)]
    assert actions == ["disk_torn_write"] * ckpt._IO_ATTEMPTS


@pytest.mark.tier1
def test_crash_post_tmp_pre_rename(tmp_path, monkeypatch):
    """Point 2: tmp fully written, crash before the data rename.  The
    complete ``.tmp`` is still not a candidate — restore lands on the
    previous step and the stale tmp is later GC-proof (skipped)."""
    ckpt.save_checkpoint(tmp_path, _dict_state(3), 3)
    _crash_in(monkeypatch, "replace", "data")
    with pytest.raises(SimulatedCrash):
        ckpt.save_checkpoint(tmp_path, _dict_state(6), 6)
    assert (tmp_path / "ckpt-00000006.msgpack.tmp").exists()
    assert not (tmp_path / "ckpt-00000006.msgpack").exists()
    assert _restored_value(tmp_path) == (3, 3.0)
    assert ckpt.latest_checkpoint_step(tmp_path) == 3


@pytest.mark.tier1
def test_crash_post_rename_pre_digest(tmp_path, monkeypatch):
    """Point 3: data renamed into place, crash before the digest
    sidecar lands.  The digest-less file is legacy-accepted (the
    protocol unlinks the OLD digest first, so stale-digest-over-new-
    bytes can never reject it): restore lands on the NEW step; the
    pointer — never updated — still names the old one, which is the
    licensed digest-gap shape invariant 14 accepts."""
    ckpt.save_checkpoint(tmp_path, _dict_state(3), 3)
    _crash_in(monkeypatch, "write_text", "sidecar")
    with pytest.raises(SimulatedCrash):
        ckpt.save_checkpoint(tmp_path, _dict_state(6), 6)
    assert (tmp_path / "ckpt-00000006.msgpack").exists()
    assert not (tmp_path / "ckpt-00000006.msgpack.sha256").exists()
    assert _restored_value(tmp_path) == (6, 6.0)
    ptr = json.loads((tmp_path / "checkpoint.json").read_text())
    assert ptr["latest_step"] == 3


@pytest.mark.tier1
def test_crash_post_digest_pre_pointer(tmp_path, monkeypatch):
    """Point 4: artifact and digest fully durable, crash before the
    pointer write.  The step is restorable (the scan unions with the
    pointer), nothing is corrupt, and the digest verifies."""
    ckpt.save_checkpoint(tmp_path, _dict_state(3), 3)
    _crash_in(monkeypatch, "write_text", "pointer")
    with pytest.raises(SimulatedCrash):
        ckpt.save_checkpoint(tmp_path, _dict_state(6), 6)
    assert (tmp_path / "ckpt-00000006.msgpack.sha256").exists()
    ckpt.verify_artifact(tmp_path / "ckpt-00000006.msgpack")
    assert _restored_value(tmp_path) == (6, 6.0)
    assert json.loads(
        (tmp_path / "checkpoint.json").read_text())["latest_step"] == 3


@pytest.mark.tier1
def test_crash_mid_pointer(tmp_path, monkeypatch):
    """Point 5: crash between the pointer's tmp write and its rename
    (and, separately, a torn pointer body): ``checkpoint.json`` is
    either the intact OLD pointer or unreadable — both fall back to
    the directory scan and land on the newest durable step."""
    ckpt.save_checkpoint(tmp_path, _dict_state(3), 3)
    _crash_in(monkeypatch, "replace", "pointer")
    with pytest.raises(SimulatedCrash):
        ckpt.save_checkpoint(tmp_path, _dict_state(6), 6)
    monkeypatch.undo()
    assert (tmp_path / "checkpoint.json.tmp").exists()
    assert json.loads(
        (tmp_path / "checkpoint.json").read_text())["latest_step"] == 3
    # restore unions the directory scan with the (stale) pointer and
    # tries newest-first: the fully-durable step 6 wins
    assert _restored_value(tmp_path) == (6, 6.0)
    # a non-atomic legacy overwrite that tore mid-body: scan fallback
    (tmp_path / "checkpoint.json").write_text('{"latest_step": 6, "la')
    assert ckpt.latest_checkpoint_step(tmp_path) == 6


@pytest.mark.tier1
def test_enospc_exhausts_retries_and_leaves_dir_restorable(tmp_path):
    """A full disk across the whole retry budget: the save raises
    ENOSPC having written NOTHING durable; restore lands on the
    previous step and every firing is journaled for licensing."""
    ckpt.save_checkpoint(tmp_path, _dict_state(3), 3)
    journal = tmp_path / "storage_faults.jsonl"
    storage.arm_faults(0, [{"kind": "enospc_after_bytes", "bytes": 0,
                            "match": ".msgpack",
                            "times": ckpt._IO_ATTEMPTS}], journal)
    with pytest.raises(OSError) as ei:
        ckpt.save_checkpoint(tmp_path, _dict_state(6), 6)
    assert ei.value.errno == errno.ENOSPC
    assert not (tmp_path / "ckpt-00000006.msgpack").exists()
    assert _restored_value(tmp_path) == (3, 3.0)
    recs = load_jsonl(journal)
    assert [r["action"] for r in recs] == \
        ["disk_enospc"] * ckpt._IO_ATTEMPTS
    assert all(r["worker"] == 0 for r in recs)


@pytest.mark.tier1
def test_transient_fault_absorbed_by_retries(tmp_path):
    """One EIO firing inside a 3-attempt budget: the save SUCCEEDS,
    the firing is still journaled — licensing is 'a fault fired', not
    'a save failed', so absorbed faults stay visible."""
    journal = tmp_path / "storage_faults.jsonl"
    storage.arm_faults(0, [{"kind": "eio", "op": "write", "nth": 1,
                            "match": ".msgpack", "times": 1}], journal)
    ckpt.save_checkpoint(tmp_path, _dict_state(6), 6)
    assert _restored_value(tmp_path) == (6, 6.0)
    assert [r["action"] for r in load_jsonl(journal)] == ["disk_eio"]


@pytest.mark.tier1
def test_crash_rename_falls_back_with_journaled_cause(tmp_path):
    """The power-cut model: rename applied, data never hit the
    platter.  The writer believes the save succeeded (no error), the
    pointer names the hollow artifact — and the digest sidecar catches
    it at restore: fallback to the previous step with BOTH the cause
    and the fallback journaled, plus the injector's own license."""
    ckpt.save_checkpoint(tmp_path, _dict_state(3), 3)
    journal = tmp_path / "storage_faults.jsonl"
    storage.arm_faults(0, [{"kind": "crash_rename",
                            "match": "ckpt-00000006.msgpack",
                            "times": 1}], journal)
    ckpt.save_checkpoint(tmp_path, _dict_state(6), 6)  # "succeeds"
    assert (tmp_path / "ckpt-00000006.msgpack").stat().st_size == 0
    assert json.loads(
        (tmp_path / "checkpoint.json").read_text())["latest_step"] == 6
    events = []
    assert _restored_value(tmp_path, events) == (3, 3.0)
    actions = {e["action"]: e for e in events}
    assert actions["corrupt_checkpoint_fallback"]["bad_step"] == 6
    assert actions["fallback_restore"]["step"] == 3
    assert [r["action"] for r in load_jsonl(journal)] == \
        ["disk_crash_rename"]


@pytest.mark.tier1
def test_at_step_gating_arms_scripts_late(tmp_path):
    """``at_step`` holds a script quiet until the trainer reports
    progress past it — the chaos schedule's step axis."""
    journal = tmp_path / "storage_faults.jsonl"
    storage.arm_faults(0, [{"kind": "eio", "op": "write", "nth": 1,
                            "at_step": 10, "match": ".msgpack",
                            "times": ckpt._IO_ATTEMPTS}], journal)
    ckpt.save_checkpoint(tmp_path, _dict_state(5), 5)  # before: quiet
    storage.note_step(10)
    with pytest.raises(OSError):
        ckpt.save_checkpoint(tmp_path, _dict_state(10), 10)
    assert _restored_value(tmp_path) == (5, 5.0)


# ---------------------------------------------------------------------------
# invariant 14: licensing + exemptions replay from the artifacts
# ---------------------------------------------------------------------------

def _worker_trial(tmp_path):
    d = tmp_path / "worker0"
    d.mkdir(parents=True, exist_ok=True)
    return tmp_path, d


def _recovery(d, records):
    with open(d / "recovery_journal.jsonl", "a") as fh:
        for r in records:
            fh.write(json.dumps({"event": "recovery", **r}) + "\n")


@pytest.mark.tier1
def test_storage_faults_invariant_licenses_real_run(tmp_path):
    """End-to-end licensing: a crash_rename trial's artifacts — the
    injector journal, the hollow artifact, the fallback events — must
    replay green, and invariant 5 must accept the torn target ONLY
    through the storage exemption."""
    trial, d = _worker_trial(tmp_path)
    ckpt.save_checkpoint(d, _dict_state(3), 3)
    storage.arm_faults(0, [{"kind": "crash_rename",
                            "match": "ckpt-00000006.msgpack"}],
                       d / "storage_faults.jsonl")
    ckpt.save_checkpoint(d, _dict_state(6), 6)
    events = []
    ckpt.restore_checkpoint(d, _dict_state(0), on_event=events.append)
    storage.clear_faults()  # flush the injector's journal sink
    _recovery(d, [{"layer": "checkpoint", **e} for e in events])

    sf = load_storage_faults(trial)
    assert [r["action"] for r in sf[0]] == ["disk_crash_rename"]
    violations, applicable = check_storage_faults(trial, [])
    assert applicable and violations == []
    # invariant 5: damaged WITHOUT the exemption, green with it
    exempt = storage_exempt_targets(sf)
    assert exempt == {0: {"ckpt-00000006.msgpack"}}
    assert check_checkpoint_dir(d, exempt[0], worker=0) == []
    assert any(v.invariant == "checkpoint_integrity"
               for v in check_checkpoint_dir(d, set(), worker=0))


@pytest.mark.tier1
def test_storage_faults_invariant_flags_unlicensed_damage(tmp_path):
    """The other half of the licensing books: a save_failed nobody
    injected, a fallback with no scripted corruption, and a pointer
    past a missing digest in a clean run are each violations; a trial
    with no storage evidence at all is skipped, not passed."""
    trial, d = _worker_trial(tmp_path)
    violations, applicable = check_storage_faults(trial, [])
    assert not applicable and violations == []

    _recovery(d, [{"action": "save_failed", "step": 5,
                   "error": "OSError: nobody injected this"}])
    violations, applicable = check_storage_faults(trial, [])
    assert applicable
    assert [v.invariant for v in violations] == ["storage_faults"]
    assert "save_failed" in violations[0].detail

    (d / "recovery_journal.jsonl").unlink()
    _recovery(d, [{"action": "corrupt_checkpoint_fallback", "bad_step": 6,
                   "error": "CheckpointCorruptError: rot"},
                  {"action": "fallback_restore", "step": 3}])
    # a slow-io firing makes the trial applicable but corrupts nothing
    # — it cannot license a restore walking past rotten bytes
    with open(d / "storage_faults.jsonl", "w") as fh:
        fh.write(json.dumps({"event": "fault", "action": "disk_slow_io",
                             "worker": 0, "path": "ckpt-00000006.msgpack.tmp",
                             "op": "write", "ms": 5.0}) + "\n")
    violations, _ = check_storage_faults(trial, [])
    assert any("no injected corruption" in v.detail for v in violations)
    # a supervisor corrupt_latest_checkpoint firing licenses the same
    licensed, _ = check_storage_faults(
        trial, [{"event": "fault", "action": "corrupt_latest_checkpoint",
                 "worker": 0, "target": "ckpt-00000006.msgpack"}])
    assert licensed == []

    # pointer published past a digest that never landed, clean run
    (d / "recovery_journal.jsonl").unlink()
    ckpt.save_checkpoint(d, _dict_state(6), 6)
    (d / "ckpt-00000006.msgpack.sha256").unlink()
    _recovery(d, [{"action": "save_failed", "step": 9, "error": "x"}])
    sf_journal = d / "storage_faults.jsonl"
    with open(sf_journal, "w") as fh:
        fh.write(json.dumps({"event": "fault", "action": "disk_enospc",
                             "worker": 0, "path": "ckpt-00000009.msgpack.tmp",
                             "op": "write", "at_step": 9}) + "\n")
    violations, _ = check_storage_faults(trial, [])
    assert violations == []  # the disk firing explains the gap too
    sf_journal.unlink()
    violations, _ = check_storage_faults(trial, [])
    details = [v.detail for v in violations]
    assert any("digest sidecar never landed" in s for s in details)


@pytest.mark.tier1
def test_disk_fault_script_validation():
    """Unknown kinds and unknown fields are typed errors at arm time —
    a chaos schedule typo must not silently no-op a campaign."""
    with pytest.raises(ValueError, match="unknown disk fault kind"):
        storage.DiskFaultInjector(0, [{"kind": "enospc"}])
    with pytest.raises(ValueError, match="unknown field"):
        storage.DiskFaultInjector(0, [{"kind": "eio", "bogus": 1}])


@pytest.mark.tier1
def test_durability_policy_knob():
    """The fsync policy is a typed knob; 'full' must keep the whole
    save protocol working (fsyncs added, semantics unchanged)."""
    from distributedmnist_tpu.core.config import ConfigError
    assert storage.durability() == "none"
    with pytest.raises(ConfigError, match="valid policies"):
        storage.set_durability("paranoid")
    try:
        storage.set_durability("full")
        assert storage.journal_sync_enabled()
    finally:
        storage.set_durability("none")


@pytest.mark.tier1
def test_durability_full_save_restore_roundtrip(tmp_path):
    try:
        storage.set_durability("full")
        ckpt.save_checkpoint(tmp_path, _dict_state(4), 4)
    finally:
        storage.set_durability("none")
    got = ckpt.restore_checkpoint(tmp_path, _dict_state(0))
    assert got is not None and got[2] == 4
