"""Elastic world-size reconfiguration (ROADMAP item 2): mesh-portable
checkpoints, the supervisor's shrink/grow verb, the chaos resize fault,
and the cross-world resume invariant.

Three layers under test:

* **Checkpoint portability** — a ZeRO-1 artifact saved under one
  replica count restores bitwise onto another (the Zero1Plan is
  re-derived from the NEW world; padding/chunk ownership re-computed),
  the data cursor reassigns across host counts with no sample range
  dropped or double-visited, and a strict same-world consumer gets the
  typed ``WorldSizeMismatchError`` instead of a raw structure error.
* **Supervisor elasticity** — below-quorum with budgets exhausted
  SHRINKS the world to the survivors (quorum rescaled, journaled as
  ``event: "reconfigure"``); an explicit grow seeds a fresh worker
  from a survivor's checkpoint and promotes a warm standby into it.
* **Chaos + invariants** — resize is the sixth seeded fault kind, the
  report counts scheduled-vs-fired faults, and a run whose world
  changed without the journaled license fails replay.
"""

import json
import time

import jax
import numpy as np
import pytest

from conftest import base_config
from distributedmnist_tpu.data.datasets import make_synthetic
from distributedmnist_tpu.data.pipeline import (BatchIterator,
                                                consumed_sample_ranges)
from distributedmnist_tpu.launch.chaos import (ChaosCampaign, ChaosConfig,
                                               ChaosFault, ChaosSchedule,
                                               count_fired_faults,
                                               generate_schedule)
from distributedmnist_tpu.launch.cluster import (LocalClusterConfig,
                                                 LocalProcessCluster)
from distributedmnist_tpu.launch.exec import (CommandExecutor, FaultPlan,
                                              RetryPolicy)
from distributedmnist_tpu.launch.supervisor import (ClusterSupervisor,
                                                    SupervisorConfig)
from distributedmnist_tpu.obsv.invariants import check_run
from distributedmnist_tpu.obsv.journal import (load_reconfigure_events,
                                               summarize_chaos)
from distributedmnist_tpu.parallel.api import canonical_save_state
from distributedmnist_tpu.train import checkpoint as ckpt
from distributedmnist_tpu.train.loop import Trainer

pytestmark = pytest.mark.tier1


# ---------------------------------------------------------------------------
# mesh-portable checkpoints
# ---------------------------------------------------------------------------

def _world_cfg(n_replicas: int, train_dir: str):
    return base_config(
        optim={"momentum": 0.9},
        parallel={"shard_weight_update": True},
        mesh={"num_replicas": n_replicas},
        train={"max_steps": 4, "log_every_steps": 2,
               "save_interval_steps": 2, "save_results_period": 0,
               "train_dir": train_dir, "async_checkpoint": False})


def test_zero1_checkpoint_restores_across_world_sizes(tmp_path,
                                                      synthetic_datasets):
    """Save at n=8 → restore at n=2 and n=1: params BITWISE equal, the
    re-derived Zero1Plan owns correctly re-padded chunks (momentum
    unpacks to the canonical buffers exactly), and the cross-world
    restore is journaled. Then the grow direction: a n=2 artifact
    restores onto the full 8-replica mesh."""
    d8 = str(tmp_path / "w8")
    t8 = Trainer(_world_cfg(8, d8), datasets=synthetic_datasets)
    assert t8._zero1_plan is not None and t8._zero1_plan.n == 8
    t8.run()
    digest = ckpt.state_params_digest(t8.state)
    canonical = canonical_save_state(t8.state, t8._zero1_plan).momentum
    world, step = ckpt.read_checkpoint_world(d8)
    assert step == 4 and world["num_replicas"] == 8

    for n_new in (2, 1):
        t = Trainer(_world_cfg(n_new, d8), datasets=synthetic_datasets)
        assert int(jax.device_get(t.state.step)) == 4
        # bitwise params across the world change
        assert ckpt.state_params_digest(t.state) == digest
        # chunk ownership: the live momentum (re-packed for n_new)
        # unpacks to the SAME canonical buffers the n=8 run saved —
        # wrong padding or chunk assignment would scramble this
        got = canonical_save_state(t.state, t._zero1_plan).momentum
        for a, b in zip(jax.tree.leaves(got), jax.tree.leaves(canonical)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        if n_new > 1:
            assert t._zero1_plan is not None and t._zero1_plan.n == n_new
            for leaf, lp in zip(
                    jax.tree.leaves(t.state.momentum),
                    jax.tree.leaves(t._zero1_plan.leaf_plans,
                                    is_leaf=lambda x: hasattr(x, "sharded"))):
                if lp.sharded:
                    assert leaf.shape == (lp.chunk * n_new,)
        else:
            assert t._zero1_plan is None  # n=1: nothing to shard
        # the world change left journaled evidence
        events = [json.loads(l)
                  for l in open(tmp_path / "w8" / "recovery_journal.jsonl")]
        assert any(e.get("action") == "cross_world_restore"
                   and e["saved_world"]["num_replicas"] == 8
                   and e["new_world"]["num_replicas"] == n_new
                   for e in events)

    # grow: 2 → 8
    d2 = str(tmp_path / "w2")
    t2 = Trainer(_world_cfg(2, d2), datasets=synthetic_datasets)
    t2.run()
    dig2 = ckpt.state_params_digest(t2.state)
    canon2 = canonical_save_state(t2.state, t2._zero1_plan).momentum
    t8b = Trainer(_world_cfg(8, d2), datasets=synthetic_datasets)
    assert int(jax.device_get(t8b.state.step)) == 4
    assert ckpt.state_params_digest(t8b.state) == dig2
    got = canonical_save_state(t8b.state, t8b._zero1_plan).momentum
    for a, b in zip(jax.tree.leaves(got), jax.tree.leaves(canon2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_zero1_pack_repacks_foreign_world_flat_layout():
    """Unit view of the portability fix: a leaf flat-packed under
    n_old re-packs exactly under n_new (padding is zeros by contract),
    and a genuinely mismatched leaf still raises."""
    from jax.sharding import PartitionSpec as P
    from distributedmnist_tpu.parallel.partition_rules import (
        make_zero1_plan, zero1_pack, zero1_unpack)
    params = {"w": np.arange(10, dtype=np.float32).reshape(2, 5)}
    specs = {"w": P()}
    p8 = make_zero1_plan(params, specs, "replica", 8)
    p2 = make_zero1_plan(params, specs, "replica", 2)
    flat8 = zero1_pack(params, p8)["w"]
    assert flat8.shape == (16,)  # ceil(10/8)*8
    repacked = zero1_pack({"w": flat8}, p2)["w"]
    np.testing.assert_array_equal(repacked, zero1_pack(params, p2)["w"])
    np.testing.assert_array_equal(zero1_unpack({"w": repacked}, p2)["w"],
                                  params["w"])
    with pytest.raises(ValueError, match="cannot pack"):
        zero1_pack({"w": np.arange(4, dtype=np.float32)}, p2)
    # an oversized 1-D leaf whose tail is REAL DATA (not zero padding)
    # must refuse loudly — truncating it would be silent corruption
    with pytest.raises(ValueError, match="refusing to truncate"):
        zero1_pack({"w": np.arange(1, 17, dtype=np.float32)}, p2)


def test_data_cursor_reassignment_property():
    """The no-drop/no-double-visit contract: after reassigning cursors
    from a 4-host world into a 2-host world, the union of consumed
    sample-slot ranges is unchanged and per-host ranges stay
    disjoint."""
    ds = make_synthetic(num_train=260, num_test=16).train
    B = 24
    olds = [BatchIterator(ds, B, seed=3, host_id=h, num_hosts=4)
            for h in range(4)]
    for _ in range(55):           # lockstep: one global batch per tick
        for it in olds:
            next(it)
    states = [it.state() for it in olds]
    assert all(s["batches"] == 55 for s in states)

    def union(ranges):
        r = sorted(ranges)
        assert all(a[1] <= b[0] for a, b in zip(r, r[1:])), "overlap"
        assert all(a[1] == b[0] for a, b in zip(r, r[1:])), "gap"
        return (r[0][0], r[-1][1])

    old_union = union(x for s in states for x in consumed_sample_ranges(s))
    assert old_union == (0, 55 * B)

    news = [BatchIterator(ds, B, seed=3, host_id=h, num_hosts=2)
            for h in range(2)]
    for it in news:
        # any old host's state carries the same lockstep coordinate
        it.restore(states[it.host_id])
    new_states = [it.state() for it in news]
    assert union(x for s in new_states
                 for x in consumed_sample_ranges(s)) == old_union
    # the new-world cursor is a genuine stream position: epoch/pos
    # re-derived from the NEW shard's batches-per-epoch
    for it in news:
        assert it.batches_consumed == 55
    # same-world restore is byte-exact (legacy behavior preserved)
    again = BatchIterator(ds, B, seed=3, host_id=1, num_hosts=4)
    again.restore(states[1])
    assert again.state() == states[1]


def test_world_size_mismatch_error_is_typed(tmp_path):
    """A strict same-world consumer gets WorldSizeMismatchError naming
    saved vs requested world — branchable, unlike the raw structure
    error it used to surface as."""
    from distributedmnist_tpu.train.checkpoint import (
        WorldSizeMismatchError, restore_checkpoint, save_checkpoint)
    state = {"params": {"w": np.arange(8, dtype=np.float32)}}
    saved_world = {"num_replicas": 8, "process_count": 1,
                   "mesh": {"replica": 8}}
    save_checkpoint(tmp_path, state, step=3,
                    extra={"world": saved_world})
    want = {"num_replicas": 2, "process_count": 1, "mesh": {"replica": 2}}
    with pytest.raises(WorldSizeMismatchError) as ei:
        restore_checkpoint(tmp_path, state, expect_world=want)
    assert ei.value.saved_world == saved_world
    assert ei.value.requested_world == want
    assert "restore_for_topology" in str(ei.value)
    # matching world restores fine through the same gate
    got = restore_checkpoint(tmp_path, state, expect_world=saved_world)
    assert got is not None and got[2] == 3
    # and the typed error must NOT be swallowed by the corruption
    # fallback (it is not a CheckpointCorruptError)
    from distributedmnist_tpu.train.checkpoint import CheckpointCorruptError
    assert not issubclass(WorldSizeMismatchError, CheckpointCorruptError)


# ---------------------------------------------------------------------------
# supervisor shrink/grow (shell payload — real worker processes)
# ---------------------------------------------------------------------------

_RESUMING_LOOP = ('i=$( [ -f ckpt ] && cat ckpt || echo 0 ); '
                  'echo $i >> boots.txt; '
                  'while [ $i -lt 400 ]; do i=$((i+1)); '
                  'echo "{\\"step\\": $i, \\"loss\\": 1.0}" '
                  '>> train_log.jsonl; '
                  'if [ $((i % 5)) -eq 0 ]; then echo $i > ckpt; fi; '
                  'sleep 0.05; done')

_STANDBY_LOOP = (
    'touch "$DMT_STANDBY_ACTIVATION.ready"; '
    'while [ ! -f "$DMT_STANDBY_ACTIVATION" ]; do sleep 0.05; done; '
    'cd "$(python3 -c "import json,os;'
    "print(json.load(open(os.environ['DMT_STANDBY_ACTIVATION']))"
    "['train_dir'])" '")" && ' + _RESUMING_LOOP)


def _cluster(tmp_path, fault_plan=None, num_workers=2, standby_command=""):
    cfg = LocalClusterConfig(name="el", workdir=str(tmp_path / "cl"),
                             num_workers=num_workers,
                             train_command=_RESUMING_LOOP,
                             standby_command=standby_command)
    ex = CommandExecutor(journal=cfg.root / "command_journal.jsonl",
                         retry=RetryPolicy(max_attempts=1),
                         fault_plan=fault_plan)
    return LocalProcessCluster(cfg, ex)


def test_elastic_shrink_below_quorum_reconfigures_and_finishes(tmp_path):
    """The satellite + tentpole in one: worker 2 dies past its (zero)
    restart budget with quorum == num_workers; an elastic supervisor
    drains the survivors, reshapes 3→2, RESCALES quorum (3 would abort
    the resized world instantly), relaunches, and the run reaches the
    target resuming from the last checkpoints — all journaled as
    event:"reconfigure" with the drain→first-moved-step latency."""
    c = _cluster(tmp_path, num_workers=3,
                 fault_plan=FaultPlan(kill_worker_at_step={2: 7}))
    c.create()
    sup = ClusterSupervisor(c, SupervisorConfig(
        quorum=3, max_restarts_per_worker=0, elastic=True, min_workers=2,
        reconfigure_drain_s=5.0))
    got = sup.run_until_step(40, poll_secs=0.2, timeout_secs=120.0)
    assert got["step"] >= 40
    rs = got["recovery"]["reconfigure"]
    assert rs["count"] == 1
    tr = rs["transitions"][0]
    assert (tr["old_world"], tr["new_world"]) == (3, 2)
    assert tr["trigger"] == "below_quorum"
    assert tr["quorum"] == 3 and tr["effective_quorum"] == 2
    assert tr["reconfigure_s"] > 0  # drain→first-moved-step closed
    # journaled causal license, artifact-side
    recs = load_reconfigure_events(c.exec.journal_path)
    assert [r["action"] for r in recs] == ["begin", "reshape",
                                           "relaunched", "resume"]
    # roster shrank to the survivors, ids and logdirs preserved
    state = json.loads(c.state_path.read_text())
    assert [w["worker"] for w in state["workers"]] == [0, 1]
    # survivors RESUMED from their checkpoints, not step 0
    for k in (0, 1):
        boots = [int(x) for x in
                 (c.cfg.worker_dir(k) / "boots.txt").read_text().split()]
        assert len(boots) == 2 and boots[1] > 0 and boots[1] % 5 == 0, boots
    c.delete()


def test_non_elastic_below_quorum_still_aborts(tmp_path):
    """elastic=False keeps the established bounded-degradation
    contract: below quorum with nothing restartable aborts."""
    from distributedmnist_tpu.launch.cluster import ClusterError
    c = _cluster(tmp_path, num_workers=2,
                 fault_plan=FaultPlan(kill_worker_at_step={1: 2}))
    c.create()
    sup = ClusterSupervisor(c, SupervisorConfig(
        quorum=2, max_restarts_per_worker=0))
    with pytest.raises(ClusterError, match="< quorum 2"):
        sup.run_until_step(50, poll_secs=0.2, timeout_secs=120.0)
    assert not load_reconfigure_events(c.exec.journal_path)
    c.delete()


def test_reconfigure_grow_promotes_standby_and_seeds_checkpoint(tmp_path):
    """The grow path, supervisor-level: an explicit reconfigure 2→3
    seeds the new worker's logdir from a survivor's checkpoint and
    promotes the parked warm standby into it (via: standby); the
    larger world reaches the target step."""
    c = _cluster(tmp_path, standby_command=_STANDBY_LOOP)
    c.create()
    sup = ClusterSupervisor(c, SupervisorConfig(quorum=1,
                                                standby_workers=1))
    c.run_train()
    c.ensure_standbys(1)
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline:
        prog = c.worker_progress()
        st = c.status()
        if (prog and min(prog.values()) >= 6
                and any(s["ready"] for s in st.get("standbys", []))):
            break
        time.sleep(0.2)
    rec = sup.reconfigure(3, trigger="manual")
    assert rec["old_world"] == 2 and rec["new_world"] == 3
    assert rec["grown"] == {"2": 0}
    try:
        got = sup.supervise_until_step(40, poll_secs=0.2,
                                       timeout_secs=120.0)
    finally:
        c.kill_all()
    assert got["step"] >= 40
    tr = got["recovery"]["reconfigure"]["transitions"][0]
    assert tr["via"]["2"] == "standby"  # warm grow, not a cold spawn
    assert tr["reconfigure_s"] > 0
    # the grown worker resumed from the SEEDED checkpoint, not step 0
    boots = [int(x) for x in
             (c.cfg.worker_dir(2) / "boots.txt").read_text().split()]
    assert boots[0] > 0 and boots[0] % 5 == 0, boots
    state = json.loads(c.state_path.read_text())
    assert [w["worker"] for w in state["workers"]] == [0, 1, 2]
    c.delete()


def test_wait_drained_covers_whole_process_group(tmp_path):
    """The drain must wait for the process GROUP, not the recorded sh
    leader: dash FORKS the payload, so on a group SIGTERM the leader
    dies instantly while the python trainer behind it is still
    flushing its preemption checkpoint — a leader-pid wait would
    SIGKILL that flush mid-write (measured: the resumed run lost its
    preemption checkpoint and rewound a full save interval)."""
    slow_flush = (
        "python3 -c \""
        "import signal, sys, time\n"
        "def h(*a):\n"
        "    time.sleep(1.5)\n"  # the flush window a leader-wait loses
        "    open('flushed', 'w').write('1')\n"
        "    sys.exit(75)\n"
        "signal.signal(signal.SIGTERM, h)\n"
        "open('ready', 'w').write('1')\n"
        "[time.sleep(0.1) for _ in range(600)]\"")
    cfg = LocalClusterConfig(name="dr", workdir=str(tmp_path / "cl"),
                             num_workers=1, train_command=slow_flush)
    ex = CommandExecutor(journal=cfg.root / "command_journal.jsonl",
                         retry=RetryPolicy(max_attempts=1))
    c = LocalProcessCluster(cfg, ex)
    c.create()
    c.run_train()
    flag = c.cfg.worker_dir(0) / "flushed"
    deadline = time.monotonic() + 10.0
    # wait until the payload proves its handler is installed
    while (not (c.cfg.worker_dir(0) / "ready").exists()
           and time.monotonic() < deadline):
        time.sleep(0.1)
    t0 = time.monotonic()
    c.stop_all()
    assert c.wait_drained(10.0, poll_secs=0.2)
    took = time.monotonic() - t0
    # the group-wait outlived the leader's instant death and covered
    # the whole 1.5 s flush — and the flush actually landed
    assert flag.exists(), "drain SIGKILLed the flush"
    assert took >= 1.0, f"drain returned in {took:.2f}s — leader-only wait"
    c.delete()


def test_quorum_rescale_clamps_into_new_world():
    cfg = SupervisorConfig(quorum=3)
    assert cfg.rescaled_quorum(2) == 2
    assert cfg.rescaled_quorum(5) == 3
    assert cfg.rescaled_quorum(1) == 1
    assert SupervisorConfig(quorum=1).rescaled_quorum(4) == 1


def test_can_reconfigure_requires_backend_override():
    """The base class DEFINES reconfigure (raising), so a hasattr probe
    would drain a gcloud cluster and then crash mid-reshape; the
    capability check demands an actual override."""
    from distributedmnist_tpu.launch.cluster import GcloudTpuBackend
    sup = ClusterSupervisor.__new__(ClusterSupervisor)
    sup.backend = GcloudTpuBackend.__new__(GcloudTpuBackend)
    assert not sup._can_reconfigure()
    sup.backend = LocalProcessCluster.__new__(LocalProcessCluster)
    assert sup._can_reconfigure()
    sup.backend = object()  # scripted test backends: no verb at all
    assert not sup._can_reconfigure()


# ---------------------------------------------------------------------------
# chaos: the sixth fault kind + scheduled-vs-fired accounting
# ---------------------------------------------------------------------------

def test_generate_schedule_resize_kind_and_legacy_stability():
    """The resize draw rides AFTER every legacy draw: resize-less
    configs reproduce their historical schedules byte-identically, and
    with candidates armed exactly one cluster-level resize appears."""
    base = generate_schedule(7, 3, 2, (6, 20), max_faults=3)
    with_rz = generate_schedule(7, 3, 2, (6, 20), max_faults=3,
                                resize_worlds=(1, 3), resize_prob=1.0)
    assert tuple(f for f in with_rz.faults
                 if f.kind != "resize") == base.faults
    rz = [f for f in with_rz.faults if f.kind == "resize"]
    assert len(rz) == 1
    assert rz[0].world in (1, 3) and 6 <= rz[0].step <= 20
    assert "resize(→" in with_rz.describe()
    # FaultPlan mapping + file-format roundtrip (the reproducer seam)
    plan = with_rz.to_fault_plan()
    assert plan.resize_world_at_step == (rz[0].step, rz[0].world)
    assert FaultPlan.from_json_dict(plan.to_json_dict()) == plan


def test_chaos_resize_trial_shrinks_world_and_invariants_pass(tmp_path):
    """A seeded trial with the resize fault armed: the supervised run
    reshapes mid-run, completes on the smaller world, and every
    applicable invariant — including the new cross-world resume
    invariant — passes; the report records scheduled vs fired."""
    cfg = ChaosConfig(name="rz", trials=1, seed=0, until_step=30,
                      payload="shell", workdir=str(tmp_path),
                      resize_prob=1.0, resize_worlds=(1,), shrink=False,
                      trial_timeout_s=90.0, drain_timeout_s=30.0)
    summary = ChaosCampaign(cfg).run()
    assert summary["all_green"] is True, summary
    assert summary["invariants"]["reconfigure"]["pass"] == 1
    assert summary["reconfigures"] == 1
    assert summary["faults"]["scheduled"] >= 1
    assert 1 <= summary["faults"]["fired"] <= summary["faults"]["scheduled"]
    # the resize itself FIRED; faults still scheduled on the dropped
    # worker after the shrink legitimately land in `unfired` — the
    # accounting this PR adds is what makes that visible
    per = summary["faults"]["per_trial"][0]
    assert not any(f["kind"] == "resize" for f in per["unfired"])
    assert all(f.get("worker") == 1 for f in per["unfired"]), per
    rec = [json.loads(l) for l in
           open(tmp_path / "rz" / "chaos_report.jsonl")][0]
    assert any(f["kind"] == "resize"
               for f in rec["schedule"]["faults"])
    assert rec["final_world"] == 1
    # a second summarize pass over the artifact reproduces the verdict
    again = summarize_chaos(tmp_path / "rz" / "chaos_report.jsonl")
    assert again["all_green"] and again["faults"] == summary["faults"]


def test_chaos_report_counts_scheduled_but_never_fired_faults(tmp_path):
    """PR 7's blind spot closed: a kill scheduled past run-end fires
    nothing — the report must say so instead of looking identical to a
    real all-quiet run."""
    trial = tmp_path / "t"
    trial.mkdir()
    (trial / "command_journal.jsonl").write_text(json.dumps(
        {"event": "fault", "action": "kill_worker", "worker": 0,
         "at_step": 9, "planned_step": 8}) + "\n")
    sched = ChaosSchedule(seed=1, trial=0, faults=(
        ChaosFault("kill", worker=0, step=8),
        ChaosFault("kill", worker=1, step=1000),   # never fires
        ChaosFault("resize", step=2000, world=1),  # never fires
    ))
    got = count_fired_faults(trial, sched)
    assert got["scheduled"] == 3 and got["fired"] == 1
    assert {f["kind"] for f in got["unfired"]} == {"kill", "resize"}
    # ...and the campaign aggregate surfaces it
    (trial / "chaos_report.jsonl").write_text(json.dumps(
        {"event": "chaos_trial", "trial": 0, "seed": 1,
         "outcome": "completed", "verdicts": {}, "violations": [],
         "faults": got, "reconfigures": 0}) + "\n")
    s = summarize_chaos(trial / "chaos_report.jsonl")
    assert s["faults"] == {"scheduled": 3, "fired": 1, "never_fired": 2,
                           "per_trial": [{"trial": 0, "scheduled": 3,
                                          "fired": 1,
                                          "unfired": got["unfired"]}]}


# ---------------------------------------------------------------------------
# the cross-world resume invariant, artifact-only
# ---------------------------------------------------------------------------

def _write_trial(trial, steps=10, workers=(0,), journal_lines=()):
    trial.mkdir(parents=True, exist_ok=True)
    for k in workers:
        d = trial / f"worker{k}"
        d.mkdir(exist_ok=True)
        with open(d / "train_log.jsonl", "w") as fh:
            for s in range(1, steps + 1):
                fh.write(json.dumps({"step": s, "loss": 1.0}) + "\n")
    with open(trial / "command_journal.jsonl", "w") as fh:
        for rec in journal_lines:
            fh.write(json.dumps(rec) + "\n")
    (trial / "state.json").write_text(json.dumps(
        {"phase": "running",
         "workers": [{"worker": k, "pid": None,
                      "logdir": str(trial / f"worker{k}")}
                     for k in workers]}))


def test_reconfigure_invariant_requires_causal_license(tmp_path):
    """A run whose final roster differs from its launch world with NO
    journaled reconfigure event fails replay; adding the journaled
    reshape (the license) turns the same artifacts green."""
    outcome = {"outcome": "completed", "step": 10, "target": 10,
               "num_workers": 2, "final_world": 1,
               "supervisor": {"quorum": 1, "max_restarts_per_worker": 2}}
    trial = tmp_path / "silent"
    _write_trial(trial, workers=(0,))
    got = check_run(trial, outcome=outcome)
    assert got["verdicts"]["reconfigure"] == "fail"
    assert any("no causal license" in v["detail"]
               for v in got["violations"])

    licensed = tmp_path / "licensed"
    _write_trial(licensed, workers=(0,), journal_lines=[
        {"event": "reconfigure", "layer": "supervisor", "action": "begin",
         "old_world": 2, "new_world": 1, "trigger": "below_quorum"},
        {"event": "reconfigure", "layer": "cluster", "action": "reshape",
         "old_world": 2, "new_world": 1, "workers": [0], "dropped": [1],
         "grown": {}},
        {"event": "reconfigure", "layer": "supervisor",
         "action": "relaunched", "old_world": 2, "new_world": 1,
         "workers": [0], "via": {"0": "respawn"}},
    ])
    got = check_run(licensed, outcome=outcome)
    assert got["verdicts"]["reconfigure"] == "pass", got["violations"]

    # a journal that lies about the final roster fails too
    lying = tmp_path / "lying"
    _write_trial(lying, workers=(0,), journal_lines=[
        {"event": "reconfigure", "layer": "cluster", "action": "reshape",
         "old_world": 2, "new_world": 1, "workers": [0, 1], "grown": {}},
    ])
    got = check_run(lying, outcome=outcome)
    assert got["verdicts"]["reconfigure"] == "fail"
    assert any("disagree" in v["detail"] for v in got["violations"])


def test_reconfigure_supersedes_open_episode_not_unrecovered():
    """A kill opens a recovery episode; a reconfigure fires while the
    worker is still booting. The reshape replaces the in-flight
    restart, so no per-worker resume ever closes the episode — it must
    be filed as SUPERSEDED, not left distorting `unrecovered` on a
    fully recovered run."""
    from distributedmnist_tpu.obsv.journal import summarize_mttr
    got = summarize_mttr([
        {"action": "detect", "worker": 1, "time": 10.0},
        {"action": "episode_superseded", "worker": 1,
         "by": "reconfigure", "time": 12.0},
    ])
    assert got == {"episodes": 0, "unrecovered": 0, "superseded": 1}
    # without the supersede the same journal reads unrecovered
    got2 = summarize_mttr([{"action": "detect", "worker": 1, "time": 10.0}])
    assert got2["unrecovered"] == 1 and got2["superseded"] == 0


def test_grown_worker_seeded_dir_still_integrity_checked(tmp_path):
    """A grown worker torn down before its first step has no metrics
    to splice — but its SEEDED checkpoint dir must still pass invariant
    5 (a source file copied while torn is exactly what the digest
    sidecars exist to catch)."""
    outcome = {"outcome": "completed", "step": 10, "target": 10,
               "num_workers": 1, "final_world": 2,
               "supervisor": {"quorum": 1, "max_restarts_per_worker": 2}}
    trial = tmp_path / "g"
    _write_trial(trial, workers=(0,), journal_lines=[
        {"event": "reconfigure", "layer": "cluster", "action": "reshape",
         "old_world": 1, "new_world": 2, "workers": [0, 1], "dropped": [],
         "grown": {"1": 0}},
        {"event": "reconfigure", "layer": "supervisor",
         "action": "relaunched", "old_world": 1, "new_world": 2,
         "workers": [0, 1], "via": {"0": "respawn", "1": "respawn"}},
    ])
    # worker1: seeded artifacts, NO step records; digest sidecar lies
    d1 = trial / "worker1"
    d1.mkdir()
    (d1 / "ckpt-00000005.msgpack").write_bytes(b"torn-mid-copy")
    (d1 / "ckpt-00000005.msgpack.sha256").write_text("0" * 64)
    (trial / "state.json").write_text(json.dumps(
        {"phase": "running",
         "workers": [{"worker": 0, "pid": None,
                      "logdir": str(trial / "worker0")},
                     {"worker": 1, "pid": None, "logdir": str(d1)}]}))
    got = check_run(trial, outcome=outcome)
    assert got["verdicts"]["checkpoint_integrity"] == "fail"
    assert any(v["worker"] == 1 and v["invariant"] == "checkpoint_integrity"
               for v in got["violations"])


def test_grow_seeds_only_newest_checkpoint(tmp_path):
    """Backend-level grow seeding resolves the checkpoint.json pointer
    and copies ONLY that step's artifacts — every retained cadence save
    would multiply disk per grown worker and leave stale steps as
    silent fallback candidates."""
    c = _cluster(tmp_path, num_workers=1)
    c.create()
    src = c.cfg.worker_dir(0)
    src.mkdir(parents=True, exist_ok=True)
    for s in (5, 10):
        (src / f"ckpt-{s:08d}.msgpack").write_bytes(b"x" * 8)
        (src / f"ckpt-{s:08d}.msgpack.sha256").write_text("y")
    (src / "checkpoint.json").write_text(json.dumps(
        {"latest_step": 10, "latest_path": "ckpt-00000010.msgpack"}))
    rec = c.reconfigure(2)
    assert rec["grown"] == {"1": 0}
    seeded = sorted(p.name for p in c.cfg.worker_dir(1).glob("ckpt*"))
    assert seeded == ["ckpt-00000010.msgpack",
                      "ckpt-00000010.msgpack.sha256"]
    assert (c.cfg.worker_dir(1) / "checkpoint.json").exists()
    c.delete()


def test_reconfigure_invariant_skipped_without_world_change(tmp_path):
    outcome = {"outcome": "completed", "step": 10, "target": 10,
               "num_workers": 1,
               "supervisor": {"quorum": 1, "max_restarts_per_worker": 2}}
    trial = tmp_path / "plain"
    _write_trial(trial, workers=(0,))
    got = check_run(trial, outcome=outcome)
    assert got["verdicts"]["reconfigure"] == "skipped"
