"""Model-family numerics tests (≙ SURVEY §2.1 "Model" row parity)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributedmnist_tpu.core.config import ModelConfig
from distributedmnist_tpu.models import available, get_model
from distributedmnist_tpu.models import cnn


def test_registry_lists_families():
    assert {"mnist_cnn", "resnet20", "transformer"} <= set(available())


def test_all_registered_models_buildable():
    """Every advertised family must init+apply (regression: registry
    used to list families whose modules didn't exist)."""
    for name in available():
        cfg = ModelConfig(name=name, compute_dtype="float32",
                          num_channels=3 if name == "resnet20" else 1,
                          image_size=32 if name == "resnet20" else 28,
                          seq_len=32, model_dim=32, num_heads=2, num_layers=1)
        model = get_model(cfg)
        params = model.init(jax.random.PRNGKey(0))
        x = jnp.zeros((2,) + model.input_shape, model.input_dtype)
        logits = model.apply(params, x, train=False)
        assert logits.shape[0] == 2
        assert jnp.all(jnp.isfinite(logits))


def test_every_model_exports_predictions():
    """The serving tier's model-agnostic contract: EVERY registered
    family carries a ``predictions`` export producing a per-example
    probability distribution (softmax class probs for classifiers,
    next-token distribution for the LM) — the same registry-driven
    genericity the trainer has."""
    for name in available():
        cfg = ModelConfig(name=name, compute_dtype="float32",
                          num_channels=3 if name == "resnet20" else 1,
                          image_size=32 if name == "resnet20" else 28,
                          seq_len=32, model_dim=32, num_heads=2, num_layers=1)
        model = get_model(cfg)
        assert callable(model.predictions), name
        params = model.init(jax.random.PRNGKey(0))
        x = jnp.zeros((2,) + model.input_shape, model.input_dtype)
        probs = model.predictions(model.apply(params, x, train=False))
        # one distribution per example, regardless of family
        assert probs.ndim == 2 and probs.shape[0] == 2, (name, probs.shape)
        expected_classes = (cfg.vocab_size if name == "transformer"
                            else cfg.num_classes)
        assert probs.shape[1] == expected_classes, name
        np.testing.assert_allclose(np.asarray(probs).sum(axis=-1),
                                   np.ones(2), rtol=1e-5)
        assert np.all(np.asarray(probs) >= 0), name


def test_cnn_param_shapes_and_init_constants():
    """Parity with reference init (src/mnist.py:81-101): conv1 bias 0,
    conv2/fc biases 0.1, truncated-normal weights with stddev 0.1."""
    params = cnn.init(jax.random.PRNGKey(66478))
    assert params["conv1"]["w"].shape == (5, 5, 1, 32)
    assert params["conv2"]["w"].shape == (5, 5, 32, 64)
    assert params["fc1"]["w"].shape == (7 * 7 * 64, 512)
    assert params["fc2"]["w"].shape == (512, 10)
    np.testing.assert_array_equal(np.asarray(params["conv1"]["b"]), 0.0)
    np.testing.assert_allclose(np.asarray(params["conv2"]["b"]), 0.1, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(params["fc1"]["b"]), 0.1, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(params["fc2"]["b"]), 0.1, rtol=1e-6)
    # truncated at ±2σ = ±0.2
    w = np.asarray(params["fc1"]["w"])
    assert np.abs(w).max() <= 0.2 + 1e-6
    assert 0.05 < w.std() < 0.15


def test_cnn_loss_matches_manual_xent():
    logits = jnp.array([[2.0, 1.0, 0.1], [0.5, 2.5, 0.2]])
    labels = jnp.array([0, 1])
    got = float(cnn.loss_fn(logits, labels))
    p = jax.nn.log_softmax(logits)
    want = float(-(p[0, 0] + p[1, 1]) / 2)
    assert got == pytest.approx(want, rel=1e-6)


def test_cnn_predictions_softmax_parity():
    """≙ tf.nn.softmax export (src/mnist.py:166-167): rows are proper
    distributions and exp-normalized logits."""
    logits = jnp.array([[2.0, 1.0, 0.1], [0.5, 2.5, 0.2]])
    probs = np.asarray(cnn.predictions(logits))
    np.testing.assert_allclose(probs.sum(axis=-1), 1.0, rtol=1e-6)
    np.testing.assert_allclose(
        probs, np.exp(logits) / np.exp(logits).sum(-1, keepdims=True),
        rtol=1e-6)


def test_cnn_accuracy():
    logits = jnp.array([[2.0, 1.0], [0.1, 3.0], [5.0, 0.0], [0.0, 1.0]])
    labels = jnp.array([0, 1, 1, 1])
    assert float(cnn.accuracy(logits, labels)) == pytest.approx(0.75)


def test_cnn_dropout_train_vs_eval():
    params = cnn.init(jax.random.PRNGKey(1))
    x = jax.random.normal(jax.random.PRNGKey(2), (4, 28, 28, 1))
    eval_logits = cnn.apply(params, x, train=False, compute_dtype=jnp.float32)
    k = jax.random.PRNGKey(3)
    train_logits = cnn.apply(params, x, train=True, dropout_key=k,
                             compute_dtype=jnp.float32)
    assert not np.allclose(np.asarray(eval_logits), np.asarray(train_logits))
    # dropout requires a key
    with pytest.raises(ValueError):
        cnn.apply(params, x, train=True, compute_dtype=jnp.float32)
    # deterministic given the key
    again = cnn.apply(params, x, train=True, dropout_key=k,
                      compute_dtype=jnp.float32)
    np.testing.assert_array_equal(np.asarray(train_logits), np.asarray(again))


def test_resnet20_learns_a_step():
    from distributedmnist_tpu.models import resnet
    params = resnet.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 32, 32, 3)) * 0.3
    y = jnp.array([0, 1, 2, 3])

    def loss(p):
        return cnn.loss_fn(resnet.apply(p, x, compute_dtype=jnp.float32), y)

    l0 = float(loss(params))
    g = jax.grad(loss)(params)
    params2 = jax.tree.map(lambda p_, g_: p_ - 0.1 * g_, params, g)
    assert float(loss(params2)) < l0


def test_transformer_next_token_loss_decreases():
    from distributedmnist_tpu.models import transformer
    params = transformer.init(jax.random.PRNGKey(0), vocab_size=17,
                              model_dim=32, num_heads=2, num_layers=1,
                              max_seq_len=16)
    toks = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 17)

    def loss(p):
        logits = transformer.apply(p, toks, num_heads=2,
                                   compute_dtype=jnp.float32)
        return transformer.loss_fn(logits, toks)

    l0 = float(loss(params))
    g = jax.grad(loss)(params)
    params2 = jax.tree.map(lambda p_, g_: p_ - 0.5 * g_, params, g)
    assert float(loss(params2)) < l0
