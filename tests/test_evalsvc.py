"""Continuous-evaluator tests (≙ src/nn_eval.py behavior contract)."""

import re

import pytest

from conftest import base_config


def _train(tmp_train_dir, synthetic_datasets, steps=30):
    from distributedmnist_tpu.train.loop import Trainer
    cfg = base_config(train={"train_dir": tmp_train_dir, "max_steps": steps,
                             "log_every_steps": 10, "save_interval_steps": 10})
    t = Trainer(cfg, datasets=synthetic_datasets)
    t.run()
    return cfg


@pytest.mark.slow  # trains + polls a full evaluator loop; ~70 s on the tier-1 box
def test_evaluator_reads_checkpoints(tmp_train_dir, synthetic_datasets,
                                     tmp_path, capsys):
    from distributedmnist_tpu.core.config import EvalConfig
    from distributedmnist_tpu.evalsvc import Evaluator
    cfg = _train(tmp_train_dir, synthetic_datasets, steps=120)
    ecfg = EvalConfig(eval_dir=str(tmp_path / "eval"), run_once=True,
                      eval_interval_secs=0.01)
    ev = Evaluator(tmp_train_dir, ecfg, cfg=cfg, datasets=synthetic_datasets)
    results = ev.run()
    assert len(results) == 1
    r = results[0]
    assert r["step"] == 120
    assert r["num_examples"] == synthetic_datasets.test.num_examples
    assert r["precision_at_1"] >= 0.99
    # the reference-parity parseable line (src/nn_eval.py:102-103)
    out = capsys.readouterr().out
    m = re.search(r"Num examples: (\d+) Precision @ 1: ([0-9.]+) "
                  r"Loss: ([0-9.]+) Time: ([0-9.]+)", out)
    assert m, out
    assert int(m.group(1)) == r["num_examples"]


@pytest.mark.slow  # ~25 s; the service loop stays covered in tier-1 by
# test_evaluator_adopts_checkpoint_config
def test_evaluator_skips_unchanged_step(tmp_train_dir, synthetic_datasets, tmp_path):
    """≙ the global-step-unchanged skip (src/nn_eval.py:84-88)."""
    from distributedmnist_tpu.core.config import EvalConfig
    from distributedmnist_tpu.evalsvc import Evaluator
    cfg = _train(tmp_train_dir, synthetic_datasets)
    ecfg = EvalConfig(eval_dir=str(tmp_path / "eval"), max_evals=1,
                      eval_interval_secs=0.01)
    ev = Evaluator(tmp_train_dir, ecfg, cfg=cfg, datasets=synthetic_datasets)
    ev.run()
    assert ev.last_step_evaluated == 30
    # second poll with no new checkpoint: evaluate_checkpoint not re-run
    from distributedmnist_tpu.train import checkpoint as ckpt
    assert ckpt.latest_checkpoint_step(tmp_train_dir) == ev.last_step_evaluated


@pytest.mark.slow  # boots a real single-device evaluator subprocess; ~60 s
def test_evaluator_single_device_mode(tmp_train_dir, synthetic_datasets,
                                      tmp_path):
    """The lean co-located mode: a data-parallel checkpoint evaluates
    on ONE ambient device (no forced mesh, no collectives), matching
    the full-mesh evaluation; model-sharded configs are refused."""
    import pytest

    from distributedmnist_tpu.core.config import EvalConfig
    from distributedmnist_tpu.evalsvc import Evaluator
    cfg = _train(tmp_train_dir, synthetic_datasets, steps=120)
    ecfg = EvalConfig(eval_dir=str(tmp_path / "eval"), run_once=True,
                      eval_interval_secs=0.01)
    ev = Evaluator(tmp_train_dir, ecfg, cfg=cfg, datasets=synthetic_datasets,
                   single_device=True)
    assert ev.topo.num_replicas == 1
    assert len(ev.topo.mesh.devices.flatten()) == 1
    results = ev.run()
    assert results[0]["step"] == 120
    assert results[0]["precision_at_1"] >= 0.99

    pp_cfg = cfg.override({"mesh.pipeline_parallelism": 2})
    with pytest.raises(ValueError, match="single_device"):
        Evaluator(tmp_train_dir, ecfg, cfg=pp_cfg,
                  datasets=synthetic_datasets, single_device=True)


def test_evaluator_skips_corrupt_checkpoint_and_retries(
        tmp_train_dir, synthetic_datasets, tmp_path):
    """The satellite regression this path never had: a corrupt/torn
    newest checkpoint makes the evaluator SKIP-AND-RETRY (via the
    shared train/checkpoint.py CheckpointFollower), not crash — and
    once a good publish lands, it evaluates that. Pins the contract
    that CheckpointCorruptError flows into the follower's ValueError
    skip path instead of killing the long-running service."""
    from pathlib import Path

    from distributedmnist_tpu.core.config import EvalConfig
    from distributedmnist_tpu.evalsvc import Evaluator
    cfg = _train(tmp_train_dir, synthetic_datasets, steps=20)
    newest = Path(tmp_train_dir) / "ckpt-00000020.msgpack"
    good_bytes = newest.read_bytes()
    # tear the newest artifact; its digest sidecar stays — the read
    # fails verification (CheckpointCorruptError, a ValueError)
    newest.write_bytes(good_bytes[: len(good_bytes) // 2])
    ev = Evaluator(tmp_train_dir, EvalConfig(eval_dir=str(tmp_path / "e")),
                   cfg=cfg, datasets=synthetic_datasets)
    assert ev.poll_once() is None          # skipped, no crash
    assert ev.last_step_evaluated == -1    # nothing consumed
    assert ev.follower.skips == 1
    assert ev.follower.last_error[0] == 20
    assert ev.poll_once() is None          # retried, still skipped
    assert ev.follower.skips == 2
    newest.write_bytes(good_bytes)         # the re-publish lands
    out = ev.poll_once()
    assert out is not None and out["step"] == 20
    assert ev.last_step_evaluated == 20


def test_evaluator_adopts_checkpoint_config(tmp_train_dir, synthetic_datasets, tmp_path):
    """The evaluator rebuilds the exact trainer config from the
    checkpoint itself — no trainer/evaluator graph skew."""
    from distributedmnist_tpu.core.config import EvalConfig
    from distributedmnist_tpu.evalsvc import Evaluator
    cfg = _train(tmp_train_dir, synthetic_datasets)
    ev = Evaluator(tmp_train_dir, EvalConfig(eval_dir=str(tmp_path / "e")),
                   datasets=synthetic_datasets)
    assert ev.cfg.data.batch_size == cfg.data.batch_size
    assert ev.cfg.model == cfg.model
