"""Online straggler-discipline controller (train/discipline.py).

Covers the pure decision core (dead band, cooldown, bounds — the
broker decide() contract), the controller's journaled begin/complete
licensing, the rolling-CDF gauges it reads, the ``discipline`` replay
invariant (including the pinned doctored-unlicensed-change failure),
and the epoch-spliced determinism comparison in check_run.
"""

import json

import pytest

import numpy as np

from distributedmnist_tpu.core.config import ConfigError, SyncConfig
from distributedmnist_tpu.obsv import invariants as inv
from distributedmnist_tpu.obsv import schema
from distributedmnist_tpu.obsv.journal import summarize_discipline
from distributedmnist_tpu.obsv.timing import StepTimeCollector
from distributedmnist_tpu.train import discipline as disc
from distributedmnist_tpu.train.discipline import (DisciplineController,
                                                   DisciplineParams,
                                                   WindowStats, decide,
                                                   discipline_trace,
                                                   quorum_floor,
                                                   static_params,
                                                   threshold_holds)

pytestmark = pytest.mark.tier1

N = 8


def _cfg(**kw) -> SyncConfig:
    base = dict(mode="quorum", adaptive=True, adaptive_window_steps=4,
                adaptive_cooldown_steps=4)
    base.update(kw)
    return SyncConfig(**base)


def _ws(ratio: float, base: float = 50.0, n: int = 4) -> WindowStats:
    return WindowStats(p50_ms=base, p90_ms=base, p99_ms=base * ratio,
                       n_samples=n, fast_p50_ms=base)


def _params(cfg: SyncConfig, k: int | None = None) -> DisciplineParams:
    p = static_params(cfg, N)
    return p if k is None else DisciplineParams(
        k=k, timeout_ms=p.timeout_ms, interval_ms=p.interval_ms,
        num_replicas=N)


# ---------------------------------------------------------------------------
# decide(): the pure core
# ---------------------------------------------------------------------------

def test_decide_requires_adaptive_and_full_window():
    cfg = _cfg()
    cur = _params(cfg)
    off = SyncConfig(mode="quorum")
    assert decide(off, _ws(9.0), cur, None, 10) is None
    assert decide(cfg, None, cur, None, 10) is None
    assert decide(cfg, _ws(9.0, n=3), cur, None, 10) is None  # short


def test_decide_dead_band_is_hysteresis():
    cfg = _cfg(adaptive_tail_high=2.0, adaptive_tail_low=1.3)
    cur = _params(cfg, k=6)
    # between the marks: nothing, in BOTH directions
    assert decide(cfg, _ws(1.6), cur, None, 10) is None
    d = decide(cfg, _ws(2.0), cur, None, 10)
    assert d is not None and d.decision == "tighten" and d.new_k == 5
    d = decide(cfg, _ws(1.3), cur, None, 10)
    assert d is not None and d.decision == "relax" and d.new_k == 7


def test_decide_cooldown_suppresses_everything():
    cfg = _cfg(adaptive_cooldown_steps=10, adaptive_window_steps=4)
    cur = _params(cfg, k=6)
    assert decide(cfg, _ws(9.0), cur, last_change_t=5, now=14) is None
    assert decide(cfg, _ws(9.0), cur, last_change_t=5, now=15) is not None


def test_decide_quorum_bounds_floor_and_static_ceiling():
    cfg = _cfg(adaptive_min_quorum_frac=0.5)
    floor = quorum_floor(cfg, N)
    assert floor == 4
    # at the floor, a blown tail is a no-op, not a change
    assert decide(cfg, _ws(9.0), _params(cfg, k=floor), None, 10) is None
    # at the static ceiling, a calm tail is a no-op
    assert decide(cfg, _ws(1.0), _params(cfg), None, 10) is None


def test_decide_timeout_retargets_from_cohort_pace():
    cfg = _cfg(mode="timeout", timeout_ms=1000.0,
               adaptive_timeout_factor=1.5, adaptive_timeout_floor_ms=1.0)
    cur = _params(cfg)
    d = decide(cfg, _ws(9.0, base=50.0), cur, None, 10)
    assert d is not None and d.decision == "tighten"
    assert d.new_timeout_ms == pytest.approx(75.0)
    # sub-percent retarget sits in the dead band
    tight = DisciplineParams(k=cur.k, timeout_ms=75.2, interval_ms=0.0,
                             num_replicas=N)
    assert decide(cfg, _ws(9.0, base=50.0), tight, None, 10) is None
    # relax restores the configured deadline, never past it
    d = decide(cfg, _ws(1.0), tight, None, 10)
    assert d is not None and d.new_timeout_ms == pytest.approx(1000.0)
    assert decide(cfg, _ws(1.0), cur, None, 10) is None  # already static


def test_decide_property_k_stays_bounded_no_change_in_cooldown():
    import random
    rng = random.Random(0)
    cfg = _cfg()
    floor, static_k = quorum_floor(cfg, N), static_params(cfg, N).k
    cur, last = _params(cfg), None
    for step in range(5, 400):
        d = decide(cfg, _ws(rng.choice([0.5, 1.0, 1.6, 3.0, 9.0])),
                   cur, last, step)
        if d is not None:
            assert floor <= d.new_k <= static_k
            assert abs(d.new_k - cur.k) == 1  # one notch at a time
            if last is not None:
                assert step - last >= cfg.adaptive_cooldown_steps
            cur = DisciplineParams(k=d.new_k,
                                   timeout_ms=d.new_timeout_ms,
                                   interval_ms=cur.interval_ms,
                                   num_replicas=N)
            last = step


def test_window_stats_prefers_cohort_pace_over_pooled_p50():
    # two 8x stragglers of four drag the POOLED median to the midpoint;
    # the fastest replica's median keeps the signal out of the dead band
    s = WindowStats(p50_ms=225.0, p90_ms=400.0, p99_ms=400.0,
                    n_samples=6, fast_p50_ms=50.0)
    assert s.tail_ratio == pytest.approx(8.0)
    no_fast = WindowStats(p50_ms=225.0, p90_ms=400.0, p99_ms=400.0,
                          n_samples=6)
    assert no_fast.tail_ratio == pytest.approx(400.0 / 225.0)
    assert WindowStats(0.0, 0.0, 0.0, 6).tail_ratio == 0.0


def test_threshold_holds_matches_invariant_semantics():
    assert threshold_holds(2.0, ">=", 2.0)
    assert not threshold_holds(1.9, ">=", 2.0)
    assert threshold_holds(1.3, "<=", 1.3)
    assert not threshold_holds(1.4, "<=", 1.3)


# ---------------------------------------------------------------------------
# config validation
# ---------------------------------------------------------------------------

def test_adaptive_knob_validation():
    with pytest.raises(ConfigError, match="maskable"):
        SyncConfig(mode="sync", adaptive=True).validate()
    with pytest.raises(ConfigError, match="window"):
        _cfg(adaptive_window_steps=1).validate()
    with pytest.raises(ConfigError, match="cooldown"):
        _cfg(adaptive_window_steps=8,
             adaptive_cooldown_steps=4).validate()
    with pytest.raises(ConfigError, match="high > low"):
        _cfg(adaptive_tail_high=1.2, adaptive_tail_low=1.3).validate()
    with pytest.raises(ConfigError, match="min_quorum_frac"):
        _cfg(adaptive_min_quorum_frac=0.0).validate()
    with pytest.raises(ConfigError, match="timeout_factor"):
        _cfg(adaptive_timeout_factor=0.5).validate()
    with pytest.raises(ConfigError, match="floor"):
        _cfg(adaptive_timeout_floor_ms=0.0).validate()
    # a starting quorum below the adaptive floor is a contradiction
    with pytest.raises(ConfigError, match="floor"):
        _cfg(num_replicas_to_aggregate=2).validate(num_replicas=8)
    _cfg().validate(num_replicas=8)  # defaults are coherent


# ---------------------------------------------------------------------------
# the controller: journaling + the traced-vector swap
# ---------------------------------------------------------------------------

def _run_controller(ratios, cfg=None):
    cfg = cfg or _cfg()
    journal: list[dict] = []
    vectors: list[tuple] = []
    ctrl = DisciplineController(
        cfg, N, journal.append,
        lambda k, t, i: (vectors.append((k, t, i)) or (k, t, i)))
    for step, r in enumerate(ratios, start=1):
        ctrl.maybe_adapt(step, _ws(r))
    return ctrl, journal, vectors


def test_controller_journals_licensed_pairs_and_swaps_vector():
    ratios = [1.0] * 4 + [9.0] * 10 + [1.0] * 10
    ctrl, journal, vectors = _run_controller(ratios)
    begins = [r for r in journal if r["action"] == "begin"]
    completes = [r for r in journal if r["action"] == "complete"]
    assert len(begins) == len(completes) == ctrl.changes >= 2
    for r in journal:  # every record passes the declared schema
        assert schema.validate_event(r, source="test") == []
    for b, c in zip(begins, completes):
        assert threshold_holds(b["value"], b["op"], b["threshold"])
        assert c["effective_step"] == b["at_step"] + 1
        assert c["k"] == b["new_k"]
    # one staged vector per change, plus the initial one
    assert len(vectors) == ctrl.changes + 1
    assert ctrl.trace == discipline_trace(journal)
    assert ctrl.summary()["changes"] == ctrl.changes


def test_controller_tightens_to_floor_then_relaxes_to_static():
    cfg = _cfg()
    ctrl, journal, _ = _run_controller([9.0] * 40, cfg)
    assert ctrl.current.k == quorum_floor(cfg, N)
    ctrl2, j2, _ = _run_controller([9.0] * 20 + [1.0] * 40, cfg)
    assert ctrl2.current.k == static_params(cfg, N).k
    s = summarize_discipline(j2)
    assert s["by_direction"].get("tighten", 0) >= 1
    assert s["by_direction"].get("relax", 0) >= 1
    assert s["flaps"] == 0  # cooldown-spaced reversals are not flaps
    assert s["completed"] == s["changes"]
    assert s["reaction_s"]["p50"] >= 0


def test_controller_refuses_non_adaptive_config():
    with pytest.raises(ValueError, match="adaptive"):
        DisciplineController(SyncConfig(mode="quorum"), N,
                             lambda r: None, lambda k, t, i: None)


def test_summarize_discipline_counts_tight_reversal_as_flap():
    def begin(step, decision):
        return {"event": "discipline", "action": "begin",
                "decision": decision, "at_step": step,
                "cooldown_steps": 4}
    flappy = [begin(10, "tighten"), begin(14, "relax")]
    assert summarize_discipline(flappy)["flaps"] == 1
    spaced = [begin(10, "tighten"), begin(30, "relax")]
    assert summarize_discipline(spaced)["flaps"] == 0


# ---------------------------------------------------------------------------
# rolling CDF gauges (obsv/timing.py)
# ---------------------------------------------------------------------------

def test_rolling_cdf_gauges():
    c = StepTimeCollector(num_replicas=4)
    c.enable_rolling_cdf(4)
    c.add(np.array([50.0, 50.0, 400.0, 400.0]), 0.05)
    assert c.rolling_cdf() is None  # never decide on a half window
    for _ in range(4):
        c.add(np.array([50.0, 50.0, 400.0, 400.0]), 0.05)
    r = c.rolling_cdf()
    assert r is not None and r["window_steps"] == 4
    assert r["fast_p50_ms"] == pytest.approx(50.0)
    assert r["p99_ms"] == pytest.approx(400.0, rel=0.01)
    assert r["tail_ratio"] == pytest.approx(8.0, rel=0.01)
    assert len(r["per_replica"]) == 4
    assert "rolling_cdf" in c.report()  # armed → gauges in the report
    plain = StepTimeCollector(num_replicas=4)
    plain.add(np.array([1.0, 1.0, 1.0, 1.0]), 0.01)
    assert "rolling_cdf" not in plain.report()  # present iff armed
    with pytest.raises(ValueError):
        plain.enable_rolling_cdf(0)


# ---------------------------------------------------------------------------
# the replay invariant
# ---------------------------------------------------------------------------

def _begin(step, new_k, old_k, value=8.0, op=">=", thr=2.0,
           decision="tighten"):
    return {"event": "discipline", "action": "begin", "time": 1.0,
            "decision": decision, "trigger": "tail_ratio",
            "value": value, "threshold": thr, "op": op,
            "old_k": old_k, "new_k": new_k,
            "old_timeout_ms": 1000.0, "new_timeout_ms": 1000.0,
            "at_step": step}


def _complete(step, k, decision="tighten"):
    return {"event": "discipline", "action": "complete", "time": 1.1,
            "decision": decision, "trigger": "tail_ratio",
            "reaction_s": 0.01, "k": k, "timeout_ms": 1000.0,
            "effective_step": step + 1}


def _step(step, k):
    return {"event": "step", "step": step, "loss": 1.0,
            "discipline": [float(k), 1000.0]}


def _licensed_log(change_at=2, old_k=4, new_k=3, steps=4):
    recs = []
    for s in range(1, steps + 1):
        recs.append(_step(s, new_k if s > change_at else old_k))
        if s == change_at:
            recs += [_begin(s, new_k, old_k), _complete(s, new_k)]
    return recs


def test_check_discipline_green_and_not_applicable():
    log = _licensed_log()
    steps = [r for r in log if r.get("event") == "step"]
    violations, applicable = inv.check_discipline(steps, log)
    assert applicable and violations == []
    v, app = inv.check_discipline([{"event": "step", "step": 1}],
                                  [{"event": "step", "step": 1}])
    assert not app and v == []


def test_check_discipline_pins_doctored_unlicensed_change():
    """Acceptance: a step record whose [k, timeout] pair changed with
    no licensing begin/complete at that boundary MUST fail replay."""
    log = _licensed_log()
    steps = [dict(r) for r in log if r.get("event") == "step"]
    steps[2]["discipline"] = [2.0, 1000.0]  # doctor step 3's pair
    violations, _ = inv.check_discipline(steps, log)
    assert any("unlicensed" in v.detail or "licensing complete"
               in v.detail for v in violations)
    # deleting the begin breaks the pairing too
    no_begin = [r for r in log if r.get("action") != "begin"]
    v2, _ = inv.check_discipline(
        [r for r in log if r.get("event") == "step"], no_begin)
    assert any("no open begin" in v.detail for v in v2)


def test_check_discipline_rejects_fabricated_license():
    bad = [_begin(2, 3, 4, value=1.5, op=">=", thr=2.0), _complete(2, 3)]
    v, app = inv.check_discipline([], bad)
    assert app and any("does not hold" in x.detail for x in v)
    malformed = [_begin(2, 3, 4, value=None), _complete(2, 3)]
    v2, _ = inv.check_discipline([], malformed)
    assert any("malformed license" in x.detail for x in v2)


def test_check_discipline_single_flight_and_boundary():
    dangling = [_begin(2, 3, 4)]
    v, _ = inv.check_discipline([], dangling)
    assert any("never closed" in x.detail for x in v)
    overlapping = [_begin(2, 3, 4), _begin(6, 2, 3), _complete(6, 2)]
    v2, _ = inv.check_discipline([], overlapping)
    assert any("single-flight" in x.detail for x in v2)
    # complete landing on the wrong pair / wrong boundary
    mismatch = [_begin(2, 3, 4), _complete(2, 2)]
    v3, _ = inv.check_discipline([], mismatch)
    assert any("begin declared" in x.detail for x in v3)
    off = [_begin(2, 3, 4),
           dict(_complete(2, 3), effective_step=5)]
    v4, _ = inv.check_discipline([], off)
    assert any("epoch boundary" in x.detail for x in v4)


def test_discipline_trace_skips_malformed_completes():
    log = _licensed_log() + [{"event": "discipline",
                              "action": "complete", "k": "junk"}]
    assert discipline_trace(log) == [(3, 3.0, 1000.0)]


# ---------------------------------------------------------------------------
# epoch-spliced determinism (check_run)
# ---------------------------------------------------------------------------

def _write_log(path, records):
    path.parent.mkdir(parents=True, exist_ok=True)
    with open(path, "w") as fh:
        for r in records:
            fh.write(json.dumps(r) + "\n")


def _trial_with_checkpoint(root, state, log_records, steps=4):
    from distributedmnist_tpu.train import checkpoint as ckpt
    w0 = root / "worker0"
    _write_log(w0 / "train_log.jsonl", log_records)
    ckpt.save_checkpoint(w0, ("full", state), step=steps)
    (root / "command_journal.jsonl").write_text("")
    return {"outcome": "completed", "step": steps, "target": steps,
            "supervisor": {"quorum": 1}}


def test_check_run_splices_determinism_at_epoch_divergence(tmp_path):
    """Invariant 3 under the controller: equal discipline traces →
    the bitwise digest comparison runs (and a doctored state FAILS);
    divergent traces → the comparison is spliced out for that worker
    (skip with the splice counted), while the discipline licensing
    invariant still replays."""
    from distributedmnist_tpu.train import checkpoint as ckpt

    state_a = {"params": {"w": np.arange(8, dtype=np.float32)},
               "momentum": {"w": np.zeros(8, dtype=np.float32)},
               "step": np.int32(4)}
    state_b = {"params": {"w": np.arange(8, dtype=np.float32) + 1.0},
               "momentum": {"w": np.zeros(8, dtype=np.float32)},
               "step": np.int32(4)}
    ref = tmp_path / "reference" / "worker0"
    _write_log(ref / "train_log.jsonl", _licensed_log())
    ckpt.save_checkpoint(ref, ("full", state_a), step=4)

    # same trace, different state: the bitwise claim applies and fails
    t1 = tmp_path / "trial1"
    outcome = _trial_with_checkpoint(t1, state_b, _licensed_log())
    got = inv.check_run(t1, outcome=outcome, reference_dir=ref)
    assert got["verdicts"]["discipline"] == "pass"
    assert got["verdicts"]["determinism"] == "fail"
    assert got["determinism_workers_spliced"] == 0

    # divergent trace (an extra licensed change): spliced out, skipped
    diverged = _licensed_log() + [_begin(4, 2, 3), _complete(4, 2)]
    t2 = tmp_path / "trial2"
    outcome2 = _trial_with_checkpoint(t2, state_b, diverged)
    got2 = inv.check_run(t2, outcome=outcome2, reference_dir=ref)
    assert got2["verdicts"]["discipline"] == "pass"
    assert got2["verdicts"]["determinism"] == "skipped"
    assert got2["determinism_workers_spliced"] == 1
    assert not any(v["invariant"] == "determinism"
                   for v in got2["violations"])

    # the licensing invariant is NOT relaxed by the splice
    t3 = tmp_path / "trial3"
    doctored = [dict(r) for r in diverged]
    for r in doctored:
        if r.get("event") == "step" and r["step"] == 2:
            r["discipline"] = [2.0, 1000.0]  # unlicensed early change
    outcome3 = _trial_with_checkpoint(t3, state_b, doctored)
    got3 = inv.check_run(t3, outcome=outcome3, reference_dir=ref)
    assert got3["verdicts"]["discipline"] == "fail"


def test_check_run_discipline_skipped_when_never_armed(tmp_path):
    w0 = tmp_path / "worker0"
    _write_log(w0 / "train_log.jsonl",
               [{"step": s, "loss": 1.0} for s in range(1, 5)])
    (tmp_path / "command_journal.jsonl").write_text("")
    got = inv.check_run(tmp_path, outcome={
        "outcome": "completed", "step": 4, "target": 4,
        "supervisor": {"quorum": 1}})
    assert got["verdicts"]["discipline"] == "skipped"


# ---------------------------------------------------------------------------
# end to end: the trainer under a seeded spike profile
# ---------------------------------------------------------------------------

def test_trainer_adapts_quorum_under_spike_profile(tmp_train_dir,
                                                   synthetic_datasets):
    """The whole loop on 8 virtual devices: spike stragglers blow the
    rolling tail ratio, the controller tightens the traced quorum, the
    step records observe the change, and the artifact set replays green
    against the discipline invariant."""
    from pathlib import Path

    from conftest import base_config
    from distributedmnist_tpu.obsv.report import load_jsonl
    from distributedmnist_tpu.train.loop import Trainer

    cfg = base_config(
        sync={"mode": "quorum", "adaptive": True,
              "adaptive_window_steps": 4, "adaptive_cooldown_steps": 4,
              "straggler_profile": "spike",
              "straggler_spike_prob": 0.25,
              "straggler_spike_scale": 8.0},
        train={"max_steps": 14, "log_every_steps": 1,
               "train_dir": tmp_train_dir})
    run_summary = Trainer(cfg, datasets=synthetic_datasets).run()
    summary = run_summary["discipline"]
    assert summary["changes"] >= 1
    assert summary["current_k"] < 8  # tightened off the static quorum

    log = load_jsonl(Path(tmp_train_dir) / "train_log.jsonl")
    steps = [r for r in log if r.get("event") == "step"
             and isinstance(r.get("step"), int)]
    assert all("discipline" in r for r in steps)  # armed → observed
    pairs = {tuple(r["discipline"]) for r in steps}
    assert len(pairs) >= 2  # the change is visible in the series
    violations, applicable = inv.check_discipline(steps, log)
    assert applicable and violations == []
    assert discipline_trace(log) == [tuple(t) for t in summary["trace"]]
