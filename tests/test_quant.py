"""Quantized serving path: the publish-time PTQ pass, the sidecar
artifact contract (digest-verified, additive, byte-unchanged fp32
artifacts), the parity guard, and the serving-side tier machinery's
journal/invariant extensions."""

import json
from pathlib import Path

import numpy as np
import pytest

from conftest import base_config


# ---------------------------------------------------------------------------
# the quantizer itself
# ---------------------------------------------------------------------------

@pytest.mark.tier1
def test_int8_per_channel_quantization_math():
    from distributedmnist_tpu.quant.ptq import (dequantize_tree_int8,
                                                quantize_leaf_int8,
                                                quantize_tree_int8)
    rng = np.random.default_rng(0)
    w = rng.normal(size=(5, 5, 3, 8)).astype(np.float32)
    got = quantize_leaf_int8(w)
    assert got["q"].dtype == np.int8 and got["q"].shape == w.shape
    # per LAST-axis channel: one scale per output channel
    assert got["scale"].shape == (1, 1, 1, 8)
    # straight-line reference for channel 0
    absmax = np.abs(w[..., 0]).max()
    assert np.isclose(got["scale"][0, 0, 0, 0], absmax / 127.0)
    # dequantize error bounded by half a quantization step per element
    deq = np.asarray(dequantize_tree_int8(got))
    assert np.max(np.abs(deq - w) / got["scale"]) <= 0.5 + 1e-6

    tree = {"fc": {"w": w[0, 0], "b": np.ones(8, np.float32)},
            "emb": np.arange(4, dtype=np.int32)}
    q = quantize_tree_int8(tree)
    assert set(q["fc"]["w"]) == {"q", "scale"}     # 2-D: quantized
    assert q["fc"]["b"].dtype == np.float32        # 1-D float: passthrough
    assert q["emb"].dtype == np.int32              # integer: untouched
    back = dequantize_tree_int8(q)
    assert np.asarray(back["fc"]["b"]).dtype == np.float32
    assert np.allclose(np.asarray(back["fc"]["w"]), w[0, 0], atol=2e-2)


@pytest.mark.tier1
def test_bf16_tier_cast_and_input_fake_quant():
    import ml_dtypes

    from distributedmnist_tpu.quant.ptq import (cast_tree_bf16,
                                                dynamic_input_fake_quant)
    tree = {"w": np.ones((2, 2), np.float32), "ids": np.zeros(2, np.int32)}
    b = cast_tree_bf16(tree)
    assert b["w"].dtype == ml_dtypes.bfloat16 and b["ids"].dtype == np.int32
    x = np.linspace(-0.5, 0.5, 64, dtype=np.float32)
    xq = np.asarray(dynamic_input_fake_quant(x))
    # round-trip lands on the per-tensor int8 grid: ≤ half-step error
    assert np.max(np.abs(xq - x)) <= 0.5 / 127 / 2 + 1e-6


@pytest.mark.tier1
def test_publish_tier_validation_is_typed():
    from distributedmnist_tpu.core.config import ConfigError, QuantConfig
    assert QuantConfig().resolved_publish_tiers() == ()
    assert QuantConfig(
        publish_tiers="int8,bf16").resolved_publish_tiers() == ("int8",
                                                                "bf16")
    with pytest.raises(ConfigError, match="int4.*valid tiers"):
        QuantConfig(publish_tiers="int4").resolved_publish_tiers()
    # fp32 is the artifact, never a sidecar tier
    with pytest.raises(ConfigError, match="fp32"):
        QuantConfig(publish_tiers="fp32").resolved_publish_tiers()


@pytest.mark.tier1
def test_serve_compute_dtype_through_effective_model_config():
    from distributedmnist_tpu.core.config import (ConfigError,
                                                  ExperimentConfig,
                                                  effective_model_config)
    cfg = ExperimentConfig.from_dict({
        "model": {"compute_dtype": "float32"},
        "precision": {"compute_dtype": "bfloat16"},
        "serve": {"compute_dtype": "float16"}})
    # training-side resolution ignores the serve section
    assert effective_model_config(cfg).compute_dtype == "bfloat16"
    # serving-side: serve.compute_dtype wins, then precision, then model
    assert effective_model_config(cfg, serving=True).compute_dtype == \
        "float16"
    cfg2 = cfg.override({"serve.compute_dtype": ""})
    assert effective_model_config(cfg2, serving=True).compute_dtype == \
        "bfloat16"
    with pytest.raises(ConfigError, match="serve.compute_dtype.*valid"):
        effective_model_config(
            cfg.override({"serve.compute_dtype": "float8_e4m3"}),
            serving=True)
    with pytest.raises(ConfigError, match="precision.compute_dtype"):
        effective_model_config(
            cfg2.override({"precision.compute_dtype": "int7"}))


@pytest.mark.tier1
def test_serve_precision_tier_validation_is_typed(tmp_path):
    from distributedmnist_tpu.core.config import ConfigError, ServeConfig
    from distributedmnist_tpu.servesvc.server import ServingReplica
    with pytest.raises(ConfigError, match="precision_tier.*valid tiers"):
        ServingReplica(tmp_path, serve_dir=tmp_path / "r",
                       scfg=ServeConfig(precision_tier="int4"),
                       cfg=base_config())


# ---------------------------------------------------------------------------
# sidecar artifact contract
# ---------------------------------------------------------------------------

@pytest.mark.tier1
def test_quant_sidecar_write_read_digest_and_torn(tmp_path):
    from distributedmnist_tpu.train import checkpoint as ckpt
    tiers = {"int8": {"w": {"q": np.ones((2, 2), np.int8),
                            "scale": np.ones((1, 2), np.float32)}}}
    path = ckpt.write_quant_sidecar(tmp_path, 7, tiers,
                                    {"step": 7, "tiers": ["int8"]})
    assert path.name == "ckpt-00000007.quant.msgpack"
    assert ckpt.quant_sidecar_digest(tmp_path, 7)
    got = ckpt.read_quant_sidecar(tmp_path, 7)
    assert got["meta"]["step"] == 7
    assert got["tiers"]["int8"]["w"]["q"].dtype == np.int8
    # a sidecar never makes a step loadable on its own
    assert ckpt.loadable_steps(tmp_path) == []
    assert ckpt.latest_checkpoint_step(tmp_path) is None
    # torn bytes against the intact digest sidecar: refused, typed
    data = path.read_bytes()
    path.write_bytes(data[: len(data) // 2])
    with pytest.raises(ckpt.CheckpointCorruptError):
        ckpt.read_quant_sidecar(tmp_path, 7)
    with pytest.raises(FileNotFoundError):
        ckpt.read_quant_sidecar(tmp_path, 8)


@pytest.mark.tier1
def test_enospc_sidecar_publish_never_costs_a_checkpoint(tmp_path):
    """ISSUE 20 pin: the quant sidecar is an ADDITIVE artifact — a
    disk that fills up mid-publish (storage-shim ENOSPC across the
    whole retry budget) is logged by the publisher and journaled by
    the injector, the fp32 checkpoint stays durable and loadable, and
    a serving replica configured for the tier falls back to fp32 with
    a journaled ``follow_quant_sidecar_fallback`` — never a crash,
    never a checkpoint failure."""
    from distributedmnist_tpu.quant.ptq import QuantPublisher
    from distributedmnist_tpu.train import checkpoint as ckpt
    from distributedmnist_tpu.train import storage
    state = {"params": {"w": np.full((4, 3), 3.0, np.float32)},
             "step": np.int32(3)}
    ckpt.save_checkpoint(tmp_path, state, 3)
    state_sd, _ = ckpt._checkpoint_state_dict(tmp_path, 3)
    journal = tmp_path / "storage_faults.jsonl"
    storage.arm_faults(0, [{"kind": "enospc_after_bytes", "bytes": 0,
                            "match": ".quant.",
                            "times": ckpt._IO_ATTEMPTS}], journal)
    try:
        cfg = base_config(quant={"publish_tiers": "int8",
                                 "calibration_examples": 0})
        pub = QuantPublisher(None, cfg, None, calib_inputs=None)
        meta = pub.publish(tmp_path, ("full", state_sd), 3)
        assert meta is None and pub.published == 0  # logged, swallowed
        assert not ckpt.quant_sidecar_path(tmp_path, 3).exists()
        # the fp32 artifact the save already landed is untouched
        ckpt.verify_artifact(tmp_path / "ckpt-00000003.msgpack")
        got = ckpt.restore_checkpoint(tmp_path, state)
        assert got is not None and got[2] == 3
        # every firing journaled — invariant 14's license survives
        from distributedmnist_tpu.obsv.report import load_jsonl
        recs = load_jsonl(journal)
        assert [r["action"] for r in recs] == \
            ["disk_enospc"] * ckpt._IO_ATTEMPTS
        assert all(".quant." in r["path"] for r in recs)
    finally:
        storage.clear_faults()
    # the serving half: tier configured, sidecar absent → journaled
    # fp32 fallback, not an error
    from distributedmnist_tpu.core.config import ServeConfig
    from distributedmnist_tpu.servesvc.server import ServingReplica
    r = ServingReplica(tmp_path, serve_dir=tmp_path / "replica",
                       scfg=ServeConfig(precision_tier="int8"),
                       cfg=base_config())
    assert r._read_quant_tier(3, 0.0) is None
    r._serve_log.close()
    from distributedmnist_tpu.obsv.report import load_jsonl as _lj
    swaps = _lj(tmp_path / "replica" / "serve_log.jsonl")
    fb = [x for x in swaps
          if x.get("action") == "follow_quant_sidecar_fallback"]
    assert len(fb) == 1 and fb[0]["reason"] == "sidecar_absent"
    assert fb[0]["step"] == 3 and fb[0]["tier"] == "int8"


# ---------------------------------------------------------------------------
# publish-time pass on a real Trainer (shared run: publish on)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def quant_run(tmp_path_factory, synthetic_datasets):
    """One 20-step run publishing int8+bf16 sidecars at steps 10/20,
    plus a QUANT-LESS same-seed twin — the byte-unchanged-artifact
    comparison baseline."""
    from distributedmnist_tpu.train.loop import Trainer
    with_q = tmp_path_factory.mktemp("with_quant")
    without_q = tmp_path_factory.mktemp("without_quant")
    mk = lambda d, tiers: base_config(  # noqa: E731
        train={"train_dir": str(d), "max_steps": 20,
               "log_every_steps": 10, "save_interval_steps": 10},
        quant={"publish_tiers": tiers, "calibration_examples": 64})
    t = Trainer(mk(with_q, "int8,bf16"), datasets=synthetic_datasets)
    t.run()
    Trainer(mk(without_q, ""), datasets=synthetic_datasets).run()
    return {"with": with_q, "without": without_q,
            "cfg": mk(with_q, "int8,bf16"),
            "published": t._quant_publisher.published}


def test_fp32_artifact_byte_unchanged_by_quant_pass(quant_run):
    """The acceptance pin: sidecars are ADDITIVE. (a) The quant-less
    same-seed twin trains BITWISE-identical params (publishing never
    touches the train state); (b) the with-quant artifacts still pass
    their own digest verification AFTER the sidecars were published
    (publishing never rewrote artifact bytes — the digest sidecar was
    written before the pass ran); (c) re-running the pass over an
    existing dir leaves the artifact's bytes byte-identical."""
    import hashlib

    from distributedmnist_tpu.train import checkpoint as ckpt
    assert quant_run["published"] == 2  # steps 10 and 20
    for step in (10, 20):
        # (a) bitwise params parity across the publish knob
        pw = ckpt.checkpoint_params_digest(quant_run["with"], step)
        po = ckpt.checkpoint_params_digest(quant_run["without"], step)
        assert pw[0] == po[0], f"step {step} params diverged"
        # (b) digest verification still passes post-publish
        ckpt.verify_artifact(quant_run["with"]
                             / f"ckpt-{step:08d}.msgpack")
        assert (quant_run["with"]
                / f"ckpt-{step:08d}.quant.msgpack").exists()
        assert not (quant_run["without"]
                    / f"ckpt-{step:08d}.quant.msgpack").exists()
    # (c) the pass over an EXISTING dir: artifact bytes untouched
    artifact = quant_run["without"] / "ckpt-00000020.msgpack"
    before = hashlib.sha256(artifact.read_bytes()).hexdigest()
    from distributedmnist_tpu.quant.ptq import QuantPublisher
    state_sd, _ = ckpt._checkpoint_state_dict(quant_run["without"], 20)
    pub = QuantPublisher(None, quant_run["cfg"], None,
                         calib_inputs=None)  # no calibration: pure write
    meta = pub.publish(quant_run["without"], ("full", state_sd), 20)
    assert meta is not None and pub.published == 1
    assert hashlib.sha256(artifact.read_bytes()).hexdigest() == before
    # the sidecar's recorded source digest IS the artifact's canonical
    # params digest — a verifiable cross-artifact identity
    meta = ckpt.read_quant_sidecar(quant_run["with"], 20)["meta"]
    got = ckpt.checkpoint_params_digest(quant_run["with"], 20)
    assert meta["source_params_digest"] == got[0]


def test_quant_publish_idempotent_per_source_digest(quant_run):
    """Re-publishing a step whose sidecar already records the SAME
    source params digest is a skip, not a second pass — the final save
    at max_steps re-triggers the cadence step's publish whenever the
    async writer drained between the two enqueues, and the duplicate
    must not pay the quantize work, rewrite bytes, or bump the
    telemetry the tests gate on (the published==2 race this pins)."""
    import hashlib

    from distributedmnist_tpu.quant.ptq import QuantPublisher
    from distributedmnist_tpu.train import checkpoint as ckpt
    sidecar = quant_run["with"] / "ckpt-00000020.quant.msgpack"
    before = hashlib.sha256(sidecar.read_bytes()).hexdigest()
    state_sd, _ = ckpt._checkpoint_state_dict(quant_run["with"], 20)
    pub = QuantPublisher(None, quant_run["cfg"], None, calib_inputs=None)
    meta = pub.publish(quant_run["with"], ("full", state_sd), 20)
    assert meta is not None  # the existing sidecar's meta, returned
    assert pub.published == 0  # skipped — no second pass
    assert hashlib.sha256(sidecar.read_bytes()).hexdigest() == before


def test_cross_knob_restore_ignores_sidecars(quant_run, synthetic_datasets):
    """A dir full of sidecars restores into a quant-less config (and
    the restored step/params match) — the sidecar can never poison the
    training resume path."""
    from distributedmnist_tpu.train.loop import Trainer
    cfg = base_config(train={"train_dir": str(quant_run["with"]),
                             "max_steps": 20, "log_every_steps": 10,
                             "save_interval_steps": 0})
    t = Trainer(cfg, datasets=synthetic_datasets)  # resume=True default
    assert t._start_step == 20
    assert t._quant_publisher is None


def test_quant_sidecar_gc_with_step(quant_run, tmp_path,
                                    synthetic_datasets):
    """Sidecars garbage-collect with their step (keep=1 leaves only
    the newest step's artifact + sidecar families)."""
    from distributedmnist_tpu.train.loop import Trainer
    d = tmp_path / "gc"
    cfg = base_config(
        train={"train_dir": str(d), "max_steps": 20,
               "log_every_steps": 10, "save_interval_steps": 10,
               "keep_checkpoints": 1},
        quant={"publish_tiers": "int8", "calibration_examples": 0})
    Trainer(cfg, datasets=synthetic_datasets).run()
    steps = {int(p.name[5:13]) for p in d.glob("ckpt-*")}
    assert steps == {20}, sorted(p.name for p in d.glob("ckpt-*"))
    assert (d / "ckpt-00000020.quant.msgpack").exists()


def test_parity_refusal_blocks_publish(tmp_path, synthetic_datasets,
                                       monkeypatch):
    """A tier whose calibration agreement misses the epsilon floor is
    NOT published — speed never silently buys wrongness."""
    from distributedmnist_tpu.quant import ptq
    from distributedmnist_tpu.train import checkpoint as ckpt
    from distributedmnist_tpu.train.loop import Trainer

    def bad_calibration(model, template, params_sd, tiers, x, labels=None,
                        predict_cache=None):
        return {"examples": 4,
                **{t: {"agreement": 0.5, "examples": 4} for t in tiers}}

    monkeypatch.setattr(ptq, "calibrate_tiers", bad_calibration)
    d = tmp_path / "refused"
    cfg = base_config(
        train={"train_dir": str(d), "max_steps": 10,
               "log_every_steps": 5, "save_interval_steps": 0},
        quant={"publish_tiers": "int8", "calibration_examples": 8})
    t = Trainer(cfg, datasets=synthetic_datasets)
    t.run()
    assert (d / "ckpt-00000010.msgpack").exists()  # checkpoint fine
    assert t._quant_publisher.published == 0
    assert (10, "int8") in t._quant_publisher.refused
    with pytest.raises(FileNotFoundError):
        ckpt.read_quant_sidecar(d, 10)


def test_tier_predict_parity_on_eval_split(quant_run, synthetic_datasets):
    """The accuracy-parity oracle in unit form: the dequantize-in-graph
    predicts (the exact fns the replica serves) agree with fp32 top-1
    on the full eval split within the published epsilon."""
    import jax

    from distributedmnist_tpu.core.config import effective_model_config
    from distributedmnist_tpu.models.registry import get_model
    from distributedmnist_tpu.quant.ptq import (build_tier_predict,
                                                parity_report)
    from distributedmnist_tpu.train import checkpoint as ckpt
    cfg = quant_run["cfg"]
    model = get_model(effective_model_config(cfg))
    template = model.init(jax.random.PRNGKey(0))
    payload = ckpt.read_quant_sidecar(quant_run["with"], 20)
    state_sd, _ = ckpt._checkpoint_state_dict(quant_run["with"], 20)
    params_sd = state_sd["params"]
    x = synthetic_datasets.test.images
    labels = synthetic_datasets.test.labels
    ref = np.asarray(jax.jit(build_tier_predict(model, template, "fp32"))(
        params_sd, x))
    for tier in ("int8", "bf16"):
        probs = np.asarray(
            jax.jit(build_tier_predict(model, template, tier))(
                payload["tiers"][tier], x))
        rep = parity_report(ref, probs, labels)
        eps = cfg.quant.parity_epsilon
        assert rep["agreement"] >= 1.0 - eps, (tier, rep)
        assert rep["top1_tier"] >= rep["top1_ref"] - eps, (tier, rep)


# ---------------------------------------------------------------------------
# journal + invariant extensions
# ---------------------------------------------------------------------------

@pytest.mark.tier1
def test_summarize_serving_swaps_defaults_legacy_to_fp32():
    from distributedmnist_tpu.obsv.journal import summarize_serving_swaps
    records = [
        {"action": "weight_swap", "step": 10},            # legacy: no tier
        {"action": "weight_swap", "step": 20, "tier": "int8"},
        {"action": "weight_swap", "step": 30, "tier": None},
        {"action": "follow_quant_sidecar_fallback", "step": 20},
        {"action": "respond", "id": 1},
    ]
    got = summarize_serving_swaps(records)
    assert got == {"swaps": 3, "by_tier": {"fp32": 2, "int8": 1},
                   "quant_sidecar_fallbacks": 1}


@pytest.mark.tier1
def test_summarize_chaos_serving_counts_tierless_trials_as_fp32(tmp_path):
    """The chaos aggregate replays PRE-quantization trial records (no
    serve_swaps/by_tier at all) without a KeyError, counting their
    swaps as fp32."""
    from distributedmnist_tpu.obsv.journal import summarize_chaos
    legacy = {"event": "chaos_trial", "trial": 0, "outcome": "completed",
              "serving": {"issued": 10, "dropped": 0, "responses": 10,
                          "rejected": 0, "errors": 0, "reject_rate": 0.0,
                          "model_steps_served": [10]},
              "serve_swaps": {"swaps": 3}}   # pre-tier record: no by_tier
    modern = {"event": "chaos_trial", "trial": 1, "outcome": "completed",
              "serving": {"issued": 5, "dropped": 0, "responses": 5,
                          "rejected": 0, "errors": 0, "reject_rate": 0.0,
                          "model_steps_served": [20],
                          "tiers_served": ["int8"]},
              "serve_swaps": {"swaps": 2, "by_tier": {"int8": 2},
                              "quant_sidecar_fallbacks": 1}}
    p = tmp_path / "chaos_report.jsonl"
    p.write_text(json.dumps(legacy) + "\n" + json.dumps(modern) + "\n")
    got = summarize_chaos(p)["serving"]
    assert got["swaps_by_tier"] == {"fp32": 3, "int8": 2}
    assert got["quant_sidecar_fallbacks"] == 1


@pytest.mark.tier1
def test_serve_digest_invariant_matches_torn_artifact_by_name(tmp_path):
    """A swap that read the INTACT quant sidecar after the fp32
    artifact was torn (or vice versa) is digest verification working —
    only a swap sourced from the torn artifact itself violates."""
    from distributedmnist_tpu.obsv.invariants import check_serving

    def trial(swap_source, torn):
        d = tmp_path / f"t_{swap_source[-20:]}_{torn[-20:]}"
        (d / "worker1").mkdir(parents=True)
        (d / "worker1" / "train_log.jsonl").write_text("")
        (d / "worker1" / "serve_log.jsonl").write_text("".join(
            json.dumps(r) + "\n" for r in [
                {"event": "serve", "action": "weight_swap", "step": 20,
                 "tier": "int8", "digest": "d", "time": 101.0,
                 "source_artifact": swap_source}]))
        journal = [{"event": "fault",
                    "action": "corrupt_latest_checkpoint",
                    "worker": 0, "target": torn, "ts": 100.0}]
        violations, applicable, _, _ = check_serving(
            d, {"serve_workers": [1]}, journal)
        assert applicable
        return {v.invariant for v in violations}

    quant = "ckpt-00000020.quant.msgpack"
    fp32 = "ckpt-00000020.msgpack"
    assert "serve_digest" not in trial(swap_source=quant, torn=fp32)
    assert "serve_digest" in trial(swap_source=quant, torn=quant)
    assert "serve_digest" in trial(swap_source=fp32, torn=fp32)
