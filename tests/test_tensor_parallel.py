"""Tensor parallelism correctness: Megatron-style sharded transformer
(column-parallel qkv/MLP-in, row-parallel wo/MLP-out) must match the
dense single-device model exactly — forward, one-step update, and in
composition with data and sequence parallelism (DP×TP×SP)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from conftest import (LOSS_TOL, assert_update_parity,
                      base_config)
from distributedmnist_tpu.core.config import MeshConfig
from distributedmnist_tpu.core.mesh import make_topology
from distributedmnist_tpu.models import transformer
from distributedmnist_tpu.models.registry import get_model
from distributedmnist_tpu.parallel.api import (build_eval_step,
                                               build_train_step,
                                               init_train_state,
                                               state_partition_specs)
from distributedmnist_tpu.train.lr_schedule import constant

LR = 0.1


def _cfg(n_replicas=1, heads=4, sp_attention="ring"):
    return base_config(
        data={"dataset": "synthetic_lm", "batch_size": 4 * n_replicas},
        model={"name": "transformer", "compute_dtype": "float32",
               "seq_len": 32, "model_dim": 32, "num_heads": heads,
               "num_layers": 2, "vocab_size": 37,
               "attention_impl": "dense", "sp_attention": sp_attention},
        sync={"mode": "sync", "straggler_profile": "none"},
    )


def _tokens(cfg, key=0):
    b, s = cfg.data.batch_size, cfg.model.seq_len
    toks = jax.random.randint(jax.random.PRNGKey(key), (b, s), 0,
                              cfg.model.vocab_size)
    return {"image": toks, "label": toks}


def test_tp_forward_matches_dense():
    cfg = _cfg()
    model = get_model(cfg.model)
    params = model.init(jax.random.PRNGKey(0))
    toks = _tokens(cfg)["image"]
    want = transformer.apply(params, toks, num_heads=4,
                             compute_dtype=jnp.float32)

    topo = make_topology(MeshConfig(num_replicas=1, model_parallelism=4))
    specs = transformer.param_partition_specs(2, topo.model_axis)
    sharded_params = topo.device_put_state(params, specs)
    tp_apply = model.sharded_apply_factory(None, topo.model_axis)

    fn = jax.jit(jax.shard_map(
        lambda p, t: tp_apply(p, t, None),
        mesh=topo.mesh, in_specs=(specs, P()), out_specs=P()))
    got = fn(sharded_params, toks)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def _dense_update(cfg, batch):
    model = get_model(cfg.model)
    params = model.init(jax.random.PRNGKey(cfg.model.init_seed))

    def loss_fn(p):
        logits = transformer.apply(p, batch["image"],
                                   num_heads=cfg.model.num_heads,
                                   compute_dtype=jnp.float32)
        return transformer.loss_fn(logits, batch["label"])

    loss, grads = jax.value_and_grad(loss_fn)(params)
    return loss, jax.tree.map(lambda p, g: p - LR * g, params, grads)


@pytest.mark.parametrize("n_replicas,n_model,n_seq", [
    (1, 4, 1),   # pure TP
    (2, 2, 1),   # DP × TP
    (2, 2, 2),   # DP × TP × SP — the full 3D mesh
])
def test_tp_step_matches_dense_update(n_replicas, n_model, n_seq):
    cfg = _cfg(n_replicas=n_replicas)
    batch = _tokens(cfg)
    want_loss, want_params = _dense_update(cfg, batch)

    topo = make_topology(MeshConfig(num_replicas=n_replicas,
                                    model_parallelism=n_model,
                                    seq_parallelism=n_seq))
    model = get_model(cfg.model)
    specs = state_partition_specs(model, cfg, topo)
    state = topo.device_put_state(init_train_state(model, cfg), specs)
    step_fn = build_train_step(model, cfg, topo, constant(LR))
    gbatch = topo.device_put_batch(batch, seq_sharded=True)
    state, metrics = step_fn(state, gbatch)

    np.testing.assert_allclose(float(metrics["loss"]), float(want_loss),
                               **LOSS_TOL)  # 2e-4 under the check_rep shim
    got_full = jax.device_get(state.params)  # gathers shards
    assert_update_parity(got_full, want_params)


def test_tp_eval_step_matches_dense():
    cfg = _cfg(n_replicas=2)
    topo = make_topology(MeshConfig(num_replicas=2, model_parallelism=2))
    model = get_model(cfg.model)
    specs = state_partition_specs(model, cfg, topo)
    state = topo.device_put_state(init_train_state(model, cfg), specs)
    eval_fn = build_eval_step(model, cfg, topo)

    toks = _tokens(cfg)["image"]
    weight = np.ones(toks.shape[0], np.float32)
    correct, loss_sum, wsum = eval_fn(
        state.params, {"image": toks, "label": toks, "weight": weight})
    # dense reference
    params = model.init(jax.random.PRNGKey(cfg.model.init_seed))
    logits = model.apply(params, toks, train=False)
    c_ref, l_ref, w_ref = model.eval_metrics(logits, toks, jnp.asarray(weight))
    np.testing.assert_allclose(float(correct), float(c_ref), rtol=1e-5)
    np.testing.assert_allclose(float(loss_sum), float(l_ref), rtol=1e-4)
    np.testing.assert_allclose(float(wsum), float(w_ref), rtol=1e-6)


def test_tp_rejects_indivisible_heads():
    cfg = _cfg(heads=2)  # 2 heads cannot split over 4 TP ranks
    topo = make_topology(MeshConfig(num_replicas=1, model_parallelism=4))
    model = get_model(cfg.model)
    step_fn = build_train_step(model, cfg, topo, constant(LR))
    specs = state_partition_specs(model, cfg, topo)
    with pytest.raises(Exception, match="divisible"):
        state = topo.device_put_state(init_train_state(model, cfg), specs)
        step_fn(state, topo.device_put_batch(_tokens(cfg), seq_sharded=True))


def test_trainer_end_to_end_3d_mesh(tmp_train_dir):
    """Full Trainer on a (replica=2, model=2, seq=2) mesh with quorum
    masks on the replica axis, checkpoint save + TP-sharded restore."""
    from distributedmnist_tpu.train.loop import Trainer

    cfg = _cfg(n_replicas=2)
    cfg = cfg.override({
        "mesh.num_replicas": 2, "mesh.model_parallelism": 2,
        "mesh.seq_parallelism": 2,
        "sync.mode": "quorum", "sync.num_replicas_to_aggregate": 1,
        "sync.straggler_profile": "lognormal",
        "train.max_steps": 12, "train.train_dir": tmp_train_dir,
        "train.log_every_steps": 6, "train.save_interval_secs": 0,
        "train.save_interval_steps": 6,
    })
    tr = Trainer(cfg)
    summary = tr.run()
    assert summary["final_step"] == 12
    assert summary["last_metrics"]["num_contributors"] == 1.0
    ev = tr.evaluate("test")
    assert np.isfinite(ev["loss"])

    tr2 = Trainer(cfg.override({"train.resume": True, "train.max_steps": 14}))
    assert tr2._start_step == 12
    assert tr2.run()["final_step"] == 14
