"""Cluster execution engine: CommandExecutor + backends, exercised with
REAL subprocesses (no mocks of subprocess) — the executed-process
evidence the argv-level pod tests never had (VERDICT gap #1; ≙ the
reference orchestrator actually driving clusters,
tools/tf_ec2.py:237-271, :536-569)."""

import json
import shlex
import time
from pathlib import Path

import pytest

from distributedmnist_tpu.launch.cluster import (LocalClusterConfig,
                                                 LocalProcessCluster,
                                                 make_backend,
                                                 parse_poll_output)
from distributedmnist_tpu.launch.exec import (CommandExecutor, ExecError,
                                              FaultPlan, RetryPolicy)
from distributedmnist_tpu.obsv.journal import load_journal, summarize_journal

pytestmark = pytest.mark.tier1


# ---------------------------------------------------------------------------
# CommandExecutor
# ---------------------------------------------------------------------------

def test_run_real_command_journals_result(tmp_path):
    journal = tmp_path / "journal.jsonl"
    with CommandExecutor(journal=journal) as ex:
        res = ex.run(["sh", "-c", "echo out; echo err >&2"], verb="probe")
    assert res.ok and res.returncode == 0 and res.attempts == 1
    assert res.stdout == "out\n" and res.stderr == "err\n"
    (rec,) = load_journal(journal)
    assert rec["verb"] == "probe" and rec["rc"] == 0
    assert rec["stdout_tail"] == "out\n" and rec["stderr_tail"] == "err\n"
    assert rec["duration_ms"] > 0 and rec["attempt"] == 1
    assert rec["will_retry"] is False


def test_nonzero_rc_raises_with_check_and_not_without(tmp_path):
    ex = CommandExecutor(retry=RetryPolicy(max_attempts=1))
    res = ex.run(["sh", "-c", "echo boom >&2; exit 3"], check=False)
    assert not res.ok and res.returncode == 3
    with pytest.raises(ExecError, match=r"rc=3"):
        ex.run(["sh", "-c", "exit 3"])


def test_timeout_is_a_failure(tmp_path):
    ex = CommandExecutor(retry=RetryPolicy(max_attempts=1), timeout_s=0.2)
    t0 = time.monotonic()
    res = ex.run(["sh", "-c", "sleep 30"], check=False)
    assert time.monotonic() - t0 < 10  # the hung command did not hang us
    assert res.timed_out and res.returncode is None and not res.ok
    with pytest.raises(ExecError, match="timed out"):
        ex.run(["sh", "-c", "sleep 30"])


def test_missing_binary_is_permanent_no_retries(tmp_path):
    journal = tmp_path / "journal.jsonl"
    ex = CommandExecutor(journal=journal, retry=RetryPolicy(max_attempts=5))
    with pytest.raises(ExecError, match="not found"):
        ex.run(["dmt-no-such-binary-for-test"])
    recs = load_journal(journal)
    assert len(recs) == 1 and recs[0]["error"] == "binary not found"


def test_retry_backoff_recovers_transient_failure(tmp_path):
    """(a) of the fault-injection acceptance: first n attempts of a verb
    fail (synthesized by the plan), the retry/backoff budget absorbs
    them, and the REAL command then runs and succeeds."""
    journal = tmp_path / "journal.jsonl"
    delays: list[float] = []
    ex = CommandExecutor(
        journal=journal,
        retry=RetryPolicy(max_attempts=3, backoff_s=0.05, multiplier=2.0,
                          jitter_frac=0.25, seed=0),
        fault_plan=FaultPlan(fail_first={"flaky": 2}),
        sleep=delays.append)
    res = ex.run(["echo", "recovered"], verb="flaky")
    assert res.ok and res.attempts == 3 and res.stdout == "recovered\n"
    # exponential backoff with ±25% jitter: two retry sleeps
    assert len(delays) == 2
    assert 0.05 * 0.75 <= delays[0] <= 0.05 * 1.25
    assert 0.10 * 0.75 <= delays[1] <= 0.10 * 1.25
    recs = load_journal(journal)
    assert [r["attempt"] for r in recs] == [1, 2, 3]
    assert [r["will_retry"] for r in recs] == [True, True, False]
    assert recs[0]["injected"] and recs[1]["injected"] and not recs[2]["injected"]
    s = summarize_journal(journal)
    assert s["commands"] == 1 and s["attempts"] == 3
    assert s["retries"] == 2 and s["failures"] == 0 and s["injected"] == 2


def test_retry_budget_exhausted_raises(tmp_path):
    journal = tmp_path / "journal.jsonl"
    ex = CommandExecutor(
        journal=journal,
        retry=RetryPolicy(max_attempts=2, backoff_s=0.0, jitter_frac=0.0),
        fault_plan=FaultPlan(fail_first={"flaky": 99}))
    with pytest.raises(ExecError, match=r"after 2 attempt"):
        ex.run(["echo", "never"], verb="flaky")
    s = summarize_journal(journal)
    assert s["failures"] == 1 and s["retries"] == 1


def test_fault_delay_applies_to_command_class():
    slept: list[float] = []
    ex = CommandExecutor(fault_plan=FaultPlan(delay_ms={"probe": 40.0}),
                         sleep=slept.append)
    ex.run(["true"], verb="probe")
    ex.run(["true"], verb="other")
    assert slept == [0.04]  # only the targeted class is delayed


def test_dry_run_records_and_journals_without_executing(tmp_path):
    journal = tmp_path / "journal.jsonl"
    ex = CommandExecutor(journal=journal, dry_run=True)
    assert ex.run(["definitely-not-a-binary", "--flag"]) is None
    assert ex.recorded == [["definitely-not-a-binary", "--flag"]]
    recs = json.loads(journal.read_text().splitlines()[0])
    assert recs["dry_run"] is True
    assert summarize_journal(journal)["dry_run"] == 1


def test_fault_plan_file_roundtrip(tmp_path):
    p = tmp_path / "plan.json"
    p.write_text(json.dumps({"fail_first": {"create": 1},
                             "delay_ms": {"poll": 5},
                             "kill_worker_at_step": {"1": 7}}))
    plan = FaultPlan.from_file(p)
    assert plan.should_fail("create", 1) and not plan.should_fail("create", 2)
    assert plan.command_delay_s("poll") == 0.005
    assert plan.kill_worker_at_step == {1: 7}  # JSON str keys → int
    p.write_text(json.dumps({"kill_wroker": {}}))
    with pytest.raises(ExecError, match="kill_wroker"):
        FaultPlan.from_file(p)


def test_parse_poll_output_torn_and_empty():
    assert parse_poll_output(None) == {"step": -1, "record": None}
    assert parse_poll_output("") == {"step": -1, "record": None}
    assert parse_poll_output('{"step": 8, "loss"') == {"step": -1,
                                                      "record": None}
    got = parse_poll_output('{"step": 12, "loss": 0.5}\n')
    assert got["step"] == 12 and got["record"]["loss"] == 0.5


def test_parse_poll_output_scans_back_past_torn_tail():
    """A torn final line (the writer mid-append) must not make live
    progress look stalled for a whole poll tick: the parser scans
    backwards to the last INTACT record in the tail window."""
    got = parse_poll_output('{"step": 11, "loss": 0.7}\n'
                            '{"step": 12, "loss": 0.5}\n'
                            '{"step": 13, "lo')
    assert got["step"] == 12 and got["record"]["loss"] == 0.5
    # nothing intact in the window at all → still -1
    assert parse_poll_output('garbage\n{"step": 9,')["step"] == -1


# ---------------------------------------------------------------------------
# LocalProcessCluster verbs (each one a real subprocess)
# ---------------------------------------------------------------------------

def _local(tmp_path, **cfg_kw) -> LocalProcessCluster:
    cfg_kw.setdefault("num_workers", 2)
    cfg = LocalClusterConfig(name="t", workdir=str(tmp_path / "cl"), **cfg_kw)
    return LocalProcessCluster(cfg)


def test_create_makes_worker_dirs_and_state(tmp_path):
    c = _local(tmp_path)
    c.create()
    assert c.cfg.worker_dir(0).is_dir() and c.cfg.worker_dir(1).is_dir()
    state = json.loads(c.state_path.read_text())
    assert state["phase"] == "created"
    assert [w["worker"] for w in state["workers"]] == [0, 1]
    got = c.status()
    assert got["state"] == "CREATED" and got["idle"] is True
    assert all(not w["alive"] for w in got["workers"])


def test_exec_all_runs_in_each_worker_dir(tmp_path):
    c = _local(tmp_path)
    c.create()
    c.exec_all("echo payload-$DMT_WORKER_INDEX > touched.txt")
    for k in range(2):
        assert (c.cfg.worker_dir(k) / "touched.txt").read_text().strip() \
            == f"payload-{k}"
    c.exec_all("rm touched.txt", worker="1")
    assert (c.cfg.worker_dir(0) / "touched.txt").exists()
    assert not (c.cfg.worker_dir(1) / "touched.txt").exists()


def test_poll_reads_worker0_structured_log(tmp_path):
    c = _local(tmp_path)
    c.create()
    assert c.poll() == {"step": -1, "record": None}  # log not there yet
    (c.cfg.worker_dir(0) / "train_log.jsonl").write_text(
        json.dumps({"step": 3}) + "\n" + json.dumps({"step": 7}) + "\n")
    assert c.poll()["step"] == 7


def test_download_copies_worker_dir(tmp_path):
    c = _local(tmp_path)
    c.create()
    (c.cfg.worker_dir(0) / "train_log.jsonl").write_text('{"step": 1}\n')
    dest = tmp_path / "dl"
    c.download(dest)
    assert (dest / "worker0" / "train_log.jsonl").exists()


def test_delete_marks_state_and_journal_parses(tmp_path):
    c = _local(tmp_path)
    c.create()
    c.delete()
    assert c.status()["state"] == "DELETED"
    s = summarize_journal(c.exec.journal_path)
    assert s["failures"] == 0 and s["commands"] >= 1
    assert "create" in s["by_verb"]


def test_make_backend_pluggability(tmp_path):
    from distributedmnist_tpu.launch.cluster import (ClusterError,
                                                     GcloudTpuBackend)
    ex = CommandExecutor(dry_run=True)
    assert isinstance(make_backend("local", None, ex), LocalProcessCluster)
    assert isinstance(make_backend("gcloud", None, ex), GcloudTpuBackend)
    with pytest.raises(ClusterError, match="unknown backend"):
        make_backend("k8s", None, ex)


def test_cluster_config_file_roundtrip_and_unknown_key(tmp_path):
    from distributedmnist_tpu.launch.cluster import ClusterError
    p = tmp_path / "cluster.json"
    p.write_text(json.dumps({"name": "x", "num_workers": 3}))
    cfg = LocalClusterConfig.from_file(p)
    assert (cfg.name, cfg.num_workers) == ("x", 3)
    p.write_text(json.dumps({"num_wrokers": 3}))
    with pytest.raises(ClusterError, match="num_wrokers"):
        LocalClusterConfig.from_file(p)


def test_repo_cluster_configs_parse():
    """The committed cluster/fault JSONs must load via the same safe
    parsers the CLI uses."""
    root = Path(__file__).resolve().parents[1] / "configs" / "cluster"
    cfg = LocalClusterConfig.from_file(root / "local_2w.json")
    assert cfg.num_workers == 2
    plan = FaultPlan.from_file(root / "fault_kill_worker1_at_step10.json")
    assert plan.kill_worker_at_step == {1: 10}


def test_cluster_cli_dry_run_prints_commands(tmp_path, capsys, monkeypatch):
    from distributedmnist_tpu.launch.cluster import main
    monkeypatch.chdir(tmp_path)
    cfgp = tmp_path / "c.json"
    cfgp.write_text(json.dumps({"workdir": str(tmp_path / "w")}))
    main(["create", "--backend", "local", "--config", str(cfgp), "--dry-run"])
    cmds = json.loads(capsys.readouterr().out)
    assert any(c.startswith("sh -c") and "mkdir -p" in c for c in cmds)


def test_launch_cli_delegates_cluster(tmp_path, capsys):
    from distributedmnist_tpu.launch.__main__ import main
    cfgp = tmp_path / "c.json"
    cfgp.write_text(json.dumps({"workdir": str(tmp_path / "w")}))
    main(["cluster", "create", "--backend", "local",
          "--config", str(cfgp), "--dry-run"])
    assert "mkdir" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# full lifecycle with the REAL `launch train` payload (slow: boots jax
# in each worker) — the executed-process closure of VERDICT gap #1
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_lifecycle_smoke_real_train(tmp_path):
    # no PYTHONPATH in env: the backend itself must make this package
    # importable from the workers' logdir cwds (the README CLI recipe
    # runs exactly this way, with nothing pip-installed)
    cfg = LocalClusterConfig(
        name="smoke", num_workers=2, workdir=str(tmp_path / "cl"),
        train_command=(
            "python -m distributedmnist_tpu.launch train "
            "train.train_dir=. data.dataset=synthetic data.batch_size=16 "
            "data.synthetic_train_size=64 data.synthetic_test_size=32 "
            "model.compute_dtype=float32 train.max_steps=8 "
            "train.log_every_steps=1 train.save_interval_steps=0"))
    c = LocalProcessCluster(cfg)
    from distributedmnist_tpu.launch.cluster import run_until_step
    c.create()
    got = run_until_step(c, target=4, poll_secs=1.0, timeout_secs=600.0)
    assert got["step"] >= 4 and got["record"] is not None
    dest = tmp_path / "dl"
    c.download(dest)
    assert (dest / "worker0" / "train_log.jsonl").exists()
    c.delete()
    assert c.status()["state"] == "DELETED" and c.status()["idle"]
    recs = load_journal(c.exec.journal_path)
    verbs = {r["verb"] for r in recs}
    assert {"create", "poll", "download"} <= verbs
