"""Config system tests (≙ SURVEY §5.6 — safe literals replace eval'd Cfg)."""

import json

import pytest

from distributedmnist_tpu.core.config import (ConfigError, ExperimentConfig,
                                              parse_cli_overrides)

pytestmark = pytest.mark.tier1


def test_defaults_roundtrip():
    cfg = ExperimentConfig()
    d = cfg.to_dict()
    assert ExperimentConfig.from_dict(d) == cfg


def test_from_file_json(tmp_path):
    p = tmp_path / "cfg.json"
    p.write_text(json.dumps({"name": "exp1", "sync": {"mode": "quorum",
                                                     "num_replicas_to_aggregate": 4}}))
    cfg = ExperimentConfig.from_file(p)
    assert cfg.name == "exp1"
    assert cfg.sync.mode == "quorum"
    assert cfg.sync.num_replicas_to_aggregate == 4


def test_from_file_python_literal(tmp_path):
    p = tmp_path / "cfg.py"
    p.write_text("{'name': 'lit', 'data': {'batch_size': 512}}")
    cfg = ExperimentConfig.from_file(p)
    assert cfg.data.batch_size == 512


def test_from_file_rejects_code(tmp_path):
    p = tmp_path / "cfg.py"
    p.write_text("__import__('os').system('true') or {}")
    with pytest.raises(ConfigError):
        ExperimentConfig.from_file(p)


def test_unknown_key_rejected():
    with pytest.raises(ConfigError):
        ExperimentConfig.from_dict({"sync": {"no_such_knob": 1}})


def test_dotted_overrides():
    cfg = ExperimentConfig().override({"sync.mode": "timeout",
                                       "train.max_steps": 42,
                                       "optim.initial_learning_rate": 8e-4})
    assert cfg.sync.mode == "timeout"
    assert cfg.train.max_steps == 42
    assert cfg.optim.initial_learning_rate == 8e-4


def test_cli_override_parsing():
    out = parse_cli_overrides(["sync.mode=quorum", "train.max_steps=7",
                               "data.shard_mode=independent"])
    assert out == {"sync.mode": "quorum", "train.max_steps": 7,
                   "data.shard_mode": "independent"}
    with pytest.raises(ConfigError):
        parse_cli_overrides(["nonsense"])


def test_save_load(tmp_path):
    cfg = ExperimentConfig().override({"name": "saved"})
    p = tmp_path / "out.json"
    cfg.save(p)
    assert ExperimentConfig.from_file(p) == cfg
