"""Config system tests (≙ SURVEY §5.6 — safe literals replace eval'd Cfg)."""

import json

import pytest

from distributedmnist_tpu.core.config import (ConfigError, ExperimentConfig,
                                              parse_cli_overrides)

pytestmark = pytest.mark.tier1


def test_defaults_roundtrip():
    cfg = ExperimentConfig()
    d = cfg.to_dict()
    assert ExperimentConfig.from_dict(d) == cfg


def test_from_file_json(tmp_path):
    p = tmp_path / "cfg.json"
    p.write_text(json.dumps({"name": "exp1", "sync": {"mode": "quorum",
                                                     "num_replicas_to_aggregate": 4}}))
    cfg = ExperimentConfig.from_file(p)
    assert cfg.name == "exp1"
    assert cfg.sync.mode == "quorum"
    assert cfg.sync.num_replicas_to_aggregate == 4


def test_from_file_python_literal(tmp_path):
    p = tmp_path / "cfg.py"
    p.write_text("{'name': 'lit', 'data': {'batch_size': 512}}")
    cfg = ExperimentConfig.from_file(p)
    assert cfg.data.batch_size == 512


def test_from_file_rejects_code(tmp_path):
    p = tmp_path / "cfg.py"
    p.write_text("__import__('os').system('true') or {}")
    with pytest.raises(ConfigError):
        ExperimentConfig.from_file(p)


def test_unknown_key_rejected():
    with pytest.raises(ConfigError):
        ExperimentConfig.from_dict({"sync": {"no_such_knob": 1}})


def test_dotted_overrides():
    cfg = ExperimentConfig().override({"sync.mode": "timeout",
                                       "train.max_steps": 42,
                                       "optim.initial_learning_rate": 8e-4})
    assert cfg.sync.mode == "timeout"
    assert cfg.train.max_steps == 42
    assert cfg.optim.initial_learning_rate == 8e-4


def test_cli_override_parsing():
    out = parse_cli_overrides(["sync.mode=quorum", "train.max_steps=7",
                               "data.shard_mode=independent"])
    assert out == {"sync.mode": "quorum", "train.max_steps": 7,
                   "data.shard_mode": "independent"}
    with pytest.raises(ConfigError):
        parse_cli_overrides(["nonsense"])


def test_save_load(tmp_path):
    cfg = ExperimentConfig().override({"name": "saved"})
    p = tmp_path / "out.json"
    cfg.save(p)
    assert ExperimentConfig.from_file(p) == cfg


def test_parallel_knob_validation_is_typed_and_build_time():
    """ISSUE 12 satellite: comm_buckets < 1 and resident_sharded
    without shard_weight_update are typed ConfigErrors naming the knob
    / dependency, raised at BUILD time (zero1_plan_for — every
    state/step builder routes through it), never a shape error
    mid-step."""
    from distributedmnist_tpu.core.config import ParallelConfig

    with pytest.raises(ConfigError, match="comm_buckets"):
        ParallelConfig(comm_buckets=0).validate()
    with pytest.raises(ConfigError, match="shard_weight_update"):
        ParallelConfig(resident_sharded=True).validate()
    # the valid combos pass
    ParallelConfig().validate()
    ParallelConfig(shard_weight_update=True, comm_buckets=4,
                   resident_sharded=True).validate()

    # and the build path actually hits it: zero1_plan_for validates
    # FIRST, so even a config whose plan would be None (no sharding)
    # refuses the orphaned resident_sharded knob
    from distributedmnist_tpu.core.mesh import make_topology
    from distributedmnist_tpu.models.registry import get_model
    from distributedmnist_tpu.parallel.api import zero1_plan_for
    cfg = ExperimentConfig.from_dict(
        {"model": {"compute_dtype": "float32"},
         "parallel": {"resident_sharded": True}})
    with pytest.raises(ConfigError, match="shard_weight_update"):
        zero1_plan_for(get_model(cfg.model), cfg, make_topology())
