"""Fault-injected lifecycle tests: REAL worker processes, injected
failures, and the recovery behaviour the execution layer promises
(≙ the dead/slow-worker regime of arXiv:1604.00981 applied to the
control plane; VERDICT gap #1's executed-process evidence).

The worker payload is a cheap shell loop emitting ``train_log.jsonl``
step records — the same observable surface as ``launch train``,
without booting jax per worker — so every test here runs real
subprocesses AND stays in the tier-1 budget. The jax-booting
realization of the same lifecycle is the ``slow``-marked smoke in
``test_cluster_exec.py``.

Acceptance coverage:
  (a) transient command failure recovered by retry/backoff within the
      attempt budget,
  (b) a mid-run worker kill is detected and surfaced by ``status()``,
  (c) a ``run_until_step`` poll timeout still tears the cluster down;
and every run leaves a parseable JSONL command journal.
"""

import json
import time

import pytest

from distributedmnist_tpu.launch.cluster import (ClusterError,
                                                 LocalClusterConfig,
                                                 LocalProcessCluster,
                                                 run_until_step,
                                                 wait_until_step)
from distributedmnist_tpu.launch.exec import (CommandExecutor, FaultPlan,
                                              RetryPolicy)
from distributedmnist_tpu.obsv.journal import load_journal, summarize_journal

pytestmark = pytest.mark.tier1

# ~50 ms per step, 400 steps: outlives every test's observation window
# without leaving long-lived orphans if a teardown assert fails
_STEP_LOOP = ('i=0; while [ $i -lt 400 ]; do i=$((i+1)); '
              'echo "{\\"step\\": $i, \\"loss\\": 1.0}" >> train_log.jsonl; '
              'sleep 0.05; done')


def _cluster(tmp_path, train_command=_STEP_LOOP, num_workers=2,
             fault_plan=None, retry=None) -> LocalProcessCluster:
    cfg = LocalClusterConfig(name="fi", workdir=str(tmp_path / "cl"),
                             num_workers=num_workers,
                             train_command=train_command)
    ex = CommandExecutor(journal=cfg.root / "command_journal.jsonl",
                         retry=retry or RetryPolicy(max_attempts=1),
                         fault_plan=fault_plan)
    return LocalProcessCluster(cfg, ex)


def _alive(cluster) -> dict[int, bool]:
    return {w["worker"]: w["alive"] for w in cluster.status()["workers"]}


def test_transient_create_failure_recovered_by_retry(tmp_path):
    """(a) The fault plan fails the first 2 attempts of ``create``; the
    retry budget absorbs them and the REAL mkdir then runs."""
    c = _cluster(tmp_path,
                 fault_plan=FaultPlan(fail_first={"create": 2}),
                 retry=RetryPolicy(max_attempts=3, backoff_s=0.01,
                                   jitter_frac=0.0))
    c.create()
    assert c.cfg.worker_dir(0).is_dir() and c.cfg.worker_dir(1).is_dir()
    recs = [r for r in load_journal(c.exec.journal_path)
            if r["verb"] == "create"]
    assert [r["attempt"] for r in recs] == [1, 2, 3]
    assert [r["injected"] for r in recs] == [True, True, False]
    s = summarize_journal(c.exec.journal_path)
    assert s["retries"] == 2 and s["failures"] == 0
    c.delete()


def test_midrun_worker_kill_surfaces_in_status(tmp_path):
    """(b) The plan kills worker 1 once a poll observes step >= 3; the
    next status() probe (a real ``kill -0`` per pid) reports it dead
    while worker 0 keeps running — the loss the aggregation layer's
    backup-worker policies exist for, observed at the execution layer."""
    c = _cluster(tmp_path,
                 fault_plan=FaultPlan(kill_worker_at_step={1: 3}))
    c.create()
    c.run_train()
    try:
        got = wait_until_step(c, target=6, poll_secs=0.1, timeout_secs=60.0)
        assert got["step"] >= 6
        time.sleep(0.2)  # let the killed pid be reaped
        alive = _alive(c)
        assert alive[0] is True and alive[1] is False
        assert c.status()["idle"] is False  # worker 0 still training
        # load_journal filters event=command; read raw for fault events
        raw = [json.loads(line) for line in
               c.exec.journal_path.read_text().splitlines()]
        faults = [r for r in raw if r.get("event") == "fault"]
        assert faults and faults[0]["action"] == "kill_worker"
        assert faults[0]["worker"] == 1 and faults[0]["at_step"] >= 3
    finally:
        c.kill_all()
    time.sleep(0.2)
    assert not any(_alive(c).values())
    c.delete()
    assert summarize_journal(c.exec.journal_path)["failures"] == 0


def test_run_until_step_poll_timeout_tears_cluster_down(tmp_path):
    """(c) A run that never reaches the target step times out — and the
    finally-path still kills every worker: a hung run must not leave
    processes (on a cloud backend: billing) behind."""
    stall = ('echo "{\\"step\\": 1, \\"loss\\": 1.0}" >> train_log.jsonl; '
             'sleep 60')
    c = _cluster(tmp_path, train_command=stall)
    c.create()
    with pytest.raises(ClusterError, match=r"step 100.*last seen: 1"):
        run_until_step(c, target=100, poll_secs=0.1, timeout_secs=1.0)
    time.sleep(0.2)
    assert not any(_alive(c).values())  # torn down on the error path
    assert c.status()["idle"] is True
    # the journal alone reconstructs the episode: spawns, polls, kills
    raw = [json.loads(line) for line in
           c.exec.journal_path.read_text().splitlines()]
    assert sum(r.get("event") == "spawn" for r in raw) == 2
    verbs = {r["verb"] for r in raw if r.get("event") == "command"}
    assert {"create", "poll", "kill"} <= verbs
    assert summarize_journal(c.exec.journal_path)["attempts"] >= 4
    c.delete()


def test_dead_cluster_fails_fast_not_at_poll_timeout(tmp_path):
    """Workers that crash on boot (here: exit immediately) must fail
    the wait NOW — without this, a dead cluster spins at step -1 until
    the poll timeout (24 h by default on the CLI)."""
    c = _cluster(tmp_path, train_command="true")
    c.create()
    c.run_train()
    time.sleep(0.3)  # let both workers exit
    t0 = time.monotonic()
    with pytest.raises(ClusterError, match="no live workers"):
        wait_until_step(c, target=5, poll_secs=0.1, timeout_secs=300.0)
    assert time.monotonic() - t0 < 30  # far from the 300 s timeout
    c.delete()


def test_command_class_delay_straggles_the_poll(tmp_path):
    """The straggler knob: delaying the ``poll`` class stretches the
    observed poll latency without failing anything — the slow-worker
    half of the arXiv:1604.00981 regime, on the control plane."""
    c = _cluster(tmp_path, fault_plan=FaultPlan(delay_ms={"poll": 120.0}))
    c.create()
    (c.cfg.worker_dir(0) / "train_log.jsonl").write_text('{"step": 9}\n')
    t0 = time.monotonic()
    got = c.poll()
    dt = time.monotonic() - t0
    assert got["step"] == 9 and dt >= 0.12
    recs = [r for r in load_journal(c.exec.journal_path)
            if r["verb"] == "poll"]
    assert recs[0]["injected_delay_ms"] == 120.0
    c.delete()
