"""Checkpoint round-trip + resume tests (≙ SURVEY §5.4)."""

import numpy as np
import jax

from conftest import base_config
from distributedmnist_tpu.train import checkpoint as ckpt


def _state_and_model(mode="sync"):
    from distributedmnist_tpu.core.config import ExperimentConfig
    from distributedmnist_tpu.models.registry import get_model
    from distributedmnist_tpu.parallel.api import init_train_state
    cfg = base_config(sync={"mode": mode})
    model = get_model(cfg.model)
    return init_train_state(model, cfg), model, cfg


def test_roundtrip_identity(tmp_path):
    state, model, _ = _state_and_model()
    ckpt.save_checkpoint(tmp_path, state, 7, extra={"note": "hi"})
    template, _, _ = _state_and_model()
    restored, extra, step = ckpt.restore_checkpoint(tmp_path, template)
    assert step == 7
    assert extra == {"note": "hi"}
    for a, b in zip(jax.tree.leaves(state.params), jax.tree.leaves(restored.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_roundtrip_interval_state(tmp_path):
    """Interval mode carries a window accumulator — must survive."""
    state, _, _ = _state_and_model(mode="interval")
    assert state.window_acc is not None
    ckpt.save_checkpoint(tmp_path, state, 3)
    template, _, _ = _state_and_model(mode="interval")
    restored, _, step = ckpt.restore_checkpoint(tmp_path, template)
    assert step == 3
    assert restored.window_acc is not None


def test_latest_pointer_and_gc(tmp_path):
    state, _, _ = _state_and_model()
    for s in (1, 2, 3, 4, 5, 6, 7):
        ckpt.save_checkpoint(tmp_path, state, s, keep=3)
    assert ckpt.latest_checkpoint_step(tmp_path) == 7
    kept = sorted(p.name for p in tmp_path.glob("ckpt-*.msgpack"))
    assert len(kept) == 3
    assert kept[-1] == "ckpt-00000007.msgpack"


def test_missing_dir_returns_none(tmp_path):
    state, _, _ = _state_and_model()
    assert ckpt.restore_checkpoint(tmp_path / "nope", state) is None


def test_torn_pointer_falls_back_to_scan(tmp_path):
    state, _, _ = _state_and_model()
    ckpt.save_checkpoint(tmp_path, state, 5)
    (tmp_path / "checkpoint.json").write_text("{not json")
    assert ckpt.latest_checkpoint_step(tmp_path) == 5


def test_trainer_resume_continues(tmp_train_dir, synthetic_datasets):
    from distributedmnist_tpu.train.loop import Trainer
    cfg = base_config(train={"train_dir": tmp_train_dir, "max_steps": 10,
                             "log_every_steps": 5, "save_interval_steps": 5})
    t1 = Trainer(cfg, datasets=synthetic_datasets)
    t1.run()
    t2 = Trainer(cfg, datasets=synthetic_datasets)
    assert t2._start_step == 10
    s = t2.run(max_steps=14)
    assert s["final_step"] == 14
    # data iterator resumed, not restarted
    assert t2.train_iter.state()["pos"] > 0 or t2.train_iter.state()["epoch"] > 0


def test_sharded_checkpoint_reassembles_global_arrays(tmp_path):
    """Per-host sharded format (SURVEY §2.3 'per-host array
    serialization'): two hand-built shard files — each holding the
    slabs one process would address — plus a manifest must restore to
    the exact full global arrays on a reader of ANY process count."""
    import json
    from flax import serialization

    d = tmp_path / "sharded"
    d.mkdir()
    full_a = np.arange(8 * 3, dtype=np.float32).reshape(8, 3)
    full_b = np.float32(7.0)

    # process 0: rows 0:4 of a, plus the locally-complete scalar b;
    # process 1: rows 4:8 of a
    shard0 = {"leaves": {
        "params/a": {"indices": [[[0, 4], [0, 3]]], "datas": [full_a[0:4]]},
        "step": np.int32(5),
        "params/b": full_b,
    }}
    shard1 = {"leaves": {
        "params/a": {"indices": [[[4, 8], [0, 3]]], "datas": [full_a[4:8]]},
    }}
    (d / "ckpt-00000005.shard000-of-002.msgpack").write_bytes(
        serialization.msgpack_serialize(shard0))
    (d / "ckpt-00000005.shard001-of-002.msgpack").write_bytes(
        serialization.msgpack_serialize(shard1))
    manifest = {"step": 5, "num_shards": 2,
                "leaves": {"params/a": {"shape": [8, 3], "dtype": "float32"},
                           "params/b": {"full": True},
                           "step": {"full": True}},
                "extra": {"config": {"note": "sharded"}}}
    (d / "ckpt-00000005.manifest.json").write_text(json.dumps(manifest))

    template = {"params": {"a": np.zeros((8, 3), np.float32),
                           "b": np.zeros((), np.float32)},
                "step": np.zeros((), np.int32),
                "none_field": None}
    restored = ckpt.restore_checkpoint(d, template)
    assert restored is not None
    state, extra, step = restored
    assert step == 5
    assert extra == {"config": {"note": "sharded"}}
    np.testing.assert_array_equal(state["params"]["a"], full_a)
    np.testing.assert_array_equal(state["params"]["b"], full_b)
    assert int(state["step"]) == 5
    assert state["none_field"] is None
    # latest_checkpoint_step's scan path must parse shard/manifest names
    assert ckpt.latest_checkpoint_step(d) == 5
    # read_checkpoint_extra without a template
    assert ckpt.read_checkpoint_extra(d) == ({"config": {"note": "sharded"}}, 5)


def test_sharded_snapshot_roundtrip_single_process(tmp_path):
    """snapshot_for_save → save_checkpoint → restore on a live
    TP-sharded state (single process: leaves are fully addressable, so
    the snapshot itself reports 'full'; the per-leaf slab extraction is
    exercised by forcing the sharded writer with a fake snapshot)."""
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from distributedmnist_tpu.core.mesh import make_topology
    from distributedmnist_tpu.core.config import MeshConfig

    topo = make_topology(MeshConfig(num_replicas=4, model_parallelism=2))
    w = jax.device_put(jnp.arange(6 * 4, dtype=jnp.float32).reshape(6, 4),
                       NamedSharding(topo.mesh, P(None, "model")))
    state = {"w": w, "step": jax.device_put(jnp.int32(3), topo.replicated)}
    # single-process: everything is addressable → classic single file
    kind = ckpt.snapshot_for_save(state)[0]
    assert kind == "full"
    ckpt.save_checkpoint(tmp_path, state, 3)
    restored, _, step = ckpt.restore_checkpoint(
        tmp_path, jax.tree.map(lambda x: np.zeros(x.shape, x.dtype), state))
    assert step == 3
    np.testing.assert_array_equal(restored["w"],
                                  np.arange(24, dtype=np.float32).reshape(6, 4))
