"""Checkpoint round-trip + resume tests (≙ SURVEY §5.4)."""

import numpy as np
import jax

from conftest import base_config
from distributedmnist_tpu.train import checkpoint as ckpt


def _state_and_model(mode="sync"):
    from distributedmnist_tpu.core.config import ExperimentConfig
    from distributedmnist_tpu.models.registry import get_model
    from distributedmnist_tpu.parallel.api import init_train_state
    cfg = base_config(sync={"mode": mode})
    model = get_model(cfg.model)
    return init_train_state(model, cfg), model, cfg


def test_roundtrip_identity(tmp_path):
    state, model, _ = _state_and_model()
    ckpt.save_checkpoint(tmp_path, state, 7, extra={"note": "hi"})
    template, _, _ = _state_and_model()
    restored, extra, step = ckpt.restore_checkpoint(tmp_path, template)
    assert step == 7
    assert extra == {"note": "hi"}
    for a, b in zip(jax.tree.leaves(state.params), jax.tree.leaves(restored.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_roundtrip_interval_state(tmp_path):
    """Interval mode carries a window accumulator — must survive."""
    state, _, _ = _state_and_model(mode="interval")
    assert state.window_acc is not None
    ckpt.save_checkpoint(tmp_path, state, 3)
    template, _, _ = _state_and_model(mode="interval")
    restored, _, step = ckpt.restore_checkpoint(tmp_path, template)
    assert step == 3
    assert restored.window_acc is not None


def test_latest_pointer_and_gc(tmp_path):
    state, _, _ = _state_and_model()
    for s in (1, 2, 3, 4, 5, 6, 7):
        ckpt.save_checkpoint(tmp_path, state, s, keep=3)
    assert ckpt.latest_checkpoint_step(tmp_path) == 7
    kept = sorted(p.name for p in tmp_path.glob("ckpt-*.msgpack"))
    assert len(kept) == 3
    assert kept[-1] == "ckpt-00000007.msgpack"


def test_missing_dir_returns_none(tmp_path):
    state, _, _ = _state_and_model()
    assert ckpt.restore_checkpoint(tmp_path / "nope", state) is None


def test_torn_pointer_falls_back_to_scan(tmp_path):
    state, _, _ = _state_and_model()
    ckpt.save_checkpoint(tmp_path, state, 5)
    (tmp_path / "checkpoint.json").write_text("{not json")
    assert ckpt.latest_checkpoint_step(tmp_path) == 5


def test_trainer_resume_continues(tmp_train_dir, synthetic_datasets):
    from distributedmnist_tpu.train.loop import Trainer
    cfg = base_config(train={"train_dir": tmp_train_dir, "max_steps": 10,
                             "log_every_steps": 5, "save_interval_steps": 5})
    t1 = Trainer(cfg, datasets=synthetic_datasets)
    t1.run()
    t2 = Trainer(cfg, datasets=synthetic_datasets)
    assert t2._start_step == 10
    s = t2.run(max_steps=14)
    assert s["final_step"] == 14
    # data iterator resumed, not restarted
    assert t2.train_iter.state()["pos"] > 0 or t2.train_iter.state()["epoch"] > 0
