"""Checkpoint round-trip + resume tests (≙ SURVEY §5.4)."""

import numpy as np
import jax
import pytest

from conftest import base_config
from distributedmnist_tpu.train import checkpoint as ckpt


def _state_and_model(mode="sync"):
    from distributedmnist_tpu.core.config import ExperimentConfig
    from distributedmnist_tpu.models.registry import get_model
    from distributedmnist_tpu.parallel.api import init_train_state
    cfg = base_config(sync={"mode": mode})
    model = get_model(cfg.model)
    return init_train_state(model, cfg), model, cfg


def test_roundtrip_identity(tmp_path):
    state, model, _ = _state_and_model()
    ckpt.save_checkpoint(tmp_path, state, 7, extra={"note": "hi"})
    template, _, _ = _state_and_model()
    restored, extra, step = ckpt.restore_checkpoint(tmp_path, template)
    assert step == 7
    assert extra == {"note": "hi"}
    for a, b in zip(jax.tree.leaves(state.params), jax.tree.leaves(restored.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_roundtrip_interval_state(tmp_path):
    """Interval mode carries a window accumulator — must survive."""
    state, _, _ = _state_and_model(mode="interval")
    assert state.window_acc is not None
    ckpt.save_checkpoint(tmp_path, state, 3)
    template, _, _ = _state_and_model(mode="interval")
    restored, _, step = ckpt.restore_checkpoint(tmp_path, template)
    assert step == 3
    assert restored.window_acc is not None


def test_latest_pointer_and_gc(tmp_path):
    state, _, _ = _state_and_model()
    for s in (1, 2, 3, 4, 5, 6, 7):
        ckpt.save_checkpoint(tmp_path, state, s, keep=3)
    assert ckpt.latest_checkpoint_step(tmp_path) == 7
    kept = sorted(p.name for p in tmp_path.glob("ckpt-*.msgpack"))
    assert len(kept) == 3
    assert kept[-1] == "ckpt-00000007.msgpack"


def test_missing_dir_returns_none(tmp_path):
    state, _, _ = _state_and_model()
    assert ckpt.restore_checkpoint(tmp_path / "nope", state) is None


def test_params_digest_live_and_file_agree(tmp_path):
    """The chaos determinism seam: the digest of the live state equals
    the digest recomputed from the saved artifact alone, and any
    single-leaf perturbation changes it."""
    state, _, _ = _state_and_model()
    live = ckpt.state_params_digest(state)
    ckpt.save_checkpoint(tmp_path, state, 4)
    got = ckpt.checkpoint_params_digest(tmp_path)
    assert got == (live, 4)
    bumped = state.replace(params=jax.tree.map(
        lambda p: p + np.asarray(1e-6, p.dtype)
        if np.issubdtype(np.asarray(p).dtype, np.floating) else p,
        state.params))
    assert ckpt.state_params_digest(bumped) != live
    assert ckpt.checkpoint_params_digest(tmp_path / "nope") is None


def test_torn_pointer_falls_back_to_scan(tmp_path):
    state, _, _ = _state_and_model()
    ckpt.save_checkpoint(tmp_path, state, 5)
    (tmp_path / "checkpoint.json").write_text("{not json")
    assert ckpt.latest_checkpoint_step(tmp_path) == 5


def test_trainer_resume_continues(tmp_train_dir, synthetic_datasets):
    from distributedmnist_tpu.train.loop import Trainer
    cfg = base_config(train={"train_dir": tmp_train_dir, "max_steps": 10,
                             "log_every_steps": 5, "save_interval_steps": 5})
    t1 = Trainer(cfg, datasets=synthetic_datasets)
    t1.run()
    t2 = Trainer(cfg, datasets=synthetic_datasets)
    assert t2._start_step == 10
    s = t2.run(max_steps=14)
    assert s["final_step"] == 14
    # data iterator resumed, not restarted
    assert t2.train_iter.state()["pos"] > 0 or t2.train_iter.state()["epoch"] > 0


def test_sharded_checkpoint_reassembles_global_arrays(tmp_path):
    """Per-host sharded format (SURVEY §2.3 'per-host array
    serialization'): two hand-built shard files — each holding the
    slabs one process would address — plus a manifest must restore to
    the exact full global arrays on a reader of ANY process count."""
    import json
    from flax import serialization

    d = tmp_path / "sharded"
    d.mkdir()
    full_a = np.arange(8 * 3, dtype=np.float32).reshape(8, 3)
    full_b = np.float32(7.0)

    # process 0: rows 0:4 of a, plus the locally-complete scalar b;
    # process 1: rows 4:8 of a
    shard0 = {"leaves": {
        "params/a": {"indices": [[[0, 4], [0, 3]]], "datas": [full_a[0:4]]},
        "step": np.int32(5),
        "params/b": full_b,
    }}
    shard1 = {"leaves": {
        "params/a": {"indices": [[[4, 8], [0, 3]]], "datas": [full_a[4:8]]},
    }}
    (d / "ckpt-00000005.shard000-of-002.msgpack").write_bytes(
        serialization.msgpack_serialize(shard0))
    (d / "ckpt-00000005.shard001-of-002.msgpack").write_bytes(
        serialization.msgpack_serialize(shard1))
    manifest = {"step": 5, "num_shards": 2,
                "leaves": {"params/a": {"shape": [8, 3], "dtype": "float32"},
                           "params/b": {"full": True},
                           "step": {"full": True}},
                "extra": {"config": {"note": "sharded"}}}
    (d / "ckpt-00000005.manifest.json").write_text(json.dumps(manifest))

    template = {"params": {"a": np.zeros((8, 3), np.float32),
                           "b": np.zeros((), np.float32)},
                "step": np.zeros((), np.int32),
                "none_field": None}
    restored = ckpt.restore_checkpoint(d, template)
    assert restored is not None
    state, extra, step = restored
    assert step == 5
    assert extra == {"config": {"note": "sharded"}}
    np.testing.assert_array_equal(state["params"]["a"], full_a)
    np.testing.assert_array_equal(state["params"]["b"], full_b)
    assert int(state["step"]) == 5
    assert state["none_field"] is None
    # latest_checkpoint_step's scan path must parse shard/manifest names
    assert ckpt.latest_checkpoint_step(d) == 5
    # read_checkpoint_extra without a template
    assert ckpt.read_checkpoint_extra(d) == ({"config": {"note": "sharded"}}, 5)


def test_sharded_snapshot_roundtrip_single_process(tmp_path):
    """snapshot_for_save → save_checkpoint → restore on a live
    TP-sharded state (single process: leaves are fully addressable, so
    the snapshot itself reports 'full'; the per-leaf slab extraction is
    exercised by forcing the sharded writer with a fake snapshot)."""
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from distributedmnist_tpu.core.mesh import make_topology
    from distributedmnist_tpu.core.config import MeshConfig

    topo = make_topology(MeshConfig(num_replicas=4, model_parallelism=2))
    w = jax.device_put(jnp.arange(6 * 4, dtype=jnp.float32).reshape(6, 4),
                       NamedSharding(topo.mesh, P(None, "model")))
    state = {"w": w, "step": jax.device_put(jnp.int32(3), topo.replicated)}
    # single-process: everything is addressable → classic single file
    kind = ckpt.snapshot_for_save(state)[0]
    assert kind == "full"
    ckpt.save_checkpoint(tmp_path, state, 3)
    restored, _, step = ckpt.restore_checkpoint(
        tmp_path, jax.tree.map(lambda x: np.zeros(x.shape, x.dtype), state))
    assert step == 3
    np.testing.assert_array_equal(restored["w"],
                                  np.arange(24, dtype=np.float32).reshape(6, 4))


# ---------------------------------------------------------------------------
# corruption fallback (robustness PR): checksums, torn writes, and the
# previous-loadable-step fallback with journaled recovery events
# ---------------------------------------------------------------------------

def _dict_state(v: float):
    return {"params": {"w": np.full((4, 3), v, np.float32)},
            "step": np.int32(int(v))}


def _save_two(tmp_path):
    ckpt.save_checkpoint(tmp_path, _dict_state(3), 3)
    ckpt.save_checkpoint(tmp_path, _dict_state(6), 6)


@pytest.mark.tier1
def test_checkpoint_follower_skip_and_retry(tmp_path):
    """The shared hot-follow loop (evalsvc + servesvc): pointer read,
    step-advanced check, and skip-and-retry when the read raises —
    a torn publish costs polls, never the service."""
    state, _, _ = _state_and_model()
    f = ckpt.CheckpointFollower(tmp_path)
    assert f.newest_step() is None
    assert f.poll(lambda s: 1 / 0) is None  # nothing published: no read
    ckpt.save_checkpoint(tmp_path, state, 5)

    def bad(step):
        raise ckpt.CheckpointCorruptError(f"torn step {step}")

    events = []
    f = ckpt.CheckpointFollower(tmp_path, on_event=events.append)
    assert f.poll(bad) is None
    assert f.last_step == -1 and f.skips == 1
    assert f.last_error == (5, "CheckpointCorruptError: torn step 5")
    assert events[0]["action"] == "follow_skip"
    assert f.poll(bad) is None and f.skips == 2  # retried, still skipped
    got = f.poll(lambda step: ("consumed", step))
    assert got == ("consumed", 5) and f.last_step == 5
    # unchanged step: the read is NOT re-run
    assert f.poll(lambda s: 1 / 0) is None
    ckpt.save_checkpoint(tmp_path, state, 9)
    assert f.poll(lambda step: step) == 9  # advanced: consumed


@pytest.mark.tier1
def test_truncated_latest_falls_back_to_previous_step(tmp_path):
    """A torn write of the newest checkpoint (truncated msgpack) must
    not wedge the resume: restore lands on the previous loadable step
    and journals the fallback through the on_event hook."""
    _save_two(tmp_path)
    latest = tmp_path / "ckpt-00000006.msgpack"
    latest.write_bytes(latest.read_bytes()[: latest.stat().st_size // 2])
    events = []
    restored = ckpt.restore_checkpoint(tmp_path, _dict_state(0),
                                       on_event=events.append)
    assert restored is not None
    state, _, step = restored
    assert step == 3
    np.testing.assert_array_equal(state["params"]["w"],
                                  np.full((4, 3), 3, np.float32))
    actions = {e["action"]: e for e in events}
    assert actions["corrupt_checkpoint_fallback"]["bad_step"] == 6
    assert actions["fallback_restore"]["step"] == 3


@pytest.mark.tier1
def test_checksum_mismatch_detected_via_digest_sidecar(tmp_path):
    """Bytes swapped out from under the digest sidecar (valid msgpack,
    wrong content — silent corruption a parse can't see) are caught by
    the sha256 check and fall back."""
    from flax import serialization

    _save_two(tmp_path)
    assert (tmp_path / "ckpt-00000006.msgpack.sha256").exists()
    # plausible but wrong bytes, written WITHOUT updating the sidecar
    (tmp_path / "ckpt-00000006.msgpack").write_bytes(
        serialization.msgpack_serialize(
            {"state": {"params": {"w": np.zeros((4, 3), np.float32)}}}))
    events = []
    _, _, step = ckpt.restore_checkpoint(tmp_path, _dict_state(0),
                                         on_event=events.append)
    assert step == 3
    assert any("sha256 mismatch" in e.get("error", "") for e in events)


@pytest.mark.tier1
def test_explicit_step_restore_raises_on_corruption(tmp_path):
    """An explicitly requested step never falls back silently — the
    caller asked for THAT step."""
    _save_two(tmp_path)
    latest = tmp_path / "ckpt-00000006.msgpack"
    latest.write_bytes(b"\x00garbage")
    with pytest.raises(ckpt.CheckpointCorruptError):
        ckpt.restore_checkpoint(tmp_path, _dict_state(0), step=6)


@pytest.mark.tier1
def test_corrupt_manifest_and_shard_fall_back(tmp_path):
    """Sharded layout: a garbled manifest or a truncated shard at the
    newest step both fall back to the previous complete step, and the
    events are journaled."""
    import json
    from flax import serialization

    def write_sharded(step, v):
        shard = {"leaves": {"params/w": {
            "indices": [[[0, 4], [0, 3]]],
            "datas": [np.full((4, 3), v, np.float32)]}}}
        (tmp_path / f"ckpt-{step:08d}.shard000-of-001.msgpack").write_bytes(
            serialization.msgpack_serialize(shard))
        manifest = {"step": step, "num_shards": 1,
                    "leaves": {"params/w": {"shape": [4, 3],
                                            "dtype": "float32"}},
                    "extra": {}}
        (tmp_path / f"ckpt-{step:08d}.manifest.json").write_text(
            json.dumps(manifest))

    template = {"params": {"w": np.zeros((4, 3), np.float32)}}
    write_sharded(5, 5.0)
    write_sharded(7, 7.0)

    # (a) torn manifest at the newest step
    mpath = tmp_path / "ckpt-00000007.manifest.json"
    good_manifest = mpath.read_text()
    mpath.write_text(good_manifest[: len(good_manifest) // 2])
    events = []
    state, _, step = ckpt.restore_checkpoint(tmp_path, template,
                                             on_event=events.append)
    assert step == 5
    np.testing.assert_array_equal(state["params"]["w"],
                                  np.full((4, 3), 5, np.float32))
    assert any(e["action"] == "corrupt_checkpoint_fallback"
               and e["bad_step"] == 7 for e in events)

    # (b) manifest restored, shard truncated instead
    mpath.write_text(good_manifest)
    spath = tmp_path / "ckpt-00000007.shard000-of-001.msgpack"
    spath.write_bytes(spath.read_bytes()[:10])
    _, _, step = ckpt.restore_checkpoint(tmp_path, template)
    assert step == 5


@pytest.mark.tier1
def test_io_retry_wrapper_absorbs_transient_errors():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("transient")
        return "ok"

    assert ckpt._io_retries(flaky, "flaky") == "ok"
    assert len(calls) == 3

    def missing():
        raise FileNotFoundError("gone")

    with pytest.raises(FileNotFoundError):  # permanent, no retries
        ckpt._io_retries(missing, "missing")


@pytest.mark.tier1
def test_shard_missing_required_leaf_falls_back(tmp_path):
    """A shard set that parses cleanly but lacks a leaf the state
    requires (a swapped or half-written legacy shard) is damage to THAT
    step — restore must fall back, not crash with a bare KeyError."""
    import json
    from flax import serialization

    def write_sharded(step, leaves):
        (tmp_path / f"ckpt-{step:08d}.shard000-of-001.msgpack").write_bytes(
            serialization.msgpack_serialize({"leaves": leaves}))
        manifest = {"step": step, "num_shards": 1,
                    "leaves": {k: {"shape": [2], "dtype": "float32"}
                               for k in leaves},
                    "extra": {}}
        (tmp_path / f"ckpt-{step:08d}.manifest.json").write_text(
            json.dumps(manifest))

    full = {"params/w": {"indices": [[[0, 2]]],
                         "datas": [np.ones(2, np.float32)]},
            "params/b": {"indices": [[[0, 2]]],
                         "datas": [np.full(2, 2.0, np.float32)]}}
    write_sharded(3, full)
    write_sharded(9, {"params/w": full["params/w"]})  # b missing at 9

    template = {"params": {"w": np.zeros(2, np.float32),
                           "b": np.zeros(2, np.float32)}}
    state, _, step = ckpt.restore_checkpoint(tmp_path, template)
    assert step == 3
    np.testing.assert_array_equal(state["params"]["b"],
                                  np.full(2, 2.0, np.float32))
