"""The long-context family end-to-end: transformer + synthetic_lm
dataset through the full Trainer stack (8-replica SPMD, masked psum).
The reference has no attention model at all (SURVEY §5.7); this guards
the framework's sequence path as a first-class citizen."""

from conftest import base_config


def lm_config(**over):
    cfg = base_config(**over)
    return cfg.override({
        "data": {"dataset": "synthetic_lm", "batch_size": 32,
                 "synthetic_train_size": 512, "synthetic_test_size": 128,
                 "use_native_pipeline": False},
        "model": {"name": "transformer", "seq_len": 64, "model_dim": 64,
                  "num_heads": 4, "num_layers": 2, "vocab_size": 32,
                  "compute_dtype": "float32"},
        "optim": {"initial_learning_rate": 0.05,
                  "learning_rate_decay_factor": 1.0},
    })


def test_transformer_trains_through_trainer(tmp_train_dir):
    from distributedmnist_tpu.train.loop import Trainer

    cfg = lm_config(train={"max_steps": 40, "log_every_steps": 20,
                           "train_dir": tmp_train_dir,
                           "save_interval_steps": 0,
                           "save_results_period": 0})
    t = Trainer(cfg)
    first_losses = []
    s = t.run(step_callback=lambda step, rec: first_losses.append(rec["loss"]))
    assert s["final_step"] == 40
    # next-token loss must fall well below uniform log(32) ≈ 3.47
    assert first_losses[-1] < first_losses[0] - 0.5, first_losses[:3] + first_losses[-3:]
    ev = t.evaluate("test")
    assert ev["loss"] < 3.0
    assert 0.0 < ev["accuracy"] <= 1.0


def test_transformer_quorum_mode(tmp_train_dir):
    from distributedmnist_tpu.train.loop import Trainer

    cfg = lm_config(train={"max_steps": 10, "log_every_steps": 5,
                           "train_dir": tmp_train_dir,
                           "save_interval_steps": 0,
                           "save_results_period": 0},
                    sync={"mode": "quorum", "num_replicas_to_aggregate": 5,
                          "straggler_profile": "lognormal"})
    t = Trainer(cfg)
    s = t.run()
    assert s["last_metrics"]["num_contributors"] == 5.0


def test_remat_matches_dense_exactly(topo8):
    """jax.checkpoint is a pure memory/FLOPs trade: with remat on, the
    loss and one-step update must be bit-comparable to the non-remat
    model (same graph numerics, recomputed not stored)."""
    import jax
    import numpy as np

    from conftest import base_config
    from distributedmnist_tpu.models.registry import get_model
    from distributedmnist_tpu.parallel.api import (build_train_step,
                                                   init_train_state)
    from distributedmnist_tpu.train.lr_schedule import constant

    results = {}
    for remat in (False, True):
        cfg = base_config(
            data={"dataset": "synthetic_lm", "batch_size": 8},
            model={"name": "transformer", "compute_dtype": "float32",
                   "seq_len": 16, "model_dim": 32, "num_heads": 4,
                   "num_layers": 2, "vocab_size": 37,
                   "attention_impl": "dense", "remat": remat},
            sync={"mode": "sync", "straggler_profile": "none"},
        )
        cfg = cfg.override({"mesh.num_replicas": 8})
        model = get_model(cfg.model)
        state = topo8.device_put_replicated(init_train_state(model, cfg))
        step_fn = build_train_step(model, cfg, topo8, constant(0.1))
        toks = jax.random.randint(jax.random.PRNGKey(3), (8, 16), 0, 37)
        state, metrics = step_fn(
            state, topo8.device_put_batch({"image": toks, "label": toks}))
        results[remat] = (float(metrics["loss"]),
                          jax.tree.leaves(jax.device_get(state.params)))
    np.testing.assert_allclose(results[False][0], results[True][0],
                               rtol=1e-6)
    for a, b in zip(results[False][1], results[True][1]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)
