"""The long-context family end-to-end: transformer + synthetic_lm
dataset through the full Trainer stack (8-replica SPMD, masked psum).
The reference has no attention model at all (SURVEY §5.7); this guards
the framework's sequence path as a first-class citizen."""

from conftest import base_config


def lm_config(**over):
    cfg = base_config(**over)
    return cfg.override({
        "data": {"dataset": "synthetic_lm", "batch_size": 32,
                 "synthetic_train_size": 512, "synthetic_test_size": 128,
                 "use_native_pipeline": False},
        "model": {"name": "transformer", "seq_len": 64, "model_dim": 64,
                  "num_heads": 4, "num_layers": 2, "vocab_size": 32,
                  "compute_dtype": "float32"},
        "optim": {"initial_learning_rate": 0.05,
                  "learning_rate_decay_factor": 1.0},
    })


def test_transformer_trains_through_trainer(tmp_train_dir):
    from distributedmnist_tpu.train.loop import Trainer

    cfg = lm_config(train={"max_steps": 40, "log_every_steps": 20,
                           "train_dir": tmp_train_dir,
                           "save_interval_steps": 0,
                           "save_results_period": 0})
    t = Trainer(cfg)
    first_losses = []
    s = t.run(step_callback=lambda step, rec: first_losses.append(rec["loss"]))
    assert s["final_step"] == 40
    # next-token loss must fall well below uniform log(32) ≈ 3.47
    assert first_losses[-1] < first_losses[0] - 0.5, first_losses[:3] + first_losses[-3:]
    ev = t.evaluate("test")
    assert ev["loss"] < 3.0
    assert 0.0 < ev["accuracy"] <= 1.0


def test_transformer_quorum_mode(tmp_train_dir):
    from distributedmnist_tpu.train.loop import Trainer

    cfg = lm_config(train={"max_steps": 10, "log_every_steps": 5,
                           "train_dir": tmp_train_dir,
                           "save_interval_steps": 0,
                           "save_results_period": 0},
                    sync={"mode": "quorum", "num_replicas_to_aggregate": 5,
                          "straggler_profile": "lognormal"})
    t = Trainer(cfg)
    s = t.run()
    assert s["last_metrics"]["num_contributors"] == 5.0


def test_remat_matches_dense_exactly(topo8):
    """jax.checkpoint is a pure memory/FLOPs trade: with remat on, the
    loss and one-step update must be bit-comparable to the non-remat
    model (same graph numerics, recomputed not stored)."""
    import jax
    import numpy as np

    from conftest import base_config
    from distributedmnist_tpu.models.registry import get_model
    from distributedmnist_tpu.parallel.api import (build_train_step,
                                                   init_train_state)
    from distributedmnist_tpu.train.lr_schedule import constant

    results = {}
    for remat in (False, True):
        cfg = base_config(
            data={"dataset": "synthetic_lm", "batch_size": 8},
            model={"name": "transformer", "compute_dtype": "float32",
                   "seq_len": 16, "model_dim": 32, "num_heads": 4,
                   "num_layers": 2, "vocab_size": 37,
                   "attention_impl": "dense", "remat": remat},
            sync={"mode": "sync", "straggler_profile": "none"},
        )
        cfg = cfg.override({"mesh.num_replicas": 8})
        model = get_model(cfg.model)
        state = topo8.device_put_replicated(init_train_state(model, cfg))
        step_fn = build_train_step(model, cfg, topo8, constant(0.1))
        toks = jax.random.randint(jax.random.PRNGKey(3), (8, 16), 0, 37)
        state, metrics = step_fn(
            state, topo8.device_put_batch({"image": toks, "label": toks}))
        results[remat] = (float(metrics["loss"]),
                          jax.tree.leaves(jax.device_get(state.params)))
    np.testing.assert_allclose(results[False][0], results[True][0],
                               rtol=1e-6)
    for a, b in zip(results[False][1], results[True][1]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)


def test_remat_save_attn_matches_full(topo8):
    """remat_policy='save_attn' (attention residuals resident, FFN
    recomputed) is the same math as full remat — loss and one-step
    update must agree to float tolerance, through the flash kernel
    whose custom-vjp residuals the policy keeps."""
    import jax
    import numpy as np

    from conftest import base_config
    from distributedmnist_tpu.models.registry import get_model
    from distributedmnist_tpu.parallel.api import (build_train_step,
                                                   init_train_state)
    from distributedmnist_tpu.train.lr_schedule import constant

    results = {}
    for policy in ("full", "save_attn"):
        cfg = base_config(
            data={"dataset": "synthetic_lm", "batch_size": 8},
            model={"name": "transformer", "compute_dtype": "float32",
                   "seq_len": 16, "model_dim": 32, "num_heads": 4,
                   "num_layers": 2, "vocab_size": 37,
                   "attention_impl": "flash", "remat": True,
                   "remat_policy": policy},
            sync={"mode": "sync", "straggler_profile": "none"},
        )
        cfg = cfg.override({"mesh.num_replicas": 8})
        model = get_model(cfg.model)
        state = topo8.device_put_replicated(init_train_state(model, cfg))
        step_fn = build_train_step(model, cfg, topo8, constant(0.1))
        toks = jax.random.randint(jax.random.PRNGKey(3), (8, 16), 0, 37)
        state, metrics = step_fn(
            state, topo8.device_put_batch({"image": toks, "label": toks}))
        results[policy] = (float(metrics["loss"]),
                           jax.tree.leaves(jax.device_get(state.params)))
    np.testing.assert_allclose(results["full"][0], results["save_attn"][0],
                               rtol=1e-6)
    for a, b in zip(results["full"][1], results["save_attn"][1]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)


def test_remat_save_attn_refuses_ring_sp(topo8):
    """Ring attention has no fused VJP — outside a checkpoint AD would
    save its per-step scan residuals, the memory remat exists to avoid.
    The registry must refuse the combination loudly."""
    import pytest

    from conftest import base_config
    from distributedmnist_tpu.models.registry import get_model
    from distributedmnist_tpu.parallel.api import (build_train_step,
                                                   init_train_state)
    from distributedmnist_tpu.train.lr_schedule import constant

    cfg = base_config(
        data={"dataset": "synthetic_lm", "batch_size": 8},
        model={"name": "transformer", "compute_dtype": "float32",
               "seq_len": 16, "model_dim": 32, "num_heads": 4,
               "num_layers": 2, "vocab_size": 37,
               "attention_impl": "flash", "sp_attention": "ring",
               "remat": True, "remat_policy": "save_attn"},
        sync={"mode": "sync", "straggler_profile": "none"},
    )
    from distributedmnist_tpu.core.config import MeshConfig
    from distributedmnist_tpu.core.mesh import make_topology

    cfg = cfg.override({"mesh.num_replicas": 4, "mesh.seq_parallelism": 2})
    topo = make_topology(MeshConfig(num_replicas=4, seq_parallelism=2))
    model = get_model(cfg.model)
    with pytest.raises(ValueError, match="save_attn"):
        build_train_step(model, cfg, topo, constant(0.1))

    # dense attention has no fused VJP either — O(s²) residuals would
    # stay resident; refused at model build
    with pytest.raises(ValueError, match="flash"):
        get_model(cfg.model.__class__(**{
            **{f.name: getattr(cfg.model, f.name)
               for f in __import__("dataclasses").fields(cfg.model)},
            "attention_impl": "dense", "sp_attention": "ring"}))

    # pipeline stage scans only support full per-layer remat — a
    # silently-ignored policy must be refused, not degraded
    cfg_pp = base_config(
        data={"dataset": "synthetic_lm", "batch_size": 8},
        model={"name": "transformer", "compute_dtype": "float32",
               "seq_len": 16, "model_dim": 32, "num_heads": 4,
               "num_layers": 2, "vocab_size": 37,
               "attention_impl": "flash", "remat": True,
               "remat_policy": "save_attn"},
        sync={"mode": "sync", "straggler_profile": "none"},
    ).override({"mesh.num_replicas": 4, "mesh.pipeline_parallelism": 2})
    topo_pp = make_topology(MeshConfig(num_replicas=4,
                                       pipeline_parallelism=2))
    model_pp = get_model(cfg_pp.model)
    with pytest.raises(ValueError, match="remat_policy"):
        build_train_step(model_pp, cfg_pp, topo_pp, constant(0.1))
