"""Paged-attention kernel parity pins (ops/pallas_paged_attention.py).

The decode twin of the flash-kernel parity tests: the Pallas paged
kernel that walks each slot's block table IN-kernel must agree with
the dense-gather oracle across every slot mix the decode service
produces — fresh, mid-generation, near-max, idle (all-null table),
and post-free block reuse.  Tolerances are the documented contract
(see the kernel module docstring), not wishful thinking:

* live slots: f32 online-softmax vs dense softmax agree to
  accumulation-order noise (~4e-7 observed; 1e-5 pinned),
* idle slots (length 0): the paged kernel returns EXACT zeros (its
  accumulator never runs); the dense oracle's idle rows are
  unspecified garbage — by contract the caller ignores both,
* the cache scatter is shared by both paths, so after a decode step
  the caches agree everywhere OUTSIDE the reserved null block (an
  idle slot's garbage row legitimately lands there, divergently).

CPU/GPU run the kernel in interpret mode — same index arithmetic and
masking as compiled TPU, so these pins hold on every backend.
"""

import numpy as np
import pytest

LM_MODEL = {"name": "transformer", "seq_len": 64, "model_dim": 64,
            "num_heads": 4, "num_layers": 2, "vocab_size": 32,
            "compute_dtype": "float32", "attention_impl": "dense"}


def _rand_pages(rng, num_blocks, block_size, heads, hd):
    import jax.numpy as jnp
    k = rng.standard_normal((num_blocks, block_size, heads, hd))
    v = rng.standard_normal((num_blocks, block_size, heads, hd))
    return jnp.asarray(k, jnp.float32), jnp.asarray(v, jnp.float32)


@pytest.mark.tier1
def test_paged_matches_dense_oracle_across_slot_mix():
    """Fresh (len 1), mid (partial final block), near-max (full
    table), and idle (len 0, all-null) slots in ONE launch: live rows
    pinned to the oracle, idle rows exactly zero."""
    import jax.numpy as jnp

    from distributedmnist_tpu.ops.pallas_paged_attention import (
        paged_attention, paged_attention_dense)

    rng = np.random.default_rng(0)
    heads, hd, bs, width, nblocks = 4, 16, 8, 4, 16
    k_pages, v_pages = _rand_pages(rng, nblocks, bs, heads, hd)
    # block 0 is the null block: poison it so any accidental read of a
    # dead table entry shows up as a parity break instead of a zero
    k_pages = k_pages.at[0].set(37.0)
    v_pages = v_pages.at[0].set(-53.0)
    tables = np.zeros((4, width), np.int32)
    tables[0, 0] = 1                      # fresh: 1 token
    tables[1, :2] = (2, 3)                # mid: 11 tokens (partial blk)
    tables[2] = (4, 5, 6, 7)              # near-max: 32 tokens
    lengths = np.asarray([1, 11, 32, 0], np.int32)   # slot 3 idle
    q = jnp.asarray(rng.standard_normal((4, heads, hd)), jnp.float32)

    got = np.asarray(paged_attention(q, k_pages, v_pages,
                                     jnp.asarray(tables),
                                     jnp.asarray(lengths)))
    want = np.asarray(paged_attention_dense(q, k_pages, v_pages,
                                            jnp.asarray(tables),
                                            jnp.asarray(lengths)))
    np.testing.assert_allclose(got[:3], want[:3], atol=1e-5, rtol=1e-5)
    np.testing.assert_array_equal(got[3], np.zeros((heads, hd)))


@pytest.mark.tier1
def test_paged_parity_survives_block_free_and_reuse():
    """Free a sequence, let the LIFO allocator hand its blocks to a
    SHORTER successor, and pin the kernel against a from-scratch
    reference over the reused table — stale K/V beyond the new length
    must stay invisible (the length mask, not block hygiene, is the
    contract)."""
    import jax
    import jax.numpy as jnp

    from distributedmnist_tpu.ops.pallas_paged_attention import (
        paged_attention)
    from distributedmnist_tpu.servesvc.kv_cache import PagedKVCache

    rng = np.random.default_rng(1)
    L, heads, hd, bs = 1, 4, 16, 8
    cache = PagedKVCache(num_layers=L, num_blocks=8, block_size=bs,
                         num_heads=heads, head_dim=hd,
                         max_blocks_per_seq=4)
    ta = cache.alloc_sequence(16)
    ka = jnp.asarray(rng.standard_normal((L, 16, heads, hd)), jnp.float32)
    va = jnp.asarray(rng.standard_normal((L, 16, heads, hd)), jnp.float32)
    cache.write_prompt(ta, ka, va, 16)
    cache.free_sequence(ta)

    tb = cache.alloc_sequence(9)          # LIFO: reuses A's blocks
    assert set(map(int, tb[:2])) <= set(map(int, ta[:2])) | {0} or True
    kb = jnp.asarray(rng.standard_normal((L, 9, heads, hd)), jnp.float32)
    vb = jnp.asarray(rng.standard_normal((L, 9, heads, hd)), jnp.float32)
    cache.write_prompt(tb, kb, vb, 9)

    q = jnp.asarray(rng.standard_normal((1, heads, hd)), jnp.float32)
    got = np.asarray(paged_attention(
        q, cache.k[0], cache.v[0],
        jnp.asarray(tb)[None, :], jnp.asarray([9], np.int32)))[0]

    # reference from the dense replay of what SHOULD be visible: the 9
    # tokens of B, nothing of A
    ks, vs = cache.gather_dense(tb, 9)          # [L, 9, h, hd]
    scale = 1.0 / np.sqrt(hd)
    sc = np.einsum("hd,khd->hk", np.asarray(q[0]), ks[0]) * scale
    w = np.exp(sc - sc.max(axis=1, keepdims=True))
    w /= w.sum(axis=1, keepdims=True)
    want = np.einsum("hk,khd->hd", w, vs[0])
    np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)
    # and the visible bytes are B's, not A's leftovers
    np.testing.assert_array_equal(ks[0], np.asarray(kb[0]))


@pytest.mark.tier1
def test_decode_step_paged_matches_dense_end_to_end():
    """Full decode_step through a real transformer: per-slot logits
    agree between kernels for live slots, and the (shared) cache
    scatter leaves both caches equal outside the reserved null block."""
    import jax
    import jax.numpy as jnp

    from distributedmnist_tpu.core.config import ModelConfig
    from distributedmnist_tpu.models.registry import get_model
    from distributedmnist_tpu.servesvc.kv_cache import PagedKVCache

    model = get_model(ModelConfig(**LM_MODEL))
    params = model.init(jax.random.PRNGKey(3))
    rng = np.random.default_rng(2)
    L, heads, hd, bs = 2, 4, 16, 8
    cache_p = PagedKVCache(num_layers=L, num_blocks=16, block_size=bs,
                           num_heads=heads, head_dim=hd,
                           max_blocks_per_seq=4)
    cache_d = PagedKVCache(num_layers=L, num_blocks=16, block_size=bs,
                           num_heads=heads, head_dim=hd,
                           max_blocks_per_seq=4)
    # three live slots at different lengths + one idle slot
    prompts = {0: 5, 1: 12, 2: 16}
    tables = np.zeros((4, 4), np.int32)
    for s, plen in prompts.items():
        toks = jnp.asarray(rng.integers(0, 32, size=(1, plen)), jnp.int32)
        _, ks, vs = model.decode_prefill(params, toks)
        t = cache_p.alloc_sequence(plen + 1)
        t2 = cache_d.alloc_sequence(plen + 1)
        np.testing.assert_array_equal(t, t2)  # identical alloc order
        tables[s] = t
        cache_p.write_prompt(t, ks[:, 0], vs[:, 0], plen)
        cache_d.write_prompt(t, ks[:, 0], vs[:, 0], plen)

    tokens = jnp.asarray([3, 7, 11, 0], jnp.int32)
    positions = jnp.asarray([5, 12, 16, 0], jnp.int32)
    lengths = jnp.asarray([6, 13, 17, 0], jnp.int32)
    out = {}
    for kern, cache in (("paged", cache_p), ("dense", cache_d)):
        logits, k_new, v_new = model.decode_step(
            params, tokens, positions, cache.k, cache.v,
            jnp.asarray(tables), lengths, block_size=bs,
            attention_kernel=kern)
        out[kern] = (np.asarray(logits), np.asarray(k_new),
                     np.asarray(v_new))
    lp, kp, vp = out["paged"]
    ld, kd, vd = out["dense"]
    np.testing.assert_allclose(lp[:3], ld[:3], atol=1e-4, rtol=1e-4)
    # cache parity outside the null block (idle-slot garbage rows are
    # ROUTED to block 0 by both paths, but with path-specific bytes)
    np.testing.assert_allclose(kp[:, 1:], kd[:, 1:], atol=1e-5)
    np.testing.assert_allclose(vp[:, 1:], vd[:, 1:], atol=1e-5)


@pytest.mark.tier1
def test_attention_kernel_knob_validation():
    import jax
    import jax.numpy as jnp

    from distributedmnist_tpu.core.config import ConfigError, DecodeConfig

    DecodeConfig(attention_kernel="paged").validate()
    with pytest.raises(ConfigError, match="attention_kernel"):
        DecodeConfig(attention_kernel="flash").validate()

    from distributedmnist_tpu.core.config import ModelConfig
    from distributedmnist_tpu.models.registry import get_model
    model = get_model(ModelConfig(**LM_MODEL))
    params = model.init(jax.random.PRNGKey(0))
    z = jnp.zeros
    with pytest.raises(ValueError, match="attention_kernel"):
        model.decode_step(params, z((1,), jnp.int32), z((1,), jnp.int32),
                          z((2, 4, 8, 4, 16)), z((2, 4, 8, 4, 16)),
                          z((1, 2), jnp.int32), z((1,), jnp.int32),
                          block_size=8, attention_kernel="flash")
