"""Live multi-host execution: two real ``jax.distributed`` processes
(4 virtual CPU devices each → one 8-device global mesh) training and
evaluating through the full product stack, asserted for loss/param
parity against the single-process 8-device run.

This is the one reference capability — an actually-running
multi-process cluster (reference src/mnist_distributed_train.py:25-35)
— that unit tests cannot cover in-process: ``jax.distributed``
bring-up (core/mesh.initialize_distributed), per-process batch
assembly (``make_array_from_process_local_data`` in
Topology.device_put_batch), host-sharded ingest (data/pipeline
``shard_mode="sharded"``) and the striped multi-host eval with its
process allgather (train/evaluation.run_full_eval).

Parity argument: the dataset equals the global batch (full-batch
steps), so the multiset of rows per step is identical however the
hosts shard it; with equal per-replica row counts the replica-mean of
means equals the global mean, making losses and SGD updates equal up
to float reassociation.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

from conftest import base_config

_CHILD = """
import json, os, sys
from distributedmnist_tpu.core.mesh import initialize_distributed, simulate_devices
simulate_devices(4)           # per-process local devices
initialize_distributed()      # before any backend touch
import jax
import numpy as np
from distributedmnist_tpu.core.config import ExperimentConfig
from distributedmnist_tpu.train.loop import Trainer

cfg = ExperimentConfig.from_dict(json.loads(os.environ["DML_CFG"]))
t = Trainer(cfg)
sleep_ms = float(os.environ.get("DML_SLEEP_MS", "0"))
if sleep_ms:
    # a REAL slowdown of this process's step loop (not a configured
    # delay constant): every batch fetch stalls the host, exactly like
    # slow ingest or CPU contention would — the measured-timing path
    # must observe it and the policies must act on it
    import time as _time
    _base_iter = t.train_iter
    def _slow(it, secs):
        while True:
            _time.sleep(secs)
            yield next(it)
    t.train_iter = _slow(_base_iter, sleep_ms / 1000.0)
start_step = t._start_step
summary = t.run()
ev = t.evaluate()
leaves = jax.tree.leaves(jax.device_get(t.state.params))
times = t.collector.matrix()
print("RESULT " + json.dumps({
    "process_count": jax.process_count(),
    "local_devices": jax.local_device_count(),
    "global_devices": len(jax.devices()),
    "start_step": start_step,
    "final_step": summary["final_step"],
    "loss": summary["last_metrics"]["loss"],
    "param_l1": float(sum(np.abs(np.asarray(x), dtype=np.float64).sum()
                          for x in leaves)),
    "eval_accuracy": ev["accuracy"],
    "eval_loss": ev["loss"],
    "eval_num_examples": ev["num_examples"],
    # the multi-host-safety claim under test: every process holds the
    # full replicated [n] timing vector and contribution flags
    # (parallel/api._gather_replicated's one-hot psum)
    "flags": summary["last_metrics"]["flags"],
    "num_contributors": summary["last_metrics"]["num_contributors"],
    "last_step_times": times[-1].tolist() if times.size else [],
}))
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _cfg_dict(train_dir: str) -> dict:
    # Full-batch (dataset == global batch) for the parity argument
    # above; dropout off because dropout masks are keyed by replica
    # and rows land on different replicas across launch shapes.
    return {
        "data": {"dataset": "synthetic", "batch_size": 128,
                 "synthetic_train_size": 128, "synthetic_test_size": 96,
                 "use_native_pipeline": False},
        "model": {"compute_dtype": "float32", "dropout_rate": 0.0},
        "optim": {"learning_rate_decay_factor": 1.0},
        "sync": {"mode": "sync", "straggler_profile": "none"},
        "eval": {"eval_batch_size": 32},
        "train": {"max_steps": 4, "log_every_steps": 2,
                  "save_interval_steps": 0, "save_results_period": 0,
                  "train_dir": train_dir},
    }


def _launch(tmp_path, cfg_dicts=None, sleep_ms=(0.0, 0.0),
            child=None, local_devices=4):
    port = _free_port()
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)
        env["XLA_FLAGS"] = (f"--xla_force_host_platform_device_count="
                            f"{local_devices}")
        env["JAX_COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
        env["JAX_NUM_PROCESSES"] = "2"
        env["JAX_PROCESS_ID"] = str(pid)
        env["DML_SLEEP_MS"] = str(sleep_ms[pid])
        env["DML_LOCAL_DEVICES"] = str(local_devices)
        env["DML_CFG"] = json.dumps(
            cfg_dicts[pid] if cfg_dicts is not None
            else _cfg_dict(str(tmp_path / f"multihost_p{pid}")))
        procs.append(subprocess.Popen(
            [sys.executable, "-c", child or _CHILD], env=env, cwd=os.getcwd(),
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    results = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=600)
            assert p.returncode == 0, f"child failed:\n{err[-4000:]}"
            line = [ln for ln in out.splitlines()
                    if ln.startswith("RESULT ")]
            assert line, f"no RESULT line:\n{out[-2000:]}\n{err[-2000:]}"
            results.append(json.loads(line[-1][len("RESULT "):]))
    finally:
        for q in procs:  # a failed sibling must not orphan the other
            if q.poll() is None:
                q.kill()
    return results


@pytest.mark.slow  # boots 2 real gloo worker processes; ~100 s on the tier-1 box (and crashes in jaxlib-0.4.37 gloo: EnforceNotMet pair.cc)
def test_two_process_training_matches_single_process(tmp_path):
    r0, r1 = _launch(tmp_path)
    for r in (r0, r1):
        assert r["process_count"] == 2
        assert r["local_devices"] == 4
        assert r["global_devices"] == 8
        assert r["final_step"] == 4
    # both processes observe the same global state
    np.testing.assert_allclose(r0["loss"], r1["loss"], rtol=1e-6)
    np.testing.assert_allclose(r0["param_l1"], r1["param_l1"], rtol=1e-6)
    assert r0["eval_num_examples"] == r1["eval_num_examples"] == 96

    # single-process 8-device reference run, identical config
    from distributedmnist_tpu.train.loop import Trainer
    import jax
    cfg = base_config(**_cfg_dict(str(tmp_path / "single")))
    t = Trainer(cfg)
    summary = t.run()
    ev = t.evaluate()
    leaves = jax.tree.leaves(jax.device_get(t.state.params))
    param_l1 = float(sum(np.abs(np.asarray(x), dtype=np.float64).sum()
                         for x in leaves))

    np.testing.assert_allclose(r0["loss"], summary["last_metrics"]["loss"],
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(r0["param_l1"], param_l1, rtol=1e-6)
    np.testing.assert_allclose(r0["eval_loss"], ev["loss"],
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(r0["eval_accuracy"], ev["accuracy"],
                               rtol=1e-5, atol=1e-6)
    assert ev["num_examples"] == 96


@pytest.mark.slow  # boots 2 real gloo worker processes; passes standalone
# but under full-suite load reliably hits the known jaxlib-0.4.37 gloo
# SIGABRT (gloo::EnforceNotMet pair.cc) — same crash its 3 slow-marked
# siblings were quarantined for
def test_two_process_quorum_gathers_on_every_host(tmp_path):
    """Quorum mode across two live processes: the k-of-n mask, the
    replicated [n] timing vector and the flags gather — the exact paths
    `_gather_replicated` exists for (parallel/api.py: a one-hot psum is
    statically replicated, so non-addressable processes can materialize
    it; an all_gather could not leave shard_map replicated) — must
    produce identical values on BOTH hosts, and match the seeded
    single-process run."""
    def qcfg(train_dir):
        d = _cfg_dict(train_dir)
        d["sync"] = {"mode": "quorum", "num_replicas_to_aggregate": 6,
                     "straggler_profile": "lognormal"}
        d["train"]["max_steps"] = 3
        return d

    r0, r1 = _launch(tmp_path, [qcfg(str(tmp_path / "q_p0")),
                                qcfg(str(tmp_path / "q_p1"))])
    for r in (r0, r1):
        assert r["global_devices"] == 8
        assert r["num_contributors"] == 6.0
        assert sum(r["flags"]) == 6
        assert len(r["last_step_times"]) == 8
    # every host holds the same replicated vectors
    assert r0["flags"] == r1["flags"]
    np.testing.assert_allclose(r0["last_step_times"], r1["last_step_times"],
                               rtol=1e-6)
    np.testing.assert_allclose(r0["loss"], r1["loss"], rtol=1e-6)

    # the straggler model is keyed by (seed, step, replica) — a
    # single-process run with the same config selects the same quorum.
    # (No loss parity here, deliberately: masking replica r drops
    # whichever ROWS replica r holds, and the host-sharded ingest
    # assigns different rows per replica across launch shapes — only
    # the selection itself is layout-invariant.)
    from distributedmnist_tpu.train.loop import Trainer
    records = []
    cfg = base_config(**qcfg(str(tmp_path / "q_single")))
    t = Trainer(cfg)
    t.run(step_callback=lambda s, rec: records.append(rec))
    assert records[-1]["flags"] == r0["flags"]


@pytest.mark.slow  # boots 2 real gloo worker processes (jaxlib-0.4.37 gloo crash)
def test_slow_process_loses_quorum_by_measured_time(tmp_path):
    """A REALLY slow process — its host loop stalled by an actual
    sleep, not a configured delay — must lose quorum membership through
    the measured-timing path: each process feeds its own measured step
    time into its replicas' rows of the [n] vector
    (Topology.device_put_measured), and the quorum policy ranks on it
    (≙ measured per-worker times driving aggregation,
    src/timeout_manager.py:48-61). k=4 of 8 with process 1 sleeping
    250 ms per step ⇒ steady-state contributors are exactly process 0's
    replicas 0–3."""
    def qcfg(train_dir):
        d = _cfg_dict(train_dir)
        # straggler_profile "none" → the REAL measured host step time
        # drives the policies (train/loop.py inject_measured)
        d["sync"] = {"mode": "quorum", "num_replicas_to_aggregate": 4,
                     "straggler_profile": "none"}
        d["train"]["max_steps"] = 6
        return d

    r0, r1 = _launch(tmp_path, [qcfg(str(tmp_path / "s_p0")),
                                qcfg(str(tmp_path / "s_p1"))],
                     sleep_ms=(0.0, 250.0))
    for r in (r0, r1):
        assert r["num_contributors"] == 4.0
        # process 1's measured times dwarf process 0's
        times = r["last_step_times"]
        assert min(times[4:]) > 10 * max(times[0], 1e-3), times
        # ... and exactly its replicas are evicted from the quorum
        assert r["flags"] == [1, 1, 1, 1, 0, 0, 0, 0]
    assert r0["flags"] == r1["flags"]


@pytest.mark.slow  # boots real worker processes twice (save, kill, resume); ~40 s
def test_two_process_save_kill_resume(tmp_path):
    """Checkpoint/resume across process death on a live two-process
    cluster: phase 1 trains 4 steps into a SHARED train_dir (process 0
    is the writer, ≙ the chief's NFS checkpoints,
    tools/tf_ec2.py:61-68) and the cluster dies; phase 2's fresh
    processes must both restore step 4 and finish at 8 with exactly the
    params a never-killed single-process 8-step run produces."""
    shared = str(tmp_path / "mh_shared")

    def pcfg(max_steps):
        d = _cfg_dict(shared)
        d["train"]["max_steps"] = max_steps
        return d

    r0, r1 = _launch(tmp_path, [pcfg(4), pcfg(4)])
    assert r0["start_step"] == r1["start_step"] == 0
    assert r0["final_step"] == r1["final_step"] == 4

    s0, s1 = _launch(tmp_path, [pcfg(8), pcfg(8)])
    for s in (s0, s1):
        assert s["start_step"] == 4, "resume must pick up the checkpoint"
        assert s["final_step"] == 8
    np.testing.assert_allclose(s0["param_l1"], s1["param_l1"], rtol=1e-6)

    # exact-resume oracle: one uninterrupted 8-step run
    from distributedmnist_tpu.train.loop import Trainer
    import jax
    cfg = base_config(**_cfg_dict(str(tmp_path / "oracle")))
    cfg = cfg.override({"train.max_steps": 8})
    t = Trainer(cfg)
    t.run()
    leaves = jax.tree.leaves(jax.device_get(t.state.params))
    param_l1 = float(sum(np.abs(np.asarray(x), dtype=np.float64).sum()
                         for x in leaves))
    np.testing.assert_allclose(s0["param_l1"], param_l1, rtol=1e-6)


# Child for the cross-process TENSOR-PARALLEL cluster: params are
# Megatron-sharded over the model axis of a (replica=2, model=2) mesh
# spanning both processes, so no process can materialize the full
# arrays — the per-host sharded checkpoint format (train/checkpoint.py)
# is the only way to save. param_l1 is computed IN-PROGRAM (a jitted
# global reduction comes out replicated), since jax.device_get of
# non-addressable shards is exactly what multi-host TP forbids.
_CHILD_TP = """
import glob, json, os, sys
from distributedmnist_tpu.core.mesh import initialize_distributed, simulate_devices
simulate_devices(int(os.environ.get("DML_LOCAL_DEVICES", "2")))
initialize_distributed()
import jax
import jax.numpy as jnp
import numpy as np
from distributedmnist_tpu.core.config import ExperimentConfig
from distributedmnist_tpu.train.loop import Trainer

cfg = ExperimentConfig.from_dict(json.loads(os.environ["DML_CFG"]))
t = Trainer(cfg)
start_step = t._start_step
summary = t.run()
ev = t.evaluate()
l1 = jax.jit(lambda p: sum(jnp.sum(jnp.abs(l.astype(jnp.float32)))
                           for l in jax.tree.leaves(p)))(t.state.params)
shards = sorted(os.path.basename(f) for f in
                glob.glob(os.path.join(cfg.train.train_dir, "ckpt-*")))
print("RESULT " + json.dumps({
    "process_count": jax.process_count(),
    "start_step": start_step,
    "final_step": summary["final_step"],
    "loss": summary["last_metrics"]["loss"],
    "param_l1": float(l1),
    "eval_accuracy": ev["accuracy"],
    "eval_loss": ev["loss"],
    "ckpt_files": shards,
}))
"""


def _tp_cfg_dict(train_dir: str, max_steps: int) -> dict:
    return {
        "data": {"dataset": "synthetic_lm", "batch_size": 8,
                 "synthetic_train_size": 8, "synthetic_test_size": 8,
                 "use_native_pipeline": False},
        "model": {"name": "transformer", "compute_dtype": "float32",
                  "seq_len": 16, "model_dim": 32, "num_heads": 4,
                  "num_layers": 2, "vocab_size": 37,
                  "attention_impl": "dense", "dropout_rate": 0.0},
        "mesh": {"num_replicas": 2, "model_parallelism": 2},
        "optim": {"learning_rate_decay_factor": 1.0},
        "sync": {"mode": "sync", "straggler_profile": "none"},
        "eval": {"eval_batch_size": 8},
        "train": {"max_steps": max_steps, "log_every_steps": 2,
                  "save_interval_steps": 0, "save_results_period": 0,
                  "train_dir": train_dir},
    }


@pytest.mark.slow  # boots real gloo worker processes (jaxlib-0.4.37 gloo crash)
def test_two_process_tp_sharded_save_kill_resume_and_eval(tmp_path):
    """The round-5 per-host checkpoint proof (SURVEY §2.3 'per-host
    array serialization'): a live 2-process cluster with params
    TENSOR-SHARDED across it trains, writes the sharded checkpoint
    (one shard file per process + manifest), dies, resumes exactly,
    and the checkpoint is then evaluated LIVE by the standalone
    evaluator on its own single-process mesh — the reassembly path a
    DP-only format cannot provide."""
    shared = str(tmp_path / "mh_tp_shared")

    r0, r1 = _launch(tmp_path,
                     [_tp_cfg_dict(shared, 4), _tp_cfg_dict(shared, 4)],
                     child=_CHILD_TP, local_devices=2)
    assert r0["start_step"] == r1["start_step"] == 0
    assert r0["final_step"] == r1["final_step"] == 4
    np.testing.assert_allclose(r0["loss"], r1["loss"], rtol=1e-6)
    # the sharded layout really engaged: one shard per process + manifest
    assert any("shard000-of-002" in f for f in r0["ckpt_files"]), r0["ckpt_files"]
    assert any("shard001-of-002" in f for f in r0["ckpt_files"])
    assert any("manifest" in f for f in r0["ckpt_files"])
    assert not any(f.endswith("ckpt-00000004.msgpack") for f in r0["ckpt_files"])

    s0, s1 = _launch(tmp_path,
                     [_tp_cfg_dict(shared, 8), _tp_cfg_dict(shared, 8)],
                     child=_CHILD_TP, local_devices=2)
    for s in (s0, s1):
        assert s["start_step"] == 4, "resume must reassemble the shards"
        assert s["final_step"] == 8
    np.testing.assert_allclose(s0["param_l1"], s1["param_l1"], rtol=1e-6)

    # exact-resume oracle: one uninterrupted single-process run on the
    # SAME logical mesh (4 of this process's devices)
    import jax
    import jax.numpy as jnp
    from distributedmnist_tpu.train.loop import Trainer
    cfg = base_config(**_tp_cfg_dict(str(tmp_path / "tp_oracle"), 8))
    t = Trainer(cfg)
    t.run()
    ev = t.evaluate()
    l1 = float(jax.jit(lambda p: sum(jnp.sum(jnp.abs(l.astype(jnp.float32)))
                                     for l in jax.tree.leaves(p)))(t.state.params))
    np.testing.assert_allclose(s0["param_l1"], l1, rtol=1e-6)
    np.testing.assert_allclose(s0["eval_loss"], ev["loss"], rtol=1e-5,
                               atol=1e-6)

    # LIVE evaluation of the sharded checkpoint by the standalone
    # evaluator service (full-mesh mode, config bootstrapped from the
    # checkpoint manifest itself)
    from distributedmnist_tpu.core.config import EvalConfig
    from distributedmnist_tpu.evalsvc import Evaluator
    evs = Evaluator(shared, EvalConfig(eval_dir=str(tmp_path / "tp_eval"),
                                       run_once=True))
    rec = evs.evaluate_checkpoint()
    assert rec is not None and rec["step"] == 8
    np.testing.assert_allclose(rec["loss"], ev["loss"], rtol=1e-5,
                               atol=1e-6)
