"""Contribution-mask policy tests (≙ the reference's three aggregation
disciplines, SURVEY §2.2, as pure mask math)."""

import pytest

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from distributedmnist_tpu.core import prng
from distributedmnist_tpu.core.config import SyncConfig
from distributedmnist_tpu.parallel import policies

pytestmark = pytest.mark.tier1


def _flags_for_times(topo8, times, k):
    def fn(t):
        return policies.quorum_flag(t[0], k, "replica")[None]

    return np.asarray(jax.jit(jax.shard_map(
        fn, mesh=topo8.mesh, in_specs=(P("replica"),),
        out_specs=P("replica")))(jnp.asarray(times, jnp.float32)))


def test_quorum_selects_exactly_k_fastest(topo8):
    times = [5.0, 1.0, 9.0, 3.0, 7.0, 2.0, 8.0, 4.0]
    flags = _flags_for_times(topo8, times, k=3)
    assert flags.sum() == 3
    # fastest three are replicas 1 (1.0), 5 (2.0), 3 (3.0)
    np.testing.assert_array_equal(flags, [0, 1, 0, 1, 0, 1, 0, 0])


def test_quorum_exact_k_under_ties(topo8):
    flags = _flags_for_times(topo8, [1.0] * 8, k=5)
    assert flags.sum() == 5  # lexicographic (time, id) tie-break
    np.testing.assert_array_equal(flags, [1, 1, 1, 1, 1, 0, 0, 0])


def test_quorum_k_equals_n_is_full_sync(topo8):
    flags = _flags_for_times(topo8, [3.0, 1.0, 4.0, 1.0, 5.0, 9.0, 2.0, 6.0], k=8)
    assert flags.sum() == 8


def test_timeout_flag():
    assert float(policies.timeout_flag(jnp.float32(10.0), 50.0)) == 1.0
    assert float(policies.timeout_flag(jnp.float32(51.0), 50.0)) == 0.0


def test_resolve_aggregate_k():
    assert policies.resolve_aggregate_k(SyncConfig(), 8) == 8  # -1 → n
    assert policies.resolve_aggregate_k(
        SyncConfig(num_replicas_to_aggregate=3), 8) == 3


def test_straggler_profiles_deterministic():
    root = prng.root_key(0)
    for profile in ("none", "lognormal", "spike"):
        cfg = SyncConfig(straggler_profile=profile)
        a = float(policies.sample_step_time_ms(cfg, root, 3, 2, jnp.float32(0)))
        b = float(policies.sample_step_time_ms(cfg, root, 3, 2, jnp.float32(0)))
        assert a == b, profile
    # continuous profiles vary step to step (spike only on spike steps)
    for profile in ("none", "lognormal"):
        cfg = SyncConfig(straggler_profile=profile)
        a = float(policies.sample_step_time_ms(cfg, root, 3, 2, jnp.float32(0)))
        c = float(policies.sample_step_time_ms(cfg, root, 4, 2, jnp.float32(0)))
        assert a != c, f"{profile}: time must vary across steps"
    # spike profile spikes at its configured rate
    cfg = SyncConfig(straggler_profile="spike", straggler_spike_prob=0.3)
    ts = [float(policies.sample_step_time_ms(cfg, root, s, 0, jnp.float32(0)))
          for s in range(100)]
    n_spikes = sum(t > cfg.straggler_mean_ms * 2 for t in ts)
    assert 10 <= n_spikes <= 60


def test_spike_profile_tail_magnitude():
    """Spike steps realize at exactly mean × spike_scale — the 8x
    stall the adaptive-discipline bench's straggler phases model."""
    cfg = SyncConfig(straggler_profile="spike", straggler_mean_ms=50.0,
                     straggler_spike_prob=0.3, straggler_spike_scale=8.0)
    root = prng.root_key(0)
    ts = np.array([
        float(policies.sample_step_time_ms(cfg, root, s, 0, jnp.float32(0)))
        for s in range(100)])
    spiked = ts[ts > cfg.straggler_mean_ms * 2]
    assert len(spiked) > 0
    np.testing.assert_allclose(
        spiked, cfg.straggler_mean_ms * cfg.straggler_spike_scale)
    np.testing.assert_allclose(ts[ts <= cfg.straggler_mean_ms * 2],
                               cfg.straggler_mean_ms)


def test_traced_quorum_k_swaps_without_recompile(topo8):
    """The adaptive controller's contract at the policy layer: ``k`` is
    a traced operand, so retightening the quorum is a buffer swap into
    the SAME compiled executable — jit cache stays at one entry."""
    def fn(t, k):
        return policies.quorum_flag(t[0], k[0], "replica")[None]

    jitted = jax.jit(jax.shard_map(
        fn, mesh=topo8.mesh, in_specs=(P("replica"), P()),
        out_specs=P("replica")))
    times = jnp.asarray([5.0, 1.0, 9.0, 3.0, 7.0, 2.0, 8.0, 4.0],
                        jnp.float32)
    for k, want in ((3.0, 3), (5.0, 5), (8.0, 8)):
        flags = np.asarray(jitted(times, jnp.asarray([k], jnp.float32)))
        assert flags.sum() == want, k
    assert jitted._cache_size() == 1


def test_lognormal_profile_statistics():
    cfg = SyncConfig(straggler_profile="lognormal", straggler_mean_ms=50.0,
                     straggler_sigma=0.5)
    root = prng.root_key(7)
    samples = np.array([
        float(policies.sample_step_time_ms(cfg, root, s, r, jnp.float32(0)))
        for s in range(64) for r in range(8)])
    assert samples.min() > 0
    # mean-preserving lognormal: E[t] = mean_ms
    assert 40.0 < samples.mean() < 60.0
    # heavy right tail
    assert np.percentile(samples, 99) > 2 * np.median(samples)


def test_measured_time_feeds_through():
    cfg = SyncConfig(straggler_profile="none")
    root = prng.root_key(0)
    t = float(policies.sample_step_time_ms(cfg, root, 0, 0, jnp.float32(123.0)))
    assert 123.0 <= t < 123.01  # base + sub-microsecond jitter


def test_delayed_replica_is_the_one_masked(topo8, synthetic_datasets, tmp_path):
    """End-to-end per-replica timing: with no synthetic straggler model,
    the quorum mask must select on the REAL measured timing vector — an
    artificially delayed replica is exactly the one masked every step
    (≙ measured per-worker CDF timing driving backup-worker selection,
    src/timeout_manager.py:48-61 + arXiv:1604.00981 semantics)."""
    from distributedmnist_tpu.train.loop import Trainer
    from tests.conftest import base_config

    cfg = base_config(
        sync={"mode": "quorum", "num_replicas_to_aggregate": 7,
              "straggler_profile": "none"},
        train={"max_steps": 4, "log_every_steps": 1,
               "save_interval_steps": 0, "save_results_period": 0,
               "train_dir": str(tmp_path / "train")},
    )
    trainer = Trainer(cfg, topo=topo8, datasets=synthetic_datasets)
    delay = np.zeros(topo8.local_replica_count, np.float32)
    delay[3] = 5000.0  # replica 3 is a severe straggler
    trainer.delay_injection_ms = delay

    records = []
    trainer.run(step_callback=lambda s, r: records.append(r))
    assert len(records) == 4
    for r in records:
        assert r["flags"][3] == 0, r  # the delayed replica is masked
        assert sum(r["flags"]) == 7   # everyone else contributes
        assert r["num_contributors"] == 7.0


def test_measured_timing_unsupported_on_uneven_meshes(topo8, monkeypatch):
    """When replicas don't split evenly over processes (e.g. cross-host
    TP with num_replicas < processes) per-host measured timing has no
    well-defined owner: device_put_measured must refuse, while the
    zeros default (identical everywhere) still works."""
    import jax as _jax
    monkeypatch.setattr(_jax, "process_count", lambda: 3)
    assert not topo8.measured_timing_supported
    with np.testing.assert_raises(ValueError):
        topo8.device_put_measured(np.zeros(2, np.float32))
    # the zeros default must work even on the uneven mesh (identical
    # values whoever materializes them) — asserted BEFORE undo
    z = topo8.zeros_measured()
    assert z.shape == (8,)
    np.testing.assert_array_equal(np.asarray(z), np.zeros(8))


def test_measured_stage_matches_one_shot_put(topo8):
    """MeasuredStage (the train loop's per-step staging) must place the
    identical [n] vector device_put_measured would — validated once,
    sharding cached, buffer reused across steps."""
    stage = topo8.measured_stage()
    v = np.arange(8, dtype=np.float32) * 1.5
    np.testing.assert_array_equal(np.asarray(stage.put(v)),
                                  np.asarray(topo8.device_put_measured(v)))
    # through the reusable assembly buffer, twice — the second write
    # must not corrupt the first staged vector
    stage.buffer[:] = 3.0
    a = stage.put()
    stage.buffer[:] = 7.0
    b = stage.put()
    np.testing.assert_array_equal(np.asarray(a), np.full(8, 3.0, np.float32))
    np.testing.assert_array_equal(np.asarray(b), np.full(8, 7.0, np.float32))
    with np.testing.assert_raises(ValueError):
        stage.put(np.zeros(3, np.float32))


def test_measured_stage_reuses_zero_buffer(topo8):
    """The all-zeros vector (no injection, no skew) is staged once and
    the same device buffer handed back — no per-step H2D at all."""
    stage = topo8.measured_stage()
    stage.buffer[:] = 0.0
    z1 = stage.put()
    z2 = stage.put(np.zeros(8, np.float32))
    assert z1 is z2
    np.testing.assert_array_equal(np.asarray(z1), np.zeros(8))


def test_measured_stage_refuses_uneven_mesh(topo8, monkeypatch):
    import jax as _jax
    monkeypatch.setattr(_jax, "process_count", lambda: 3)
    with np.testing.assert_raises(ValueError):
        topo8.measured_stage()
