"""Mixture-of-experts + expert parallelism: routing math vs a manual
per-token loop, EP-sharded execution vs the dense-MoE oracle (forward
and one-step update), capacity-overflow behavior, and Trainer e2e."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from conftest import (LOSS_TOL, assert_update_parity,
                      base_config)
from distributedmnist_tpu.core.config import MeshConfig
from distributedmnist_tpu.core.mesh import make_topology
from distributedmnist_tpu.models import transformer
from distributedmnist_tpu.models.registry import get_model
from distributedmnist_tpu.ops.moe import moe_ffn
from distributedmnist_tpu.parallel.api import (build_train_step,
                                               init_train_state,
                                               state_partition_specs)
from distributedmnist_tpu.train.lr_schedule import constant

LR = 0.1
E, D, FF = 4, 8, 16


def _moe_weights(key):
    ks = jax.random.split(key, 3)
    return (jax.random.normal(ks[0], (D, E)) * 0.5,
            jax.random.normal(ks[1], (E, D, FF)) * 0.1,
            jax.random.normal(ks[2], (E, FF, D)) * 0.1)


def test_moe_ffn_matches_per_token_loop():
    router, w1, w2 = _moe_weights(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 6, D))
    out, aux = moe_ffn(x, router, w1, w2, num_experts=E,
                       capacity_factor=8.0)  # capacity: nothing dropped
    xf = np.asarray(x).reshape(-1, D)
    probs = jax.nn.softmax(xf @ np.asarray(router), axis=-1)
    want = np.zeros_like(xf)
    for t in range(xf.shape[0]):
        e = int(np.argmax(probs[t]))
        h = np.maximum(xf[t] @ np.asarray(w1)[e], 0.0)
        want[t] = float(probs[t, e]) * (h @ np.asarray(w2)[e])
    np.testing.assert_allclose(np.asarray(out).reshape(-1, D), want,
                               rtol=1e-4, atol=1e-5)
    assert float(aux) > 0.0 and np.isfinite(float(aux))


def test_capacity_overflow_drops_tokens():
    _, w1, w2 = _moe_weights(jax.random.PRNGKey(2))
    # positive inputs + positive router column 0 → every token routes
    # to expert 0 → capacity ceil(cf*t/E) overflows
    router = jnp.zeros((D, E)).at[:, 0].set(1.0)
    x = jnp.abs(jax.random.normal(jax.random.PRNGKey(3), (1, 8, D))) + 0.1
    out, _ = moe_ffn(x, router, w1, w2, num_experts=E, capacity_factor=1.0)
    # capacity = ceil(1.0 * 8 / 4) = 2 → tokens 2..7 dropped (zero output)
    norms = np.linalg.norm(np.asarray(out)[0], axis=-1)
    assert (norms[:2] > 1e-6).all()
    assert np.allclose(norms[2:], 0.0, atol=1e-6)


def test_ep_capacity_is_shard_local():
    """Under EP the capacity budget is per token GROUP (ops/moe.py):
    with every token routed to expert 0 and cf=1.0, each of the 4
    groups keeps ceil(t_g/E)=1 token — its first — where the dense
    oracle keeps the first ceil(t/E)=4 tokens overall. The documented
    GShard shard-local-capacity trade, asserted."""
    _, w1, w2 = _moe_weights(jax.random.PRNGKey(2))
    router = jnp.zeros((D, E)).at[:, 0].set(1.0)
    x = jnp.abs(jax.random.normal(jax.random.PRNGKey(3), (1, 16, D))) + 0.1

    topo = make_topology(MeshConfig(num_replicas=1, expert_parallelism=4))
    axis = topo.expert_axis

    def fn(x, router, w1, w2):
        return moe_ffn(x, router, w1, w2, num_experts=E,
                       capacity_factor=1.0, expert_axis=axis)

    out, _ = jax.jit(jax.shard_map(
        fn, mesh=topo.mesh,
        in_specs=(P(), P(), P(axis), P(axis)),
        out_specs=(P(), P())))(x, router, w1, w2)
    norms = np.linalg.norm(np.asarray(out)[0], axis=-1)
    kept = norms > 1e-6
    # groups are contiguous 4-token slices; each keeps exactly its first
    assert kept.tolist() == [True, False, False, False] * 4


def test_ep_matches_unsharded():
    # With an EXPLICIT num_groups the routing math is mesh-invariant
    # (ops/moe.py): the EP-sharded dispatch must equal the dense oracle
    # EXACTLY — output AND aux — including with BINDING capacity, since
    # both paths route the same fixed per-row groups.
    router, w1, w2 = _moe_weights(jax.random.PRNGKey(4))
    x = jax.random.normal(jax.random.PRNGKey(5), (2, 8, D))

    topo = make_topology(MeshConfig(num_replicas=1, expert_parallelism=4))
    axis = topo.expert_axis

    for cf in (4.0, 1.0):  # loose AND binding capacity
        want, want_aux = moe_ffn(x, router, w1, w2, num_experts=E,
                                 capacity_factor=cf, num_groups=4)

        def fn(x, router, w1, w2):
            return moe_ffn(x, router, w1, w2, num_experts=E,
                           capacity_factor=cf, num_groups=4,
                           expert_axis=axis)

        got, got_aux = jax.jit(jax.shard_map(
            fn, mesh=topo.mesh,
            in_specs=(P(), P(), P(axis), P(axis)),
            out_specs=(P(), P())))(x, router, w1, w2)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(float(got_aux), float(want_aux),
                                   rtol=1e-6)


def test_ep_tp_matches_unsharded():
    """EP×TP: experts over the expert axis AND every expert's hidden
    dim Megatron-sharded over the model axis; one fused psum over both
    reassembles the unsharded result."""
    router, w1, w2 = _moe_weights(jax.random.PRNGKey(8))
    x = jax.random.normal(jax.random.PRNGKey(9), (2, 8, D))
    want, want_aux = moe_ffn(x, router, w1, w2, num_experts=E,
                             capacity_factor=4.0, num_groups=2)

    topo = make_topology(MeshConfig(num_replicas=1, model_parallelism=2,
                                    expert_parallelism=2))
    e_ax, m_ax = topo.expert_axis, topo.model_axis

    def fn(x, router, w1, w2):
        return moe_ffn(x, router, w1, w2, num_experts=E,
                       capacity_factor=4.0, num_groups=2,
                       expert_axis=e_ax, tp_axis=m_ax)

    got, got_aux = jax.jit(jax.shard_map(
        fn, mesh=topo.mesh,
        in_specs=(P(), P(), P(e_ax, None, m_ax), P(e_ax, m_ax, None)),
        out_specs=(P(), P())))(x, router, w1, w2)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(float(got_aux), float(want_aux), rtol=1e-6)


def test_bf16_compute_dtype():
    """MoE FFN runs in the compute dtype (routing stays f32)."""
    router, w1, w2 = (w.astype(jnp.bfloat16)
                      for w in _moe_weights(jax.random.PRNGKey(6)))
    x = jax.random.normal(jax.random.PRNGKey(7), (2, 8, D), jnp.bfloat16)
    out, aux = moe_ffn(x, router, w1, w2, num_experts=E, capacity_factor=4.0)
    assert out.dtype == jnp.bfloat16
    assert aux.dtype == jnp.float32
    ref, _ = moe_ffn(*(v.astype(jnp.float32) for v in (x, router, w1, w2)),
                     num_experts=E, capacity_factor=4.0)
    np.testing.assert_allclose(np.asarray(out, np.float32), np.asarray(ref),
                               atol=0.15, rtol=0.15)


def _cfg(n_replicas=1):
    return base_config(
        data={"dataset": "synthetic_lm", "batch_size": 4 * n_replicas},
        model={"name": "transformer", "compute_dtype": "float32",
               "seq_len": 16, "model_dim": 16, "num_heads": 2,
               "num_layers": 2, "vocab_size": 31, "attention_impl": "dense",
               # moe_num_groups EXPLICIT → identical routing math on
               # every mesh in the parametrize grid (and in the dense
               # oracle), drops included; cf=4 keeps capacity loose so
               # update parity is about dispatch, not drop patterns
               "num_experts": 4, "expert_capacity_factor": 4.0,
               "moe_num_groups": 4},
        sync={"mode": "sync", "straggler_profile": "none"},
    )


def _tokens(cfg, key=0):
    b, s = cfg.data.batch_size, cfg.model.seq_len
    toks = jax.random.randint(jax.random.PRNGKey(key), (b, s), 0,
                              cfg.model.vocab_size)
    return {"image": toks, "label": toks}


def _dense_moe_update(cfg, batch):
    model = get_model(cfg.model)
    params = model.init(jax.random.PRNGKey(cfg.model.init_seed))

    def loss_fn(p):
        logits, aux = transformer.apply(
            p, batch["image"], num_heads=cfg.model.num_heads,
            compute_dtype=jnp.float32, num_experts=cfg.model.num_experts,
            capacity_factor=cfg.model.expert_capacity_factor,
            moe_num_groups=cfg.model.moe_num_groups,
            moe_router_top_k=cfg.model.moe_router_top_k,
            return_aux=True)
        return (transformer.loss_fn(logits, batch["label"])
                + cfg.model.moe_aux_weight * aux)

    loss, grads = jax.value_and_grad(loss_fn)(params)
    return loss, jax.tree.map(lambda p, g: p - LR * g, params, grads)


@pytest.mark.parametrize("n_replicas,n_expert,n_model,n_seq", [
    (1, 4, 1, 1),   # pure EP
    (2, 2, 1, 1),   # DP×EP
    (1, 2, 2, 1),   # EP×TP: experts AND their hidden dims sharded
    (2, 1, 2, 1),   # DP×TP on a MoE model (all experts on every rank)
    (1, 2, 1, 2),   # SP×EP: seq-sharded tokens through grouped dispatch
    (1, 2, 2, 2),   # SP×EP×TP: all three model-side axes at once
])
def test_ep_step_matches_dense_update(n_replicas, n_expert, n_model, n_seq):
    cfg = _cfg(n_replicas=n_replicas)
    batch = _tokens(cfg)
    want_loss, want_params = _dense_moe_update(cfg, batch)

    topo = make_topology(MeshConfig(num_replicas=n_replicas,
                                    model_parallelism=n_model,
                                    expert_parallelism=n_expert,
                                    seq_parallelism=n_seq))
    model = get_model(cfg.model)
    specs = state_partition_specs(model, cfg, topo)
    state = topo.device_put_state(init_train_state(model, cfg, topo), specs)
    step_fn = build_train_step(model, cfg, topo, constant(LR))
    state, metrics = step_fn(state, topo.device_put_batch(batch,
                                                          seq_sharded=True))
    np.testing.assert_allclose(float(metrics["loss"]), float(want_loss),
                               **LOSS_TOL)  # 2e-4 under the check_rep shim
    got = jax.device_get(state.params)
    assert_update_parity(got, want_params)


@pytest.mark.parametrize("n_replicas,n_stage,n_expert,n_model,microbatches", [
    (1, 2, 2, 1, 2),   # PP×EP: experts sharded inside pipeline stages
    (1, 2, 2, 1, 4),   # more microbatches → smaller microbatch-local groups
    (2, 2, 1, 1, 2),   # DP×PP on the MoE model (all experts on every stage)
    (1, 2, 2, 2, 2),   # PP×EP×TP: layer × expert × hidden-slice sharding
])
def test_pp_ep_step_matches_dense_update(n_replicas, n_stage, n_expert,
                                         n_model, microbatches):
    """MoE through the pipeline: per-tick grouped dispatch over fixed
    per-row groups (microbatch-split-invariant), per-tick aux
    accumulated across the real ticks (bubbles excluded) — must equal
    the dense single-device update exactly."""
    cfg = _cfg(n_replicas=n_replicas)
    batch = _tokens(cfg)
    want_loss, want_params = _dense_moe_update(cfg, batch)

    topo = make_topology(MeshConfig(num_replicas=n_replicas,
                                    pipeline_parallelism=n_stage,
                                    pipeline_microbatches=microbatches,
                                    expert_parallelism=n_expert,
                                    model_parallelism=n_model))
    model = get_model(cfg.model)
    specs = state_partition_specs(model, cfg, topo)
    state = topo.device_put_state(init_train_state(model, cfg, topo), specs)
    step_fn = build_train_step(model, cfg, topo, constant(LR))
    state, metrics = step_fn(state, topo.device_put_batch(batch))

    np.testing.assert_allclose(float(metrics["loss"]), float(want_loss),
                               **LOSS_TOL)  # 2e-4 under the check_rep shim
    got = jax.device_get(state.params)
    want_stacked = transformer.stack_block_params(want_params)
    assert_update_parity(got, want_stacked)


@pytest.mark.slow  # PP*EP Trainer e2e; superset coverage stays via test_trainer_end_to_end_pp_sp_ep
def test_trainer_end_to_end_pp_ep(tmp_train_dir):
    """Full Trainer on (replica=2, stage=2, expert=2): MoE pipeline
    training with quorum on the replica axis, eval through the M=1
    pipeline apply, resume with stacked expert-sharded params."""
    from distributedmnist_tpu.train.loop import Trainer

    cfg = _cfg(n_replicas=2).override({
        "mesh.num_replicas": 2, "mesh.pipeline_parallelism": 2,
        "mesh.pipeline_microbatches": 2, "mesh.expert_parallelism": 2,
        "sync.mode": "quorum", "sync.num_replicas_to_aggregate": 1,
        "sync.straggler_profile": "lognormal",
        "train.max_steps": 8, "train.train_dir": tmp_train_dir,
        "train.log_every_steps": 4, "train.save_interval_secs": 0,
        "train.save_interval_steps": 4,
    })
    tr = Trainer(cfg)
    assert tr.run()["final_step"] == 8
    ev = tr.evaluate("test")
    assert np.isfinite(ev["loss"])
    tr2 = Trainer(cfg.override({"train.max_steps": 10}))
    assert tr2._start_step == 8
    assert tr2.run()["final_step"] == 10


def test_trainer_end_to_end_pp_sp_ep(tmp_train_dir):
    """Full Trainer at (stage=2, seq=2, expert=2): seq-sharded batches
    through the MoE pipeline, eval, and checkpoint/resume."""
    from distributedmnist_tpu.train.loop import Trainer

    cfg = _cfg(n_replicas=1).override({
        "mesh.num_replicas": 1, "mesh.pipeline_parallelism": 2,
        "mesh.pipeline_microbatches": 2, "mesh.seq_parallelism": 2,
        "mesh.expert_parallelism": 2,
        "train.max_steps": 6, "train.train_dir": tmp_train_dir,
        "train.log_every_steps": 3, "train.save_interval_secs": 0,
        "train.save_interval_steps": 3,
    })
    tr = Trainer(cfg)
    assert tr.run()["final_step"] == 6
    ev = tr.evaluate("test")
    assert np.isfinite(ev["loss"])
    tr2 = Trainer(cfg.override({"train.max_steps": 8}))
    assert tr2._start_step == 6
    assert tr2.run()["final_step"] == 8


def test_pp_sp_ep_step_matches_dense_update():
    """The full stack at once — PP (layer stages) × SP (seq-sharded
    tokens, ring attention lockstep in the pipeline scan) × EP (grouped
    expert dispatch): per-tick routing stats pmean over (expert, seq)
    and accumulate over real ticks, the SP partial loss pre-divides the
    replicated aux — everything must still reproduce the dense
    single-device update exactly."""
    cfg = _cfg(n_replicas=1)
    batch = _tokens(cfg)
    want_loss, want_params = _dense_moe_update(cfg, batch)

    topo = make_topology(MeshConfig(num_replicas=1, pipeline_parallelism=2,
                                    pipeline_microbatches=2,
                                    seq_parallelism=2, expert_parallelism=2))
    model = get_model(cfg.model)
    specs = state_partition_specs(model, cfg, topo)
    state = topo.device_put_state(init_train_state(model, cfg, topo), specs)
    step_fn = build_train_step(model, cfg, topo, constant(LR))
    state, metrics = step_fn(state, topo.device_put_batch(batch,
                                                          seq_sharded=True))

    np.testing.assert_allclose(float(metrics["loss"]), float(want_loss),
                               **LOSS_TOL)  # 2e-4 under the check_rep shim
    got = jax.device_get(state.params)
    want_stacked = transformer.stack_block_params(want_params)
    assert_update_parity(got, want_stacked)


def test_top2_matches_two_expert_oracle():
    """GShard top-2 routing vs a manual per-token two-expert loop:
    renormalized gates g_i/(g1+g2), capacity non-binding."""
    router, w1, w2 = _moe_weights(jax.random.PRNGKey(10))
    x = jax.random.normal(jax.random.PRNGKey(11), (2, 6, D))
    out, aux = moe_ffn(x, router, w1, w2, num_experts=E,
                       capacity_factor=8.0, router_top_k=2)
    xf = np.asarray(x).reshape(-1, D)
    probs = np.asarray(jax.nn.softmax(xf @ np.asarray(router), axis=-1))
    want = np.zeros_like(xf)
    for t in range(xf.shape[0]):
        order = np.argsort(-probs[t])
        e1, e2 = int(order[0]), int(order[1])
        g1, g2 = probs[t, e1], probs[t, e2]
        for ei, gi in ((e1, g1), (e2, g2)):
            h = np.maximum(xf[t] @ np.asarray(w1)[ei], 0.0)
            want[t] += (gi / (g1 + g2)) * (h @ np.asarray(w2)[ei])
    np.testing.assert_allclose(np.asarray(out).reshape(-1, D), want,
                               rtol=1e-4, atol=1e-5)
    assert np.isfinite(float(aux))


def _top2_oracle(x2d, router, w1, w2, e, cap):
    """Independent numpy implementation of the documented GShard top-2
    semantics: sequential queue filling (round-2 positions offset by
    ALL round-1 claims, kept or dropped), renormalized gates."""
    probs = np.asarray(jax.nn.softmax(x2d @ np.asarray(router), axis=-1))
    t = x2d.shape[0]
    order = np.argsort(-probs, axis=-1)
    e1, e2 = order[:, 0], order[:, 1]
    claims = np.zeros(e, int)
    kept1 = np.zeros(t, bool)
    for i in range(t):            # round 1 arrival order
        kept1[i] = claims[e1[i]] < cap
        claims[e1[i]] += 1
    pos2_base = claims.copy()     # round 2 starts after ALL round-1 claims
    kept2 = np.zeros(t, bool)
    for i in range(t):
        kept2[i] = pos2_base[e2[i]] < cap
        pos2_base[e2[i]] += 1
    want = np.zeros_like(x2d)
    for i in range(t):
        g1, g2 = probs[i, e1[i]], probs[i, e2[i]]
        denom = g1 + g2
        if kept1[i]:
            h = np.maximum(x2d[i] @ np.asarray(w1)[e1[i]], 0.0)
            want[i] += (g1 / denom) * (h @ np.asarray(w2)[e1[i]])
        if kept2[i]:
            h = np.maximum(x2d[i] @ np.asarray(w1)[e2[i]], 0.0)
            want[i] += (g2 / denom) * (h @ np.asarray(w2)[e2[i]])
    return want, kept1, kept2


def test_top2_overflow_to_second_choice():
    """A token whose first choice overflows still flows through its
    second choice, and round-2 queue positions start after round-1's
    claims — pinned against an independent numpy implementation of the
    GShard semantics on a construction where both effects bind."""
    _, w1, w2 = _moe_weights(jax.random.PRNGKey(12))
    # build inputs whose router logits we control exactly: three token
    # kinds via directions u, v, w in the first 3 coords
    router = jnp.zeros((D, E))
    router = router.at[0, :].set(jnp.asarray([2.0, 1.0, 0.0, -9.0]))
    router = router.at[1, :].set(jnp.asarray([2.0, 0.0, 1.0, -9.0]))
    router = router.at[2, :].set(jnp.asarray([0.0, 2.0, 1.0, -9.0]))
    rows = ([[1.0, 0, 0] + [0.0] * (D - 3)] * 4      # first e0, second e1
            + [[0, 1.0, 0] + [0.0] * (D - 3)] * 4    # first e0, second e2
            + [[0, 0, 1.0] + [0.0] * (D - 3)] * 2)   # first e1, second e2
    x = jnp.asarray([rows])                          # [1, 10, D]
    # gs=10, top-2 cap = ceil(1.0·2·10/4) = 5:
    # e0 round-1 claims 8 → tokens 5-7 overflow their FIRST choice but
    #   keep their second (e2, offset 0) — overflow-to-second-choice;
    # e1 round-1 claims 2 (tokens 8,9) → u-tokens' round-2 queue on e1
    #   starts at position 2 → token 3's pos 5 ≥ cap — the round-2
    #   offset binding.
    out, _ = moe_ffn(x, router, w1, w2, num_experts=E,
                     capacity_factor=1.0, router_top_k=2)
    want, kept1, kept2 = _top2_oracle(np.asarray(x)[0], router, w1, w2,
                                      E, cap=5)
    # the construction really exercises both effects:
    assert not kept1[5:8].any() and kept2[5:8].all()   # overflow → 2nd
    assert kept2[:3].all() and not kept2[3]            # offset binds at t=3
    np.testing.assert_allclose(np.asarray(out)[0], want,
                               rtol=1e-4, atol=1e-5)


def test_top2_ep_matches_unsharded():
    """Top-2 routing through the expert-parallel all-to-all dispatch ==
    the dense top-2 oracle, output and aux (explicit num_groups)."""
    router, w1, w2 = _moe_weights(jax.random.PRNGKey(14))
    x = jax.random.normal(jax.random.PRNGKey(15), (2, 8, D))
    want, want_aux = moe_ffn(x, router, w1, w2, num_experts=E,
                             capacity_factor=2.0, router_top_k=2,
                             num_groups=4)

    topo = make_topology(MeshConfig(num_replicas=1, expert_parallelism=4))
    axis = topo.expert_axis

    def fn(x, router, w1, w2):
        return moe_ffn(x, router, w1, w2, num_experts=E,
                       capacity_factor=2.0, router_top_k=2, num_groups=4,
                       expert_axis=axis)

    got, got_aux = jax.jit(jax.shard_map(
        fn, mesh=topo.mesh,
        in_specs=(P(), P(), P(axis), P(axis)),
        out_specs=(P(), P())))(x, router, w1, w2)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(float(got_aux), float(want_aux), rtol=1e-6)


@pytest.mark.parametrize("top_k", [1, 2])
def test_top_k_train_step_matches_dense(top_k):
    """The full train step with top-k routing on a DP×EP mesh equals
    the dense oracle update (the top-2 path through value_and_grad)."""
    cfg = _cfg(n_replicas=2).override({"model.moe_router_top_k": top_k})
    batch = _tokens(cfg)
    want_loss, want_params = _dense_moe_update(cfg, batch)

    topo = make_topology(MeshConfig(num_replicas=2, expert_parallelism=2))
    model = get_model(cfg.model)
    specs = state_partition_specs(model, cfg, topo)
    state = topo.device_put_state(init_train_state(model, cfg, topo), specs)
    step_fn = build_train_step(model, cfg, topo, constant(LR))
    state, metrics = step_fn(state, topo.device_put_batch(batch))
    np.testing.assert_allclose(float(metrics["loss"]), float(want_loss),
                               **LOSS_TOL)  # 2e-4 under the check_rep shim
    got = jax.device_get(state.params)
    assert_update_parity(got, want_params)


def test_pp_moe_eval_invariant_to_microbatch_count():
    """Eval metrics through the pipelined MoE apply must be IDENTICAL
    at every microbatch count — token groups nest inside rows, so the
    microbatch split cannot change routing (the round-4 M=1 force is
    gone)."""
    from distributedmnist_tpu.parallel.api import build_eval_step

    results = {}
    for m in (1, 4):
        cfg = _cfg(n_replicas=1).override({
            "mesh.num_replicas": 1, "mesh.pipeline_parallelism": 2,
            "mesh.expert_parallelism": 2, "mesh.pipeline_microbatches": m})
        topo = make_topology(cfg.mesh)
        model = get_model(cfg.model)
        state = init_train_state(model, cfg, topo)
        specs = state_partition_specs(model, cfg, topo)
        state = topo.device_put_state(state, specs)
        eval_fn = build_eval_step(model, cfg, topo)
        batch = _tokens(cfg)
        eb = {"image": batch["image"], "label": batch["label"],
              "weight": jnp.ones((cfg.data.batch_size,), jnp.float32)}
        correct, loss_sum, weight = eval_fn(state.params, topo.device_put_batch(eb))
        results[m] = (float(correct), float(loss_sum), float(weight))
    np.testing.assert_allclose(results[1], results[4], rtol=1e-6)


@pytest.mark.parametrize(
    "n_replicas,n_stage,n_expert,n_model,n_seq,chunks,microbatches", [
        (1, 2, 2, 1, 1, 2, 2),   # 1F1B × EP
        (2, 2, 2, 1, 1, 2, 2),   # DP × 1F1B × EP
        (1, 2, 2, 2, 1, 2, 2),   # 1F1B × EP × TP
        (1, 2, 2, 1, 2, 2, 2),   # 1F1B × SP × EP (Ulysses)
    ])
def test_1f1b_ep_step_matches_dense_update(n_replicas, n_stage, n_expert,
                                           n_model, n_seq, chunks,
                                           microbatches):
    """MoE through the fused interleaved-1F1B engine: the per-row-group
    aux is linear across chunks/microbatches, so each chunk's aux
    accumulates on forward works and every backward chunk seeds its aux
    output with the constant weight (ops/pipeline.py with_aux) — the
    whole thing must reproduce the dense single-device update exactly,
    completing the composition matrix."""
    cfg = _cfg(n_replicas=n_replicas).override({
        "model.num_layers": 4,
        "model.sp_attention": "ulysses",
        "mesh.num_replicas": n_replicas,
        "mesh.pipeline_parallelism": n_stage,
        "mesh.expert_parallelism": n_expert,
        "mesh.model_parallelism": n_model,
        "mesh.seq_parallelism": n_seq,
        "mesh.pipeline_microbatches": microbatches,
        "mesh.pipeline_schedule": "1f1b",
        "mesh.pipeline_chunks": chunks})
    batch = _tokens(cfg)
    want_loss, want_params = _dense_moe_update(cfg, batch)

    topo = make_topology(cfg.mesh)
    model = get_model(cfg.model)
    specs = state_partition_specs(model, cfg, topo)
    state = topo.device_put_state(init_train_state(model, cfg, topo), specs)
    step_fn = build_train_step(model, cfg, topo, constant(LR))
    state, metrics = step_fn(state, topo.device_put_batch(batch,
                                                          seq_sharded=True))

    np.testing.assert_allclose(float(metrics["loss"]), float(want_loss),
                               **LOSS_TOL)  # 2e-4 under the check_rep shim
    got = jax.device_get(state.params)
    want_stacked = transformer.stack_block_params_chunked(
        want_params, n_stage, chunks)
    assert_update_parity(got, want_stacked)


def test_1f1b_moe_eval_matches_gpipe_eval():
    """Eval through the chunked forward ring with expert sharding must
    equal the gpipe pipeline eval on the same (re-ordered) params."""
    from distributedmnist_tpu.parallel.api import build_eval_step

    results = {}
    for schedule in ("gpipe", "1f1b"):
        cfg = _cfg(n_replicas=1).override({
            "model.num_layers": 4,
            "mesh.num_replicas": 1, "mesh.pipeline_parallelism": 2,
            "mesh.expert_parallelism": 2, "mesh.pipeline_microbatches": 2,
            "mesh.pipeline_schedule": schedule,
            "mesh.pipeline_chunks": 2 if schedule == "1f1b" else 1})
        topo = make_topology(cfg.mesh)
        model = get_model(cfg.model)
        state = init_train_state(model, cfg, topo)
        specs = state_partition_specs(model, cfg, topo)
        state = topo.device_put_state(state, specs)
        eval_fn = build_eval_step(model, cfg, topo)
        batch = _tokens(cfg)
        eb = {"image": batch["image"], "label": batch["label"],
              "weight": jnp.ones((cfg.data.batch_size,), jnp.float32)}
        correct, loss_sum, weight = eval_fn(state.params,
                                            topo.device_put_batch(eb))
        results[schedule] = (float(correct), float(loss_sum), float(weight))
    np.testing.assert_allclose(results["gpipe"], results["1f1b"], rtol=1e-6)


def test_ep_on_dense_model_rejected():
    """expert_parallelism on a model without experts must refuse, not
    silently waste the axis."""
    cfg = _cfg().override({"model.num_experts": 0})
    topo = make_topology(MeshConfig(num_replicas=1, expert_parallelism=2))
    with pytest.raises(ValueError, match="expert"):
        build_train_step(get_model(cfg.model), cfg, topo, constant(LR))


def test_trainer_end_to_end_ep(tmp_train_dir):
    from distributedmnist_tpu.train.loop import Trainer

    cfg = _cfg(n_replicas=2)
    cfg = cfg.override({
        "mesh.num_replicas": 2, "mesh.expert_parallelism": 4,
        "sync.mode": "quorum", "sync.num_replicas_to_aggregate": 1,
        "sync.straggler_profile": "lognormal",
        "train.max_steps": 10, "train.train_dir": tmp_train_dir,
        "train.log_every_steps": 5, "train.save_interval_secs": 0,
        "train.save_interval_steps": 5,
    })
    tr = Trainer(cfg)
    summary = tr.run()
    assert summary["final_step"] == 10
    ev = tr.evaluate("test")
    assert np.isfinite(ev["loss"])
