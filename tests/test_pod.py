"""Pod launcher: argv construction parity with the reference's
orchestrator subcommands (tools/tf_ec2.py:828-856), exercised through
the dry-run seam — no gcloud needed."""

import json
import os

import pytest

from distributedmnist_tpu.launch.pod import (PodConfig, PodError, PodManager,
                                             Runner)

pytestmark = pytest.mark.tier1


def _mgr(**cfg_kw):
    cfg = PodConfig(name="t", zone="z", project="p", **cfg_kw)
    return PodManager(cfg, Runner(dry_run=True))


def test_create_builds_gcloud_argv():
    m = _mgr(accelerator_type="v4-32", spot=True, setup_command="pip list")
    m.create()
    create, setup = m.runner.recorded
    assert create[:6] == ["gcloud", "compute", "tpus", "tpu-vm", "create", "t"]
    assert ["--zone", "z"] == create[6:8] and ["--project", "p"] == create[8:10]
    assert ["--accelerator-type", "v4-32"] == create[10:12]
    assert create[-1] == "--spot"
    assert setup[4] == "ssh" and setup[-1].endswith("pip list")
    assert ["--worker", "all"] in [setup[i:i + 2] for i in range(len(setup))]


def test_env_exports_precede_command():
    m = _mgr(env={"JAX_PLATFORMS": "tpu", "FLAG": "a b"})
    m.exec("echo hi")
    cmd = m.runner.recorded[0][-1]
    assert cmd.startswith("export JAX_PLATFORMS=tpu; export FLAG='a b'; ")
    assert cmd.endswith("echo hi")


def test_run_train_is_detached_with_logs():
    m = _mgr(train_command="python train.py", remote_outdir="/tmp/out")
    m.run_train()
    cmd = m.runner.recorded[0][-1]
    assert "mkdir -p /tmp/out" in cmd
    assert "nohup python train.py" in cmd
    assert "/tmp/out/train_stdout.log" in cmd and cmd.rstrip().endswith("&")


def test_kill_targets_single_worker():
    m = _mgr()
    m.kill_all(worker="3")
    argv = m.runner.recorded[0]
    i = argv.index("--worker")
    assert argv[i + 1] == "3"
    assert "pkill" in argv[-1]


def test_download_scp_shape():
    m = _mgr(remote_outdir="/tmp/out")
    m.download("/tmp/local", worker="0")
    argv = m.runner.recorded[0]
    assert argv[:5] == ["gcloud", "compute", "tpus", "tpu-vm", "scp"]
    assert "--recurse" in argv
    assert argv[-2] == "t:/tmp/out" and argv[-1] == "/tmp/local"


def test_clean_launch_and_run_sequence():
    m = _mgr()
    m.clean_launch_and_run()
    verbs = [a[4] for a in m.runner.recorded]
    assert verbs == ["delete", "create", "ssh"]


def test_config_file_roundtrip_and_unknown_key(tmp_path):
    p = tmp_path / "pod.json"
    p.write_text(json.dumps({"name": "x", "zone": "eu", "spot": True}))
    cfg = PodConfig.from_file(p)
    assert (cfg.name, cfg.zone, cfg.spot) == ("x", "eu", True)
    p.write_text(json.dumps({"nmae": "typo"}))
    with pytest.raises(PodError, match="nmae"):
        PodConfig.from_file(p)


def test_missing_binary_is_a_clear_error():
    # a name that cannot exist on PATH — never invokes a real gcloud
    with pytest.raises(PodError, match="gcloud"):
        Runner(dry_run=False).run(["dmt-no-such-binary-for-test"])


def test_poll_argv_tails_structured_log():
    m = _mgr(remote_outdir="/tmp/out")
    assert m.poll() is None  # dry-run: argv recorded, no result
    argv = m.runner.recorded[0]
    i = argv.index("--worker")
    assert argv[i + 1] == "0"
    assert "tail -n 3 /tmp/out/train_log.jsonl" in argv[-1]


def test_run_until_step_dry_run_sequence():
    m = _mgr()
    got = m.run_until_step(500)
    assert got == {"step": 500, "record": None, "dry_run": True}
    cmds = [a[-1] for a in m.runner.recorded]
    assert "nohup" in cmds[0]          # launch
    assert "tail -n 3" in cmds[1]      # exactly one poll (no spin)
    assert "pkill" in cmds[2]          # stop at step N
    assert len(cmds) == 3


class _ScriptedRunner(Runner):
    """Live-mode runner whose ssh polls return a scripted progression
    of train_log tails — the until-step loop's test seam."""

    def __init__(self, tails):
        super().__init__(dry_run=False)
        self.tails = list(tails)

    def run(self, argv, check=True, capture=False, **kw):
        self.recorded.append(list(argv))
        cmd = argv[-1]
        if "tail -n 3" in cmd:
            out = self.tails.pop(0) if self.tails else ""
            return type("R", (), {"stdout": out, "returncode": 0})()
        return type("R", (), {"stdout": "", "returncode": 0})()


def test_wait_until_step_follows_log_and_returns_at_target():
    tails = ["",                                        # log not there yet
             json.dumps({"step": 40, "loss": 1.0}),
             "{\"step\": 80, \"loss\"",                 # torn write → retry
             json.dumps({"step": 120, "loss": 0.2})]
    m = PodManager(PodConfig(name="t", zone="z", remote_outdir="/tmp/out"),
                   _ScriptedRunner(tails))
    got = m.wait_until_step(100, poll_secs=0.0)
    assert got["step"] == 120 and got["record"]["loss"] == 0.2
    polls = [a for a in m.runner.recorded if "tail -n 3" in a[-1]]
    assert len(polls) == 4


def test_wait_until_step_times_out_with_last_seen():
    m = PodManager(PodConfig(name="t", zone="z"),
                   _ScriptedRunner([json.dumps({"step": 7})] * 50))
    with pytest.raises(PodError, match=r"step 100.*last seen: 7"):
        m.wait_until_step(100, poll_secs=0.0, timeout_secs=0.0)


# ---------------------------------------------------------------------------
# stubbed `gcloud` on PATH: the same verbs as EXECUTED processes — every
# PodManager action below goes through a real subprocess.run of a real
# `gcloud` executable (a recording stub), no dry-run, no mocks
# (VERDICT gap #1's "stubbed gcloud smoke" recipe)
# ---------------------------------------------------------------------------

_GCLOUD_STUB = r"""#!/bin/sh
# Recording gcloud stub: append each invocation, answer the verbs the
# pod layer uses, optionally fail the first $GCLOUD_STUB_FAIL_FIRST
# calls (transient-outage rehearsal).
log="${GCLOUD_STUB_LOG:?}"
printf '%s\n' "$*" >> "$log"
if [ -n "${GCLOUD_STUB_FAIL_FIRST:-}" ] \
   && [ "$(wc -l < "$log")" -le "$GCLOUD_STUB_FAIL_FIRST" ]; then
    echo "stub: injected transient failure" >&2
    exit 1
fi
case "$*" in
  *" describe "*)  echo '{"state": "READY"}' ;;
  *"pgrep -c"*)    echo 0 ;;
  *"tail -n 3"*)   cat "${GCLOUD_STUB_POLL:-/dev/null}" 2>/dev/null ;;
esac
exit 0
"""


@pytest.fixture()
def gcloud_stub(tmp_path, monkeypatch):
    """Install a recording `gcloud` at the front of PATH; returns the
    invocation log path."""
    bindir = tmp_path / "bin"
    bindir.mkdir()
    stub = bindir / "gcloud"
    stub.write_text(_GCLOUD_STUB)
    stub.chmod(0o755)
    log = tmp_path / "gcloud_calls.log"
    monkeypatch.setenv("PATH", f"{bindir}:{os.environ['PATH']}")
    monkeypatch.setenv("GCLOUD_STUB_LOG", str(log))
    return log


def _live_mgr(tmp_path, **runner_kw):
    cfg = PodConfig(name="t", zone="z", project="p",
                    remote_outdir="/tmp/out")
    runner = Runner(journal=tmp_path / "journal.jsonl", **runner_kw)
    return PodManager(cfg, runner)


def test_stubbed_gcloud_full_lifecycle_executes(tmp_path, monkeypatch,
                                                gcloud_stub):
    """create → run → status → poll → download → delete, each verb a
    REAL subprocess.run of the PATH `gcloud` — the executed-process
    coverage the dry-run argv tests never had."""
    from distributedmnist_tpu.obsv.journal import summarize_journal
    poll_file = tmp_path / "poll.json"
    poll_file.write_text(json.dumps({"step": 120, "loss": 0.2}) + "\n")
    monkeypatch.setenv("GCLOUD_STUB_POLL", str(poll_file))
    m = _live_mgr(tmp_path)

    m.create()
    m.run_train()
    got = m.status()
    assert got["state"] == "READY" and got["idle"] is True
    assert m.poll() == {"step": 120, "record": {"step": 120, "loss": 0.2}}
    dest = tmp_path / "dl"
    m.download(dest)
    assert dest.is_dir()  # local side effect; the scp itself is stubbed
    m.delete()

    calls = gcloud_stub.read_text().splitlines()
    for want in ("compute tpus tpu-vm create t",
                 "compute tpus tpu-vm delete t",
                 "compute tpus tpu-vm describe t",
                 "compute tpus tpu-vm scp"):
        assert any(want in c for c in calls), want
    ssh_cmds = [c for c in calls if " ssh " in f" {c} "]
    assert any("nohup" in c for c in ssh_cmds)       # run_train
    assert any("pgrep -c" in c for c in ssh_cmds)    # status probe
    assert any("tail -n 3" in c for c in ssh_cmds)   # poll
    s = summarize_journal(m.runner.journal_path)
    assert s["failures"] == 0 and s["commands"] == len(calls)


def test_stubbed_gcloud_run_until_step_stops_run(tmp_path, monkeypatch,
                                                 gcloud_stub):
    """The benchmark-driver shape against executed processes: launch,
    poll the (scripted) remote log past the target, kill."""
    poll_file = tmp_path / "poll.json"
    poll_file.write_text(json.dumps({"step": 500}) + "\n")
    monkeypatch.setenv("GCLOUD_STUB_POLL", str(poll_file))
    m = _live_mgr(tmp_path)
    got = m.run_until_step(500, poll_secs=0.0)
    assert got["step"] == 500
    calls = gcloud_stub.read_text().splitlines()
    assert any("nohup" in c for c in calls)
    assert any("pkill" in c for c in calls)  # stopped at the target


def test_stubbed_gcloud_transient_failure_recovered_by_retry(
        tmp_path, monkeypatch, gcloud_stub):
    """A gcloud outage of 2 REAL nonzero-rc invocations is absorbed by
    the runner's retry budget; the third executes clean."""
    from distributedmnist_tpu.launch.exec import RetryPolicy
    from distributedmnist_tpu.obsv.journal import load_journal
    monkeypatch.setenv("GCLOUD_STUB_FAIL_FIRST", "2")
    m = _live_mgr(tmp_path,
                  retry=RetryPolicy(max_attempts=3, backoff_s=0.01,
                                    jitter_frac=0.0))
    m.delete()
    assert len(gcloud_stub.read_text().splitlines()) == 3
    recs = load_journal(m.runner.journal_path)
    assert [r["attempt"] for r in recs] == [1, 2, 3]
    assert [r["rc"] for r in recs] == [1, 1, 0]


def test_stubbed_gcloud_exhausted_retries_is_pod_error(tmp_path, monkeypatch,
                                                       gcloud_stub):
    monkeypatch.setenv("GCLOUD_STUB_FAIL_FIRST", "99")
    from distributedmnist_tpu.launch.exec import RetryPolicy
    m = _live_mgr(tmp_path, retry=RetryPolicy(max_attempts=2, backoff_s=0.0,
                                              jitter_frac=0.0))
    with pytest.raises(PodError, match="after 2 attempt"):
        m.create()


def test_cli_dry_run_prints_commands(capsys):
    from distributedmnist_tpu.launch.pod import main
    main(["create", "--dry-run"])
    out = capsys.readouterr().out
    cmds = json.loads(out)
    assert any(c.startswith("gcloud compute tpus tpu-vm create") for c in cmds)


def test_launch_cli_delegates_pod(capsys):
    from distributedmnist_tpu.launch.__main__ import main
    main(["pod", "delete", "--dry-run"])
    out = capsys.readouterr().out
    assert "delete" in out and "gcloud" in out
