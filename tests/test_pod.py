"""Pod launcher: argv construction parity with the reference's
orchestrator subcommands (tools/tf_ec2.py:828-856), exercised through
the dry-run seam — no gcloud needed."""

import json

import pytest

from distributedmnist_tpu.launch.pod import (PodConfig, PodError, PodManager,
                                             Runner)


def _mgr(**cfg_kw):
    cfg = PodConfig(name="t", zone="z", project="p", **cfg_kw)
    return PodManager(cfg, Runner(dry_run=True))


def test_create_builds_gcloud_argv():
    m = _mgr(accelerator_type="v4-32", spot=True, setup_command="pip list")
    m.create()
    create, setup = m.runner.recorded
    assert create[:6] == ["gcloud", "compute", "tpus", "tpu-vm", "create", "t"]
    assert ["--zone", "z"] == create[6:8] and ["--project", "p"] == create[8:10]
    assert ["--accelerator-type", "v4-32"] == create[10:12]
    assert create[-1] == "--spot"
    assert setup[4] == "ssh" and setup[-1].endswith("pip list")
    assert ["--worker", "all"] in [setup[i:i + 2] for i in range(len(setup))]


def test_env_exports_precede_command():
    m = _mgr(env={"JAX_PLATFORMS": "tpu", "FLAG": "a b"})
    m.exec("echo hi")
    cmd = m.runner.recorded[0][-1]
    assert cmd.startswith("export JAX_PLATFORMS=tpu; export FLAG='a b'; ")
    assert cmd.endswith("echo hi")


def test_run_train_is_detached_with_logs():
    m = _mgr(train_command="python train.py", remote_outdir="/tmp/out")
    m.run_train()
    cmd = m.runner.recorded[0][-1]
    assert "mkdir -p /tmp/out" in cmd
    assert "nohup python train.py" in cmd
    assert "/tmp/out/train_stdout.log" in cmd and cmd.rstrip().endswith("&")


def test_kill_targets_single_worker():
    m = _mgr()
    m.kill_all(worker="3")
    argv = m.runner.recorded[0]
    i = argv.index("--worker")
    assert argv[i + 1] == "3"
    assert "pkill" in argv[-1]


def test_download_scp_shape():
    m = _mgr(remote_outdir="/tmp/out")
    m.download("/tmp/local", worker="0")
    argv = m.runner.recorded[0]
    assert argv[:5] == ["gcloud", "compute", "tpus", "tpu-vm", "scp"]
    assert "--recurse" in argv
    assert argv[-2] == "t:/tmp/out" and argv[-1] == "/tmp/local"


def test_clean_launch_and_run_sequence():
    m = _mgr()
    m.clean_launch_and_run()
    verbs = [a[4] for a in m.runner.recorded]
    assert verbs == ["delete", "create", "ssh"]


def test_config_file_roundtrip_and_unknown_key(tmp_path):
    p = tmp_path / "pod.json"
    p.write_text(json.dumps({"name": "x", "zone": "eu", "spot": True}))
    cfg = PodConfig.from_file(p)
    assert (cfg.name, cfg.zone, cfg.spot) == ("x", "eu", True)
    p.write_text(json.dumps({"nmae": "typo"}))
    with pytest.raises(PodError, match="nmae"):
        PodConfig.from_file(p)


def test_missing_binary_is_a_clear_error():
    # a name that cannot exist on PATH — never invokes a real gcloud
    with pytest.raises(PodError, match="gcloud"):
        Runner(dry_run=False).run(["dmt-no-such-binary-for-test"])


def test_poll_argv_tails_structured_log():
    m = _mgr(remote_outdir="/tmp/out")
    assert m.poll() is None  # dry-run: argv recorded, no result
    argv = m.runner.recorded[0]
    i = argv.index("--worker")
    assert argv[i + 1] == "0"
    assert "tail -n 1 /tmp/out/train_log.jsonl" in argv[-1]


def test_run_until_step_dry_run_sequence():
    m = _mgr()
    got = m.run_until_step(500)
    assert got == {"step": 500, "record": None, "dry_run": True}
    cmds = [a[-1] for a in m.runner.recorded]
    assert "nohup" in cmds[0]          # launch
    assert "tail -n 1" in cmds[1]      # exactly one poll (no spin)
    assert "pkill" in cmds[2]          # stop at step N
    assert len(cmds) == 3


class _ScriptedRunner(Runner):
    """Live-mode runner whose ssh polls return a scripted progression
    of train_log tails — the until-step loop's test seam."""

    def __init__(self, tails):
        super().__init__(dry_run=False)
        self.tails = list(tails)

    def run(self, argv, check=True, capture=False):
        self.recorded.append(list(argv))
        cmd = argv[-1]
        if "tail -n 1" in cmd:
            out = self.tails.pop(0) if self.tails else ""
            return type("R", (), {"stdout": out, "returncode": 0})()
        return type("R", (), {"stdout": "", "returncode": 0})()


def test_wait_until_step_follows_log_and_returns_at_target():
    tails = ["",                                        # log not there yet
             json.dumps({"step": 40, "loss": 1.0}),
             "{\"step\": 80, \"loss\"",                 # torn write → retry
             json.dumps({"step": 120, "loss": 0.2})]
    m = PodManager(PodConfig(name="t", zone="z", remote_outdir="/tmp/out"),
                   _ScriptedRunner(tails))
    got = m.wait_until_step(100, poll_secs=0.0)
    assert got["step"] == 120 and got["record"]["loss"] == 0.2
    polls = [a for a in m.runner.recorded if "tail -n 1" in a[-1]]
    assert len(polls) == 4


def test_wait_until_step_times_out_with_last_seen():
    m = PodManager(PodConfig(name="t", zone="z"),
                   _ScriptedRunner([json.dumps({"step": 7})] * 50))
    with pytest.raises(PodError, match=r"step 100.*last seen: 7"):
        m.wait_until_step(100, poll_secs=0.0, timeout_secs=0.0)


def test_cli_dry_run_prints_commands(capsys):
    from distributedmnist_tpu.launch.pod import main
    main(["create", "--dry-run"])
    out = capsys.readouterr().out
    cmds = json.loads(out)
    assert any(c.startswith("gcloud compute tpus tpu-vm create") for c in cmds)


def test_launch_cli_delegates_pod(capsys):
    from distributedmnist_tpu.launch.__main__ import main
    main(["pod", "delete", "--dry-run"])
    out = capsys.readouterr().out
    assert "delete" in out and "gcloud" in out
