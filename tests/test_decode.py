"""Continuous-batching decode service: sampling math, paged-decode ==
full-context parity (dense and flash prefill), the DecodeReplica
end-to-end over real sockets (streaming, refill, admission, graceful
drain), deterministic swap-policy drives (pin / restart), and the
decode_swap replay invariant over handcrafted journals."""

import json
import shutil
import socket
import threading
import time
from pathlib import Path

import numpy as np
import pytest

LM_MODEL = {"name": "transformer", "seq_len": 64, "model_dim": 64,
            "num_heads": 4, "num_layers": 2, "vocab_size": 32,
            "compute_dtype": "float32", "attention_impl": "dense"}


# ---------------------------------------------------------------------------
# sampling (models/registry.sample_token)
# ---------------------------------------------------------------------------

@pytest.mark.tier1
def test_sample_token_greedy_is_argmax():
    import jax.numpy as jnp

    from distributedmnist_tpu.models.registry import sample_token

    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(size=(5, 32)).astype(np.float32))
    got = sample_token(logits)
    np.testing.assert_array_equal(np.asarray(got),
                                  np.argmax(np.asarray(logits), axis=-1))


@pytest.mark.tier1
def test_sample_token_temperature_to_zero_converges_to_greedy():
    import jax
    import jax.numpy as jnp

    from distributedmnist_tpu.models.registry import sample_token

    rng = np.random.default_rng(1)
    logits = jnp.asarray(rng.normal(size=(32,)).astype(np.float32))
    greedy = int(np.argmax(np.asarray(logits)))
    # tiny temperature: every key must sample the mode
    for seed in range(8):
        got = int(sample_token(logits, jax.random.PRNGKey(seed),
                               temperature=1e-6))
        assert got == greedy
    # top_k=1 is greedy at any temperature
    got = int(sample_token(logits, jax.random.PRNGKey(0),
                           temperature=5.0, top_k=1))
    assert got == greedy
    # missing key is a loud error, not a silent greedy fallback
    with pytest.raises(ValueError, match="PRNG key"):
        sample_token(logits, temperature=1.0)


@pytest.mark.tier1
def test_sample_token_top_k_restricts_support():
    import jax
    import jax.numpy as jnp

    from distributedmnist_tpu.models.registry import sample_token

    rng = np.random.default_rng(2)
    logits_np = rng.normal(size=(32,)).astype(np.float32)
    logits = jnp.asarray(logits_np)
    top3 = set(np.argsort(logits_np)[-3:].tolist())
    for seed in range(24):
        got = int(sample_token(logits, jax.random.PRNGKey(seed),
                               temperature=2.0, top_k=3))
        assert got in top3


# ---------------------------------------------------------------------------
# paged decode == full-context forward (the numerical core)
# ---------------------------------------------------------------------------

def _greedy_paged(model, params, prompt, n_new, *, block_size=8,
                  num_blocks=32, slot=1, num_slots=3):
    """Greedy-generate ``n_new`` tokens through the paged cache, using
    a non-zero slot in a wider-than-needed slot shape (the fixed
    compiled shape the replica runs)."""
    import functools

    import jax
    import jax.numpy as jnp

    from distributedmnist_tpu.servesvc.kv_cache import PagedKVCache

    L, H, HD = model.decode_cache_shape
    cache = PagedKVCache(L, num_blocks, block_size, H, HD,
                         max_blocks_per_seq=16, dtype=jnp.float32)
    plen = len(prompt)
    bucket = 16
    toks = np.zeros((1, bucket), np.int32)
    toks[0, :plen] = prompt
    logits, ks, vs = model.decode_prefill(params, jnp.asarray(toks))
    table = cache.alloc_sequence(plen + n_new)
    cache.write_prompt(table, ks[:, 0], vs[:, 0], plen)
    step = jax.jit(functools.partial(model.decode_step,
                                     block_size=block_size))
    gen = [int(jnp.argmax(logits[0, plen - 1]))]
    length = plen
    width = cache.max_blocks_per_seq
    for _ in range(n_new - 1):
        tokens = np.zeros(num_slots, np.int32)
        positions = np.zeros(num_slots, np.int32)
        lengths = np.zeros(num_slots, np.int32)
        tables = np.zeros((num_slots, width), np.int32)
        tokens[slot] = gen[-1]
        positions[slot] = length
        lengths[slot] = length + 1
        tables[slot] = table
        lg, cache.k, cache.v = step(
            params, jnp.asarray(tokens), jnp.asarray(positions),
            cache.k, cache.v, jnp.asarray(tables), jnp.asarray(lengths))
        length += 1
        gen.append(int(jnp.argmax(lg[slot])))
    return gen


@pytest.mark.tier1
def test_paged_decode_matches_full_context_greedy():
    """Greedy decode through the paged cache reproduces the argmax of
    the full-context forward token-for-token — the claim that one
    compiled decode shape serves any sequence length correctly."""
    import jax
    import jax.numpy as jnp

    from distributedmnist_tpu.core.config import ModelConfig
    from distributedmnist_tpu.models.registry import get_model

    model = get_model(ModelConfig(**LM_MODEL))
    params = model.init(jax.random.PRNGKey(0))
    prompt = [3, 7, 1, 9, 2, 11, 4]
    gen = _greedy_paged(model, params, prompt, 9)
    ref_seq = list(prompt)
    for _ in range(9):
        full = model.apply(params,
                           jnp.asarray(np.array(ref_seq, np.int32)[None]),
                           train=False)
        ref_seq.append(int(jnp.argmax(full[0, -1])))
    assert gen == ref_seq[len(prompt):]


@pytest.mark.tier1
def test_prefill_logits_match_plain_apply_and_flash_kernel():
    """The prefill export is the SAME forward as the training apply
    (logits bitwise-close), through the dense path and the fused
    pallas flash kernel alike — the prefill-reuses-the-flash-kernel
    claim, pinned in interpret mode."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from distributedmnist_tpu.core.config import ModelConfig
    from distributedmnist_tpu.models.registry import get_model

    dense_cfg = ModelConfig(**LM_MODEL)
    flash_cfg = dataclasses.replace(dense_cfg, attention_impl="flash")
    dense = get_model(dense_cfg)
    flash = get_model(flash_cfg)
    params = dense.init(jax.random.PRNGKey(3))
    toks = jnp.asarray(
        np.random.default_rng(0).integers(0, 32, size=(2, 16))
        .astype(np.int32))
    ref = dense.apply(params, toks, train=False)
    for model, tol in ((dense, 0.0), (flash, 2e-4)):
        logits, ks, vs = model.decode_prefill(params, toks)
        assert ks.shape == (2, 2, 16, 4, 16) and vs.shape == ks.shape
        if tol == 0.0:
            np.testing.assert_array_equal(np.asarray(logits),
                                          np.asarray(ref))
        else:
            np.testing.assert_allclose(np.asarray(logits),
                                       np.asarray(ref),
                                       rtol=tol, atol=tol)


@pytest.mark.tier1
def test_decode_config_validation():
    from distributedmnist_tpu.core.config import ConfigError, DecodeConfig

    DecodeConfig().validate()
    with pytest.raises(ConfigError, match="swap_policy"):
        DecodeConfig(swap_policy="replay").validate()
    with pytest.raises(ConfigError, match="num_blocks"):
        DecodeConfig(num_blocks=4, max_prompt_len=64,
                     max_new_tokens=64, block_size=8).validate()
    assert DecodeConfig(block_size=16, max_prompt_len=64,
                        max_new_tokens=33).max_blocks_per_seq() == 7


# ---------------------------------------------------------------------------
# shared LM publisher (one short deterministic training run per module)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def lm_published(tmp_path_factory):
    staging = tmp_path_factory.mktemp("lm_staging")
    from distributedmnist_tpu.core.config import ExperimentConfig
    cfg = ExperimentConfig.from_dict({
        "data": {"dataset": "synthetic_lm", "batch_size": 32,
                 "synthetic_train_size": 256, "synthetic_test_size": 64,
                 "use_native_pipeline": False},
        "model": dict(LM_MODEL),
        "train": {"max_steps": 20, "log_every_steps": 10,
                  "train_dir": str(staging),
                  "save_interval_steps": 10, "save_results_period": 0,
                  "async_checkpoint": False},
    })
    from distributedmnist_tpu.train.loop import Trainer
    Trainer(cfg).run()
    steps = sorted(int(p.name[5:13]) for p in staging.glob("ckpt-*.msgpack"))
    assert steps == [10, 20]
    return {"staging": staging, "cfg": cfg, "steps": steps}


def publish_step(staging: Path, serve_dir: Path, step: int) -> None:
    name = f"ckpt-{step:08d}.msgpack"
    serve_dir.mkdir(parents=True, exist_ok=True)
    for sfx in ("", ".sha256"):
        shutil.copy2(staging / (name + sfx), serve_dir / (name + sfx))
    tmp = serve_dir / "checkpoint.json.tmp"
    tmp.write_text(json.dumps({"latest_step": step, "latest_path": name,
                               "written_at": time.time()}))
    tmp.replace(serve_dir / "checkpoint.json")


def make_replica(lm_published, tmp_path, policy="pin", slots=3,
                 max_new=10):
    from distributedmnist_tpu.core.config import DecodeConfig, ServeConfig
    from distributedmnist_tpu.servesvc.decode import DecodeReplica
    serve_src = tmp_path / "publish"
    publish_step(lm_published["staging"], serve_src, 10)
    rep = DecodeReplica(
        serve_src, serve_dir=tmp_path / "replica",
        scfg=ServeConfig(poll_secs=0.05),
        dcfg=DecodeConfig(decode_slots=slots, block_size=8,
                          num_blocks=32, max_prompt_len=16,
                          max_new_tokens=max_new, swap_policy=policy),
        cfg=lm_published["cfg"])
    return rep, serve_src


def serve_records(rep) -> list[dict]:
    return [json.loads(l) for l in
            (rep.serve_dir / "serve_log.jsonl").read_text().splitlines()
            if l.strip()]


class StubConn:
    """Direct-drive connection double: collects every streamed line."""

    def __init__(self):
        self.lines: list[dict] = []

    def settimeout(self, t):
        pass

    def gettimeout(self):
        return None

    def sendall(self, b):
        for line in b.decode().splitlines():
            self.lines.append(json.loads(line))

    def close(self):
        pass


def admit_direct(rep, req: dict) -> object:
    """Admit one request the way _handle_conn would (validation +
    admit journal + queue), without a socket — what lets the swap
    tests drive the decode loop deterministically."""
    conn = StubConn()
    seq = rep._build_item(req, conn)
    assert seq is not None
    rep._journal({"action": "admit", "id": seq.req_id,
                  "deadline_ms": round(
                      (seq.deadline_at - seq.admitted_at) * 1e3, 3)})
    rep._queue.put_nowait(seq)
    return seq, conn


# ---------------------------------------------------------------------------
# the replica end-to-end (real sockets, threads, streaming)
# ---------------------------------------------------------------------------

def test_decode_replica_streams_and_batches_end_to_end(lm_published,
                                                       tmp_path):
    from distributedmnist_tpu.servesvc.client import ServeClient
    from distributedmnist_tpu.servesvc.loadgen import (make_prompt_fn,
                                                       run_load)

    rep, serve_src = make_replica(lm_published, tmp_path)
    rep.start()
    try:
        client = ServeClient([("127.0.0.1", rep.bound_port)],
                             deadline_s=30.0)
        meta = client.meta()
        assert meta["decode"] is True and meta["vocab_size"] == 32
        assert meta["model_step"] == 10
        streamed = []
        # ids 100/101: the loadgen below issues ids 0..11, and a reused
        # id is now a DUPLICATE the dedup cache answers from the first
        # execution — colliding would hide two of the 14 executions
        out = client.generate([1, 2, 3, 4, 5], request_id=100,
                              max_tokens=6,
                              on_token=lambda r: streamed.append(
                                  r.get("token")))
        assert out["status"] == "ok", out
        assert out["finish_reason"] == "max_tokens"
        assert len(out["tokens"]) == 6 and streamed == out["tokens"]
        assert out["ttft_ms"] is not None
        # greedy determinism: the same prompt generates the same tokens
        out2 = client.generate([1, 2, 3, 4, 5], request_id=101,
                               max_tokens=6)
        assert out2["tokens"] == out["tokens"]
        # continuous batching: 3 slots, 12 concurrent requests of
        # wildly different lengths — all complete, zero drops, and the
        # loadgen summary carries the decode latency split
        s = run_load(client, 12, 4, make_prompt_fn(32, 16),
                     journal_path=tmp_path / "lg.jsonl", decode=True)
        assert s["dropped"] == 0 and s["errors"] == 0, s
        assert s["responses"] == 12
        assert s["tokens_streamed"] > 12  # every response streamed
        assert "ttft_ms" in s and "inter_token_ms" in s
        assert s["tokens_per_sec"] > 0
        recs = serve_records(rep)
        fins = [r for r in recs if r["action"] == "decode_finish"]
        assert len(fins) >= 14  # 2 singles + 12 loadgen
        # more sequences finished than slots exist: slots turned over
        assert len(fins) > rep.dcfg.decode_slots
        admits = [r for r in recs if r["action"] == "admit"]
        assert len(admits) == len(fins)  # exactly-one-terminal
        # bad requests are typed, never crashes: too-long prompt,
        # out-of-vocab token, missing prompt
        for bad in ({"id": 90, "prompt": [1] * 99},
                    {"id": 91, "prompt": [999]},
                    {"id": 92, "inputs": [1, 2]}):
            got = _raw_request(rep.bound_port, bad)
            assert got["status"] == "rejected"
            assert got["reason"] == "bad_request"
    finally:
        rep.stop()
    # graceful stop: journal closed with serve_stop, no dangling admits
    recs = serve_records(rep)
    assert recs[-1]["action"] == "serve_stop"


def _raw_request(port: int, payload: dict, timeout=10.0) -> dict:
    with socket.create_connection(("127.0.0.1", port),
                                  timeout=timeout) as conn:
        conn.settimeout(timeout)
        conn.sendall((json.dumps(payload) + "\n").encode())
        buf = b""
        while b"\n" not in buf:
            chunk = conn.recv(65536)
            if not chunk:
                break
            buf += chunk
    return json.loads(buf.decode().splitlines()[0])


def test_decode_replica_sheds_typed_on_stop_mid_generation(lm_published,
                                                           tmp_path):
    """SIGTERM-equivalent stop with generations in flight: every
    admitted request still reaches exactly one typed terminal."""
    from distributedmnist_tpu.servesvc.client import ServeClient

    rep, _ = make_replica(lm_published, tmp_path, slots=2, max_new=10)
    rep.start()
    outcomes = []

    def gen(i):
        client = ServeClient([("127.0.0.1", rep.bound_port)],
                             deadline_s=10.0, max_attempts=1)
        outcomes.append(client.generate([1, 2, 3], request_id=i,
                                        max_tokens=10))

    try:
        threads = [threading.Thread(target=gen, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        time.sleep(0.25)  # let some get admitted / generating
    finally:
        rep.stop()
    for t in threads:
        t.join(timeout=15)
    assert len(outcomes) == 4
    # every client outcome is terminal (ok, a typed reject, or the
    # client-side error after its bounded retry) — nothing hangs
    assert all(o.get("status") in ("ok", "rejected", "error")
               for o in outcomes), outcomes
    recs = serve_records(rep)
    admits = sum(1 for r in recs if r["action"] == "admit")
    terminal = sum(1 for r in recs
                   if r["action"] == "decode_finish"
                   or (r["action"] == "reject" and r.get("admitted")))
    assert admits == terminal  # server-side books balance


# ---------------------------------------------------------------------------
# swap-during-generation policies (deterministic direct drive)
# ---------------------------------------------------------------------------

def _drive_swap(lm_published, tmp_path, policy):
    rep, serve_src = make_replica(lm_published, tmp_path, policy=policy,
                                  slots=2, max_new=8)
    rep._load_initial()
    assert rep.model_step == 10
    seq, conn = admit_direct(rep, {"id": 7, "prompt": [1, 2, 3],
                                   "max_tokens": 8,
                                   "deadline_ms": 60000})
    rep._admit_new()
    assert rep._slots[0] is seq and len(seq.tokens) == 1
    rep._step_active()
    publish_step(lm_published["staging"], serve_src, 20)
    got = rep.follower.poll(rep._read_weights)
    assert got is not None and got[0] == "swap"
    rep._staged = got[1:]
    rep._maybe_swap()
    assert rep.model_step == 20
    while rep._slots[0] is not None:
        rep._step_active()
    return rep, conn


def test_swap_policy_pin_finishes_on_old_weights(lm_published, tmp_path):
    rep, conn = _drive_swap(lm_published, tmp_path, "pin")
    recs = serve_records(rep)
    fin = next(r for r in recs if r["action"] == "decode_finish")
    sw = next(r for r in recs if r["action"] == "weight_swap"
              and not r.get("initial"))
    assert fin["model_step"] == fin["started_step"] == 10
    assert sw["sequences_pinned"] == 1
    assert sw["sequences_restarted"] == 0
    assert not any(r["action"] == "seq_restart" for r in recs)
    # the pinned version was released the moment its sequence finished
    assert not rep._versions
    # a fresh admission runs on the NEW weights
    seq2, conn2 = admit_direct(rep, {"id": 8, "prompt": [4, 5],
                                     "max_tokens": 2,
                                     "deadline_ms": 60000})
    rep._admit_new()
    while rep._slots[0] is not None:
        rep._step_active()
    assert conn2.lines[-1]["model_step"] == 20
    # the invariant replays green over the real journal
    assert _decode_swap_violations(rep, tmp_path / "pin_trial") == []


def test_swap_policy_restart_reprefills_with_license(lm_published,
                                                     tmp_path):
    rep, conn = _drive_swap(lm_published, tmp_path, "restart")
    recs = serve_records(rep)
    fin = next(r for r in recs if r["action"] == "decode_finish")
    sw = next(r for r in recs if r["action"] == "weight_swap"
              and not r.get("initial"))
    restart = next(r for r in recs if r["action"] == "seq_restart")
    assert fin["model_step"] == 20 and fin["started_step"] == 10
    assert fin["restarts"] == 1
    assert sw["sequences_restarted"] == 1
    assert restart["from_step"] == 10 and restart["to_step"] == 20
    assert restart["tokens_discarded"] >= 1
    # the stream told the client to reset before re-streaming
    events = [l.get("stream") for l in conn.lines if "stream" in l]
    assert "restart" in events
    # the terminal carries the full regenerated sequence
    final = conn.lines[-1]
    assert final["status"] == "ok" and len(final["tokens"]) == 8
    assert _decode_swap_violations(rep, tmp_path / "restart_trial") == []


def _decode_swap_violations(rep, troot):
    from distributedmnist_tpu.obsv.invariants import check_serving
    (troot / "worker1").mkdir(parents=True)
    shutil.copy2(rep.serve_dir / "serve_log.jsonl",
                 troot / "worker1" / "serve_log.jsonl")
    violations, applicable, _, decode_applicable = check_serving(
        troot, {"serve_workers": [1]}, [])
    assert applicable and decode_applicable
    return [v.to_dict() for v in violations]


# ---------------------------------------------------------------------------
# the decode_swap invariant over handcrafted journals
# ---------------------------------------------------------------------------

def _decode_trial(tmp_path, records) -> Path:
    trial = tmp_path / "trial"
    (trial / "worker1").mkdir(parents=True)
    (trial / "worker1" / "serve_log.jsonl").write_text(
        "".join(json.dumps({"event": "serve", **r}) + "\n"
                for r in records))
    (trial / "worker1" / "train_log.jsonl").write_text("")
    return trial


def _swap_rec(step, t, **over):
    return {"action": "weight_swap", "step": step, "from_step": step - 10,
            "digest": "d", "tier": "fp32", "source_artifact": None,
            "source_digest": "d", "swap_ms": 1.0, "time": t, **over}


def _finish_rec(rid, model_step, started_step, t):
    return {"action": "decode_finish", "id": rid, "reason": "max_tokens",
            "tokens_streamed": 4, "model_step": model_step,
            "started_step": started_step, "latency_ms": 5.0, "time": t}


def _check(trial):
    from distributedmnist_tpu.obsv.invariants import check_serving
    violations, applicable, _, decode_applicable = check_serving(
        trial, {"serve_workers": [1]}, [])
    assert applicable
    return decode_applicable, {v.invariant for v in violations}, violations


@pytest.mark.tier1
def test_decode_swap_invariant_clean_pin_and_restart(tmp_path):
    # pin: every finish on its started step — green
    dec, by_inv, _ = _check(_decode_trial(tmp_path / "a", [
        _swap_rec(20, 100.0, sequences_pinned=1, sequences_restarted=0),
        {"action": "admit", "id": 1, "deadline_ms": 100.0, "time": 100.1},
        _finish_rec(1, 10, 10, 100.2),
    ]))
    assert dec and "decode_swap" not in by_inv
    # restart: step changed WITH the seq_restart license — green
    dec, by_inv, _ = _check(_decode_trial(tmp_path / "b", [
        _swap_rec(20, 100.0, sequences_pinned=0, sequences_restarted=1),
        {"action": "admit", "id": 1, "deadline_ms": 100.0, "time": 100.05},
        {"action": "seq_restart", "id": 1, "from_step": 10,
         "to_step": 20, "tokens_discarded": 2, "time": 100.1},
        _finish_rec(1, 20, 10, 100.2),
    ]))
    assert dec and "decode_swap" not in by_inv


@pytest.mark.tier1
def test_decode_swap_invariant_catches_unlicensed_step_change(tmp_path):
    dec, by_inv, v = _check(_decode_trial(tmp_path, [
        _swap_rec(20, 100.0, sequences_pinned=0, sequences_restarted=0),
        {"action": "admit", "id": 1, "deadline_ms": 100.0, "time": 100.1},
        _finish_rec(1, 20, 10, 100.2),  # drifted, no license
    ]))
    assert dec and "decode_swap" in by_inv
    assert "no live seq_restart license" in v[0].detail


@pytest.mark.tier1
def test_decode_swap_invariant_catches_restart_without_swap(tmp_path):
    dec, by_inv, _ = _check(_decode_trial(tmp_path / "none", [
        {"action": "admit", "id": 1, "deadline_ms": 100.0, "time": 100.0},
        {"action": "seq_restart", "id": 1, "from_step": 10,
         "to_step": 20, "tokens_discarded": 2, "time": 100.1},
        _finish_rec(1, 20, 10, 100.2),
    ]))
    assert dec and "decode_swap" in by_inv
    # ORDER matters: a swap journaled only AFTER the restart is not a
    # license — the restart ran on weights nothing had installed yet
    dec, by_inv, _ = _check(_decode_trial(tmp_path / "late", [
        {"action": "admit", "id": 1, "deadline_ms": 100.0, "time": 100.0},
        {"action": "seq_restart", "id": 1, "from_step": 10,
         "to_step": 20, "tokens_discarded": 2, "time": 100.1},
        _swap_rec(20, 100.15),
        _finish_rec(1, 20, 10, 100.2),
    ]))
    assert "decode_swap" in by_inv


@pytest.mark.tier1
def test_decode_swap_license_is_consumed_per_generation(tmp_path):
    """Request ids recycle across sweeps in one journal: a legitimate
    restart in generation 1 must not launder a LATER generation's
    unlicensed mixed-weights finish under the same id."""
    dec, by_inv, _ = _check(_decode_trial(tmp_path, [
        _swap_rec(20, 100.0, sequences_pinned=0, sequences_restarted=1),
        {"action": "admit", "id": 1, "deadline_ms": 100.0, "time": 100.05},
        {"action": "seq_restart", "id": 1, "from_step": 10,
         "to_step": 20, "tokens_discarded": 2, "time": 100.1},
        _finish_rec(1, 20, 10, 100.2),   # licensed — consumed here
        {"action": "admit", "id": 1, "deadline_ms": 100.0, "time": 100.3},
        _finish_rec(1, 30, 20, 100.4),   # drifted again, NO new license
    ]))
    assert dec and "decode_swap" in by_inv


@pytest.mark.tier1
def test_decode_swap_invariant_skipped_for_classification_trials(tmp_path):
    dec, by_inv, _ = _check(_decode_trial(tmp_path, [
        _swap_rec(20, 100.0),
        {"action": "admit", "id": 1, "deadline_ms": 100.0, "time": 100.1},
        {"action": "respond", "id": 1, "model_step": 20, "tier": "fp32",
         "batch": 1, "bucket": 1, "latency_ms": 2.0, "time": 100.2},
    ]))
    assert not dec and "decode_swap" not in by_inv


# ---------------------------------------------------------------------------
# chaos decode-mode wiring + the acceptance trial
# ---------------------------------------------------------------------------

@pytest.mark.tier1
def test_chaos_decode_payload_wiring():
    from distributedmnist_tpu.launch.chaos import ChaosConfig
    from distributedmnist_tpu.launch.cluster import ClusterError

    cfg = ChaosConfig(payload="serving", serve_decode=True,
                      serve_replicas=2)
    cmd = cfg.resolved_train_command()
    assert "model.name=transformer" in cmd
    assert "data.dataset=synthetic_lm" in cmd
    wc = cfg.resolved_worker_commands()
    assert set(wc) == {"1", "2"}
    assert all("--decode" in c for c in wc.values())
    assert all("--max-new-tokens 16" in c for c in wc.values())
    # prompt + generation must fit the compact LM's position table —
    # the replica validates at boot, so the payload must pin both
    assert all("--max-prompt-len 16" in c for c in wc.values())
    # decode serves fp32 only: quant tiers refused at config build
    with pytest.raises(ClusterError, match="fp32"):
        ChaosConfig(payload="serving", serve_decode=True,
                    serve_precision_tiers=("int8",))


@pytest.mark.slow  # boots an LM publisher + 2 decode replicas + reference
def test_decode_chaos_trial_end_to_end(tmp_path):
    """The acceptance scenario: a seeded decode-mode serving trial —
    replica killed mid-generation, published checkpoint torn, live
    generate load throughout — completes with dropped == 0 and ALL
    serving invariants (including decode_swap) passing."""
    from distributedmnist_tpu.launch.chaos import ChaosConfig, run_campaign

    cfg = ChaosConfig(
        name="decodetrial", workdir=str(tmp_path), payload="serving",
        serve_decode=True, trials=1, seed=0, until_step=60,
        save_interval_steps=10, serve_replicas=2,
        request_deadline_s=10.0, serve_fault_window=(3, 20),
        shrink=False, trial_timeout_s=420.0)
    summary = run_campaign(cfg)
    assert summary["all_green"], summary
    assert summary["faults"]["fired"] > 0, summary["faults"]
    sv = summary["serving"]
    assert sv["issued"] > 0 and sv["dropped"] == 0, sv
    assert sv["tokens_streamed"] > 0
    assert sv["ttft_p99_ms"] is not None
    inv = summary["invariants"]
    assert inv["decode_swap"]["fail"] == 0
    assert (inv["decode_swap"]["pass"]
            + inv["decode_swap"]["skipped"]) == 1
