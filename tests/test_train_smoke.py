"""End-to-end training smoke tests on the 8-device CPU mesh — the
integration-test role the reference delegated to a live EC2 cluster +
evaluator process (SURVEY §4)."""

import numpy as np
import pytest

from conftest import base_config


def make_trainer(tmp_train_dir, synthetic_datasets, **over):
    from distributedmnist_tpu.train.loop import Trainer
    over.setdefault("train", {})
    over["train"] = {"train_dir": tmp_train_dir, **over["train"]}
    cfg = base_config(**over)
    return Trainer(cfg, datasets=synthetic_datasets)


def test_sync_training_reduces_loss(tmp_train_dir, synthetic_datasets):
    t = make_trainer(tmp_train_dir, synthetic_datasets,
                     train={"max_steps": 40, "log_every_steps": 10})
    first = {}

    def cb(step, rec):
        if step == 1:
            first.update(rec)

    summary = t.run(step_callback=cb)
    assert summary["final_step"] == 40
    assert summary["updates_applied"] == 40
    assert summary["last_metrics"]["loss"] < first["loss"]


@pytest.mark.slow  # trains past the smoke budget (the >=99% oracle); ~50 s
def test_convergence_oracle(tmp_train_dir, synthetic_datasets):
    """Reaches ≥99% test accuracy — mirroring the reference's evaluator
    oracle (src/nn_eval.py:95-103) as an automated assertion."""
    t = make_trainer(tmp_train_dir, synthetic_datasets,
                     train={"max_steps": 120, "log_every_steps": 40})
    t.run()
    result = t.evaluate("test")
    assert result["accuracy"] >= 0.99, result
    assert result["num_examples"] == synthetic_datasets.test.num_examples


@pytest.mark.slow  # trains a full large-batch recipe to the oracle; ~2 min
def test_lamb_large_batch_convergence_oracle(tmp_train_dir,
                                             synthetic_datasets):
    """Time-to-target-accuracy for the large-batch playbook (ROADMAP
    item 4, arXiv:1909.09756): LAMB + linear-warmup/polynomial-decay +
    gradient accumulation + fp32-master-weight bf16 params must reach
    the same ≥99% oracle as the SGD baseline — within a FIXED
    applied-update budget, not just loss parity. The effective batch
    here (256×2=512) is 4× the baseline oracle's 128, in under half the
    baseline's 120 updates: large batches buying wall-clock is the
    paper's whole premise."""
    from distributedmnist_tpu.train.loop import Trainer
    cfg = base_config(
        data={"batch_size": 256},
        optim={"name": "lamb", "initial_learning_rate": 0.02,
               "weight_decay": 1e-4, "schedule": "polynomial",
               "warmup_steps": 5, "poly_power": 2.0},
        precision={"param_dtype": "bfloat16", "master_weights": True},
        train={"max_steps": 50, "grad_accum_steps": 2,
               "log_every_steps": 25, "train_dir": tmp_train_dir,
               "save_interval_steps": 0, "save_results_period": 0})
    t = Trainer(cfg, datasets=synthetic_datasets)
    summary = t.run()
    assert summary["updates_applied"] <= 50
    result = t.evaluate("test")
    assert result["accuracy"] >= 0.99, result


def test_metrics_shapes(tmp_train_dir, synthetic_datasets, topo8):
    t = make_trainer(tmp_train_dir, synthetic_datasets,
                     train={"max_steps": 3, "log_every_steps": 1})
    summary = t.run()
    m = t.collector.matrix()
    assert m.shape == (3, topo8.num_replicas)
    assert np.all(m >= 0)
    assert summary["timing"]["barrier"]["count"] == 3


def test_fresh_run_truncates_train_log(tmp_train_dir, synthetic_datasets):
    """A NON-resumed run into a reused train_dir must not concatenate
    its step series onto the previous run's train_log.jsonl (reports
    read the file as one monotone series); a resumed run appends."""
    import json
    from pathlib import Path

    log = Path(tmp_train_dir) / "train_log.jsonl"

    def step_series():
        # the log is event-typed (step records ride beside the
        # compile record the AOT precompile journals)
        return [r["step"] for r in map(json.loads,
                                       log.read_text().splitlines())
                if r.get("event", "step") == "step"]

    make_trainer(tmp_train_dir, synthetic_datasets,
                 train={"max_steps": 4, "log_every_steps": 2}).run()
    n_first = len(step_series())

    # fresh rerun (resume off): old series replaced, steps restart at 1
    make_trainer(tmp_train_dir, synthetic_datasets,
                 train={"max_steps": 4, "log_every_steps": 2,
                        "resume": False}).run()
    steps = step_series()
    assert len(steps) == n_first and steps[0] == 1

    # resumed run: appends, series stays monotone
    make_trainer(tmp_train_dir, synthetic_datasets,
                 train={"max_steps": 6, "log_every_steps": 2}).run()
    steps = step_series()
    assert steps == sorted(steps) and steps[-1] == 6


@pytest.mark.slow  # jax.profiler trace windows are ~2 min on CPU
def test_trace_every_steps_dumps_per_window(tmp_train_dir,
                                            synthetic_datasets):
    """train.trace_every_steps writes one profiler trace per cadence
    window under profile/step_<k> (≙ --timeline_logging's per-iteration
    trace dumps, src/distributed_train.py:354-358)."""
    from pathlib import Path

    t = make_trainer(tmp_train_dir, synthetic_datasets,
                     train={"max_steps": 5, "log_every_steps": 5,
                            "trace_every_steps": 2})
    t.run()
    windows = sorted(p.name for p in
                     (Path(tmp_train_dir) / "profile").iterdir())
    assert windows == ["step_0", "step_2", "step_4"]
    for w in windows:  # each window holds a real trace artifact
        dumped = list((Path(tmp_train_dir) / "profile" / w).rglob("*"))
        assert any(p.is_file() for p in dumped), w


def test_trace_and_profile_window_conflict(tmp_train_dir,
                                           synthetic_datasets):
    import pytest

    t = make_trainer(tmp_train_dir, synthetic_datasets,
                     train={"max_steps": 3, "profile_steps": (1, 2),
                            "trace_every_steps": 2})
    with pytest.raises(ValueError, match="not both"):
        t.run()


def test_injected_device_delay_costs_quorum_membership(tmp_train_dir,
                                                       synthetic_datasets):
    """Per-replica DEVICE-side timing (sync.measure_device_skew): a
    REAL injected device delay — an actual matmul program dispatched
    onto one replica's device each step, not a configured constant —
    must raise that replica's measured time and cost it quorum
    membership, single-process (the round-4 gap: the measured vector
    carried one host dt for every local replica, so within-host quorum
    ranking degenerated to jitter)."""
    import jax
    import numpy as np
    from conftest import base_config
    from distributedmnist_tpu.train.loop import Trainer

    cfg = base_config(
        data={"dataset": "synthetic", "batch_size": 64,
              "use_native_pipeline": False},
        model={"compute_dtype": "float32"},
        sync={"mode": "quorum", "num_replicas_to_aggregate": 7,
              "straggler_profile": "none", "measure_device_skew": True},
        train={"max_steps": 6, "train_dir": tmp_train_dir,
               "log_every_steps": 6, "save_interval_steps": 0,
               "save_results_period": 0},
    )
    t = Trainer(cfg, datasets=synthetic_datasets)
    assert t._device_probe is not None
    slow_r = 3
    dev = dict(t._device_probe.devices)[slow_r]
    arg = jax.device_put(np.random.default_rng(0)
                         .standard_normal((640, 640)).astype(np.float32), dev)
    heavy = jax.jit(lambda a: a @ a @ a)
    heavy(arg).block_until_ready()   # compile outside the timed steps
    t.device_work_injection = {slow_r: (heavy, arg)}
    summary = t.run()
    flags = summary["last_metrics"]["flags"]
    assert flags[slow_r] == 0.0, flags     # the loaded device lost quorum
    assert sum(flags) == 7.0, flags        # exactly k contributors remain
