"""Network chaos proxy (launch/netchaos.py) + the exactly-once books
it exists to exercise (ISSUE 19).

Four layers:

* the proxy's fault scripts against a stub upstream — latency journals
  and delays, the one-shot reset cuts at EXACTLY ``after_bytes`` then
  heals, the blackhole holds one connection while siblings flow, the
  partition window arms at FIRST live traffic (not proxy boot) and
  heals after ``duration_s``;
* the network schedule grammar — deterministic in (seed, trial),
  always one mid-stream reset + one partition, bounded intensity —
  and its FaultPlan JSON round-trip (the shrunk-reproducer format);
* ``summarize_net_chaos`` over handcrafted artifacts;
* invariant 13 (``check_net_faults``) both ways: a retried id absorbed
  as a dedup hit passes; leaked duplicate terminals, dishonest dedup
  hits, and unlicensed double executions each fail.

Every record the proxy journals is run through the event-schema
validator — the proxy is an emitter like any other.
"""

import json
import socket
import threading
import time
from pathlib import Path

import pytest

from distributedmnist_tpu.launch.netchaos import (ChaosProxy,
                                                  NetChaosError)
from distributedmnist_tpu.obsv import schema


class EchoUpstream:
    """Line-oriented stub replica: reads one ``\\n``-terminated line
    per connection, answers with ``reply`` (default: echo the line),
    closes. Accepts any number of connections, each on its own
    thread."""

    def __init__(self, reply: bytes | None = None):
        self.reply = reply
        self.sock = socket.create_server(("127.0.0.1", 0))
        self.sock.settimeout(0.1)
        self.port = self.sock.getsockname()[1]
        self._stop = threading.Event()
        self._t = threading.Thread(target=self._accept, daemon=True)
        self._t.start()

    def _accept(self):
        while not self._stop.is_set():
            try:
                conn, _ = self.sock.accept()
            except TimeoutError:
                continue
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn):
        with conn:
            conn.settimeout(5.0)
            buf = b""
            try:
                while b"\n" not in buf:
                    chunk = conn.recv(4096)
                    if not chunk:
                        return
                    buf += chunk
                conn.sendall(self.reply if self.reply is not None
                             else buf)
            except OSError:
                pass

    def close(self):
        self._stop.set()
        try:
            self.sock.close()
        except OSError:
            pass
        self._t.join(timeout=5)


def _exchange(port: int, payload: bytes = b"ping\n",
              timeout: float = 5.0) -> bytes:
    """One request through the proxy; returns all bytes until EOF or
    reset (partial bytes on reset, not an exception)."""
    got = b""
    try:
        with socket.create_connection(("127.0.0.1", port),
                                      timeout=timeout) as conn:
            conn.settimeout(timeout)
            conn.sendall(payload)
            while True:
                chunk = conn.recv(4096)
                if not chunk:
                    break
                got += chunk
    except OSError:
        pass  # an RST at ANY point yields the partial bytes, not a raise
    return got


def _assert_conforming(records):
    for r in records:
        assert schema.validate_event(r) == [], r


def test_unknown_script_kind_rejected():
    with pytest.raises(NetChaosError):
        ChaosProxy(("127.0.0.1", 1), [{"kind": "wormhole"}], worker=0)


def test_passthrough_latency_delays_and_journals_once():
    up = EchoUpstream()
    journal: list[dict] = []
    proxy = ChaosProxy(("127.0.0.1", up.port),
                       [{"kind": "latency", "delay_ms": 80.0,
                         "jitter_ms": 20.0}],
                       worker=3, journal=journal.append, seed=7)
    try:
        port = proxy.start()
        t0 = time.monotonic()
        assert _exchange(port) == b"ping\n"
        assert time.monotonic() - t0 >= 0.08
        _exchange(port)  # second conn: delayed again, journaled once
        lats = [r for r in journal if r["action"] == "net_latency"]
        assert len(lats) == 1 and lats[0]["worker"] == 3
        assert lats[0]["delay_ms"] == 80.0
        _assert_conforming(journal)
    finally:
        proxy.stop()
        up.close()


def test_reset_cuts_at_exact_byte_once_then_heals():
    up = EchoUpstream(reply=b"x" * 512)
    journal: list[dict] = []
    proxy = ChaosProxy(("127.0.0.1", up.port),
                       [{"kind": "reset", "after_bytes": 100}],
                       worker=1, journal=journal.append)
    try:
        port = proxy.start()
        # the cut is mid-stream and byte-exact: the client saw SOME of
        # the response (the dangerous case — the server committed the
        # outcome) but not all of it
        assert len(_exchange(port)) == 100
        rst = [r for r in journal if r["action"] == "net_reset"]
        assert len(rst) == 1
        assert rst[0]["bytes_passed"] == 100 and rst[0]["mid_stream"]
        # one-shot: the retry (a fresh connection) gets the full reply
        assert _exchange(port) == b"x" * 512
        assert len([r for r in journal
                    if r["action"] == "net_reset"]) == 1
        _assert_conforming(journal)
    finally:
        proxy.stop()
        up.close()


def test_blackhole_holds_one_conn_while_sibling_flows():
    up = EchoUpstream()
    journal: list[dict] = []
    proxy = ChaosProxy(("127.0.0.1", up.port),
                       [{"kind": "blackhole", "conn": 0,
                         "hold_s": 1.5}],
                       worker=1, journal=journal.append)
    try:
        port = proxy.start()
        # conn ordinal 0: swallowed — no bytes ever come back
        victim = socket.create_connection(("127.0.0.1", port),
                                          timeout=5.0)
        victim.settimeout(0.4)
        victim.sendall(b"ping\n")
        with pytest.raises(TimeoutError):
            victim.recv(4096)
        # a half-open peer must not wedge the proxy: conn 1 flows
        assert _exchange(port) == b"ping\n"
        bh = [r for r in journal if r["action"] == "net_blackhole"]
        assert len(bh) == 1 and bh[0]["conn"] == 0
        _assert_conforming(journal)
        victim.close()
    finally:
        proxy.stop()
        up.close()


def test_partition_arms_on_first_conn_cuts_then_heals():
    up = EchoUpstream()
    journal: list[dict] = []
    proxy = ChaosProxy(("127.0.0.1", up.port),
                       [{"kind": "partition", "start_s": 0.4,
                         "duration_s": 0.6}],
                       worker=1, journal=journal.append)
    try:
        port = proxy.start()
        # idle well past start_s: the window must NOT have opened —
        # its clock arms at the first accepted connection
        time.sleep(0.6)
        t0 = time.monotonic()
        assert _exchange(port) == b"ping\n"
        assert journal == []
        # inside [t0+0.4, t0+1.0): the link is down with an RST, not
        # a hang — the client's retry loop sees it immediately
        time.sleep(max(0.0, t0 + 0.7 - time.monotonic()))
        assert _exchange(port) == b""
        part = [r for r in journal if r["action"] == "net_partition"]
        assert len(part) == 1 and part[0]["duration_s"] == 0.6
        # after the window: healed
        time.sleep(max(0.0, t0 + 1.2 - time.monotonic()))
        assert _exchange(port) == b"ping\n"
        _assert_conforming(journal)
    finally:
        proxy.stop()
        up.close()


def test_serve_json_resolver_follows_restart(tmp_path):
    up = EchoUpstream()
    ep = tmp_path / "serve.json"
    ep.write_text('{"host": "127.0.0.1", "po')  # torn ready-file
    proxy = ChaosProxy(ep, [], worker=1)
    try:
        port = proxy.start()
        # unresolvable upstream: the connection is refused (RST), the
        # client's failover treats it like a dead replica
        assert _exchange(port) == b""
        # the replica "restarts" onto a new port; re-resolved per conn
        ep.write_text(json.dumps({"host": "127.0.0.1",
                                  "port": up.port}))
        assert _exchange(port) == b"ping\n"
    finally:
        proxy.stop()
        up.close()


# ---------------------------------------------------------------------------
# schedule grammar + FaultPlan round-trip
# ---------------------------------------------------------------------------

def test_network_schedule_grammar_and_determinism():
    from distributedmnist_tpu.launch.chaos import (
        generate_network_schedule)

    a = generate_network_schedule(7, 3, [1, 2], max_faults=3)
    b = generate_network_schedule(7, 3, [1, 2], max_faults=3)
    assert a == b
    kinds_seen = set()
    for seed in range(5):
        for t in range(10):
            s = generate_network_schedule(seed, t, [1, 2], max_faults=3)
            kinds = [f.kind for f in s.faults]
            kinds_seen.update(kinds)
            # the two mandatory scripts, exactly once each
            assert kinds.count("net_reset") == 1
            assert kinds.count("net_partition") == 1
            # at most one script of a kind per worker, bounded
            # intensity, every kind a net kind on a serve worker
            kw = [(f.kind, f.worker) for f in s.faults]
            assert len(kw) == len(set(kw))
            assert 2 <= len(s.faults) <= 3
            for f in s.faults:
                assert f.kind.startswith("net_")
                assert f.worker in (1, 2)
                net = dict(f.net)
                if f.kind == "net_reset":
                    # above any meta/classifier response, inside a
                    # decode stream: the cut is always mid-generation
                    assert 450 <= net["after_bytes"] <= 800
                elif f.kind == "net_partition":
                    assert 1.0 <= net["start_s"] <= 4.0
                    assert 0.75 <= net["duration_s"] <= 2.0
                elif f.kind == "net_latency":
                    assert 10.0 <= net["delay_ms"] <= 60.0
                elif f.kind == "net_bandwidth":
                    assert net["bytes_per_s"] >= 8_192
    assert "net_latency" in kinds_seen or "net_bandwidth" in kinds_seen


def test_network_schedule_fault_plan_roundtrip(tmp_path):
    from distributedmnist_tpu.launch.chaos import (
        generate_network_schedule)
    from distributedmnist_tpu.launch.exec import FaultPlan

    s = generate_network_schedule(0, 0, [1, 2], max_faults=3)
    plan = s.to_fault_plan()
    assert plan.net_faults, "net schedules must produce proxy scripts"
    for worker, scripts in plan.net_faults.items():
        assert worker in (1, 2)
        for sc in scripts:
            # proxy-script kinds are UNprefixed (netchaos grammar)
            assert sc["kind"] in ("latency", "bandwidth", "reset",
                                  "blackhole", "partition")
    p = tmp_path / "plan.json"
    p.write_text(json.dumps(plan.to_json_dict()))
    assert FaultPlan.from_file(p) == plan


# ---------------------------------------------------------------------------
# artifacts: the net aggregate + invariant 13
# ---------------------------------------------------------------------------

def _write_jsonl(path: Path, records) -> None:
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text("".join(json.dumps(r) + "\n" for r in records))


def _net_trial(tmp_path, *, leak_terminal=False, dishonest_dedup=False,
               double_exec=False) -> tuple[Path, list[dict]]:
    """A handcrafted network trial: id 1 was reset mid-response, the
    client retried, the replica's dedup cache absorbed the replay."""
    trial = tmp_path / "trial"
    journal = [{"event": "fault", "action": "net_reset", "worker": 1,
                "after_bytes": 500, "bytes_passed": 500,
                "mid_stream": True, "conn": 0, "ts": 50.0}]
    load = [{"event": "load", "action": "issue", "id": i, "time": 1.0 + i}
            for i in range(3)]
    load += [{"event": "load", "action": "outcome", "id": i,
              "status": "ok", "attempts": 2 if i == 1 else 1,
              "retried": i == 1, "latency_ms": 5.0, "time": 2.0 + i}
             for i in range(3)]
    if leak_terminal:
        load.append({"event": "load", "action": "outcome", "id": 1,
                     "status": "ok", "attempts": 2, "retried": True,
                     "latency_ms": 9.0, "time": 9.0})
    _write_jsonl(trial / "loadgen.jsonl", load)
    serve = [{"event": "serve", "action": "admit", "id": i,
              "deadline_ms": 1000.0, "time": 10.0 + i}
             for i in range(3)]
    serve += [{"event": "serve", "action": "respond", "id": i,
               "model_step": 10, "tier": "fp32", "batch": 1,
               "bucket": 1, "latency_ms": 5.0, "time": 20.0 + i}
              for i in range(3)]
    # the replay of id 1 after its respond: honest dedup
    serve.append({"event": "serve", "action": "dedup_hit", "id": 1,
                  "status": "ok", "age_s": 0.2, "time": 30.0})
    if dishonest_dedup:
        # a hit for an id this replica never completed
        serve.append({"event": "serve", "action": "dedup_hit", "id": 9,
                      "status": "ok", "age_s": 0.1, "time": 31.0})
    _write_jsonl(trial / "worker1" / "serve_log.jsonl", serve)
    if double_exec:
        # id 5 admitted+executed on TWO replicas that were never
        # net-faulted and that nobody retried against — a duplicate
        # involving the faulted worker 1 would be licensed, this isn't
        for k in (2, 3):
            _write_jsonl(trial / f"worker{k}" / "serve_log.jsonl", [
                {"event": "serve", "action": "admit", "id": 5,
                 "deadline_ms": 1000.0, "time": 12.0 + k},
                {"event": "serve", "action": "respond", "id": 5,
                 "model_step": 10, "tier": "fp32", "batch": 1,
                 "bucket": 1, "latency_ms": 5.0, "time": 22.0 + k}])
    _write_jsonl(trial / "command_journal.jsonl", journal)
    return trial, journal


def test_invariant13_clean_retry_with_dedup_passes(tmp_path):
    from distributedmnist_tpu.obsv.invariants import check_net_faults
    trial, journal = _net_trial(tmp_path)
    violations, applicable = check_net_faults(trial, {}, journal)
    assert applicable and violations == []


def test_invariant13_duplicate_terminal_fails(tmp_path):
    from distributedmnist_tpu.obsv.invariants import check_net_faults
    trial, journal = _net_trial(tmp_path, leak_terminal=True)
    violations, applicable = check_net_faults(trial, {}, journal)
    assert applicable
    assert any("duplicate terminal" in v.detail for v in violations)


def test_invariant13_dishonest_dedup_hit_fails(tmp_path):
    from distributedmnist_tpu.obsv.invariants import check_net_faults
    trial, journal = _net_trial(tmp_path, dishonest_dedup=True)
    violations, applicable = check_net_faults(trial, {}, journal)
    assert applicable
    assert any("never computed" in v.detail for v in violations)


def test_invariant13_unlicensed_double_execution_fails(tmp_path):
    from distributedmnist_tpu.obsv.invariants import check_net_faults
    trial, journal = _net_trial(tmp_path, double_exec=True)
    violations, applicable = check_net_faults(trial, {}, journal)
    assert applicable
    assert any("unlicensed double execution" in v.detail
               for v in violations)


def test_invariant13_not_applicable_without_net_evidence(tmp_path):
    from distributedmnist_tpu.obsv.invariants import (INVARIANTS,
                                                      check_net_faults)
    assert "net_faults" in INVARIANTS
    (tmp_path / "t").mkdir()
    violations, applicable = check_net_faults(tmp_path / "t", {}, [])
    assert not applicable and violations == []


def test_summarize_net_chaos_aggregates_and_absents(tmp_path):
    from distributedmnist_tpu.obsv.journal import summarize_net_chaos
    trial, _ = _net_trial(tmp_path)
    got = summarize_net_chaos(trial)
    assert got is not None
    assert got["faults"] == {"net_reset": 1} and got["fired"] == 1
    assert got["dedup_hits"] == 1 and got["retried"] == 1
    assert got["retry_rate"] == round(1 / 3, 4)
    assert got["attempts"]["max"] == 2.0
    # a non-network trial carries no net slot at all
    empty = tmp_path / "empty"
    empty.mkdir()
    assert summarize_net_chaos(empty) is None
