"""Ulysses (all-to-all) sequence parallelism vs the dense oracle —
including composition with the pallas flash kernel as the per-device
inner attention."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from distributedmnist_tpu.core.mesh import make_seq_topology
from distributedmnist_tpu.ops.pallas_attention import flash_attention
from distributedmnist_tpu.ops.ring_attention import local_self_attention
from distributedmnist_tpu.ops.ulysses_attention import ulysses_self_attention


def _qkv(key, b=2, h=8, s=32, d=8):
    ks = jax.random.split(key, 3)
    return tuple(jax.random.normal(k, (b, h, s, d), jnp.float32) for k in ks)


def _run(q, k, v, causal, attention_fn=None):
    topo = make_seq_topology(8)
    axis = topo.seq_axis

    def fn(q, k, v):
        return ulysses_self_attention(q, k, v, axis, causal=causal,
                                      attention_fn=attention_fn)

    spec = P(None, None, axis, None)
    sharded = jax.jit(jax.shard_map(fn, mesh=topo.mesh,
                                    in_specs=(spec,) * 3, out_specs=spec))
    return sharded(q, k, v)


@pytest.mark.parametrize("causal", [True, False])
def test_matches_oracle(causal):
    q, k, v = _qkv(jax.random.PRNGKey(0))
    want = local_self_attention(q, k, v, causal=causal)
    got = _run(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_flash_inner_kernel():
    """Ulysses + pallas flash: the long-context flagship composition."""
    q, k, v = _qkv(jax.random.PRNGKey(1), s=64)
    want = local_self_attention(q, k, v, causal=True)
    got = _run(q, k, v, True, attention_fn=flash_attention)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("inner", [None, flash_attention],
                         ids=["dense", "flash"])
def test_grads_match_oracle(inner):
    """With inner=flash this differentiates the pallas backward kernels
    THROUGH shard_map — the flagship Ulysses+flash composition — so the
    kernels' vma declarations are locked in by CI."""
    q, k, v = _qkv(jax.random.PRNGKey(2))

    def obj_local(qkv):
        return jnp.sum(local_self_attention(*qkv, causal=True) ** 2)

    def obj_ulysses(qkv):
        return jnp.sum(_run(*qkv, True, attention_fn=inner) ** 2)

    g_l = jax.grad(obj_local)((q, k, v))
    g_u = jax.grad(obj_ulysses)((q, k, v))
    for a, b in zip(jax.tree.leaves(g_u), jax.tree.leaves(g_l)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_head_divisibility_guard():
    q, k, v = _qkv(jax.random.PRNGKey(3), h=6)
    with pytest.raises(ValueError, match="not divisible"):
        _run(q, k, v, True)
