"""Flash-attention kernel vs the dense oracle (interpret mode on the
CPU test platform; the same kernel compiles via Mosaic on TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributedmnist_tpu.ops.pallas_attention import flash_attention
from distributedmnist_tpu.ops.ring_attention import local_self_attention


def _qkv(key, b=2, h=2, s=64, d=32, dtype=jnp.float32):
    ks = jax.random.split(key, 3)
    mk = lambda k: jax.random.normal(k, (b, h, s, d), dtype)
    return mk(ks[0]), mk(ks[1]), mk(ks[2])


@pytest.mark.parametrize("causal", [True, False])
def test_matches_dense_oracle(causal):
    q, k, v = _qkv(jax.random.PRNGKey(0))
    out = flash_attention(q, k, v, causal=causal)
    ref = local_self_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_ragged_seq_and_head_dim():
    # s not a block multiple, d not a lane multiple — exercises padding+mask
    q, k, v = _qkv(jax.random.PRNGKey(1), b=1, h=3, s=37, d=24)
    out = flash_attention(q, k, v, causal=True, block_q=16, block_k=16)
    ref = local_self_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_multi_block_streaming():
    # several k blocks per q block: the online-softmax rescale path
    q, k, v = _qkv(jax.random.PRNGKey(2), s=128)
    out = flash_attention(q, k, v, causal=False, block_q=32, block_k=32)
    ref = local_self_attention(q, k, v, causal=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("bq,bk", [(96, 64), (64, 96)])
def test_asymmetric_blocks(bq, bk):
    # regression: padding must cover the lcm of both block sizes, or
    # tail key blocks are silently skipped / tail q rows never written
    q, k, v = _qkv(jax.random.PRNGKey(5), s=96, d=16)
    out = flash_attention(q, k, v, causal=False, block_q=bq, block_k=bk)
    ref = local_self_attention(q, k, v, causal=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_bfloat16_io():
    q, k, v = _qkv(jax.random.PRNGKey(3), dtype=jnp.bfloat16)
    out = flash_attention(q, k, v, causal=True)
    assert out.dtype == jnp.bfloat16
    ref = local_self_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               atol=3e-2, rtol=3e-2)


def test_grad_flows():
    q, k, v = _qkv(jax.random.PRNGKey(4), b=1, h=1, s=32, d=16)

    def f(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True) ** 2)

    def f_ref(q, k, v):
        return jnp.sum(local_self_attention(q, k, v, causal=True) ** 2)

    g = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-3)


@pytest.mark.parametrize("causal,s,d,bq,bk", [
    (True, 128, 32, 32, 32),    # multi-block both grids
    (False, 96, 16, 96, 64),    # asymmetric blocks, lcm padding
    (True, 37, 24, 16, 16),     # ragged seq + head dim: padded-row lse
    (False, 100, 64, 128, 128), # seq not a sublane multiple, one block
])
def test_pallas_backward_matches_oracle(causal, s, d, bq, bk):
    """The dedicated dq / dkv pallas kernels vs autodiff through the
    dense oracle, across block/padding geometries."""
    q, k, v = _qkv(jax.random.PRNGKey(7), b=2, h=2, s=s, d=d)

    def f(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal,
                                       block_q=bq, block_k=bk) ** 2)

    def f_ref(q, k, v):
        return jnp.sum(local_self_attention(q, k, v, causal=causal) ** 2)

    g = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-4, rtol=2e-3)


@pytest.mark.parametrize("causal", [True, False])
def test_fused_and_split_backward_agree(causal):
    """The single-visit fused backward (taken when the whole sequence
    fits one block pair) vs the split dq/dkv kernels at the SAME
    geometry — block overrides select the path: (128,128) at s=128 is
    one block pair (fused), (64,64) is a 2x2 grid (split). Pins that
    the two implementations cannot drift apart numerically."""
    q, k, v = _qkv(jax.random.PRNGKey(11), b=2, h=2, s=128, d=64)

    def g(bq, bk):
        return jax.grad(lambda q, k, v: jnp.sum(flash_attention(
            q, k, v, causal=causal, block_q=bq, block_k=bk) ** 2),
            argnums=(0, 1, 2))(q, k, v)

    fused = g(128, 128)
    split = g(64, 64)
    for a, b in zip(fused, split):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-4, rtol=2e-3)


def test_pallas_backward_bf16_io():
    q, k, v = _qkv(jax.random.PRNGKey(8), s=64, d=32, dtype=jnp.bfloat16)

    def f(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True) ** 2)

    g = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda q, k, v: jnp.sum(
        local_self_attention(q.astype(jnp.float32), k.astype(jnp.float32),
                             v.astype(jnp.float32), causal=True) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, gr):
        assert a.dtype == jnp.bfloat16
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   atol=0.15, rtol=0.1)
