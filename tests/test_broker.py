"""Resource-broker tests: the pure decision core replayed over signal
traces (determinism, hysteresis, cooldown, bounds), the executor over a
scripted roster backend (begin -> reshape -> complete ordering,
worker_commands role plumbing), the autoscale replay invariant, and
the chaos-side broker config surface.

The decision core is a pure function of (config, signals,
last-change-time, now) — the property tests here drive it with seeded
random traces and assert the invariants the hysteresis band and
cooldown exist to provide: the roster never leaves its bounds and
never flaps.
"""

import dataclasses
import json
import random
import time

import pytest

from distributedmnist_tpu.core.config import BrokerConfig
from distributedmnist_tpu.launch.broker import (SCALE_DOWN, SCALE_UP,
                                                Decision, ResourceBroker,
                                                collect_signals, decide,
                                                tail_heartbeat,
                                                threshold_holds)
from distributedmnist_tpu.launch.chaos import (ChaosConfig,
                                               _merge_load_summaries)
from distributedmnist_tpu.launch.cluster import (ClusterError,
                                                 LocalClusterConfig)
from distributedmnist_tpu.launch.supervisor import (ClusterSupervisor,
                                                    SupervisorConfig)
from distributedmnist_tpu.obsv.invariants import check_autoscale
from distributedmnist_tpu.obsv.journal import summarize_autoscale
from distributedmnist_tpu.obsv.schema import validate_event

pytestmark = pytest.mark.tier1

_CFG = BrokerConfig(cooldown_s=10.0, min_serve_replicas=1,
                    max_serve_replicas=3, min_train_workers=1,
                    max_train_workers=4)


def _sig(**kw):
    return dict(kw)


# ---------------------------------------------------------------------------
# decide(): the pure core
# ---------------------------------------------------------------------------

def test_decide_is_deterministic():
    args = (_CFG, 1, 2, _sig(p99_ms=900.0, reject_rate=0.0), None, 100.0)
    assert decide(*args) == decide(*args)
    assert decide(*args) == Decision(SCALE_UP, "p99_ms", 900.0,
                                     _CFG.p99_high_ms, ">=", 1, 2, 2, 1)


def test_decide_no_signals_no_decision():
    assert decide(_CFG, 2, 2, {}, None, 100.0) is None


def test_decide_dead_band_is_hysteresis():
    """A signal hovering BETWEEN the low and high marks decides
    nothing in either direction — the band is dead by design."""
    mid = (_CFG.p99_low_ms + _CFG.p99_high_ms) / 2
    assert decide(_CFG, 2, 2, _sig(p99_ms=mid), None, 100.0) is None


def test_decide_cooldown_suppresses_everything():
    hot = _sig(p99_ms=2 * _CFG.p99_high_ms)
    assert decide(_CFG, 1, 2, hot, last_change_t=95.0, now=100.0) is None
    got = decide(_CFG, 1, 2, hot, last_change_t=95.0,
                 now=95.0 + _CFG.cooldown_s)
    assert got is not None and got.decision == SCALE_UP


def test_decide_scale_up_respects_both_bounds():
    hot = _sig(reject_rate=1.0)
    # serving already at max
    assert decide(_CFG, _CFG.max_serve_replicas, 2, hot, None, 0.0) is None
    # no train worker to give up (the publisher is protected)
    assert decide(_CFG, 1, _CFG.min_train_workers, hot, None, 0.0) is None


def test_decide_scale_down_needs_every_signal_calm():
    calm_but_one = _sig(p99_ms=_CFG.p99_low_ms,
                        reject_rate=_CFG.reject_high)
    assert decide(_CFG, 2, 1, calm_but_one, None, 0.0) is None
    calm = _sig(p99_ms=_CFG.p99_low_ms, reject_rate=0.0)
    got = decide(_CFG, 2, 1, calm, None, 0.0)
    assert got is not None and got.decision == SCALE_DOWN
    assert got.new_serve == 1 and got.new_train == 2
    # at the serving floor nothing shrinks, however calm
    assert decide(_CFG, _CFG.min_serve_replicas, 1, calm, None, 0.0) is None


def test_decide_kv_pressure_is_inverted():
    got = decide(_CFG, 1, 2, _sig(kv_free_frac=0.02), None, 0.0)
    assert got is not None and got.decision == SCALE_UP
    assert got.trigger == "kv_free_frac" and got.op == "<="


def test_decide_scale_down_caps_train_growth():
    calm = _sig(p99_ms=0.0)
    got = decide(_CFG, 2, _CFG.max_train_workers, calm, None, 0.0)
    assert got is not None and got.decision == SCALE_DOWN
    assert got.new_train == _CFG.max_train_workers  # shed, don't grow


def test_decide_train_rate_never_triggers():
    assert decide(_CFG, 1, 2, _sig(train_steps_per_s=1e9), None, 0.0) is None


def test_decide_property_bounds_and_no_flap():
    """Property: replay seeded random signal traces through a stateful
    loop exactly the way the broker does (cooldown from the last
    change) — the roster NEVER leaves its configured bounds, and two
    consecutive opposite-direction decisions are never closer than the
    cooldown (no flapping)."""
    for trial in range(20):
        rng = random.Random(1000 + trial)
        serve, train = 1, 3
        last_t = None
        changes: list[tuple[float, str]] = []
        for step in range(200):
            now = step * 1.0
            sig = {}
            if rng.random() < 0.9:
                sig["p99_ms"] = rng.uniform(0, 2 * _CFG.p99_high_ms)
            if rng.random() < 0.5:
                sig["queue_frac"] = rng.random()
            if rng.random() < 0.3:
                sig["kv_free_frac"] = rng.random()
            d = decide(_CFG, serve, train, sig, last_t, now)
            if d is None:
                continue
            assert d.old_serve == serve and d.old_train == train
            serve, train = d.new_serve, d.new_train
            last_t = now
            changes.append((now, d.decision))
            assert _CFG.min_serve_replicas <= serve \
                <= _CFG.max_serve_replicas
            assert _CFG.min_train_workers <= train \
                <= _CFG.max_train_workers
        for (t0, d0), (t1, d1) in zip(changes, changes[1:]):
            assert t1 - t0 >= _CFG.cooldown_s
            # a reversal inside the cooldown window would be a flap
            if d1 != d0:
                assert t1 - t0 >= _CFG.cooldown_s


# ---------------------------------------------------------------------------
# signal collection
# ---------------------------------------------------------------------------

def test_threshold_holds_both_ops():
    assert threshold_holds(5.0, ">=", 5.0)
    assert not threshold_holds(4.9, ">=", 5.0)
    assert threshold_holds(0.1, "<=", 0.1)
    assert not threshold_holds(0.2, "<=", 0.1)


def test_collect_signals_folds_window_and_heartbeats():
    window = {"time": 100.0, "p99_ms": 321.0, "reject_rate": 0.25,
              "ttft_p99_ms": 42.0}
    hbs = [{"queue_depth": 2, "queue_limit": 8,
            "kv_blocks_free": 10, "kv_blocks_total": 100},
           {"queue_depth": 7, "queue_limit": 8,
            "kv_blocks_free": 90, "kv_blocks_total": 100}]
    sig = collect_signals(window, hbs, train_steps_per_s=3.5, now=101.0,
                          window_s=10.0)
    assert sig["p99_ms"] == 321.0 and sig["reject_rate"] == 0.25
    assert sig["ttft_p99_ms"] == 42.0
    assert sig["queue_frac"] == 7 / 8        # worst replica
    assert sig["kv_free_frac"] == 10 / 100   # scarcest pool
    assert sig["train_steps_per_s"] == 3.5


def test_collect_signals_drops_stale_window():
    window = {"time": 100.0, "p99_ms": 999.0}
    sig = collect_signals(window, [], now=100.0 + 60.0, window_s=10.0)
    assert "p99_ms" not in sig


def test_tail_heartbeat_skips_torn_tail(tmp_path):
    log = tmp_path / "train_log.jsonl"
    log.write_text(
        json.dumps({"event": "heartbeat", "step": 3,
                    "queue_depth": 1}) + "\n"
        + json.dumps({"event": "step", "step": 9}) + "\n"
        + '{"event": "heartbeat", "step": 4, "queue_')  # torn write
    hb = tail_heartbeat(tmp_path)
    assert hb is not None and hb["step"] == 3
    assert tail_heartbeat(tmp_path / "missing") is None


def test_tail_heartbeat_empty_file(tmp_path):
    # a replica that crashed before its first heartbeat leaves an
    # empty log — no heartbeat is the answer, not an exception
    (tmp_path / "train_log.jsonl").write_text("")
    assert tail_heartbeat(tmp_path) is None


def test_tail_heartbeat_all_lines_torn(tmp_path):
    # a partition can tear EVERY buffered line (half-written page):
    # the backward scan must walk off the top and report nothing
    (tmp_path / "train_log.jsonl").write_text(
        '{"event": "heartbeat", "st\n{"event": "heartbeat"')
    assert tail_heartbeat(tmp_path) is None


# ---------------------------------------------------------------------------
# ResourceBroker.execute over a scripted roster backend
# ---------------------------------------------------------------------------

class _FakeRoster:
    """The backend surface the broker drives, over an in-memory roster
    with a REAL LocalClusterConfig (so the worker_commands role
    plumbing and resolved_standby_command guard run for real)."""

    def __init__(self, tmp_path, num_workers, worker_commands,
                 standby_command=""):
        self.cfg = LocalClusterConfig(
            name="fake", workdir=str(tmp_path), num_workers=num_workers,
            train_command="train-payload",
            worker_commands=worker_commands,
            standby_command=standby_command)
        self.ids = list(range(num_workers))
        self.alive = {k: True for k in self.ids}
        self.reshapes: list[dict] = []
        self.restarted: list[int] = []
        self.stopped: list[str] = []
        self.promoted: list[int] = []
        self.promote_ok = False
        for k in self.ids:
            self.cfg.worker_dir(k).mkdir(parents=True, exist_ok=True)

    def workers(self):
        return [{"worker": k, "pid": 1000 + k,
                 "alive": self.alive.get(k, False),
                 "logdir": str(self.cfg.worker_dir(k))}
                for k in self.ids]

    def status(self):
        return {"state": "running", "workers": self.workers(), "idle": []}

    def stop_all(self, worker="all"):
        self.stopped.append(worker)
        if worker != "all":
            self.alive[int(worker)] = False

    def reconfigure(self, new_num_workers, survivors=None):
        old = list(self.ids)
        keep = sorted(survivors if survivors is not None else old)
        nxt = max(old) + 1
        grown = []
        while len(keep) < new_num_workers:
            grown.append(nxt)
            keep.append(nxt)
            nxt += 1
        self.ids = sorted(keep)
        for k in grown:
            self.alive[k] = True
            self.cfg.worker_dir(k).mkdir(parents=True, exist_ok=True)
        self.cfg = dataclasses.replace(self.cfg,
                                       num_workers=new_num_workers)
        rec = {"event": "reconfigure", "layer": "cluster",
               "action": "reshape", "old_world": len(old),
               "new_world": new_num_workers, "old_workers": old,
               "workers": list(self.ids), "grown": grown}
        self.reshapes.append(rec)
        return rec

    def restart_worker(self, k):
        self.restarted.append(k)
        self.alive[k] = True

    def promote_standby(self, k):
        self.promoted.append(k)
        self.alive[k] = self.promote_ok or self.alive.get(k, False)
        return self.promote_ok

    def kill_all(self, worker="all"):
        pass


_SERVE_CMD = "serve-payload"


def _brokered(tmp_path, num_workers=3, serve_ids=(1,), standby=""):
    cmds = {str(k): _SERVE_CMD for k in serve_ids}
    backend = _FakeRoster(tmp_path, num_workers, cmds,
                          standby_command=standby)
    sup = ClusterSupervisor(backend, SupervisorConfig(seed=7))
    broker = ResourceBroker(sup, BrokerConfig(cooldown_s=0.0,
                                              settle_timeout_s=5.0),
                            serve_command=_SERVE_CMD)
    return backend, sup, broker


def test_broker_requires_serve_command(tmp_path):
    backend = _FakeRoster(tmp_path, 2, {"1": _SERVE_CMD})
    sup = ClusterSupervisor(backend, SupervisorConfig())
    with pytest.raises(ValueError):
        ResourceBroker(sup)


def test_broker_scale_up_trades_trainer_for_replica(tmp_path):
    backend, sup, broker = _brokered(tmp_path)
    changed = broker.tick({"workers": backend.workers(),
                           "worker_progress": {0: 5, 2: 5}})
    # no pressure journaled anywhere -> no decision
    assert changed is False and backend.reshapes == []

    d = decide(broker.cfg, 1, 2, {"p99_ms": 900.0}, None, time.time())
    assert d is not None
    assert broker.execute(d, [1], [0, 2], time.time()) is True
    # victim: the highest train id, never the publisher
    assert backend.stopped == ["2"]
    assert backend.reshapes[0]["workers"] == [0, 1, 3]
    # the grown slot got the serving payload registered and cold-spawned
    assert backend.cfg.worker_commands["3"] == _SERVE_CMD
    assert backend.restarted == [3]

    # settlement: the new replica's endpoint card going live closes the
    # decision with a measured reaction time
    (backend.cfg.worker_dir(3) / "serve.json").write_text("{}")
    assert broker.tick({"workers": backend.workers()}) is False
    actions = [r["action"] for r in sup.events
               if r.get("event") == "autoscale"]
    assert actions == ["begin", "complete"]
    complete = [r for r in sup.events if r.get("action") == "complete"][0]
    assert complete["serve"] == 2 and complete["train"] == 1
    assert complete["worker"] == 3 and complete["dropped"] == 2
    assert broker.fired == 1


def test_broker_scale_down_returns_slot_to_training(tmp_path):
    backend, sup, broker = _brokered(tmp_path, num_workers=3,
                                     serve_ids=(1, 2))
    d = decide(broker.cfg, 2, 1, {"p99_ms": 0.0}, None, time.time())
    assert d is not None and d.decision == SCALE_DOWN
    assert broker.execute(d, [1, 2], [0], time.time()) is True
    # victim: the newest replica; a train worker grows back
    assert backend.stopped == ["2"]
    assert "2" not in backend.cfg.worker_commands
    assert backend.cfg.worker_commands.get("1") == _SERVE_CMD
    assert backend.restarted == [3]
    assert "3" not in backend.cfg.worker_commands  # the slot trains

    (backend.cfg.worker_dir(3) / "train_log.jsonl").write_text(
        json.dumps({"event": "step", "step": 1}) + "\n")
    broker.tick({"workers": backend.workers()})
    complete = [r for r in sup.events if r.get("action") == "complete"][0]
    assert complete["decision"] == SCALE_DOWN
    assert complete["serve"] == 1 and complete["train"] == 2


def test_broker_promotes_matching_standby_pool(tmp_path):
    backend, sup, broker = _brokered(tmp_path, standby=_SERVE_CMD)
    backend.promote_ok = True
    d = decide(broker.cfg, 1, 2, {"p99_ms": 900.0}, None, time.time())
    broker.execute(d, [1], [0, 2], time.time())
    assert backend.promoted == [3]
    assert backend.restarted == []  # warm path: no cold spawn
    assert backend.cfg.worker_commands["3"] == _SERVE_CMD


def test_broker_skips_pool_parked_on_wrong_payload(tmp_path):
    backend, sup, broker = _brokered(tmp_path, standby="other-payload")
    backend.promote_ok = True
    d = decide(broker.cfg, 1, 2, {"p99_ms": 900.0}, None, time.time())
    broker.execute(d, [1], [0, 2], time.time())
    assert backend.promoted == []   # guard refused the role swap
    assert backend.restarted == [3]


def test_broker_settle_timeout_journals_error(tmp_path):
    backend, sup, broker = _brokered(tmp_path)
    broker.cfg = BrokerConfig(cooldown_s=0.0, settle_timeout_s=0.0)
    d = decide(broker.cfg, 1, 2, {"p99_ms": 900.0}, None, time.time())
    broker.execute(d, [1], [0, 2], time.time())
    time.sleep(0.02)  # past the zero settle budget; no serve.json ever
    broker.tick({"workers": backend.workers()})
    actions = [r["action"] for r in sup.events
               if r.get("event") == "autoscale"]
    assert actions == ["begin", "error"]
    assert broker.fired == 0


def test_broker_events_validate_against_schema(tmp_path):
    backend, sup, broker = _brokered(tmp_path)
    d = decide(broker.cfg, 1, 2, {"p99_ms": 900.0}, None, time.time())
    broker.execute(d, [1], [0, 2], time.time())
    (backend.cfg.worker_dir(3) / "serve.json").write_text("{}")
    broker.tick({"workers": backend.workers()})
    recs = [r for r in sup.events if r.get("event") == "autoscale"]
    assert len(recs) == 2
    for r in recs:
        validate_event(r, source="test")


# ---------------------------------------------------------------------------
# the autoscale replay invariant
# ---------------------------------------------------------------------------

def _begin(decision=SCALE_UP, value=900.0, threshold=500.0, op=">=",
           t=100.0, **kw):
    return {"event": "autoscale", "layer": "broker", "action": "begin",
            "decision": decision, "trigger": "p99_ms", "value": value,
            "threshold": threshold, "op": op, "old_serve": 1,
            "new_serve": 2, "old_train": 2, "new_train": 1,
            "cooldown_s": 10.0, "time": t, **kw}


def _complete(decision=SCALE_UP, t=105.0):
    return {"event": "autoscale", "layer": "broker", "action": "complete",
            "decision": decision, "trigger": "p99_ms", "reaction_s": 2.0,
            "serve": 2, "train": 1, "time": t}


def _reshape(new_world=3, **kw):
    return {"event": "reconfigure", "layer": "cluster",
            "action": "reshape", "old_world": 3, "new_world": new_world,
            **kw}


def test_check_autoscale_not_applicable_without_broker():
    violations, applicable = check_autoscale({}, [_reshape()])
    assert not applicable and violations == []


def test_check_autoscale_licensed_run_is_green():
    journal = [_begin(), _reshape(new_world=3), _complete()]
    violations, applicable = check_autoscale({"broker": True}, journal)
    assert applicable and violations == []


def test_check_autoscale_flags_unlicensed_reshape():
    violations, _ = check_autoscale({"broker": True}, [_reshape()])
    assert any("unlicensed" in v.detail for v in violations)


def test_check_autoscale_flags_license_that_does_not_hold():
    journal = [_begin(value=100.0, threshold=500.0, op=">="),
               _reshape(), _complete()]
    violations, _ = check_autoscale({"broker": True}, journal)
    assert any("never crossed" in v.detail for v in violations)


def test_check_autoscale_flags_world_mismatch():
    journal = [_begin(), _reshape(new_world=7), _complete()]
    violations, _ = check_autoscale({"broker": True}, journal)
    assert any("lands on world 7" in v.detail for v in violations)


def test_check_autoscale_flags_dangling_and_overlapping_begins():
    violations, _ = check_autoscale({"broker": True}, [_begin()])
    assert any("never closed" in v.detail for v in violations)
    violations, _ = check_autoscale(
        {"broker": True},
        [_begin(t=100.0), _begin(t=101.0, decision=SCALE_DOWN)])
    assert any("overlapping" in v.detail for v in violations)


def test_check_autoscale_supervisor_reconfigure_keeps_own_license():
    """A fault-path reshape licensed by the supervisor's own
    reconfigure begin does not consume (or need) an autoscale one."""
    journal = [
        {"event": "reconfigure", "layer": "supervisor",
         "action": "begin", "old_world": 3, "new_world": 2},
        _reshape(new_world=2),
    ]
    violations, applicable = check_autoscale({"broker": True}, journal)
    assert applicable and violations == []


# ---------------------------------------------------------------------------
# summaries
# ---------------------------------------------------------------------------

def test_summarize_autoscale_counts_and_flaps():
    recs = [
        _begin(t=100.0), _complete(t=102.0),
        # a reversal 5 s after a 10 s-cooldown decision: one flap
        _begin(decision=SCALE_DOWN, value=1.0, threshold=150.0,
               op="<=", t=105.0),
        _complete(decision=SCALE_DOWN, t=106.0),
        {"event": "autoscale", "action": "error",
         "decision": SCALE_UP, "error": "boom", "time": 107.0},
    ]
    got = summarize_autoscale(recs)
    assert got["decisions"] == 2 and got["completed"] == 2
    assert got["errors"] == 1
    assert got["by_direction"] == {SCALE_UP: 1, SCALE_DOWN: 1}
    assert got["flaps"] == 1
    assert got["reaction_s"]["max"] == 2.0


def test_summarize_autoscale_spaced_reversal_is_not_a_flap():
    recs = [_begin(t=100.0), _complete(t=101.0),
            _begin(decision=SCALE_DOWN, t=100.0 + 50.0)]
    assert summarize_autoscale(recs)["flaps"] == 0


def test_merge_load_summaries_sums_counts_takes_worst_tails():
    a = {"issued": 10, "terminal": 10, "dropped": 0, "responses": 9,
         "rejected": 1, "errors": 0, "by_reason": {"rejected:overload": 1},
         "duration_s": 2.0, "model_steps_served": [3],
         "tiers_served": ["fp32"],
         "latency_ms": {"p50": 5.0, "p99": 20.0}}
    b = {"issued": 20, "terminal": 20, "dropped": 0, "responses": 20,
         "rejected": 0, "errors": 0, "by_reason": {},
         "duration_s": 3.0, "model_steps_served": [3, 5],
         "tiers_served": ["fp32"],
         "latency_ms": {"p50": 4.0, "p99": 80.0}}
    got = _merge_load_summaries([a, None, b])
    assert got["issued"] == 30 and got["dropped"] == 0
    assert got["rejected"] == 1
    assert got["by_reason"] == {"rejected:overload": 1}
    assert got["latency_ms"]["p99"] == 80.0  # worst phase bounds the gate
    assert got["model_steps_served"] == [3, 5]
    assert got["phases_merged"] == 2
    assert _merge_load_summaries([None, None]) is None


# ---------------------------------------------------------------------------
# config surfaces
# ---------------------------------------------------------------------------

def test_broker_config_validate_rejects_bad_marks():
    with pytest.raises(ValueError):
        BrokerConfig(p99_low_ms=500.0, p99_high_ms=100.0).validate()
    with pytest.raises(ValueError):
        BrokerConfig(min_serve_replicas=3,
                     max_serve_replicas=1).validate()
    with pytest.raises(ValueError):
        BrokerConfig(min_train_workers=0).validate()
    BrokerConfig().validate()  # defaults are coherent


def test_chaos_config_broker_validation():
    with pytest.raises(ClusterError):
        ChaosConfig(payload="shell", broker=True)
    with pytest.raises(ClusterError):
        ChaosConfig(payload="serving", broker=True,
                    broker_train_workers=1)
    with pytest.raises(ClusterError):
        ChaosConfig(payload="serving", broker=True,
                    serve_precision_tiers=("int8",))


def test_chaos_config_broker_roster_adds_donor_trainers():
    cfg = ChaosConfig(payload="serving", broker=True, serve_replicas=2,
                      broker_train_workers=3, until_step=24)
    assert cfg.trial_num_workers() == 1 + 2 + 2
    cmds = cfg.resolved_worker_commands()
    serve = cfg.resolved_serve_command()
    assert cmds["1"] == serve and cmds["2"] == serve
    # donors run the publisher payload with a 10x step budget so they
    # never finish inside the trial window
    assert "train.max_steps=240" in cmds["3"]
    assert cmds["3"] == cmds["4"] != serve
    # non-broker rosters are unchanged by the new knobs
    plain = ChaosConfig(payload="serving", serve_replicas=2)
    assert plain.trial_num_workers() == 3
    assert set(plain.resolved_worker_commands()) == {"1", "2"}
