"""graftcheck (distributedmnist_tpu.analysis) — the static-analysis
toolchain's own contract.

Three layers:

* fixture snippets per checker — a known-bad snippet must produce the
  expected finding, the known-good twin must stay clean;
* schema-registry round-trips — the ``obsv/schema.py`` registry, the
  ``obsv/journal.py`` summarizers and the runtime validator must agree
  on required fields (the drift this PR exists to kill);
* the self-check — graftcheck over the package + tests must be clean
  modulo the checked-in baseline, with no stale baseline entries.
"""

from __future__ import annotations

import ast
import json
from pathlib import Path

import pytest

from distributedmnist_tpu.analysis import (CHECKERS, iter_sources,
                                           load_baseline, run_checkers)
from distributedmnist_tpu.analysis.core import Source
from distributedmnist_tpu.analysis import (config_check,
                                           durability_check, jax_check,
                                           net_check, schema_check,
                                           threads_check)
from distributedmnist_tpu.obsv import schema

REPO = Path(__file__).resolve().parents[1]
PKG = REPO / "distributedmnist_tpu"


def src(path: str, text: str) -> Source:
    return Source(path=path, tree=ast.parse(text), text=text)


def keys(findings) -> set[str]:
    return {f.key for f in findings}


# ---------------------------------------------------------------------------
# schema checker fixtures
# ---------------------------------------------------------------------------

class TestSchemaChecker:
    def check(self, text: str):
        return schema_check.check(
            [src("distributedmnist_tpu/launch/snippet.py", text)])

    def test_unknown_kind_flagged(self):
        got = self.check('sink.write({"event": "telemetry", "x": 1})\n')
        assert any("unknown-kind.telemetry" in k for k in keys(got))

    def test_missing_required_field_flagged(self):
        got = self.check(
            'sink.write({"event": "save", "save_stall_ms": 1.0,\n'
            '            "async_snapshot": True})\n')
        assert any("missing.save.at_step" in k for k in keys(got))

    def test_undeclared_field_flagged(self):
        # the PR-12 lesson as a fixture: a save record writing "step"
        # would fake training progress to the resume watch
        got = self.check(
            'sink.write({"event": "save", "at_step": 3, "step": 3,\n'
            '            "save_stall_ms": 1.0, "async_snapshot": True})\n')
        assert any("undeclared.save.step" in k for k in keys(got))

    def test_undeclared_action_flagged(self):
        got = self.check('j({"event": "recovery", "action": "resurrect",'
                         ' "worker": 1})\n')
        assert any("unknown-action.recovery.resurrect" in k
                   for k in keys(got))

    def test_conforming_record_clean(self):
        got = self.check(
            'sink.write({"event": "save", "at_step": 3, "time": 1.0,\n'
            '            "save_stall_ms": 1.0, "async_snapshot": True})\n')
        assert got == []

    def test_dynamic_payload_checks_literal_keys_only(self):
        # **extra hides fields from the AST: no missing-required
        # finding, but a literally-written unknown key still fires
        got = self.check('sink.write({"event": "save", "bogus": 1,'
                         ' **extra})\n')
        ks = keys(got)
        assert any("undeclared.save.bogus" in k for k in ks)
        assert not any("missing" in k for k in ks)

    def test_wrapper_kwargs_checked(self):
        # supervisor-style wrapper: action arg0, payload kwargs
        text = 'self._event("detect", worker=1, kindz="dead")\n'
        got = schema_check.check(
            [src("distributedmnist_tpu/launch/supervisor.py", text)])
        ks = keys(got)
        assert any("undeclared.recovery.detect.kindz" in k for k in ks)
        assert any("missing.recovery.detect.kind" in k for k in ks)

    def test_tests_are_exempt(self):
        got = schema_check.check(
            [src("tests/test_x.py",
                 'w({"event": "telemetry", "x": 1})\n')])
        assert got == []


# ---------------------------------------------------------------------------
# config checker fixtures
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def config_source():
    text = (PKG / "core" / "config.py").read_text()
    return src("distributedmnist_tpu/core/config.py", text)


class TestConfigChecker:
    def test_unknown_knob_flagged(self, config_source):
        bad = src("distributedmnist_tpu/train/snippet.py",
                  "def f(cfg):\n    return cfg.train.max_stepz\n")
        got = config_check.check([config_source, bad])
        assert any("unknown.train.max_stepz" in k for k in keys(got))

    def test_declared_knob_and_method_clean(self, config_source):
        good = src("distributedmnist_tpu/train/snippet.py",
                   "def f(cfg):\n"
                   "    a = cfg.train.max_steps\n"
                   "    b = cfg.quant.resolved_publish_tiers()\n"
                   "    c = cfg.data.effective_device_prefetch_depth()\n")
        got = config_check.check([config_source, good])
        assert not any(k.startswith("config:distributedmnist_tpu/train/")
                       for k in keys(got))

    def test_dead_knob_flagged_and_read_clears_it(self, config_source):
        reader = src("distributedmnist_tpu/train/snippet.py",
                     "def f(cfg):\n    return cfg.train.max_steps\n")
        got = keys(config_check.check([config_source, reader]))
        assert any("dead.train.seed" in k for k in got)  # nothing reads it here
        assert not any("dead.train.max_steps" in k for k in got)

    def test_real_tree_has_no_dead_knobs(self):
        srcs = iter_sources([PKG, REPO / "tests"], repo_root=REPO)
        got = keys(config_check.check(srcs))
        dead = sorted(k for k in got if ":dead." in k)
        assert dead == [], f"declared-but-unread knobs: {dead}"

    def test_audit_covers_paged_and_tp_knobs(self, config_source):
        """The PR-17 knobs are declared AND genuinely consumed — the
        audit must neither dead-flag them on the real tree nor accept
        a typo'd read of them."""
        srcs = iter_sources([PKG], repo_root=REPO)
        got = keys(config_check.check(srcs))
        for knob in ("decode.attention_kernel", "serve.tp_ranks",
                     "serve.tp_group_max_restarts",
                     "serve.tp_group_poll_secs",
                     # the protocol-hardening knobs: consumed by the
                     # replica's conn threads and dedup cache
                     "serve.conn_read_timeout_s",
                     "serve.conn_write_timeout_s",
                     "serve.dedup_cache_size"):
            assert not any(f"dead.{knob}" in k for k in got), knob
        bad = src("distributedmnist_tpu/servesvc/snippet.py",
                  "def f(cfg):\n    return cfg.serve.tp_rankz\n")
        got = keys(config_check.check([config_source, bad]))
        assert any("unknown.serve.tp_rankz" in k for k in got)


# ---------------------------------------------------------------------------
# paged checker fixtures (dense-materialization lint, servesvc/ scope)
# ---------------------------------------------------------------------------

class TestPagedChecker:
    def check(self, text: str,
              path: str = "distributedmnist_tpu/servesvc/snippet.py"):
        from distributedmnist_tpu.analysis import paged_check
        return paged_check.check([src(path, text)])

    def test_dense_gather_in_hot_function_flagged(self):
        got = self.check(
            "def _step_active(self):\n"
            "    ks, vs = self.cache.gather_dense(table, length)\n")
        assert any("dense-gather._step_active.gather_dense" in k
                   for k in keys(got))

    def test_table_rebuild_in_hot_loop_flagged(self):
        got = self.check(
            "def _step_active(self):\n"
            "    for s in self._slots:\n"
            "        tables = np.zeros((n, width))\n")
        assert any("table-rebuild._step_active.zeros" in k
                   for k in keys(got))

    def test_cached_rebuild_outside_loop_clean(self):
        # the epoch-keyed cache shape: built once per composition
        # change, OUTSIDE any loop — exactly what decode.py does now
        got = self.check(
            "def _tables_for(self, version):\n"
            "    tables = np.zeros((n, width))\n"
            "    return tables\n")
        assert got == []

    def test_cold_path_and_other_trees_exempt(self):
        hot = ("def decode_step(self):\n"
               "    ks = gather_dense(table, n)\n")
        # same text outside servesvc/ (the dense oracle lives in
        # models/ and tests/) is out of scope by design
        assert self.check(
            hot, path="distributedmnist_tpu/models/transformer.py") == []
        assert self.check(hot, path="tests/test_x.py") == []
        # non-hot function names in servesvc are fine too (setup /
        # oracle helpers)
        got = self.check("def _debug_dump(self):\n"
                         "    ks = gather_dense(table, n)\n")
        assert got == []

    def test_real_servesvc_tree_is_clean(self):
        from distributedmnist_tpu.analysis import paged_check
        srcs = iter_sources([PKG / "servesvc"], repo_root=REPO)
        got = paged_check.check(srcs)
        assert got == [], [f.key for f in got]


# ---------------------------------------------------------------------------
# net checker fixtures (socket-deadline lint, servesvc/ + launch/ scope)
# ---------------------------------------------------------------------------

class TestNetChecker:
    def check(self, text: str,
              path: str = "distributedmnist_tpu/servesvc/snippet.py"):
        return net_check.check([src(path, text)])

    def test_recv_without_timeout_flagged(self):
        got = self.check(
            "class Replica:\n"
            "    def _read(self, conn):\n"
            "        return conn.recv(65536)\n")
        assert any("Replica._read.recv" in k for k in keys(got))

    def test_class_level_settimeout_clears_all_methods(self):
        # the listener idiom: settimeout in start(), accept elsewhere —
        # evidence is class-scoped, so the sibling method is clean
        got = self.check(
            "class Replica:\n"
            "    def start(self, sock):\n"
            "        sock.settimeout(0.2)\n"
            "    def _accept_loop(self, sock):\n"
            "        conn, addr = sock.accept()\n"
            "        return conn.recv(65536)\n")
        assert got == []

    def test_create_connection_without_timeout_flagged(self):
        got = self.check(
            "import socket\n"
            "def dial(host, port):\n"
            "    return socket.create_connection((host, port))\n")
        assert any("dial.create_connection" in k for k in keys(got))

    def test_create_connection_with_timeout_clean(self):
        # kwarg or 2nd positional arg both bound the connect
        for call in ("socket.create_connection((h, p), timeout=1.0)",
                     "socket.create_connection((h, p), 1.0)"):
            got = self.check(
                f"import socket\ndef dial(h, p):\n    return {call}\n")
            assert got == [], call

    def test_other_trees_and_tests_exempt(self):
        bad = ("class C:\n"
               "    def f(self, conn):\n"
               "        return conn.recv(1)\n")
        assert self.check(
            bad, path="distributedmnist_tpu/models/net.py") == []
        assert self.check(bad, path="tests/test_x.py") == []

    def test_real_wire_paths_are_clean(self):
        # the lint's reason to exist: every blocking socket op the
        # serving/launch stack ships today is deadline-bounded
        srcs = iter_sources([PKG / "servesvc", PKG / "launch"],
                            repo_root=REPO)
        got = net_check.check(srcs)
        assert got == [], [f.key for f in got]


# ---------------------------------------------------------------------------
# durability checker fixtures
# ---------------------------------------------------------------------------

class TestDurabilityChecker:
    def check(self, text: str,
              path: str = "distributedmnist_tpu/train/snippet.py"):
        return durability_check.check([src(path, text)])

    def test_raw_write_in_train_flagged(self):
        # in the checkpoint-owning package ANY raw write is a bypass
        got = self.check(
            "def save(p, data):\n"
            '    with open(p, "wb") as fh:\n'
            "        fh.write(data)\n")
        assert any('save.open(mode="wb")' in k for k in keys(got))

    def test_raw_rename_and_path_writes_in_train_flagged(self):
        got = self.check(
            "import os\n"
            "def publish(tmp, dst):\n"
            "    dst.write_bytes(b'x')\n"
            "    os.replace(tmp, dst)\n")
        assert any("publish.write_bytes()" in k for k in keys(got))
        assert any("publish.os.replace()" in k for k in keys(got))

    def test_shim_routed_calls_clean(self):
        got = self.check(
            "from . import storage\n"
            "def save(tmp, dst, data):\n"
            '    storage.write_bytes(tmp, data, role="data")\n'
            '    storage.replace(tmp, dst, role="data")\n')
        assert got == []

    def test_reads_and_nonliteral_modes_clean(self):
        got = self.check(
            "def load(p, mode):\n"
            '    with open(p) as a, open(p, "rb") as b:\n'
            "        pass\n"
            "    return open(p, mode)\n")
        assert got == []

    def test_elsewhere_only_durable_paths_flagged(self):
        launch = "distributedmnist_tpu/launch/snippet.py"
        # a supervisor writing its own results file is out of scope
        assert self.check(
            'def report(d):\n'
            '    (d / "results.json").write_text("{}")\n',
            path=launch) == []
        # ... but writing a checkpoint pointer behind the shim is not
        got = self.check(
            'def meddle(d):\n'
            '    (d / "checkpoint.json").write_text("{}")\n',
            path=launch)
        assert any("meddle.write_text()" in k for k in keys(got))

    def test_shim_and_tests_exempt(self):
        bad = 'def f(p):\n    open(p, "w").write("x")\n'
        assert self.check(
            bad, path="distributedmnist_tpu/train/storage.py") == []
        assert self.check(bad, path="tests/test_x.py") == []

    def test_real_durable_write_paths_are_clean(self):
        # the lint's reason to exist: every durable write the train/
        # quant stack ships today routes through the storage shim
        srcs = iter_sources([PKG], repo_root=REPO)
        got = durability_check.check(srcs)
        assert got == [], [f.key for f in got]


# ---------------------------------------------------------------------------
# concurrency checker fixtures
# ---------------------------------------------------------------------------

_RACY = """
import threading

class Racy:
    def __init__(self):
        self.counter = 0
        self._lock = threading.Lock()
        self.t = threading.Thread(target=self._work)

    def _work(self):
        while True:
            self.counter += 1

    def bump(self):
        self.counter += 1
"""

_LOCKED = _RACY.replace(
    "    def bump(self):\n        self.counter += 1\n",
    "    def bump(self):\n        with self._lock:\n"
    "            self.counter += 1\n").replace(
    "        while True:\n            self.counter += 1\n",
    "        while True:\n            with self._lock:\n"
    "                self.counter += 1\n")


class TestThreadsChecker:
    def check(self, text):
        return threads_check.check(
            [src("distributedmnist_tpu/servesvc/snippet.py", text)])

    def test_cross_root_unguarded_write_flagged(self):
        got = self.check(_RACY)
        assert any(k.endswith("Racy.counter") for k in keys(got))

    def test_lock_guarded_writes_clean(self):
        assert self.check(_LOCKED) == []

    def test_init_writes_exempt(self):
        # construction happens-before thread start: a class whose only
        # shared-attr writes are in __init__ is clean
        text = _RACY.replace(
            "    def bump(self):\n        self.counter += 1\n", "")
        text = text.replace(
            "        while True:\n            self.counter += 1\n",
            "        while True:\n            pass\n")
        assert self.check(text) == []

    def test_timer_function_and_positional_target_resolved(self):
        # Timer's callable is arg 1 (or function=); Thread's is arg 1
        # (or target=) — arg0 is interval/group, never the callable
        for spawn in ("threading.Timer(0.5, self._work).start()",
                      "threading.Timer(0.5, function=self._work)"
                      ".start()",
                      "threading.Thread(None, self._work).start()"):
            text = f"""
import threading

class Racy:
    def __init__(self):
        self.counter = 0

    def start(self):
        {spawn}

    def _work(self):
        self.counter += 1

    def bump(self):
        self.counter += 1
"""
            got = self.check(text)
            assert any(k.endswith("Racy.counter") for k in keys(got)), \
                spawn

    def test_thread_target_via_loop_tuple_resolved(self):
        text = """
import threading

class Looper:
    def __init__(self):
        self.state = 0

    def start(self):
        for target in (self._a, self._b):
            threading.Thread(target=target).start()

    def _a(self):
        self.state = 1

    def _b(self):
        self.state = 2
"""
        got = self.check(text)
        assert any(k.endswith("Looper.state") for k in keys(got))


# ---------------------------------------------------------------------------
# jax checker fixtures
# ---------------------------------------------------------------------------

class TestJaxChecker:
    def check(self, text):
        return jax_check.check(
            [src("distributedmnist_tpu/parallel/snippet.py", text)])

    def test_use_after_donate_flagged(self):
        got = self.check(
            "from jax import jit\n"
            "f = jit(lambda s: s, donate_argnums=0)\n"
            "def g(state):\n"
            "    out = f(state)\n"
            "    return state\n")
        assert any("donate.g.state" in k for k in keys(got))

    def test_rebind_is_clean(self):
        got = self.check(
            "from jax import jit\n"
            "f = jit(lambda s: s, donate_argnums=0)\n"
            "def g(state):\n"
            "    state = f(state)\n"
            "    return state\n")
        assert got == []

    def test_loop_donation_without_rebind_flagged(self):
        got = self.check(
            "from jax import jit\n"
            "f = jit(lambda s, b: s, donate_argnums=0)\n"
            "def train_loop(state, batches):\n"
            "    for b in batches:\n"
            "        out = f(state, b)\n")
        assert any("donate-loop.train_loop.state" in k for k in keys(got))

    def test_loop_donation_with_rebind_clean(self):
        got = self.check(
            "from jax import jit\n"
            "f = jit(lambda s, b: s, donate_argnums=0)\n"
            "def train_loop(state, batches):\n"
            "    for b in batches:\n"
            "        state = f(state, b)\n")
        assert got == []

    def test_branch_return_does_not_poison_sibling(self):
        # the parallel/api.py fast-path shape: two alternative returns
        # must not read as use-after-donate
        got = self.check(
            "from jax import jit\n"
            "f = jit(lambda s: s, donate_argnums=0)\n"
            "def g(state, exe):\n"
            "    if exe is not None:\n"
            "        return exe(state)\n"
            "    return f(state)\n")
        assert got == []

    def test_item_in_hot_loop_flagged(self):
        got = self.check(
            "from jax import jit\n"
            "f = jit(lambda x: x)\n"
            "def run_loop(xs):\n"
            "    for x in xs:\n"
            "        y = f(x)\n"
            "        print(y.item())\n")
        assert any("host-sync.run_loop.item" in k for k in keys(got))

    def test_float_over_jitted_result_in_loop_flagged(self):
        got = self.check(
            "from jax import jit\n"
            "f = jit(lambda x: x)\n"
            "def step_loop(xs):\n"
            "    t = 0.0\n"
            "    for x in xs:\n"
            "        y = f(x)\n"
            "        t += float(y)\n")
        assert any("host-sync.step_loop.float" in k for k in keys(got))

    def test_scalar_loop_var_into_jit_flagged(self):
        got = self.check(
            "from jax import jit\n"
            "f = jit(lambda i, x: x)\n"
            "def run(x):\n"
            "    for i in range(10):\n"
            "        f(i, x)\n")
        assert any("scalar-jit.run.i" in k for k in keys(got))

    def test_static_argnums_silences_scalar_signature(self):
        got = self.check(
            "from jax import jit\n"
            "f = jit(lambda i, x: x, static_argnums=0)\n"
            "def run(x):\n"
            "    for i in range(10):\n"
            "        f(i, x)\n")
        assert got == []

    def test_donation_respects_argnums_positions(self):
        # donate_argnums=(0,): reading the NON-donated batch after the
        # call is fine; reading the donated state is not
        got = self.check(
            "from jax import jit\n"
            "f = jit(lambda s, b: s, donate_argnums=(0,))\n"
            "def g(state, batch):\n"
            "    out = f(state, batch)\n"
            "    print(batch)\n"
            "    return out\n")
        assert got == []
        got = self.check(
            "from jax import jit\n"
            "f = jit(lambda s, b: s, donate_argnums=(0,))\n"
            "def g(state, batch):\n"
            "    out = f(state, batch)\n"
            "    print(state)\n")
        assert any("donate.g.state" in k for k in keys(got))

    def test_device_iteration_not_scalar_hazard(self):
        # iterating device arrays (timing.py's token warmup) is not the
        # python-scalar recompile hazard
        got = self.check(
            "from jax import jit\n"
            "f = jit(lambda x: x)\n"
            "def run(tokens):\n"
            "    for t in tokens:\n"
            "        f(t)\n")
        assert got == []


# ---------------------------------------------------------------------------
# registry round-trips: emitters, summarizers and the validator agree
# ---------------------------------------------------------------------------

class TestSchemaRegistry:
    def test_reconfigure_summary_projects_registry_fields(self):
        from distributedmnist_tpu.obsv.journal import (
            summarize_reconfigure_events)
        begin = {"event": "reconfigure", "action": "begin",
                 "old_world": 3, "new_world": 2,
                 "trigger": "below_quorum", "quorum": 3,
                 "effective_quorum": 2, "survivors": [0, 1]}
        got = summarize_reconfigure_events([begin])
        assert set(got["transitions"][0]) == set(
            schema.required_fields(schema.RECONFIGURE, "begin"))

    def test_quorum_transition_summary_projects_registry_fields(self):
        from distributedmnist_tpu.obsv.journal import (
            summarize_recovery_events)
        rec = {"event": "recovery", "action": "quorum_transition",
               "workers_alive": 2, "num_workers": 3, "quorum": 2,
               "degraded": True}
        got = summarize_recovery_events([rec])
        assert set(got["quorum_transitions"][0]) == set(
            schema.required_fields(schema.RECOVERY, "quorum_transition"))

    def test_summarizer_read_fields_are_declared(self):
        # every field summarize_mttr projects off a resume record must
        # be a declared resume field — reader/emitter agreement
        fields = schema.payload_fields(schema.RECOVERY, "resume")
        for f in ("mttr_s", "resume_after_respawn_s", "step"):
            assert f in fields

    def test_every_required_field_validates(self):
        for kind, sch in schema.EVENT_SCHEMAS.items():
            rec = {"event": kind}
            for f in sch.required:
                rec[f] = 0
            if sch.actions:
                for action, act in sch.actions.items():
                    r = dict(rec, action=action,
                             **{f: 0 for f in act.required})
                    assert schema.validate_event(r) == [], (kind, action)
            else:
                assert schema.validate_event(rec) == [], kind

    def test_validator_catches_drift(self):
        assert schema.validate_event({"event": "nope"})
        assert schema.validate_event({"event": "save"})  # missing fields
        assert schema.validate_event(
            {"event": "save", "at_step": 1, "save_stall_ms": 0.0,
             "async_snapshot": True, "step": 1})  # undeclared field
        assert schema.validate_event(
            {"event": "recovery", "action": "resurrect"})
        # non-journal rows (no "event") pass vacuously
        assert schema.validate_event({"name": "sweep", "acc": 0.9}) == []

    def test_non_string_action_is_a_problem(self):
        # a dynamically-built payload that sets action=None must be
        # flagged, not skipped as "no action to check"
        assert schema.validate_event(
            {"event": "serve", "action": None, "garbage": 1})

    def test_check_event_raises(self):
        with pytest.raises(schema.EventSchemaError):
            schema.check_event({"event": "telemetry"})

    def test_env_gate(self, monkeypatch):
        monkeypatch.setenv("DMT_VALIDATE_EVENTS", "0")
        schema.maybe_check_event({"event": "telemetry"})  # gated off
        monkeypatch.setenv("DMT_VALIDATE_EVENTS", "1")
        with pytest.raises(schema.EventSchemaError):
            schema.maybe_check_event({"event": "telemetry"})

    def test_jsonl_sink_enforces_in_tests(self, tmp_path):
        # conftest turns DMT_VALIDATE_EVENTS on for the whole suite:
        # the shared sink must refuse a nonconforming record
        from distributedmnist_tpu.core.log import JsonlSink
        with JsonlSink(tmp_path / "j.jsonl") as sink:
            sink.write({"event": "heartbeat", "step": 1})  # conforming
            sink.write({"rows": 3})                        # non-event
            with pytest.raises(schema.EventSchemaError):
                sink.write({"event": "heartbeat"})  # missing step


# ---------------------------------------------------------------------------
# the self-check: graftcheck over this very tree
# ---------------------------------------------------------------------------

class TestSelfCheck:
    def test_package_clean_modulo_baseline(self):
        sources = iter_sources([PKG, REPO / "tests"], repo_root=REPO)
        findings = run_checkers(sources)
        baseline = load_baseline()
        new = [f for f in findings if f.key not in baseline]
        assert new == [], (
            "graftcheck found non-baselined findings:\n"
            + "\n".join(f"{f.path}:{f.line}: {f.message}" for f in new))
        fired = {f.key for f in findings}
        stale = sorted(set(baseline) - fired)
        assert stale == [], f"stale baseline entries: {stale}"

    def test_unparseable_file_is_a_finding(self, tmp_path):
        bad = tmp_path / "broken.py"
        bad.write_text("def broken(:\n")
        sources = iter_sources([bad], repo_root=tmp_path)
        findings = run_checkers(sources)
        assert any(f.checker == "parse"
                   and "syntax-error" in f.key for f in findings)

    def test_targeted_run_does_not_report_untested_baseline_stale(self):
        # a subset invocation (roots that exclude servesvc) must not
        # read the ServingReplica suppressions as stale — exit 0
        import subprocess, sys
        p = subprocess.run(
            [sys.executable, "-m", "distributedmnist_tpu.analysis",
             "distributedmnist_tpu/train"],
            cwd=REPO, capture_output=True, text=True, timeout=120)
        assert p.returncode == 0, p.stdout[-2000:] + p.stderr[-2000:]
        assert "STALE" not in p.stdout

    def test_unknown_checker_is_a_usage_error(self):
        import subprocess, sys
        p = subprocess.run(
            [sys.executable, "-m", "distributedmnist_tpu.analysis",
             "--checkers", "cofnig"],
            cwd=REPO, capture_output=True, text=True, timeout=60)
        assert p.returncode != 0
        assert "unknown checker" in p.stderr

    def test_all_checkers_registered(self):
        run_checkers([])  # force registration imports
        assert set(CHECKERS) == {"schema", "config", "threads", "jax",
                                 "paged", "net", "durability"}

    def test_baseline_entries_carry_justifications(self):
        raw = json.loads(
            (PKG / "analysis" / "baseline.json").read_text())
        for entry in raw["accepted"]:
            assert entry.get("justification", "").strip(), entry["key"]

    def test_cli_json_exits_zero(self):
        import subprocess, sys
        p = subprocess.run(
            [sys.executable, "-m", "distributedmnist_tpu.analysis",
             "--format", "json"],
            cwd=REPO, capture_output=True, text=True, timeout=120)
        assert p.returncode == 0, p.stdout[-2000:] + p.stderr[-2000:]
        report = json.loads(p.stdout)
        assert report["ok"] is True
        assert report["files_analyzed"] > 50
