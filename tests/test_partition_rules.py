"""The regex partition-rule engine (parallel/partition_rules.py):
first-match-wins semantics, the explicit unmatched-leaf error, stacked
(scan/pipeline) layer paths, and — the load-bearing property — parity
of the engine-derived spec trees against the models' hand-built
``tp_param_specs`` / ``pp_param_specs`` output for every model family
and axis combination the meshes use."""

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from distributedmnist_tpu.core.config import ExperimentConfig, MeshConfig
from distributedmnist_tpu.core.mesh import make_topology
from distributedmnist_tpu.models.registry import get_model
from distributedmnist_tpu.parallel.api import (abstract_train_params,
                                               params_partition_specs)
from distributedmnist_tpu.parallel.partition_rules import (
    LeafShardPlan, UnmatchedLeafError, make_zero1_plan,
    match_partition_rules, spec_is_replicated, tree_path_names, zero1_pack,
    zero1_state_specs, zero1_unpack)

pytestmark = pytest.mark.tier1

IS_SPEC = lambda x: isinstance(x, P)  # noqa: E731


def assert_spec_trees_equal(got, want):
    gl, gt = jax.tree.flatten(got, is_leaf=IS_SPEC)
    wl, wt = jax.tree.flatten(want, is_leaf=IS_SPEC)
    assert gt == wt, f"structure mismatch: {gt} != {wt}"
    assert gl == wl, f"spec mismatch:\n  got  {gl}\n  want {wl}"


# ---------------------------------------------------------------------------
# engine semantics
# ---------------------------------------------------------------------------

def test_first_match_wins_ordering():
    tree = {"a": {"w": np.zeros((4, 4))}, "b": np.zeros((4,))}
    specs = match_partition_rules(
        [(r"a/w$", P("x")), (r".*", P())], tree)
    assert specs["a"]["w"] == P("x") and specs["b"] == P()
    # the same table reversed: the catch-all eats everything first
    specs = match_partition_rules(
        [(r".*", P()), (r"a/w$", P("x"))], tree)
    assert specs["a"]["w"] == P() and specs["b"] == P()


def test_unmatched_leaf_is_an_explicit_error():
    tree = {"covered": np.zeros((4,)), "orphan": np.zeros((4, 4))}
    with pytest.raises(UnmatchedLeafError, match="orphan"):
        match_partition_rules([(r"^covered$", P())], tree)


def test_scalars_never_partition():
    tree = {"scalar": np.zeros(()), "one": np.zeros((1,)),
            "vec": np.zeros((4,))}
    # the catch-all names an axis; scalars must still come out P()
    specs = match_partition_rules([(r".*", P("x"))], tree)
    assert specs["scalar"] == P() and specs["one"] == P()
    assert specs["vec"] == P("x")


def test_paths_cover_list_and_stacked_layouts():
    from distributedmnist_tpu.models import transformer
    params = transformer.init(jax.random.PRNGKey(0), num_layers=2,
                              vocab_size=16, model_dim=8, num_heads=2,
                              max_seq_len=8)
    flat_paths = set(tree_path_names(params))
    assert "blocks/0/wqkv" in flat_paths and "blocks/1/w2" in flat_paths
    stacked_paths = set(tree_path_names(
        transformer.stack_block_params(params)))
    assert "blocks/wqkv" in stacked_paths
    assert "blocks/ln1/scale" in stacked_paths


# ---------------------------------------------------------------------------
# parity: engine-derived specs vs the hand-built spec trees
# ---------------------------------------------------------------------------

def _transformer_cfg(**model):
    d = {"name": "transformer", "num_layers": 4, "num_heads": 4,
         "model_dim": 32, "seq_len": 16, "vocab_size": 64,
         "compute_dtype": "float32", "dropout_rate": 0.0}
    d.update(model)
    return ExperimentConfig.from_dict({"model": d})


def test_replicated_models_derive_all_replicated(topo8):
    for name in ("mnist_cnn", "resnet20"):
        cfg = ExperimentConfig.from_dict({"model": {"name": name}})
        model = get_model(cfg.model)
        specs = params_partition_specs(model, cfg, topo8)
        leaves = jax.tree.leaves(specs, is_leaf=IS_SPEC)
        assert leaves and all(spec_is_replicated(s) for s in leaves)


@pytest.mark.parametrize("num_experts", [0, 4])
def test_engine_matches_hand_built_tp_specs(num_experts):
    cfg = _transformer_cfg(num_experts=num_experts)
    topo = make_topology(MeshConfig(
        num_replicas=2, model_parallelism=2,
        expert_parallelism=2 if num_experts else 1))
    model = get_model(cfg.model)
    got = params_partition_specs(model, cfg, topo)
    want = model.tp_param_specs(
        topo.model_axis, topo.expert_axis if num_experts else None)
    assert_spec_trees_equal(got, want)


@pytest.mark.parametrize("tp,ep", [(False, False), (True, False),
                                   (True, True)])
def test_engine_matches_hand_built_pp_specs(tp, ep):
    num_experts = 4 if ep else 0
    cfg = _transformer_cfg(num_experts=num_experts)
    topo = make_topology(MeshConfig(
        num_replicas=1, pipeline_parallelism=2,
        model_parallelism=2 if tp else 1,
        expert_parallelism=2 if ep else 1))
    model = get_model(cfg.model)
    got = params_partition_specs(model, cfg, topo)
    want = model.pp_param_specs(
        topo.stage_axis, topo.model_axis if tp else None,
        topo.expert_axis if ep else None)
    assert_spec_trees_equal(got, want)


def test_engine_specs_cover_1f1b_chunked_layout():
    """The chunk-interleaved (1f1b) layout has the same tree structure
    as the stacked one — the engine's stacked rules must cover it."""
    cfg = _transformer_cfg().override({"mesh.pipeline_schedule": "1f1b",
                                       "mesh.pipeline_chunks": 2,
                                       "mesh.pipeline_parallelism": 2,
                                       "mesh.num_replicas": 1})
    topo = make_topology(cfg.mesh)
    model = get_model(cfg.model)
    got = params_partition_specs(model, cfg, topo)
    want = model.pp_param_specs(topo.stage_axis, None, None)
    assert_spec_trees_equal(got, want)


def test_capable_model_without_rule_table_refuses_sharded_mesh():
    """A model that passes the TP capability check but declares no rule
    table must fail loudly — the replicated fallback table would
    silently double-count its model-axis psums."""
    import jax.numpy as jnp

    from distributedmnist_tpu.models.registry import Model
    dummy = Model(
        name="dummy", init=lambda k: {"w": jnp.zeros((4, 4))},
        apply=lambda p, x, **kw: x, loss=lambda l, y: l.sum(),
        accuracy=lambda l, y: l.sum(), input_shape=(4,),
        tp_param_specs=lambda m, e=None: {"w": P(None, m)},
        sharded_apply_factory=lambda *a, **kw: None)
    cfg = ExperimentConfig.from_dict({})
    topo = make_topology(MeshConfig(num_replicas=1, model_parallelism=2))
    with pytest.raises(ValueError, match="partition_rules"):
        params_partition_specs(dummy, cfg, topo)


def test_unsupported_mesh_still_raises():
    """The engine path must preserve the capability errors: a mesh
    demanding TP from a TP-less model fails loudly at spec time."""
    cfg = ExperimentConfig.from_dict({"model": {"name": "mnist_cnn"}})
    model = get_model(cfg.model)
    topo = make_topology(MeshConfig(num_replicas=1, model_parallelism=2))
    with pytest.raises(ValueError, match="tensor-parallel"):
        params_partition_specs(model, cfg, topo)


# ---------------------------------------------------------------------------
# ZeRO-1 shard plan
# ---------------------------------------------------------------------------

def test_zero1_plan_padding_and_fallbacks():
    tree = {"big": np.zeros((10,), np.float32),      # uneven: pads 10→16
            "tiny": np.zeros((4,), np.float32),      # < n: falls back
            "tp": np.zeros((8, 8), np.float32)}      # sharded elsewhere
    specs = {"big": P(), "tiny": P(), "tp": P(None, "model")}
    plan = make_zero1_plan(tree, specs, "replica", 8)
    lp = plan.leaf_plans
    assert lp["big"].sharded and lp["big"].pad == 16 and lp["big"].chunk == 2
    assert not lp["tiny"].sharded
    assert not lp["tp"].sharded  # tensor-parallel leaf keeps its placement
    mspecs = zero1_state_specs(plan, specs)
    assert mspecs["big"] == P("replica")
    assert mspecs["tiny"] == P() and mspecs["tp"] == P(None, "model")


def test_zero1_pack_unpack_exact_roundtrip():
    rng = np.random.default_rng(0)
    tree = {"w": rng.normal(size=(3, 7)).astype(np.float32),
            "tiny": rng.normal(size=(2,)).astype(np.float32)}
    specs = {"w": P(), "tiny": P()}
    plan = make_zero1_plan(tree, specs, "replica", 8)
    packed = zero1_pack(tree, plan)
    assert packed["w"].shape == (24,)              # 21 → pad 24
    assert np.all(packed["w"][21:] == 0)
    assert packed["tiny"].shape == (2,)            # fallback untouched
    back = zero1_unpack(packed, plan)
    np.testing.assert_array_equal(back["w"], tree["w"])
    np.testing.assert_array_equal(back["tiny"], tree["tiny"])
    # packing an already-packed tree is the identity (flat-layout
    # artifacts restore exactly too)
    repacked = zero1_pack(packed, plan)
    np.testing.assert_array_equal(repacked["w"], packed["w"])


def test_zero1_min_leaf_size_floor():
    tree = {"w": np.zeros((64,), np.float32)}
    specs = {"w": P()}
    plan = make_zero1_plan(tree, specs, "replica", 8, min_leaf_size=128)
    assert not plan.leaf_plans["w"].sharded
    assert not plan.any_sharded


def test_plan_mirrors_abstract_params_tree(topo8):
    """The plan the state/init/update/checkpoint consumers share is
    derived from abstract (eval_shape) params — its structure must
    match the real param tree exactly."""
    cfg = ExperimentConfig.from_dict(
        {"model": {"name": "mnist_cnn"},
         "parallel": {"shard_weight_update": True}})
    model = get_model(cfg.model)
    abstract = abstract_train_params(model, cfg, topo8)
    specs = params_partition_specs(model, cfg, topo8, params=abstract)
    plan = make_zero1_plan(abstract, specs, topo8.replica_axis, 8)
    is_lp = lambda x: isinstance(x, LeafShardPlan)  # noqa: E731
    assert (jax.tree.structure(plan.leaf_plans, is_leaf=is_lp)
            == jax.tree.structure(abstract))
