"""Ring attention correctness: the sharded ring must match the
single-device oracle exactly (sequence-parallel path, SURVEY §5.7 —
a capability the reference lacks entirely but this framework treats
as first-class)."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from distributedmnist_tpu.core.mesh import make_seq_topology
from distributedmnist_tpu.ops.ring_attention import (local_self_attention,
                                                     ring_self_attention)


def _qkv(key, b=2, h=2, s=32, d=8):
    ks = jax.random.split(key, 3)
    return tuple(jax.random.normal(k, (b, h, s, d), jnp.float32) for k in ks)


def _run_ring(q, k, v, causal):
    topo = make_seq_topology(8)
    axis = topo.seq_axis

    def fn(q, k, v):
        return ring_self_attention(q, k, v, axis, causal=causal)

    spec = P(None, None, axis, None)  # shard the sequence dim
    sharded = jax.jit(jax.shard_map(fn, mesh=topo.mesh,
                                    in_specs=(spec, spec, spec),
                                    out_specs=spec))
    return sharded(q, k, v)


def test_ring_matches_local_causal():
    q, k, v = _qkv(jax.random.PRNGKey(0))
    want = local_self_attention(q, k, v, causal=True)
    got = _run_ring(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_ring_matches_local_full():
    q, k, v = _qkv(jax.random.PRNGKey(1))
    want = local_self_attention(q, k, v, causal=False)
    got = _run_ring(q, k, v, causal=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_ring_grads_match_local():
    q, k, v = _qkv(jax.random.PRNGKey(2))

    def local_obj(qkv):
        return jnp.sum(local_self_attention(*qkv, causal=True) ** 2)

    def ring_obj(qkv):
        topo = make_seq_topology(8)
        axis = topo.seq_axis
        spec = P(None, None, axis, None)

        def fn(q, k, v):
            return ring_self_attention(q, k, v, axis, causal=True)

        out = jax.shard_map(fn, mesh=topo.mesh, in_specs=(spec,) * 3,
                            out_specs=spec)(*qkv)
        return jnp.sum(out ** 2)

    g_local = jax.grad(local_obj)((q, k, v))
    g_ring = jax.grad(ring_obj)((q, k, v))
    for a, b in zip(jax.tree.leaves(g_ring), jax.tree.leaves(g_local)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_transformer_with_ring_attention_matches_local():
    """Full model equivalence: sequence-sharded forward == local forward."""
    from distributedmnist_tpu.models import transformer
    params = transformer.init(jax.random.PRNGKey(0), vocab_size=31,
                              model_dim=16, num_heads=2, num_layers=2,
                              max_seq_len=64)
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 64), 0, 31)
    want = transformer.apply(params, toks, num_heads=2,
                             compute_dtype=jnp.float32)

    topo = make_seq_topology(8)
    axis = topo.seq_axis

    def fn(params, toks, positions):
        def ring_attn(q, k, v):
            return ring_self_attention(q, k, v, axis, causal=True)
        return transformer.apply(params, toks, num_heads=2,
                                 attention_fn=ring_attn,
                                 positions=positions,
                                 compute_dtype=jnp.float32)

    positions = jnp.arange(64)
    sharded = jax.jit(jax.shard_map(
        fn, mesh=topo.mesh,
        in_specs=(P(), P(None, axis), P(axis)),
        out_specs=P(None, axis, None)))
    got = sharded(params, toks, positions)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)
