"""Tensor-parallel serving groups (servesvc/tp_group.py + the
ServingReplica TP topology branch).

The supervision contract under test is die-as-a-unit: a TP replica is
one process group holding one sharded weight set, so ANY rank dying
must take the whole group down (journaled ``rank_exit`` →
``group_down``) before a unit restart (``group_restart`` →
``group_start``) — a half-dead group must never serve.  The group
journal chain is replayed by the ``serve_group`` invariant, checked
here both ways (conforming and violating histories).

The supervisor is exercised with stub rank processes (``sleep``
children via an injected spawn_fn) — the lifecycle logic owes nothing
to jax.  The sharded-boot test drives the real DecodeReplica with
``tp_ranks=2`` on the conftest-simulated device mesh.
"""

import json
import signal
import subprocess
import sys
import time
from pathlib import Path

import numpy as np
import pytest

LM_MODEL = {"name": "transformer", "seq_len": 64, "model_dim": 64,
            "num_heads": 4, "num_layers": 2, "vocab_size": 32,
            "compute_dtype": "float32", "attention_impl": "dense"}


def _stub_spawn(rank, attempt):
    return subprocess.Popen([sys.executable, "-c",
                             "import time; time.sleep(30)"])


def _group_records(serve_dir) -> list[dict]:
    p = Path(serve_dir) / "group_log.jsonl"
    return [json.loads(l) for l in p.read_text().splitlines() if l.strip()]


def _actions(recs):
    return [r["action"] for r in recs]


# ---------------------------------------------------------------------------
# supervisor lifecycle
# ---------------------------------------------------------------------------

@pytest.mark.tier1
def test_group_die_as_a_unit_and_restart(tmp_path):
    from distributedmnist_tpu.servesvc.tp_group import ServeGroup

    g = ServeGroup(tmp_path / "g", 2, _stub_spawn, max_restarts=2,
                   poll_secs=0.01)
    g.start()
    first = dict(g.procs)
    assert all(p.poll() is None for p in first.values())
    roster = json.loads((tmp_path / "g" / "group.json").read_text())
    assert roster["ranks"] == 2 and roster["attempt"] == 0
    assert set(roster["pids"]) == {"0", "1"}

    first[1].kill()                      # murder one rank
    first[1].wait()
    assert g.step()                      # detect → teardown → restart
    # die-as-a-unit: the SURVIVING rank of attempt 0 was killed too
    assert first[0].poll() is not None
    # and a whole fresh group is up
    assert g.attempt == 1
    assert all(p.poll() is None for p in g.procs.values())
    acts = _actions(_group_records(tmp_path / "g"))
    i_exit = acts.index("rank_exit")
    assert acts[:2] == ["group_start", "rank_spawn"]
    assert acts[i_exit:i_exit + 2] == ["rank_exit", "group_down"]
    assert "group_restart" in acts[i_exit:]
    assert acts.count("group_start") == 2

    g.stop()
    assert all(p.poll() is not None for p in g.procs.values())
    acts = _actions(_group_records(tmp_path / "g"))
    assert acts[-1] == "group_stop"


@pytest.mark.tier1
def test_group_restart_budget_exhausted(tmp_path):
    from distributedmnist_tpu.servesvc.tp_group import ServeGroup

    g = ServeGroup(tmp_path / "g", 2, _stub_spawn, max_restarts=0,
                   poll_secs=0.01)
    g.start()
    g.procs[0].kill()
    g.procs[0].wait()
    assert not g.step()                  # budget 0: over, no respawn
    acts = _actions(_group_records(tmp_path / "g"))
    assert acts[-3:] == ["rank_exit", "group_down", "group_stop"]
    assert "group_restart" not in acts
    assert all(p.poll() is not None for p in g.procs.values())


@pytest.mark.tier1
def test_group_restart_on_rank0_socket_reset_via_proxy(tmp_path):
    """ISSUE 19 crossover: a rank whose WIRE dies (chaos-proxy RST
    mid-stream, not a signal) exits like any other crash — the
    supervisor must still journal the full die-as-a-unit chain
    ``rank_exit`` → ``group_down`` → ``group_restart``."""
    import socket
    import threading

    from distributedmnist_tpu.launch.netchaos import ChaosProxy
    from distributedmnist_tpu.servesvc.tp_group import ServeGroup

    # upstream: a tiny streamer the proxied rank reads from — accepts
    # serially (attempt 0's rank 0, then attempt 1's) and drips bytes
    # so the proxy's downstream pump crosses the reset threshold
    lsock = socket.create_server(("127.0.0.1", 0))
    lsock.settimeout(0.2)
    up_port = lsock.getsockname()[1]
    stop = threading.Event()

    def streamer():
        while not stop.is_set():
            try:
                conn, _ = lsock.accept()
            except TimeoutError:
                continue
            with conn:
                try:
                    while not stop.is_set():
                        conn.sendall(b"x" * 16)
                        time.sleep(0.01)
                except OSError:
                    pass

    t = threading.Thread(target=streamer, daemon=True)
    t.start()

    proxy = ChaosProxy(("127.0.0.1", up_port),
                       [{"kind": "reset", "after_bytes": 64}], worker=0)
    proxy_port = proxy.start()

    # rank 0 is a real socket reader through the proxy: it exits(1)
    # the moment its connection dies; rank 1 is the inert stub
    reader = ("import socket, sys\n"
              f"s = socket.create_connection(('127.0.0.1', {proxy_port}),"
              " timeout=10)\n"
              "s.settimeout(10)\n"
              "try:\n"
              "    while True:\n"
              "        if not s.recv(4096):\n"
              "            sys.exit(1)\n"
              "except OSError:\n"
              "    sys.exit(1)\n")

    def spawn(rank, attempt):
        if rank == 0:
            return subprocess.Popen([sys.executable, "-c", reader])
        return _stub_spawn(rank, attempt)

    g = ServeGroup(tmp_path / "g", 2, spawn, max_restarts=2,
                   poll_secs=0.01)
    try:
        g.start()
        # the one-shot reset fires after ~4 drip chunks; poll until
        # the supervisor has seen the exit and restarted the unit
        deadline = time.time() + 10.0
        while g.attempt == 0 and time.time() < deadline:
            g.step()
            time.sleep(0.02)
        assert g.attempt == 1, "proxy reset never took rank 0 down"
        assert all(p.poll() is None for p in g.procs.values())
        acts = _actions(_group_records(tmp_path / "g"))
        i_exit = acts.index("rank_exit")
        assert acts[i_exit:i_exit + 2] == ["rank_exit", "group_down"]
        assert "group_restart" in acts[i_exit:]
        recs = _group_records(tmp_path / "g")
        assert recs[i_exit]["rank"] == 0
    finally:
        g.stop()
        proxy.stop()
        stop.set()
        t.join(timeout=5)
        lsock.close()


@pytest.mark.tier1
def test_default_spawn_fn_rewrites_rank_argv(tmp_path, monkeypatch):
    """The supervisor re-invokes the SAME serve command per rank, with
    only serve-dir/rank identity rewritten (and any stale --tp-rank*
    flags stripped, including the two-token form)."""
    from distributedmnist_tpu.servesvc import tp_group

    captured = []

    class FakePopen:
        pid = 4242

        def __init__(self, cmd, **kw):
            captured.append((cmd, kw))

    monkeypatch.setattr(tp_group.subprocess, "Popen", FakePopen)
    base = ["serve", "--train_dir", "/pub", "--serve-dir", "old",
            "--tp-ranks", "2", "--decode", "--port", "0"]
    spawn = tp_group.default_spawn_fn(base, tmp_path / "w1", 2)
    spawn(0, 0)
    spawn(1, 0)
    for rank, (cmd, _kw) in enumerate(captured):
        args = cmd[cmd.index("serve"):]
        assert args.count("--serve-dir") == 1
        assert "old" not in args
        assert args[args.index("--tp-rank") + 1] == str(rank)
        assert args[args.index("--tp-ranks") + 1] == "2"
        assert "--decode" in args and "--train_dir" in args
    assert (captured[0][0][captured[0][0].index("--serve-dir") + 1]
            == str(tmp_path / "w1"))
    assert (captured[1][0][captured[1][0].index("--serve-dir") + 1]
            == str(tmp_path / "w1" / "rank1"))


# ---------------------------------------------------------------------------
# serve_group invariant replay
# ---------------------------------------------------------------------------

def _write_group_log(d: Path, actions: list[dict]) -> None:
    d.mkdir(parents=True, exist_ok=True)
    with open(d / "group_log.jsonl", "w") as f:
        for a in actions:
            f.write(json.dumps({"event": "serve", "time": time.time(),
                                **a}) + "\n")


@pytest.mark.tier1
def test_serve_group_invariant_passes_on_unit_restart(tmp_path):
    from distributedmnist_tpu.obsv.invariants import check_serve_group

    _write_group_log(tmp_path / "worker1", [
        {"action": "group_start", "ranks": 2, "attempt": 0},
        {"action": "rank_spawn", "rank": 0, "pid": 1},
        {"action": "rank_spawn", "rank": 1, "pid": 2},
        {"action": "rank_exit", "rank": 1, "pid": 2, "rc": -9},
        {"action": "group_down", "reason": "rank 1 exited (rc=-9)",
         "ranks": 2, "rank": 1},
        {"action": "group_restart", "attempt": 1, "backoff_s": 0.25},
        {"action": "group_start", "ranks": 2, "attempt": 1},
        {"action": "group_stop", "ranks": 2},
    ])
    violations, applicable = check_serve_group(tmp_path)
    assert applicable and not violations


@pytest.mark.tier1
def test_serve_group_invariant_catches_half_dead_group(tmp_path):
    from distributedmnist_tpu.obsv.invariants import check_serve_group

    # restart WITHOUT a group_down: the surviving rank was never killed
    _write_group_log(tmp_path / "worker1", [
        {"action": "group_start", "ranks": 2, "attempt": 0},
        {"action": "rank_exit", "rank": 1, "pid": 2, "rc": -9},
        {"action": "group_start", "ranks": 2, "attempt": 1},
    ])
    violations, applicable = check_serve_group(tmp_path)
    assert applicable
    assert any("no group_down" in v.detail for v in violations)

    # trailing unanswered rank_exit: the group may still be half-alive
    _write_group_log(tmp_path / "worker2", [
        {"action": "group_start", "ranks": 2, "attempt": 0},
        {"action": "rank_exit", "rank": 0, "pid": 1, "rc": 1},
    ])
    violations, _ = check_serve_group(tmp_path)
    assert any(v.worker == 2 for v in violations)


@pytest.mark.tier1
def test_check_run_skips_serve_group_without_group_log(tmp_path):
    from distributedmnist_tpu.obsv.invariants import check_run

    (tmp_path / "worker0").mkdir()
    res = check_run(tmp_path, outcome={})
    assert res["verdicts"]["serve_group"] == "skipped"


# ---------------------------------------------------------------------------
# shard digests
# ---------------------------------------------------------------------------

@pytest.mark.tier1
def test_rank_shard_digest_distinct_per_rank_and_deterministic():
    import jax

    from distributedmnist_tpu.core.config import ModelConfig
    from distributedmnist_tpu.models.registry import get_model
    from distributedmnist_tpu.servesvc.tp_group import rank_shard_digest

    model = get_model(ModelConfig(**LM_MODEL))
    params = jax.device_get(model.init(jax.random.PRNGKey(0)))
    specs = model.tp_param_specs("model")
    d0 = rank_shard_digest(params, specs, 0, 2)
    d1 = rank_shard_digest(params, specs, 1, 2)
    assert d0 != d1                      # ranks hold different shards
    assert d0 == rank_shard_digest(params, specs, 0, 2)
    # no specs → whole-tree digest, identical across ranks (the
    # documented degraded mode, still a digest)
    w0 = rank_shard_digest(params, None, 0, 2)
    assert w0 == rank_shard_digest(params, None, 1, 2)


# ---------------------------------------------------------------------------
# real TP replica boot (simulated mesh)
# ---------------------------------------------------------------------------

def test_decode_replica_boots_tensor_parallel(tmp_path):
    """tp_ranks=2 builds a replica=1 × model=2 serving mesh, and the
    mesh-portable restore actually SHARDS the followed checkpoint —
    at least the attention/FFN weights carry the model axis."""
    import jax

    from distributedmnist_tpu.core.config import (DecodeConfig,
                                                  ExperimentConfig,
                                                  ServeConfig)

    staging = tmp_path / "staging"
    cfg = ExperimentConfig.from_dict({
        "data": {"dataset": "synthetic_lm", "batch_size": 32,
                 "synthetic_train_size": 256, "synthetic_test_size": 64,
                 "use_native_pipeline": False},
        "model": dict(LM_MODEL),
        "train": {"max_steps": 10, "log_every_steps": 10,
                  "train_dir": str(staging),
                  "save_interval_steps": 10, "save_results_period": 0,
                  "async_checkpoint": False},
    })
    from distributedmnist_tpu.train.loop import Trainer
    Trainer(cfg).run()

    from distributedmnist_tpu.servesvc.decode import DecodeReplica
    rep = DecodeReplica(
        staging, serve_dir=tmp_path / "replica",
        scfg=ServeConfig(poll_secs=0.05, tp_ranks=2),
        dcfg=DecodeConfig(decode_slots=2, block_size=8, num_blocks=32,
                          max_prompt_len=16, max_new_tokens=4),
        cfg=cfg)
    assert rep.topo.mesh.shape["model"] == 2
    rep._load_initial(timeout_s=120)
    tp_leaves = [
        l for l in jax.tree.leaves(rep._params)
        if "model" in (ax for spec in [getattr(l.sharding, "spec", ())]
                       for entry in (spec or ())
                       for ax in (entry if isinstance(entry, tuple)
                                  else (entry,)) if ax)]
    assert tp_leaves, "no param leaf is sharded over the model axis"

    # a classification replica (MLP, no TP specs) refuses tp_ranks>1
    # with a config error instead of serving replicated silently
    from distributedmnist_tpu.core.config import ConfigError
    from distributedmnist_tpu.servesvc.server import ServingReplica
    mnist_cfg = ExperimentConfig.from_dict(
        {"data": {"dataset": "synthetic", "batch_size": 8}})
    with pytest.raises(ConfigError, match="tp_ranks"):
        ServingReplica(tmp_path / "nope", serve_dir=tmp_path / "nope2",
                       scfg=ServeConfig(tp_ranks=2), cfg=mnist_cfg)
