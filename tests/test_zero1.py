"""ZeRO-1 cross-replica sharded weight update
(``parallel.shard_weight_update``, arXiv:2004.13336): numerics parity
vs the replicated baseline on the 8-device mesh, masking semantics,
per-chip optimizer-state accounting, and the canonical-layout
checkpoint contract (save→restore roundtrip, restore across the knob,
digest stability for the determinism invariant)."""

import jax
import numpy as np
import pytest

from conftest import LOSS_TOL, assert_update_parity, base_config
from distributedmnist_tpu.data.datasets import make_synthetic
from distributedmnist_tpu.models.registry import get_model
from distributedmnist_tpu.parallel.api import (build_train_step,
                                               canonical_save_state,
                                               init_train_state,
                                               pack_restored_state,
                                               state_partition_specs,
                                               zero1_plan_for)
from distributedmnist_tpu.train import checkpoint as ckpt
from distributedmnist_tpu.train.loop import Trainer
from distributedmnist_tpu.train.lr_schedule import constant

pytestmark = pytest.mark.tier1

LR = 0.1


def _cfg(shard: bool, **over):
    sections = {"optim": {"momentum": 0.9},
                "parallel": {"shard_weight_update": shard}}
    for k, v in over.items():
        if isinstance(v, dict) and k in sections:
            sections[k].update(v)
        else:
            sections[k] = v
    return base_config(**sections)


def _run_steps(cfg, topo, batch, steps=4):
    model = get_model(cfg.model)
    state = topo.device_put_state(init_train_state(model, cfg, topo),
                                  state_partition_specs(model, cfg, topo))
    step_fn = build_train_step(model, cfg, topo, constant(LR))
    gbatch = topo.device_put_batch(batch)
    metrics_hist = []
    for _ in range(steps):
        state, m = step_fn(state, gbatch)
        metrics_hist.append(m)
    return state, metrics_hist


@pytest.fixture(scope="module")
def batch64():
    ds = make_synthetic(num_train=64, num_test=16)
    return {"image": ds.train.images[:64], "label": ds.train.labels[:64]}


def test_sharded_update_matches_replicated_sync(topo8, batch64):
    st_r, hist_r = _run_steps(_cfg(False), topo8, batch64)
    st_s, hist_s = _run_steps(_cfg(True), topo8, batch64)
    for mr, ms in zip(hist_r, hist_s):
        np.testing.assert_allclose(float(ms["loss"]), float(mr["loss"]),
                                   **LOSS_TOL)
    # pure-DP ZeRO-1 has no pcast-transpose caveat: compare params
    # directly too (tight), on top of the shim-aware helper
    assert_update_parity(jax.device_get(st_s.params),
                         jax.device_get(st_r.params))
    for a, b in zip(jax.tree.leaves(jax.device_get(st_s.params)),
                    jax.tree.leaves(jax.device_get(st_r.params))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-6)
    # and the sharded momentum unpacks to the replicated buffers
    plan = zero1_plan_for(get_model(_cfg(True).model), _cfg(True), topo8)
    mom_s = canonical_save_state(st_s, plan).momentum
    for a, b in zip(jax.tree.leaves(mom_s),
                    jax.tree.leaves(jax.device_get(st_r.momentum))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-6)


def test_sharded_update_matches_replicated_quorum(topo8, batch64):
    """Quorum masking composes: the same deterministic straggler draws
    select the same contributors under both disciplines, so losses and
    params agree."""
    over = {"sync": {"mode": "quorum", "num_replicas_to_aggregate": 5,
                     "straggler_profile": "lognormal"}}
    st_r, hist_r = _run_steps(_cfg(False, **over), topo8, batch64)
    st_s, hist_s = _run_steps(_cfg(True, **over), topo8, batch64)
    for mr, ms in zip(hist_r, hist_s):
        assert float(ms["num_contributors"]) == 5.0
        np.testing.assert_allclose(float(ms["loss"]), float(mr["loss"]),
                                   **LOSS_TOL)
    for a, b in zip(jax.tree.leaves(jax.device_get(st_s.params)),
                    jax.tree.leaves(jax.device_get(st_r.params))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-6)


def test_all_masked_step_is_true_noop(topo8, batch64):
    """timeout_ms=0 masks every replica: params, momentum and
    updates_applied must come through bitwise untouched (momentum decay
    is select-guarded on the shards)."""
    cfg = _cfg(True, sync={"mode": "timeout", "timeout_ms": 0.0})
    model = get_model(cfg.model)
    state = topo8.device_put_state(init_train_state(model, cfg, topo8),
                                   state_partition_specs(model, cfg, topo8))
    before_p = jax.device_get(state.params)
    before_m = jax.device_get(state.momentum)
    step_fn = build_train_step(model, cfg, topo8, constant(LR))
    state, m = step_fn(state, topo8.device_put_batch(batch64))
    assert float(m["num_contributors"]) == 0.0
    assert int(jax.device_get(state.updates_applied)) == 0
    for a, b in zip(jax.tree.leaves(before_p),
                    jax.tree.leaves(jax.device_get(state.params))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(jax.tree.leaves(before_m),
                    jax.tree.leaves(jax.device_get(state.momentum))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_momentum_state_is_replica_sharded(topo8):
    """The memory claim itself: per-chip momentum bytes under ZeRO-1
    land at ~1/8 of replicated (padding slack only)."""
    def bytes_per_chip(cfg):
        model = get_model(cfg.model)
        state = topo8.device_put_state(
            init_train_state(model, cfg, topo8),
            state_partition_specs(model, cfg, topo8))
        return sum(
            int(np.prod(l.sharding.shard_shape(l.shape))) * l.dtype.itemsize
            for l in jax.tree.leaves(state.momentum))
    rep, shd = bytes_per_chip(_cfg(False)), bytes_per_chip(_cfg(True))
    assert shd <= rep * (1 / 8 + 0.02), (shd, rep)


def test_interval_mode_falls_back_replicated(topo8):
    """interval mode keeps the windowed accumulator replicated: the
    knob is a documented no-op (plan None), and the step still builds
    and runs."""
    from jax.sharding import PartitionSpec
    from distributedmnist_tpu.parallel.partition_rules import \
        spec_is_replicated
    cfg = _cfg(True, sync={"mode": "interval", "interval_ms": 10.0})
    model = get_model(cfg.model)
    assert zero1_plan_for(model, cfg, topo8) is None
    specs = state_partition_specs(model, cfg, topo8)
    assert all(spec_is_replicated(s) for s in jax.tree.leaves(
        specs.momentum, is_leaf=lambda x: isinstance(x, PartitionSpec)))
    build_train_step(model, cfg, topo8, constant(LR))  # must not raise


# ---------------------------------------------------------------------------
# bucketed comm overlap + resident-sharded params (ISSUE 12 tentpole)
# ---------------------------------------------------------------------------

def _canon(state, cfg, topo):
    plan = zero1_plan_for(get_model(cfg.model), cfg, topo)
    return canonical_save_state(state, plan)


def test_bucketed_update_bitwise_equals_monolithic(topo8, batch64):
    """parallel.comm_buckets regroups the sharded leaves' collectives
    into layer-ordered buckets; the per-element cross-replica sums are
    unchanged, so losses, params AND canonical momentum must stay
    BITWISE equal to the monolithic (comm_buckets=1) path — the
    correctness bar PR 6 set, pinned exactly (no tolerance)."""
    cfg_m, cfg_b = _cfg(True), _cfg(True, parallel={
        "shard_weight_update": True, "comm_buckets": 4})
    st_m, hist_m = _run_steps(cfg_m, topo8, batch64)
    st_b, hist_b = _run_steps(cfg_b, topo8, batch64)
    for mm, mb in zip(hist_m, hist_b):
        assert float(mm["loss"]) == float(mb["loss"])  # bitwise
    for a, b in zip(jax.tree.leaves(jax.device_get(st_m.params)),
                    jax.tree.leaves(jax.device_get(st_b.params))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(jax.tree.leaves(_canon(st_m, cfg_m, topo8).momentum),
                    jax.tree.leaves(_canon(st_b, cfg_b, topo8).momentum)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_resident_sharded_bitwise_and_param_memory(topo8, batch64):
    """parallel.resident_sharded keeps the params themselves in the
    replica-split flat layout between steps (the arXiv:2004.13336 §5
    ending): losses and canonical params/momentum stay bitwise equal
    to the classic layout, per-chip param bytes drop to ~1/8 for the
    sharded leaves, and logical_params reassembles the replicated
    view the eval step consumes."""
    from distributedmnist_tpu.parallel.api import logical_params
    cfg_m = _cfg(True)
    cfg_r = _cfg(True, parallel={"shard_weight_update": True,
                                 "comm_buckets": 2,
                                 "resident_sharded": True})
    st_m, hist_m = _run_steps(cfg_m, topo8, batch64)
    st_r, hist_r = _run_steps(cfg_r, topo8, batch64)
    for mm, mr in zip(hist_m, hist_r):
        assert float(mm["loss"]) == float(mr["loss"])  # bitwise
    canon_m, canon_r = _canon(st_m, cfg_m, topo8), _canon(st_r, cfg_r, topo8)
    for a, b in zip(jax.tree.leaves(canon_m.params),
                    jax.tree.leaves(canon_r.params)):
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(a)), np.asarray(jax.device_get(b)))
    for a, b in zip(jax.tree.leaves(canon_m.momentum),
                    jax.tree.leaves(canon_r.momentum)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def param_bytes_per_chip(st):
        return sum(
            int(np.prod(l.sharding.shard_shape(l.shape))) * l.dtype.itemsize
            for l in jax.tree.leaves(st.params))
    rep, res = param_bytes_per_chip(st_m), param_bytes_per_chip(st_r)
    assert res <= rep * (1 / 8 + 0.02), (res, rep)

    plan_r = zero1_plan_for(get_model(cfg_r.model), cfg_r, topo8)
    for a, b in zip(jax.tree.leaves(
                        logical_params(st_r.params, plan_r, topo8)),
                    jax.tree.leaves(canon_m.params)):
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(a)), np.asarray(jax.device_get(b)))


def test_comm_bucket_assignment_layer_ordered_and_balanced(topo8):
    """The bucket partition is a pure function of the plan: contiguous
    in flatten (layer) order, covers every sharded leaf exactly once,
    clamps to the sharded-leaf count, and collapses to one bucket at
    comm_buckets=1."""
    from distributedmnist_tpu.parallel.partition_rules import \
        comm_bucket_assignment
    import dataclasses as dc
    cfg = _cfg(True, parallel={"shard_weight_update": True,
                               "comm_buckets": 3})
    plan = zero1_plan_for(get_model(cfg.model), cfg, topo8)
    buckets = comm_bucket_assignment(plan)
    flat = [i for b in buckets for i in b]
    assert flat == sorted(flat)  # contiguous, layer-ordered
    lps = jax.tree.leaves(plan.leaf_plans,
                          is_leaf=lambda x: hasattr(x, "sharded"))
    assert set(flat) == {i for i, lp in enumerate(lps) if lp.sharded}
    assert 1 <= len(buckets) <= min(3, len(flat))
    one = comm_bucket_assignment(dc.replace(plan, comm_buckets=1))
    assert len(one) == 1 and one[0] == flat
    many = comm_bucket_assignment(dc.replace(plan, comm_buckets=999))
    assert len(many) == len(flat)  # clamped to the sharded-leaf count


# ---------------------------------------------------------------------------
# checkpoint contract
# ---------------------------------------------------------------------------

def _trainer_cfg(shard: bool, train_dir: str, max_steps: int = 4):
    return _cfg(shard, train={"max_steps": max_steps, "log_every_steps": 2,
                              "save_interval_steps": 2,
                              "save_results_period": 0,
                              "train_dir": train_dir,
                              "async_checkpoint": False})


def test_checkpoint_roundtrip_and_cross_knob_restore(tmp_path,
                                                     synthetic_datasets):
    """Save→restore roundtrip of replica-sharded opt state is exact;
    the artifact is canonical, so it restores onto
    shard_weight_update=false (and the digests are the ones a
    replicated same-seed run produces)."""
    d1 = str(tmp_path / "shard")
    t1 = Trainer(_trainer_cfg(True, d1), topo=None,
                 datasets=synthetic_datasets)
    assert t1._zero1_plan is not None
    t1.run()
    flat_momentum = jax.device_get(t1.state.momentum)
    digest = ckpt.state_params_digest(t1.state)

    # resume under the SAME knob: momentum packs back bitwise
    t2 = Trainer(_trainer_cfg(True, d1), datasets=synthetic_datasets)
    assert int(jax.device_get(t2.state.step)) == 4
    for a, b in zip(jax.tree.leaves(flat_momentum),
                    jax.tree.leaves(jax.device_get(t2.state.momentum))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert ckpt.state_params_digest(t2.state) == digest

    # restore onto the replicated discipline: canonical layout loads
    # with no migration, momentum arrives in logical shapes
    t3 = Trainer(_trainer_cfg(False, d1), datasets=synthetic_datasets)
    assert t3._zero1_plan is None
    assert int(jax.device_get(t3.state.step)) == 4
    logical = canonical_save_state(
        t1.state, t1._zero1_plan).momentum
    for a, b in zip(jax.tree.leaves(logical),
                    jax.tree.leaves(jax.device_get(t3.state.momentum))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert ckpt.state_params_digest(t3.state) == digest

    # the reverse direction: a replicated run's checkpoint restores
    # onto shard_weight_update=true (pack on restore). d2 doubles as
    # the digest-stability acceptance: the replicated same-seed run's
    # artifact hashes identically (params AND canonical opt state) to
    # the sharded run's — what lets chaos invariant 3 compare runs
    # without caring which discipline produced which.
    d2 = str(tmp_path / "rep")
    t4 = Trainer(_trainer_cfg(False, d2), datasets=synthetic_datasets)
    t4.run()
    assert (ckpt.checkpoint_params_digest(d1)
            == ckpt.checkpoint_params_digest(d2))
    assert (ckpt.checkpoint_opt_state_digest(d1)
            == ckpt.checkpoint_opt_state_digest(d2))
    t5 = Trainer(_trainer_cfg(True, d2), datasets=synthetic_datasets)
    assert int(jax.device_get(t5.state.step)) == 4
    packed = pack_restored_state(
        canonical_save_state(t5.state, t5._zero1_plan), t5._zero1_plan)
    for leaf, lp in zip(
            jax.tree.leaves(packed.momentum),
            jax.tree.leaves(t5._zero1_plan.leaf_plans,
                            is_leaf=lambda x: hasattr(x, "sharded"))):
        if lp.sharded:
            assert leaf.shape == (lp.pad,)


def test_cross_knob_restore_bucketed_resident(tmp_path,
                                              synthetic_datasets):
    """ISSUE 12 cross-knob matrix extension: a checkpoint saved with
    comm_buckets=4 / resident_sharded=true restores BITWISE into the
    monolithic layout and vice versa — the canonical artifact contract
    holds across the new knobs (params digest, opt-state digest, and
    the packed momentum on the reverse graft)."""
    over = {"parallel": {"shard_weight_update": True, "comm_buckets": 4,
                         "resident_sharded": True}}
    d1 = str(tmp_path / "bucketres")
    t1 = Trainer(_cfg(True, **over,
                      train={"max_steps": 4, "log_every_steps": 2,
                             "save_interval_steps": 2,
                             "save_results_period": 0, "train_dir": d1,
                             "async_checkpoint": False}),
                 datasets=synthetic_datasets)
    assert t1._zero1_plan is not None and t1._zero1_plan.params_sharded
    s1 = t1.run()
    # overlap gauges surface in the timing report iff bucketing is on
    # (the prefetch_queue_depth pattern, obsv/timing.py)
    overlap = s1["timing"]["overlap"]
    assert overlap["bucket_count"] >= 1
    assert len(overlap["per_bucket_pad_elems"]) == overlap["bucket_count"]
    assert overlap["snapshot_stall_ms"]["count"] >= 1
    # live flat-layout state canonicalizes to the same digest a
    # replicated/monolithic same-seed run produces
    digest = s1["params_digest"]

    # bucketed+resident artifact → monolithic layout (buckets=1,
    # resident off): loads with no migration, digests agree
    t2 = Trainer(_trainer_cfg(True, d1), datasets=synthetic_datasets)
    assert int(jax.device_get(t2.state.step)) == 4
    assert ckpt.state_params_digest(t2.state) == digest

    # the reverse: a monolithic artifact restores into the
    # bucketed+resident layout; packed params land as [pad]-flat
    # replica shards and canonicalize back to the same digest
    d2 = str(tmp_path / "mono")
    t3 = Trainer(_trainer_cfg(True, d2), datasets=synthetic_datasets)
    s3 = t3.run()
    assert s3["params_digest"] == digest
    assert (ckpt.checkpoint_params_digest(d1)
            == ckpt.checkpoint_params_digest(d2))
    assert (ckpt.checkpoint_opt_state_digest(d1)
            == ckpt.checkpoint_opt_state_digest(d2))
    t4 = Trainer(_cfg(True, **over,
                      train={"max_steps": 4, "log_every_steps": 2,
                             "save_interval_steps": 2,
                             "save_results_period": 0, "train_dir": d2,
                             "async_checkpoint": False}),
                 datasets=synthetic_datasets)
    assert int(jax.device_get(t4.state.step)) == 4
    for leaf, lp in zip(
            jax.tree.leaves(t4.state.params),
            jax.tree.leaves(t4._zero1_plan.leaf_plans,
                            is_leaf=lambda x: hasattr(x, "sharded"))):
        if lp.sharded:
            assert leaf.shape == (lp.pad,)
    assert ckpt.state_params_digest(
        canonical_save_state(t4.state, t4._zero1_plan)) == digest


def test_cross_optimizer_restore_is_typed_error(tmp_path,
                                                synthetic_datasets):
    """Saving under one optimizer and restoring under another must
    raise the typed OptimizerStateMismatchError, not silently graft
    mismatched opt-state trees (momentum and LARS state even share a
    tree SHAPE, so a structural check alone would quietly corrupt the
    trust-ratio math)."""
    d = str(tmp_path / "xopt")
    Trainer(_trainer_cfg(False, d), datasets=synthetic_datasets).run()

    def trainer_with(optim_over):
        cfg = base_config(
            optim=optim_over,
            parallel={"shard_weight_update": False},
            train={"max_steps": 4, "log_every_steps": 2,
                   "save_interval_steps": 2, "save_results_period": 0,
                   "train_dir": d, "async_checkpoint": False})
        return Trainer(cfg, datasets=synthetic_datasets)

    # saved under momentum-SGD (the _trainer_cfg default): every other
    # state kind refuses, naming both sides
    for other in ({"name": "lamb", "momentum": 0.0},
                  {"name": "lars", "momentum": 0.0},
                  {"momentum": 0.0}):  # stateless sgd
        with pytest.raises(ckpt.OptimizerStateMismatchError,
                           match="momentum"):
            trainer_with(other)

    # same kind under a different hyperparameter restores fine
    t = trainer_with({"momentum": 0.8})
    assert int(jax.device_get(t.state.step)) == 4

    # the reverse direction: a lamb artifact refuses a momentum restore
    d2 = str(tmp_path / "xopt_lamb")
    cfg_lamb = base_config(
        optim={"name": "lamb", "momentum": 0.0,
               "initial_learning_rate": 1e-3},
        train={"max_steps": 4, "log_every_steps": 2,
               "save_interval_steps": 2, "save_results_period": 0,
               "train_dir": d2, "async_checkpoint": False})
    Trainer(cfg_lamb, datasets=synthetic_datasets).run()
    with pytest.raises(ckpt.OptimizerStateMismatchError, match="lamb"):
        Trainer(_trainer_cfg(False, d2), datasets=synthetic_datasets)


def test_determinism_invariant_covers_opt_state(tmp_path):
    """obsv/invariants.py #3: identical artifacts pass with the
    opt-state digest compared (not skipped); a doctored momentum buffer
    in an otherwise-identical checkpoint is a determinism violation.
    Handcrafted checkpoints — the verdict reads artifacts alone, no
    Trainer needed."""
    from distributedmnist_tpu.obsv.invariants import determinism_verdict

    state = {"params": {"w": np.arange(8, dtype=np.float32)},
             "momentum": {"w": np.full(8, 0.25, np.float32)},
             "step": np.int32(4)}
    ref = tmp_path / "ref"
    trial = tmp_path / "trial" / "worker0"
    for d in (ref, trial):
        ckpt.save_checkpoint(d, ("full", state), step=4)
    checked, violations = determinism_verdict(trial, ref)
    assert checked and violations == []

    # doctor ONLY the momentum in the trial's latest checkpoint
    import hashlib

    from flax import serialization

    def bump_first_array(node):
        for k in sorted(node):
            if isinstance(node[k], dict):
                if bump_first_array(node[k]):
                    return True
            else:
                leaf = np.array(node[k])
                leaf.reshape(-1)[0] += 1.0
                node[k] = leaf
                return True
        return False

    step = ckpt.latest_checkpoint_step(trial)
    path = trial / f"ckpt-{step:08d}.msgpack"
    payload = serialization.msgpack_restore(path.read_bytes())
    assert bump_first_array(payload["state"]["momentum"])
    data = serialization.msgpack_serialize(payload)
    path.write_bytes(data)
    (trial / (path.name + ".sha256")).write_text(
        hashlib.sha256(data).hexdigest())

    checked, violations = determinism_verdict(trial, ref)
    assert checked
    assert any("optimizer state" in v.detail for v in violations)
