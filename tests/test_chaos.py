"""Chaos campaign engine tests: seeded schedule generation, the
transient-stall primitive's restart-vs-wait race, the invariant
checker against clean AND doctored artifact sets, greedy schedule
shrinking, and a real (shell-payload) campaign through the CLI.

The jax-booting realization — a train-payload campaign whose
kill+corrupt trial ends bitwise equal to the fault-free reference —
is the ``slow``-marked e2e at the bottom.
"""

import json
from pathlib import Path

import pytest

from distributedmnist_tpu.launch.chaos import (_SHELL_PAYLOAD, ChaosCampaign,
                                               ChaosConfig, ChaosFault,
                                               ChaosSchedule,
                                               generate_schedule)
from distributedmnist_tpu.launch.cluster import (ClusterError,
                                                 LocalClusterConfig,
                                                 LocalProcessCluster)
from distributedmnist_tpu.launch.exec import (CommandExecutor, FaultPlan,
                                              RetryPolicy)
from distributedmnist_tpu.launch.supervisor import (ClusterSupervisor,
                                                    SupervisorConfig)
from distributedmnist_tpu.obsv import invariants as inv
from distributedmnist_tpu.obsv.journal import (load_recovery_events,
                                               summarize_chaos)

pytestmark = pytest.mark.tier1

# the campaign's own resuming shell payload (~20 steps/s, file
# "checkpoint" every 5 steps, each boot appends its start to boots.txt)
_LOOP = _SHELL_PAYLOAD.format(limit=400)


def _cluster(tmp_path, fault_plan=None, num_workers=2):
    cfg = LocalClusterConfig(name="chaos-t", workdir=str(tmp_path / "cl"),
                             num_workers=num_workers, train_command=_LOOP)
    ex = CommandExecutor(journal=cfg.root / "command_journal.jsonl",
                         retry=RetryPolicy(max_attempts=1),
                         fault_plan=fault_plan)
    return LocalProcessCluster(cfg, ex)


# ---------------------------------------------------------------------------
# drain: boot-aware per-worker stall clocks
# ---------------------------------------------------------------------------

class _StubDrainCluster:
    """Duck-typed stand-in for LocalProcessCluster: one live worker
    with a fixed progress reading and a controllable spawned_at."""

    def __init__(self, logdir, spawned_at, alive=True):
        self._worker = {"worker": 0, "pid": 1, "alive": alive,
                        "logdir": str(logdir), "spawned_at": spawned_at}

    def status(self):
        return {"state": "RUNNING", "workers": [dict(self._worker)]}

    def worker_progress(self):
        return {0: 7}  # static: no log movement, ever


def test_drain_stall_clock_waits_for_post_restart_first_log(tmp_path):
    """PR 4 rough edge: a worker restarted near the end of the run
    spends a whole jax boot (> drain_stall_s) with no log movement, and
    the old global stall clock killed it mid-boot. The clock must not
    start until the worker has logged at least one line AFTER its own
    (re)spawn; a genuinely stalled (already-logging) worker still gets
    the early give-up."""
    import time

    cfg = ChaosConfig(name="drain-t", workdir=str(tmp_path),
                      payload="shell", poll_secs=0.05,
                      drain_stall_s=0.25, drain_timeout_s=1.2)
    camp = ChaosCampaign(cfg)
    logdir = tmp_path / "worker0"
    logdir.mkdir()
    log = logdir / "train_log.jsonl"
    log.write_text('{"step": 7, "loss": 1.0}\n')

    # (a) mid-boot: the respawn postdates the last log line — the stall
    # clock stays parked and the drain rides to its hard timeout
    booting = _StubDrainCluster(logdir, spawned_at=time.time() + 3600)
    t0 = time.monotonic()
    camp._drain(booting)
    waited = time.monotonic() - t0
    assert waited >= 1.0, f"gave up on a booting worker after {waited:.2f}s"

    # (b) logged since its spawn, then stalled: early give-up applies
    stalled = _StubDrainCluster(logdir, spawned_at=time.time() - 3600)
    t0 = time.monotonic()
    camp._drain(stalled)
    waited = time.monotonic() - t0
    assert 0.2 <= waited < 1.0, f"early give-up missed ({waited:.2f}s)"

    # (c) no spawn timestamp at all (pre-upgrade state file): legacy
    # behavior — the stall clock runs
    legacy = _StubDrainCluster(logdir, spawned_at=None)
    t0 = time.monotonic()
    camp._drain(legacy)
    assert time.monotonic() - t0 < 1.0


def test_spawned_at_recorded_and_surfaced(tmp_path):
    """LocalProcessCluster stamps each incarnation's spawn time into
    the state file and status() — what the drain's boot detection keys
    off."""
    import time

    cluster = _cluster(tmp_path)
    try:
        cluster.create()
        before = time.time()
        cluster.run_train()
        st = cluster.status()
        w = st["workers"][0]
        assert w["spawned_at"] is not None and w["spawned_at"] >= before
        first = w["spawned_at"]
        cluster.restart_worker(0)
        st = cluster.status()
        assert st["workers"][0]["spawned_at"] >= first
    finally:
        cluster.kill_all()
        cluster.exec.close()


def test_promoted_standby_inherits_incarnation_spawned_at(tmp_path):
    """Satellite (PR 4 drain edge, standby flavor): promotion must
    stamp the worker's ``spawned_at`` with the PROMOTION time — the
    drain's per-incarnation stall clock then stays parked until the
    promoted process logs its first line in the adopted dir, exactly
    as for a cold restart's boot. Without the fresh stamp, the
    standby's ORIGINAL spawn time (long past) would unpark the clock
    immediately and an old log line would read as 'logged, then
    stalled'."""
    import time

    standby_cmd = ('touch "$DMT_STANDBY_ACTIVATION.ready"; '
                   'while [ ! -f "$DMT_STANDBY_ACTIVATION" ]; '
                   'do sleep 0.05; done; sleep 60')
    cfg = LocalClusterConfig(name="pr", workdir=str(tmp_path / "cl"),
                             num_workers=1, train_command="sleep 60",
                             standby_command=standby_cmd)
    ex = CommandExecutor(journal=cfg.root / "command_journal.jsonl",
                         retry=RetryPolicy(max_attempts=1))
    c = LocalProcessCluster(cfg, ex)
    try:
        c.create()
        c.run_train()
        first_spawn = c.status()["workers"][0]["spawned_at"]
        c.ensure_standbys(1)
        deadline = time.time() + 20
        while time.time() < deadline:
            if any(sb["ready"] for sb in c.status().get("standbys", [])):
                break
            time.sleep(0.1)
        else:
            raise AssertionError("standby never ready")
        # an OLD log line predating the promotion: must read as
        # "hasn't logged since promotion", i.e. clock parked
        log = Path(c.cfg.worker_dir(0)) / "train_log.jsonl"
        log.write_text('{"step": 3, "loss": 1.0}\n')
        before = time.time()
        assert c.promote_standby(0) is True
        w = c.status()["workers"][0]
        assert w["spawned_at"] >= before > first_spawn
        assert ChaosCampaign._logged_since_spawn(w) is False
        # the drain parks on exactly this reading (stub-clock cousin of
        # test_drain_stall_clock_waits_for_post_restart_first_log)
        camp = ChaosCampaign(ChaosConfig(name="prd",
                                         workdir=str(tmp_path / "d"),
                                         payload="shell", poll_secs=0.05,
                                         drain_stall_s=0.25,
                                         drain_timeout_s=1.2))
        t0 = time.monotonic()
        camp._drain(_StubDrainCluster(c.cfg.worker_dir(0),
                                      spawned_at=w["spawned_at"]))
        assert time.monotonic() - t0 >= 1.0, "drain gave up mid-adoption"
    finally:
        c.kill_all()
        ex.close()


def test_drain_closes_open_mttr_episode(tmp_path):
    """Regression (the first seeded campaign's mttr.episodes=0): a
    worker restarted near run-end finishes its boot DURING the drain —
    the drain must close the supervised loop's open recovery episode
    the tick that worker's log first moves since its own spawn, so the
    trial's MTTR still counts the episode. A worker that resumed,
    finished, and exited before the first drain tick (alive=False)
    closes too; one that never logged since spawn stays open."""
    import time

    cfg = ChaosConfig(name="drain-m", workdir=str(tmp_path),
                      payload="shell", poll_secs=0.05,
                      drain_stall_s=0.25, drain_timeout_s=1.2)
    camp = ChaosCampaign(cfg)
    logdir = tmp_path / "worker0"
    logdir.mkdir()
    (logdir / "train_log.jsonl").write_text('{"step": 7, "loss": 1.0}\n')

    def open_sup():
        sup = ClusterSupervisor(_StubDrainCluster(logdir, None))
        sup._watch_resume = {0}
        sup._detect_t[0] = time.time() - 5.0
        sup._respawn_t[0] = time.time() - 2.0
        sup.events.append({"event": "recovery", "action": "detect",
                           "worker": 0, "time": sup._detect_t[0]})
        return sup

    # (a) exited-after-finishing worker, log postdates its spawn: the
    # pre-return sweep closes the episode with the drain's progress step
    sup = open_sup()
    camp._drain(_StubDrainCluster(logdir, spawned_at=time.time() - 3600,
                                  alive=False), sup)
    assert sup.open_episodes == set()
    resume = next(e for e in sup.events if e["action"] == "resume")
    assert resume["worker"] == 0 and resume["step"] == 7
    assert resume["mttr_s"] == pytest.approx(5.0, abs=1.0)
    assert resume["resume_after_respawn_s"] == pytest.approx(2.0, abs=1.0)
    assert sup.summary()["mttr"]["episodes"] == 1

    # (b) still booting (spawn postdates the log): never falsely closed
    sup = open_sup()
    camp._drain(_StubDrainCluster(logdir, spawned_at=time.time() + 3600,
                                  alive=False), sup)
    assert sup.open_episodes == {0}
    assert sup.summary()["mttr"] == {"episodes": 0, "unrecovered": 1,
                                     "superseded": 0}

    # (c) log moved since spawn but the newest record is the restarted
    # trainer's compile event (it wedged before its first step): a
    # compile write is NOT a resume — the episode must stay open
    with open(logdir / "train_log.jsonl", "a") as fh:
        fh.write('{"event": "compile", "compile_s": 1.2}\n')
    sup = open_sup()
    camp._drain(_StubDrainCluster(logdir, spawned_at=time.time() - 3600,
                                  alive=False), sup)
    assert sup.open_episodes == {0}
    assert sup.summary()["mttr"]["unrecovered"] == 1


# ---------------------------------------------------------------------------
# adaptive stall timeout: derived from the measured boot, not hardcoded
# ---------------------------------------------------------------------------

def test_chaos_config_from_file_accepts_inline_json(tmp_path):
    # `--chaos-config` takes a file path OR inline JSON (every recipe in
    # verify SKILL.md uses the inline form) — both must parse identically.
    p = tmp_path / "c.json"
    p.write_text('{"seed": 9, "serve_fault_window": [3, 20]}')
    from_path = ChaosConfig.from_file(p)
    inline = ChaosConfig.from_file('{"seed": 9, "serve_fault_window": [3, 20]}')
    assert inline == from_path
    assert inline.seed == 9 and inline.serve_fault_window == (3, 20)
    with pytest.raises(ClusterError):
        ChaosConfig.from_file('{"not_a_knob": 1}')
    # CLI flag overrides merge BEFORE construction: a JSON arming
    # broker relies on `--payload serving` to satisfy __post_init__'s
    # cross-field check (a post-hoc replace() would raise at build)
    cfg = ChaosConfig.from_file(
        '{"broker": true, "broker_train_workers": 2}',
        overrides={"payload": "serving", "seed": 3})
    assert cfg.broker and cfg.payload == "serving" and cfg.seed == 3


def test_stall_timeout_derives_from_measured_boot():
    cfg = ChaosConfig()
    # un-measured: the historical worst-case default stands
    assert cfg.resolved_stall_timeout_s() == 90.0
    # measured warm boot: detection drops to mult×boot with a floor —
    # the regression this satellite exists for: a stalled warm worker
    # is detected in ~20 s, not 90
    assert cfg.resolved_stall_timeout_s(measured_boot_s=4.0) == 20.0
    assert cfg.resolved_stall_timeout_s(measured_boot_s=10.0) == 30.0
    # a slow box never loosens past the old cap
    assert cfg.resolved_stall_timeout_s(measured_boot_s=500.0) == 90.0
    # explicit config and the shell payload are untouched
    assert ChaosConfig(stall_timeout_s=7.0).resolved_stall_timeout_s(4.0) \
        == 7.0
    assert ChaosConfig(payload="shell").resolved_stall_timeout_s(4.0) == 2.5


def test_campaign_threads_reference_boot_into_trial_stall_timeout(tmp_path):
    """The campaign measures the reference run's spawn→first-log cost
    and derives every trial's stall timeout from it (then keeps
    re-deriving from each trial's own boots)."""
    cfg = ChaosConfig(name="boot", trials=2, seed=0, until_step=20,
                      workdir=str(tmp_path), payload="shell", shrink=False)
    seen: list[tuple[str, float | None, float]] = []

    class BootCampaign(ChaosCampaign):
        def _run_trial(self, rel, plan, seed, num_workers,
                       measured_boot_s=None):
            stall = self.cfg.resolved_stall_timeout_s(measured_boot_s)
            seen.append((rel, measured_boot_s, stall))
            root = self.cfg.root / rel
            root.mkdir(parents=True, exist_ok=True)
            (root / "command_journal.jsonl").write_text("")
            outcome = {"name": rel, "seed": seed, "target": 20,
                       "num_workers": num_workers, "outcome": "completed",
                       "step": 20, "boot_s": 6.0 if rel == "reference"
                       else 2.0,
                       "supervisor": {"quorum": 1,
                                      "max_restarts_per_worker": 2,
                                      "stall_timeout_s": stall},
                       "recovery": {"mttr": {"episodes": 0}},
                       "fault_plan": plan.to_json_dict(),
                       "duration_s": 0.0, "reference_dir": None}
            (root / "outcome.json").write_text(json.dumps(outcome))
            return outcome

    summary = BootCampaign(cfg).run()
    assert [s[0] for s in seen] == ["reference", "trial000", "trial001"]
    assert seen[0][1] is None                       # reference: unmeasured
    assert seen[1][1] == 6.0                        # ref's measured boot
    assert seen[2][1] == 2.0                        # trial000's warm boot
    # shell payload keeps its own default; the derivation is visible in
    # the per-trial report records regardless of payload
    report = (cfg.root / "chaos_report.jsonl").read_text().splitlines()
    recs = [json.loads(l) for l in report]
    assert [r["boot_s"] for r in recs] == [2.0, 2.0]
    assert all("mttr" in r for r in recs)
    assert "mttr" in summary and summary["mttr"]["episodes"] == 0


# ---------------------------------------------------------------------------
# schedule generation
# ---------------------------------------------------------------------------

def test_generate_schedule_seeded_and_bounded():
    a = generate_schedule(7, 3, 2, (6, 20), max_faults=3)
    b = generate_schedule(7, 3, 2, (6, 20), max_faults=3)
    assert a == b  # same (seed, trial) ⇒ same schedule, replayable
    kinds_seen = set()
    # sweep several seeds too — the nightly CI rotates the campaign
    # seed, so the invariants below must hold off the beaten path
    for seed in range(5):
        for t in range(10):
            s = generate_schedule(seed, t, 2, (6, 20), max_faults=3)
            assert s.faults, "min intensity is 1 fault"
            worker_kinds = [(f.kind, f.worker) for f in s.faults
                            if f.kind != "delay"]
            assert len(worker_kinds) == len(set(worker_kinds))
            # hang and stall never share a worker: the stall's timed
            # SIGCONT would silently resume the "permanent" hang
            for w in (0, 1):
                assert not ({("hang", w), ("stall", w)}
                            <= set(worker_kinds))
            # max_faults bounds intensity UNITS (corrupt+kill pair = 1)
            units = sum(1 for f in s.faults
                        if f.kind not in ("delay", "kill"))
            units += sum(1 for f in s.faults if f.kind == "kill"
                         and not any(g.kind == "corrupt"
                                     and g.worker == f.worker
                                     for g in s.faults))
            assert 1 <= units <= 3
            for f in s.faults:
                kinds_seen.add(f.kind)
                if f.kind == "delay":
                    assert f.verb in ("poll", "status", "progress")
                else:
                    assert 0 <= f.worker < 2
                    assert 6 <= f.step <= 20
                if f.kind == "stall":
                    assert f.ms > 0
            # a corrupt draw always rides with a kill on the SAME step
            for f in s.faults:
                if f.kind == "corrupt":
                    assert any(g.kind == "kill" and g.worker == f.worker
                               and g.step == f.step for g in s.faults), s
    # 20 seeded trials cover the whole primitive space
    assert {"kill", "hang", "stall", "corrupt"} <= kinds_seen


def test_schedule_to_fault_plan_json_roundtrip(tmp_path):
    s = ChaosSchedule(seed=1, trial=0, faults=(
        ChaosFault("kill", worker=0, step=9),
        ChaosFault("corrupt", worker=0, step=9),
        ChaosFault("stall", worker=1, step=7, ms=850.0),
        ChaosFault("hang", worker=1, step=12),
        ChaosFault("delay", verb="poll", ms=25.0)))
    plan = s.to_fault_plan()
    assert plan.stall_worker_for_ms_at_step == {1: (7, 850.0)}
    assert plan.kill_worker_at_step == {0: 9}
    # file-format roundtrip (what the shrunk reproducer is emitted as)
    p = tmp_path / "plan.json"
    p.write_text(json.dumps(plan.to_json_dict()))
    assert FaultPlan.from_file(p) == plan


# ---------------------------------------------------------------------------
# the transient-stall primitive: restart-vs-wait race
# ---------------------------------------------------------------------------

def test_transient_stall_recovers_alone_supervisor_waits(tmp_path):
    """A stall SHORTER than the stall timeout: the worker resumes by
    itself via the timed SIGCONT and the supervisor must NOT restart
    it — the race's wait side, untestable with the permanent hang."""
    c = _cluster(tmp_path, fault_plan=FaultPlan(
        stall_worker_for_ms_at_step={1: (5, 800)}))
    c.create()
    sup = ClusterSupervisor(c, SupervisorConfig(
        quorum=1, max_restarts_per_worker=2, restart_backoff_s=0.1,
        stall_timeout_s=3.0, seed=11))
    got = sup.run_until_step(60, poll_secs=0.2, timeout_secs=120.0)
    assert got["step"] >= 60
    by_action = got["recovery"]["by_action"]
    assert "restart" not in by_action and "detect" not in by_action
    raw = [json.loads(l) for l in c.exec.journal_path.read_text().splitlines()]
    stalls = [r for r in raw if r.get("action") == "stall_worker"]
    assert stalls and stalls[0]["worker"] == 1 and stalls[0]["stall_ms"] == 800
    # the worker actually moved again after the stall (one boot only)
    boots = (c.cfg.worker_dir(1) / "boots.txt").read_text().split()
    assert len(boots) == 1
    # satellite: the schedule seed is stamped on every recovery event
    events = load_recovery_events(c.exec.journal_path)
    assert events and all(e.get("seed") == 11 for e in events)
    c.delete()


def test_stall_past_timeout_loses_race_and_is_restarted(tmp_path):
    """A stall LONGER than the stall timeout: the supervisor's hang
    detector wins the race — kill + restart, and the run completes."""
    c = _cluster(tmp_path, fault_plan=FaultPlan(
        stall_worker_for_ms_at_step={1: (5, 8000)}))
    c.create()
    sup = ClusterSupervisor(c, SupervisorConfig(
        quorum=1, max_restarts_per_worker=2, restart_backoff_s=0.1,
        stall_timeout_s=1.0))
    got = sup.run_until_step(60, poll_secs=0.2, timeout_secs=120.0)
    assert got["step"] >= 60
    events = load_recovery_events(c.exec.journal_path)
    hung = [e for e in events if e["action"] == "detect"
            and e.get("kind") == "hung"]
    assert hung and hung[0]["worker"] == 1
    assert got["recovery"]["by_action"].get("restart", 0) >= 1
    c.delete()


# ---------------------------------------------------------------------------
# invariant checking: splicing, doctored artifacts
# ---------------------------------------------------------------------------

def test_splice_rollbacks_and_metrics_log_check():
    recs = [{"step": s} for s in [1, 2, 3, 4, 5, 6, 7, 8, 5, 6, 7, 8, 9]]
    spliced, rewinds = inv.splice_rollbacks(recs)
    assert [r["step"] for r in spliced] == list(range(1, 10))
    assert rewinds == 1
    assert inv.check_metrics_log(recs, allowed_rewinds=1) == []
    # an unexplained rewind (duplicate record, no journaled cause)
    v = inv.check_metrics_log(recs, allowed_rewinds=0)
    assert v and v[0].invariant == "metrics_log"
    # a gap survives splicing and is reported
    v = inv.check_metrics_log([{"step": s} for s in [1, 2, 3, 7]],
                              allowed_rewinds=0)
    assert any("gap" in x.detail for x in v)
    # a log that starts past step 1 lost its head
    v = inv.check_metrics_log([{"step": s} for s in [4, 5, 6]],
                              allowed_rewinds=0)
    assert any("starts at step 4" in x.detail for x in v)


def _clean_artifacts(root, steps=10):
    """A minimal healthy trial artifact set: one worker, a contiguous
    log, a detect→restart→resume episode in the command journal."""
    w0 = root / "worker0"
    w0.mkdir(parents=True)
    with open(w0 / "train_log.jsonl", "w") as fh:
        for s in range(1, steps + 1):
            fh.write(json.dumps({"step": s, "loss": 1.0}) + "\n")
    with open(root / "command_journal.jsonl", "w") as fh:
        for action in ("detect", "restart_scheduled", "restart", "resume"):
            fh.write(json.dumps({"event": "recovery", "action": action,
                                 "worker": 0}) + "\n")
    return {"outcome": "completed", "step": steps, "target": steps,
            "supervisor": {"quorum": 1, "max_restarts_per_worker": 2}}


def test_check_run_passes_on_clean_artifacts(tmp_path):
    outcome = _clean_artifacts(tmp_path)
    got = inv.check_run(tmp_path, outcome=outcome)
    assert got["violations"] == []
    assert got["verdicts"]["terminal_state"] == "pass"
    assert got["verdicts"]["metrics_log"] == "pass"
    assert got["verdicts"]["causality"] == "pass"
    assert got["verdicts"]["checkpoint_integrity"] == "pass"
    assert got["verdicts"]["determinism"] == "skipped"  # no reference


def test_checker_flags_duplicated_step_record(tmp_path):
    """Acceptance: a doctored artifact set with a duplicated step
    record must surface as the specific metrics_log violation."""
    outcome = _clean_artifacts(tmp_path)
    log = tmp_path / "worker0" / "train_log.jsonl"
    lines = log.read_text().splitlines()
    lines.insert(6, lines[5])  # duplicate one record; no extra cause
    # ...but the journal explains ONE rewind (the restart) — add a
    # second duplicate so the rewinds exceed every journaled cause
    lines.insert(9, lines[8])
    log.write_text("\n".join(lines) + "\n")
    got = inv.check_run(tmp_path, outcome=outcome)
    assert got["verdicts"]["metrics_log"] == "fail"
    assert any("rewind" in v["detail"] for v in got["violations"])


def test_checker_flags_restart_without_detect(tmp_path):
    """Acceptance: deleting the detect event breaks journal causality
    — a restart nobody detected a reason for."""
    outcome = _clean_artifacts(tmp_path)
    jpath = tmp_path / "command_journal.jsonl"
    recs = [json.loads(l) for l in jpath.read_text().splitlines()]
    recs = [r for r in recs if r["action"] != "detect"]
    jpath.write_text("".join(json.dumps(r) + "\n" for r in recs))
    got = inv.check_run(tmp_path, outcome=outcome)
    assert got["verdicts"]["causality"] == "fail"
    assert any("not preceded by a detect" in v["detail"]
               for v in got["violations"])


def test_checker_flags_fallback_restore_without_corruption_event(tmp_path):
    outcome = _clean_artifacts(tmp_path)
    with open(tmp_path / "worker0" / "recovery_journal.jsonl", "w") as fh:
        fh.write(json.dumps({"event": "recovery", "layer": "checkpoint",
                             "action": "fallback_restore", "step": 4}) + "\n")
    got = inv.check_run(tmp_path, outcome=outcome)
    assert got["verdicts"]["causality"] == "fail"


def test_checker_flags_digest_mismatch_unless_journaled_fault(tmp_path):
    outcome = _clean_artifacts(tmp_path)
    w0 = tmp_path / "worker0"
    (w0 / "ckpt-00000005.msgpack").write_bytes(b"torn bytes")
    (w0 / "ckpt-00000005.msgpack.sha256").write_text("0" * 64)
    got = inv.check_run(tmp_path, outcome=outcome)
    assert got["verdicts"]["checkpoint_integrity"] == "fail"
    assert any("sha256 mismatch" in v["detail"] for v in got["violations"])
    # ...but a corruption the INJECTOR journaled is the plan working
    with open(tmp_path / "command_journal.jsonl", "a") as fh:
        fh.write(json.dumps({"event": "fault",
                             "action": "corrupt_latest_checkpoint",
                             "worker": 0,
                             "target": "ckpt-00000005.msgpack"}) + "\n")
    got = inv.check_run(tmp_path, outcome=outcome)
    assert got["verdicts"]["checkpoint_integrity"] == "pass"


def test_checker_flags_illegal_terminal_state(tmp_path):
    outcome = _clean_artifacts(tmp_path)
    outcome.update(outcome="aborted", error="weird crash")
    got = inv.check_run(tmp_path, outcome=outcome)
    assert got["verdicts"]["terminal_state"] == "fail"
    assert any("below_quorum_abort" in v["detail"]
               for v in got["violations"])


def test_pointer_must_resolve(tmp_path):
    outcome = _clean_artifacts(tmp_path)
    (tmp_path / "worker0" / "checkpoint.json").write_text(
        json.dumps({"latest_step": 9, "latest_path": "ckpt-gone.msgpack"}))
    got = inv.check_run(tmp_path, outcome=outcome)
    assert got["verdicts"]["checkpoint_integrity"] == "fail"


# ---------------------------------------------------------------------------
# shrinking
# ---------------------------------------------------------------------------

def test_shrink_faults_finds_single_culprit():
    culprit = ChaosFault("kill", worker=1, step=7)
    extras = (ChaosFault("stall", worker=0, step=6, ms=500.0),
              ChaosFault("hang", worker=0, step=9),
              ChaosFault("delay", verb="poll", ms=20.0))
    minimal, probes = inv.shrink_faults(
        extras[:1] + (culprit,) + extras[1:],
        lambda fs: culprit in fs)
    assert minimal == (culprit,)
    assert probes <= 12


def test_campaign_shrinks_seeded_synthetic_failure(tmp_path):
    """Acceptance: shrinking on a seeded synthetic failure emits the
    minimal reproducer FaultPlan. The trial runner is stubbed with an
    artifact fabricator whose invariant violation persists iff the
    kill fault is present — the campaign must shrink seed 0 / trial 0's
    corrupt+kill pair down to the kill alone and write the plan."""
    cfg = ChaosConfig(name="synth", trials=1, seed=0, until_step=20,
                      workdir=str(tmp_path), payload="shell",
                      shrink=True, shrink_max_probes=8)

    class SyntheticCampaign(ChaosCampaign):
        def _run_trial(self, rel, plan, seed, num_workers,
                       measured_boot_s=None):
            root = self.cfg.root / rel
            root.mkdir(parents=True, exist_ok=True)
            (root / "command_journal.jsonl").write_text("")
            # the "bug": any run containing a kill stops short of target
            buggy = bool(plan.kill_worker_at_step)
            outcome = {"name": rel, "seed": seed, "target": 20,
                       "num_workers": num_workers,
                       "outcome": "completed",
                       "step": 12 if buggy else 20,
                       "supervisor": {"quorum": 1,
                                      "max_restarts_per_worker": 2},
                       "fault_plan": plan.to_json_dict(),
                       "duration_s": 0.0, "reference_dir": None}
            (root / "outcome.json").write_text(json.dumps(outcome))
            return outcome

    summary = SyntheticCampaign(cfg).run()
    assert summary["all_green"] is False
    assert summary["failing_trials"][0]["invariants"] == ["terminal_state"]
    assert len(summary["reproducers"]) == 1
    repro = FaultPlan.from_file(summary["reproducers"][0])
    # seed 0 trial 0 generates corrupt(w1)+kill(w1); the corrupt fault
    # is innocent here, so the minimal reproducer is the kill alone
    assert repro.kill_worker_at_step and not \
        repro.corrupt_latest_checkpoint_at_step
    report = json.loads((cfg.root / "chaos_report.json").read_text())
    assert report["reproducers"] == summary["reproducers"]


# ---------------------------------------------------------------------------
# a real campaign over shell-payload worker processes, through the CLI
# ---------------------------------------------------------------------------

def test_chaos_cli_shell_campaign_all_green(tmp_path, capsys):
    from distributedmnist_tpu.launch.cluster import main
    ccfg = tmp_path / "chaos.json"
    ccfg.write_text(json.dumps({"workdir": str(tmp_path / "cw"),
                                "num_workers": 2,
                                "trial_timeout_s": 90.0,
                                "drain_timeout_s": 30.0}))
    main(["chaos", "--trials", "2", "--seed", "0", "--until-step", "20",
          "--payload", "shell", "--no-shrink", "--chaos-config", str(ccfg)])
    summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert summary["trials"] == 2
    assert summary["all_green"] is True, summary
    assert summary["outcomes"] == {"completed": 2}
    # every applicable invariant green, determinism skipped (no real
    # checkpoints in the shell payload)
    assert summary["invariants"]["determinism"]["skipped"] == 2
    for invariant in ("terminal_state", "metrics_log", "causality",
                      "checkpoint_integrity"):
        assert summary["invariants"][invariant]["pass"] == 2
    # the report names every trial's schedule + verdicts, and a second
    # summarize pass over the artifact reproduces the printed summary
    report = tmp_path / "cw" / "chaos" / "chaos_report.jsonl"
    trials = [json.loads(l) for l in report.read_text().splitlines()]
    assert [t["trial"] for t in trials] == [0, 1]
    assert all(t["schedule"]["faults"] and t["verdicts"] for t in trials)
    again = summarize_chaos(report)
    assert again["all_green"] and again["outcomes"] == {"completed": 2}


# ---------------------------------------------------------------------------
# acceptance e2e: REAL `launch train` workers under a kill+corrupt
# schedule — the recovered trial's final params are BITWISE equal to
# the fault-free same-seed reference (slow: boots jax ~4x)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_train_campaign_kill_corrupt_trial_bitwise_deterministic(tmp_path):
    from distributedmnist_tpu.train.checkpoint import checkpoint_params_digest
    cfg = ChaosConfig(name="e2e", trials=1, seed=0, until_step=40,
                      workdir=str(tmp_path), payload="train",
                      save_interval_steps=5, shrink=False,
                      trial_timeout_s=600.0, drain_timeout_s=300.0)
    # seed 0 / trial 0 is the corrupt+kill pair on worker 1 (asserted
    # here so a generator change that would silently drop the
    # acceptance scenario fails loudly instead)
    sched = generate_schedule(0, 0, 2, cfg.step_window(),
                              max_faults=cfg.max_faults,
                              stall_ms_range=cfg.resolved_stall_ms_range())
    kinds = {f.kind for f in sched.faults}
    assert "corrupt" in kinds and "kill" in kinds
    summary = ChaosCampaign(cfg).run()
    assert summary["all_green"] is True, summary
    assert summary["invariants"]["determinism"]["pass"] == 1
    # belt and braces on the acceptance claim: recompute both digests
    ref = checkpoint_params_digest(cfg.root / "reference" / "worker0")
    trial = json.loads((cfg.root / "trial000" / "outcome.json").read_text())
    assert trial["outcome"] == "completed"
    for w in (0, 1):
        got = checkpoint_params_digest(cfg.root / "trial000" / f"worker{w}")
        assert got == ref, (w, got, ref)
    # the episode is replayable from the artifact alone: every recovery
    # event carries the schedule seed
    events = load_recovery_events(cfg.root / "trial000"
                                  / "command_journal.jsonl")
    assert events and all(e.get("seed") == 0 for e in events)
