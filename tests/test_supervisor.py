"""Self-healing supervisor tests: REAL worker processes, injected
faults, automatic recovery (the tentpole of the robustness PR).

Tier-1 tests use the same cheap shell-loop payload as
``test_fault_injection.py`` — extended with a file-based
checkpoint/resume so a restarted worker observably continues from its
last save instead of step 1. The jax-booting realization (real
``launch train`` workers, kill + corrupt-latest-checkpoint, Trainer
fallback resume) is the ``slow``-marked e2e at the bottom.
"""

import json
import time

import pytest

from distributedmnist_tpu.launch.cluster import (ClusterError,
                                                 LocalClusterConfig,
                                                 LocalProcessCluster)
from distributedmnist_tpu.launch.exec import (CommandExecutor, FaultPlan,
                                              RetryPolicy)
from distributedmnist_tpu.launch.supervisor import (ClusterSupervisor,
                                                    SupervisorConfig)
from distributedmnist_tpu.obsv.journal import (load_recovery_events,
                                               summarize_recovery)

pytestmark = pytest.mark.tier1

# ~50 ms per step with a file "checkpoint" every 5 steps: a restarted
# worker resumes from `ckpt` instead of step 1, making resume-from-
# checkpoint observable without booting jax. Each boot appends its
# starting step to boots.txt — the unambiguous resume evidence (a log
# rewind can vanish when a kill lands exactly on a checkpoint boundary)
_RESUMING_LOOP = ('i=$( [ -f ckpt ] && cat ckpt || echo 0 ); '
                  'echo $i >> boots.txt; '
                  'while [ $i -lt 400 ]; do i=$((i+1)); '
                  'echo "{\\"step\\": $i, \\"loss\\": 1.0}" '
                  '>> train_log.jsonl; '
                  'if [ $((i % 5)) -eq 0 ]; then echo $i > ckpt; fi; '
                  'sleep 0.05; done')


def _cluster(tmp_path, fault_plan=None, num_workers=2,
             train_command=_RESUMING_LOOP):
    cfg = LocalClusterConfig(name="sup", workdir=str(tmp_path / "cl"),
                             num_workers=num_workers,
                             train_command=train_command)
    ex = CommandExecutor(journal=cfg.root / "command_journal.jsonl",
                         retry=RetryPolicy(max_attempts=1),
                         fault_plan=fault_plan)
    return LocalProcessCluster(cfg, ex)


def _worker_steps(cluster, k):
    log = cluster.cfg.worker_dir(k) / "train_log.jsonl"
    return [json.loads(l)["step"] for l in log.read_text().splitlines()]


def test_supervisor_restarts_killed_worker_resumes_from_checkpoint(tmp_path):
    """The core loop: a mid-run worker kill is detected, the worker is
    restarted within its budget, it resumes from its last checkpoint
    (not step 1), and the run reaches the target — the journal alone
    shows the detect → restart → resume chain."""
    # kill once worker 1's OWN log shows step >= 7: its step-5 ckpt
    # exists by then, so the restart observably resumes mid-sequence
    c = _cluster(tmp_path, fault_plan=FaultPlan(kill_worker_at_step={1: 7}))
    c.create()
    sup = ClusterSupervisor(c, SupervisorConfig(
        quorum=1, max_restarts_per_worker=2, restart_backoff_s=0.1))
    # target well past the kill: the run now ends as soon as the
    # FASTEST worker reaches it, so leave room for the restarted
    # worker's detect → restart → resume chain to land first
    got = sup.run_until_step(45, poll_secs=0.2, timeout_secs=120.0)
    assert got["step"] >= 45
    assert got["recovery"]["restarts_by_worker"] == {1: 1}

    s = summarize_recovery(c.exec.journal_path)
    chain = s["by_worker"][1]
    assert [a for a in chain if a in ("detect", "restart", "resume")] == \
        ["detect", "restart", "resume"]
    # degraded then healthy again
    degraded = [q["degraded"] for q in s["quorum_transitions"]]
    assert True in degraded and degraded[-1] is False
    # the restarted worker resumed from its ckpt file, not from scratch:
    # boots.txt records each boot's starting step — the second boot
    # starts at the checkpointed step (a multiple of 5, never 0)
    boots = [int(l) for l in (c.cfg.worker_dir(1) / "boots.txt")
             .read_text().split()]
    assert len(boots) == 2 and boots[0] == 0, boots
    assert boots[1] > 0 and boots[1] % 5 == 0, boots
    c.delete()


# A GATED realization of the resuming loop for races the poll cadence
# used to lose under contention: the worker runs freely to step 2, then
# HOLDS until the test drops a `go` file in the cluster root (`..` from
# each worker's cwd). The test controls exactly when the survivors may
# outrun the supervisor — the poll-cadence assumption the flake note in
# PR 10 asked to make explicit, as a release gate instead of a timing
# bet.
_GATED_LOOP = ('i=$( [ -f ckpt ] && cat ckpt || echo 0 ); '
               'echo $i >> boots.txt; '
               'while [ $i -lt 400 ]; do '
               'if [ $i -ge 2 ]; then '
               'while [ ! -f ../go ]; do sleep 0.05; done; fi; '
               'i=$((i+1)); '
               'echo "{\\"step\\": $i, \\"loss\\": 1.0}" '
               '>> train_log.jsonl; '
               'if [ $((i % 5)) -eq 0 ]; then echo $i > ckpt; fi; '
               'sleep 0.05; done')


def test_degraded_quorum_continues_when_budget_exhausted(tmp_path):
    """A worker with no restart budget left degrades the cluster; with
    ``workers_alive >= quorum`` the run keeps going to the target
    instead of today's all-or-nothing fail-fast.

    Deterministic by construction (the PR 10 deflake): the old shape
    raced the fault trigger + detection polls against a free-running
    45-steps/s shell payload, and under box contention the survivors
    reached the target before the supervisor ever observed the death —
    identical failure at pristine HEAD. Now the payload HOLDS at step
    2 until the test releases it: worker 2 is killed outright before
    supervision starts, the first poll deterministically sees it dead
    (detect → budget exhausted → degraded quorum), and only THEN are
    the survivors released to run to the target."""
    import threading

    c = _cluster(tmp_path, num_workers=3, train_command=_GATED_LOOP)
    c.create()
    c.run_train()
    try:
        # wait for worker 2 to boot and reach its hold point, then
        # kill it — no fault-plan/poll race, the death precedes tick 1
        log2 = c.cfg.worker_dir(2) / "train_log.jsonl"
        deadline = time.time() + 60.0
        while time.time() < deadline:
            if log2.exists() and log2.read_text().strip():
                break
            time.sleep(0.05)
        else:
            raise AssertionError("worker 2 never produced a log line")
        c.kill_all(worker="2")

        sup = ClusterSupervisor(c, SupervisorConfig(
            quorum=2, max_restarts_per_worker=0))
        result: dict = {}

        def supervise():
            result["got"] = sup.supervise_until_step(
                15, poll_secs=0.2, timeout_secs=120.0)

        th = threading.Thread(target=supervise, daemon=True)
        th.start()
        # explicit ordering: the budget-exhausted event must land
        # BEFORE the survivors may move past their hold point
        deadline = time.time() + 60.0
        while time.time() < deadline:
            if any(e["action"] == "restart_budget_exhausted"
                   for e in sup.events):
                break
            time.sleep(0.05)
        else:
            raise AssertionError("budget exhaustion never journaled")
        (c.cfg.root / "go").touch()
        th.join(timeout=120.0)
        assert not th.is_alive(), "supervised run did not finish"
        got = result["got"]
        assert got["step"] >= 15
        by_action = got["recovery"]["by_action"]
        assert by_action.get("restart_budget_exhausted") == 1
        assert "restart" not in by_action
        s = summarize_recovery(c.exec.journal_path)
        assert s["quorum_transitions"][0]["workers_alive"] == 2
        assert s["quorum_transitions"][0]["degraded"] is True
    finally:
        c.kill_all()
    c.delete()


def test_restart_restores_quorum_instead_of_aborting(tmp_path):
    """Regression: the below-quorum check must not fire off the stale
    liveness snapshot taken BEFORE this tick's restart — with
    quorum == num_workers, the first recovery would otherwise abort the
    run right after the restart that saved it."""
    c = _cluster(tmp_path, fault_plan=FaultPlan(kill_worker_at_step={1: 7}))
    c.create()
    sup = ClusterSupervisor(c, SupervisorConfig(
        quorum=2, max_restarts_per_worker=2, restart_backoff_s=0.1))
    got = sup.run_until_step(30, poll_secs=0.2, timeout_secs=120.0)
    assert got["step"] >= 30
    assert got["recovery"]["by_action"].get("restart") == 1
    assert "below_quorum_abort" not in got["recovery"]["by_action"]
    c.delete()


def test_degraded_run_finishes_when_worker0_is_the_lost_one(tmp_path):
    """Regression: target progress must follow the FASTEST worker's
    log, not only worker 0's tail — a degraded run whose permanently
    dead worker is worker 0 still finishes on the survivors."""
    c = _cluster(tmp_path, fault_plan=FaultPlan(kill_worker_at_step={0: 3}))
    c.create()
    sup = ClusterSupervisor(c, SupervisorConfig(
        quorum=1, max_restarts_per_worker=0))
    got = sup.run_until_step(20, poll_secs=0.2, timeout_secs=60.0)
    assert got["step"] >= 20  # reached via worker 1's log
    by_action = got["recovery"]["by_action"]
    assert by_action.get("restart_budget_exhausted") == 1
    c.delete()


def test_below_quorum_aborts_when_nothing_restartable(tmp_path):
    """Dropping under quorum with the budget exhausted fails loudly —
    degraded continuation is bounded, not unconditional."""
    c = _cluster(tmp_path, fault_plan=FaultPlan(kill_worker_at_step={1: 2}))
    c.create()
    sup = ClusterSupervisor(c, SupervisorConfig(
        quorum=2, max_restarts_per_worker=0))
    with pytest.raises(ClusterError, match="< quorum 2"):
        sup.run_until_step(50, poll_secs=0.2, timeout_secs=120.0)
    raw = load_recovery_events(c.exec.journal_path)
    assert any(r["action"] == "below_quorum_abort" for r in raw)
    # run_until_step's finally tore the cluster down
    time.sleep(0.2)
    assert not any(w["alive"] for w in c.status()["workers"])
    c.delete()


def test_hung_worker_detected_by_stall_and_restarted(tmp_path):
    """FaultPlan.hang_worker_at_step SIGSTOPs a worker: the pid stays
    alive (invisible to the liveness probe) while its log stalls — the
    supervisor's progress-based stall detector must kill + restart it."""
    c = _cluster(tmp_path, fault_plan=FaultPlan(hang_worker_at_step={1: 3}))
    c.create()
    sup = ClusterSupervisor(c, SupervisorConfig(
        quorum=1, max_restarts_per_worker=2, restart_backoff_s=0.1,
        stall_timeout_s=1.0))
    # target far enough past the hang (step 3) that detection (~1 s),
    # restart, and resume all land before worker 0 finishes
    got = sup.run_until_step(70, poll_secs=0.2, timeout_secs=120.0)
    assert got["step"] >= 70
    s = summarize_recovery(c.exec.journal_path)
    hung = [r for r in load_recovery_events(c.exec.journal_path)
            if r["action"] == "detect" and r.get("kind") == "hung"]
    assert hung and hung[0]["worker"] == 1
    assert s["by_action"].get("restart", 0) >= 1
    assert s["resume_steps"].get(1, -1) >= 0
    c.delete()


def test_stale_state_file_tolerated_without_manual_cleanup(tmp_path):
    """Satellite: a garbled state.json (a previous driver killed
    mid-run) must not wedge the lifecycle — create/run work, and a
    stale recorded pid that is STILL alive is reaped before respawn so
    two generations of workers never write the same logs."""
    import subprocess

    c = _cluster(tmp_path)
    c.create()
    # (a) corrupt state file → treated as absent, create() rebuilds
    c.state_path.write_text("{torn json" )
    assert c.status()["state"] == "ABSENT"
    c.create()
    state = json.loads(c.state_path.read_text())
    assert state["phase"] == "created"

    # (b) stale state with a live leftover pid → reaped on run_train
    straggler = subprocess.Popen(["sleep", "60"])
    state["workers"][0]["pid"] = straggler.pid
    state["phase"] = "running"
    c.state_path.write_text(json.dumps(state))
    c.run_train()
    try:
        time.sleep(0.3)
        assert straggler.poll() is not None  # the leftover was killed
        raw = [json.loads(l) for l in
               c.exec.journal_path.read_text().splitlines()]
        assert any(r.get("action") == "stale_worker_reaped" and
                   r.get("pid") == straggler.pid for r in raw)
        assert any(r.get("action") == "stale_state" for r in raw)
        # the fresh workers are alive and logging
        assert sum(w["alive"] for w in c.status()["workers"]) == 2
    finally:
        c.kill_all()
        if straggler.poll() is None:
            straggler.kill()
    c.delete()


def test_fault_plan_new_actions_roundtrip_from_file(tmp_path):
    plan_path = tmp_path / "plan.json"
    plan_path.write_text(json.dumps({
        "kill_worker_at_step": {"0": 5},
        "hang_worker_at_step": {"1": 7},
        "corrupt_latest_checkpoint_at_step": {"1": 7},
    }))
    plan = FaultPlan.from_file(plan_path)
    assert plan.kill_worker_at_step == {0: 5}
    assert plan.hang_worker_at_step == {1: 7}
    assert plan.corrupt_latest_checkpoint_at_step == {1: 7}


def test_corrupt_latest_checkpoint_fault_truncates_pointer_target(tmp_path):
    """The corrupt action hits exactly the file the pointer names, once
    a poll observes the trigger step."""
    c = _cluster(tmp_path, fault_plan=FaultPlan(
        corrupt_latest_checkpoint_at_step={0: 3}))
    c.create()
    wd = c.cfg.worker_dir(0)
    target = wd / "ckpt-00000004.msgpack"
    target.write_bytes(b"x" * 1000)
    (wd / "checkpoint.json").write_text(json.dumps(
        {"latest_step": 4, "latest_path": target.name}))
    (wd / "train_log.jsonl").write_text('{"step": 5}\n')
    c.poll()
    assert target.stat().st_size == 500
    raw = [json.loads(l) for l in
           c.exec.journal_path.read_text().splitlines()]
    ev = [r for r in raw if r.get("action") == "corrupt_latest_checkpoint"]
    assert ev and ev[0]["target"] == target.name
    c.poll()  # fires at most once
    assert target.stat().st_size == 500
    c.delete()


def test_supervise_cli_dry_run(tmp_path, capsys):
    from distributedmnist_tpu.launch.cluster import main
    cfgp = tmp_path / "c.json"
    cfgp.write_text(json.dumps({"workdir": str(tmp_path / "w")}))
    main(["supervise", "--backend", "local", "--config", str(cfgp),
          "--until-step", "5", "--quorum", "2", "--dry-run"])
    out = capsys.readouterr().out
    assert '"dry_run": true' in out


# ---------------------------------------------------------------------------
# warm standbys + MTTR (ROADMAP item 5)
# ---------------------------------------------------------------------------

# A standby realization of the resuming shell loop: signal ready, park
# until the promotion writes the activation file, then adopt the
# assigned worker dir and run the same loop there (what `launch train`
# does natively via DMT_STANDBY_ACTIVATION + Trainer.adopt_train_dir).
_STANDBY_LOOP = (
    'touch "$DMT_STANDBY_ACTIVATION.ready"; '
    'while [ ! -f "$DMT_STANDBY_ACTIVATION" ]; do sleep 0.05; done; '
    'cd "$(python3 -c "import json,os;'
    "print(json.load(open(os.environ['DMT_STANDBY_ACTIVATION']))"
    "['train_dir'])" '")" && ' + _RESUMING_LOOP)


def _standby_cluster(tmp_path, fault_plan=None,
                     standby_command=_STANDBY_LOOP):
    cfg = LocalClusterConfig(name="sup", workdir=str(tmp_path / "cl"),
                             num_workers=2, train_command=_RESUMING_LOOP,
                             standby_command=standby_command)
    ex = CommandExecutor(journal=cfg.root / "command_journal.jsonl",
                         retry=RetryPolicy(max_attempts=1),
                         fault_plan=fault_plan)
    return LocalProcessCluster(cfg, ex)


def test_standby_promotion_resumes_worker_with_mttr(tmp_path):
    """A killed worker is recovered by PROMOTING the parked standby
    (journaled as restart via=standby), which resumes from the dead
    worker's checkpoint; the resume event closes the episode with
    detect→respawned→first-moved-step latencies and the summary
    reports MTTR percentiles. The pool back-fills after promotion."""
    c = _standby_cluster(tmp_path,
                         fault_plan=FaultPlan(kill_worker_at_step={1: 7}))
    c.create()
    sup = ClusterSupervisor(c, SupervisorConfig(
        quorum=1, max_restarts_per_worker=2, restart_backoff_s=0.1,
        standby_workers=1))
    got = sup.run_until_step(60, poll_secs=0.2, timeout_secs=120.0)
    assert got["step"] >= 60
    restart = next(e for e in sup.events if e["action"] == "restart")
    assert restart["via"] == "standby"
    assert restart["respawn_s"] >= 0
    resume = next(e for e in sup.events if e["action"] == "resume")
    assert resume["mttr_s"] > 0
    assert resume["detected_at"] <= resume["respawned_at"]
    mttr = got["recovery"]["mttr"]
    assert mttr["episodes"] == 1 and mttr["p50_s"] == mttr["max_s"] > 0
    # promoted process adopted worker 1's dir and RESUMED from its ckpt
    boots = [int(l) for l in (c.cfg.worker_dir(1) / "boots.txt")
             .read_text().split()]
    assert len(boots) == 2 and boots[1] > 0 and boots[1] % 5 == 0, boots
    # per-incarnation clock: promotion stamped a fresh spawned_at on
    # the worker (the chaos drain's stall parking keys off it)
    w1 = next(w for w in c.status()["workers"] if w["worker"] == 1)
    assert w1["spawned_at"] >= resume["respawned_at"] - 1.0
    # the pool back-filled with a FRESH slot id (never the consumed
    # standby's dir, where a stale activation file would instantly
    # mis-activate the new spare)
    state = json.loads(c.state_path.read_text())
    assert [sb["standby"] for sb in state["standbys"]] == [1]
    c.delete()


def test_no_ready_standby_falls_back_to_cold_restart(tmp_path):
    """Standbys that never reach ready (still booting, wedged) must not
    stall recovery: the due restart falls back to a cold respawn."""
    c = _standby_cluster(tmp_path,
                         fault_plan=FaultPlan(kill_worker_at_step={1: 7}),
                         standby_command="sleep 600")  # never ready
    c.create()
    sup = ClusterSupervisor(c, SupervisorConfig(
        quorum=1, max_restarts_per_worker=2, restart_backoff_s=0.1,
        standby_workers=1))
    got = sup.run_until_step(45, poll_secs=0.2, timeout_secs=120.0)
    assert got["step"] >= 45
    restart = next(e for e in sup.events if e["action"] == "restart")
    assert restart["via"] == "respawn"
    assert got["recovery"]["mttr"]["episodes"] == 1
    c.delete()


class _ScriptedBackend:
    """Scripted poll sequence — deterministic tick-level control the
    real process cluster can't give: worker 1 dies, is restarted, and
    its log first moves on the SAME tick worker 0 reaches the target."""

    def __init__(self, script):
        self.script = script  # [(step, {worker: alive}, {worker: step})]
        self.tick = 0
        self.restarted = []

    def _frame(self):
        return self.script[min(self.tick, len(self.script) - 1)]

    def poll(self):
        step, alive, prog = self._frame()
        self.tick += 1
        return {"step": step,
                "workers": [{"worker": k, "alive": a}
                            for k, a in alive.items()],
                "worker_progress": dict(prog)}

    def worker_progress(self):
        return dict(self._frame()[2])

    def restart_worker(self, k):
        self.restarted.append(k)

    def kill_all(self, worker="all"):
        pass


def test_resume_on_target_tick_still_closes_mttr_episode():
    """Regression: the run completing must not swallow the recovery
    episode. Worker 1's post-restart log movement lands on the very
    tick worker 0 reaches the target — the resume (and its MTTR
    fields) must be journaled BEFORE target_reached returns, or the
    trial reports mttr.episodes=0 despite a full detect→restart chain
    (the exact undercount the first seeded chaos campaign showed)."""
    backend = _ScriptedBackend([
        # tick 1: worker 1 dead → detect + immediate (0-backoff)
        # restart, watch_resume={1}
        (5, {0: True, 1: False}, {0: 5, 1: 4}),
        # tick 2: worker 1's log moves AND worker 0 hits the target
        (10, {0: True, 1: True}, {0: 10, 1: 6}),
    ])
    sup = ClusterSupervisor(backend, SupervisorConfig(
        quorum=1, max_restarts_per_worker=2, restart_backoff_s=0.0))
    got = sup.supervise_until_step(10, poll_secs=0.05, timeout_secs=10.0)
    assert got["step"] >= 10 and backend.restarted == [1]
    resume = next(e for e in sup.events if e["action"] == "resume")
    assert resume["worker"] == 1 and resume["step"] == 6
    assert resume["mttr_s"] > 0 and resume["detected_at"] > 0
    mttr = got["recovery"]["mttr"]
    assert mttr["episodes"] == 1 and mttr["unrecovered"] == 0
    assert sup.open_episodes == set()
    # the events are ordered evidence: resume precedes target_reached
    actions = [e["action"] for e in sup.events]
    assert actions.index("resume") < actions.index("target_reached")


def test_open_episode_surfaces_as_unrecovered_and_close_episode():
    """A run that ends while the restarted worker is still booting
    leaves the episode OPEN: the summary counts it as unrecovered
    (never silently dropped), open_episodes names the worker, and a
    later close_episode — the chaos drain observing the worker's first
    post-boot log line — journals the closing resume with MTTR."""
    backend = _ScriptedBackend([
        (5, {0: True, 1: False}, {0: 5, 1: 4}),
        # worker 1 restarted but its log NEVER moves before the target
        (10, {0: True, 1: True}, {0: 10, 1: 4}),
    ])
    sup = ClusterSupervisor(backend, SupervisorConfig(
        quorum=1, max_restarts_per_worker=2, restart_backoff_s=0.0))
    got = sup.supervise_until_step(10, poll_secs=0.05, timeout_secs=10.0)
    assert sup.open_episodes == {1}
    mttr = got["recovery"]["mttr"]
    assert mttr["episodes"] == 0 and mttr["unrecovered"] == 1
    sup.close_episode(1, step=7)
    assert sup.open_episodes == set()
    resume = next(e for e in sup.events if e["action"] == "resume")
    assert resume["step"] == 7 and resume["mttr_s"] > 0
    mttr = sup.summary()["mttr"]
    assert mttr["episodes"] == 1 and mttr["unrecovered"] == 0
    sup.close_episode(1, step=8)  # idempotent: no second resume
    assert sum(1 for e in sup.events if e["action"] == "resume") == 1


def test_summarize_mttr_percentiles_and_legacy_fallback():
    from distributedmnist_tpu.obsv.journal import summarize_mttr
    # explicit mttr_s (the supervisor's stamped episodes)
    events = []
    for k, m in ((0, 2.0), (1, 4.0), (1, 10.0)):
        events.append({"action": "detect", "worker": k, "time": 100.0})
        events.append({"action": "resume", "worker": k, "time": 100.0 + m,
                       "mttr_s": m, "resume_after_respawn_s": m / 2})
    got = summarize_mttr(events)
    assert got["episodes"] == 3
    assert got["p50_s"] == 4.0 and got["max_s"] == 10.0
    assert got["mean_s"] == pytest.approx(16.0 / 3, abs=1e-3)
    assert got["by_worker"] == {0: [2.0], 1: [4.0, 10.0]}
    assert got["resume_after_respawn_max_s"] == 5.0
    # legacy journal without mttr_s: falls back to event timestamps
    legacy = [{"action": "detect", "worker": 0, "time": 50.0},
              {"action": "resume", "worker": 0, "time": 53.5}]
    assert summarize_mttr(legacy)["max_s"] == 3.5
    # no episodes: the key is still present and countable
    assert summarize_mttr([])["episodes"] == 0


# ---------------------------------------------------------------------------
# acceptance e2e: REAL `launch train` workers, mid-run kill + corrupted
# latest checkpoint — the supervised run still reaches the target, the
# restarted worker falls back to the previous loadable step, and the
# journal shows the full episode (slow: boots jax 3x)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_supervised_real_train_survives_kill_and_corrupt_checkpoint(tmp_path):
    # 200 steps ≈ 30-50 s of training per worker on this box: the run
    # must outlive the restarted worker's ~15-30 s jax reboot, or the
    # resume event (the restarted worker's OWN log moving again) could
    # never land inside the supervised window
    train_cmd = (
        "python -m distributedmnist_tpu.launch train "
        "train.train_dir=. data.dataset=synthetic data.batch_size=32 "
        "data.synthetic_train_size=64 data.synthetic_test_size=32 "
        "model.compute_dtype=float32 train.max_steps=200 "
        "train.log_every_steps=1 train.save_interval_steps=2 "
        "train.async_checkpoint=false")
    cfg = LocalClusterConfig(name="heal", workdir=str(tmp_path / "cl"),
                             num_workers=2, train_command=train_cmd)
    ex = CommandExecutor(
        journal=cfg.root / "command_journal.jsonl",
        retry=RetryPolicy(max_attempts=1),
        # trigger at worker 1's OWN step 6: saves land every 2 steps,
        # so at least two loadable checkpoints exist before the latest
        # is torn — the fallback has somewhere to go
        fault_plan=FaultPlan(kill_worker_at_step={1: 6},
                             corrupt_latest_checkpoint_at_step={1: 6}))
    c = LocalProcessCluster(cfg, ex)
    c.create()
    sup = ClusterSupervisor(c, SupervisorConfig(
        quorum=1, max_restarts_per_worker=2, restart_backoff_s=0.5))
    c.run_train()
    try:
        # supervise across the workers' WHOLE run (steps are fast next
        # to the jax boot a restart pays — a short target would be
        # reached before the restarted worker even comes back up)
        got = sup.supervise_until_step(200, poll_secs=1.0,
                                       timeout_secs=600.0)
        assert got["step"] >= 200

        s = summarize_recovery(c.exec.journal_path)
        chain = [a for a in s["by_worker"][1]
                 if a in ("detect", "restart", "resume")]
        assert chain[:3] == ["detect", "restart", "resume"]

        # the restarted worker's Trainer hit the corrupted latest and
        # fell back to the previous loadable step — its own recovery
        # journal (written by train/checkpoint.py via the Trainer hook)
        # proves it; the reboot may still be in flight when worker 0
        # finishes, so wait for it
        w1 = cfg.worker_dir(1)

        def rewind_steps():
            steps = _worker_steps(c, 1)
            return [steps[i] for i in range(1, len(steps))
                    if steps[i] <= steps[i - 1]]

        deadline = time.monotonic() + 180
        worker_recovery: list = []
        while time.monotonic() < deadline:
            worker_recovery = load_recovery_events(
                w1 / "recovery_journal.jsonl")
            # the journal lands at Trainer init; the first post-resume
            # LOG line only after recompile — wait for both
            if (any(r["action"] == "fallback_restore"
                    for r in worker_recovery) and rewind_steps()):
                break
            time.sleep(1.0)
        actions = [r["action"] for r in worker_recovery]
        assert "corrupt_checkpoint_fallback" in actions, actions
        assert "fallback_restore" in actions, actions
        fb = next(r for r in worker_recovery
                  if r["action"] == "fallback_restore")
        bad = next(r for r in worker_recovery
                   if r["action"] == "corrupt_checkpoint_fallback")
        assert fb["step"] < bad["bad_step"]
        # and its train log shows the rewind: a resumed step <= the
        # fallback step + 1 after the kill point
        drops = rewind_steps()
        assert drops and min(drops) <= fb["step"] + 1, _worker_steps(c, 1)
    finally:
        c.kill_all()
    c.delete()
